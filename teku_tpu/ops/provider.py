"""JaxBls12381 — the TPU-backed BLS provider behind the node's SPI.

Plugs the batched verification kernel (teku_tpu/ops/verify.py) into the
same provider seam the reference exposes for blst (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/BLS12381.java:
34-157, installed via bls/BLS.java:51-62 setBlsImplementation).  The
pure-Python oracle remains the host-side fallback and supplies the rare
non-batch operations (key generation, signing), mirroring how the
reference keeps BlstLoader's graceful-degradation path.

Host/device split:
- host: wire-format parsing (flag bits, x < P), SHA-256 message
  expansion, pubkey cache bookkeeping, random multipliers — all
  marshaling vectorized with numpy (no per-lane Python bigint work on
  the hot path);
- device: pubkey decompression + subgroup checks for cache misses (one
  batched dispatch), and the whole verification pipeline — per-lane
  multi-key aggregation, hash-to-G2, scalar muls, Miller loops, final
  exponentiation — in ONE jitted call per padded batch-shape bucket.

Batch sizes (and the per-lane key-count axis) are padded to powers of
two so the jit cache stays small and shapes stay static (XLA recompiles
nothing after warm-up).
"""

import secrets
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls import hash_to_curve as OH
from ..infra import compilecache, faults, tracing
from ..infra.metrics import GLOBAL_REGISTRY
from ..crypto.bls.constants import P, R
from ..crypto.bls.pure_impl import PureBls12381
from ..crypto.bls.spi import BLS12381, BatchSemiAggregate
from . import limbs as fp
from . import mxu
from . import points as PT
from . import verify as V

# jax is imported by now (via ops/__init__): install the compile-cache
# hit/miss listener so dispatch outcomes below can be classified
compilecache.ensure_instrumented()

_G1_INF = bytes([0xC0] + [0] * 47)
_G2_INF = bytes([0xC0] + [0] * 95)

# Process-level dispatch observability (module-level because the staged
# verify jits in ops/verify.py are shared across provider instances).
# First dispatch of a (padded, kmax) bucket shape is the one that pays
# the XLA work — `compile` when it was a fresh compile, `cache_load`
# when the persistent compile cache served it from disk; everything
# after hits the in-memory jit cache (`cache_hit`).  `path` is the
# active mont_mul engine (vpu | mxu, ops/mxu.py).
_SEEN_SHAPES: set = set()
_SEEN_LOCK = threading.Lock()
_M_JIT = GLOBAL_REGISTRY.labeled_counter(
    "bls_jit_dispatch_total",
    "verify dispatches by padded bucket shape (lanes x keys), "
    "jit-cache outcome (compile|cache_load|cache_hit) and mont_mul "
    "path (vpu|mxu)",
    labelnames=("shape", "outcome", "path"))
_M_LANES_REAL = GLOBAL_REGISTRY.counter(
    "bls_dispatch_lanes_real_total",
    "real (non-padding) lanes dispatched to the device")
_M_LANES_PADDED = GLOBAL_REGISTRY.counter(
    "bls_dispatch_lanes_padded_total",
    "total lanes dispatched including pow-2 padding")


def _padding_waste() -> float:
    # read real BEFORE padded (writers inc padded first): a dispatch
    # landing between the reads skews the ratio high, never negative
    real = _M_LANES_REAL.value
    padded = _M_LANES_PADDED.value
    return (padded - real) / padded if padded else 0.0


# pow-2 padding trades jit-cache size for dead lanes: this is the dead
# fraction, a direct throughput observable (0.3 means 30% of device
# work verified nothing)
GLOBAL_REGISTRY.gauge(
    "bls_dispatch_padding_waste_ratio",
    "fraction of dispatched lanes that were pow-2 padding",
    supplier=_padding_waste)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def bytes_to_limbs_np(b: np.ndarray) -> np.ndarray:
    """Vectorized big-endian byte matrix (N, nbytes) -> limb matrix
    (N, L), replacing per-lane Python bigint conversion on the dispatch
    hot path."""
    le = b[:, ::-1].astype(np.uint64)          # little-endian bytes
    n, nb = le.shape
    out = np.zeros((n, fp.L), dtype=np.int64)
    for i in range(fp.L):
        bit0 = fp.W * i
        byte0, shift = divmod(bit0, 8)
        acc = np.zeros(n, dtype=np.uint64)
        for k in range(5):                     # 26 + 7 bits span <= 5 bytes
            idx = byte0 + k
            if idx < nb:
                acc |= le[:, idx] << np.uint64(8 * k)
        out[:, i] = ((acc >> np.uint64(shift))
                     & np.uint64(fp.MASK)).astype(np.int64)
    return out


class _Semi(BatchSemiAggregate):
    """Parsed, host-validated triple awaiting the device dispatch."""

    __slots__ = ("pk_limbs", "message", "sig_x_bytes", "sig_large",
                 "sig_inf")

    def __init__(self, pk_limbs, message, sig_x_bytes, sig_large, sig_inf):
        self.pk_limbs = pk_limbs     # list of (x_mont, y_mont) np (L,)
        self.message = message
        self.sig_x_bytes = sig_x_bytes  # (2, 48) BE bytes of (x1, x0)
        self.sig_large = sig_large
        self.sig_inf = sig_inf


def _parse_g2_wire(sig: bytes):
    """Host wire checks for a compressed G2 signature.

    Returns (x_bytes (2, 48), large, is_inf) or None when malformed.
    On-curve and subgroup membership are checked on device."""
    if len(sig) != 96 or not sig[0] & 0x80:
        return None
    if sig[0] & 0x40:
        if any(sig[1:]) or (sig[0] & 0x3F):
            return None
        return (np.zeros((2, 48), dtype=np.uint8), False, True)
    x1 = int.from_bytes(bytes([sig[0] & 0x1F]) + sig[1:48], "big")
    x0 = int.from_bytes(sig[48:96], "big")
    if x0 >= P or x1 >= P:
        return None
    xb = np.frombuffer(sig, dtype=np.uint8).reshape(2, 48).copy()
    xb[0, 0] &= 0x1F
    return (xb, bool(sig[0] & 0x20), False)


def _parse_g1_wire(pk: bytes):
    """Host wire checks for a compressed G1 pubkey; same contract."""
    if len(pk) != 48 or not pk[0] & 0x80:
        return None
    if pk[0] & 0x40:
        if any(pk[1:]) or (pk[0] & 0x3F):
            return None
        return (0, False, True)
    x = int.from_bytes(bytes([pk[0] & 0x1F]) + pk[1:], "big")
    if x >= P:
        return None
    return (x, bool(pk[0] & 0x20), False)


class JaxBls12381(BLS12381):
    """TPU provider: batched pairing verification as single dispatches."""

    name = "jax-tpu"

    def __init__(self, max_batch: int = 4096, max_keys_per_lane: int = 2048,
                 min_bucket: int = 4, mesh=None):
        self._pure = PureBls12381()
        self.max_batch = max_batch
        # optional multi-chip dispatch: lanes shard over the mesh's dp
        # axis, partial products ride one all_gather (teku_tpu/parallel)
        self._sharded = None
        if mesh is not None:
            from ..parallel import ShardedVerifier
            self._sharded = ShardedVerifier(mesh, min_bucket=min_bucket)
            min_bucket = self._sharded.min_bucket
        self.max_keys_per_lane = max_keys_per_lane
        # tiny batches pad up to one shared bucket: a couple of masked
        # lanes cost microseconds on device, a fresh XLA compile costs
        # minutes — fewer distinct shapes is strictly better
        self.min_bucket = min_bucket
        # pk bytes -> ("ok", x_mont (L,), y_mont (L,)) | ("bad",)
        self._pk_cache: dict = {}
        self._u_cache: dict = {}
        # staged dispatch: five small programs instead of one monolith
        # whose TPU compile is unbounded (ops/verify.py staged_jits)
        self._verify_jit = V.verify_staged
        self._pk_validate_jit = jax.jit(self._pk_validate_kernel)
        # observability: proof that node traffic actually reaches the
        # device path (mirrors the reference's signature_verifications_*
        # counters at AggregatingSignatureVerificationService.java:76-98)
        self.dispatch_count = 0
        self.lanes_dispatched = 0
        # the mont_mul engine resolved when this provider was built —
        # jitted programs KEEP the engine they were traced with, so
        # the dispatch metric labels with this, not a re-resolution
        # (a mid-process set_path() affects only not-yet-traced shapes)
        self.mont_path = mxu.resolve()

    # ------------------------------------------------------------------
    # Host-side SPI ops delegated to the oracle (rare, non-batch paths)
    # ------------------------------------------------------------------
    def secret_key_to_public_key(self, secret: int) -> bytes:
        return self._pure.secret_key_to_public_key(secret)

    def sign(self, secret: int, message: bytes) -> bytes:
        return self._pure.sign(secret, message)

    def aggregate_public_keys(self, public_keys: Sequence[bytes]) -> bytes:
        return self._pure.aggregate_public_keys(public_keys)

    def aggregate_signatures(self, signatures: Sequence[bytes]) -> bytes:
        return self._pure.aggregate_signatures(signatures)

    def signature_is_valid(self, signature: bytes) -> bool:
        return self._pure.signature_is_valid(signature)

    # ------------------------------------------------------------------
    # Pubkey cache with batched device validation
    # ------------------------------------------------------------------
    @staticmethod
    def _pk_validate_kernel(x_plain, large):
        ok, pt = PT.g1_recover_y(x_plain, large)
        ok = ok & PT.g1_in_subgroup(pt)
        # Z == 1 by construction: (X, Y) are already the affine coords
        return ok, fp.compress(pt[0]), fp.compress(pt[1])

    def _resolve_pks(self, all_pks: Sequence[bytes]):
        """Fill the cache for every unseen pubkey in one device dispatch."""
        if len(self._pk_cache) > 200_000:
            # Bound like _u_cache: pubkey bytes can be attacker-influenced,
            # so an unbounded cache (including "bad" entries) is a slow
            # memory-growth vector.
            self._pk_cache.clear()
        miss = {}
        for pk in all_pks:
            if pk in self._pk_cache or pk in miss:
                continue
            wire = _parse_g1_wire(pk)
            if wire is None or wire[2]:   # malformed or infinity
                self._pk_cache[pk] = ("bad",)
            else:
                miss[pk] = wire
        miss = list(miss.items())
        if not miss:
            return
        # floor of 16 keeps the validation program at very few distinct
        # shapes (same compile-cost argument as the verify min_bucket)
        n = max(_next_pow2(len(miss)), 16)
        xs = np.zeros((n, fp.L), dtype=np.int64)
        large = np.zeros(n, dtype=bool)
        for i, (_, (x, lg, _inf)) in enumerate(miss):
            xs[i] = fp.int_to_limbs(x)
            large[i] = lg
        ok, gx, gy = self._pk_validate_jit(xs, large)
        ok = np.asarray(ok)
        gx, gy = np.asarray(gx), np.asarray(gy)
        for i, (pk, _) in enumerate(miss):
            if ok[i]:
                self._pk_cache[pk] = ("ok", gx[i], gy[i])
            else:
                self._pk_cache[pk] = ("bad",)

    def public_key_is_valid(self, public_key: bytes) -> bool:
        self._resolve_pks([public_key])
        return self._pk_cache[public_key][0] == "ok"

    # ------------------------------------------------------------------
    # Message hashing (host SHA-256 -> field draws, cached)
    # ------------------------------------------------------------------
    def _u_draws(self, message: bytes):
        hit = self._u_cache.get(message)
        if hit is None:
            (a, b), (c, d) = OH.hash_to_field_fq2(message, 2)
            hit = (fp.int_to_mont(a), fp.int_to_mont(b),
                   fp.int_to_mont(c), fp.int_to_mont(d))
            if len(self._u_cache) > 100_000:
                self._u_cache.clear()
            self._u_cache[message] = hit
        return hit

    # ------------------------------------------------------------------
    # Verification API — everything lands in the batched kernel
    # ------------------------------------------------------------------
    def prepare_batch_verify(
        self, triple: Tuple[Sequence[bytes], bytes, bytes]
    ) -> Optional[BatchSemiAggregate]:
        public_keys, message, signature = triple
        if not public_keys or len(public_keys) > self.max_keys_per_lane:
            return None
        self._resolve_pks(public_keys)
        points = []
        for pk in public_keys:
            entry = self._pk_cache[pk]
            if entry[0] != "ok":
                return None
            points.append((entry[1], entry[2]))
        sig = _parse_g2_wire(signature)
        if sig is None:
            return None
        return _Semi(points, message, *sig)

    def complete_batch_verify(
        self, semi_aggregates: Sequence[Optional[BatchSemiAggregate]]
    ) -> bool:
        if any(sa is None for sa in semi_aggregates):
            return False
        if not semi_aggregates:
            return True
        semis: List[_Semi] = list(semi_aggregates)
        if len(semis) > self.max_batch:
            # split oversized batches; all chunks must pass
            return all(
                self.complete_batch_verify(semis[i:i + self.max_batch])
                for i in range(0, len(semis), self.max_batch))
        return self._dispatch(semis, randomize=True)

    def batch_verify(
        self, triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
    ) -> bool:
        # wire parse + pk-cache resolve is host work too: the trace's
        # host_prep stage sums this with _dispatch's array packing
        with tracing.span("host_prep"):
            semis = [self.prepare_batch_verify(t) for t in triples]
        return self.complete_batch_verify(semis)

    def verify(self, public_key: bytes, message: bytes,
               signature: bytes) -> bool:
        return self.fast_aggregate_verify([public_key], message, signature)

    def fast_aggregate_verify(self, public_keys: Sequence[bytes],
                              message: bytes, signature: bytes) -> bool:
        semi = self.prepare_batch_verify((public_keys, message, signature))
        if semi is None:
            return False
        return self._dispatch([semi], randomize=False)

    def aggregate_verify(self, public_keys: Sequence[bytes],
                         messages: Sequence[bytes], signature: bytes) -> bool:
        if not public_keys or len(public_keys) != len(messages):
            return False
        # prod_i e(pk_i, H(m_i)) == e(g1, sig): the r=1 batch with the
        # signature attached to lane 0 and infinity signatures elsewhere.
        semis = []
        for i, (pk, msg) in enumerate(zip(public_keys, messages)):
            sig = signature if i == 0 else _G2_INF
            semi = self.prepare_batch_verify(([pk], msg, sig))
            if semi is None:
                return False
            semis.append(semi)
        return self._dispatch(semis, randomize=False)

    # ------------------------------------------------------------------
    def _dispatch(self, semis: List[_Semi], randomize: bool) -> bool:
        # `bls.dispatch` fault site: the supervisor/breaker tests prove
        # hang/exception containment at the REAL device-dispatch seam
        faults.check("bls.dispatch")
        n = len(semis)
        self.dispatch_count += 1
        self.lanes_dispatched += n
        with tracing.span("host_prep"):
            padded = max(_next_pow2(n), self.min_bucket)
            kmax = _next_pow2(max(len(s.pk_limbs) for s in semis))
            pk_xs = np.zeros((padded, kmax, fp.L), dtype=np.int64)
            pk_ys = np.zeros((padded, kmax, fp.L), dtype=np.int64)
            pk_present = np.zeros((padded, kmax), dtype=bool)
            u0c0 = np.zeros((padded, fp.L), dtype=np.int64)
            u0c1 = np.zeros((padded, fp.L), dtype=np.int64)
            u1c0 = np.zeros((padded, fp.L), dtype=np.int64)
            u1c1 = np.zeros((padded, fp.L), dtype=np.int64)
            sig_bytes = np.zeros((padded, 2, 48), dtype=np.uint8)
            s_large = np.zeros(padded, dtype=bool)
            s_inf = np.zeros(padded, dtype=bool)
            lane_valid = np.zeros(padded, dtype=bool)
            for i, s in enumerate(semis):
                for j, (x, y) in enumerate(s.pk_limbs):
                    pk_xs[i, j] = x
                    pk_ys[i, j] = y
                    pk_present[i, j] = True
                u0c0[i], u0c1[i], u1c0[i], u1c1[i] = \
                    self._u_draws(s.message)
                sig_bytes[i] = s.sig_x_bytes
                s_large[i] = s.sig_large
                s_inf[i] = s.sig_inf
                lane_valid[i] = True
            sx1 = bytes_to_limbs_np(sig_bytes[:, 0])
            sx0 = bytes_to_limbs_np(sig_bytes[:, 1])
            if randomize:
                # one os-entropy draw for the whole batch (the
                # reference uses SecureRandom per multiplier,
                # BlstBLS12381.java:191-195); zero lanes are nudged to
                # 1 (2^-64 bias, negligible)
                rs = np.frombuffer(secrets.token_bytes(8 * padded),
                                   dtype=np.uint64).copy()
                rs[rs == 0] = 1
            else:
                rs = np.ones(padded, dtype=np.uint64)
            r_bits = np.asarray(PT.scalar_from_uint64(rs))
        shape = f"{padded}x{kmax}"
        # the staged jits are module-level (shared across providers),
        # but a ShardedVerifier's jit cache is per-instance — key the
        # seen-set on the kernel that will actually serve the dispatch
        cache_key = (id(self._sharded) if self._sharded is not None
                     else 0, shape)
        with _SEEN_LOCK:
            first = cache_key not in _SEEN_SHAPES
            _SEEN_SHAPES.add(cache_key)
        mont_path = self.mont_path
        # first dispatch of a shape pays the XLA work: diff the
        # persistent-cache counters around it to tell a fresh compile
        # from a disk cache load (racy under concurrent first
        # dispatches — the label may misattribute, the counts don't)
        cache_before = compilecache.stats() if first else None
        # padded first: a scrape between the two incs must read the
        # ratio high, never negative
        _M_LANES_PADDED.inc(padded)
        _M_LANES_REAL.inc(n)
        outcome = "cache_hit"
        try:
            with tracing.span("device_execute"):
                if self._sharded is not None:
                    ok, lane_ok = self._sharded(
                        pk_xs, pk_ys, pk_present, (u0c0, u0c1),
                        (u1c0, u1c1), (sx0, sx1), s_large, s_inf,
                        r_bits, lane_valid)
                else:
                    ok, lane_ok = self._verify_jit(
                        pk_xs, pk_ys, pk_present, (u0c0, u0c1),
                        (u1c0, u1c1), (sx0, sx1), s_large, s_inf,
                        r_bits, lane_valid)
                # np.asarray forces the device round-trip, so the span
                # covers execute-to-host-synchronized, not dispatch-only
                lane_ok = np.asarray(lane_ok)
                verdict = bool(np.asarray(ok)) and bool(lane_ok[:n].all())
        finally:
            if first:
                outcome = compilecache.classify_first_dispatch(
                    compilecache.delta(cache_before))
            _M_JIT.labels(shape=shape, outcome=outcome,
                          path=mont_path).inc()
        return faults.transform("bls.dispatch", verdict)
