"""Batched optimal ate pairing on BLS12-381 for TPU (JAX).

Miller loop in Jacobian coordinates on the twist with sparse line
multiplications, mirroring the oracle's production loop
(teku_tpu/crypto/bls/pairing.py) on limb towers; the reference client
gets this from blst's Pairing (mul_n_aggregate / commit / merge /
finalverify, reference: infrastructure/bls/src/main/java/tech/pegasys/
teku/bls/impl/blst/BlstBLS12381.java:124-189).

Compile/runtime structure: the BLS parameter |z| = 0xD201000000010000 has
Hamming weight 6, so the 63 Miller iterations are grouped into runs —
each maximal run of doubling-only iterations is one lax.scan (body traced
once), and the 5 iterations that also add are unrolled.  The compiled
graph is O(#runs), the runtime does no wasted add-steps, and everything
broadcasts over leading batch dims.

Final exponentiation: easy part then the Hayashida-Hayasaka-Teruya
x-chain hard part, computing f^(3d) (cofactor 3 preserves is_one /
equality / bilinearity — see the oracle's derivation and import-time
assert in crypto/bls/pairing.py:220-229); cyclotomic powers use
Granger-Scott squaring.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls.constants import X_ABS
from . import limbs as fp
from . import towers as T

_X_BITS = bin(X_ABS)[3:]   # bits below the MSB


def _parse_runs(bits: str):
    """[(n_double_only, has_trailing_add_iter), ...] covering all bits."""
    runs = []
    n = 0
    for c in bits:
        if c == "0":
            n += 1
        else:
            runs.append((n, True))
            n = 0
    if n:
        runs.append((n, False))
    return runs

_RUNS = _parse_runs(_X_BITS)


# --------------------------------------------------------------------------
# Line-evaluation steps (Jacobian on the twist E'/Fq2)
# --------------------------------------------------------------------------

def _dbl_step(t, px_neg, py):
    """Double T; line through T evaluated at P as sparse (c0, c1, c2).
    Independent fq2 multiplies are gathered into wide calls per round;
    lazy intermediates are compressed before they would breach the limb
    layer's operand-magnitude contract.  T coords must be one unit."""
    X, Y, Z = t
    A, B, Z2 = T._fq2u(T.fq2_sqr(T._fq2s([X, Y, Z])))
    XB, E = T._fq2u(T.fq2_compress(T._fq2s(
        [T.fq2_add(X, B), T.fq2_add(T.fq2_add(A, A), A)])))
    # round 2: squares of (X+B), B, E and product Y*Z
    r2 = T._fq2u(T.fq2_mul(T._fq2s([XB, B, E, Y]),
                           T._fq2s([XB, B, E, Z])))
    XB2, Cc, Fv, YZ = r2
    D = T.fq2_sub(T.fq2_sub(XB2, A), Cc)
    D = T.fq2_add(D, D)
    Z3 = T.fq2_add(YZ, YZ)
    D, X3, Z3 = T._fq2u(T.fq2_compress(T._fq2s(
        [D, T.fq2_sub(Fv, T.fq2_add(D, D)), Z3])))
    C2 = T.fq2_add(Cc, Cc)
    C4 = T.fq2_add(C2, C2)
    C8 = T.fq2_add(C4, C4)
    # round 3: E*(D-X3), Z3*Z2, E*X, E*Z2
    r3 = T._fq2u(T.fq2_mul(T._fq2s([E, Z3, E, E]),
                           T._fq2s([T.fq2_sub(D, X3), Z2, X, Z2])))
    EDX, Z3Z2, EX, EZ2 = r3
    Y3 = T.fq2_sub(EDX, C8)
    X3, Y3, Z3 = T._fq2u(T.fq2_compress(T._fq2s([X3, Y3, Z3])))
    # scale by the G1 coordinates (two fq2-by-fp muls in one width-4 call)
    xiz = T.fq2_mul_by_xi(Z3Z2)
    sc = fp.mont_mul(
        jnp.stack([xiz[0], xiz[1], EZ2[0], EZ2[1]], axis=-2),
        jnp.stack([py, py, px_neg, px_neg], axis=-2))
    c0 = (sc[..., 0, :], sc[..., 1, :])
    c1 = T.fq2_sub(EX, T.fq2_add(B, B))
    c2 = (sc[..., 2, :], sc[..., 3, :])
    return (X3, Y3, Z3), (c0, c1, c2)


def _add_step(t, q, px_neg, py):
    """Mixed-add affine Q into T; chord line at P as sparse coeffs.
    T coords and affine Q must be one unit."""
    X, Y, Z = t
    xq, yq = q
    Z2 = T.fq2_sqr(Z)
    r1 = T._fq2u(T.fq2_mul(T._fq2s([xq, Z2]), T._fq2s([Z2, Z])))
    U2, Z3cu = r1
    S2 = T.fq2_mul(yq, Z3cu)
    H, r = T._fq2u(T.fq2_compress(T._fq2s(
        [T.fq2_sub(U2, X), T.fq2_sub(S2, Y)])))
    r2 = T._fq2u(T.fq2_mul(T._fq2s([H, r, Z]), T._fq2s([H, r, H])))
    H2, R2, Z3 = r2
    r3 = T._fq2u(T.fq2_mul(T._fq2s([H, X, r, yq]),
                           T._fq2s([H2, H2, xq, Z3])))
    H3, V, RXQ, YQZ3 = r3
    X3 = T.fq2_sub(T.fq2_sub(R2, H3), T.fq2_add(V, V))
    r4 = T._fq2u(T.fq2_mul(T._fq2s([r, Y]),
                           T._fq2s([T.fq2_sub(V, X3), H3])))
    Y3 = T.fq2_sub(r4[0], r4[1])
    X3, Y3, Z3 = T._fq2u(T.fq2_compress(T._fq2s([X3, Y3, Z3])))
    xiz3 = T.fq2_mul_by_xi(Z3)
    sc = fp.mont_mul(
        jnp.stack([xiz3[0], xiz3[1], r[0], r[1]], axis=-2),
        jnp.stack([py, py, px_neg, px_neg], axis=-2))
    c0 = (sc[..., 0, :], sc[..., 1, :])
    c1 = T.fq2_sub(RXQ, YQZ3)
    c2 = (sc[..., 2, :], sc[..., 3, :])
    return (X3, Y3, Z3), (c0, c1, c2)


def _mul_by_line(f, line):
    """f * (c0 + (c1 v + c2 v^2) w): all 18 fq2 multiplies of the two
    sparse v-products and two by-fq2 products in ONE wide call."""
    c0, c1, c2 = line
    f0, f1 = f
    A = T._fq2s([f1[1], f1[2], f1[0], f1[2], f1[0], f1[1],
                 f0[1], f0[2], f0[0], f0[2], f0[0], f0[1],
                 f0[0], f0[1], f0[2], f1[0], f1[1], f1[2]])
    B = T._fq2s([c2, c1, c1, c2, c2, c1,
                 c2, c1, c1, c2, c2, c1,
                 c0, c0, c0, c0, c0, c0])
    p = T._fq2u(T.fq2_mul(A, B))

    def sparse_combine(m):
        # (a0 + a1 v + a2 v^2)(c1 v + c2 v^2) from products
        # m = [a1c2, a2c1, a0c1, a2c2, a0c2, a1c1]
        return (T.fq2_mul_by_xi(T.fq2_add(m[0], m[1])),
                T.fq2_add(m[2], T.fq2_mul_by_xi(m[3])),
                T.fq2_add(m[4], m[5]))

    t1 = sparse_combine(p[0:6])
    s0 = sparse_combine(p[6:12])
    f0c0 = (p[12], p[13], p[14])
    f1c0 = (p[15], p[16], p[17])
    res0 = T.fq6_add(f0c0, (T.fq2_mul_by_xi(t1[2]), t1[0], t1[1]))
    res1 = T.fq6_add(s0, f1c0)
    return T.fq12_compress((res0, res1))


# --------------------------------------------------------------------------
# Miller loop
# --------------------------------------------------------------------------

def miller_loop(p, q, mask=None):
    """Batched Miller loop.

    p: affine G1 (x, y) Montgomery limb arrays; q: affine G2 ((x,y) Fq2).
    mask: optional bool batch array — lanes where False produce ONE (the
    contribution of an infinity input, matching the oracle's convention).
    Returns the un-exponentiated Fq12 Miller value, conjugated for the
    negative BLS parameter.
    """
    px, py = p
    px_neg = fp.neg(px)
    t = (q[0], q[1], T._bcast2(T.FQ2_ONE_NP, q[0]))
    f = T.fq12_ones(px.shape[:-1])

    def dbl_iter(state, _):
        f, t = state
        f = T.fq12_sqr(f)
        t, line = _dbl_step(t, px_neg, py)
        f = _mul_by_line(f, line)
        return (f, t), None

    for n_dbl, has_add in _RUNS:
        if n_dbl:
            (f, t), _ = lax.scan(dbl_iter, (f, t), None, length=n_dbl)
        if has_add:
            (f, t), _ = dbl_iter((f, t), None)
            t, line = _add_step(t, q, px_neg, py)
            f = _mul_by_line(f, line)

    f = T.fq12_conj(f)   # negative BLS parameter
    if mask is not None:
        f = T.fq12_select(mask, f, T.fq12_ones(px.shape[:-1]))
    return f


def batch_product(f):
    """Product of Fq12 values over the leading batch axis (axis 0) via
    log2-depth pairwise reduction."""
    n = jax.tree_util.tree_leaves(f)[0].shape[0]
    while n > 1:
        half = n // 2
        odd = n - 2 * half
        a = jax.tree_util.tree_map(lambda x: x[:half], f)
        b = jax.tree_util.tree_map(lambda x: x[half:2 * half], f)
        prod = T.fq12_mul(a, b)
        if odd:
            tail = jax.tree_util.tree_map(lambda x: x[2 * half:], f)
            f = jax.tree_util.tree_map(
                lambda x, y: jnp.concatenate([x, y], axis=0), prod, tail)
            n = half + 1
        else:
            f = prod
            n = half
    return jax.tree_util.tree_map(lambda x: x[0], f)


# --------------------------------------------------------------------------
# Final exponentiation
# --------------------------------------------------------------------------

def _cyclo_pow_abs_x(f):
    """f^|z| for cyclotomic f: Granger-Scott squarings over the runs."""
    result = f

    def sqr_iter(r, _):
        return T.fq12_cyclo_sqr(r), None

    for n_dbl, has_add in _RUNS:
        total = n_dbl + (1 if has_add else 0)
        if total:
            result, _ = lax.scan(sqr_iter, result, None, length=total)
        if has_add:
            result = T.fq12_mul(result, f)
    return result


def _pow_z(f):
    """f^z for cyclotomic f (z < 0: conjugate == inverse there)."""
    return T.fq12_conj(_cyclo_pow_abs_x(f))


def final_exponentiation(f):
    """f^(3*(p^12-1)/r): easy part, then the HHT x-chain hard part
    (identical chain to the oracle: crypto/bls/pairing.py:247-259)."""
    g = T.fq12_mul(T.fq12_conj(f), T.fq12_inv(f))
    g = T.fq12_mul(T.fq12_frobenius(g, 2), g)
    a = T.fq12_mul(_pow_z(g), T.fq12_conj(g))            # g^(z-1)
    a = T.fq12_mul(_pow_z(a), T.fq12_conj(a))            # g^((z-1)^2)
    b = T.fq12_mul(_pow_z(a), T.fq12_frobenius(a, 1))    # a^(z+p)
    c = T.fq12_mul(T.fq12_mul(_pow_z(_pow_z(b)), T.fq12_frobenius(b, 2)),
                   T.fq12_conj(b))                       # b^(z^2+p^2-1)
    return T.fq12_mul(c, T.fq12_mul(T.fq12_sqr(g), g))   # * g^3


def pairing_check(f):
    """final_exponentiation(f) == 1 (per-lane or scalar)."""
    return T.fq12_is_one(final_exponentiation(f))
