"""MXU-path Montgomery multiplier: int8 digit-split matmul kernels.

The VPU-only `mont_mul` (ops/limbs.py) computes the 15x15 schoolbook
limb products as ~225 int64 lane multiplies per lane-pair — and the
v5e roofline (PERF.md) puts that path slightly UNDER the 50k
sigs/sec/chip target.  The MXU offers two orders of magnitude more
int8 throughput, but only for dense contractions, so this module
reformulates the product:

1. PRE-COMPRESS both operands (one carry scan each) to unit-bounded
   limbs: low limbs in [0, 2^W), signed top limb.  The lazy-reduction
   contract (`units(a) * units(b) <= 64`, ops/limbs.py) bounds any
   operand a caller may legally feed to |value| < 64 * 2M < 2^(bits+8),
   so the compressed top limb is |top| < 2^(bits + 8 - W*(L-1)) —
   2^25 for fp381.  Compression is what makes the int8 digit range
   sufficient for EVERY call site; no caller audit is needed.
2. DIGIT-SPLIT each W-bit limb into ND 7-bit digits (ND = 4 for
   W = 26; the top limb's top digit is left unmasked so it carries the
   sign and the top-limb overflow).  All digits fit int8: low-limb
   digits are in [0, 128), the signed top digit is |d| < 128 by the
   bound above (checked at build time in `make_digit_kernels`).
3. OUTER-PRODUCT the digit vectors as one batched int8 x int8 -> int32
   `lax.dot_general` (lanes are the batch dims, the contraction is the
   rank-1 K axis) — the (L*ND) x (L*ND) digit-product matrix per
   lane-pair that PERF.md's roofline section planned ("a 60x60 int8
   matmul per lane-pair, batched over lanes").
4. FOLD back: digit planes p+q=s collapse via a constant one-hot
   contraction (int32), limb anti-diagonals i+j=k via the same static
   pad-and-sum the VPU path uses, and the 2*ND-1 digit planes weight
   into int64 columns (t[k] = sum_s C[k,s] << 7s) — feeding the field's
   EXISTING `_mont_reduce` scan unchanged.

Column bound (the correctness contract; the analysis lives in
PERF.md): digit products are < 2^14, a p+q=s plane sums <= ND of them
(< 2^16), an anti-diagonal sums <= L planes (< 2^20) — all exact in
int32.  The int64 columns are bounded by the SAME schoolbook bound as
the VPU path with unit operands: |t[k]| <= L * 2^(2W + slack) < 2^60,
far inside `_mont_reduce`'s 2^62 input contract.

Path selection is process-global config (CLI `--mont-path` / env
`TEKU_TPU_MONT_MUL` / `set_path()`), resolved at TRACE time:

- ``vpu``  — the elementwise pad-and-sum path (default on CPU);
- ``mxu``  — the digit-split matmul path; on a non-TPU dispatch device
  this falls back to vpu with ONE warning (the int8 matmul shape is a
  pessimization on CPU/VPU backends — never fail, never be slow
  silently);
- ``auto`` — mxu exactly when the dispatch device is a TPU;
- ``mxu-force`` — mxu regardless of device (tests and A/B microbench
  need the kernel ON the CPU oracle box).

The swap is gated by the layer-validation tests: cross-path parity in
tests/test_ops_limbs.py asserts bit-identical `canonical()` images.
"""

import logging
import threading

import numpy as np

from ..infra.env import env_str

_LOG = logging.getLogger(__name__)

DIGIT_BITS = 7                        # int8 digit width (unsigned part)
PATHS = ("vpu", "mxu", "auto", "mxu-force")
ENV_VAR = "TEKU_TPU_MONT_MUL"

# The lazy-reduction operand contract: units(a) * units(b) <= 64 means
# either operand alone is a signed sum of at most 64 units, each with
# |value| < 2M — so |value| < 64 * 2M = 2^(UNITS_SLACK_BITS) * M.
UNITS_SLACK_BITS = 7

_lock = threading.Lock()
_state = {"path": None}               # None -> read ENV_VAR at resolve()
_warned_fallback = [False]
_warned_invalid = [False]


def set_path(path) -> None:
    """Install the process-global multiplier path (CLI/loader seam).

    ``None`` resets to env/default resolution."""
    if path is not None and path not in PATHS:
        raise ValueError(
            f"unknown mont_mul path {path!r} (use one of {'/'.join(PATHS)})")
    with _lock:
        _state["path"] = path
        _warned_fallback[0] = False   # a reconfigure may warn once again
        _warned_invalid[0] = False


def get_path() -> str:
    """The CONFIGURED path (may be 'auto'); see resolve() for the
    effective one."""
    configured = _state["path"]
    if configured is None:
        configured = env_str(ENV_VAR, "auto")
    if configured not in PATHS:
        # warn ONCE: get_path() runs per mont_mul call during tracing,
        # so an unthrottled warn would emit thousands of lines
        with _lock:
            if not _warned_invalid[0]:
                _warned_invalid[0] = True
                _LOG.warning("%s=%r is not one of %s; using auto",
                             ENV_VAR, configured, "/".join(PATHS))
                from ..infra import flightrecorder
                flightrecorder.config_demotion(
                    "mont_mul", configured, "auto",
                    f"{ENV_VAR} not one of "
                    f"{'/'.join(PATHS)}; using auto")
        configured = "auto"
    return configured


def _device_is_tpu() -> bool:
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def resolve() -> str:
    """The EFFECTIVE path for the next trace: 'vpu' or 'mxu'.

    Explicit ``mxu`` on a non-TPU device falls back to vpu with one
    WARN — a CPU int8 "matmul" dispatch must never be the silent reason
    a node is slow (satellite contract, tests/test_compile_cache.py)."""
    configured = get_path()
    if configured == "vpu":
        return "vpu"
    if configured == "mxu-force":
        return "mxu"
    is_tpu = _device_is_tpu()
    if configured == "auto":
        return "mxu" if is_tpu else "vpu"
    # configured == "mxu"
    if is_tpu:
        return "mxu"
    with _lock:
        if not _warned_fallback[0]:
            _warned_fallback[0] = True
            try:
                import jax
                device = jax.default_backend()
            except Exception:  # pragma: no cover
                device = "unknown"
            _LOG.warning(
                "--mont-path mxu requested but the dispatch device is "
                "%r (not a TPU); falling back to the vpu path (use "
                "mxu-force to override for A/B testing)", device)
            # mirror the WARN into the flight recorder so a
            # mis-knobbed node boot self-explains at
            # /teku/v1/admin/flight_recorder
            from ..infra import flightrecorder
            flightrecorder.config_demotion(
                "mont_mul", "mxu", "vpu",
                "mxu requested on a non-TPU device; vpu path "
                "serves (mxu-force overrides for A/B)",
                device=str(device))
    return "vpu"


def active() -> bool:
    """True when the next mont_mul trace should take the MXU path."""
    return resolve() == "mxu"


class force:
    """Context manager pinning the path (tests / bench A/B):

        with mxu.force("mxu-force"):
            out = jax.jit(fp.mont_mul)(a, b)
    """

    def __init__(self, path: str):
        self._path = path
        self._prev = None

    def __enter__(self):
        self._prev = _state["path"]
        set_path(self._path)
        return self

    def __exit__(self, *exc):
        set_path(self._prev)
        return False


# --------------------------------------------------------------------------
# Kernel factory (shared by ops/limbs.py and ops/modfield.make_field)
# --------------------------------------------------------------------------

def make_digit_kernels(L: int, W: int, modulus_bits: int,
                       compress, mont_reduce):
    """Build (mont_mul_mxu, mont_sqr_mxu) for one fixed-width field.

    `compress` and `mont_reduce` are the FIELD'S own carry machinery —
    the MXU path only replaces how the 2L schoolbook product columns
    are built; reduction semantics (output in (-M, 2M)) are untouched,
    which is what makes vpu/mxu outputs bit-identical after the same
    reduction scan.
    """
    import jax.numpy as jnp
    from jax import lax

    # digits per limb: enough for the W-bit low limbs AND the signed
    # top limb of a compressed maximal lazy operand (|top| < 2^top_bits)
    top_bits = modulus_bits + UNITS_SLACK_BITS + 1 - W * (L - 1)
    need_bits = max(W, top_bits)
    nd = -(-need_bits // DIGIT_BITS)          # ceil
    # the top digit is signed int8: it must hold the residue above
    # DIGIT_BITS*(nd-1) bits, i.e. |top| < 128 * 2^(DIGIT_BITS*(nd-1))
    if need_bits > DIGIT_BITS * (nd - 1) + 7:
        nd += 1  # pragma: no cover - only for exotic (W, modulus) combos
    dmask = (1 << DIGIT_BITS) - 1
    n_planes = 2 * nd - 1
    shifts = np.arange(nd, dtype=np.int64) * DIGIT_BITS
    # one-hot digit-plane fold: E[p, q, s] = [p + q == s]
    fold = np.zeros((nd, nd, n_planes), dtype=np.int32)
    for p in range(nd):
        for q in range(nd):
            fold[p, q, p + q] = 1
    plane_w = np.asarray([1 << (DIGIT_BITS * s) for s in range(n_planes)],
                         dtype=np.int64)

    def digit_split(a):
        """(..., L) compressed limbs -> (..., L, nd) int8 digits.

        The LAST digit of every limb is left unmasked: for low limbs
        it equals the masked value (limb < 2^W <= 2^(DIGIT_BITS*nd));
        for the signed top limb it carries sign + overflow (arithmetic
        shift), so sum(d[p] << 7p) reconstructs the limb exactly."""
        d = a[..., :, None] >> jnp.asarray(shifts)
        d = jnp.concatenate([d[..., :nd - 1] & dmask, d[..., nd - 1:]],
                            axis=-1)
        return d.astype(jnp.int8)

    def _columns(da, db):
        """Digit arrays (..., L, nd) -> int64 product columns (..., 2L)."""
        batch = da.shape[:-2]
        nb = len(batch)
        a2 = da.reshape(batch + (L * nd, 1))
        b2 = db.reshape(batch + (1, L * nd))
        dn = (((nb + 1,), (nb,)), (tuple(range(nb)), tuple(range(nb))))
        # the MXU contraction: batched (L*nd x 1) @ (1 x L*nd) int8 ->
        # int32 digit-product matrix per lane-pair
        outer = lax.dot_general(a2, b2, dimension_numbers=dn,
                                preferred_element_type=jnp.int32)
        outer = outer.reshape(batch + (L, nd, L, nd))
        # fold digit planes p+q=s (constant one-hot contraction, int32)
        planes = jnp.einsum("...ipjq,pqs->...ijs", outer,
                            jnp.asarray(fold))
        # fold limb anti-diagonals i+j=k: static pads, same trick as
        # the VPU path — XLA fuses them into one elementwise reduction
        t = sum(jnp.pad(planes[..., i, :, :],
                        [(0, 0)] * nb + [(i, L - i), (0, 0)])
                for i in range(L))                     # (..., 2L, planes)
        # weight the 2*nd-1 planes back into int64 limb columns
        return jnp.sum(t.astype(jnp.int64) * jnp.asarray(plane_w),
                       axis=-1)

    def mont_mul_mxu(a, b):
        a, b = jnp.broadcast_arrays(a, b)
        t = _columns(digit_split(compress(a)), digit_split(compress(b)))
        return mont_reduce(t)

    def mont_sqr_mxu(a):
        da = digit_split(compress(a))
        return mont_reduce(_columns(da, da))

    return mont_mul_mxu, mont_sqr_mxu
