"""Batched hash-to-G2 for TPU (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_).

Split host/device at the hash boundary: expand_message_xmd is SHA-256
over short inputs (microseconds on host, no device win), while the field
math — simplified SWU, 3-isogeny, cofactor clearing — runs batched and
branch-free on device.  The reference client hashes inside native blst
(reference: infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/
blst/HashToCurve.java:23 — the DST this module shares via the oracle).

DIVISIONLESS DESIGN.  Field inversion (Fermat, a ~380-iteration scan) is
the compile-time and runtime hotspot, so the map runs fully projective:

- SSWU computes x = xn/xd and y = yp/xd^3 without ever dividing (the
  RFC's non-division form: x1n = -B(tv2+1), x1d = A*tv2, with the
  exceptional case selected in).  The square root is taken on
  gval = gx_num * xd^3 — same residue class as gx, so the QR decision
  and the 4-candidate constant-time sqrt shape are unchanged — and the
  root IS the projective y: (yp)^2 = gval  <=>  (yp/xd^3)^2 = gx.
- The 3-isogeny maps numerators/denominators homogeneously
  (x = XN/XD, y = YN/YD), still division-free.
- ONE batched inversion (limbs.inv_many — a single Fermat for the whole
  batch via Montgomery's trick) converts both draws of every lane to
  affine, where the RFC sgn0 sign is applied.

Square roots use ONE Fq2 exponentiation per draw via the SSWU identity
gx2 = Z^3 u^6 gx1: candidates for sqrt(gval2) reuse the same power times
u^3 (Z^3)^((q+7)/16) (q = p^2 ≡ 9 mod 16).

Cofactor clearing is Budroni-Pintore via the psi endomorphism, matching
the oracle's production path (crypto/bls/hash_to_curve.py:152-158).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls import fields as F
from ..crypto.bls import hash_to_curve as OH
from ..crypto.bls.constants import (DST_G2_POP, ISO3_X_DEN, ISO3_X_NUM,
                                    ISO3_Y_DEN, ISO3_Y_NUM, P, SSWU_A2,
                                    SSWU_B2, SSWU_Z2, X_ABS)
from . import limbs as fp
from . import points as PT
from . import towers as T

# --------------------------------------------------------------------------
# Host-computed constants (oracle arithmetic, converted once)
# --------------------------------------------------------------------------

_Z3_POW_E = F.fq2_pow(
    F.fq2_mul(F.fq2_sqr(SSWU_Z2), SSWU_Z2), T.SQRT_EXP)

_C = {name: T.fq2_const(val) for name, val in dict(
    A=SSWU_A2, B=SSWU_B2, Z=SSWU_Z2, Z3E=_Z3_POW_E,
    R1=T._SQRT_M1, R2=T._SQRT_C2, R3=T._SQRT_C3,
).items()}


def _c(name, like):
    return T._bcast2(_C[name], like)


# --------------------------------------------------------------------------
# Map to curve: projective SSWU on E', fully batched, no inversions
# --------------------------------------------------------------------------

def fq2_sgn0(a):
    """RFC 9380 sgn0 on a Montgomery-form element (device)."""
    plain = T.fq2_from_mont(a)
    a0_odd = plain[0][..., 0] & 1
    a0_zero = fp.is_zero(plain[0])
    a1_odd = plain[1][..., 0] & 1
    return a0_odd | (a0_zero.astype(jnp.int64) & a1_odd)


def map_to_curve_sswu_proj(u):
    """Batched divisionless simplified SWU: Fq2 u -> (xn, xd, yp) on E'
    with x = xn/xd and y = yp/xd^3 (sgn0 sign NOT yet applied)."""
    one = T._bcast2(T.FQ2_ONE_NP, u)
    u2 = T.fq2_sqr(u)
    tv = T.fq2_compress(T.fq2_mul(_c("Z", u), u2))
    tv2 = T.fq2_compress(T.fq2_add(T.fq2_sqr(tv), tv))   # Z^2 u^4 + Z u^2
    tv2_zero = T.fq2_is_zero(tv2)
    # x1 = (-B/A)(1 + 1/tv2)  ==  -B(tv2+1) / (A tv2); exceptional case
    # tv2 == 0  ->  x1 = B/(Z A)
    r1 = T._fq2u(T.fq2_mul(
        T._fq2s([_c("B", u), _c("A", u)]),
        T._fq2s([T.fq2_add(tv2, one),
                 T.fq2_select(tv2_zero, _c("Z", u), tv2)])))
    x1n = T.fq2_select(tv2_zero, _c("B", u), T.fq2_neg(r1[0]))
    xd = T.fq2_compress(r1[1])
    x1n = T.fq2_compress(x1n)

    # gx1n = x1n^3 + A x1n xd^2 + B xd^3  (numerator of g(x1) over xd^3)
    sq = T._fq2u(T.fq2_sqr(T._fq2s([x1n, xd])))
    x1n2, xd2 = (T.fq2_compress(s) for s in sq)
    r2 = T._fq2u(T.fq2_mul(
        T._fq2s([x1n2, xd2, T.fq2_compress(T.fq2_mul(_c("A", u), x1n))]),
        T._fq2s([x1n, xd, xd2])))
    x1n3, xd3, axd2 = r2
    xd3 = T.fq2_compress(xd3)
    gx1n = T.fq2_add(T.fq2_add(x1n3, axd2),
                     T.fq2_mul(_c("B", u), xd3))
    # the sqrt runs on gval = gx1n * xd^3: same QR class as g(x1), and a
    # root yp of gval is exactly the projective y (y = yp/xd^3)
    gval = T.fq2_compress(T.fq2_mul(T.fq2_compress(gx1n), xd3))

    cand = T.fq2_pow_static(gval, T.SQRT_EXP)
    # second candidate set for x2 = tv*x1: gval2 = tv^3 gval = Z^3 u^6 gval
    u3 = T.fq2_compress(T.fq2_mul(u2, u))
    cand2 = T.fq2_mul(T.fq2_mul(u3, _c("Z3E", u)), cand)
    tv3 = T.fq2_compress(T.fq2_mul(T.fq2_compress(T.fq2_sqr(tv)), tv))
    gval2 = T.fq2_compress(T.fq2_mul(tv3, gval))

    found1 = jnp.zeros(tv2_zero.shape, dtype=bool)
    y1 = cand
    found2 = jnp.zeros(tv2_zero.shape, dtype=bool)
    y2 = cand2
    for root in (None, "R1", "R2", "R3"):
        t1 = cand if root is None else T.fq2_mul(_c(root, u), cand)
        m1 = T.fq2_eq(T.fq2_sqr(t1), gval) & ~found1
        y1 = T.fq2_select(m1, t1, y1)
        found1 |= m1
        t2 = cand2 if root is None else T.fq2_mul(_c(root, u), cand2)
        m2 = T.fq2_eq(T.fq2_sqr(t2), gval2) & ~found2
        y2 = T.fq2_select(m2, t2, y2)
        found2 |= m2

    xn = T.fq2_select(found1, x1n, T.fq2_compress(T.fq2_mul(tv, x1n)))
    yp = T.fq2_select(found1, y1, y2)
    return T.fq2_compress(xn), xd, T.fq2_compress(yp)


def iso_map_proj(xn, xd, yp):
    """3-isogeny E' -> E on projective inputs, division-free.

    Input x = xn/xd, y = yp/xd^3; output x = XN/XD, y = YN/YD with all
    four homogeneous in (xn, xd)."""
    sq = T._fq2u(T.fq2_sqr(T._fq2s([xn, xd])))
    xn2, xd2 = (T.fq2_compress(s) for s in sq)
    r = T._fq2u(T.fq2_mul(T._fq2s([xn2, xd2]), T._fq2s([xn, xd])))
    xn3, xd3 = (T.fq2_compress(s) for s in r)
    xd_pows = [None, xd, xd2, xd3]
    xn_pows = [None, xn, xn2, xn3]

    def homog(coeffs):
        """sum_i k_i xn^i xd^(d-i) for ascending coeffs of degree d."""
        d = len(coeffs) - 1
        acc = None
        for i, k in enumerate(coeffs):
            kc = T._bcast2(T.fq2_const(k), xn)
            term = kc
            if i:
                term = T.fq2_mul(term, xn_pows[i])
            if d - i:
                term = T.fq2_mul(T.fq2_compress(term), xd_pows[d - i])
            acc = term if acc is None else T.fq2_add(acc, term)
        return T.fq2_compress(acc)

    XN = homog(ISO3_X_NUM)                       # deg 3
    XD = T.fq2_mul(xd, homog(ISO3_X_DEN))        # deg 2 -> * xd
    YN = T.fq2_mul(yp, homog(ISO3_Y_NUM))        # y factor: yp/xd^3
    YD = T.fq2_mul(xd3, homog(ISO3_Y_DEN))       # matching xd^3
    return XN, T.fq2_compress(XD), T.fq2_compress(YN), T.fq2_compress(YD)


def _proj_to_affine_signed(u, XN, XD, YN, YD):
    """Batched projective -> affine with RFC sgn0(u) sign fix; ONE
    inversion of XD*YD per element, batched into a single Fermat
    exponentiation across the whole batch (limbs.inv_many)."""
    pinv = T.fq2_inv(T.fq2_compress(T.fq2_mul(XD, YD)))
    r = T._fq2u(T.fq2_mul(T._fq2s([XN, YN]),
                          T._fq2s([T.fq2_compress(T.fq2_mul(pinv, YD)),
                                   T.fq2_compress(T.fq2_mul(pinv, XD))])))
    x, y = (T.fq2_compress(c) for c in r)
    flip = fq2_sgn0(u) != fq2_sgn0(y)
    y = T.fq2_select(flip, T.fq2_neg(y), y)
    return x, T.fq2_compress(y)


def map_to_curve_sswu(u):
    """Affine SSWU on E' (test/oracle parity surface): projective map +
    affine conversion + sgn0 sign."""
    xn, xd, yp = map_to_curve_sswu_proj(u)
    # y = yp/xd^3: reuse the generic converter with XD=xd, YN=yp, YD=xd^3
    xd3 = T.fq2_compress(T.fq2_mul(T.fq2_compress(T.fq2_sqr(xd)), xd))
    return _proj_to_affine_signed(u, xn, xd, yp, xd3)


# --------------------------------------------------------------------------
# Cofactor clearing (Budroni-Pintore) + full pipeline
# --------------------------------------------------------------------------

def clear_cofactor(p):
    """h_eff*P = [x^2-x-1]P + [x-1]psi(P) + psi^2(2P), with the BLS
    parameter negative: [x]Q computed as -[|x|]Q."""
    def mul_x(q):
        return PT.point_neg(PT.G2_KIT,
                            PT.scalar_mul_static(PT.G2_KIT, X_ABS, q))

    a = PT.point_add(PT.G2_KIT, mul_x(p), PT.point_neg(PT.G2_KIT, p))
    res = PT.point_add(PT.G2_KIT, mul_x(a), PT.point_neg(PT.G2_KIT, p))
    res = PT.point_add(PT.G2_KIT, res, PT.g2_psi(a))
    dbl = PT.point_double(PT.G2_KIT, p)
    res = PT.point_add(PT.G2_KIT, res, PT.g2_psi(PT.g2_psi(dbl)))
    return res


def hash_to_g2_device(u0, u1):
    """Device pipeline: two Fq2 draws -> G2 Jacobian point (in-subgroup).

    Both draws are stacked on a leading axis so the map, the isogeny and
    the (single, batched) inversion run once at double width.

    The RFC's sgn0 sign applies to the E' point BEFORE the isogeny
    (y' = yp/xd^3); flipping y' flips the isogeny output, so the affine
    y' (needed only for its sign) and the affine E coordinates are all
    recovered from ONE shared inversion of xd^3 * XD * YD."""
    U = T.tree_stack([u0, u1])
    xn, xd, yp = map_to_curve_sswu_proj(U)
    XN, XD, YN, YD = iso_map_proj(xn, xd, yp)
    xd3 = T.fq2_compress(T.fq2_mul(T.fq2_compress(T.fq2_sqr(xd)), xd))
    xd3_XD = T.fq2_compress(T.fq2_mul(xd3, XD))
    pinv = T.fq2_inv(T.fq2_compress(T.fq2_mul(xd3_XD, YD)))  # batched
    r = T._fq2u(T.fq2_mul(
        T._fq2s([T.fq2_compress(T.fq2_mul(XD, YD)),
                 T.fq2_compress(T.fq2_mul(xd3, YD)),
                 xd3_XD]),
        T._fq2s([pinv, pinv, pinv])))
    inv_xd3, inv_XD, inv_YD = (T.fq2_compress(c) for c in r)
    r2 = T._fq2u(T.fq2_mul(T._fq2s([yp, XN, YN]),
                           T._fq2s([inv_xd3, inv_XD, inv_YD])))
    y_prime, x, y = (T.fq2_compress(c) for c in r2)
    flip = fq2_sgn0(U) != fq2_sgn0(y_prime)
    y = T.fq2_select(flip, T.fq2_neg(y), y)
    y = T.fq2_compress(y)
    one = T._bcast2(T.FQ2_ONE_NP, x)
    (x0, y0, o0), (x1, y1, o1) = T.tree_unstack((x, y, one), 2)
    r = PT.point_add(PT.G2_KIT, (x0, y0, o0), (x1, y1, o1))
    return clear_cofactor(r)


def messages_to_fields(messages, dst: bytes = DST_G2_POP):
    """Host: list of message bytes -> batched Montgomery Fq2 draws (u0, u1).

    Mirrors the oracle's hash_to_field (crypto/bls/hash_to_curve.py:54-65).
    """
    u0c0, u0c1, u1c0, u1c1 = [], [], [], []
    for msg in messages:
        (a, b), (c, d) = OH.hash_to_field_fq2(msg, 2, dst)
        u0c0.append(fp.int_to_mont(a))
        u0c1.append(fp.int_to_mont(b))
        u1c0.append(fp.int_to_mont(c))
        u1c1.append(fp.int_to_mont(d))
    return ((np.stack(u0c0), np.stack(u0c1)),
            (np.stack(u1c0), np.stack(u1c1)))


def to_affine_g2(p):
    """Jacobian -> affine on device (one batched inversion); infinity
    lanes return garbage coords — callers carry the infinity mask."""
    zinv = T.fq2_inv(p[2])
    zinv2 = T.fq2_sqr(zinv)
    x = T.fq2_mul(p[0], zinv2)
    y = T.fq2_mul(p[1], T.fq2_mul(zinv2, zinv))
    out = fp.compress(jnp.stack([x[0], x[1], y[0], y[1]], axis=-2))
    return ((out[..., 0, :], out[..., 1, :]),
            (out[..., 2, :], out[..., 3, :]))
