"""Batched hash-to-G2 for TPU (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_).

Split host/device at the hash boundary: expand_message_xmd is SHA-256
over short inputs (microseconds on host, no device win), while the field
math — simplified SWU, 3-isogeny, cofactor clearing — runs batched and
branch-free on device.  The reference client hashes inside native blst
(reference: infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/
blst/HashToCurve.java:23 — the DST this module shares via the oracle).

Branch-free SSWU: the RFC's exceptional cases and the two-candidate x
selection are computed unconditionally and resolved with selects.  Square
roots use ONE Fq2 exponentiation per u via the SSWU identity
g(x2) = Z^3 u^6 g(x1): candidates for sqrt(g(x1)) are gx1^((q+7)/16)
times the four 8th-roots-of-unity square roots (q = p^2 ≡ 9 mod 16), and
candidates for sqrt(g(x2)) reuse the same power times u^3 (Z^3)^((q+7)/16).

Cofactor clearing is Budroni-Pintore via the psi endomorphism, matching
the oracle's production path (crypto/bls/hash_to_curve.py:152-158).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls import fields as F
from ..crypto.bls import hash_to_curve as OH
from ..crypto.bls.constants import (DST_G2_POP, ISO3_X_DEN, ISO3_X_NUM,
                                    ISO3_Y_DEN, ISO3_Y_NUM, P, SSWU_A2,
                                    SSWU_B2, SSWU_Z2, X_ABS)
from . import limbs as fp
from . import points as PT
from . import towers as T

# --------------------------------------------------------------------------
# Host-computed constants (oracle arithmetic, converted once)
# --------------------------------------------------------------------------

_NEG_B_OVER_A = F.fq2_neg(F.fq2_mul(SSWU_B2, F.fq2_inv(SSWU_A2)))
_X1_EXC = F.fq2_mul(SSWU_B2, F.fq2_inv(F.fq2_mul(SSWU_Z2, SSWU_A2)))
_Z3_POW_E = F.fq2_pow(
    F.fq2_mul(F.fq2_sqr(SSWU_Z2), SSWU_Z2), T.SQRT_EXP)

_C = {name: T.fq2_const(val) for name, val in dict(
    A=SSWU_A2, B=SSWU_B2, Z=SSWU_Z2,
    NEG_B_OVER_A=_NEG_B_OVER_A, X1_EXC=_X1_EXC, Z3E=_Z3_POW_E,
    R1=T._SQRT_M1, R2=T._SQRT_C2, R3=T._SQRT_C3,
).items()}


def _c(name, like):
    return T._bcast2(_C[name], like)


# --------------------------------------------------------------------------
# Map to curve (SSWU on E' then 3-isogeny to E), fully batched
# --------------------------------------------------------------------------

def _gx_prime(x, like):
    x3 = T.fq2_mul(T.fq2_sqr(x), x)
    return T.fq2_add(T.fq2_add(x3, T.fq2_mul(_c("A", like), x)),
                     _c("B", like))


def fq2_sgn0(a):
    """RFC 9380 sgn0 on a Montgomery-form element (device)."""
    plain = T.fq2_from_mont(a)
    a0_odd = plain[0][..., 0] & 1
    a0_zero = fp.is_zero(plain[0])
    a1_odd = plain[1][..., 0] & 1
    return a0_odd | (a0_zero.astype(jnp.int64) & a1_odd)


def map_to_curve_sswu(u):
    """Batched simplified SWU: Fq2 u -> affine point on E' (total)."""
    z_u2 = T.fq2_mul(_c("Z", u), T.fq2_sqr(u))
    tv = T.fq2_add(T.fq2_sqr(z_u2), z_u2)
    tv_zero = T.fq2_is_zero(tv)
    x1 = T.fq2_mul(_c("NEG_B_OVER_A", u),
                   T.fq2_add(T._bcast2(T.FQ2_ONE_NP, u), T.fq2_inv(tv)))
    x1 = T.fq2_select(tv_zero, _c("X1_EXC", u), x1)
    gx1 = _gx_prime(x1, u)

    # one exponentiation serves both sqrt cases
    cand = T.fq2_pow_static(gx1, T.SQRT_EXP)
    x2 = T.fq2_mul(z_u2, x1)
    gx2 = _gx_prime(x2, u)   # == Z^3 u^6 gx1 by the SSWU identity
    u3 = T.fq2_mul(T.fq2_sqr(u), u)
    cand2 = T.fq2_mul(T.fq2_mul(u3, _c("Z3E", u)), cand)

    found1 = jnp.zeros(tv_zero.shape, dtype=bool)
    y1 = cand
    found2 = jnp.zeros(tv_zero.shape, dtype=bool)
    y2 = cand2
    for root in (None, "R1", "R2", "R3"):
        t1 = cand if root is None else T.fq2_mul(_c(root, u), cand)
        m1 = T.fq2_eq(T.fq2_sqr(t1), gx1) & ~found1
        y1 = T.fq2_select(m1, t1, y1)
        found1 |= m1
        t2 = cand2 if root is None else T.fq2_mul(_c(root, u), cand2)
        m2 = T.fq2_eq(T.fq2_sqr(t2), gx2) & ~found2
        y2 = T.fq2_select(m2, t2, y2)
        found2 |= m2

    x = T.fq2_select(found1, x1, x2)
    y = T.fq2_select(found1, y1, y2)
    flip = fq2_sgn0(u) != fq2_sgn0(y)
    y = T.fq2_select(flip, T.fq2_neg(y), y)
    return x, y


def iso_map(x, y):
    """3-isogeny E' -> E, affine->affine, one fused inversion."""
    def horner(coeffs):
        acc = T._bcast2(T.fq2_const(coeffs[-1]), x)
        for c in reversed(coeffs[:-1]):
            acc = T.fq2_add(T.fq2_mul(acc, x), T._bcast2(T.fq2_const(c), x))
        return acc

    x_num = horner(ISO3_X_NUM)
    x_den = horner(ISO3_X_DEN)
    y_num = horner(ISO3_Y_NUM)
    y_den = horner(ISO3_Y_DEN)
    # one inversion: 1/(x_den*y_den), then recover both
    inv_prod = T.fq2_inv(T.fq2_mul(x_den, y_den))
    x_out = T.fq2_mul(x_num, T.fq2_mul(inv_prod, y_den))
    y_out = T.fq2_mul(y, T.fq2_mul(y_num, T.fq2_mul(inv_prod, x_den)))
    return x_out, y_out


# --------------------------------------------------------------------------
# Cofactor clearing (Budroni-Pintore) + full pipeline
# --------------------------------------------------------------------------

def clear_cofactor(p):
    """h_eff*P = [x^2-x-1]P + [x-1]psi(P) + psi^2(2P), with the BLS
    parameter negative: [x]Q computed as -[|x|]Q."""
    def mul_x(q):
        return PT.point_neg(PT.G2_KIT,
                            PT.scalar_mul_static(PT.G2_KIT, X_ABS, q))

    a = PT.point_add(PT.G2_KIT, mul_x(p), PT.point_neg(PT.G2_KIT, p))
    res = PT.point_add(PT.G2_KIT, mul_x(a), PT.point_neg(PT.G2_KIT, p))
    res = PT.point_add(PT.G2_KIT, res, PT.g2_psi(a))
    dbl = PT.point_double(PT.G2_KIT, p)
    res = PT.point_add(PT.G2_KIT, res, PT.g2_psi(PT.g2_psi(dbl)))
    return res


def hash_to_g2_device(u0, u1):
    """Device pipeline: two Fq2 draws -> G2 Jacobian point (in-subgroup)."""
    x0, y0 = iso_map(*map_to_curve_sswu(u0))
    x1, y1 = iso_map(*map_to_curve_sswu(u1))
    one = T._bcast2(T.FQ2_ONE_NP, x0)
    r = PT.point_add(PT.G2_KIT, (x0, y0, one), (x1, y1, one))
    return clear_cofactor(r)


def messages_to_fields(messages, dst: bytes = DST_G2_POP):
    """Host: list of message bytes -> batched Montgomery Fq2 draws (u0, u1).

    Mirrors the oracle's hash_to_field (crypto/bls/hash_to_curve.py:54-65).
    """
    u0c0, u0c1, u1c0, u1c1 = [], [], [], []
    for msg in messages:
        (a, b), (c, d) = OH.hash_to_field_fq2(msg, 2, dst)
        u0c0.append(fp.int_to_mont(a))
        u0c1.append(fp.int_to_mont(b))
        u1c0.append(fp.int_to_mont(c))
        u1c1.append(fp.int_to_mont(d))
    return ((np.stack(u0c0), np.stack(u0c1)),
            (np.stack(u1c0), np.stack(u1c1)))


def to_affine_g2(p):
    """Jacobian -> affine on device (one inversion); infinity lanes
    return garbage coords — callers carry the infinity mask."""
    zinv = T.fq2_inv(p[2])
    zinv2 = T.fq2_sqr(zinv)
    x = T.fq2_mul(p[0], zinv2)
    y = T.fq2_mul(p[1], T.fq2_mul(zinv2, zinv))
    out = fp.compress(jnp.stack([x[0], x[1], y[0], y[1]], axis=-2))
    return ((out[..., 0, :], out[..., 1, :]),
            (out[..., 2, :], out[..., 3, :]))
