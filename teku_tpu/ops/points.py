"""Branch-free elliptic-curve group ops for G1/G2 on TPU (JAX).

Jacobian-coordinate arithmetic over the limb fields, written as total
functions: every operation (including the exceptional cases — infinity
inputs, P == Q, P == -Q) is computed unconditionally and resolved with
lane selects, so the same compiled kernel is correct for every input and
batching is plain broadcasting.  This is the TPU replacement for blst's
P1/P2 point arithmetic behind the reference's BLS provider (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/blst/
BlstBLS12381.java; points parsed/validated in BlstPublicKey.java /
BlstSignature.java).

Fast subgroup membership uses endomorphism eigenvalue identities instead
of a full [r] scalar multiplication (the approach production pairing
libraries use):
- G1: phi(P) == [-z^2]P with phi(x,y) = (beta*x, y), beta a primitive
  cube root of unity.  ker(phi - lambda) has degree lambda^2+lambda+1 =
  z^4 - z^2 + 1 = r, so the identity holds exactly on the r-torsion.
- G2: psi(Q) == [z]Q with psi the untwist-Frobenius-twist map; on G2 psi
  acts as [p] and p ≡ z (mod r).
Both identities are validated against the oracle's multiply-by-r checks
in tests/test_ops_points.py.

Scalar multiplication over runtime scalars (the 64-bit batch-verify
random multipliers) is a scan over bit lanes — double always, add
selected — i.e. constant-time by construction.
"""

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls import fields as F
from ..crypto.bls.constants import B_G1, B_G2, P, X_ABS
from . import limbs as fp
from . import towers as T


class FieldKit(NamedTuple):
    """Static namespace of field ops a curve group is generic over."""
    add: callable
    sub: callable
    mul: callable
    sqr: callable
    neg: callable
    double: callable
    is_zero: callable
    eq: callable
    select: callable
    const: callable       # host int-tuple / int -> device constant
    b_coeff: object       # curve b as a host constant (device-ready)
    stack: callable       # list of elements -> wide-lane element
    unstack: callable     # wide-lane element -> list
    zero_many: callable   # list of (lazy) elements -> list of zero-masks
    compress: callable    # lazy element -> one-unit element


def _fp_const(v: int):
    return jnp.asarray(fp.int_to_mont(v))


def _fq2_const(v):
    c = T.fq2_const(v)
    return (jnp.asarray(c[0]), jnp.asarray(c[1]))


def _fp_stack(elems):
    return jnp.stack(elems, axis=-2)


def _fp_unstack(s):
    return [s[..., i, :] for i in range(s.shape[-2])]


def _fp_zero_many(elems):
    """Batched ≡0-mod-P tests: ONE canonical map for all of them."""
    c = fp.canonical(jnp.stack(elems, axis=-2))
    z = jnp.all(c == 0, axis=-1)
    return [z[..., i] for i in range(len(elems))]


def _fq2_zero_many(elems):
    c = fp.canonical(jnp.stack(
        [comp for e in elems for comp in e], axis=-2))
    z = jnp.all(c == 0, axis=-1)
    return [z[..., 2 * i] & z[..., 2 * i + 1] for i in range(len(elems))]


def _fq2_compress(a):
    return T.fq2_compress(a)


G1_KIT = FieldKit(
    add=fp.add, sub=fp.sub, mul=fp.mont_mul, sqr=fp.mont_sqr, neg=fp.neg,
    double=fp.double, is_zero=fp.is_zero, eq=fp.eq, select=fp.select,
    const=_fp_const, b_coeff=B_G1, stack=_fp_stack, unstack=_fp_unstack,
    zero_many=_fp_zero_many, compress=fp.compress,
)

G2_KIT = FieldKit(
    add=T.fq2_add, sub=T.fq2_sub, mul=T.fq2_mul, sqr=T.fq2_sqr,
    neg=T.fq2_neg, double=T.fq2_double, is_zero=T.fq2_is_zero,
    eq=T.fq2_eq, select=T.fq2_select, const=_fq2_const, b_coeff=B_G2,
    stack=T._fq2s, unstack=T._fq2u,
    zero_many=_fq2_zero_many, compress=_fq2_compress,
)


# --------------------------------------------------------------------------
# Point structure: (X, Y, Z) tuple of field elements; Z == 0 <=> infinity.
# --------------------------------------------------------------------------

def leaf_shape(x):
    """Shape of a field element's first array leaf.

    Tower elements nest coordinate tuples ((c0, c1) for Fq2, deeper
    for Fq6/Fq12); every leaf shares one (batch..., L) shape, so the
    first leaf names it.  Shared by the broadcast helpers below and
    scalar_mul_static's dense-exponent fallback (which used to unwrap
    tuples with its own while-loop)."""
    while isinstance(x, tuple):
        x = x[0]
    return x.shape


def _broadcast_const(k: FieldKit, c, like):
    if k is G1_KIT:
        return jnp.broadcast_to(c, like.shape)
    shape = leaf_shape(like)
    return (jnp.broadcast_to(c[0], shape), jnp.broadcast_to(c[1], shape))


def _zero_like(k: FieldKit, x):
    if k is G1_KIT:
        return jnp.zeros_like(x)
    return (jnp.zeros_like(x[0]), jnp.zeros_like(x[1]))


def infinity_like(k: FieldKit, x):
    """Infinity with the batch shape of field element x."""
    one = _broadcast_const(k, k.const(1 if k is G1_KIT else (1, 0)), x)
    return (one, one, _zero_like(k, x))


def is_infinity(k: FieldKit, p):
    return k.is_zero(p[2])


def point_neg(k: FieldKit, p):
    return (p[0], k.neg(p[1]), p[2])


def point_double(k: FieldKit, p):
    """Jacobian doubling (a=0).  Total: doubling infinity gives Z3=0.
    Independent multiplies batched into wide-lane rounds; intermediates
    compressed where lazy unit counts would breach the mul contract.
    Inputs must be one-unit coordinates; output is compressed."""
    X1, Y1, Z1 = p
    A, B, YZ = k.unstack(k.mul(k.stack([X1, Y1, Y1]),
                               k.stack([X1, Y1, Z1])))
    XB, E = k.unstack(k.compress(k.stack(
        [k.add(X1, B), k.add(k.add(A, A), A)])))
    XB2, C, Fv = k.unstack(k.mul(k.stack([XB, B, E]),
                                 k.stack([XB, B, E])))
    D = k.sub(k.sub(XB2, A), C)
    D = k.add(D, D)
    D, X3 = k.unstack(k.compress(k.stack([D, k.sub(Fv, k.add(D, D))])))
    C2 = k.add(C, C)
    C4 = k.add(C2, C2)
    C8 = k.add(C4, C4)
    Y3 = k.sub(k.mul(E, k.sub(D, X3)), C8)
    Z3 = k.add(YZ, YZ)
    X3, Y3, Z3 = k.unstack(k.compress(k.stack([X3, Y3, Z3])))
    return (X3, Y3, Z3)


def point_add(k: FieldKit, p, q):
    """Unified Jacobian addition: every exceptional case (either input at
    infinity, P == Q, P == -Q) is computed and selected lane-wise; the
    four predicate zero-tests share one canonical map.  Inputs must be
    one-unit coordinates; output is compressed."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1, Z2Z2, Z1Z2 = k.unstack(k.mul(k.stack([Z1, Z2, Z1]),
                                       k.stack([Z1, Z2, Z2])))
    U1, U2, Z2c, Z1c = k.unstack(k.mul(
        k.stack([X1, X2, Z2, Z1]),
        k.stack([Z2Z2, Z1Z1, Z2Z2, Z1Z1])))
    S1, S2 = k.unstack(k.mul(k.stack([Y1, Y2]), k.stack([Z2c, Z1c])))
    H = k.sub(U2, U1)
    sdiff = k.sub(S2, S1)
    H, rr = k.unstack(k.compress(k.stack([H, k.add(sdiff, sdiff)])))
    H2 = k.add(H, H)
    I, R2 = k.unstack(k.mul(k.stack([H2, rr]), k.stack([H2, rr])))
    J, V, ZZH = k.unstack(k.mul(
        k.stack([H, U1, k.add(Z1Z2, Z1Z2)]),
        k.stack([I, I, H])))
    X3 = k.sub(k.sub(R2, J), k.add(V, V))
    RVX, S1J = k.unstack(k.mul(k.stack([rr, S1]),
                               k.stack([k.sub(V, X3), J])))
    Y3 = k.sub(RVX, k.add(S1J, S1J))
    Z3 = ZZH
    out = tuple(k.unstack(k.compress(k.stack([X3, Y3, Z3]))))

    same_x, same_y, p_inf, q_inf = k.zero_many([H, sdiff, Z1, Z2])
    finite = (~p_inf) & (~q_inf)
    # P == Q (and both finite): double
    dbl = point_double(k, p)
    use_dbl = finite & same_x & same_y
    # P == -Q: infinity (select via zeroing Z)
    to_inf = finite & same_x & ~same_y
    out = _select_point(k, use_dbl, dbl, out)
    out = (out[0], out[1], k.select(to_inf, k.sub(out[2], out[2]), out[2]))
    out = _select_point(k, p_inf, q, out)
    out = _select_point(k, q_inf & ~p_inf, p, out)
    return out


def _select_point(k: FieldKit, cond, a, b):
    return tuple(k.select(cond, x, y) for x, y in zip(a, b))


def point_eq(k: FieldKit, p, q):
    """Equality in Jacobian coordinates (cross-multiplied), total; all
    four zero-tests share one canonical map."""
    Z1Z1, Z2Z2 = k.unstack(k.mul(k.stack([p[2], q[2]]),
                                 k.stack([p[2], q[2]])))
    Z2c, Z1c = k.unstack(k.mul(k.stack([q[2], p[2]]),
                               k.stack([Z2Z2, Z1Z1])))
    m = k.unstack(k.mul(k.stack([p[0], q[0], p[1], q[1]]),
                        k.stack([Z2Z2, Z1Z1, Z2c, Z1c])))
    x_eq, y_eq, p_inf, q_inf = k.zero_many(
        [k.sub(m[0], m[1]), k.sub(m[2], m[3]), p[2], q[2]])
    both_inf = p_inf & q_inf
    one_inf = p_inf ^ q_inf
    return (x_eq & y_eq & ~one_inf) | both_inf


# --------------------------------------------------------------------------
# Scalar multiplication
# --------------------------------------------------------------------------

SCALAR_WINDOW = 4


def ladder_plan(nbits: int, window: int):
    """Host-side plan for scalar_mul_bits: MSB zero-padding to a
    window multiple + window count.  Returns (pad, n_windows)."""
    pad = -nbits % window
    return pad, (nbits + pad) // window


def ladder_op_counts(nbits: int, window: int) -> dict:
    """Executed point-op counts of the windowed ladder for a given
    bit width — the observable the irregular-width regression test
    pins (and PERF.md's cost model cites).  Derived from the SAME
    ladder_plan scalar_mul_bits executes."""
    _, nwin = ladder_plan(nbits, window)
    return {
        "doubles": (nwin - 1) * window,
        "adds": nwin - 1,              # one gathered add per digit
        "table_adds": 1 << window,     # build scan length
        "total": (nwin - 1) * (window + 1) + (1 << window),
    }


def scalar_mul_bits(k: FieldKit, bits, p, window: int = SCALAR_WINDOW):
    """[s]P for runtime scalars given as a bit array.

    bits: int array (..., NBITS), MSB first, matching P's batch shape.

    Fixed-window ladder: the bit-serial form pays a double AND a
    (select-discarded but computed) add per bit — for the 64-bit batch
    multipliers that is 64 doubles + 64 adds.  A per-lane 2^w table
    (2^w - 2 adds once) and one gathered add per w-bit digit pays
    64 doubles + 16 adds + 14 build adds: ~35% fewer point ops in the
    scalars stage.  Still constant-time: every digit gathers and adds
    (digit 0 adds the infinity row, which point_add absorbs).

    Irregular widths (e.g. 33-bit GLV half-scalars, 255-bit parity
    oracles) are MSB zero-padded to a window multiple instead of
    demoting to the bit-serial ladder — a leading zero digit just
    starts the accumulator at the (absorbed) infinity row, and the
    op count stays the windowed one (ladder_op_counts pins the win).
    """
    nbits = bits.shape[-1]
    pad, _ = ladder_plan(nbits, window)
    if pad:
        bits = jnp.concatenate(
            [jnp.zeros(bits.shape[:-1] + (pad,), bits.dtype), bits],
            axis=-1)
        nbits += pad
    # table rows [0]P..[2^w - 1]P, stacked on a leading axis.  Built
    # with a scan so the graph holds ONE point_add body (an unrolled
    # build inlines 2^w - 2 adds and measurably bloats XLA compiles).
    def build(carry, _):
        return point_add(k, carry, p), carry
    _, table = lax.scan(build, infinity_like(k, p[0]), None,
                        length=1 << window)

    def gather(d):
        # leaf (2^w, ..., L); d (...,) -> (..., L)
        def take(leaf):
            idx = jnp.broadcast_to(
                d[None, ..., None], (1,) + d.shape + (leaf.shape[-1],))
            return jnp.take_along_axis(leaf, idx, axis=0)[0]
        return jax.tree_util.tree_map(take, table)

    # MSB-first base-2^w digits, scanned: (..., nbits) -> (nwin, ...)
    weights = jnp.asarray([1 << (window - 1 - t) for t in range(window)],
                          dtype=bits.dtype)
    digits = jnp.moveaxis(
        (bits.reshape(bits.shape[:-1] + (-1, window)) * weights)
        .sum(axis=-1), -1, 0)

    def body(acc, d):
        for _ in range(window):
            acc = point_double(k, acc)
        acc = point_add(k, acc, gather(d))
        return acc, None

    acc = gather(digits[0])              # leading doubles of inf elided
    acc, _ = lax.scan(body, acc, digits[1:])
    return acc


def scalar_mul_static(k: FieldKit, e: int, p):
    """[e]P for a static non-negative exponent.

    The bit pattern is static, so zero bits pay ONLY a doubling: maximal
    runs of doubling-only iterations run as one lax.scan each and the
    point_adds are unrolled at the (few) one-bits — for the BLS parameter
    (Hamming weight 6) this drops ~58 of 64 adds versus a naive
    double-and-always-add ladder."""
    assert e >= 0
    if e == 0:
        return infinity_like(k, p[0])
    # acc starts at P (top bit), then per remaining bit: double (+ add)
    bits = bin(e)[3:]
    runs = []        # [(n_doubles, add_after)]
    n = 0
    for c in bits:
        n += 1
        if c == "1":
            runs.append((n, True))
            n = 0
    if n:
        runs.append((n, False))

    if len(runs) > 16:
        # DENSE exponent: the runs decomposition would inline ~one
        # point_add per one-bit, building a graph big enough to crash
        # XLA's compiler (observed: CPU backend segfault, TPU compile
        # blowup).  One masked-add scan keeps the program tiny; the
        # static-unroll fast path stays for the sparse exponents it was
        # built for (the BLS parameter, Hamming weight 6).
        nbits = len(bits) + 1
        bit_arr = jnp.asarray([int(c) for c in bin(e)[2:]],
                              dtype=jnp.int64)
        lane_shape = leaf_shape(p[0])[:-1]   # bits over the batch dims
        bit_arr = jnp.broadcast_to(bit_arr, lane_shape + (nbits,))
        return scalar_mul_bits(k, bit_arr, p)

    def dbl_body(acc, _):
        return point_double(k, acc), None

    acc = p
    for n_dbl, has_add in runs:
        acc, _ = lax.scan(dbl_body, acc, None, length=n_dbl)
        if has_add:
            acc = point_add(k, acc, p)
    return acc


def point_batch_sum(k: FieldKit, p):
    """Sum points over the leading batch axis via log-depth pairwise
    adds.  (Lives here so the MSM kernels (ops/msm.py) and the verify
    pipeline (ops/verify.py) share one reduction.)"""
    n = jax.tree_util.tree_leaves(p)[0].shape[0]
    while n > 1:
        half = n // 2
        odd = n - 2 * half
        a = jax.tree_util.tree_map(lambda x: x[:half], p)
        b = jax.tree_util.tree_map(lambda x: x[half:2 * half], p)
        s = point_add(k, a, b)
        if odd:
            tail = jax.tree_util.tree_map(lambda x: x[2 * half:], p)
            p = jax.tree_util.tree_map(
                lambda x, y: jnp.concatenate([x, y], axis=0), s, tail)
            n = half + 1
        else:
            p = s
            n = half
    return jax.tree_util.tree_map(lambda x: x[0], p)


def scalar_from_uint64(vals):
    """uint64 scalar array (...,) -> int64 bit array (..., 64) MSB first."""
    vals = jnp.asarray(vals).astype(jnp.uint64)
    shifts = jnp.arange(63, -1, -1, dtype=jnp.uint64)
    return ((vals[..., None] >> shifts) & 1).astype(jnp.int64)


# --------------------------------------------------------------------------
# Endomorphisms + fast subgroup checks
# --------------------------------------------------------------------------

# beta: primitive cube root of unity in Fq (acts x -> beta*x on G1).
# Only ONE of the two non-trivial cube roots has eigenvalue -z^2 (the
# other has eigenvalue z^2 - 1 mod r and would reject every valid point),
# so the import-time assert below verifies the eigenvalue identity
# phi(G) == [-z^2]G on the G1 generator itself.
_BETA = pow(2, (P - 1) // 3, P)
if _BETA == 1:  # pragma: no cover - 2 is not a cube in Fq for this P
    _BETA = pow(3, (P - 1) // 3, P)
assert _BETA != 1 and pow(_BETA, 3, P) == 1


def _check_beta_eigenvalue() -> None:
    from ..crypto.bls.constants import R as _R
    from ..crypto.bls.curve import FQ_OPS, G1_GENERATOR, point_mul, to_affine
    gx, gy = G1_GENERATOR[0], G1_GENERATOR[1]
    lam = (-(X_ABS * X_ABS)) % _R
    expect = to_affine(FQ_OPS, point_mul(FQ_OPS, lam, (gx, gy, 1)))
    assert expect == (_BETA * gx % P, gy), (
        "beta has the wrong GLV eigenvalue")


_check_beta_eigenvalue()

# psi constants: untwist-Frobenius-twist on our tower (w^2 = v, v^3 = xi):
#   x-part picks up (v^(p-1))^-1 = FROB6_C1^-1
#   y-part picks up (w^(p-1))^-3 = FROB12_C1^-3
_PSI_X = F.fq2_inv(F.FROB6_C1)
_PSI_Y = F.fq2_inv(F.fq2_mul(F.fq2_mul(F.FROB12_C1, F.FROB12_C1), F.FROB12_C1))


def g1_phi(p):
    """GLV endomorphism (x, y, z) -> (beta*x, y, z)."""
    beta = _fp_const(_BETA)
    return (fp.mont_mul(p[0], beta), p[1], p[2])


def g2_psi(q):
    """Untwist-Frobenius-twist endomorphism on E'(Fq2)."""
    cx = _fq2_const(_PSI_X)
    cy = _fq2_const(_PSI_Y)
    return (T.fq2_mul(T.fq2_conj(q[0]), cx),
            T.fq2_mul(T.fq2_conj(q[1]), cy),
            T.fq2_conj(q[2]))


def g1_in_subgroup(p):
    """phi(P) == [-z^2]P  (infinity counts as in-subgroup)."""
    lhs = g1_phi(p)
    rhs = point_neg(G1_KIT, scalar_mul_static(G1_KIT, X_ABS * X_ABS, p))
    return point_eq(G1_KIT, lhs, rhs) | is_infinity(G1_KIT, p)


def g2_in_subgroup(q):
    """psi(Q) == [z]Q with z < 0  (infinity counts as in-subgroup)."""
    lhs = g2_psi(q)
    rhs = point_neg(G2_KIT, scalar_mul_static(G2_KIT, X_ABS, q))
    return point_eq(G2_KIT, lhs, rhs) | is_infinity(G2_KIT, q)


# --------------------------------------------------------------------------
# On-curve checks + batched decompression (y-recovery)
# --------------------------------------------------------------------------

def is_on_curve(k: FieldKit, p):
    """Y^2 == X^3 + b*Z^6, total (infinity is on-curve)."""
    b = _broadcast_const(k, k.const(k.b_coeff), p[0])
    z2 = k.sqr(p[2])
    z6 = k.mul(k.sqr(z2), z2)
    lhs = k.sqr(p[1])
    rhs = k.add(k.mul(k.sqr(p[0]), p[0]), k.mul(b, z6))
    return k.eq(lhs, rhs) | is_infinity(k, p)


def g1_recover_y(x_plain, y_is_large):
    """Batched G1 decompression from plain-form x limbs.

    Returns (valid, point).  valid=False lanes: x not on curve.
    Subgroup check NOT included (separate, it costs a scalar mul).
    """
    x = fp.to_mont(x_plain)
    b = jnp.broadcast_to(_fp_const(B_G1), x.shape)
    rhs = fp.add(fp.mont_mul(fp.mont_sqr(x), x), b)
    y = fp.sqrt_candidate(rhs)
    ok = fp.eq(fp.mont_sqr(y), rhs)
    # wire sign: flip if computed root's "largeness" mismatches the flag
    half = jnp.asarray(fp.int_to_limbs((P - 1) // 2))
    y_plain = fp.from_mont(y)
    large = fp.gt(y_plain, half)
    y = fp.select(large == y_is_large, y, fp.neg(y))
    one = jnp.broadcast_to(jnp.asarray(fp.ONE_MONT), x.shape)
    return ok, (x, y, one)


def g2_recover_y(x_plain, y_is_large):
    """Batched G2 decompression from plain-form Fq2 x limbs (c0, c1)."""
    x = (fp.to_mont(x_plain[0]), fp.to_mont(x_plain[1]))
    b = _broadcast_const(G2_KIT, _fq2_const(B_G2), x)
    rhs = T.fq2_add(T.fq2_mul(T.fq2_sqr(x), x), b)
    ok, y = T.fq2_sqrt(rhs)
    y = T.fq2_compress(y)
    large = T.fq2_is_large(T.fq2_from_mont(y))
    y = T.fq2_select(large == y_is_large, y, T.fq2_neg(y))
    one = _broadcast_const(G2_KIT, _fq2_const((1, 0)), x)
    return ok, (x, y, one)


# --------------------------------------------------------------------------
# Host conversions (tests / boundaries)
# --------------------------------------------------------------------------

def g1_to_device(p_jac):
    """Oracle G1 Jacobian point (ints) -> device point (unbatched)."""
    return tuple(jnp.asarray(fp.int_to_mont(c)) for c in p_jac)


def g1_from_device(p, index=()):
    return tuple(fp.mont_to_int(np.asarray(c)[index]) for c in p)


def g2_to_device(p_jac):
    return tuple(T.fq2_to_device(c) for c in p_jac)


def g2_from_device(p, index=()):
    return tuple(T.fq2_from_device(c, index) for c in p)
