"""Device KZG (EIP-4844) verification on the shared BLS kernel base.

The reference's KZG is native C reached over JNI (reference:
infrastructure/kzg/src/main/java/tech/pegasys/teku/kzg/CKZG4844.java:
48,104-122); SURVEY §2.12.2 plans the TPU equivalent on the SAME
bigint/pairing kernel base as the signature verifier.  This module is
that: scalar-field (Fr) barycentric blob evaluation, a fixed-shape
batched G1 ladder MSM, and the 2-pairing proof check reusing
ops/pairing's Miller loop + final exponentiation.

Batch shape: verify_blob_kzg_proof_batch folds the whole batch with
random multipliers into ONE G1 fold + ONE 2-lane multi-pairing —
  e(sum_i r_i C_i + sum_i (r_i z_i) pi_i - [sum_i r_i y_i] G1, G2)
    * e(-sum_i r_i pi_i, [s]G2) == 1
— so a 6-blob deneb block costs one small ladder dispatch + one
pairing, not 12 pairings.

Host/device split mirrors ops/provider.py: wire parsing, SHA-256
challenges and the tiny scalar bookkeeping on host (numpy/bigint);
field math, point ladders and pairings on device in fixed padded
shapes.
"""

import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls import curve as C
from ..crypto.bls.constants import R as R_MOD
from ..crypto.kzg import (BYTES_PER_BLOB, BYTES_PER_FIELD_ELEMENT,
                          FIELD_ELEMENTS_PER_BLOB, KzgError,
                          RANDOM_CHALLENGE_DOMAIN, TrustedSetup,
                          compute_challenge, roots_of_unity)
from . import limbs as fp
from . import modfield
from . import points as PT
from . import verify as V
from .provider import _next_pow2, _parse_g1_wire

FR = modfield.make_field(R_MOD, "fr")
_N = FIELD_ELEMENTS_PER_BLOB
_NBITS = 255                       # Fr scalars fit in 255 bits


def blob_bytes_to_limbs(blobs: Sequence[bytes]) -> np.ndarray:
    """(B, 4096, Lr) plain (non-Montgomery) Fr limbs from blob bytes —
    one vectorized numpy pass, no per-element Python bigints."""
    b = np.frombuffer(b"".join(blobs), dtype=np.uint8)
    b = b.reshape(len(blobs) * _N, BYTES_PER_FIELD_ELEMENT)
    le = b[:, ::-1].astype(np.uint64)
    out = np.zeros((b.shape[0], FR.L), dtype=np.int64)
    for i in range(FR.L):
        bit0 = FR.W * i
        byte0, shift = divmod(bit0, 8)
        acc = np.zeros(b.shape[0], dtype=np.uint64)
        for k in range(5):
            idx = byte0 + k
            if idx < BYTES_PER_FIELD_ELEMENT:
                acc |= le[:, idx] << np.uint64(8 * k)
        out[:, i] = ((acc >> np.uint64(shift))
                     & np.uint64(FR.MASK)).astype(np.int64)
    return out.reshape(len(blobs), _N, FR.L)


_R_LIMBS = FR.int_to_limbs(R_MOD)


def limbs_lt_modulus(limbs: np.ndarray) -> np.ndarray:
    """Vectorized canonical-range check: limb vectors < R, comparing
    limb-by-limb from the top (each field element must be canonical
    per the spec's bytes_to_bls_field)."""
    lt = np.zeros(limbs.shape[:-1], dtype=bool)
    eq = np.ones(limbs.shape[:-1], dtype=bool)
    for i in range(FR.L - 1, -1, -1):
        lt |= eq & (limbs[..., i] < _R_LIMBS[i])
        eq &= limbs[..., i] == _R_LIMBS[i]
    return lt


def int_to_bits(vals: Sequence[int], nbits: int = _NBITS) -> np.ndarray:
    """(N, nbits) MSB-first bit matrix from host ints — one
    to_bytes per scalar + a vectorized unpackbits (a Python per-bit
    loop here costs ~1M iterations per 4096-scalar MSM)."""
    nbytes = (nbits + 7) // 8
    raw = b"".join(v.to_bytes(nbytes, "big") for v in vals)
    bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8)
                         .reshape(len(vals), nbytes), axis=1)
    return bits[:, 8 * nbytes - nbits:].astype(np.int64)


# --------------------------------------------------------------------------
# Device kernels
# --------------------------------------------------------------------------

_ROOTS_MONT: Optional[np.ndarray] = None
_INV_N_MONT: Optional[np.ndarray] = None


def _eval_constants():
    global _ROOTS_MONT, _INV_N_MONT
    if _ROOTS_MONT is None:
        roots = roots_of_unity()
        _ROOTS_MONT = np.stack([FR.int_to_mont(w) for w in roots])
        _INV_N_MONT = FR.int_to_mont(pow(_N, R_MOD - 2, R_MOD))
    return _ROOTS_MONT, _INV_N_MONT


def eval_blob_kernel(poly_plain, z_mont):
    """Barycentric p(z) for a batch of blobs, entirely on device.

    poly_plain: (B, 4096, Lr) plain limbs; z_mont: (B, Lr) Montgomery.
    Returns canonical PLAIN limbs of y = p(z), shape (B, Lr).

    p(z) = (z^n - 1)/n * sum_i p_i w_i / (z - w_i); the z == w_i
    special case (p(z) = p_i) is computed and lane-selected, branch
    free.  The 4096-wide denominator inversion is ONE Fermat pass via
    Montgomery's trick (modfield.inv_many).
    """
    roots, inv_n = _eval_constants()
    roots = jnp.asarray(roots)                      # (4096, Lr)
    poly = FR.to_mont(poly_plain)                   # (B, 4096, Lr)
    denom = z_mont[:, None, :] - roots[None]        # lazy sub
    invs = FR.inv_many(denom)
    terms = FR.mont_mul(FR.mont_mul(poly, roots[None]), invs)
    acc = FR.compress(jnp.sum(terms, axis=1))       # (B, Lr)
    zn = FR.pow_static(z_mont, _N)
    one = jnp.asarray(FR.ONE_MONT)
    factor = FR.mont_mul(zn - one[None], jnp.asarray(inv_n)[None])
    y = FR.mont_mul(acc, factor)
    # z hit a root: y is exactly that poly entry
    hit = FR.is_zero(denom)                         # (B, 4096)
    special = FR.compress(jnp.sum(
        jnp.where(hit[..., None], poly, 0), axis=1))
    y = FR.select(jnp.any(hit, axis=1), special, y)
    return FR.canonical_plain(y)


def g1_validate_kernel(x_plain, large):
    """Decompression + subgroup check for commitment/proof points."""
    ok, pt = PT.g1_recover_y(x_plain, large)
    ok = ok & PT.g1_in_subgroup(pt)
    return ok, fp.compress(pt[0]), fp.compress(pt[1])


def fold_pairing_kernel(xs, ys, inf, valid, bits, group_b,
                        g2x0, g2x1, g2y0, g2y1):
    """The folded 2-pairing check.

    xs/ys: (N, L) Montgomery affine G1; inf/valid/group_b: (N,) masks;
    bits: (N, 255) scalar bits.  Lane semantics: valid & ~group_b lanes
    accumulate into the left pairing's G1 point, valid & group_b lanes
    into the right one (which is negated).  g2*: (2, ...) affine Fq2
    coords of [G2, sG2].
    """
    one = jnp.broadcast_to(jnp.asarray(fp.ONE_MONT), xs.shape)
    jac = (xs, ys, one)
    inf_pt = PT.infinity_like(PT.G1_KIT, xs)
    jac = PT._select_point(PT.G1_KIT, valid & ~inf, jac, inf_pt)
    w = PT.scalar_mul_bits(PT.G1_KIT, bits, jac)    # [s_i]P_i
    in_a = valid & ~group_b
    in_b = valid & group_b
    pa = V.point_batch_sum(PT.G1_KIT, PT._select_point(
        PT.G1_KIT, in_a, w, inf_pt))
    pb = PT.point_neg(PT.G1_KIT, V.point_batch_sum(
        PT.G1_KIT, PT._select_point(PT.G1_KIT, in_b, w, inf_pt)))
    pair = jax.tree_util.tree_map(
        lambda a, b: jnp.stack([a, b], axis=0), pa, pb)  # (2, ...)
    pair_inf = PT.is_infinity(PT.G1_KIT, pair)
    aff = V.to_affine_g1(pair)
    from . import pairing as PR
    ml = PR.miller_loop(aff, ((g2x0, g2x1), (g2y0, g2y1)),
                        mask=~pair_inf)
    return PR.pairing_check(PR.batch_product(ml))


def msm_kernel(xs, ys, present, bits):
    """Fixed-shape G1 MSM: batched constant-time ladder + log-depth
    tree sum (the Pippenger role for the prover-side commitment path;
    lanes are the batch axis so the ladder vectorizes fully).
    Returns canonical plain affine limbs + infinity flag."""
    one = jnp.broadcast_to(jnp.asarray(fp.ONE_MONT), xs.shape)
    jac = (xs, ys, one)
    inf_pt = PT.infinity_like(PT.G1_KIT, xs)
    jac = PT._select_point(PT.G1_KIT, present, jac, inf_pt)
    w = PT.scalar_mul_bits(PT.G1_KIT, bits, jac)
    total = V.point_batch_sum(PT.G1_KIT, w)
    is_inf = PT.is_infinity(PT.G1_KIT, total)
    aff = V.to_affine_g1(jax.tree_util.tree_map(
        lambda x: x[None], total))
    ax = fp.canonical_plain(aff[0][0])
    ay = fp.canonical_plain(aff[1][0])
    return is_inf, ax, ay


# --------------------------------------------------------------------------
# Host wrapper
# --------------------------------------------------------------------------

class JaxKzg:
    """Device KZG backend behind crypto/kzg's set_backend seam
    (the CKZG4844-singleton analogue, installed by the BLS loader)."""

    name = "jax-tpu"

    def __init__(self, min_bucket: int = 8):
        self.min_bucket = min_bucket
        self._eval_jit = jax.jit(eval_blob_kernel)
        self._validate_jit = jax.jit(g1_validate_kernel)
        self._fold_jit = jax.jit(fold_pairing_kernel)
        self._msm_jit = jax.jit(msm_kernel)
        self._g1_cache: dict = {}
        self._setup_cache: dict = {}
        self.dispatch_count = 0

    # -- setup constants ----------------------------------------------
    def _setup_cached(self, kind: str, setup: TrustedSetup, build):
        """id()-keyed cache entries PIN the setup object they were
        built from — a recycled id after GC must never serve another
        setup's constants."""
        key = (kind, id(setup))
        hit = self._setup_cache.get(key)
        if hit is not None and hit[0] is setup:
            return hit[1]
        value = build()
        if len(self._setup_cache) > 4:
            self._setup_cache.clear()
        self._setup_cache[key] = (setup, value)
        return value

    def _g2_consts(self, setup: TrustedSetup):
        def build():
            g2_aff = C.to_affine(C.FQ2_OPS, C.G2_GENERATOR)
            s_aff = C.to_affine(C.FQ2_OPS, setup.s_g2)
            arrs = []
            for comp in range(2):          # x then y
                for part in range(2):      # c0 then c1
                    arrs.append(np.stack([
                        fp.int_to_mont(g2_aff[comp][part]),
                        fp.int_to_mont(s_aff[comp][part])]))
            return tuple(jnp.asarray(a) for a in arrs)
        return self._setup_cached("g2", setup, build)

    def _lagrange_arrays(self, setup: TrustedSetup):
        def build():
            if setup.g1_lagrange is None:
                raise KzgError("setup has no Lagrange points")
            xs = np.zeros((_N, fp.L), dtype=np.int64)
            ys = np.zeros((_N, fp.L), dtype=np.int64)
            present = np.zeros(_N, dtype=bool)
            for i, pt in enumerate(setup.g1_lagrange):
                aff = C.to_affine(C.FQ_OPS, pt)
                if aff is None:
                    continue
                xs[i] = fp.int_to_mont(aff[0])
                ys[i] = fp.int_to_mont(aff[1])
                present[i] = True
            return (xs, ys, present)
        return self._setup_cached("lagrange", setup, build)

    # -- G1 cache ------------------------------------------------------
    def _resolve_g1(self, all_points: Sequence[bytes]):
        if len(self._g1_cache) > 100_000:
            self._g1_cache.clear()
        miss = {}
        for raw in all_points:
            if raw in self._g1_cache or raw in miss:
                continue
            wire = _parse_g1_wire(raw)
            if wire is None:
                self._g1_cache[raw] = ("bad",)
            elif wire[2]:
                self._g1_cache[raw] = ("inf",)
            else:
                miss[raw] = wire
        miss = list(miss.items())
        if not miss:
            return
        n = max(_next_pow2(len(miss)), 8)
        xs = np.zeros((n, fp.L), dtype=np.int64)
        large = np.zeros(n, dtype=bool)
        for i, (_, (x, lg, _inf)) in enumerate(miss):
            xs[i] = fp.int_to_limbs(x)
            large[i] = lg
        ok, gx, gy = self._validate_jit(xs, large)
        ok = np.asarray(ok)
        gx, gy = np.asarray(gx), np.asarray(gy)
        for i, (raw, _) in enumerate(miss):
            self._g1_cache[raw] = (("ok", gx[i], gy[i]) if ok[i]
                                   else ("bad",))

    # -- blob evaluation ----------------------------------------------
    def _evaluate(self, blobs: Sequence[bytes],
                  zs: Sequence[int]) -> List[int]:
        limbs = blob_bytes_to_limbs(blobs)
        if not limbs_lt_modulus(limbs).all():
            raise KzgError("field element out of range")
        b = len(blobs)
        pad = max(_next_pow2(b), 2)
        poly = np.zeros((pad, _N, FR.L), dtype=np.int64)
        poly[:b] = limbs
        z_mont = np.zeros((pad, FR.L), dtype=np.int64)
        for i, z in enumerate(zs):
            z_mont[i] = FR.int_to_mont(z)
        self.dispatch_count += 1
        y_plain = np.asarray(self._eval_jit(poly, z_mont))
        return [FR.limbs_to_int(y_plain[i]) for i in range(b)]

    # -- verification --------------------------------------------------
    def _fold_check(self, setup: TrustedSetup,
                    lanes: List[Tuple[tuple, int, bool]]) -> bool:
        """lanes: (cache_entry, scalar, in_group_b)."""
        n = max(_next_pow2(len(lanes)), self.min_bucket)
        xs = np.zeros((n, fp.L), dtype=np.int64)
        ys = np.zeros((n, fp.L), dtype=np.int64)
        inf = np.zeros(n, dtype=bool)
        valid = np.zeros(n, dtype=bool)
        group_b = np.zeros(n, dtype=bool)
        scalars = []
        for i, (entry, scalar, in_b) in enumerate(lanes):
            if entry[0] == "inf":
                inf[i] = True
            else:
                xs[i], ys[i] = entry[1], entry[2]
            valid[i] = True
            group_b[i] = in_b
            scalars.append(scalar % R_MOD)
        scalars += [0] * (n - len(lanes))
        bits = int_to_bits(scalars)
        g2x0, g2x1, g2y0, g2y1 = self._g2_consts(setup)
        self.dispatch_count += 1
        ok = self._fold_jit(xs, ys, inf, valid, bits, group_b,
                            g2x0, g2x1, g2y0, g2y1)
        return bool(np.asarray(ok))

    @staticmethod
    def _g1_gen_entry():
        from ..crypto.bls.constants import G1_X, G1_Y
        return ("ok", fp.int_to_mont(G1_X), fp.int_to_mont(G1_Y))

    def verify_kzg_proof(self, commitment: bytes, z: int, y: int,
                         proof: bytes, setup: TrustedSetup) -> bool:
        """e(C - [y]G1 + [z]pi, G2) * e(-pi, [s]G2) == 1."""
        self._resolve_g1([commitment, proof])
        c = self._g1_cache[commitment]
        p = self._g1_cache[proof]
        if c[0] == "bad" or p[0] == "bad":
            return False
        lanes = [(c, 1, False), (p, z % R_MOD, False),
                 (self._g1_gen_entry(), (-y) % R_MOD, False),
                 (p, 1, True)]
        return self._fold_check(setup, lanes)

    def _r_multipliers(self, blobs, commitments, proofs) -> List[int]:
        """Deterministic unpredictable fold multipliers: hash of the
        whole input set (the role of c-kzg's compute_r_powers)."""
        h = hashlib.sha256()
        h.update(RANDOM_CHALLENGE_DOMAIN)
        h.update(len(blobs).to_bytes(8, "big"))
        for b in blobs:
            h.update(hashlib.sha256(b).digest())
        for cm in commitments:
            h.update(cm)
        for pr in proofs:
            h.update(pr)
        seed = h.digest()
        out = []
        for i in range(len(blobs)):
            d = hashlib.sha256(seed + i.to_bytes(8, "big")).digest()
            out.append(int.from_bytes(d, "big") % R_MOD or 1)
        return out

    def verify_blob_kzg_proof_batch(self, blobs: Sequence[bytes],
                                    commitments: Sequence[bytes],
                                    proofs: Sequence[bytes],
                                    setup: TrustedSetup) -> bool:
        if not (len(blobs) == len(commitments) == len(proofs)):
            return False
        if not blobs:
            return True
        for b in blobs:
            if len(b) != BYTES_PER_BLOB:
                return False
        self._resolve_g1(list(commitments) + list(proofs))
        entries_c = [self._g1_cache[c] for c in commitments]
        entries_p = [self._g1_cache[p] for p in proofs]
        if any(e[0] == "bad" for e in entries_c + entries_p):
            return False
        try:
            zs = [compute_challenge(b, c)
                  for b, c in zip(blobs, commitments)]
            ys = self._evaluate(blobs, zs)
        except KzgError:
            return False
        rs = self._r_multipliers(blobs, commitments, proofs)
        lanes = []
        acc_y = 0
        for e_c, e_p, z, y, r in zip(entries_c, entries_p, zs, ys, rs):
            lanes.append((e_c, r, False))
            lanes.append((e_p, r * z, False))
            lanes.append((e_p, r, True))
            acc_y += r * y
        lanes.append((self._g1_gen_entry(), -acc_y, False))
        return self._fold_check(setup, lanes)

    def verify_blob_kzg_proof(self, blob: bytes, commitment: bytes,
                              proof: bytes, setup: TrustedSetup) -> bool:
        return self.verify_blob_kzg_proof_batch(
            [blob], [commitment], [proof], setup)

    # -- prover-side MSM (commitments/proofs from real setups) ---------
    def g1_lincomb(self, setup: TrustedSetup,
                   scalars: Sequence[int]) -> bytes:
        """MSM over the setup's Lagrange basis -> compressed G1."""
        xs, ys, present = self._lagrange_arrays(setup)
        bits = int_to_bits([s % R_MOD for s in scalars])
        if bits.shape[0] != _N:
            raise KzgError("scalar count must match basis size")
        self.dispatch_count += 1
        is_inf, ax, ay = self._msm_jit(xs, ys, present, bits)
        if bool(np.asarray(is_inf)):
            return bytes([0xC0] + [0] * 47)
        x = fp.limbs_to_int(np.asarray(ax))
        y = fp.limbs_to_int(np.asarray(ay))
        return C.g1_compress((x, y, 1))
