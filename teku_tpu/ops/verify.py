"""Batched BLS signature verification kernel — the TPU north star.

One jitted dispatch verifies a whole batch of (aggregate-pubkey, message,
signature) triples with the random-multiplier scheme (ethresear.ch/5407),
replacing the reference's native pairing loop (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/blst/
BlstBLS12381.java:124-189 — mul_n_aggregate / commit / merge /
finalverify, and BLS.batchVerify at bls/BLS.java:230-254):

  ok  <=>  prod_i e([r_i]pk_i, H(m_i)) * e(-g1, sum_i [r_i]sig_i) == 1

Everything after SHA-256 message expansion runs on device in fixed shapes:
signature decompression + psi-endomorphism subgroup checks, hash-to-G2
(SSWU + isogeny + Budroni-Pintore), constant-time 64-bit scalar
multiplications, the batched Miller loops, a log-depth product/point-sum
reduction over the batch, and one shared final exponentiation.

DEDUP-AWARE: committee-based consensus signs the same AttestationData
across whole committees, so a gossip batch has far fewer UNIQUE
messages than lanes.  The pipeline exploits this twice: hash-to-G2
runs over the unique-message bucket only (stage_h2c + stage_gather_hm
scatters the points back to lanes), and — since the pairing is
bilinear in G1 — stage_group folds every message's r-weighted pubkeys
into ONE Miller loop per unique (prod_i e([r_i]pk_i, H(m)) ==
e(sum_i [r_i]pk_i, H(m))), collapsing the two dominant per-lane stages
by the duplication factor with an unchanged verdict.

Lanes carry masks instead of branches: padding lanes (valid=False)
contribute the identity; infinity signatures contribute the infinity
point exactly like the oracle (crypto/bls/pure_impl.py:205-214).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls import curve as C
from . import h2c
from . import limbs as fp
from . import msm as MSM
from . import pairing as PR
from . import points as PT
from . import towers as T

# -g1 generator, host-computed affine constant
_NEG_G1 = C.to_affine(C.FQ_OPS, C.point_neg(C.FQ_OPS, C.G1_GENERATOR))
_NEG_G1_X = np.asarray(fp.int_to_mont(_NEG_G1[0]))
_NEG_G1_Y = np.asarray(fp.int_to_mont(_NEG_G1[1]))


# shared with the MSM kernels; re-exported for the KZG/parallel callers
point_batch_sum = PT.point_batch_sum


def to_affine_g1(p):
    """Batched Jacobian -> affine for G1 (one batched inversion: a
    single Fermat exponentiation for the whole batch)."""
    zinv = fp.inv_many(p[2])
    zinv2 = fp.mont_sqr(zinv)
    t = fp.mont_mul(jnp.stack([p[0], fp.mont_mul(zinv2, zinv)], axis=-2),
                    jnp.stack([zinv2, p[1]], axis=-2))
    return (t[..., 0, :], t[..., 1, :])


def _aggregate_lane_pks(pk_xs, pk_ys, pk_present):
    """Per-lane pubkey aggregation INSIDE the dispatch: (N, K, L) padded
    affine key matrices -> one Jacobian aggregate per lane + infinity
    flag.  Replaces the reference's host-side aggregate loop (and round
    2's per-triple device round trips) with a log2(K)-depth masked tree
    sum that ships in the same compiled program."""
    one = jnp.broadcast_to(jnp.asarray(fp.ONE_MONT), pk_xs.shape)
    if pk_xs.shape[-2] == 1:
        pk_jac = (pk_xs[..., 0, :], pk_ys[..., 0, :], one[..., 0, :])
        inf = PT.infinity_like(PT.G1_KIT, pk_jac[0])
        pk_jac = PT._select_point(PT.G1_KIT, ~pk_present[..., 0],
                                 inf, pk_jac)
    else:
        jac = (pk_xs, pk_ys, one)
        inf = PT.infinity_like(PT.G1_KIT, pk_xs)
        jac = PT._select_point(PT.G1_KIT, pk_present, jac, inf)
        jac = jax.tree_util.tree_map(
            lambda x: jnp.moveaxis(x, -2, 0), jac)   # (K, N, L)
        pk_jac = point_batch_sum(PT.G1_KIT, jac)
    return pk_jac, PT.is_infinity(PT.G1_KIT, pk_jac)


def _lane_work(pk_xs, pk_ys, pk_present, hm_aff, sig_x_plain, sig_large,
               sig_inf, r_bits, lane_valid):
    """Per-lane pipeline (shardable over the batch axis with no
    communication), COMPOSED from the stage functions below so the
    monolithic/sharded kernels and the staged dispatch can never
    diverge.

    Takes the per-lane H(m) AFFINE points (`hm_aff`), not the field
    draws: hash-to-curve runs over the batch's UNIQUE messages upstream
    (stage_h2c on a smaller bucket + stage_gather_hm, or the provider's
    device-resident H(m) cache) — in committee-based consensus a batch
    has far fewer distinct messages than lanes, and h2c is the largest
    per-lane stage.

    Returns (ml (N-lane Fq12 values), wsig (N weighted sig points),
    lane_ok (N,))."""
    pk_jac, sig_jac, lane_ok, miller_mask = stage_prepare(
        pk_xs, pk_ys, pk_present, sig_x_plain, sig_large, sig_inf,
        lane_valid)
    pk_r_jac, wsig = stage_scalars(pk_jac, sig_jac, r_bits)
    ml = stage_miller(stage_lane_affine(pk_r_jac), hm_aff, miller_mask)
    return ml, wsig, lane_ok


def _finish(ml_prod, s_sum):
    """Cross-lane combine: one Miller loop on the aggregated-signature
    lane and the shared final exponentiation."""
    s_inf = PT.is_infinity(PT.G2_KIT, s_sum)
    s_aff = h2c.to_affine_g2(tuple(
        jax.tree_util.tree_map(lambda x: x[None], c) for c in s_sum))
    neg_g1 = (jnp.asarray(_NEG_G1_X)[None], jnp.asarray(_NEG_G1_Y)[None])
    ml_s = PR.miller_loop(neg_g1, s_aff, mask=~s_inf[None])
    f = T.fq12_mul(ml_prod, jax.tree_util.tree_map(lambda x: x[0], ml_s))
    return PR.pairing_check(f)


def verify_kernel(pk_xs, pk_ys, pk_present, u0, u1, group_idx,
                  group_present, sig_x_plain, sig_large, sig_inf,
                  r_bits, lane_valid):
    """The batched verification dispatch (single device), dedup-aware.

    pk_xs/pk_ys: (N, K, L) Montgomery limbs — per-triple pubkeys, each
        already validated (subgroup, non-infinity) by the caller's
        cache, padded to K along axis 1; aggregation happens in-kernel.
    u0/u1: Fq2 draws of the batch's UNIQUE messages' hash_to_field
        (host SHA-256), padded to a pow-2 bucket U <= N — h2c runs at
        unique width, not lane width.
    group_idx/group_present: (U, G) lane indices/mask of each unique
        message's lanes (stage_group: bilinearity folds those lanes
        into one Miller loop per unique).
    pk_present: (N, K) — False for key-padding slots.
    sig_x_plain: ((N, L), (N, L)) plain-form Fq2 x of each signature;
    sig_large: (N,) wire sign bit; sig_inf: (N,) infinity-signature mask.
    r_bits: (N, 64) bits of the nonzero random multipliers, MSB first.
    lane_valid: (N,) — False for padding lanes.

    Returns (ok, lane_ok): ok is the whole-batch pairing verdict;
    lane_ok flags lanes whose signature failed decompression/subgroup
    checks or whose keys aggregated to infinity (the caller must AND
    `ok` with all valid lanes' lane_ok).
    """
    hm_uniq = stage_h2c(u0, u1)
    pk_jac, sig_jac, lane_ok, miller_mask = stage_prepare(
        pk_xs, pk_ys, pk_present, sig_x_plain, sig_large, sig_inf,
        lane_valid)
    pk_r_jac, wsig = stage_scalars(pk_jac, sig_jac, r_bits)
    agg_aff, u_mask = stage_group(pk_r_jac, miller_mask, group_idx,
                                  group_present)
    ml = stage_miller(agg_aff, hm_uniq, u_mask)
    ok = _finish(PR.batch_product(ml), point_batch_sum(PT.G2_KIT, wsig))
    return ok, lane_ok


# --------------------------------------------------------------------------
# Staged variant: the SAME math as verify_kernel, split into five
# separately-jitted programs.  The monolithic kernel's TPU-XLA compile is
# unbounded in practice (>60 min observed on v5e); each stage compiles in
# minutes, caches independently in the persistent compile cache, and the
# chain keeps all intermediates on device.
# --------------------------------------------------------------------------

def stage_prepare(pk_xs, pk_ys, pk_present, sig_x_plain, sig_large,
                  sig_inf, lane_valid):
    """Key aggregation + signature decompression/subgroup checks."""
    pk_jac, pk_inf = _aggregate_lane_pks(pk_xs, pk_ys, pk_present)
    dec_ok, sig_pt = PT.g2_recover_y(sig_x_plain, sig_large)
    in_sub = PT.g2_in_subgroup(sig_pt)
    sig_ok = (dec_ok & in_sub) | sig_inf
    use_inf = sig_inf | ~sig_ok | ~lane_valid
    sig_jac = PT._select_point(
        PT.G2_KIT, use_inf, PT.infinity_like(PT.G2_KIT, sig_pt[0]), sig_pt)
    return pk_jac, sig_jac, sig_ok & ~pk_inf, lane_valid & ~pk_inf


def stage_h2c(u0, u1):
    """Hash-to-G2 map + cofactor clearing + batched affine.

    Runs over the UNIQUE-message bucket, not lanes: callers dedup the
    batch's messages, dispatch this at the (smaller, pow-2) unique
    width, and scatter the mapped points back with stage_gather_hm."""
    return h2c.to_affine_g2(h2c.hash_to_g2_device(u0, u1))


def stage_gather_hm(hm_uniq, lane_map):
    """Scatter the unique-message H(m) points back into lanes: one
    device gather of the affine coordinate arrays along the unique
    axis.  `lane_map` is the (N,) unique index of each lane's message
    (padding lanes may carry any in-range index — downstream masks,
    not the gathered point, decide their contribution)."""
    return jax.tree_util.tree_map(lambda x: x[lane_map], hm_uniq)


def stage_scalars(pk_jac, sig_jac, r_bits):
    """Random-multiplier scalar muls (Jacobian G1 out — the affine
    conversion happens per-lane in stage_lane_affine or per-UNIQUE in
    stage_group, whichever path runs)."""
    pk_r_jac = PT.scalar_mul_bits(PT.G1_KIT, r_bits, pk_jac)
    wsig = PT.scalar_mul_bits(PT.G2_KIT, r_bits, sig_jac)
    return pk_r_jac, wsig


def stage_lane_affine(pk_r_jac):
    """Per-lane batched G1 affine (the non-grouped pipeline)."""
    return to_affine_g1(pk_r_jac)


def stage_group(pk_r_jac, miller_mask, group_idx, group_present):
    """Fold each unique message's lanes into ONE pairing input.

    The pairing is bilinear in its G1 argument, so lanes sharing H(m)
    satisfy prod_i e([r_i]pk_i, H(m)) == e(sum_i [r_i]pk_i, H(m)): the
    per-lane Miller loops of a committee-duplicated batch collapse to
    one loop per UNIQUE message.  Masked lanes (padding/invalid) enter
    the sum as infinity — exactly the identity contribution the
    per-lane mask gave them — and a unique whose aggregate is infinity
    is masked out of the Miller stage (e(infinity, Q) == 1).

    group_idx: (U, G) lane indices of each unique's lanes (padded rows
    arbitrary); group_present: (U, G) False for group padding.
    Returns ((x, y) affine aggregates (U, L), u_mask (U,))."""
    inf = PT.infinity_like(PT.G1_KIT, pk_r_jac[0])
    masked = PT._select_point(PT.G1_KIT, miller_mask, pk_r_jac, inf)
    grouped = jax.tree_util.tree_map(lambda x: x[group_idx], masked)
    inf_g = PT.infinity_like(PT.G1_KIT, grouped[0])
    grouped = PT._select_point(PT.G1_KIT, group_present, grouped, inf_g)
    if group_idx.shape[1] == 1:
        agg = jax.tree_util.tree_map(lambda x: x[:, 0], grouped)
    else:
        gmoved = jax.tree_util.tree_map(
            lambda x: jnp.moveaxis(x, 1, 0), grouped)   # (G, U, L)
        agg = point_batch_sum(PT.G1_KIT, gmoved)
    u_mask = ~PT.is_infinity(PT.G1_KIT, agg)
    # affine conversion now costs ONE batched inversion at unique
    # width, not lane width (infinity aggregates give garbage coords —
    # u_mask carries them out of the Miller loop)
    return to_affine_g1(agg), u_mask


def stage_scalars_pippenger(pk_jac, sig_jac, glv_digits, group_idx,
                            group_present, miller_mask):
    """The MSM-grade replacement for stage_scalars + stage_group
    (ops/msm.py): multipliers arrive GLV-decomposed as (N, 2, nwin)
    w-bit digit arrays (r_i = k1_i + k2_i*lambda mod r), the per-group
    G1 folds run as Pippenger bucket MSMs over (lane, phi(lane))
    columns — ONE doubling chain per group row — and the whole-batch
    G2 signature fold collapses to a single bucketed MSM (stage_finish
    only ever consumes the wsig SUM, so `wsig` comes back as a
    1-batch point and point_batch_sum is the identity on it).

    Same output contract as stage_group + the wsig half of
    stage_scalars: (agg_aff (U, ...), u_mask (U,), wsig (1, ...))."""
    agg = MSM.g1_grouped_msm(pk_jac, glv_digits, group_idx,
                             group_present, miller_mask)
    u_mask = ~PT.is_infinity(PT.G1_KIT, agg)
    wsig = MSM.g2_msm(sig_jac, glv_digits)
    return to_affine_g1(agg), u_mask, wsig


def stage_miller(pk_r_aff, hm_aff, mask):
    """Miller loops — width-polymorphic: per-lane inputs on the
    hm-gather path, per-unique aggregates on the grouped path."""
    return PR.miller_loop(pk_r_aff, hm_aff, mask=mask)


def stage_finish(ml, wsig):
    """Cross-lane reduction + final exponentiation + verdict."""
    return _finish(PR.batch_product(ml), point_batch_sum(PT.G2_KIT, wsig))


_STAGED_JITS = None
_STAGED_LOCK = __import__("threading").Lock()


def staged_jits():
    global _STAGED_JITS
    if _STAGED_JITS is None:
        with _STAGED_LOCK:      # batch_verify runs via asyncio.to_thread
            if _STAGED_JITS is None:
                from ..infra import aotstore
                from . import mxu
                # mont path is part of the traced program, so it is
                # part of the store identity (an executable traced
                # for vpu must not serve an mxu process)
                mont = mxu.resolve()

                def _wrap(name, fn):
                    return aotstore.wrap(f"stage:{name}:{mont}",
                                         jax.jit(fn))
                _STAGED_JITS = {
                    "prepare": _wrap("prepare", stage_prepare),
                    "h2c": _wrap("h2c", stage_h2c),
                    "gather": _wrap("gather", stage_gather_hm),
                    "scalars": _wrap("scalars", stage_scalars),
                    "affine": _wrap("affine", stage_lane_affine),
                    "group": _wrap("group", stage_group),
                    "scalars_pip": _wrap("scalars_pip",
                                         stage_scalars_pippenger),
                    "miller": _wrap("miller", stage_miller),
                    "finish": _wrap("finish", stage_finish),
                }
    return _STAGED_JITS


def _stage_runner(on_stage):
    import time
    jits = staged_jits()

    def run(name, *args):
        t0 = time.time()
        out = jits[name](*args)
        if on_stage is not None:
            jax.block_until_ready(out)
            on_stage(name, time.time() - t0)
        return out

    return run


def verify_staged_hm(pk_xs, pk_ys, pk_present, hm_aff, sig_x_plain,
                     sig_large, sig_inf, r_bits, lane_valid,
                     on_stage=None):
    """The staged PER-LANE pipeline downstream of hash-to-curve:
    per-lane H(m) affine points in (the provider's H(m) cache or
    stage_h2c + stage_gather_hm supplies them), verdict out.  This is
    the parity surface for the grouped path and the composition the
    sharded kernel uses.  `on_stage(name, seconds)` reports per-stage
    wall time (bench)."""
    run = _stage_runner(on_stage)
    pk_jac, sig_jac, lane_ok, miller_mask = run(
        "prepare", pk_xs, pk_ys, pk_present, sig_x_plain, sig_large,
        sig_inf, lane_valid)
    pk_r_jac, wsig = run("scalars", pk_jac, sig_jac, r_bits)
    pk_r_aff = run("affine", pk_r_jac)
    ml = run("miller", pk_r_aff, hm_aff, miller_mask)
    ok = run("finish", ml, wsig)
    return ok, lane_ok


def verify_staged_grouped(pk_xs, pk_ys, pk_present, hm_uniq, group_idx,
                          group_present, sig_x_plain, sig_large,
                          sig_inf, r_bits, lane_valid, on_stage=None):
    """The staged GROUPED pipeline: unique-width H(m) points in (from
    stage_h2c over uniques or the device H(m) cache), per-message
    pubkey aggregation via stage_group, Miller loops at UNIQUE width."""
    run = _stage_runner(on_stage)
    pk_jac, sig_jac, lane_ok, miller_mask = run(
        "prepare", pk_xs, pk_ys, pk_present, sig_x_plain, sig_large,
        sig_inf, lane_valid)
    pk_r_jac, wsig = run("scalars", pk_jac, sig_jac, r_bits)
    agg_aff, u_mask = run("group", pk_r_jac, miller_mask, group_idx,
                          group_present)
    ml = run("miller", agg_aff, hm_uniq, u_mask)
    ok = run("finish", ml, wsig)
    return ok, lane_ok


def verify_staged_pippenger(pk_xs, pk_ys, pk_present, hm_uniq,
                            group_idx, group_present, sig_x_plain,
                            sig_large, sig_inf, glv_digits, lane_valid,
                            on_stage=None):
    """The staged GROUPED pipeline with the MSM-grade scalars stage
    (`--msm-path pippenger`): GLV digit arrays replace r_bits, the
    scalars_pip program absorbs stage_group, verdict contract is
    bit-identical to verify_staged_grouped driven with the effective
    multipliers r_i = k1_i + k2_i*lambda (tests/test_msm.py)."""
    run = _stage_runner(on_stage)
    pk_jac, sig_jac, lane_ok, miller_mask = run(
        "prepare", pk_xs, pk_ys, pk_present, sig_x_plain, sig_large,
        sig_inf, lane_valid)
    agg_aff, u_mask, wsig = run("scalars_pip", pk_jac, sig_jac,
                                glv_digits, group_idx, group_present,
                                miller_mask)
    ml = run("miller", agg_aff, hm_uniq, u_mask)
    ok = run("finish", ml, wsig)
    return ok, lane_ok


def verify_staged(pk_xs, pk_ys, pk_present, u0, u1, group_idx,
                  group_present, sig_x_plain, sig_large, sig_inf,
                  r_bits, lane_valid, on_stage=None):
    """Same contract as verify_kernel (unique-message draws + group
    index), via the staged programs.  `on_stage(name, seconds)` reports
    per-stage wall time (bench)."""
    run = _stage_runner(on_stage)
    hm_uniq = run("h2c", u0, u1)
    return verify_staged_grouped(pk_xs, pk_ys, pk_present, hm_uniq,
                                 group_idx, group_present, sig_x_plain,
                                 sig_large, sig_inf, r_bits, lane_valid,
                                 on_stage=on_stage)


def verify_kernel_sharded_grouped(mesh, axis: str = "dp",
                                  msm_path: str = "ladder"):
    """Multi-chip variant of the DEDUP-AWARE pipeline: message groups
    are the sharding unit, so every chip keeps the unique-message
    Miller grouping (and, with ``msm_path="pippenger"``, the bucketed
    MSM scalars stage) that the lane-sharded kernel forfeits.

    GROUP-ALIGNED contract (the provider's shard planner,
    teku_tpu/parallel.plan_group_shards, builds these layouts):

    - lanes are PERMUTED so each shard's lane block holds exactly the
      lanes of the message-group rows that shard owns (a group never
      crosses a shard boundary); lane-sharded inputs: pk_xs/pk_ys
      (N, K, L), pk_present (N, K), sig_x ((N, L), (N, L)), sig_large/
      sig_inf/lane_valid (N,), and the scalars array — r_bits (N, 64)
      on the ladder path, glv_digits (N, 2, nwin) on the pippenger
      path;
    - group rows are ROW-sharded: hm_rows (the per-row H(m) affine
      tree, (U, L) leaves), group_idx (U, G) of SHARD-LOCAL lane
      indices, group_present (U, G).  Padding rows aggregate to
      infinity and mask themselves out of the Miller stage, so empty
      shards contribute exactly the identity.

    Per shard: prepare -> scalars+group (ladder) or the fused
    Pippenger MSM -> Miller loops at LOCAL row width -> local Fq12
    product + local G2 weighted-signature sum; then ONE all_gather of
    those two tiny partials crosses the ICI and the final
    exponentiation is replicated.  Returns (ok, lane_ok) with lane_ok
    in the PERMUTED lane order (callers un-permute on the host).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    lane = P(axis)
    lane2 = P(axis, None)        # (N, L) / (N, 64) / (N, K)
    lane3 = P(axis, None, None)  # (N, K, L) / (N, 2, nwin)
    row2 = P(axis, None)         # (U, G) and the (U, L) hm leaves
    pippenger = msm_path == "pippenger"

    def shard_fn(pk_xs, pk_ys, pk_present, hm_rows, group_idx,
                 group_present, sig_x, sig_large, sig_inf, scalars,
                 lane_valid):
        pk_jac, sig_jac, lane_ok, miller_mask = stage_prepare(
            pk_xs, pk_ys, pk_present, sig_x, sig_large, sig_inf,
            lane_valid)
        if pippenger:
            agg_aff, u_mask, wsig = stage_scalars_pippenger(
                pk_jac, sig_jac, scalars, group_idx, group_present,
                miller_mask)
        else:
            pk_r_jac, wsig = stage_scalars(pk_jac, sig_jac, scalars)
            agg_aff, u_mask = stage_group(pk_r_jac, miller_mask,
                                          group_idx, group_present)
        ml = stage_miller(agg_aff, hm_rows, u_mask)
        local_prod = PR.batch_product(ml)
        local_sum = point_batch_sum(PT.G2_KIT, wsig)
        # the tiny per-device partials (one Fq12 value + one G2 point)
        # are the ONLY cross-chip traffic; combine + finish replicated
        gathered_prod = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis), local_prod)
        gathered_sum = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis), local_sum)
        ok = _finish(PR.batch_product(gathered_prod),
                     point_batch_sum(PT.G2_KIT, gathered_sum))
        return ok, lane_ok

    in_specs = (lane3, lane3, lane2,
                ((row2, row2), (row2, row2)),   # hm rows (affine x, y)
                row2, row2,                     # group idx / present
                (lane2, lane2), lane, lane,
                lane3 if pippenger else lane2,  # glv digits | r bits
                lane)
    out_specs = (P(), lane)
    return shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def verify_kernel_sharded(mesh, axis: str = "dp"):
    """LEGACY multi-chip variant: lanes sharded over `axis` with NO
    message grouping (every lane pays its own Miller row — groups
    would cross shard boundaries), per-device local reductions, then
    an all_gather of one Fq12 value + one G2 point per device rides
    the ICI; the final exponentiation is replicated.  The production
    mesh path uses verify_kernel_sharded_grouped, which keeps the
    dedup pipeline by making group rows the sharding unit; this form
    remains the dryrun/CI harness kernel and the hm-input parity
    surface.

    hm-INPUT contract: the caller supplies per-lane H(m) affine points
    (hash-to-curve over unique messages is a global operation — the
    provider runs it once, cache-aware, before sharding lanes), so the
    shard function's inputs are all lane-sharded.

    Returns a function taking (pk_xs, pk_ys, pk_present, hm, sig_x,
    sig_large, sig_inf, r_bits, lane_valid) with verify_kernel's result
    (to be called with GLOBAL batch arrays; N must divide the mesh size).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    lane = P(axis)
    lane2 = P(axis, None)       # (N, L) and (N, 64)
    lane3 = P(axis, None, None)  # (N, K, L)

    def shard_fn(pk_xs, pk_ys, pk_present, hm, sig_x, sig_large,
                 sig_inf, r_bits, lane_valid):
        ml, wsig, lane_ok = _lane_work(pk_xs, pk_ys, pk_present, hm,
                                       sig_x, sig_large, sig_inf, r_bits,
                                       lane_valid)
        local_prod = PR.batch_product(ml)
        local_sum = point_batch_sum(PT.G2_KIT, wsig)
        # gather the tiny per-device partials and combine identically
        gathered_prod = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis), local_prod)
        gathered_sum = jax.tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axis), local_sum)
        total_prod = PR.batch_product(gathered_prod)
        total_sum = point_batch_sum(PT.G2_KIT, gathered_sum)
        ok = _finish(total_prod, total_sum)
        return ok, lane_ok

    in_specs = (lane3, lane3, lane2,
                ((lane2, lane2), (lane2, lane2)),   # hm affine x, y
                (lane2, lane2), lane, lane, lane2, lane)
    out_specs = (P(), lane)
    return shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def aggregate_points_kernel(kit, xs, ys, present):
    """Sum a padded batch of affine points; absent lanes are infinity.
    Returns the Jacobian sum."""
    one = PT._broadcast_const(kit, kit.const(1 if kit is PT.G1_KIT else (1, 0)),
                              xs)
    jac = (xs, ys, one)
    inf = PT.infinity_like(kit, xs)
    jac = PT._select_point(kit, present, jac, inf)
    return point_batch_sum(kit, jac)
