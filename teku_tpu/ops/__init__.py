"""TPU kernel library (JAX) — fixed-width bigint crypto for the hot path.

This package is the TPU-native replacement for the reference's native
crypto layer (blst C/asm via JNI, reference: infrastructure/bls/.../impl/
blst/).

IMPORT SIDE EFFECT: the limb kernels require 64-bit integer lanes, so
importing this package enables jax x64 mode PROCESS-WIDE (new arrays and
literals default to int64/float64; arrays created earlier keep their
dtype).  teku_tpu is an application (a consensus node), not an embeddable
library, so it owns this global; anything embedding these kernels in a
32-bit JAX program must isolate them in their own process.
"""

import jax

jax.config.update("jax_enable_x64", True)
if not jax.config.jax_enable_x64:  # pragma: no cover - defensive
    raise RuntimeError("teku_tpu.ops requires jax x64 mode; enabling it failed")
