"""fp381 fixed-width limb arithmetic for TPU (JAX).

The base field Fq of BLS12-381 (381-bit prime P) as 15 limbs of 26 bits
in int64 lanes, Montgomery form (a*R mod P, R = 2^390).  This replaces
the native blst limb arithmetic the reference client calls through JNI
(reference: infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/
blst/BlstBLS12381.java — there delegated to C/asm).

LAZY-REDUCTION DESIGN.  Serial carry chains are the enemy of both XLA
compile time and TPU runtime, so they are paid only where mathematically
required:

- `add`/`sub`/`neg`/`double`/`mul_small` are PURE ELEMENTWISE lane ops —
  no carry propagation, no mod-P reduction.  Limbs are signed and are
  allowed to grow; int64 headroom absorbs it.
- `compress` folds a value back to one "unit" (low limbs canonical in
  [0, 2^W), small signed top limb) with a single carry scan.
- `mont_mul`/`mont_sqr` accept bounded lazy operands and emit one
  compressed unit with value in (-P, 2P): one reduction scan plus one
  compress scan, and NO conditional subtraction.
- Exact mod-P representatives exist only where semantics demand them
  (`canonical`, used by eq / is-zero / wire-format comparisons): a
  Montgomery multiply maps any lazy value x to x*R mod P in [0, P),
  which is a bijection on residue classes, so comparing canonical
  images decides equality.

Operand-magnitude contract: a compressed unit has low limbs < 2^W and
|top limb| < 2^22.  Callers may feed mont_mul sums/differences of units
as long as units(a) * units(b) <= 64 — the product-column bound
15 * (ua*2^W)(ub*2^W) then stays under 2^62.  Call sites that approach
the bound carry a comment.  Everything broadcasts over leading batch
dims; batching is plain array broadcasting.

TWO mont_mul engines live behind one contract: the VPU pad-and-sum
path below, and the MXU int8 digit-split matmul path (ops/mxu.py) —
`mont_mul`/`mont_sqr` dispatch at trace time on the process-global
path config (`--mont-path` / TEKU_TPU_MONT_MUL; auto = mxu only on a
TPU dispatch device).  Both emit one compressed unit in (-P, 2P)
through the SAME `_mont_reduce` scan, so outputs are bit-identical.

Layer validation: tests/test_ops_limbs.py checks every op against the
pure-Python oracle (teku_tpu/crypto/bls/fields.py), on both paths.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls.constants import P
from . import mxu as _mxu

# --------------------------------------------------------------------------
# Representation constants
# --------------------------------------------------------------------------

W = 26                    # bits per limb
L = 15                    # limb count (15*26 = 390 >= 381)
MASK = (1 << W) - 1
RADIX = 1 << W

R_MOD_P = (1 << (W * L)) % P          # Montgomery R mod P
R2_MOD_P = (R_MOD_P * R_MOD_P) % P    # R^2 mod P (to_mont multiplier)
N0INV = (-pow(P, -1, RADIX)) % RADIX  # -P^-1 mod 2^W


def int_to_limbs(x: int) -> np.ndarray:
    """Host-side: python int -> canonical limb vector (NOT Montgomery form)."""
    if not 0 <= x < (1 << (W * L)):
        raise ValueError("value out of limb range")
    return np.array([(x >> (W * i)) & MASK for i in range(L)], dtype=np.int64)


def limbs_to_int(a) -> int:
    """Host-side: (possibly lazy, signed) limb vector -> python int mod P."""
    a = np.asarray(a)
    return sum(int(a[..., i]) << (W * i) for i in range(L)) % P


P_LIMBS = int_to_limbs(P)
ZERO = np.zeros(L, dtype=np.int64)
ONE_MONT = int_to_limbs(R_MOD_P)          # 1 in Montgomery form
ONE_PLAIN = int_to_limbs(1)
R2_LIMBS = int_to_limbs(R2_MOD_P)


def int_to_mont(x: int) -> np.ndarray:
    """Host-side: python int mod P -> Montgomery-form limb vector."""
    return int_to_limbs((x % P) * R_MOD_P % P)


def mont_to_int(a) -> int:
    """Host-side: Montgomery-form limbs -> python int mod P."""
    return limbs_to_int(a) * pow(R_MOD_P, -1, P) % P


# --------------------------------------------------------------------------
# Lazy elementwise ops (no carries, no reduction)
# --------------------------------------------------------------------------

def add(a, b):
    return a + b


def sub(a, b):
    return a - b


def neg(a):
    return -a


def double(a):
    return a + a


def mul_small(a, k: int):
    """Multiply by a small static int (grows units by |k|)."""
    return a * k


def select(cond, a, b):
    """Lane select: cond True -> a, else b.  cond shape = batch shape."""
    return jnp.where(cond[..., None], a, b)


# --------------------------------------------------------------------------
# Carry machinery
# --------------------------------------------------------------------------

def compress(r):
    """One signed carry scan; folds the final carry into the top limb.

    Input: any lazy value with |limbs| < 2^62 and |value| < 2^(W*L+20).
    Output: value-preserving unit — limbs 0..L-2 in [0, 2^W), top limb
    signed with |top| ~ value / 2^(W*(L-1)).
    """
    def step(c, col):
        v = col + c
        return v >> W, v & MASK
    c0 = jnp.zeros(r.shape[:-1], dtype=jnp.int64)
    c, limbs = lax.scan(step, c0, jnp.moveaxis(r, -1, 0))
    limbs = jnp.moveaxis(limbs, 0, -1)
    return limbs.at[..., L - 1].add(c * RADIX)


def _sub_with_borrow(a, b):
    """(a - b) limbwise with sequential borrow; canonical inputs.
    Returns (diff, borrow): borrow 0 if a >= b else -1."""
    a, b = jnp.broadcast_arrays(a, b)
    def step(c, cols):
        v = cols[0] - cols[1] + c
        return v >> W, v & MASK
    c0 = jnp.zeros(a.shape[:-1], dtype=jnp.int64)
    c, limbs = lax.scan(step, c0,
                        (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0)))
    return jnp.moveaxis(limbs, 0, -1), c


def _cond_sub_p(a):
    """Canonical-limbed a in [0, 2P) -> a mod P."""
    p = jnp.asarray(P_LIMBS)
    d, borrow = _sub_with_borrow(a, p)
    return jnp.where((borrow != 0)[..., None], a, d)


def gt(a, b):
    """a > b as integers; both inputs must be truly canonical."""
    _, borrow = _sub_with_borrow(b, a)
    return borrow != 0


# --------------------------------------------------------------------------
# Montgomery multiplication
# --------------------------------------------------------------------------

def _pad_last(x, lo, hi):
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(lo, hi)])


def _mont_reduce(t):
    """Word-serial Montgomery reduction of 2L product columns (one scan),
    then compress.  Signed columns are fine: `& MASK` and arithmetic
    shifts compute the correct residues/floors.  Output value in (-P, 2P).
    """
    p_pad = _pad_last(jnp.asarray(P_LIMBS), 0, L)

    def red(t, _):
        m = ((t[..., 0] & MASK) * N0INV) & MASK
        t = t + m[..., None] * p_pad
        c = t[..., 0] >> W
        head = t[..., 1:2] + c[..., None]
        t = jnp.concatenate(
            [head, t[..., 2:], jnp.zeros_like(t[..., :1])], axis=-1)
        return t, None

    t, _ = lax.scan(red, t, None, length=L)
    return compress(t[..., :L])


def mont_mul_vpu(a, b):
    """Montgomery product a*b*R^-1 (one unit out, value in (-P, 2P)).

    Schoolbook column products built by pad-and-sum — no scatters, no
    carries; XLA fuses the static pads into one elementwise reduction.
    """
    t = sum(_pad_last(a[..., i:i + 1] * b, i, L - i) for i in range(L))
    return _mont_reduce(t)


def mont_sqr_vpu(a):
    """Montgomery squaring: symmetric cross products computed once and
    doubled (~half the limb multiplies of mont_mul)."""
    rows = []
    for i in range(L):
        diag = a[..., i:i + 1] * a[..., i:i + 1]
        cross = 2 * a[..., i:i + 1] * a[..., i + 1:]
        seg = jnp.concatenate([diag, cross], axis=-1)   # columns 2i..i+L-1
        rows.append(_pad_last(seg, 2 * i, L - i))
    return _mont_reduce(sum(rows))


# MXU path: same operand contract, same _mont_reduce, product columns
# built as batched int8 digit-split dot_general (ops/mxu.py)
mont_mul_mxu, mont_sqr_mxu = _mxu.make_digit_kernels(
    L, W, P.bit_length(), compress, _mont_reduce)


def mont_mul(a, b):
    """Montgomery product via the configured engine (vpu | mxu).

    The path is resolved at TRACE time from the process-global config;
    a jitted program keeps the path it was traced with."""
    if _mxu.active():
        return mont_mul_mxu(a, b)
    return mont_mul_vpu(a, b)


def mont_sqr(a):
    """Montgomery squaring via the configured engine (vpu | mxu)."""
    if _mxu.active():
        return mont_sqr_mxu(a)
    return mont_sqr_vpu(a)


def to_mont(a):
    """Plain limbs -> Montgomery form (one unit)."""
    return mont_mul(a, jnp.asarray(R2_LIMBS))


# --------------------------------------------------------------------------
# Canonical representatives (equality / wire formats)
# --------------------------------------------------------------------------

def canonical(a):
    """Map any bounded lazy value to THE canonical limbs of (a*R) mod P.

    a*R mod P is a bijection on residue classes, so canonical images
    decide equality and zero-ness; callers comparing against constants
    must pass them through the same map.
    """
    y = mont_mul(a, jnp.asarray(R2_LIMBS))   # value in (-P, 2P)
    y = compress(y + jnp.asarray(P_LIMBS))   # (0, 3P), canonical limbs
    return _cond_sub_p(_cond_sub_p(y))


def canonical_plain(a):
    """Exact canonical plain-form (non-Montgomery) limbs of a Montgomery
    unit — for wire-format comparisons (sign bit, x < P checks)."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    y = mont_mul(a, one)                     # value = plain, in (-P, 2P)
    y = compress(y + jnp.asarray(P_LIMBS))
    return _cond_sub_p(_cond_sub_p(y))


def is_zero(a):
    """a ≡ 0 mod P, for any bounded lazy value."""
    return jnp.all(canonical(a) == 0, axis=-1)


def eq(a, b):
    """a ≡ b mod P, for bounded lazy values."""
    return is_zero(a - b)


def from_mont(a):
    """Montgomery unit -> canonical plain limbs."""
    return canonical_plain(a)


# --------------------------------------------------------------------------
# Exponentiation with a static exponent (scan over constant bit vector)
# --------------------------------------------------------------------------

POW_WINDOW = 4


def pow_static(a, e: int, window: int = POW_WINDOW):
    """a^e mod P for a static python-int exponent; a a Montgomery unit.

    Fixed-window exponentiation as a traced scan: the bit-serial form
    pays one sqr AND one (select-discarded but still computed) mul per
    bit — 2 mont ops/bit.  A 2^w table (built once: 2^w - 2 muls) and a
    scan over the exponent's static base-2^w digits pays w sqrs + ONE
    gathered mul per digit: for the 381-bit Fermat exponents that
    dominate the verify pipeline (inversion, sqrt, sqrt_ratio) this is
    ~489 mont ops instead of ~760.  The graph stays O(1) in exponent
    length (one scan body; digits are a scanned array).
    """
    if e == 0:
        return jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape)
    if e.bit_length() <= window:
        # tiny exponent: square-and-multiply unrolled is smaller than
        # any table
        acc = a
        for bit in bin(e)[3:]:
            acc = mont_sqr(acc)
            if bit == "1":
                acc = mont_mul(acc, a)
        return acc
    n_digits = (e.bit_length() + window - 1) // window
    digits = np.array(
        [(e >> (window * i)) & ((1 << window) - 1)
         for i in range(n_digits)][::-1], dtype=np.int64)
    # table[d] = a^d, d in [0, 2^w) — scan-built so the graph holds
    # one mont_mul body, not 2^w - 2 inlined copies
    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), np.shape(a))

    def build(carry, _):
        return mont_mul(carry, a), carry
    _, table = lax.scan(build, one, None, length=1 << window)

    def body(acc, d):
        for _ in range(window):
            acc = mont_sqr(acc)
        acc = mont_mul(acc, jnp.take(table, d, axis=0))
        return acc, None

    # top digit is nonzero (bit_length > window): start from its row
    acc = jnp.take(table, jnp.asarray(digits[0]), axis=0)
    acc, _ = lax.scan(body, acc, jnp.asarray(digits[1:]))
    return acc


def inv(a):
    """Field inverse via Fermat (a^(P-2)); inv(0) ≡ 0 (callers select
    around it, branch-free)."""
    return pow_static(a, P - 2)


def inv_many(a):
    """Batched field inverse: ONE Fermat exponentiation for the whole
    batch via Montgomery's trick, parallelized with prefix/suffix
    product scans.

    a: (..., L) Montgomery units, any batch shape (flattened internally).
    Cost: one single-element a^(P-2) scan plus ~6 mont_muls per element
    (two log-depth associative scans + the recombine), versus one full
    380-bit Fermat scan per element for `inv` — the dominant
    compile-time and runtime win of the verification kernel.

    inv_many(0) ≡ 0 per-lane (zero lanes are masked out of the product
    so they cannot poison the batch).
    """
    shape = a.shape
    flat = a.reshape((-1, L))
    m = flat.shape[0]
    if m == 1:
        out = inv(flat)
        return out.reshape(shape)
    zero = is_zero(flat)                                  # (M,)
    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), flat.shape)
    safe = jnp.where(zero[:, None], one, flat)
    pre = lax.associative_scan(mont_mul, safe, axis=0)    # prefix products
    suf = lax.associative_scan(mont_mul, safe, axis=0, reverse=True)
    tinv = inv(pre[-1])                                   # ONE Fermat
    left = jnp.concatenate([one[:1], pre[:-1]], axis=0)   # prod before i
    right = jnp.concatenate([suf[1:], one[:1]], axis=0)   # prod after i
    out = mont_mul(mont_mul(left, right), tinv[None])
    out = jnp.where(zero[:, None], 0, out)
    return out.reshape(shape)


def sqrt_candidate(a):
    """a^((P+1)/4) — the square root when a is a QR (P = 3 mod 4).
    Caller must check candidate^2 == a."""
    return pow_static(a, (P + 1) // 4)
