"""fp381 fixed-width limb arithmetic for TPU (JAX).

The base field Fq of BLS12-381 (381-bit prime P) represented as 15 limbs of
26 bits each stored in int64 lanes, in Montgomery form (a*R mod P with
R = 2^390).  This replaces the native blst limb arithmetic the reference
client calls through JNI (reference: infrastructure/bls/src/main/java/tech/
pegasys/teku/bls/impl/blst/BlstBLS12381.java — there delegated to C/asm).

Design for TPU/XLA:
- Element = trailing dim of size 15; every op broadcasts over arbitrary
  leading batch dims, so batching is plain array broadcasting (no vmap
  needed) and XLA sees large fused elementwise ops feeding the VPU.
- 26-bit radix: limb products are <= 2^52 and column sums across the
  schoolbook multiply + Montgomery reduction stay < 2^58, well inside
  int64 — no data-dependent carries, no overflow branches.
- Branch-free throughout: conditional reduction is a lane-wise select,
  so everything jits with static shapes and is constant-time by
  construction (the reference gets this from blst's asm).

Layer validation: tests/test_ops_limbs.py checks every op against the
pure-Python oracle (teku_tpu/crypto/bls/fields.py).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls.constants import P

# --------------------------------------------------------------------------
# Representation constants
# --------------------------------------------------------------------------

W = 26                    # bits per limb
L = 15                    # limb count (15*26 = 390 >= 381)
MASK = (1 << W) - 1
RADIX = 1 << W

R_MOD_P = (1 << (W * L)) % P          # Montgomery R mod P
R2_MOD_P = (R_MOD_P * R_MOD_P) % P    # R^2 mod P (to_mont multiplier)
N0INV = (-pow(P, -1, RADIX)) % RADIX  # -P^-1 mod 2^W


def int_to_limbs(x: int) -> np.ndarray:
    """Host-side: python int -> canonical limb vector (NOT Montgomery form)."""
    if not 0 <= x < (1 << (W * L)):
        raise ValueError("value out of limb range")
    return np.array([(x >> (W * i)) & MASK for i in range(L)], dtype=np.int64)


def limbs_to_int(a) -> int:
    """Host-side: limb vector -> python int."""
    a = np.asarray(a)
    return sum(int(a[..., i]) << (W * i) for i in range(L))


P_LIMBS = int_to_limbs(P)
ZERO = np.zeros(L, dtype=np.int64)
ONE_MONT = int_to_limbs(R_MOD_P)          # 1 in Montgomery form
R2_LIMBS = int_to_limbs(R2_MOD_P)


def int_to_mont(x: int) -> np.ndarray:
    """Host-side: python int mod P -> Montgomery-form limb vector."""
    return int_to_limbs((x % P) * R_MOD_P % P)


def mont_to_int(a) -> int:
    """Host-side: Montgomery-form limbs -> python int mod P."""
    return limbs_to_int(a) * pow(R_MOD_P, -1, P) % P


# --------------------------------------------------------------------------
# Core ops.  All take/return int64 arrays of shape (..., L), canonical
# limbs (< 2^W), value < P, Montgomery form where noted.
# --------------------------------------------------------------------------

def _carry_propagate(r):
    """Normalize limbs after accumulation: (..., L) with values < 2^63-ish,
    total value < 2^(W*L), into canonical limbs.  Sequential carry chain
    expressed as a scan so the compiled graph is O(1) in limb count."""
    def step(c, col):
        v = col + c
        return v >> W, v & MASK
    c0 = jnp.zeros(r.shape[:-1], dtype=jnp.int64)
    _, limbs = lax.scan(step, c0, jnp.moveaxis(r, -1, 0))
    return jnp.moveaxis(limbs, 0, -1)


def _sub_with_borrow(a, b):
    """(a - b) limbwise with sequential borrow; returns (diff, borrow)
    where borrow is 0 if a >= b else -1.  Inputs canonical."""
    a, b = jnp.broadcast_arrays(a, b)
    def step(c, cols):
        v = cols[0] - cols[1] + c
        return v >> W, v & MASK   # arithmetic shift: carry 0 or -1
    c0 = jnp.zeros(a.shape[:-1], dtype=jnp.int64)
    c, limbs = lax.scan(step, c0,
                        (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0)))
    return jnp.moveaxis(limbs, 0, -1), c


def _cond_sub_p(a):
    """a < 2P canonical-limbed -> a mod P."""
    p = jnp.asarray(P_LIMBS)
    d, borrow = _sub_with_borrow(a, p)
    return jnp.where((borrow != 0)[..., None], a, d)


def add(a, b):
    """Field addition (works in either plain or Montgomery form)."""
    return _cond_sub_p(_carry_propagate(a + b))


def sub(a, b):
    """Field subtraction."""
    d, borrow = _sub_with_borrow(a, b)
    dp = _carry_propagate(d + jnp.asarray(P_LIMBS))
    return jnp.where((borrow != 0)[..., None], dp, d)


def neg(a):
    """Field negation: P - a, with -0 = 0."""
    d, _ = _sub_with_borrow(jnp.asarray(P_LIMBS), a)
    return jnp.where(is_zero(a)[..., None], jnp.zeros_like(a), d)


def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """Lane select: cond True -> a, else b.  cond shape = batch shape."""
    return jnp.where(cond[..., None], a, b)


def gt(a, b):
    """a > b as canonical plain-form (non-Montgomery) limb integers."""
    _, borrow = _sub_with_borrow(b, a)
    return borrow != 0


def _pad_last(x, lo, hi):
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(lo, hi)])


def _mont_reduce(t):
    """Word-serial Montgomery reduction of 2L product columns.

    The 15-step serial dependency (each m_i needs the running low column)
    is a scan whose body shifts the column window down one word per step;
    column magnitudes stay < 2^58, inside int64.
    """
    p_pad = _pad_last(jnp.asarray(P_LIMBS), 0, L)

    def red(t, _):
        m = ((t[..., 0] & MASK) * N0INV) & MASK
        t = t + m[..., None] * p_pad
        c = t[..., 0] >> W
        head = t[..., 1:2] + c[..., None]
        t = jnp.concatenate(
            [head, t[..., 2:], jnp.zeros_like(t[..., :1])], axis=-1)
        return t, None

    t, _ = lax.scan(red, t, None, length=L)
    return _cond_sub_p(_carry_propagate(t[..., :L]))


def mont_mul(a, b):
    """Montgomery multiplication: returns a*b*R^-1 mod P.

    Schoolbook column products built by pad-and-sum (no scatter ops —
    XLA fuses the static pads into one elementwise reduction), then the
    scan-based word-serial reduction.
    """
    t = sum(_pad_last(a[..., i:i + 1] * b, i, L - i) for i in range(L))
    return _mont_reduce(t)


def mont_sqr(a):
    """Montgomery squaring: symmetric cross products computed once and
    doubled (~half the limb multiplies of mont_mul)."""
    rows = []
    for i in range(L):
        diag = a[..., i:i + 1] * a[..., i:i + 1]
        cross = 2 * a[..., i:i + 1] * a[..., i + 1:]
        seg = jnp.concatenate([diag, cross], axis=-1)   # columns 2i..i+L-1
        rows.append(_pad_last(seg, 2 * i, L - i))
    return _mont_reduce(sum(rows))


def to_mont(a):
    """Plain limbs -> Montgomery form."""
    return mont_mul(a, jnp.asarray(R2_LIMBS))


def from_mont(a):
    """Montgomery form -> plain limbs."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def double(a):
    return add(a, a)


def mul_small(a, k: int):
    """Multiply by a small static non-negative int (k < 2^10 or so)."""
    assert 0 <= k
    if k == 0:
        return jnp.zeros_like(a)
    r = _carry_propagate(a * k)
    # value < k*P: subtract P up to k-1 times (static unroll, select each)
    for _ in range(k - 1):
        r = _cond_sub_p(r)
    return r


# --------------------------------------------------------------------------
# Exponentiation with a static exponent (scan over constant bit vector)
# --------------------------------------------------------------------------

def pow_static(a, e: int):
    """a^e mod P for a static python-int exponent; a in Montgomery form.

    Square-and-multiply over the exponent's bits as a traced scan: one
    sqr + one selected mul per bit, so the compiled graph is O(1) in the
    exponent length while the runtime is O(bits).
    """
    if e == 0:
        return jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape)
    bits = np.array([(e >> i) & 1 for i in range(e.bit_length())][::-1],
                    dtype=np.int64)

    def body(acc, bit):
        acc = mont_sqr(acc)
        acc = select(bit != 0, mont_mul(acc, a), acc)
        return acc, None

    # First bit is always 1: start from a directly to save a step.
    acc, _ = lax.scan(body, jnp.asarray(a), jnp.asarray(bits[1:]))
    return acc


def inv(a):
    """Field inverse via Fermat (a^(P-2)); a in Montgomery form.
    inv(0) returns 0 (callers select around it, branch-free)."""
    return pow_static(a, P - 2)


def sqrt_candidate(a):
    """a^((P+1)/4) — the square root when a is a QR (P = 3 mod 4).
    Caller must check candidate^2 == a."""
    return pow_static(a, (P + 1) // 4)
