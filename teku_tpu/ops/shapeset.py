"""The canonical serving shape set: one enumerable compile registry.

Every observability layer points at the compile wall (PERF.md:
``compile_wall_share`` 0.91, 842 s cold compile, the doctor's 136 s
cache-load finding) — and the fix requires knowing EXACTLY which
programs serving will dispatch.  This module is that registry: the
pow-2 bucket policy (lane bucket x unique-h2c bucket x group cap x
msm path x mont path x mesh width) as pure functions, plus the
enumeration of (kernel, argument avals) pairs the warmup/serving path
traces — the input to ``cli precompile`` and the coverage oracle for
the doctor's ``cold_compile_on_hot_path`` finding.

Anti-drift contract: ``ops/provider.py`` imports THESE functions for
its dispatch bucketing (it has no private copy), so the registry and
dispatch reality cannot diverge — tests/test_shapeset.py pins the
sharing both structurally (same function objects) and behaviorally
(``batch_plan`` reproduces the dispatch ledger's shape fields).

Pure-policy helpers up top are host-only (importable without jax);
``enumerate_programs`` imports jax lazily to chain ``jax.eval_shape``
through the real stage functions, so intermediate-stage avals are
DERIVED from the kernels, never hand-maintained.
"""

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..infra.pow2 import next_pow2

# The policy constants provider buckets with (its env knobs default to
# these — the drift test pins the equality):
H2C_MIN_BUCKET_DEFAULT = 8      # TEKU_TPU_H2C_MIN_BUCKET
GROUP_CAP_DEFAULT = 32          # TEKU_TPU_H2C_GROUP_CAP
PK_VALIDATE_FLOOR = 16          # pubkey-validation bucket floor
# the service-tier dispatch defaults (loader.make_supervisor /
# make_mesh_healer) — what a default `cli node` boot warms
SERVICE_MAX_BATCH = 256
SERVICE_MIN_BUCKET = 16


# --------------------------------------------------------------------------
# Bucket policy (pure, host-only — provider imports these)
# --------------------------------------------------------------------------

def lane_bucket(n: int, min_bucket: int) -> int:
    """Padded lane width of an n-lane single-device dispatch."""
    return max(next_pow2(n), min_bucket)


def kmax_bucket(max_keys: int) -> int:
    """Padded keys-per-lane width (the `kmax` shape axis)."""
    return next_pow2(max_keys)


def _row_size(g) -> int:
    return g if isinstance(g, int) else len(g)


def group_rows(groups: Sequence, group_cap: int) -> List[Tuple[int,
                                                               object]]:
    """Miller rows for per-unique-message lane groups: committees
    larger than the group cap split across rows (a message may own
    several rows backed by the same H(m) point).  Each group is a
    lane COUNT (registry enumeration) or a lane-index list (provider
    dispatch — this is the split rule `_begin_dispatch` runs); rows
    keep the caller's form: [(unique index, count-or-chunk)]."""
    rows: List[Tuple[int, object]] = []
    for u, g in enumerate(groups):
        size = _row_size(g)
        for off in range(0, size, group_cap):
            if isinstance(g, int):
                rows.append((u, min(group_cap, size - off)))
            else:
                rows.append((u, g[off:off + group_cap]))
    return rows


def group_bucket(rows: Sequence[Tuple[int, object]]) -> int:
    """Padded lanes-per-row width (the (U, G) gather's G axis)."""
    return next_pow2(max(_row_size(g) for _, g in rows))


def unique_bucket(n_rows: int, h2c_min_bucket: int) -> int:
    """The canonical unique bucket: H(m) arena / h2c dispatch width.
    Computed from the batch alone — identical for single-device and
    mesh dispatch of the same batch."""
    return max(next_pow2(n_rows), h2c_min_bucket)


def h2c_miss_bucket(n_missing: int, h2c_min_bucket: int) -> int:
    """Width of the h2c dispatch serving a batch's arena misses."""
    return max(next_pow2(n_missing), h2c_min_bucket)


def pk_validate_bucket(n: int) -> int:
    """Width of the pubkey-validation dispatch for n cache misses."""
    return max(next_pow2(n), PK_VALIDATE_FLOOR)


def shape_label(padded: int, kmax: int, mesh_devices: int = 0) -> str:
    """The ledger/metric `shape` string for a padded dispatch."""
    return f"{padded}x{kmax}" + (
        f"@m{mesh_devices}" if mesh_devices else "")


def batch_plan(lane_groups: Sequence[int], *, min_bucket: int,
               kmax: int = 1,
               h2c_min_bucket: int = H2C_MIN_BUCKET_DEFAULT,
               group_cap: int = GROUP_CAP_DEFAULT,
               mesh_devices: int = 0,
               h2c_missing: Optional[int] = None) -> dict:
    """The full bucket decision for one batch profile, exactly as
    ``provider._begin_dispatch`` makes it.  ``lane_groups`` is the
    batch's lanes-per-unique-message profile (``[1]*256`` = all
    unique, ``[8]*32`` = committee-duplicated); ``h2c_missing`` is how
    many unique messages miss the H(m) arena (default: all — the
    cold-boot case; 0 = fully warm, no h2c program)."""
    lanes = sum(lane_groups)
    rows = group_rows(lane_groups, group_cap)
    g_bucket = group_bucket(rows)
    u_hm = unique_bucket(len(rows), h2c_min_bucket)
    if mesh_devices >= 2:
        from .. import parallel
        plan = parallel.plan_group_shards(
            [(u, list(range(_row_size(g)))) for u, g in rows], lanes,
            mesh_devices,
            min_lanes=max(min_bucket, mesh_devices) // mesh_devices,
            min_rows=max(h2c_min_bucket // mesh_devices, 1))
        padded = plan.padded
        u_total = plan.rows_total
        lanes_per_shard = plan.lanes_per_shard
        rows_per_shard = plan.rows_per_shard
    else:
        padded = lane_bucket(lanes, min_bucket)
        u_total = u_hm
        lanes_per_shard = rows_per_shard = None
    missing = len(rows) if h2c_missing is None else h2c_missing
    from . import msm
    msm_path, _why = msm.explain(lanes=lanes, rows=len(rows))
    return {
        "lanes": lanes, "kmax": kmax, "rows": len(rows),
        "group_bucket": g_bucket, "u_hm": u_hm, "padded": padded,
        "u_total": u_total, "msm_path": msm_path,
        "mesh_devices": mesh_devices if mesh_devices >= 2 else 0,
        "lanes_per_shard": lanes_per_shard,
        "rows_per_shard": rows_per_shard,
        "h2c_bucket": (h2c_miss_bucket(missing, h2c_min_bucket)
                       if missing else 0),
        "shape": shape_label(
            padded, kmax,
            mesh_devices if mesh_devices >= 2 else 0),
    }


# --------------------------------------------------------------------------
# The warmup batch profiles (mirrors loader._warmup_batches)
# --------------------------------------------------------------------------

def warmup_profiles(max_batch: int) -> List[Tuple[str, List[int],
                                                  Optional[int]]]:
    """The (name, lane_groups, h2c_missing) profiles supervisor
    WARMING and the selfheal reshape warm dispatch, in order: the x1
    probe shape, the all-unique primary bucket, and (>= 8 lanes) the
    committee-duplicated shape whose messages the all-unique batch
    already put in the H(m) arena (zero h2c)."""
    profiles: List[Tuple[str, List[int], Optional[int]]] = [
        ("x1", [1], None),
        (f"x{max_batch}", [1] * max_batch, None),
    ]
    if max_batch >= 8:
        profiles.append(
            (f"x{max_batch}dup8", [8] * (max_batch // 8), 0))
    return profiles


def serving_shapes(max_batch: int = SERVICE_MAX_BATCH,
                   min_bucket: int = SERVICE_MIN_BUCKET,
                   mesh_devices: int = 0,
                   h2c_min_bucket: int = H2C_MIN_BUCKET_DEFAULT,
                   group_cap: int = GROUP_CAP_DEFAULT) -> set:
    """The ledger `shape` strings ``cli precompile`` covers for one
    serving config — the doctor's cold_compile_on_hot_path coverage
    oracle.  Includes every duplication profile from all-unique down
    to fully-duplicated at each pow-2 batch size up to max_batch (the
    warmup profiles are a subset)."""
    shapes = set()
    size = 1
    while size <= next_pow2(max_batch):
        dup = 1
        while dup <= size:
            groups = [dup] * (size // dup)
            if groups:
                plan = batch_plan(
                    groups, min_bucket=min_bucket,
                    h2c_min_bucket=h2c_min_bucket,
                    group_cap=group_cap, mesh_devices=mesh_devices)
                shapes.add(plan["shape"])
            dup *= 2
        size *= 2
    return shapes


# --------------------------------------------------------------------------
# Program enumeration (jax from here down)
# --------------------------------------------------------------------------

def _sds(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _scalars_aval(padded: int, msm_path: str):
    """The scalars-stage argument aval: r_bits on the ladder path,
    GLV digit arrays on the pippenger path — derived from the real
    converters so a digit-layout change cannot drift the registry."""
    import numpy as np
    if msm_path == "pippenger":
        from . import msm
        probe = msm.glv_digits_np(np.ones(1, dtype=np.uint64),
                                  np.zeros(1, dtype=np.uint64))
    else:
        from . import points as PT
        probe = np.asarray(PT.scalar_from_uint64(
            np.ones(1, dtype=np.uint64)))
    return _sds((padded,) + probe.shape[1:], probe.dtype)


def enumerate_programs(*, max_batch: int = SERVICE_MAX_BATCH,
                       min_bucket: int = SERVICE_MIN_BUCKET,
                       kmax: int = 1,
                       h2c_min_bucket: int = H2C_MIN_BUCKET_DEFAULT,
                       group_cap: int = GROUP_CAP_DEFAULT,
                       mesh: Optional[object] = None,
                       axis: str = "dp"
                       ) -> Iterator[Tuple[str, tuple, dict]]:
    """Yield (kernel name, argument avals, meta) for every program a
    supervisor boot of this config dispatches — the precompile work
    list.  Stage-input avals are chained through the REAL stage
    functions with ``jax.eval_shape``; kernel names match the ones
    ``ops/verify.py``/``teku_tpu/parallel`` register with the AOT
    store.  ``mesh`` is a live ``jax.sharding.Mesh`` (or None for
    single-device); mesh programs additionally need the gather
    scatter program and the sharded kernel itself.
    """
    import jax
    import numpy as np

    from . import limbs as fp
    from . import mxu
    from . import verify as V

    mont = mxu.resolve()
    i64 = np.int64
    i32 = np.int32
    b_ = np.bool_
    mesh_devices = 0
    if mesh is not None:
        mesh_devices = int(np.prod(
            [mesh.shape[a] for a in mesh.axis_names]))

    def stage_name(name: str) -> str:
        return f"stage:{name}:{mont}"

    seen: set = set()

    def emit(kernel: str, avals: tuple, meta: dict):
        from ..infra import aotstore
        key = (kernel, aotstore.shape_sig(avals))
        if key in seen:
            return None
        seen.add(key)
        return kernel, avals, meta

    # the probe's pubkey-validation program (one arena miss)
    pk_n = pk_validate_bucket(1)
    out = emit(f"pk_validate:{mont}",
               (_sds((pk_n, fp.L), i64), _sds((pk_n,), b_)),
               {"shape": f"pkv{pk_n}", "stage": "pk_validate"})
    if out:
        yield out

    for name, lane_groups, h2c_missing in warmup_profiles(max_batch):
        plan = batch_plan(lane_groups, min_bucket=min_bucket,
                          kmax=kmax, h2c_min_bucket=h2c_min_bucket,
                          group_cap=group_cap,
                          mesh_devices=mesh_devices,
                          h2c_missing=h2c_missing)
        meta = {"profile": name, "shape": plan["shape"],
                "msm_path": plan["msm_path"], "mont_path": mont}
        P, K, U, G = (plan["padded"], plan["kmax"], plan["u_total"],
                      plan["group_bucket"])
        # the h2c program over this profile's arena misses
        if plan["h2c_bucket"]:
            mb = plan["h2c_bucket"]
            u_half = (_sds((mb, fp.L), i64), _sds((mb, fp.L), i64))
            out = emit(stage_name("h2c"), (u_half, u_half),
                       {**meta, "stage": "h2c", "bucket": mb})
            if out:
                yield out
        # the H(m) tree at arena width feeds miller (and, on the mesh
        # path, the gather scatter): leading dim is the unique bucket
        uh = plan["u_hm"]
        u_half = (_sds((uh, fp.L), i64), _sds((uh, fp.L), i64))
        hm_uniq = jax.eval_shape(V.stage_h2c, u_half, u_half)
        prepare_in = (
            _sds((P, K, fp.L), i64), _sds((P, K, fp.L), i64),
            _sds((P, K), b_),
            (_sds((P, fp.L), i64), _sds((P, fp.L), i64)),
            _sds((P,), b_), _sds((P,), b_), _sds((P,), b_))
        scalars = _scalars_aval(P, plan["msm_path"])
        group_idx = _sds((U, G), i32)
        group_present = _sds((U, G), b_)
        if mesh_devices >= 2:
            # mesh: prepare/scalars/group run inside the sharded
            # kernel; the host-side programs are gather + the kernel
            row_gather = _sds((U,), i32)
            hm_rows = jax.eval_shape(V.stage_gather_hm, hm_uniq,
                                     row_gather)
            out = emit(stage_name("gather"), (hm_uniq, row_gather),
                       {**meta, "stage": "gather"})
            if out:
                yield out
            from .. import parallel
            kern = parallel.kernel_store_name(
                [str(d) for d in np.ravel(mesh.devices)], axis,
                plan["msm_path"])
            sig_x = (_sds((P, fp.L), i64), _sds((P, fp.L), i64))
            out = emit(kern, (
                prepare_in[0], prepare_in[1], prepare_in[2], hm_rows,
                group_idx, group_present, sig_x, _sds((P,), b_),
                _sds((P,), b_), scalars, _sds((P,), b_)),
                {**meta, "stage": "mesh_kernel", "axis": axis,
                 "devices": mesh_devices})
            if out:
                yield out
            continue
        out = emit(stage_name("prepare"), prepare_in,
                   {**meta, "stage": "prepare"})
        if out:
            yield out
        prep_out = jax.eval_shape(V.stage_prepare, *prepare_in)
        pk_jac, sig_jac, _lane_ok, miller_mask = prep_out
        if plan["msm_path"] == "pippenger":
            pip_in = (pk_jac, sig_jac, scalars, group_idx,
                      group_present, miller_mask)
            out = emit(stage_name("scalars_pip"), pip_in,
                       {**meta, "stage": "scalars_pip"})
            if out:
                yield out
            agg_aff, u_mask, wsig = jax.eval_shape(
                V.stage_scalars_pippenger, *pip_in)
        else:
            sc_in = (pk_jac, sig_jac, scalars)
            out = emit(stage_name("scalars"), sc_in,
                       {**meta, "stage": "scalars"})
            if out:
                yield out
            pk_r_jac, wsig = jax.eval_shape(V.stage_scalars, *sc_in)
            grp_in = (pk_r_jac, miller_mask, group_idx, group_present)
            out = emit(stage_name("group"), grp_in,
                       {**meta, "stage": "group"})
            if out:
                yield out
            agg_aff, u_mask = jax.eval_shape(V.stage_group, *grp_in)
        mil_in = (agg_aff, hm_uniq, u_mask)
        out = emit(stage_name("miller"), mil_in,
                   {**meta, "stage": "miller"})
        if out:
            yield out
        ml = jax.eval_shape(V.stage_miller, *mil_in)
        out = emit(stage_name("finish"), (ml, wsig),
                   {**meta, "stage": "finish"})
        if out:
            yield out
