"""Generic fixed-width Montgomery limb field for TPU (JAX).

The same lazy-reduction design as ops/limbs.py (the Fq engine of the
BLS kernel) parameterized over the modulus, so other prime fields ride
the proven pattern instead of duplicating it.  First client: the
BLS12-381 SCALAR field Fr for KZG (barycentric blob evaluation runs in
Fr — reference: c-kzg's fr_t arithmetic behind
infrastructure/kzg/src/main/java/tech/pegasys/teku/kzg/CKZG4844.java).

Contracts are identical to ops/limbs.py: elementwise add/sub/neg are
lazy (no carries), mont_mul/mont_sqr take bounded lazy operands and
emit one compressed unit with value in (-M, 2M), canonical() decides
equality, inversion is Fermat, and the batch inverse is Montgomery's
trick over two log-depth associative scans.

Like ops/limbs.py, every field built here carries BOTH multiplier
engines: the VPU pad-and-sum path and the MXU int8 digit-split matmul
path (ops/mxu.py), dispatched at trace time on the same process-global
path config.  The namespace exposes mont_mul_vpu / mont_mul_mxu (and
sqr variants) for layer-validation parity tests.
"""

from types import SimpleNamespace

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import mxu as _mxu


def make_field(modulus: int, name: str = "field",
               width: int = 26) -> SimpleNamespace:
    W = width
    L = (modulus.bit_length() + W - 1) // W
    MASK = (1 << W) - 1
    RADIX = 1 << W
    M = modulus
    R_MOD = (1 << (W * L)) % M
    R2_MOD = (R_MOD * R_MOD) % M
    N0INV = (-pow(M, -1, RADIX)) % RADIX

    def int_to_limbs(x: int) -> np.ndarray:
        if not 0 <= x < (1 << (W * L)):
            raise ValueError("value out of limb range")
        return np.array([(x >> (W * i)) & MASK for i in range(L)],
                        dtype=np.int64)

    def limbs_to_int(a) -> int:
        a = np.asarray(a)
        return sum(int(a[..., i]) << (W * i) for i in range(L)) % M

    M_LIMBS = int_to_limbs(M)
    ONE_MONT = int_to_limbs(R_MOD)
    R2_LIMBS = int_to_limbs(R2_MOD)

    def int_to_mont(x: int) -> np.ndarray:
        return int_to_limbs((x % M) * R_MOD % M)

    def mont_to_int(a) -> int:
        return limbs_to_int(a) * pow(R_MOD, -1, M) % M

    def select(cond, a, b):
        return jnp.where(cond[..., None], a, b)

    def compress(r):
        def step(c, col):
            v = col + c
            return v >> W, v & MASK
        c0 = jnp.zeros(r.shape[:-1], dtype=jnp.int64)
        c, limbs = lax.scan(step, c0, jnp.moveaxis(r, -1, 0))
        limbs = jnp.moveaxis(limbs, 0, -1)
        return limbs.at[..., L - 1].add(c * RADIX)

    def _sub_with_borrow(a, b):
        a, b = jnp.broadcast_arrays(a, b)

        def step(c, cols):
            v = cols[0] - cols[1] + c
            return v >> W, v & MASK
        c0 = jnp.zeros(a.shape[:-1], dtype=jnp.int64)
        c, limbs = lax.scan(
            step, c0, (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0)))
        return jnp.moveaxis(limbs, 0, -1), c

    def _cond_sub_m(a):
        m = jnp.asarray(M_LIMBS)
        d, borrow = _sub_with_borrow(a, m)
        return jnp.where((borrow != 0)[..., None], a, d)

    def _pad_last(x, lo, hi):
        return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(lo, hi)])

    def _mont_reduce(t):
        m_pad = _pad_last(jnp.asarray(M_LIMBS), 0, L)

        def red(t, _):
            mm = ((t[..., 0] & MASK) * N0INV) & MASK
            t = t + mm[..., None] * m_pad
            c = t[..., 0] >> W
            head = t[..., 1:2] + c[..., None]
            t = jnp.concatenate(
                [head, t[..., 2:], jnp.zeros_like(t[..., :1])], axis=-1)
            return t, None

        t, _ = lax.scan(red, t, None, length=L)
        return compress(t[..., :L])

    def mont_mul_vpu(a, b):
        t = sum(_pad_last(a[..., i:i + 1] * b, i, L - i)
                for i in range(L))
        return _mont_reduce(t)

    def mont_sqr_vpu(a):
        rows = []
        for i in range(L):
            diag = a[..., i:i + 1] * a[..., i:i + 1]
            cross = 2 * a[..., i:i + 1] * a[..., i + 1:]
            seg = jnp.concatenate([diag, cross], axis=-1)
            rows.append(_pad_last(seg, 2 * i, L - i))
        return _mont_reduce(sum(rows))

    mont_mul_mxu, mont_sqr_mxu = _mxu.make_digit_kernels(
        L, W, M.bit_length(), compress, _mont_reduce)

    def mont_mul(a, b):
        if _mxu.active():
            return mont_mul_mxu(a, b)
        return mont_mul_vpu(a, b)

    def mont_sqr(a):
        if _mxu.active():
            return mont_sqr_mxu(a)
        return mont_sqr_vpu(a)

    def to_mont(a):
        return mont_mul(a, jnp.asarray(R2_LIMBS))

    def canonical(a):
        y = mont_mul(a, jnp.asarray(R2_LIMBS))
        y = compress(y + jnp.asarray(M_LIMBS))
        return _cond_sub_m(_cond_sub_m(y))

    def canonical_plain(a):
        one = jnp.zeros_like(a).at[..., 0].set(1)
        y = mont_mul(a, one)
        y = compress(y + jnp.asarray(M_LIMBS))
        return _cond_sub_m(_cond_sub_m(y))

    def is_zero(a):
        return jnp.all(canonical(a) == 0, axis=-1)

    def pow_static(a, e: int):
        if e == 0:
            return jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape)
        bits = np.array(
            [(e >> i) & 1 for i in range(e.bit_length())][::-1],
            dtype=np.int64)

        def body(acc, bit):
            acc = mont_sqr(acc)
            acc = select(bit != 0, mont_mul(acc, a), acc)
            return acc, None

        acc, _ = lax.scan(body, jnp.asarray(a), jnp.asarray(bits[1:]))
        return acc

    def inv(a):
        return pow_static(a, M - 2)

    def inv_many(a):
        shape = a.shape
        flat = a.reshape((-1, L))
        mlen = flat.shape[0]
        if mlen == 1:
            return inv(flat).reshape(shape)
        zero = is_zero(flat)
        one = jnp.broadcast_to(jnp.asarray(ONE_MONT), flat.shape)
        safe = jnp.where(zero[:, None], one, flat)
        pre = lax.associative_scan(mont_mul, safe, axis=0)
        suf = lax.associative_scan(mont_mul, safe, axis=0, reverse=True)
        tinv = inv(pre[-1])
        left = jnp.concatenate([one[:1], pre[:-1]], axis=0)
        right = jnp.concatenate([suf[1:], one[:1]], axis=0)
        out = mont_mul(mont_mul(left, right), tinv[None])
        out = jnp.where(zero[:, None], 0, out)
        return out.reshape(shape)

    return SimpleNamespace(
        name=name, M=M, W=W, L=L, MASK=MASK,
        int_to_limbs=int_to_limbs, limbs_to_int=limbs_to_int,
        int_to_mont=int_to_mont, mont_to_int=mont_to_int,
        ONE_MONT=ONE_MONT, M_LIMBS=M_LIMBS,
        select=select, compress=compress, mont_mul=mont_mul,
        mont_sqr=mont_sqr, mont_mul_vpu=mont_mul_vpu,
        mont_sqr_vpu=mont_sqr_vpu, mont_mul_mxu=mont_mul_mxu,
        mont_sqr_mxu=mont_sqr_mxu, to_mont=to_mont, canonical=canonical,
        canonical_plain=canonical_plain, is_zero=is_zero,
        pow_static=pow_static, inv=inv, inv_many=inv_many,
    )
