"""Device-resident H(m) point cache for the dedup-aware verify pipeline.

In committee-based consensus the same ``AttestationData`` is signed by a
whole committee, so gossip keeps re-delivering signatures over the SAME
message — and hash-to-G2 is the largest per-lane stage (~2,600
mont_muls, PERF.md).  This cache keeps the mapped G2 points resident on
the device so steady-state traffic pays h2c ONCE per distinct message:
a fully-warm batch skips the h2c dispatch entirely and serves H(m) with
one gather out of the arena.

Layout: a fixed-capacity arena of four (capacity, L) limb arrays (the
affine Fq2 x and y coordinate components, Montgomery form) that lives
on the device; the host side keeps an LRU index of message digest →
arena slot.  Inserts are one batched scatter (``.at[slots].set``),
lookups one batched gather — no per-point host/device round trips, and
the point data never leaves the device.

Poison defense (fault site ``h2c.cache``): every slot records the
digest it was computed for, and a hit is RE-VERIFIED BY KEY — the slot's
recorded digest must equal the queried digest, else the entry is
treated as a miss (dropped + recomputed), never trusted blindly.  The
fault-injection tests corrupt the lookup through the site and prove a
poisoned entry cannot flip a verdict.

Knobs: ``TEKU_TPU_H2C_CACHE_CAP`` — arena capacity in points (default
4096 ≈ 2 MB of device memory; ``0``/``off`` disables the cache, the
pipeline still dedups within each batch).
"""

import threading
from collections import OrderedDict
from typing import List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from ..infra import faults
from ..infra.env import env_str
from ..infra.metrics import GLOBAL_REGISTRY
from . import limbs as fp

ENV_CAP = "TEKU_TPU_H2C_CACHE_CAP"
DEFAULT_CAP = 4096

_M_HITS = GLOBAL_REGISTRY.counter(
    "bls_h2c_cache_hits_total",
    "H(m) device-cache lookups served from the arena")
_M_MISSES = GLOBAL_REGISTRY.counter(
    "bls_h2c_cache_misses_total",
    "H(m) device-cache lookups that required a hash-to-curve dispatch")
# one eviction family across every bounded verify-path cache (pk wire
# cache, u-draw cache, H(m) arena): a re-validation storm shows up as a
# rate spike on ONE dashboard series per cache
_M_EVICTIONS = GLOBAL_REGISTRY.labeled_counter(
    "bls_cache_evictions_total",
    "LRU evictions from the bounded verify-path caches",
    labelnames=("cache",))


def evictions_counter(cache: str):
    """The shared eviction family, bound to one cache label (the
    provider wires its pk/u caches through this too)."""
    return _M_EVICTIONS.labels(cache=cache)


def configured_capacity() -> int:
    raw = env_str(ENV_CAP, "") or ""
    if raw.strip().lower() in ("off", "false", "no"):
        return 0
    try:
        return int(raw) if raw else DEFAULT_CAP
    except ValueError:
        return DEFAULT_CAP


class H2cPointCache:
    """Bounded LRU of device-resident H(m) affine points.

    Thread-safe: the batching service dispatches from worker threads.
    Arena updates are functional (`.at[].set` yields new arrays), so a
    gather launched against the previous arena stays consistent.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = (configured_capacity() if capacity is None
                         else capacity)
        self._lock = threading.Lock()
        # digest -> slot, insertion/touch order = LRU order
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        # slot -> digest it was computed for (the hit re-verification
        # record; None = never used)
        self._slot_digest: List[Optional[bytes]] = [None] * self.capacity
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._arena = None      # lazily: 4 x (capacity, L) device arrays
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def __len__(self) -> int:
        return len(self._index)

    # ------------------------------------------------------------------
    def lookup(self, digest: bytes) -> Optional[int]:
        """Arena slot holding H(m) for `digest`, or None on miss.

        A hit is re-verified by key: the slot's recorded digest must
        match, else the entry is poisoned/stale — drop it and report a
        miss so the caller recomputes.  (`h2c.cache` fault site: tests
        corrupt the resolved slot here.)"""
        with self._lock:
            slot = self._index.get(digest)
            if slot is not None:
                # fault site: a WrongResult(value=...) poisons the
                # resolved slot — the re-verification below must catch it
                slot = faults.transform("h2c.cache", slot)
                if (not isinstance(slot, int)
                        or not 0 <= slot < self.capacity
                        or self._slot_digest[slot] != digest):
                    # poisoned entry: never trust it — evict and recompute
                    self._index.pop(digest, None)
                    self.misses += 1
                    _M_MISSES.inc()
                    return None
                self._index.move_to_end(digest)
                self.hits += 1
                _M_HITS.inc()
                return slot
            self.misses += 1
            _M_MISSES.inc()
            return None

    # ------------------------------------------------------------------
    def insert(self, digests: Sequence[bytes], hm_bucket) -> np.ndarray:
        """Store the first len(digests) rows of an h2c output bucket.

        `hm_bucket` is stage_h2c's affine tree ((x0, x1), (y0, y1)) of
        (B, L) device arrays with B >= len(digests).  Returns the (k,)
        array of assigned slots.  One batched scatter; LRU entries are
        evicted as needed."""
        k = len(digests)
        if k > self.capacity:
            # an over-capacity insert would evict slots assigned
            # earlier in THIS call (duplicate scatter indices — one
            # row wins) and gather wrong points; callers bypass the
            # cache instead (provider._hm_host_plan)
            raise ValueError(
                f"insert of {k} points exceeds arena capacity "
                f"{self.capacity}")
        slots = np.zeros(k, dtype=np.int64)
        with self._lock:
            for i, dg in enumerate(digests):
                existing = self._index.get(dg)
                if existing is not None:
                    # concurrent insert of the same message: reuse slot
                    slots[i] = existing
                    self._index.move_to_end(dg)
                    continue
                if not self._free:
                    old_dg, old_slot = self._index.popitem(last=False)
                    self._slot_digest[old_slot] = None
                    self._free.append(old_slot)
                    self.evictions += 1
                    _M_EVICTIONS.labels(cache="h2c").inc()
                slot = self._free.pop()
                self._index[dg] = slot
                self._slot_digest[slot] = dg
                slots[i] = slot
            (x0, x1), (y0, y1) = hm_bucket
            idx = jnp.asarray(slots)
            if self._arena is None:
                shape = (self.capacity, fp.L)
                self._arena = tuple(
                    jnp.zeros(shape, dtype=jnp.int64) for _ in range(4))
            ax0, ax1, ay0, ay1 = self._arena
            self._arena = (ax0.at[idx].set(x0[:k]),
                           ax1.at[idx].set(x1[:k]),
                           ay0.at[idx].set(y0[:k]),
                           ay1.at[idx].set(y1[:k]))
        return slots

    # ------------------------------------------------------------------
    def gather(self, lane_slots: np.ndarray):
        """Per-lane H(m) affine tree from the arena: one device gather
        per coordinate array."""
        with self._lock:
            arena = self._arena
        assert arena is not None, "gather before any insert"
        idx = jnp.asarray(lane_slots)
        x0, x1, y0, y1 = (a[idx] for a in arena)
        return ((x0, x1), (y0, y1))

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity, "size": len(self._index),
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}
