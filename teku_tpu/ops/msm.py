"""MSM-grade scalars stage: Pippenger bucket accumulation + GLV.

The r-weighted pubkey fold ``sum_i [r_i]pk_i`` that stage_scalars +
stage_group compute per unique message IS a multi-scalar multiplication
— the unit of cryptographic throughput (2G2T, PAPERS.md) — and after
the PR-5 dedup work it dominates the per-lane budget (PERF.md stage
profile: ~2,600 mont_muls/lane on the ladder).  This module replaces
the per-lane fixed-window ladder with the two classic MSM levers,
expressed as constant-shape batched JAX so the MXU digit-split
mont_mul (ops/mxu.py) does every inner field op:

1. GLV ENDOMORPHISM.  phi(x, y) = (beta*x, y) acts as [lambda] on G1
   (lambda = -z^2 mod r, the eigenvalue ops/points.py verifies on the
   generator at import), and psi^2 acts as [z^2] = [-lambda] on G2.
   Instead of decomposing a sampled 64-bit multiplier (its honest
   lattice split mod r would GROW the halves to ~128 bits — the GLV
   short vectors have norm ~sqrt(r)), the batch multipliers are
   SAMPLED directly in decomposed form: (k1, k2) <- [0, 2^32)^2 minus
   (0, 0), effective multiplier r = k1 + k2*lambda mod r.  The map is
   injective on that range (a collision would be a lattice vector of
   norm < 2^33 against a 2^127 minimum; PERF.md "MSM scalars stage"
   has the bound), so the multiplier set still has 2^64 - 1 elements
   and batch-verify soundness is unchanged — while every scalar walk
   is 32 bits instead of 64.

2. PIPPENGER BUCKETING.  For each (message-group, window) the lanes'
   w-bit digits accumulate into 2^w - 1 bucket points via a
   constant-shape scan (gather bucket[d-1], one batched point_add,
   one-hot select scatter — every step does identical work regardless
   of digit values, so the batch semantics stay constant-time), then
   buckets collapse with the suffix-sum identity
   ``sum_b b*B_b = sum_b (suffix sums)`` and windows combine Horner-
   style.  The doubling chain runs once per GROUP (32 - w doublings)
   instead of once per lane, and the per-lane add count is
   2 points x nwin windows — O(lanes + groups * 2^w) point adds
   total vs the ladder's O(lanes * 64/w) adds + O(lanes * 64)
   doublings.

Path selection mirrors ops/mxu.py: process-global config (CLI
``--msm-path`` / env ``TEKU_TPU_MSM`` / ``set_path()``), resolved per
DISPATCH (the crossover is shape-dependent):

- ``ladder``    — the per-lane windowed ladder + stage_group fold
  (the bit-identical parity oracle; scalar_mul_bits);
- ``pippenger`` — the bucketed MSM path on any device (CPU A/B and
  the bench gate use this explicitly);
- ``auto``      — pippenger exactly when the dispatch device is a TPU
  AND the batch clears the measured crossover (lanes >=
  TEKU_TPU_MSM_AUTO_MIN_LANES and lanes/group-rows >=
  TEKU_TPU_MSM_AUTO_MIN_DUP); everything else stays on the ladder so
  small/all-unique dispatches never pay the per-group bucket
  overhead.  Why auto resolves this way is measured + documented in
  PERF.md.

The LEGACY lane-sharded kernel always takes the ladder (bucketing is a
per-message-group operation and raw lane shards split groups) —
``resolve(sharded=True)`` keeps that contract.  The production
GROUP-ALIGNED mesh kernel (verify_kernel_sharded_grouped) keeps whole
groups per shard, so its dispatches resolve by shape like any other.
"""

import logging
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls.constants import R, X_ABS
from ..infra.env import env_float, env_int, env_str
from . import points as PT

_LOG = logging.getLogger(__name__)

PATHS = ("ladder", "pippenger", "auto")
ENV_VAR = "TEKU_TPU_MSM"
ENV_WINDOW = "TEKU_TPU_MSM_WINDOW"
ENV_SEG = "TEKU_TPU_MSM_SEG"
ENV_AUTO_MIN_LANES = "TEKU_TPU_MSM_AUTO_MIN_LANES"
ENV_AUTO_MIN_DUP = "TEKU_TPU_MSM_AUTO_MIN_DUP"

# half-scalar width: multipliers are sampled as (k1, k2) in [0, 2^32)^2
GLV_BITS = 32

# the shared GLV eigenvalue: phi = [LAMBDA] on G1, psi^2 = [-LAMBDA] on
# G2 (z < 0 for BLS12-381, so z^2 = X_ABS^2 and LAMBDA = -z^2 mod r)
LAMBDA = (-(X_ABS * X_ABS)) % R

_lock = threading.Lock()
_state = {"path": None}               # None -> read ENV_VAR at resolve()
_warned_invalid = [False]


def set_path(path) -> None:
    """Install the process-global MSM path (CLI/loader seam).

    ``None`` resets to env/default resolution."""
    if path is not None and path not in PATHS:
        raise ValueError(
            f"unknown msm path {path!r} (use one of {'/'.join(PATHS)})")
    with _lock:
        _state["path"] = path
        _warned_invalid[0] = False


def get_path() -> str:
    """The CONFIGURED path (may be 'auto'); see resolve()."""
    configured = _state["path"]
    if configured is None:
        configured = env_str(ENV_VAR, "auto")
    if configured not in PATHS:
        with _lock:
            if not _warned_invalid[0]:
                _warned_invalid[0] = True
                _LOG.warning("%s=%r is not one of %s; using auto",
                             ENV_VAR, configured, "/".join(PATHS))
                # self-explaining boot: the demotion lands in the
                # flight recorder, not only a scrolled-away WARN
                from ..infra import flightrecorder
                flightrecorder.config_demotion(
                    "msm", configured, "auto",
                    f"{ENV_VAR} not one of "
                    f"{'/'.join(PATHS)}; using auto")
        configured = "auto"
    return configured


def _device_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def explain(lanes=None, rows=None, sharded: bool = False):
    """``resolve`` plus WHY: ``(path, why)`` where ``why`` is the
    JSON-able decision context the dispatch ledger records — the
    configured path, the auto rule's inputs (device, lane count,
    duplication factor, thresholds), and the rule that fired.  The
    doctor engine cites this verbatim when it explains an msm
    auto-demotion."""
    why = {"configured": get_path(), "lanes": lanes, "rows": rows}
    if sharded:
        why["rule"] = "legacy lane-sharded kernel always ladders"
        return "ladder", why     # lane shards split message groups
    configured = why["configured"]
    if configured in ("ladder", "pippenger"):
        why["rule"] = "explicitly configured"
        return configured, why
    # auto: the bucketed path wins when the per-group overhead
    # (2^w - 1 buckets reduced per window) amortizes over enough
    # duplicated lanes AND the device is the one it was tuned for
    why["tpu"] = _device_is_tpu()
    if not why["tpu"]:
        why["rule"] = "auto: dispatch device is not a TPU"
        return "ladder", why
    if not lanes or not rows:
        why["rule"] = "auto: no shape context"
        return "ladder", why
    # shared degrade-never-fail env readers: resolve() sits on the
    # live dispatch path, so a typo'd threshold must fall back to the
    # default, not fail every verification
    why["auto_min_lanes"] = env_int(ENV_AUTO_MIN_LANES, 32)
    why["auto_min_dup"] = env_float(ENV_AUTO_MIN_DUP, 2.0)
    # the rule compares the EXACT ratio (rounding first would flip the
    # decision at the crossover boundary); the record stores it rounded
    dup = lanes / rows
    why["dup"] = round(dup, 3)
    if lanes >= why["auto_min_lanes"] and dup >= why["auto_min_dup"]:
        why["rule"] = "auto: lanes and duplication clear the crossover"
        return "pippenger", why
    why["rule"] = "auto: below the lanes/duplication crossover"
    return "ladder", why


def resolve(lanes=None, rows=None, sharded: bool = False) -> str:
    """The EFFECTIVE path for one dispatch: 'ladder' or 'pippenger'.

    `lanes`/`rows` are the dispatch's real lane count and Miller-row
    count (their ratio is the duplication factor the crossover model
    keys on); `auto` without shape context resolves to the ladder.
    `sharded=True` means the LEGACY lane-sharded kernel (always
    ladders — raw lane shards split message groups); the group-aligned
    mesh path resolves with sharded=False."""
    return explain(lanes=lanes, rows=rows, sharded=sharded)[0]


class force:
    """Context manager pinning the path (tests / bench A/B)."""

    def __init__(self, path: str):
        self._path = path
        self._prev = None

    def __enter__(self):
        self._prev = _state["path"]
        set_path(self._path)
        return self

    def __exit__(self, *exc):
        set_path(self._prev)
        return False


# --------------------------------------------------------------------------
# Window geometry + host-side digit packing
# --------------------------------------------------------------------------

_warned_window = [False]


def window_env() -> int:
    """The configured bucket window width w (digits are w-bit).

    Read host-side per dispatch (the digit-array SHAPE then carries
    the choice into the traced program via window_for_nwin).  An
    invalid value degrades to the default with one warning — the same
    contract as an invalid TEKU_TPU_MSM: a typo'd tuning knob must
    never start failing live verifications at dispatch time."""
    raw = env_str(ENV_WINDOW, "4")
    try:
        w = int(raw)
        if not 1 <= w <= 8:
            raise ValueError
        return w
    except ValueError:
        with _lock:
            if not _warned_window[0]:
                _warned_window[0] = True
                _LOG.warning("%s=%r is not an int in 1..8; using 4",
                             ENV_WINDOW, raw)
        return 4


def n_windows(window: int) -> int:
    return -(-GLV_BITS // window)


def window_for_nwin(nwin: int) -> int:
    """Invert n_windows: digit-array shapes fully determine the window
    (w in 1..8 <-> nwin in {32,16,11,8,7,6,5,4} is a bijection), so
    the jitted stages never read env at trace time."""
    return -(-GLV_BITS // nwin)


def effective_scalar(k1: int, k2: int) -> int:
    """The multiplier a (k1, k2) pair encodes: k1 + k2*lambda mod r.
    Host-side; the parity tests drive scalar_mul_bits with its bits."""
    return (int(k1) + int(k2) * LAMBDA) % R


def glv_sample_from_uint64(raw: np.ndarray):
    """uint64 entropy (N,) -> (k1, k2) 32-bit half-scalar arrays.

    (0, 0) is nudged to (1, 0) — the only pair whose effective
    multiplier is 0 (PERF.md: for k2 != 0, |k2*lambda mod r| >= z^2 >
    2^127 > k1), mirroring the ladder path's zero-nudge with the same
    negligible 2^-64 bias."""
    raw = np.asarray(raw, dtype=np.uint64)
    k1 = (raw & np.uint64(0xFFFFFFFF)).copy()
    k2 = raw >> np.uint64(32)
    k1[(k1 | k2) == 0] = 1
    return k1, k2


def glv_digits_np(k1, k2, window=None) -> np.ndarray:
    """Half-scalar arrays (N,) -> (N, 2, nwin) int32 w-bit digits,
    MSB-first (Horner order).  Row [:, 0] drives the base point P,
    row [:, 1] drives the endomorphism point [lambda]P."""
    w = window_env() if window is None else window
    nwin = n_windows(w)
    k1 = np.asarray(k1, dtype=np.uint64)
    k2 = np.asarray(k2, dtype=np.uint64)
    if k1.size and (int(k1.max()) >> GLV_BITS or int(k2.max()) >> GLV_BITS):
        raise ValueError("GLV half-scalars must be < 2^%d" % GLV_BITS)
    mask = np.uint64((1 << w) - 1)
    out = np.zeros(k1.shape + (2, nwin), dtype=np.int32)
    for j in range(nwin):
        shift = np.uint64((nwin - 1 - j) * w)
        out[..., 0, j] = ((k1 >> shift) & mask).astype(np.int32)
        out[..., 1, j] = ((k2 >> shift) & mask).astype(np.int32)
    return out


_seg_cache: list = []


def _seg_len() -> int:
    """G2 accumulation segment length (TEKU_TPU_MSM_SEG, pow-2).

    g2_msm only ever runs under jit, so this executes at TRACE time
    and the jit cache keys on input shapes — which seg does not
    change.  Reading the env per call would therefore silently pin
    whatever value the first trace saw; instead the value is resolved
    ONCE per process (a kernel-layer boot knob, like the CLI-set
    TEKU_TPU_MONT_MUL: decide before the first dispatch), and an
    invalid value degrades to the default with one warning."""
    if not _seg_cache:
        raw = env_str(ENV_SEG, "32")
        try:
            seg = int(raw)
            if seg < 1 or seg & (seg - 1):
                raise ValueError
        except ValueError:
            _LOG.warning("%s=%r is not a power of two; using 32",
                         ENV_SEG, raw)
            seg = 32
        with _lock:
            if not _seg_cache:
                _seg_cache.append(seg)
    return _seg_cache[0]


# --------------------------------------------------------------------------
# Device kernels: bucket accumulate -> reduce -> window combine
# --------------------------------------------------------------------------

def _tree(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _infinity_batch(kit, like_elem, batch_shape):
    """Infinity point with an explicit batch shape, dtyped like a
    field element's leaves."""
    template = _tree(
        lambda a: jnp.zeros(batch_shape + a.shape[-1:], a.dtype),
        like_elem)
    return PT.infinity_like(kit, template)


def bucket_accumulate(kit, pts, digits, include):
    """Scatter-accumulate points into per-(row, window, bucket) sums.

    pts: point with leaves (R, C, ...); digits (R, C, nwin) int32 in
    [0, 2^w); include (R, C) — excluded columns touch nothing.
    Returns bucket points with leaves (R, nwin, B), B = 2^w - 1;
    bucket b holds the sum of included points whose digit == b + 1
    (digit 0 contributes nowhere — it is the 'add infinity' of the
    ladder, spelled as a no-op select).

    One lax.scan over C: each step gathers every (row, window)'s
    target bucket, performs ONE batched point_add, and scatters it
    back with a one-hot select — identical work per step regardless
    of digit values (constant-shape, constant-time), and duplicate
    bucket indices across steps are sequenced by the scan.
    """
    R, C, nwin = digits.shape
    w = window_for_nwin(nwin)
    B = (1 << w) - 1
    buckets = _infinity_batch(kit, pts[0], (R, nwin, B))
    xs = (_tree(lambda a: jnp.moveaxis(a, 1, 0), pts),
          jnp.moveaxis(digits, 1, 0),
          jnp.moveaxis(include, 1, 0))
    barange = jnp.arange(B, dtype=digits.dtype)

    def step(bk, inp):
        p, d, inc = inp                     # p leaves (R, L); d (R, nwin)
        idx = jnp.maximum(d - 1, 0)

        def take(leaf):                     # (R, nwin, B, L) -> (R, nwin, L)
            i = jnp.broadcast_to(idx[..., None, None],
                                 idx.shape + (1, leaf.shape[-1]))
            return jnp.take_along_axis(leaf, i, axis=2)[..., 0, :]

        cur = _tree(take, bk)
        pb = _tree(lambda a: jnp.broadcast_to(
            a[:, None], (R, nwin) + a.shape[1:]), p)
        added = PT.point_add(kit, cur, pb)
        hit = ((barange == idx[..., None]) & (d >= 1)[..., None]
               & inc[:, None, None])        # (R, nwin, B)
        added_b = _tree(lambda a: jnp.broadcast_to(
            a[..., None, :], a.shape[:-1] + (B, a.shape[-1])), added)
        return PT._select_point(kit, hit, added_b, bk), None

    buckets, _ = lax.scan(step, buckets, xs)
    return buckets


def bucket_reduce(kit, buckets):
    """Collapse buckets to per-(row, window) sums: sum_b (b+1)*B_b via
    the standard top-down suffix-sum pair (2 adds per bucket)."""
    leaves = jax.tree_util.tree_leaves(buckets)
    R, nwin = leaves[0].shape[:2]
    xs = _tree(lambda a: jnp.moveaxis(a, 2, 0)[::-1], buckets)
    inf = _infinity_batch(kit, buckets[0], (R, nwin))

    def step(carry, bpt):
        acc, tot = carry
        acc = PT.point_add(kit, acc, bpt)
        tot = PT.point_add(kit, tot, acc)
        return (acc, tot), None

    (_, tot), _ = lax.scan(step, (inf, inf), xs)
    return tot


def window_combine(kit, wsums, window: int):
    """Horner fold of per-window sums (leaves (R, nwin), MSB-first):
    w doublings + 1 add per window — the ONE doubling chain each row
    pays, (nwin - 1) * w doublings total."""
    ws = _tree(lambda a: jnp.moveaxis(a, 1, 0), wsums)
    acc = _tree(lambda a: a[0], ws)
    rest = _tree(lambda a: a[1:], ws)

    def step(acc, wpt):
        for _ in range(window):
            acc = PT.point_double(kit, acc)
        return PT.point_add(kit, acc, wpt), None

    acc, _ = lax.scan(step, acc, rest)
    return acc


def msm_rows(kit, pts, digits, include):
    """R independent MSMs: row r computes sum_c [s_rc]P_rc where s_rc
    is the MSB-first digit recomposition of digits[r, c].  Returns a
    (R,)-batched Jacobian point."""
    nwin = digits.shape[-1]
    w = window_for_nwin(nwin)
    buckets = bucket_accumulate(kit, pts, digits, include)
    return window_combine(kit, bucket_reduce(kit, buckets), w)


# --------------------------------------------------------------------------
# The two pipeline MSMs
# --------------------------------------------------------------------------

def g1_grouped_msm(pk_jac, digits, group_idx, group_present,
                   miller_mask):
    """Per-message-group G1 fold: row u gets sum over its lanes of
    [r_i]pk_i = [k1_i]pk_i + [k2_i]phi(pk_i) — each group's MSM runs
    over 2G columns (the lane points and their phi images share one
    bucket grid; phi costs ONE mont_mul per lane, not a ladder).

    Same masking contract as stage_group: miller_mask'd-out lanes are
    selected to infinity BEFORE the gather, group padding columns are
    excluded, padded rows come out infinity.  Returns the (U,)-batched
    Jacobian aggregates (the caller derives u_mask + affine)."""
    inf = PT.infinity_like(PT.G1_KIT, pk_jac[0])
    masked = PT._select_point(PT.G1_KIT, miller_mask, pk_jac, inf)
    grouped = _tree(lambda x: x[group_idx], masked)       # (U, G, ...)
    phi = PT.g1_phi(grouped)
    pts = _tree(lambda a, b: jnp.concatenate([a, b], axis=1),
                grouped, phi)                             # (U, 2G, ...)
    dg = digits[group_idx]                                # (U, G, 2, nwin)
    dg = jnp.concatenate([dg[:, :, 0, :], dg[:, :, 1, :]], axis=1)
    inc = jnp.concatenate([group_present, group_present], axis=1)
    return msm_rows(PT.G1_KIT, pts, dg, inc)


def g2_lambda_point(q):
    """[lambda]Q on G2: psi acts as [z] (z < 0), so psi^2 = [z^2] and
    [lambda]Q = [-z^2]Q = -psi^2(Q).  Two cheap Frobenius-type maps
    instead of a 127-bit ladder; coordinates are compressed back to
    one unit (psi's fq2_muls emit lazy values and point_add requires
    unit inputs)."""
    lam = PT.point_neg(PT.G2_KIT, PT.g2_psi(PT.g2_psi(q)))
    return tuple(PT.G2_KIT.compress(c) for c in lam)


def g2_msm(sig_jac, digits):
    """The whole-batch G2 fold sum_i [r_i]sig_i as ONE MSM over 2N
    columns (each lane contributes sig_i and [lambda]sig_i).

    stage_finish only ever consumes the SUM of the weighted signature
    points, so the per-lane wsig array disappears: the MSM is split
    into TEKU_TPU_MSM_SEG-column segments bucket-accumulated in
    parallel (bounding the scan's sequential depth), the segment
    bucket tables tree-add (bucket sums are additive across disjoint
    column sets), and one reduce + Horner chain finishes.  Returns a
    (1,)-batched Jacobian point — point_batch_sum of a 1-batch is the
    identity, so stage_finish's contract is unchanged."""
    lam = g2_lambda_point(sig_jac)
    pts = _tree(lambda a, b: jnp.concatenate([a, b], axis=0),
                sig_jac, lam)                             # (2N, ...)
    dg = jnp.concatenate([digits[:, 0, :], digits[:, 1, :]], axis=0)
    n2 = dg.shape[0]
    C = min(_seg_len(), n2)
    S = n2 // C                   # both pow-2: exact split
    pts_r = _tree(lambda a: a.reshape((S, C) + a.shape[1:]), pts)
    dg_r = dg.reshape(S, C, dg.shape[-1])
    inc = jnp.ones((S, C), dtype=bool)
    buckets = bucket_accumulate(PT.G2_KIT, pts_r, dg_r, inc)
    if S > 1:
        merged = PT.point_batch_sum(PT.G2_KIT, buckets)   # (nwin, B)
    else:
        merged = _tree(lambda a: a[0], buckets)
    merged = _tree(lambda a: a[None], merged)             # (1, nwin, B)
    wsums = bucket_reduce(PT.G2_KIT, merged)              # (1, nwin)
    return window_combine(PT.G2_KIT, wsums,
                          window_for_nwin(dg.shape[-1]))
