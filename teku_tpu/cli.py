"""Command-line entry point: subcommands + layered configuration.

Equivalent of the reference's CLI layer (reference: teku/src/main/java/
tech/pegasys/teku/Teku.java:37, cli/BeaconNodeCommand.java with
CLI > env (TEKU_*) > YAML layering via CascadingParamsProvider, and the
cli/subcommand/ family — node, validator-client, transition, genesis,
slashing-protection, peer): here argparse subcommands with the same
precedence rules (flags beat TEKU_TPU_* env vars beat --config-file
YAML beat defaults).

Run as `python -m teku_tpu.cli <subcommand>`.
"""

import argparse
import asyncio
import json
import logging
import os
import sys
from pathlib import Path
from typing import Any, Dict, Optional

from .infra.env import env_raw, env_str
from .infra.logs import configure as configure_logging

ENV_PREFIX = "TEKU_TPU_"


def layered_value(name: str, cli_value, yaml_cfg: Dict[str, Any],
                  default=None, cast=str):
    """CLI > env > YAML > default (reference CascadingParamsProvider).

    env_raw, not a typed helper: "unset" must stay distinguishable
    from every real value so YAML and defaults cascade beneath, and a
    malformed value fails flag validation loudly at boot with the
    operator present — the one place typo-degrades is the wrong
    contract."""
    if cli_value is not None:
        return cli_value
    env = env_raw(ENV_PREFIX + name.upper().replace("-", "_"))
    if env is not None:
        return cast(env)
    if name in yaml_cfg:
        return cast(yaml_cfg[name])
    return default


def _load_yaml(path: Optional[str]) -> Dict[str, Any]:
    if not path:
        return {}
    import yaml
    with open(path) as f:
        out = yaml.safe_load(f) or {}
    if not isinstance(out, dict):
        raise SystemExit("config file must be a mapping")
    return out


# --------------------------------------------------------------------------
# subcommands
# --------------------------------------------------------------------------

def _configure_log_format(args, yaml_cfg) -> str:
    """Opt-in structured logging (`--log-format json` /
    TEKU_TPU_LOG_FORMAT): every record becomes one JSON object carrying
    the active trace id, so logs join slow traces and flight-recorder
    events on one correlation key.  Default stays the human-scannable
    text lines."""
    choice = str(layered_value(
        "log-format", getattr(args, "log_format", None), yaml_cfg,
        "text")).lower()
    if choice not in ("text", "json"):
        raise SystemExit(
            f"invalid --log-format {choice!r} (use text or json)")
    configure_logging(fmt=choice)
    return choice


def _configure_tracing(args, yaml_cfg) -> str:
    """Hot-path tracing switch (default on: spans cost ~a perf_counter
    pair each; `off` compiles them to shared no-ops for A/B runs)."""
    from .infra import tracing

    def norm(v):
        # YAML parses bare on/off as booleans; map them back
        if isinstance(v, bool):
            return "on" if v else "off"
        return str(v).lower()

    choice = layered_value("tracing", getattr(args, "tracing", None),
                           yaml_cfg, "on", cast=norm)
    if choice not in ("on", "off"):
        raise SystemExit(f"invalid --tracing {choice!r} (use on or off)")
    tracing.set_enabled(choice == "on")
    return choice


def _configure_overload(args, yaml_cfg) -> str:
    """Overload-control switch (default on): the node wires an
    AdmissionController — deadline-aware adaptive batching, priority
    classes with strict-priority drain, and shed-by-class brownout
    under SLO feedback (`services/admission.py`).  ``off`` restores the
    fixed max-batch drain and overflow-only shedding.  The thresholds
    themselves are env knobs (TEKU_TPU_BROWNOUT_*,
    TEKU_TPU_ADMISSION_*, TEKU_TPU_VERIFY_CLASS_*_DEADLINE_MS —
    README "Overload & priority classes")."""
    def norm(v):
        if isinstance(v, bool):
            return "on" if v else "off"
        return str(v).lower()

    choice = layered_value("overload-control",
                           getattr(args, "overload_control", None),
                           yaml_cfg, "on", cast=norm)
    if choice not in ("on", "off"):
        raise SystemExit(
            f"invalid --overload-control {choice!r} (use on or off)")
    # the env var is how the choice reaches BeaconNode (and every
    # devnet node constructed inside the process)
    os.environ["TEKU_TPU_OVERLOAD_CONTROL"] = choice
    return choice


# mirrors of ops/mxu.py and ops/msm.py PATHS, spelled locally so the
# boot path never imports the ops package (whose __init__ imports jax)
# on the main thread — the env vars are how the choices reach the
# kernel layer
_MONT_PATHS = ("vpu", "mxu", "auto", "mxu-force")
_MSM_PATHS = ("ladder", "pippenger", "auto")


def _validate_mesh(choice: str) -> str:
    """`--mesh {off,auto,N}`: off | auto | a positive device count.

    YAML parses bare off/on/no/yes as booleans before this layer sees
    them, so the boolean spellings normalize instead of failing boot
    (the mesh knob must never be able to fail a node)."""
    if choice in ("off", "auto"):
        return choice
    if choice in ("false", "no", "none", "0", ""):
        return "off"
    if choice in ("true", "on", "yes"):
        return "auto"
    try:
        n = int(choice)
        if n < 1:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"invalid --mesh {choice!r} (use off, auto, or a positive "
            "device count)")
    return str(n)


def _configure_kernel(args, yaml_cfg):
    """Kernel-layer knobs that must be decided BEFORE jax loads:

    - the mont_mul engine (`--mont-path` / TEKU_TPU_MONT_MUL: vpu |
      mxu | auto; auto = the int8 digit-split MXU path exactly when
      the dispatch device is a TPU) — resolved by ops/mxu.py at trace
      time in the probe/dispatch threads;
    - the scalars-stage MSM path (`--msm-path` / TEKU_TPU_MSM: ladder
      | pippenger | auto; auto = the GLV+Pippenger bucketed MSM
      exactly when the dispatch device is a TPU and the batch clears
      the duplication crossover) — resolved by ops/msm.py per
      dispatch;
    - the persistent XLA compile cache (TEKU_TPU_XLA_CACHE_DIR, ON by
      default; =off disables) so warm boots load the multi-minute
      per-shape kernel compiles from disk instead of repaying them.

    Returns (mont_path, msm_path).
    """
    from .infra import compilecache

    choice = str(layered_value(
        "mont-mul", getattr(args, "mont_path", None), yaml_cfg,
        "auto")).lower()
    if choice not in _MONT_PATHS:
        raise SystemExit(f"invalid --mont-path {choice!r} (use one of "
                         f"{'/'.join(_MONT_PATHS)})")
    os.environ["TEKU_TPU_MONT_MUL"] = choice
    msm_choice = str(layered_value(
        "msm-path", getattr(args, "msm_path", None), yaml_cfg,
        "auto")).lower()
    if msm_choice not in _MSM_PATHS:
        raise SystemExit(f"invalid --msm-path {msm_choice!r} (use one "
                         f"of {'/'.join(_MSM_PATHS)})")
    os.environ["TEKU_TPU_MSM"] = msm_choice
    # multi-chip mesh (`--mesh {off,auto,N}` / TEKU_TPU_MESH): resolved
    # to a device mesh by the loader's probe (teku_tpu/parallel — auto
    # takes the largest pow-2 <= available devices, a non-pow-2 or
    # over-sized N demotes with one WARN instead of failing boot).  An
    # EXPLICIT numeric N also forces N virtual host devices so a
    # CPU-fallback node (or devnet) genuinely shards — this XLA flag
    # must be set before jax loads, which is why it lives here; it
    # only affects the host platform, never real TPU device counts.
    mesh_choice = _validate_mesh(str(layered_value(
        "mesh", getattr(args, "mesh", None), yaml_cfg, "off")).lower())
    os.environ["TEKU_TPU_MESH"] = mesh_choice
    if mesh_choice not in ("off", "auto") and int(mesh_choice) > 1:
        from .infra.env import ensure_virtual_devices
        ensure_virtual_devices(int(mesh_choice))
    compilecache.configure()
    return choice, msm_choice, mesh_choice


def _configure_bls(args, yaml_cfg, *, supervise: bool = True,
                   mont_path=None, msm_path=None, mesh=None):
    """Choose the BLS bring-up shape BEFORE any service starts.

    ``auto`` (the default) and ``supervised`` boot the node immediately
    on the pure oracle and return a BackendSupervisor the node runs in
    the background: device bring-up gets unbounded-but-observable
    patience instead of a 120 s probe that a ~25-minute TPU init can
    never beat (VERDICT round 5), and on READY the facade hot-swaps.
    ``jax`` keeps the reference-style hard preflight (Teku.java:74);
    ``pure`` opts out.  Returns (name, supervisor-or-None)."""
    from .crypto.bls import loader
    choice = layered_value("bls-impl", getattr(args, "bls_impl", None),
                           yaml_cfg, "auto")
    if choice in ("auto", "supervised") and supervise:
        loader.configure("supervised")      # oracle serves from slot 0
        supervisor = loader.make_supervisor(mont_path=mont_path,
                                            msm_path=msm_path,
                                            mesh=mesh)
        print("BLS implementation: pure (supervised device bring-up "
              "in background)")
        return "supervised", supervisor
    try:
        name = loader.configure("pure" if choice == "supervised"
                                else choice, mont_path=mont_path,
                                msm_path=msm_path, mesh=mesh)
    except loader.BlsLoadError as exc:
        raise SystemExit(f"BLS preflight failed: {exc}")
    print(f"BLS implementation: {name}")
    return name, None


def cmd_node(args) -> int:
    """Run a beacon node: p2p + REST + optional validators + storage."""
    from .networking import NetworkedNode
    from .api import BeaconRestApi
    from .spec import create_spec
    from .spec.genesis import interop_genesis
    from .storage.database import Database, PersistentChainStorage
    from .validator import (BeaconNodeValidatorApi, LocalSigner,
                            SlashingProtectedSigner, ValidatorClient)
    from .validator.slashing_protection import SlashingProtector

    yaml_cfg = _load_yaml(args.config_file)
    _configure_log_format(args, yaml_cfg)
    _configure_tracing(args, yaml_cfg)
    _configure_overload(args, yaml_cfg)
    # arm the crash path before anything can wedge: faulthandler file
    # + flight-recorder JSONL dump on fatal crash (infra/flightrecorder)
    from .infra import flightrecorder
    flightrecorder.install_crash_hooks()
    mont_path, msm_path, mesh = _configure_kernel(args, yaml_cfg)
    _, bls_supervisor = _configure_bls(args, yaml_cfg,
                                       mont_path=mont_path,
                                       msm_path=msm_path, mesh=mesh)
    network = layered_value("network", args.network, yaml_cfg, "minimal")
    port = int(layered_value("p2p-port", args.p2p_port, yaml_cfg, 0, int))
    rest_port = int(layered_value("rest-port", args.rest_port, yaml_cfg,
                                  5051, int))
    data_dir = layered_value("data-dir", args.data_dir, yaml_cfg)
    n_interop = int(layered_value("interop-validators",
                                  args.interop_validators, yaml_cfg, 0,
                                  int))
    total_interop = int(layered_value("interop-total",
                                      args.interop_total, yaml_cfg,
                                      max(n_interop, 64), int))

    import time
    spec = create_spec(network)
    genesis_time_cfg = int(layered_value(
        "genesis-time", args.genesis_time, yaml_cfg, 0, int))

    # an existing database wins: resume the persisted chain instead of
    # minting a fresh genesis that would orphan it (reference:
    # StorageBackedRecentChainData boot path)
    db = None
    storage = None
    restored = None
    storage_mode = layered_value("storage-mode", args.storage_mode,
                                 yaml_cfg, "prune")
    if storage_mode not in ("archive", "prune"):
        raise SystemExit(f"invalid storage-mode {storage_mode!r} "
                         "(use archive or prune)")
    if data_dir:
        Path(data_dir).mkdir(parents=True, exist_ok=True)
        db = Database(Path(data_dir) / "chain.db", spec,
                      mode=storage_mode)
        storage = PersistentChainStorage(db)
        restored = storage.restore_store(spec)
    from_db = restored is not None

    ckpt_url = layered_value("checkpoint-sync-url",
                             args.checkpoint_sync_url, yaml_cfg)
    if restored is not None:
        anchor_state = db.get_state(db.load_anchor()[0].htr())
        genesis_state = anchor_state
        genesis_time = restored.genesis_time
        sks = interop_genesis(spec.config, total_interop,
                              genesis_time)[1] if n_interop else []
        print(f"resumed from data dir: head slot "
              f"{restored.blocks[restored.get_head()].slot}")
    elif ckpt_url:
        from .node.checkpoint import checkpoint_sync_store
        restored = checkpoint_sync_store(spec, ckpt_url)
        anchor_root = restored.justified_checkpoint.root
        genesis_state = restored.block_states[anchor_root]
        genesis_time = restored.genesis_time
        sks = (interop_genesis(spec.config, total_interop,
                               genesis_time)[1] if n_interop else [])
        print(f"checkpoint-synced from {ckpt_url}: anchor slot "
              f"{genesis_state.slot}")
    else:
        # interop devnets anchor genesis at "now" unless pinned — every
        # node on the devnet must pass the SAME value to share a chain
        genesis_time = genesis_time_cfg or int(time.time())
        genesis_state, sks = interop_genesis(spec.config, total_interop,
                                             genesis_time)

    async def run():
        from .infra.events import FinalizedCheckpointChannel
        udp_port = layered_value("udp-discovery-port",
                                 args.udp_discovery_port, yaml_cfg)
        if args.bootnode and udp_port is None:
            raise SystemExit("--bootnode requires --udp-discovery-port"
                             " (use 0 for an ephemeral port)")
        nn = NetworkedNode(
            spec, genesis_state, port=port, store=restored,
            udp_discovery_port=(int(udp_port) if udp_port is not None
                                else None),
            bootnodes=args.bootnode or [])
        # the node owns the supervisor's lifecycle: bring-up starts
        # with the node and stops with it (node/node.py do_start/do_stop)
        nn.node.supervisor = bls_supervisor
        if db is not None:
            if not from_db:
                # fresh genesis OR checkpoint-synced anchor: persist it
                # so a restart resumes from here
                anchor = nn.node.store.blocks[
                    nn.node.store.justified_checkpoint.root]
                db.save_anchor(anchor,
                               nn.node.store.block_states[anchor.htr()])
            def _persist_import(root):
                storage.on_block_imported(
                    nn.node.store.signed_blocks[root],
                    nn.node.store.block_states[root])
                # verified wire sidecars outlive the in-memory pool:
                # persisted for DA-window serving, pruned by epoch
                sidecars = nn.node.blob_pool.wire_sidecars_for(root)
                if sidecars:
                    db.save_blob_sidecars(root, sidecars)
            nn.node.block_manager.on_imported.append(_persist_import)

            class _FinalizedSink:
                def on_new_finalized_checkpoint(self, checkpoint,
                                                from_optimistic_api=False):
                    storage.on_finalized(nn.node.store, checkpoint)
            nn.node.channels.subscribe(FinalizedCheckpointChannel,
                                       _FinalizedSink())

            from .infra.events import SlotEventsChannel
            from .storage.pruner import StoragePruner
            retention = layered_value("history-retention-epochs",
                                      args.history_retention_epochs,
                                      yaml_cfg)
            pruner = StoragePruner(
                db, spec.config,
                history_retention_epochs=(int(retention)
                                          if retention is not None
                                          else None))
            nn.node.blob_store = db      # req/resp DB fallback
            nn.node.storage_pruner = pruner

            class _PruneSink:
                def on_slot(self, slot):
                    pruner.on_slot(slot)
            nn.node.channels.subscribe(SlotEventsChannel, _PruneSink())
        await nn.start()
        eth1_task = None
        eth1_endpoint = layered_value("eth1-endpoint",
                                      args.eth1_endpoint, yaml_cfg)
        if eth1_endpoint:
            from .node.deposits import DepositProvider
            from .node.eth1 import (Eth1DepositFollower,
                                    JsonRpcEth1Provider)
            host, _, p = eth1_endpoint.rpartition(":")
            provider = DepositProvider(spec.config)
            follower = Eth1DepositFollower(
                provider,
                JsonRpcEth1Provider(host or "127.0.0.1", int(p)),
                follow_distance=int(layered_value(
                    "eth1-follow-distance", args.eth1_follow_distance,
                    yaml_cfg, 8, int)))
            nn.node.deposit_provider = provider
            eth1_task = asyncio.create_task(follower.run())
        api_channel = BeaconNodeValidatorApi(nn.node)
        rest_api = BeaconRestApi(nn.node, nn, port=rest_port,
                                 validator_api=api_channel,
                                 database=db)
        await rest_api.start()
        clients = []
        if n_interop:
            keys = {i: sks[i] for i in range(n_interop)}
            signer = SlashingProtectedSigner(
                LocalSigner(keys),
                SlashingProtector(Path(data_dir) / "slashing"
                                  if data_dir else None))
            clients.append(ValidatorClient(spec, api_channel, signer,
                                           sorted(keys)))
        for addr in args.peer or []:
            host, _, p = addr.rpartition(":")
            try:
                await nn.net.connect(host or "127.0.0.1", int(p))
            except OSError as exc:
                logging.warning("dial %s failed: %s", addr, exc)
        print(f"node up: p2p={nn.net.port} rest={rest_api.port} "
              f"validators={n_interop}/{total_interop}")
        # real-time slot loop
        try:
            while True:
                now = int(time.time())
                slot = max(0, (now - genesis_time)
                           // spec.config.SECONDS_PER_SLOT)
                if slot > 0:
                    await nn.node.on_slot(slot)
                    # joined late or fell behind: multipeer catch-up
                    # (gossiped blocks with unknown parents park in the
                    # pending pool; sync backfills the gap)
                    if nn.node.chain.head_slot() + 1 < slot \
                            and nn.net.peers:
                        try:
                            await nn.sync.run_until_synced(max_rounds=2)
                        except Exception:
                            logging.exception("catch-up sync failed")
                    for c in clients:
                        await c.on_slot_start(slot)
                    await asyncio.sleep(spec.config.SECONDS_PER_SLOT / 3)
                    for c in clients:
                        await c.on_attestation_due(slot)
                    await asyncio.sleep(spec.config.SECONDS_PER_SLOT / 3)
                    for c in clients:
                        await c.on_aggregation_due(slot)
                next_slot_time = genesis_time + (slot + 1) * \
                    spec.config.SECONDS_PER_SLOT
                await asyncio.sleep(max(0.1, next_slot_time - time.time()))
        finally:
            if eth1_task is not None:
                eth1_task.cancel()
            await rest_api.stop()
            await nn.stop()
            if db is not None:
                db.close()

    asyncio.run(run())
    return 0


def _hard_exit_if_virtual_devices(rc: int) -> None:
    """Devnet clean-shutdown guard (pre-existing issue, noted in PR
    10): with a FORCED virtual host device count
    (``--xla_force_host_platform_device_count``, the numeric ``--mesh
    N`` path), XLA's CPU client teardown can race Python interpreter
    finalization and segfault/abort AFTER all devnet work completed
    and the verdict was printed — turning a clean run into rc 134/139.
    Once jax has been imported under that flag, skip interpreter
    teardown entirely: flush the evidence, disarm faulthandler (its
    atexit hook would write to a closing file), and ``os._exit`` with
    the real verdict.  Nothing of value runs after this point — the
    flight recorder dumps on failure paths, the compile cache writes
    at compile time.

    Scope (``TEKU_TPU_DEVNET_HARD_EXIT``: auto|1|0): the guard is for
    STANDALONE CLI processes whose next act is exiting anyway.  An
    embedding process (the in-process pytest suite calls
    ``main(["devnet", ...])`` directly) must never be os._exit'ed out
    from under its caller — ``auto`` (default) skips whenever pytest
    is loaded; ``1`` forces, ``0`` disables."""
    mode = env_str("TEKU_TPU_DEVNET_HARD_EXIT", "auto")
    if mode in ("0", "off", "false"):
        return
    if mode != "1" and "pytest" in sys.modules:
        return
    if "jax" not in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        return
    try:
        import faulthandler
        faulthandler.disable()
    except Exception:
        pass
    logging.shutdown()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)


def cmd_devnet(args) -> int:
    """In-process devnet: N nodes, loopback gossip, fast clock."""
    from .node import Devnet

    _configure_log_format(args, {})
    _configure_tracing(args, {})
    _configure_overload(args, {})
    mont_path, msm_path, mesh = _configure_kernel(args, {})
    _, bls_supervisor = _configure_bls(args, {}, mont_path=mont_path,
                                       msm_path=msm_path, mesh=mesh)

    async def run():
        net = Devnet(n_nodes=args.nodes, n_validators=args.validators)
        if bls_supervisor is not None:
            # the facade swap is process-global; one node owns it
            net.nodes[0].supervisor = bls_supervisor
        await net.start()
        try:
            last = args.epochs * net.spec.config.SLOTS_PER_EPOCH
            for slot in range(1, last + 1):
                await net.run_slot(slot)
                if slot % net.spec.config.SLOTS_PER_EPOCH == 0:
                    print(f"epoch {slot // net.spec.config.SLOTS_PER_EPOCH}"
                          f": justified={net.min_justified_epoch()} "
                          f"finalized={net.min_finalized_epoch()} "
                          f"converged={net.heads_converged()}")
            ok = (net.heads_converged()
                  and net.min_finalized_epoch() >= args.epochs - 3)
            print("devnet", "FINALIZED" if ok else "DID NOT FINALIZE")
            return 0 if ok else 1
        finally:
            await net.stop()

    rc = asyncio.run(run())
    _hard_exit_if_virtual_devices(rc)
    return rc


def cmd_transition(args) -> int:
    """Offline state transition over SSZ files (reference `transition`
    subcommand: cli/subcommand/TransitionCommand)."""
    from .spec import create_spec
    from .spec.transition import state_transition, StateTransitionError

    from .spec.codec import deserialize_signed_block, deserialize_state
    spec = create_spec(args.network)
    state = deserialize_state(spec.config, Path(args.pre).read_bytes())
    for blk_path in args.blocks:
        signed = deserialize_signed_block(spec.config,
                                          Path(blk_path).read_bytes())
        try:
            state = state_transition(spec.config, state, signed,
                                     validate_result=not args.no_validate)
        except StateTransitionError as exc:
            print(f"invalid block {blk_path}: {exc}", file=sys.stderr)
            return 1
    Path(args.post).write_bytes(type(state).serialize(state))
    print(f"post state written: slot={state.slot} root=0x"
          f"{state.htr().hex()}")
    return 0


def cmd_genesis(args) -> int:
    """Write an interop genesis state (reference `genesis` subcommand)."""
    from .spec import create_spec
    from .spec.genesis import interop_genesis

    spec = create_spec(args.network)
    state, _sks = interop_genesis(spec.config, args.validators,
                                  args.genesis_time)
    Path(args.out).write_bytes(spec.schemas.BeaconState.serialize(state))
    print(f"genesis written: {args.out} validators={args.validators} "
          f"root=0x{state.htr().hex()}")
    return 0


def cmd_migrate_database(args) -> int:
    """Convert a data dir between storage modes in place (reference
    cli/util/DatabaseMigrater.java + `migrate-database` subcommand).

    archive -> prune: drops per-slot state snapshots and the slot
    index (PRUNE serves only the anchor + hot subtree).
    prune -> archive: rebuilds the canonical slot index from the
    persisted finalized chain; intermediate states regenerate by
    replay on demand, so no state backfill is needed.
    """
    from .spec import create_spec
    from .storage.database import Database

    spec = create_spec(args.network)
    path = Path(args.data_dir) / "chain.db"
    if not path.exists():
        print(f"no database at {path}", file=sys.stderr)
        return 1
    db = Database(path, spec, mode=args.to)
    anchor_root = db._kv.get(b"meta/anchor_root")
    if anchor_root is None:
        print("database has no anchor; nothing to migrate",
              file=sys.stderr)
        db.close()
        return 1
    dropped_states = dropped_index = 0
    if args.to == "prune":
        for key in db._kv.keys_with_prefix(b"st/"):
            if key[len(b"st/"):] != anchor_root:
                db._kv.delete(key)
                dropped_states += 1
        for key in db._kv.keys_with_prefix(b"sl/"):
            db._kv.delete(key)
            dropped_index += 1
        print(f"migrated to prune: dropped {dropped_states} state "
              f"snapshots, {dropped_index} slot-index entries")
    else:
        db._index_finalized_chain(anchor_root)
        indexed = len(db._kv.keys_with_prefix(b"sl/"))
        print(f"migrated to archive: slot index rebuilt "
              f"({indexed} entries); states regenerate by replay")
    db.compact()
    db.close()
    return 0


def cmd_debug(args) -> int:
    """Debug helpers (reference cli/subcommand/debug/: DebugDbCommand,
    PrettyPrintCommand)."""
    from .spec import create_spec

    if args.debug_cmd == "pretty-print":
        from .spec.codec import (deserialize_signed_block,
                                 deserialize_state)
        spec = create_spec(args.network)
        raw = Path(args.file).read_bytes()
        if args.type == "state":
            obj = deserialize_state(spec.config, raw)
        else:
            obj = deserialize_signed_block(spec.config, raw)

        def render(v, indent=0):
            pad = "  " * indent
            if getattr(type(v), "_ssz_fields", None):
                lines = [f"{pad}{type(v).__name__}:"]
                for name in type(v)._ssz_fields:
                    lines.append(f"{pad}  {name}:")
                    lines.append(render(getattr(v, name), indent + 2))
                return "\n".join(lines)
            if isinstance(v, bytes):
                return f"{pad}0x{v.hex()}"
            if isinstance(v, (tuple, list)):
                if len(v) > 8:
                    return f"{pad}[{len(v)} items]"
                return "\n".join(render(x, indent) for x in v) \
                    if v else f"{pad}[]"
            return f"{pad}{v}"
        print(render(obj))
        return 0
    if args.debug_cmd == "db-info":
        from .storage.database import Database
        spec = create_spec(args.network)
        path = Path(args.data_dir) / "chain.db"
        if not path.exists():
            print(f"no database at {path}", file=sys.stderr)
            return 1
        db = Database(path, spec)
        prefixes = {b"blk/": "blocks", b"st/": "states",
                    b"hot/": "hot refs", b"sl/": "slot index",
                    b"bl/": "blob sidecars", b"meta/": "meta"}
        for prefix, label in prefixes.items():
            print(f"{label}: {len(db._kv.keys_with_prefix(prefix))}")
        anchor = db.load_anchor()
        if anchor is not None:
            print(f"anchor: slot={anchor[0].slot} "
                  f"root=0x{anchor[0].htr().hex()}")
        db.close()
        return 0
    print(f"unknown debug command {args.debug_cmd}", file=sys.stderr)
    return 1


def cmd_admin_weak_subjectivity(args) -> int:
    """Compute the weak-subjectivity period for a state (reference
    cli/subcommand/admin/WeakSubjectivityCommand)."""
    from .spec import create_spec
    from .spec.codec import deserialize_state
    from .spec.weak_subjectivity import (WeakSubjectivityValidator,
                                         compute_weak_subjectivity_period)

    spec = create_spec(args.network)
    state = deserialize_state(spec.config,
                              Path(args.state).read_bytes())
    period = compute_weak_subjectivity_period(spec.config, state)
    epoch = state.slot // spec.config.SLOTS_PER_EPOCH
    print(f"state epoch: {epoch}")
    print(f"weak subjectivity period: {period} epochs")
    print(f"safe until epoch: {epoch + period}")
    if args.current_epoch is not None:
        ok = WeakSubjectivityValidator(spec.config).is_within_period(
            state, args.current_epoch)
        print(f"within period at epoch {args.current_epoch}: {ok}")
        return 0 if ok else 2
    return 0


def cmd_slashing_protection(args) -> int:
    """EIP-3076 interchange import/export (reference
    slashing-protection subcommand)."""
    from .validator.slashing_protection import SlashingProtector

    protector = SlashingProtector(args.data_dir)
    gvr = bytes.fromhex(args.genesis_validators_root.removeprefix("0x"))
    if args.action == "export":
        doc = protector.export_interchange(gvr)
        Path(args.file).write_text(json.dumps(doc, indent=2))
        print(f"exported {len(doc['data'])} records")
    else:
        doc = json.loads(Path(args.file).read_text())
        n = protector.import_interchange(doc, gvr)
        print(f"imported {n} records")
    return 0


def cmd_voluntary_exit(args) -> int:
    """Sign and submit a voluntary exit through a beacon node's REST
    API (reference cli/subcommand/VoluntaryExitCommand.java): the exit
    epoch defaults to the chain's current epoch, the signature uses the
    interop key for --validator-index, and the node's pool validation
    is the acceptance gate."""
    import json as _json
    import urllib.error
    from .crypto import bls
    from .spec import create_spec
    from .spec import helpers as H
    from .spec.config import DOMAIN_VOLUNTARY_EXIT
    from .spec.datastructures import VoluntaryExit
    from .spec.genesis import interop_secret_keys
    from .spec.milestones import build_fork_schedule, SpecMilestone
    from .validator import RemoteValidatorApi

    if not 0 <= args.validator_index < args.interop_total:
        print(f"error: --validator-index must be in "
              f"[0, {args.interop_total})", file=sys.stderr)
        return 2
    spec = create_spec(args.network or "minimal")
    remote = RemoteValidatorApi(spec, args.beacon_node)
    try:
        genesis = remote._get_json("/eth/v1/beacon/genesis")["data"]
        gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
        if args.epoch is not None:
            epoch = args.epoch
        else:
            # the NODE's head decides "current": the local clock plus
            # a guessed preset can disagree with the node's config
            head = remote._get_json(
                "/eth/v1/beacon/headers/head")["data"]
            epoch = (int(head["header"]["message"]["slot"])
                     // spec.config.SLOTS_PER_EPOCH)
    except (urllib.error.URLError, OSError) as exc:
        print(f"error: beacon node unreachable: {exc}",
              file=sys.stderr)
        return 1
    msg = VoluntaryExit(epoch=epoch,
                        validator_index=args.validator_index)
    schedule = build_fork_schedule(spec.config)
    if schedule.milestone_at_epoch(epoch) >= SpecMilestone.DENEB:
        # EIP-7044: deneb+ pins exit domains to the capella fork so
        # exits stay valid forever (spec/deneb/block.py does the same
        # on the verification side)
        version = spec.config.CAPELLA_FORK_VERSION
    else:
        version = schedule.fork_at_epoch(epoch)[1]
    domain = H.compute_domain(DOMAIN_VOLUNTARY_EXIT, version, gvr)
    sk = interop_secret_keys(args.interop_total)[args.validator_index]
    signature = bls.sign(sk, H.compute_signing_root(msg, domain))
    body = _json.dumps({
        "message": {"epoch": str(epoch),
                    "validator_index": str(args.validator_index)},
        "signature": "0x" + signature.hex()}).encode()
    try:
        remote._post("/eth/v1/beacon/pool/voluntary_exits", body,
                     ctype="application/json")
    except urllib.error.HTTPError as exc:
        print(f"exit rejected: HTTP {exc.code} "
              f"{exc.read().decode(errors='replace')}", file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError) as exc:
        print(f"error: beacon node unreachable: {exc}",
              file=sys.stderr)
        return 1
    print(f"voluntary exit submitted: validator "
          f"{args.validator_index} at epoch {epoch}")
    return 0


def cmd_validator_client(args) -> int:
    """VC-only process: duties over the REST API of a remote beacon
    node (reference `validator-client` subcommand /
    ValidatorClientCommand.java with RemoteValidatorApiHandler)."""
    import time
    from .spec import create_spec
    from .spec.genesis import interop_secret_keys
    from .validator import (LocalSigner, RemoteValidatorApi,
                            SlashingProtectedSigner, ValidatorClient)
    from .validator.slashing_protection import SlashingProtector

    # the VC's hot path is signing (host-side); no background bring-up
    _configure_log_format(args, {})
    _configure_tracing(args, {})
    mont_path, msm_path, mesh = _configure_kernel(args, {})
    _configure_bls(args, {}, supervise=False, mont_path=mont_path,
                   msm_path=msm_path, mesh=mesh)
    spec = create_spec(args.network or "minimal")
    remote = RemoteValidatorApi(spec, args.beacon_node)
    genesis = remote._get_json("/eth/v1/beacon/genesis")["data"]
    genesis_time = int(genesis["genesis_time"])
    sks = interop_secret_keys(args.interop_total)
    first = args.interop_start
    if first + args.interop_validators > args.interop_total:
        print("error: --interop-start + --interop-validators exceeds "
              "--interop-total", file=sys.stderr)
        return 2
    keys = {i: sks[i] for i in range(first,
                                     first + args.interop_validators)}
    signer = SlashingProtectedSigner(
        LocalSigner(keys),
        SlashingProtector(Path(args.data_dir) / "slashing"
                          if args.data_dir else None))
    client = ValidatorClient(spec, remote, signer, sorted(keys))
    print(f"validator client up: {len(keys)} validators "
          f"[{first}..{first + len(keys) - 1}] -> {args.beacon_node}")

    async def run():
        third = spec.config.SECONDS_PER_SLOT / 3
        while True:
            now = int(time.time())
            slot = max(0, (now - genesis_time)
                       // spec.config.SECONDS_PER_SLOT)
            if slot > 0:
                try:
                    await client.on_slot_start(slot)
                    await asyncio.sleep(third)
                    await client.on_attestation_due(slot)
                    await asyncio.sleep(third)
                    await client.on_aggregation_due(slot)
                except Exception:
                    logging.exception("duty loop error at slot %d", slot)
            next_slot_time = genesis_time + (slot + 1) * \
                spec.config.SECONDS_PER_SLOT
            await asyncio.sleep(max(0.1, next_slot_time - time.time()))

    asyncio.run(run())
    return 0


def cmd_peer(args) -> int:
    """Generate a node identity (reference `peer generate`)."""
    import secrets
    node_id = secrets.token_bytes(32)
    print(json.dumps({"node_id": node_id.hex()}))
    return 0


def cmd_loadgen(args) -> int:
    """Mainnet-shape load generator: replay seeded-deterministic
    gossip traffic (committee duplication, aggregation waves, sync
    committee, blob waves, adversarial storms) against the REAL
    signature service + admission controller on a virtual clock and
    print the per-scenario/per-class evidence."""
    from .loadgen import driver, scenarios

    if args.list:
        for name, sc in scenarios.SCENARIOS.items():
            print(f"{name:24s} {sc.description}")
        return 0
    names = (list(scenarios.DEFAULT_SWEEP) if args.scenario == "all"
             else [s.strip() for s in args.scenario.split(",")])
    for name in names:
        if name not in scenarios.SCENARIOS:
            print(f"unknown scenario {name!r}; known: "
                  f"{', '.join(scenarios.SCENARIOS)}", file=sys.stderr)
            return 2
    out = driver.run_scenarios(names, seed=args.seed, slots=args.slots,
                               validators=args.validators)
    if args.out:
        Path(args.out).write_text(json.dumps(out, indent=1))
    if args.json:
        print(json.dumps(out, indent=1))
    else:
        hdr = (f"{'scenario':24s} {'sigs/s':>8s} {'p50ms':>8s} "
               f"{'p99ms':>9s} {'dedup':>6s} {'sheds':>6s} "
               f"{'bisect':>6s} {'brownout':>8s}")
        print(hdr)
        for name, rep in out["scenarios"].items():
            print(f"{name:24s} {rep['sigs_per_sec']:>8.1f} "
                  f"{rep['p50_ms']:>8.1f} {rep['p99_ms']:>9.1f} "
                  f"{rep['dedup_ratio']:>6.2f} "
                  f"{rep['shed_total']:>6d} "
                  f"{rep['bisect_dispatches']:>6d} "
                  f"{rep['brownout']['enters']:>8d}")
        print("summary:", json.dumps(out["summary"]))
    summary = out["summary"]
    return 0 if summary["block_import_sheds_worst"] == 0 else 1


def _doctor_fetch_remote(base_url: str, last: int) -> dict:
    """Operator mode: read a LIVE node's admin endpoints and hand the
    snapshots to the engine — nothing here mutates the node."""
    import urllib.request

    def fetch(path):
        with urllib.request.urlopen(base_url.rstrip("/") + path,
                                    timeout=10) as resp:
            return json.loads(resp.read())

    out = {"records": [], "capacity": None, "slo": None,
           "flight": [], "admission": None, "mesh": None,
           "timeline": None}
    try:
        dispatches = fetch(f"/teku/v1/admin/dispatches?last={last}")
    except Exception as exc:  # noqa: BLE001 - operator-facing CLI
        raise SystemExit(
            f"doctor: cannot read {base_url.rstrip('/')}"
            f"/teku/v1/admin/dispatches ({exc}) — is the node up and "
            "does it serve the dispatch ledger?")
    out["records"] = dispatches.get("data", {}).get("records", [])
    try:
        out["capacity"] = fetch("/teku/v1/admin/capacity")["data"]
    except Exception:
        pass
    try:
        out["flight"] = fetch("/teku/v1/admin/flight_recorder").get(
            "data", [])
    except Exception:
        pass
    try:
        readiness = fetch("/teku/v1/admin/readiness")
        out["slo"] = readiness.get("slo")
        out["admission"] = readiness.get("admission")
        # the supervisor's mesh self-description (self_heal block):
        # keeps mesh_degraded diagnosable after the flight ring rolls
        out["mesh"] = (readiness.get("backend") or {}).get("mesh")
    except Exception:
        pass
    try:
        tl = fetch("/teku/v1/admin/timeline")
        out["timeline"] = {"traces": tl.get("traces") or [],
                           "events": tl.get("ring") or []}
    except Exception:
        pass
    return out


def _doctor_probe_devnet(args) -> dict:
    """Local mode: run a short LIVE in-process devnet on the REAL
    device provider (hard jax preflight — the whole point is that the
    ledger/capacity/SLO state being diagnosed is live dispatch
    evidence, not a stub), then harvest every diagnosis input."""
    from .node import Devnet
    from .crypto.bls import loader
    from .infra import capacity as cap
    from .infra import dispatchledger, flightrecorder, timeline, tracing

    mont_path, msm_path, mesh = _configure_kernel(args, {})
    try:
        loader.configure(args.bls_impl or "jax", mont_path=mont_path,
                         msm_path=msm_path, mesh=mesh)
    except loader.BlsLoadError as exc:
        raise SystemExit(f"doctor probe: BLS preflight failed: {exc}")

    async def run():
        net = Devnet(n_nodes=args.nodes, n_validators=args.validators)
        await net.start()
        try:
            for slot in range(1, args.slots + 1):
                await net.run_slot(slot)
            node = net.nodes[0]
            slo = node.slo.snapshot() if node.slo is not None else None
            admission = (node.admission.snapshot()
                         if node.admission is not None else None)
            sup = getattr(node, "supervisor", None)
            mesh = sup.mesh if sup is not None else None
            return slo, admission, mesh
        finally:
            await net.stop()

    slo, admission, mesh = asyncio.run(run())
    # same clamp the admin endpoint applies: a zero/negative --last
    # must not flip records[-last:] into a head-drop
    return {"records": dispatchledger.LEDGER.snapshot(
                last=max(1, args.last)),
            "capacity": cap.snapshot(), "slo": slo,
            "flight": flightrecorder.RECORDER.snapshot(),
            "admission": admission, "mesh": mesh,
            "timeline": {"traces": tracing.slow_traces(),
                         "events": timeline.RING.snapshot()}}


def cmd_doctor(args) -> int:
    """Explainability engine over the dispatch decision ledger: WHY is
    the latency budget being spent the way it is — cold compiles per
    shape, mesh shard makespan skew, padding waste per lane bucket,
    H(m) cache coldness, msm auto-demotions, brownouts/sheds/SLO
    burn — ranked, with every finding citing its evidence (dispatch
    records by seq + trace id, flight-recorder events).  Reads a live
    node via --url, or (default) runs a short live in-process devnet
    on the real device provider and diagnoses it."""
    from .infra import doctor

    _configure_log_format(args, {})
    _configure_tracing(args, {})
    _configure_overload(args, {})
    if args.url:
        inputs = _doctor_fetch_remote(args.url, args.last)
    else:
        inputs = _doctor_probe_devnet(args)
    diagnosis = doctor.diagnose(
        inputs["records"], capacity=inputs.get("capacity"),
        slo=inputs.get("slo"), flight_events=inputs.get("flight"),
        admission=inputs.get("admission"), mesh=inputs.get("mesh"),
        timeline=inputs.get("timeline"))
    if args.json:
        print(json.dumps(diagnosis, indent=1, default=str))
    else:
        print(doctor.render_text(diagnosis))
    if args.out:
        Path(args.out).write_text(
            json.dumps(diagnosis, indent=1, default=str))
    if not inputs["records"] and not args.url:
        # the local probe RAN a devnet: an empty ledger means the
        # device provider never dispatched — that is itself a defect
        print("doctor: probe produced no dispatch records",
              file=sys.stderr)
        return 1
    return 0


def cmd_timeline(args) -> int:
    """Unified causal timeline export (infra/timeline.py).  Joins the
    slow-trace ring, the dispatch decision ledger, the flight recorder
    and the timeline ring on the shared clock spine, then either
    resolves one trace id to its gap-free span tree (--trace-id) or
    writes the whole window as a Perfetto/Chrome trace-event file
    (--out trace.json — load in chrome://tracing or ui.perfetto.dev).
    Reads a live node via --url, or (default) runs a short live
    in-process devnet on the real device provider."""
    from .infra import schema, timeline

    _configure_log_format(args, {})
    _configure_tracing(args, {})
    _configure_overload(args, {})
    if args.url:
        inputs = _doctor_fetch_remote(args.url, args.last)
        tl = inputs.get("timeline") or {}
        traces, ring = tl.get("traces") or [], tl.get("events") or []
    else:
        inputs = _doctor_probe_devnet(args)
        tl = inputs["timeline"]
        traces, ring = tl["traces"], tl["events"]
    records = inputs.get("records") or []
    flight = inputs.get("flight") or []

    if args.trace_id:
        joined = timeline.join(
            args.trace_id, traces,
            [r for r in records
             if args.trace_id in (r.get("trace_ids") or [])],
            [e for e in flight
             if e.get("trace_id") == args.trace_id],
            [e for e in ring
             if e.get("trace_id") == args.trace_id])
        text = json.dumps(joined, indent=1, default=str)
        if args.out:
            Path(args.out).write_text(text)
        print(text)
        return 0 if joined["tree"] is not None else 1

    events = timeline.perfetto(traces, records, flight, ring)
    doc = schema.envelope("perfetto", {"traceEvents": events})
    if args.out:
        Path(args.out).write_text(json.dumps(doc, default=str))
    if args.json and not args.out:
        print(json.dumps(doc, default=str))
    else:
        tracks = sorted(e["args"]["name"] for e in events
                        if e["ph"] == "M" and e["name"] == "thread_name")
        print(f"timeline: {len(events)} trace events, "
              f"{len(traces)} trace(s), {len(records)} dispatch "
              f"record(s), tracks: {', '.join(tracks)}"
              + (f" -> {args.out}" if args.out else ""))
    # an export with nothing but track metadata means the probe saw no
    # dispatches at all — surface that the same way doctor does
    if not traces and not records and not args.url:
        print("timeline: probe produced no traces or dispatch records",
              file=sys.stderr)
        return 1
    return 0


def cmd_precompile(args) -> int:
    """Build the serving shape set into the AOT executable store
    (ops/shapeset.py enumerates it; infra/aotstore.py persists it).
    Install-time twin of supervisor WARMING: every program a boot of
    this config would compile is lowered+compiled HERE and serialized,
    so boots — and selfheal reshapes over the same device set — warm
    by deserializing in seconds instead of paying XLA.  Reports
    per-shape compile vs load (re-runs are incremental: valid entries
    are skipped as loads)."""
    import time as _time

    from .infra import aotstore, compilecache

    _configure_log_format(args, {})
    if args.store_dir:
        os.environ["TEKU_TPU_AOT_STORE_DIR"] = args.store_dir
    mont = str(args.mont_path).lower()
    if mont not in _MONT_PATHS:
        raise SystemExit(f"invalid --mont-path {mont!r} (use one of "
                         f"{'/'.join(_MONT_PATHS)})")
    os.environ["TEKU_TPU_MONT_MUL"] = mont
    msm_choice = str(args.msm_path).lower()
    if msm_choice not in _MSM_PATHS:
        raise SystemExit(f"invalid --msm-path {msm_choice!r} (use one "
                         f"of {'/'.join(_MSM_PATHS)})")
    os.environ["TEKU_TPU_MSM"] = msm_choice
    mesh_choice = _validate_mesh(str(args.mesh).lower())
    os.environ["TEKU_TPU_MESH"] = mesh_choice
    mesh_n = (int(mesh_choice)
              if mesh_choice not in ("off", "auto") else 0)
    if mesh_n > 1:
        from .infra.env import ensure_virtual_devices
        ensure_virtual_devices(mesh_n)
    compilecache.configure()
    if aotstore.store_dir() is None:
        raise SystemExit("AOT store is off (TEKU_TPU_AOT_STORE / "
                         "TEKU_TPU_AOT_STORE_DIR) — nothing to build")

    from .ops import shapeset
    from .ops import verify as V
    from .ops.provider import JaxBls12381

    mesh_obj = None
    if mesh_n >= 2:
        from . import parallel
        mesh_obj = parallel.make_mesh(mesh_n, advertise=False)
    max_batch = args.max_batch or shapeset.SERVICE_MAX_BATCH
    min_bucket = args.min_bucket or shapeset.SERVICE_MIN_BUCKET
    # constructing the provider registers the pk_validate dispatcher;
    # staged_jits() registers the stage dispatchers; the mesh kernel
    # registers per msm path below
    impl = JaxBls12381(max_batch=max_batch,
                       min_bucket=min_bucket, mesh=mesh_obj)
    V.staged_jits()
    programs = list(shapeset.enumerate_programs(
        max_batch=max_batch, min_bucket=impl.min_bucket,
        h2c_min_bucket=impl._h2c_min_bucket,
        group_cap=impl._group_cap, mesh=mesh_obj))
    print(f"precompile: {len(programs)} program(s) -> "
          f"{aotstore.store_dir()}")
    outcomes = {"compile": 0, "load": 0, "error": 0}
    t_all = _time.monotonic()
    for kernel, avals, meta in programs:
        if meta.get("stage") == "mesh_kernel":
            impl._sharded.kernel(meta["msm_path"])
        disp = aotstore.dispatchers().get(kernel)
        if disp is None:
            print(f"  SKIP {kernel}: no registered dispatcher "
                  f"({meta})", file=sys.stderr)
            outcomes["error"] += 1
            continue
        t0 = _time.monotonic()
        try:
            outcome = disp.precompile(avals)
        except Exception as exc:
            print(f"  FAIL {kernel} {meta.get('shape', '')}: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            outcomes["error"] += 1
            continue
        outcomes[outcome] += 1
        print(f"  {outcome:>7} {kernel:<28} "
              f"profile={meta.get('profile', '-'):<10} "
              f"{_time.monotonic() - t0:8.1f}s")
    print(f"precompile done in {_time.monotonic() - t_all:.1f}s: "
          f"{outcomes['compile']} compiled, {outcomes['load']} "
          f"already stored, {outcomes['error']} failed")
    return 1 if outcomes["error"] else 0


def cmd_lint(args) -> int:
    """tekulint: the AST-based invariant analyzer (teku_tpu/analysis).

    Mechanizes the review-hardening bug classes of PRs 1-12 — raw
    TEKU_TPU_* env reads, trace-time side effects inside jit'd
    kernels, torn two-read access to swap attributes, metric naming /
    label-vocabulary violations, undeclared fault sites and flight
    event kinds, duplicated private helpers, and README knob drift.
    Exit 0 = clean, 1 = unsuppressed findings (or stale suppression
    entries), 2 = the suppression file itself is invalid."""
    from .analysis import run_lint
    from .analysis.env_knob import render_knob_table
    from .analysis.suppress import SuppressionError

    try:
        report = run_lint(root=args.root,
                          suppressions_path=args.suppressions)
    except SuppressionError as exc:
        print(f"lint: {exc}", file=sys.stderr)
        return 2
    if args.knobs:
        table = render_knob_table(report.knobs)
        if args.out:
            Path(args.out).write_text(table + "\n")
        print(table)
        return 0
    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.render_text())
    if args.out:
        Path(args.out).write_text(
            json.dumps(report.to_dict(), indent=1))
    return 0 if report.clean else 1


# --------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="teku-tpu", description="TPU-native beacon node")
    sub = p.add_subparsers(dest="cmd", required=True)

    n = sub.add_parser("node", help="run a beacon node")
    n.add_argument("--network", default=None)
    n.add_argument("--config-file", default=None)
    n.add_argument("--p2p-port", type=int, default=None)
    n.add_argument("--rest-port", type=int, default=None)
    n.add_argument("--data-dir", default=None)
    n.add_argument("--storage-mode", default=None,
                   choices=["archive", "prune"],
                   help="archive keeps the full chain with state "
                        "snapshots; prune keeps finalized + hot")
    n.add_argument("--history-retention-epochs", type=int, default=None,
                   help="optionally drop finalized blocks/states older "
                        "than this many epochs (rolling-window node); "
                        "blob sidecars always prune at the DA window")
    n.add_argument("--interop-validators", type=int, default=None,
                   help="run the first N interop validators locally")
    n.add_argument("--interop-total", type=int, default=None,
                   help="total validators at genesis")
    n.add_argument("--genesis-time", type=int, default=None,
                   help="unix genesis time (default: now; devnet nodes "
                        "must agree)")
    n.add_argument("--udp-discovery-port", type=int, default=None,
                   help="enable UDP node discovery on this port "
                        "(0 = ephemeral)")
    n.add_argument("--bootnode", action="append",
                   help="UDP discovery bootstrap address ip:udp_port")
    n.add_argument("--peer", action="append",
                   help="host:port to dial (repeatable)")
    n.add_argument("--eth1-endpoint", default=None,
                   help="eth1 JSON-RPC host:port for the deposit "
                        "follower")
    n.add_argument("--eth1-follow-distance", type=int, default=None)
    n.add_argument("--checkpoint-sync-url", default=None,
                   help="REST base URL of a trusted node to anchor "
                        "from (finalized state + block)")
    n.add_argument("--bls-impl", default=None,
                   choices=["auto", "supervised", "jax", "pure"],
                   help="BLS provider: auto (= supervised) boots on "
                        "the pure oracle and hot-swaps to the JAX/TPU "
                        "kernel when background bring-up reaches READY; "
                        "jax blocks on a hard preflight and makes "
                        "accelerator failure fatal; pure opts out")
    n.add_argument("--mont-path", default=None,
                   choices=["vpu", "mxu", "auto"],
                   help="mont_mul engine for the verify kernels: vpu "
                        "(elementwise int64), mxu (int8 digit-split "
                        "matmul on the TPU matrix unit), auto "
                        "(default: mxu exactly when the dispatch "
                        "device is a TPU).  mxu on a non-TPU device "
                        "falls back to vpu with one warning.  Env: "
                        "TEKU_TPU_MONT_MUL")
    n.add_argument("--msm-path", default=None,
                   choices=["ladder", "pippenger", "auto"],
                   help="scalars-stage engine for the batch-verify "
                        "multiplier folds: ladder (per-lane windowed "
                        "double-and-add), pippenger (GLV half-scalar "
                        "split + windowed bucket MSM, one doubling "
                        "chain per message group), auto (default: "
                        "pippenger exactly when the dispatch device "
                        "is a TPU and the batch clears the "
                        "duplication crossover; see PERF.md).  Env: "
                        "TEKU_TPU_MSM")
    n.add_argument("--mesh", default=None, metavar="{off,auto,N}",
                   help="multi-chip verify mesh: off (default, "
                        "single-device dispatch), auto (largest pow-2 "
                        "<= available devices), or an explicit device "
                        "count N (non-pow-2/over-sized N demotes with "
                        "one warning; numeric N also forces N virtual "
                        "host devices on CPU fallback).  The "
                        "dedup-aware pipeline shards group-aligned: "
                        "each chip owns whole message groups.  Env: "
                        "TEKU_TPU_MESH")
    n.add_argument("--overload-control", default=None,
                   choices=["on", "off"],
                   help="adaptive batching + priority classes + "
                        "shed-by-class brownout under SLO feedback "
                        "(default on; thresholds via TEKU_TPU_BROWNOUT_"
                        "*/TEKU_TPU_ADMISSION_* env knobs)")
    n.add_argument("--tracing", default=None, choices=["on", "off"],
                   help="hot-path verify tracing: per-stage latency "
                        "histograms on /metrics and the slow-trace "
                        "ring on /teku/v1/admin/traces (default on; "
                        "off compiles spans to no-ops)")
    n.add_argument("--log-format", default=None,
                   choices=["text", "json"],
                   help="console log format: json emits one object "
                        "per line carrying the active trace id, so "
                        "logs correlate with slow traces and "
                        "flight-recorder events")
    n.set_defaults(fn=cmd_node)

    d = sub.add_parser("devnet", help="in-process fast devnet")
    d.add_argument("--nodes", type=int, default=2)
    d.add_argument("--validators", type=int, default=32)
    d.add_argument("--epochs", type=int, default=4)
    d.add_argument("--bls-impl", default=None,
                   choices=["auto", "supervised", "jax", "pure"])
    d.add_argument("--mont-path", default=None,
                   choices=["vpu", "mxu", "auto"])
    d.add_argument("--msm-path", default=None,
                   choices=["ladder", "pippenger", "auto"])
    d.add_argument("--mesh", default=None, metavar="{off,auto,N}")
    d.add_argument("--tracing", default=None, choices=["on", "off"])
    d.add_argument("--overload-control", default=None,
                   choices=["on", "off"])
    d.add_argument("--log-format", default=None,
                   choices=["text", "json"])
    d.set_defaults(fn=cmd_devnet)

    t = sub.add_parser("transition", help="offline state transition")
    t.add_argument("--network", default="minimal")
    t.add_argument("--pre", required=True)
    t.add_argument("--post", required=True)
    t.add_argument("--no-validate", action="store_true")
    t.add_argument("blocks", nargs="*")
    t.set_defaults(fn=cmd_transition)

    g = sub.add_parser("genesis", help="write an interop genesis state")
    g.add_argument("--network", default="minimal")
    g.add_argument("--validators", type=int, default=64)
    g.add_argument("--genesis-time", type=int, default=1578009600)
    g.add_argument("--out", required=True)
    g.set_defaults(fn=cmd_genesis)

    s = sub.add_parser("slashing-protection",
                       help="EIP-3076 interchange import/export")
    s.add_argument("action", choices=["import", "export"])
    s.add_argument("--data-dir", required=True)
    s.add_argument("--file", required=True)
    s.add_argument("--genesis-validators-root", default="00" * 32)
    s.set_defaults(fn=cmd_slashing_protection)

    vc = sub.add_parser("validator-client",
                        help="VC-only process against a remote node")
    ve = sub.add_parser("voluntary-exit",
                        help="sign and submit a voluntary exit")
    ve.set_defaults(fn=cmd_voluntary_exit)
    ve.add_argument("--network", default=None)
    ve.add_argument("--beacon-node", default="http://127.0.0.1:5051")
    ve.add_argument("--validator-index", type=int, required=True)
    ve.add_argument("--epoch", type=int, default=None,
                    help="exit epoch (default: current)")
    ve.add_argument("--interop-total", type=int, default=64,
                    help="interop keyset size the index signs from")

    vc.add_argument("--network", default=None)
    vc.add_argument("--beacon-node", default="http://127.0.0.1:5051",
                    help="REST base URL of the beacon node")
    vc.add_argument("--interop-validators", type=int, default=8)
    vc.add_argument("--interop-start", type=int, default=0,
                    help="first interop key index this VC owns")
    vc.add_argument("--interop-total", type=int, default=64)
    vc.add_argument("--data-dir", default=None)
    vc.add_argument("--bls-impl", default=None,
                    choices=["auto", "supervised", "jax", "pure"])
    vc.add_argument("--mont-path", default=None,
                    choices=["vpu", "mxu", "auto"])
    vc.add_argument("--msm-path", default=None,
                    choices=["ladder", "pippenger", "auto"])
    vc.add_argument("--mesh", default=None, metavar="{off,auto,N}")
    vc.add_argument("--tracing", default=None, choices=["on", "off"])
    vc.add_argument("--log-format", default=None,
                    choices=["text", "json"])
    vc.set_defaults(fn=cmd_validator_client)

    pe = sub.add_parser("peer", help="generate a node identity")
    pe.set_defaults(fn=cmd_peer)

    lg = sub.add_parser(
        "loadgen",
        help="mainnet-shape load generator (virtual clock, real "
             "service + admission controller)")
    lg.add_argument("--scenario", default="all",
                    help="comma-separated scenario names, or 'all' "
                         "(see --list)")
    lg.add_argument("--list", action="store_true",
                    help="list known scenarios and exit")
    lg.add_argument("--seed", type=int, default=1,
                    help="traffic-model seed (same seed = identical "
                         "event stream)")
    lg.add_argument("--slots", type=int, default=2,
                    help="slots of traffic per scenario")
    lg.add_argument("--validators", type=int, default=None,
                    help="modeled network size (default 1,000,000)")
    lg.add_argument("--json", action="store_true",
                    help="print the full JSON report instead of the "
                         "table")
    lg.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    lg.set_defaults(fn=cmd_loadgen)

    dr = sub.add_parser(
        "doctor",
        help="explain the current latency budget from the dispatch "
             "decision ledger + capacity/SLO/flight-recorder state")
    dr.add_argument("--url", default=None,
                    help="base URL of a live node's REST API to "
                         "diagnose (e.g. http://127.0.0.1:5051); "
                         "default runs a short live in-process devnet "
                         "on the real device provider")
    dr.add_argument("--last", type=int, default=128,
                    help="how many ledger records to read")
    dr.add_argument("--json", action="store_true",
                    help="print the machine-readable diagnosis")
    dr.add_argument("--out", default=None,
                    help="also write the JSON diagnosis to this path")
    dr.add_argument("--slots", type=int, default=4,
                    help="probe devnet: slots to run")
    dr.add_argument("--nodes", type=int, default=1,
                    help="probe devnet: node count")
    dr.add_argument("--validators", type=int, default=8,
                    help="probe devnet: validator count")
    dr.add_argument("--bls-impl", default=None,
                    help="probe devnet BLS implementation (default "
                         "jax: the probe exists to exercise the real "
                         "device dispatch path)")
    dr.add_argument("--mont-path", default=None,
                    choices=list(_MONT_PATHS))
    dr.add_argument("--msm-path", default=None,
                    choices=list(_MSM_PATHS))
    dr.add_argument("--mesh", default=None,
                    help="probe devnet mesh spec (off, auto, or N)")
    dr.add_argument("--log-format", default=None,
                    choices=["text", "json"])
    dr.add_argument("--tracing", default=None)
    dr.add_argument("--overload-control", default=None)
    dr.set_defaults(fn=cmd_doctor)

    tl = sub.add_parser(
        "timeline",
        help="unified causal timeline: join traces + dispatch ledger "
             "+ flight recorder + timeline ring on one clock spine; "
             "export a Perfetto trace or resolve one trace id to its "
             "gap-free span tree")
    tl.add_argument("--url", default=None,
                    help="base URL of a live node's REST API (default "
                         "runs a short live in-process devnet on the "
                         "real device provider)")
    tl.add_argument("--trace-id", default=None,
                    help="resolve this trace id to its joined span "
                         "tree instead of exporting the whole window")
    tl.add_argument("--out", default=None,
                    help="write the Perfetto/Chrome trace-event JSON "
                         "(or joined tree) to this path")
    tl.add_argument("--json", action="store_true",
                    help="print the trace-event JSON to stdout")
    tl.add_argument("--last", type=int, default=128,
                    help="how many ledger records to read")
    tl.add_argument("--slots", type=int, default=4,
                    help="probe devnet: slots to run")
    tl.add_argument("--nodes", type=int, default=1,
                    help="probe devnet: node count")
    tl.add_argument("--validators", type=int, default=8,
                    help="probe devnet: validator count")
    tl.add_argument("--bls-impl", default=None,
                    help="probe devnet BLS implementation")
    tl.add_argument("--mont-path", default=None,
                    choices=list(_MONT_PATHS))
    tl.add_argument("--msm-path", default=None,
                    choices=list(_MSM_PATHS))
    tl.add_argument("--mesh", default=None,
                    help="probe devnet mesh spec (off, auto, or N)")
    tl.add_argument("--log-format", default=None,
                    choices=["text", "json"])
    tl.add_argument("--tracing", default=None)
    tl.add_argument("--overload-control", default=None)
    tl.set_defaults(fn=cmd_timeline)

    ln = sub.add_parser(
        "lint",
        help="AST-based invariant analyzer over the production tree "
             "(env-knob discipline, jit purity, torn reads, metric "
             "contract, closed registries, duplicate helpers, knob "
             "doc drift)")
    ln.add_argument("--root", default=None,
                    help="tree to analyze (default: this repo)")
    ln.add_argument("--suppressions", default=None,
                    help="suppression file (default: "
                         "<root>/lint_suppressions.json; every entry "
                         "needs a justification)")
    ln.add_argument("--json", action="store_true",
                    help="print the machine-readable report")
    ln.add_argument("--out", default=None,
                    help="also write the JSON report (or --knobs "
                         "table) to this path")
    ln.add_argument("--knobs", action="store_true",
                    help="emit the auto-extracted TEKU_TPU_* knob "
                         "registry as a markdown table and exit 0")
    ln.set_defaults(fn=cmd_lint)

    pc = sub.add_parser(
        "precompile",
        help="build the serving shape set into the AOT executable "
             "store (install-time compile: boots then warm by "
             "deserializing, not compiling)")
    pc.add_argument("--max-batch", type=int,
                    default=None, dest="max_batch",
                    help="service max batch (default: the service "
                         "tier's 256)")
    pc.add_argument("--min-bucket", type=int,
                    default=None, dest="min_bucket",
                    help="smallest lane bucket (default: the service "
                         "tier's 16)")
    pc.add_argument("--mesh", default="off",
                    help="mesh width to precompile for (off or N; "
                         "forces N virtual devices on CPU like `node "
                         "--mesh N`)")
    pc.add_argument("--msm-path", default="auto", dest="msm_path",
                    help="scalar-multiplication engine "
                         f"({'/'.join(_MSM_PATHS)})")
    pc.add_argument("--mont-path", default="auto", dest="mont_path",
                    help="mont_mul engine "
                         f"({'/'.join(_MONT_PATHS)})")
    pc.add_argument("--store-dir", default=None, dest="store_dir",
                    help="AOT store directory (default: repo-adjacent "
                         ".jax_aot / TEKU_TPU_AOT_STORE_DIR)")
    pc.set_defaults(fn=cmd_precompile)

    mg = sub.add_parser("migrate-database",
                        help="convert a data dir between storage modes")
    mg.add_argument("--network", default="minimal")
    mg.add_argument("--data-dir", required=True)
    mg.add_argument("--to", required=True, choices=["archive", "prune"])
    mg.set_defaults(fn=cmd_migrate_database)

    dbg = sub.add_parser("debug", help="debug helpers")
    dbg_sub = dbg.add_subparsers(dest="debug_cmd", required=True)
    pp = dbg_sub.add_parser("pretty-print",
                            help="render an SSZ file as text")
    pp.add_argument("--network", default="minimal")
    pp.add_argument("type", choices=["state", "block"])
    pp.add_argument("file")
    di = dbg_sub.add_parser("db-info", help="database key statistics")
    di.add_argument("--network", default="minimal")
    di.add_argument("--data-dir", required=True)
    dbg.set_defaults(fn=cmd_debug)

    adm = sub.add_parser("admin", help="admin utilities")
    adm_sub = adm.add_subparsers(dest="admin_cmd", required=True)
    ws = adm_sub.add_parser("weak-subjectivity",
                            help="compute the WS period for a state")
    ws.add_argument("--network", default="minimal")
    ws.add_argument("--state", required=True)
    ws.add_argument("--current-epoch", type=int, default=None)
    ws.set_defaults(fn=cmd_admin_weak_subjectivity)
    return p


def main(argv=None) -> int:
    configure_logging()
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
