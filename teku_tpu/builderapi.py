"""Builder (MEV relay) flow: blinded blocks, bids, registrations,
circuit-broken fallback to local payloads.

Equivalent of the reference's builder stack (reference: ethereum/
executionclient/.../BuilderClient.java + builder bid validation
BuilderBidValidatorImpl.java, BuilderCircuitBreakerImpl.java, and the
blinded-block flow in spec/logic/common/util/BlindBlockUtil.java with
beacon/validator/.../ExecutionLayerBlockProductionManager): the
proposer asks a builder for a payload HEADER, signs a blinded block
over it, and only after the signed blinded block is submitted does the
builder reveal the payload body.

The blinding identity that makes this safe: an execution payload
header carries its variable fields by root, so
ExecutionPayloadHeader.htr() == ExecutionPayload.htr() and a blinded
block's root equals the full block's root — one proposer signature
covers both shapes.
"""

import logging
from dataclasses import dataclass, field
from typing import Dict, Optional

from .crypto import bls
from .spec import helpers as H
from .spec.config import DOMAIN_APPLICATION_MASK, SpecConfig
from .spec.milestones import build_fork_schedule
from .ssz import (Bytes20, Bytes32, Bytes48, Bytes96, Container, uint64,
                  uint256)
from .ssz.types import _ContainerMeta

_LOG = logging.getLogger(__name__)

def builder_domain(cfg: SpecConfig) -> bytes:
    """The builder spec's application domain: DomainType 0x00000001
    over the network's GENESIS fork version and an empty root (no
    per-fork rotation, so registrations survive forks — but the
    network IS part of the domain, per mev-boost's ComputeDomain)."""
    return H.compute_domain(DOMAIN_APPLICATION_MASK,
                            cfg.GENESIS_FORK_VERSION)


class ValidatorRegistration(Container):
    fee_recipient: Bytes20
    gas_limit: uint64
    timestamp: uint64
    pubkey: Bytes48


class SignedValidatorRegistration(Container):
    message: ValidatorRegistration
    signature: Bytes96


def sign_registration(cfg: SpecConfig, sk: int,
                      registration: ValidatorRegistration
                      ) -> SignedValidatorRegistration:
    root = H.compute_signing_root(registration, builder_domain(cfg))
    return SignedValidatorRegistration(message=registration,
                                       signature=bls.sign(sk, root))


def verify_registration(cfg: SpecConfig,
                        signed: SignedValidatorRegistration) -> bool:
    root = H.compute_signing_root(signed.message, builder_domain(cfg))
    return bls.verify(signed.message.pubkey, root, signed.signature)


# ---- blinded blocks ------------------------------------------------------

def _blinded_schemas(cfg: SpecConfig, slot: int):
    """(BlindedBeaconBlock, SignedBlindedBeaconBlock) for the milestone
    governing `slot`: the fork's body with execution_payload swapped
    for its header (reference SchemaDefinitionsBellatrix
    getBlindedBeaconBlockBodySchema)."""
    version = build_fork_schedule(cfg).version_at_slot(slot)
    S = version.schemas
    if "execution_payload" not in S.BeaconBlockBody._ssz_fields:
        raise ValueError("pre-merge fork has no blinded blocks")
    fields = []
    for name, schema in S.BeaconBlockBody._ssz_fields.items():
        if name == "execution_payload":
            fields.append(("execution_payload_header",
                           S.ExecutionPayloadHeader))
        else:
            fields.append((name, schema))
    body = _ContainerMeta(
        f"Blinded{S.BeaconBlockBody.__name__}", (Container,),
        {"__annotations__": dict(fields)})
    block = _ContainerMeta(
        f"Blinded{S.BeaconBlock.__name__}", (Container,),
        {"__annotations__": {
            "slot": uint64, "proposer_index": uint64,
            "parent_root": Bytes32, "state_root": Bytes32,
            "body": body}})
    signed = _ContainerMeta(
        f"SignedBlinded{S.BeaconBlock.__name__}", (Container,),
        {"__annotations__": {"message": block, "signature": Bytes96}})
    return block, signed


_BLINDED_CACHE: Dict = {}


def blinded_schemas(cfg: SpecConfig, slot: int):
    version = build_fork_schedule(cfg).version_at_slot(slot)
    key = (cfg, version.milestone)
    if key not in _BLINDED_CACHE:
        _BLINDED_CACHE[key] = _blinded_schemas(cfg, slot)
    return _BLINDED_CACHE[key]


def _payload_to_header(payload):
    from .spec.bellatrix.datastructures import payload_to_header
    from .spec.capella.datastructures import payload_to_header_capella
    from .spec.deneb.datastructures import payload_to_header_deneb
    fields = type(payload)._ssz_fields
    if "blob_gas_used" in fields:
        return payload_to_header_deneb(payload)
    if "withdrawals" in fields:
        return payload_to_header_capella(payload)
    return payload_to_header(payload)


def blind_block(cfg: SpecConfig, block):
    """Full BeaconBlock → BlindedBeaconBlock with the same htr."""
    BlindedBlock, _ = blinded_schemas(cfg, block.slot)
    body = block.body
    kw = {}
    for name in BlindedBlock._ssz_fields["body"]._ssz_fields:
        if name == "execution_payload_header":
            kw[name] = _payload_to_header(body.execution_payload)
        else:
            kw[name] = getattr(body, name)
    return BlindedBlock(
        slot=block.slot, proposer_index=block.proposer_index,
        parent_root=block.parent_root, state_root=block.state_root,
        body=BlindedBlock._ssz_fields["body"](**kw))


def unblind_block(cfg: SpecConfig, signed_blinded, payload):
    """SignedBlindedBeaconBlock + revealed payload → full
    SignedBeaconBlock; rejects a payload that doesn't match the header
    the proposer signed."""
    blinded = signed_blinded.message
    header = blinded.body.execution_payload_header
    if _payload_to_header(payload) != header:
        raise ValueError("revealed payload does not match signed header")
    version = build_fork_schedule(cfg).version_at_slot(blinded.slot)
    S = version.schemas
    kw = {}
    for name in S.BeaconBlockBody._ssz_fields:
        if name == "execution_payload":
            kw[name] = payload
        else:
            kw[name] = getattr(blinded.body, name)
    block = S.BeaconBlock(
        slot=blinded.slot, proposer_index=blinded.proposer_index,
        parent_root=blinded.parent_root, state_root=blinded.state_root,
        body=S.BeaconBlockBody(**kw))
    assert block.htr() == blinded.htr(), "blinding identity violated"
    return S.SignedBeaconBlock(message=block,
                               signature=signed_blinded.signature)


# ---- bids ----------------------------------------------------------------

_BID_SCHEMA_CACHE: Dict = {}


def _bid_container(cfg: SpecConfig, header_type, requests_type):
    """The builder-spec SSZ BuilderBid for this header's fork: deneb+
    headers (they carry blob_gas_used) add blob_kzg_commitments, and
    electra bids (they carry an ExecutionRequests the builder must
    reveal) add execution_requests between the commitments and the
    value (builder-specs deneb/electra BuilderBid; reference
    SchemaDefinitionsDeneb/Electra builder bid schemas)."""
    key = (cfg, header_type, requests_type)
    if key not in _BID_SCHEMA_CACHE:
        fields = {"header": header_type}
        if "blob_gas_used" in header_type._ssz_fields:
            from .ssz.types import List
            fields["blob_kzg_commitments"] = List(
                Bytes48, cfg.MAX_BLOB_COMMITMENTS_PER_BLOCK)
        if requests_type is not None:
            fields["execution_requests"] = requests_type
        fields["value"] = uint256
        fields["pubkey"] = Bytes48
        _BID_SCHEMA_CACHE[key] = _ContainerMeta(
            "BuilderBid", (Container,), {"__annotations__": fields})
    return _BID_SCHEMA_CACHE[key]


@dataclass
class BuilderBid:
    header: object          # the fork's ExecutionPayloadHeader
    value: int              # wei offered to the proposer
    pubkey: bytes           # builder's BLS key
    signature: bytes = b""
    blob_kzg_commitments: tuple = ()   # deneb+: covered by the signature
    # electra+: the fork's ExecutionRequests (deneb and electra share a
    # header type, so the requests object — not header sniffing — is
    # what selects the electra bid shape; producers at electra slots
    # MUST set it, empty requests included)
    execution_requests: object = None

    def to_ssz(self, cfg: SpecConfig):
        schema = _bid_container(
            cfg, type(self.header),
            None if self.execution_requests is None
            else type(self.execution_requests))
        kw = {"header": self.header, "value": self.value,
              "pubkey": self.pubkey}
        if "blob_kzg_commitments" in schema._ssz_fields:
            kw["blob_kzg_commitments"] = tuple(self.blob_kzg_commitments)
        if self.execution_requests is not None:
            kw["execution_requests"] = self.execution_requests
        return schema(**kw)

    def signing_root(self, cfg: SpecConfig) -> bytes:
        return H.compute_signing_root(self.to_ssz(cfg),
                                      builder_domain(cfg))


def sign_bid(cfg: SpecConfig, sk: int, bid: BuilderBid) -> BuilderBid:
    bid.signature = bls.sign(sk, bid.signing_root(cfg))
    return bid


def validate_bid(cfg: SpecConfig, bid: BuilderBid, parent_hash: bytes,
                 min_value: int = 0) -> bool:
    """reference BuilderBidValidatorImpl: builder signature, payload
    continuity, acceptable value."""
    if bid.value < min_value:
        return False
    if bid.header.parent_hash != parent_hash:
        return False
    return bls.verify(bid.pubkey, bid.signing_root(cfg), bid.signature)


# ---- the client seam + circuit breaker -----------------------------------

class BuilderClient:
    """What a relay connection provides (reference BuilderClient.java);
    implementations may be HTTP or in-process."""

    async def register_validators(self, registrations) -> None:
        raise NotImplementedError

    async def get_header(self, slot: int, parent_hash: bytes,
                         pubkey: bytes) -> Optional[BuilderBid]:
        raise NotImplementedError

    async def get_payload(self, signed_blinded_block):
        raise NotImplementedError


class BuilderCircuitBreaker:
    """reference BuilderCircuitBreakerImpl: consecutive faults disable
    the builder for a cooldown window of slots."""

    def __init__(self, fault_limit: int = 3, cooldown_slots: int = 8):
        self.fault_limit = fault_limit
        self.cooldown_slots = cooldown_slots
        self._faults = 0
        self._disabled_until = -1

    def record_fault(self, slot: int) -> None:
        self._faults += 1
        if self._faults >= self.fault_limit:
            self._disabled_until = slot + self.cooldown_slots
            self._faults = 0
            _LOG.warning("builder circuit OPEN until slot %d",
                         self._disabled_until)

    def record_success(self) -> None:
        self._faults = 0

    def is_engaged(self, slot: int) -> bool:
        return slot > self._disabled_until


class BuilderFlow:
    """Chooses builder vs local payload for a proposal (reference
    ExecutionLayerBlockProductionManager): ask the builder for a bid
    when the circuit is closed and the bid validates; otherwise fall
    back to the local payload path."""

    def __init__(self, cfg: SpecConfig, builder: Optional[BuilderClient],
                 breaker: Optional[BuilderCircuitBreaker] = None,
                 min_bid_value: int = 0):
        self.cfg = cfg
        self.builder = builder
        self.breaker = breaker or BuilderCircuitBreaker()
        self.min_bid_value = min_bid_value

    async def select_header(self, slot: int, parent_hash: bytes,
                            proposer_pubkey: bytes):
        """The builder's payload header, or None → build locally."""
        if self.builder is None or not self.breaker.is_engaged(slot):
            return None
        try:
            bid = await self.builder.get_header(slot, parent_hash,
                                                proposer_pubkey)
        except Exception:
            _LOG.exception("builder get_header failed")
            self.breaker.record_fault(slot)
            return None
        if bid is None:
            return None
        if not validate_bid(self.cfg, bid, parent_hash,
                            self.min_bid_value):
            self.breaker.record_fault(slot)
            return None
        self.breaker.record_success()
        return bid.header

    async def reveal(self, signed_blinded_block):
        """Submit the signed blinded block; the builder reveals the
        payload, which must match the signed header."""
        payload = await self.builder.get_payload(signed_blinded_block)
        return unblind_block(self.cfg, signed_blinded_block, payload)
