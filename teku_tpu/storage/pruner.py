"""Storage pruners: bound the database's growth while the node runs.

Equivalent of the reference's pruner family (reference: storage/src/
main/java/tech/pegasys/teku/storage/server/pruner/BlobSidecarPruner.java,
BlockPruner.java, StatePruner.java — periodic async jobs deleting data
past their retention windows).  Here one throttled pass owns all three
concerns:

- blob sidecars past the data-availability window
  (MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS — the spec serving horizon);
- finalized blocks/states past an OPTIONAL retention window (off by
  default: PRUNE mode already drops non-canonical data on
  finalization, ARCHIVE mode means "keep everything" — an explicit
  retention turns a node into a rolling-window node).

The pass runs at most once per epoch, from the node's on_slot phase,
and is synchronous-but-bounded: each pass walks only expired keys.
"""

import logging
from typing import Optional

_LOG = logging.getLogger(__name__)


class StoragePruner:
    def __init__(self, db, cfg,
                 blob_retention_epochs: Optional[int] = None,
                 history_retention_epochs: Optional[int] = None):
        self.db = db
        self.cfg = cfg
        self.blob_retention_epochs = (
            blob_retention_epochs if blob_retention_epochs is not None
            else cfg.MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS)
        self.history_retention_epochs = history_retention_epochs
        self._last_pruned_epoch = -1
        # observability (the reference exports the same counters)
        self.blobs_pruned_total = 0
        self.blocks_pruned_total = 0
        self.states_pruned_total = 0

    def on_slot(self, slot: int) -> None:
        epoch = slot // self.cfg.SLOTS_PER_EPOCH
        if epoch == self._last_pruned_epoch \
                or slot % self.cfg.SLOTS_PER_EPOCH != 0:
            return
        self._last_pruned_epoch = epoch
        spe = self.cfg.SLOTS_PER_EPOCH
        blob_cutoff = (epoch - self.blob_retention_epochs) * spe
        if blob_cutoff > 0:
            n = self.db.prune_blob_sidecars(blob_cutoff)
            self.blobs_pruned_total += n
            if n:
                _LOG.info("pruned %d blob sidecars below slot %d",
                          n, blob_cutoff)
        if self.history_retention_epochs is not None:
            cutoff = (epoch - self.history_retention_epochs) * spe
            if cutoff > 0:
                b, s = self.db.prune_finalized_history(cutoff)
                self.blocks_pruned_total += b
                self.states_pruned_total += s
                if b or s:
                    _LOG.info("pruned %d blocks / %d states below "
                              "slot %d", b, s, cutoff)
