"""Chain database: persistence + restart/resume on the KV engine.

Equivalent of the reference's storage server (reference: storage/src/
main/java/tech/pegasys/teku/storage/server/Database.java:45 and
kvstore/ column-family schema; StoreBuilder rebuilding the hot store on
boot): blocks and states keyed by root, the finalized anchor + hot
block set tracked in meta keys, ARCHIVE vs PRUNE state retention, and
`load_anchor()` returning what a restarting node needs to resume.
"""

import logging
from typing import Dict, List, Optional, Tuple

from ..native.kv import KvStore
from ..spec import Spec
from ..spec.codec import (deserialize_signed_block, deserialize_state,
                          serialize_signed_block)
from .store import Store

_LOG = logging.getLogger(__name__)

_BLOCK = b"blk/"
_STATE = b"st/"
_HOT = b"hot/"
_META_ANCHOR = b"meta/anchor_root"
_META_JUST = b"meta/justified"
_META_FIN = b"meta/finalized"

ARCHIVE = "archive"
PRUNE = "prune"


class Database:
    def __init__(self, path, spec: Spec, mode: str = PRUNE):
        assert mode in (ARCHIVE, PRUNE)
        self.spec = spec
        self.mode = mode
        self._kv = KvStore(path)

    # -- writes --------------------------------------------------------
    def save_block(self, signed_block, post_state=None) -> None:
        root = signed_block.message.htr()
        self._kv.put(_BLOCK + root, serialize_signed_block(signed_block))
        self._kv.put(_HOT + root, b"1")
        if post_state is not None and self.mode == ARCHIVE:
            self._kv.put(_STATE + root,
                         type(post_state).serialize(post_state))

    def save_anchor(self, anchor_block, anchor_state) -> None:
        """Persist a full (block, state) anchor — genesis or finalized
        checkpoint (the restart/checkpoint-sync entry point)."""
        if not hasattr(anchor_block, "message"):   # bare BeaconBlock
            from ..spec.milestones import build_fork_schedule
            S = build_fork_schedule(self.spec.config).version_at_slot(
                anchor_block.slot).schemas
            anchor_block = S.SignedBeaconBlock(
                message=anchor_block, signature=b"\x00" * 96)
        root = anchor_block.message.htr()
        self._kv.put(_BLOCK + root, serialize_signed_block(anchor_block))
        self._kv.put(_STATE + root,
                     type(anchor_state).serialize(anchor_state))
        self._kv.put(_META_ANCHOR, root)

    def on_finalized(self, checkpoint, state, live_roots) -> None:
        """Advance the anchor to the new finalized checkpoint, persist
        its state, drop pruned forks (PRUNE mode keeps only the
        finalized chain + hot subtree; reference pruners in
        storage/server/pruner/)."""
        root = checkpoint.root
        self._kv.put(_STATE + root, type(state).serialize(state))
        self._kv.put(_META_ANCHOR, root)
        self._kv.put(_META_FIN, checkpoint.epoch.to_bytes(8, "little")
                     + checkpoint.root)
        live = set(live_roots)
        for key in self._kv.keys_with_prefix(_HOT):
            r = key[len(_HOT):]
            if r not in live:
                self._kv.delete(key)
                if self.mode == PRUNE:
                    self._kv.delete(_BLOCK + r)
                    if r != root:
                        self._kv.delete(_STATE + r)
        self._kv.flush()

    # -- reads ---------------------------------------------------------
    def get_block(self, root: bytes):
        raw = self._kv.get(_BLOCK + root)
        if raw is None:
            return None
        return deserialize_signed_block(self.spec.config, raw)

    def get_state(self, root: bytes):
        raw = self._kv.get(_STATE + root)
        if raw is None:
            return None
        return deserialize_state(self.spec.config, raw)

    def load_anchor(self):
        """(anchor_block_message, anchor_state, hot_blocks) or None —
        everything a restarting node needs (reference StoreBuilder)."""
        root = self._kv.get(_META_ANCHOR)
        if root is None:
            return None
        signed = self.get_block(root)
        state = self.get_state(root)
        if signed is None or state is None:
            return None
        hot = []
        for key in self._kv.keys_with_prefix(_HOT):
            blk = self.get_block(key[len(_HOT):])
            if blk is not None:
                hot.append(blk)
        hot.sort(key=lambda b: b.message.slot)
        return signed.message, state, hot

    def close(self) -> None:
        self._kv.flush()
        self._kv.close()

    def compact(self) -> None:
        self._kv.compact()


class PersistentChainStorage:
    """Binds a Database to a running Store: persists imports, advances
    the anchor on finalization, and can resurrect a Store on boot
    (reference: StorageBackedRecentChainData.create)."""

    def __init__(self, db: Database):
        self.db = db

    def on_block_imported(self, signed_block, post_state) -> None:
        self.db.save_block(signed_block, post_state)

    def on_finalized(self, store: Store, checkpoint) -> None:
        state = store.block_states.get(checkpoint.root)
        if state is None:
            return
        live = [r for r in store.blocks
                if store.proto.is_descendant(checkpoint.root, r)]
        self.db.on_finalized(checkpoint, state, live)

    def restore_store(self, spec: Spec,
                      validate_signatures: bool = False) -> Optional[Store]:
        """Rebuild a fork-choice store from the persisted anchor + hot
        blocks (signatures were already verified before they were
        persisted, so the replay skips them by default)."""
        loaded = self.db.load_anchor()
        if loaded is None:
            return None
        anchor_block, anchor_state, hot = loaded
        store = Store(spec.config, anchor_state, anchor_block)
        anchor_root = anchor_block.htr()
        for signed in hot:
            if signed.message.htr() == anchor_root:
                continue
            # advance the clock to the block's slot so replay is never
            # rejected as "from the future"
            store.on_tick(store.genesis_time + signed.message.slot
                          * spec.config.SECONDS_PER_SLOT)
            try:
                store.on_block(signed,
                               validate_signatures=validate_signatures)
            except Exception as exc:
                _LOG.warning("hot block replay dropped %s: %s",
                             signed.message.htr().hex()[:8], exc)
        return store
