"""Fork-choice store + spec on_tick/on_block/on_attestation rules.

Equivalent of the reference's Store + ForkChoice pair (reference:
storage/src/main/java/tech/pegasys/teku/storage/store/Store.java and
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/forkchoice/ForkChoice.java:213-520, with the spec rules
from ethereum/spec/.../logic/common/util/ForkChoiceUtil.java): holds
blocks, states, checkpoints and votes; admits blocks via the full state
transition; answers get_head through the proto-array.
"""

from typing import Dict, List, Optional, Tuple

from ..spec.config import SpecConfig
from ..spec.datastructures import Checkpoint
from ..spec import epoch as E
from ..spec import helpers as H
from ..spec.transition import (process_slots, state_transition,
                               StateTransitionError)
from .protoarray import ProtoArray

INTERVALS_PER_SLOT = 3


class ForkChoiceError(Exception):
    """Block/attestation rejected by fork-choice rules."""


class Store:
    """get_forkchoice_store(anchor_state, anchor_block) (spec)."""

    def __init__(self, cfg: SpecConfig, anchor_state, anchor_block,
                 proposer_boost_enabled: bool = True):
        self.cfg = cfg
        anchor_root = anchor_block.htr()
        assert anchor_block.state_root == anchor_state.htr()
        anchor_epoch = H.get_current_epoch(cfg, anchor_state)
        self.time = (anchor_state.genesis_time
                     + cfg.SECONDS_PER_SLOT * anchor_state.slot)
        self.genesis_time = anchor_state.genesis_time
        self.justified_checkpoint = Checkpoint(epoch=anchor_epoch,
                                               root=anchor_root)
        self.finalized_checkpoint = Checkpoint(epoch=anchor_epoch,
                                               root=anchor_root)
        self.proposer_boost_enabled = proposer_boost_enabled
        self.blocks: Dict[bytes, object] = {anchor_root: anchor_block}
        # full signed envelopes, retained to serve req/resp block syncs;
        # the anchor gets a zero-signature envelope (its signature is
        # not part of the anchor trust model) so RPC can serve it too —
        # in the anchor slot's OWN fork family (a checkpoint-sync
        # anchor can be any milestone)
        from ..spec.milestones import build_fork_schedule
        S = build_fork_schedule(cfg).version_at_slot(
            anchor_block.slot).schemas
        self.signed_blocks: Dict[bytes, object] = {
            anchor_root: S.SignedBeaconBlock(message=anchor_block,
                                             signature=b"\x00" * 96)}
        self.block_states: Dict[bytes, object] = {anchor_root: anchor_state}
        self.checkpoint_states: Dict[Tuple[int, bytes], object] = {
            (anchor_epoch, anchor_root): anchor_state}
        # per-block unrealized checkpoints (pulled-up tips)
        self.unrealized_justifications: Dict[bytes, Checkpoint] = {
            anchor_root: self.justified_checkpoint}
        # store-level unrealized checkpoints, promoted on epoch-boundary
        # ticks (spec on_tick_per_epoch)
        self.unrealized_justified = self.justified_checkpoint
        self.unrealized_finalized = self.finalized_checkpoint
        self.proto = ProtoArray(anchor_epoch, anchor_epoch)
        self.proto.on_block(anchor_block.slot, anchor_root,
                            b"\x00" * 32, anchor_epoch, anchor_epoch,
                            epoch=anchor_epoch,
                            unrealized_justified_epoch=anchor_epoch)
        self._equivocating: set = set()

    # ------------------------------------------------------------------
    @property
    def current_slot(self) -> int:
        return (self.time - self.genesis_time) // self.cfg.SECONDS_PER_SLOT

    def current_epoch(self) -> int:
        return H.compute_epoch_at_slot(self.cfg, self.current_slot)

    def get_checkpoint_state(self, checkpoint: Checkpoint):
        """State advanced to the checkpoint epoch start (spec
        store.checkpoint_states; used for attestation validation and
        justified balances)."""
        key = (checkpoint.epoch, checkpoint.root)
        state = self.checkpoint_states.get(key)
        if state is None:
            base = self.block_states.get(checkpoint.root)
            if base is None:
                raise ForkChoiceError("unknown checkpoint root")
            target_slot = H.compute_start_slot_at_epoch(
                self.cfg, checkpoint.epoch)
            if base.slot < target_slot:
                base = process_slots(self.cfg, base, target_slot)
            self.checkpoint_states[key] = base
            state = base
        return state

    # ------------------------------------------------------------------
    # on_tick
    # ------------------------------------------------------------------

    def on_tick(self, time: int) -> None:
        prev_slot = self.current_slot
        prev_epoch = self.current_epoch()
        if time < self.time:
            return
        self.time = time
        if self.current_slot > prev_slot:
            self.proto.clear_proposer_boost()
        if self.current_epoch() > prev_epoch:
            # epoch boundary: justification the chain has earned but not
            # yet processed becomes real (spec on_tick_per_epoch →
            # update_checkpoints with the unrealized checkpoints)
            self._update_checkpoints(self.unrealized_justified,
                                     self.unrealized_finalized)

    def on_slot_start(self) -> None:
        self.proto.clear_proposer_boost()

    # ------------------------------------------------------------------
    # on_block
    # ------------------------------------------------------------------

    def on_block(self, signed_block, validate_signatures: bool = True):
        """Admit a block: parent known, not from the future, descends
        from finalized; full (batched-signature) state transition; then
        checkpoint bookkeeping + proto-array insert.  Returns the post
        state (reference ForkChoice.onBlock → spec on_block)."""
        block = signed_block.message
        parent_root = block.parent_root
        pre_state = self.block_states.get(parent_root)
        if pre_state is None:
            raise ForkChoiceError("unknown parent")
        if self.current_slot < block.slot:
            raise ForkChoiceError("block from the future")
        finalized_slot = H.compute_start_slot_at_epoch(
            self.cfg, self.finalized_checkpoint.epoch)
        if block.slot <= finalized_slot:
            raise ForkChoiceError("block slot not after finalized")
        if self.proto.ancestor_at_slot(
                parent_root, finalized_slot) != self.finalized_checkpoint.root:
            raise ForkChoiceError("block does not descend from finalized")

        root = block.htr()
        if root in self.blocks:
            return self.block_states[root]

        try:
            post = state_transition(self.cfg, pre_state, signed_block,
                                    validate_result=validate_signatures)
        except StateTransitionError as exc:
            raise ForkChoiceError(f"invalid block: {exc}") from exc

        self.blocks[root] = block
        self.signed_blocks[root] = signed_block
        self.block_states[root] = post

        # proposer boost (spec: if within the first interval of the slot)
        time_into_slot = ((self.time - self.genesis_time)
                          % self.cfg.SECONDS_PER_SLOT)
        if (self.proposer_boost_enabled
                and self.current_slot == block.slot
                and time_into_slot
                < self.cfg.SECONDS_PER_SLOT // INTERVALS_PER_SLOT):
            committee_weight = (
                H.get_total_active_balance(self.cfg, post)
                // self.cfg.SLOTS_PER_EPOCH)
            boost = (committee_weight
                     * self.cfg.PROPOSER_SCORE_BOOST) // 100
            self.proto.set_proposer_boost(root, boost)

        # pulled-up justification: run epoch accounting on the post
        # state to expose justification the chain has earned but not yet
        # processed (modern spec compute_pulled_up_tip; the reference's
        # protoarray stores the same per-node "unrealized" checkpoints)
        from ..spec.milestones import build_fork_schedule
        unrealized = build_fork_schedule(self.cfg).version_at_slot(
            post.slot).process_justification(self.cfg, post)
        uj = unrealized.current_justified_checkpoint
        uf = unrealized.finalized_checkpoint
        self.unrealized_justifications[root] = uj
        if uj.epoch > self.unrealized_justified.epoch:
            self.unrealized_justified = uj
        if uf.epoch > self.unrealized_finalized.epoch:
            self.unrealized_finalized = uf

        block_epoch = H.compute_epoch_at_slot(self.cfg, block.slot)
        pulled_up = block_epoch < self.current_epoch()
        if pulled_up:
            # block from a prior epoch: unrealized counts immediately
            self._update_checkpoints(uj, uf)
        else:
            self._update_checkpoints(post.current_justified_checkpoint,
                                     post.finalized_checkpoint)

        self.proto.on_block(
            block.slot, root, parent_root,
            post.current_justified_checkpoint.epoch,
            post.finalized_checkpoint.epoch,
            epoch=block_epoch, unrealized_justified_epoch=uj.epoch)

        # votes carried inside the block count for fork choice
        # (reference ForkChoice.applyIndexedAttestations; signatures
        # were already settled by the block's own batch verification)
        for att in block.body.attestations:
            try:
                indexed = H.get_indexed_attestation(self.cfg, post, att)
                self.on_attestation(att, is_from_block=True,
                                    indexed=indexed)
            except (ForkChoiceError, AssertionError):
                continue
        return post

    def _update_checkpoints(self, justified: Checkpoint,
                            finalized: Checkpoint) -> None:
        if justified.epoch > self.justified_checkpoint.epoch:
            self.justified_checkpoint = justified
        if finalized.epoch > self.finalized_checkpoint.epoch:
            self.finalized_checkpoint = finalized

    # ------------------------------------------------------------------
    # on_attestation
    # ------------------------------------------------------------------

    def on_attestation(self, attestation, is_from_block: bool = False,
                       indexed=None, signature_verified: bool = False):
        """Spec on_attestation: validate slot/target/block linkage, then
        record latest messages.  `signature_verified=True` skips the
        aggregate-signature re-check for attestations the gossip
        pipeline already settled through the batch verifier — without
        it every accepted attestation would pay a second, serial
        pairing here."""
        data = attestation.data
        target = data.target
        if not is_from_block:
            cur = self.current_epoch()
            prev = cur - 1 if cur > 0 else 0
            if target.epoch not in (cur, prev):
                raise ForkChoiceError("attestation target epoch not current/previous")
            if data.slot + 1 > self.current_slot:
                raise ForkChoiceError("attestation from the future")
        if target.epoch != H.compute_epoch_at_slot(self.cfg, data.slot):
            raise ForkChoiceError("attestation target/slot mismatch")
        if target.root not in self.blocks:
            raise ForkChoiceError("unknown target root")
        if data.beacon_block_root not in self.blocks:
            raise ForkChoiceError("unknown head block")
        if self.blocks[data.beacon_block_root].slot > data.slot:
            raise ForkChoiceError("attestation for block newer than slot")
        # LMD vote must be consistent with target
        expected = self.proto.ancestor_at_slot(
            data.beacon_block_root,
            H.compute_start_slot_at_epoch(self.cfg, target.epoch))
        if expected != target.root:
            raise ForkChoiceError("head block not descendant of target")

        if indexed is None:
            target_state = self.get_checkpoint_state(target)
            try:
                if (data.index >= H.get_committee_count_per_slot(
                        self.cfg, target_state, target.epoch)):
                    raise ForkChoiceError("committee index out of range")
                indexed = H.get_indexed_attestation(
                    self.cfg, target_state, attestation)
            except AssertionError as exc:
                raise ForkChoiceError(f"malformed attestation: {exc}") from exc
            # spec on_attestation: the indexed attestation must carry a
            # valid aggregate signature (skipped when the gossip
            # pipeline already batch-verified it)
            if not signature_verified:
                from ..spec.block import is_valid_indexed_attestation
                from ..spec.verifiers import SIMPLE
                if not is_valid_indexed_attestation(
                        self.cfg, target_state, indexed, SIMPLE):
                    raise ForkChoiceError("invalid indexed attestation")
        for vi in indexed.attesting_indices:
            if vi not in self._equivocating:
                self.proto.process_attestation(
                    vi, data.beacon_block_root, target.epoch)

    # ------------------------------------------------------------------
    def get_head(self) -> bytes:
        justified_state = self.get_checkpoint_state(
            self.justified_checkpoint)
        balances = [
            v.effective_balance if H.is_active_validator(
                v, H.get_current_epoch(self.cfg, justified_state)) else 0
            for v in justified_state.validators]
        return self.proto.find_head(
            self.justified_checkpoint.root,
            self.justified_checkpoint.epoch,
            self.finalized_checkpoint.epoch,
            balances, self.current_epoch())

    def get_head_state(self):
        return self.block_states[self.get_head()]
