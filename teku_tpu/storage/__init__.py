"""Storage layer: fork-choice store, proto-array, chain data, KV.

Reference: /root/reference/storage/ (Store.java, protoarray/,
client/RecentChainData.java, server/ KV database).
"""

from .protoarray import ProtoArray, VoteTracker
from .store import ForkChoiceError, Store
