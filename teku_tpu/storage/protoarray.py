"""Proto-array LMD-GHOST fork choice structure.

Equivalent of the reference's ProtoArray (reference: storage/src/main/
java/tech/pegasys/teku/storage/protoarray/ProtoArray.java, 759 LoC, and
ProtoArrayScoreCalculator.java / VoteTracker.java): an append-only
array of block nodes with parent indices, vote weights maintained by
DELTAS (each validator's balance moves from its old target to its new
target, then deltas back-propagate in one reverse sweep), and
best_child/best_descendant pointers so find_head is O(1) after each
O(n) apply pass.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class ProtoNode:
    root: bytes
    parent: Optional[int]
    justified_epoch: int            # realized (from the post state)
    finalized_epoch: int
    slot: int = 0
    epoch: int = 0                  # epoch of `slot`
    unrealized_justified_epoch: int = 0
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


@dataclass
class VoteTracker:
    """Per-validator latest message (reference VoteTracker.java)."""
    current_root: bytes = b"\x00" * 32
    next_root: bytes = b"\x00" * 32
    next_epoch: int = 0


class ProtoArray:
    def __init__(self, justified_epoch: int = 0, finalized_epoch: int = 0):
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[bytes, int] = {}
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.votes: Dict[int, VoteTracker] = {}
        self.balances: List[int] = []
        # proposer boost: one boosted root per slot, cleared on tick
        self.proposer_boost_root: bytes = b"\x00" * 32
        self.proposer_boost_amount: int = 0

    # ------------------------------------------------------------------
    def contains(self, root: bytes) -> bool:
        return root in self.indices

    def on_block(self, slot: int, root: bytes, parent_root: bytes,
                 justified_epoch: int, finalized_epoch: int,
                 epoch: int = 0,
                 unrealized_justified_epoch: Optional[int] = None) -> None:
        if root in self.indices:
            return
        parent = self.indices.get(parent_root)
        idx = len(self.nodes)
        self.nodes.append(ProtoNode(
            root=root, parent=parent, justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch, slot=slot, epoch=epoch,
            unrealized_justified_epoch=(
                justified_epoch if unrealized_justified_epoch is None
                else unrealized_justified_epoch)))
        self.indices[root] = idx
        if parent is not None:
            self._maybe_update_best_child_and_descendant(parent, idx)

    # ------------------------------------------------------------------
    def process_attestation(self, validator_index: int, block_root: bytes,
                            target_epoch: int) -> None:
        vote = self.votes.get(validator_index)
        if vote is None:
            # a first vote is always accepted (spec update_latest_messages:
            # "i not in store.latest_messages"), including target epoch 0
            self.votes[validator_index] = VoteTracker(
                next_root=block_root, next_epoch=target_epoch)
        elif target_epoch > vote.next_epoch:
            vote.next_root = block_root
            vote.next_epoch = target_epoch

    # ------------------------------------------------------------------
    def find_head(self, justified_root: bytes,
                  justified_epoch: int, finalized_epoch: int,
                  justified_balances: List[int],
                  current_epoch: int) -> bytes:
        """Apply pending vote deltas and walk best_descendant from the
        justified root (reference ForkChoiceStrategy.findHead →
        protoArray.applyScoreChanges + node walk)."""
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self._current_epoch = current_epoch
        deltas = self._compute_deltas(justified_balances)
        self._apply_score_changes(deltas)
        self.balances = list(justified_balances)
        idx = self.indices.get(justified_root)
        if idx is None:
            raise KeyError(f"unknown justified root {justified_root.hex()}")
        node = self.nodes[idx]
        best = node.best_descendant
        head = self.nodes[best] if best is not None else node
        return head.root

    # ------------------------------------------------------------------
    def _compute_deltas(self, new_balances: List[int]) -> List[int]:
        """Move each changed vote's weight old→new (reference
        ProtoArrayScoreCalculator.computeDeltas)."""
        deltas = [0] * len(self.nodes)
        old_balances = self.balances
        for vi, vote in self.votes.items():
            old_bal = old_balances[vi] if vi < len(old_balances) else 0
            new_bal = new_balances[vi] if vi < len(new_balances) else 0
            if (vote.current_root != vote.next_root
                    or old_bal != new_bal):
                i = self.indices.get(vote.current_root)
                if i is not None:
                    deltas[i] -= old_bal
                j = self.indices.get(vote.next_root)
                if j is not None:
                    deltas[j] += new_bal
                vote.current_root = vote.next_root
        return deltas

    def set_proposer_boost(self, root: bytes, amount: int) -> None:
        self.proposer_boost_root = root
        self.proposer_boost_amount = amount

    def clear_proposer_boost(self) -> None:
        self.proposer_boost_root = b"\x00" * 32
        self.proposer_boost_amount = 0

    def _apply_score_changes(self, deltas: List[int]) -> None:
        """One reverse sweep: add each node's delta (+transient proposer
        boost), bubble into the parent delta, refresh best pointers
        (reference ProtoArray.applyScoreChanges)."""
        boost_idx = self.indices.get(self.proposer_boost_root)
        for idx in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[idx]
            delta = deltas[idx]
            node.weight += delta
            if node.parent is not None:
                deltas[node.parent] += delta
        # proposer boost is transient: undo last round's boost, apply
        # this round's (the delta model is add-once, boosts are per-slot)
        prev = getattr(self, "_applied_boost", None)
        if prev is not None:
            p_idx, p_amt = prev
            self._bubble_weight(p_idx, -p_amt)
            self._applied_boost = None
        if boost_idx is not None and self.proposer_boost_amount:
            self._bubble_weight(boost_idx, self.proposer_boost_amount)
            self._applied_boost = (boost_idx, self.proposer_boost_amount)
        for idx in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[idx]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(
                    node.parent, idx)

    def _bubble_weight(self, idx: int, amount: int) -> None:
        i: Optional[int] = idx
        while i is not None:
            self.nodes[i].weight += amount
            i = self.nodes[i].parent

    # ------------------------------------------------------------------
    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """Voting-source viability (spec filter_block_tree /
        get_voting_source; reference ProtoArray.nodeIsViableForHead):
        once the store's epoch has moved past the block's own epoch, the
        block's UNREALIZED justification is its voting source — a tip
        that has earned justification the store just promoted stays
        viable even though its realized checkpoint lags.  Plus the
        lenient two-epoch tolerance.  Finalized descent is enforced at
        on_block admission."""
        current_epoch = getattr(self, "_current_epoch", None)
        if current_epoch is not None and current_epoch > node.epoch:
            voting_source = node.unrealized_justified_epoch
        else:
            voting_source = node.justified_epoch
        return (self.justified_epoch == 0
                or voting_source == self.justified_epoch
                or (current_epoch is not None
                    and voting_source + 2 >= current_epoch))

    def _leads_to_viable_head(self, node: ProtoNode) -> bool:
        if (node.best_descendant is not None):
            return self._node_is_viable_for_head(
                self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child_and_descendant(self, parent_idx: int,
                                                child_idx: int) -> None:
        child = self.nodes[child_idx]
        parent = self.nodes[parent_idx]
        child_leads = self._leads_to_viable_head(child)
        child_best = (child.best_descendant
                      if child.best_descendant is not None else child_idx)

        if parent.best_child == child_idx:
            if not child_leads:
                parent.best_child = None
                parent.best_descendant = None
            else:
                parent.best_descendant = child_best
            return
        if parent.best_child is None:
            if child_leads:
                parent.best_child = child_idx
                parent.best_descendant = child_best
            return
        best = self.nodes[parent.best_child]
        best_leads = self._leads_to_viable_head(best)
        if child_leads and not best_leads:
            take = True
        elif not child_leads:
            take = False
        else:
            cw, bw = child.weight, best.weight
            if cw != bw:
                take = cw > bw
            else:  # tie-break on root bytes (reference: compareTo)
                take = child.root > best.root
        if take:
            parent.best_child = child_idx
            parent.best_descendant = child_best

    # ------------------------------------------------------------------
    def is_descendant(self, ancestor_root: bytes, root: bytes) -> bool:
        a = self.indices.get(ancestor_root)
        i = self.indices.get(root)
        if a is None or i is None:
            return False
        while i is not None and i >= a:
            if i == a:
                return True
            i = self.nodes[i].parent
        return False

    def ancestor_at_slot(self, root: bytes, slot: int) -> Optional[bytes]:
        i = self.indices.get(root)
        while i is not None:
            node = self.nodes[i]
            if node.slot <= slot:
                return node.root
            i = node.parent
        return None
