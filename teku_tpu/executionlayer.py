"""Execution-layer seam: engine-API channel + stub.

Equivalent of the reference's execution layer (reference: ethereum/
executionlayer/src/main/java/tech/pegasys/teku/ethereum/executionlayer/
ExecutionLayerManagerImpl.java over the web3j engine JSON-RPC client,
and ExecutionLayerManagerStub for test/pre-merge operation): the node
is written against ExecutionLayerChannel; phase0/altair never call it,
bellatrix+ block processing will drive new_payload/forkchoice_updated
through it.  The stub accepts everything (the reference stub's
pre-merge behavior); the JSON-RPC client speaks engine API over a raw
asyncio HTTP connection with JWT auth when an endpoint is configured.
"""

import asyncio
import base64
import hashlib
import hmac
import json
import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

_LOG = logging.getLogger(__name__)


@dataclass
class PayloadStatus:
    status: str                      # VALID | INVALID | SYNCING
    latest_valid_hash: Optional[bytes] = None
    validation_error: Optional[str] = None


class ExecutionLayerChannel:
    """The seam bellatrix+ block processing calls through."""

    async def new_payload(self, payload) -> PayloadStatus:
        raise NotImplementedError

    async def forkchoice_updated(self, head_hash: bytes,
                                 safe_hash: bytes,
                                 finalized_hash: bytes,
                                 payload_attributes=None) -> PayloadStatus:
        raise NotImplementedError

    async def get_payload(self, payload_id: bytes):
        raise NotImplementedError


class ExecutionLayerStub(ExecutionLayerChannel):
    """Accept-everything stub (reference ExecutionLayerManagerStub):
    correct for phase0/altair and for pre-merge test chains."""

    def __init__(self):
        self.new_payload_calls = 0
        self.forkchoice_calls = 0

    async def new_payload(self, payload) -> PayloadStatus:
        self.new_payload_calls += 1
        return PayloadStatus(status="VALID")

    async def forkchoice_updated(self, head_hash, safe_hash,
                                 finalized_hash,
                                 payload_attributes=None) -> PayloadStatus:
        self.forkchoice_calls += 1
        return PayloadStatus(status="VALID")

    async def get_payload(self, payload_id):
        raise NotImplementedError("stub cannot build payloads")


def _jwt_token(secret: bytes) -> str:
    """Engine-API JWT (HS256, iat claim) — reference executionclient/
    auth/."""
    def b64(data: bytes) -> str:
        return base64.urlsafe_b64encode(data).rstrip(b"=").decode()
    header = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = b64(json.dumps({"iat": int(time.time())}).encode())
    signing = f"{header}.{payload}".encode()
    sig = b64(hmac.new(secret, signing, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


class EngineJsonRpcClient(ExecutionLayerChannel):
    """Minimal engine JSON-RPC client over raw asyncio HTTP (the
    reference uses web3j; same wire protocol)."""

    def __init__(self, host: str, port: int, jwt_secret: bytes):
        self.host = host
        self.port = port
        self.jwt_secret = jwt_secret
        self._id = 0

    async def _call(self, method: str, params) -> Dict[str, Any]:
        from .infra.jsonrpc import http_json_rpc
        self._id += 1
        token = _jwt_token(self.jwt_secret)
        return await http_json_rpc(
            self.host, self.port, method, params, request_id=self._id,
            headers={"Authorization": f"Bearer {token}"})

    async def new_payload(self, payload) -> PayloadStatus:
        result = await self._call("engine_newPayloadV1", [payload])
        return PayloadStatus(
            status=result.get("status", "INVALID"),
            validation_error=result.get("validationError"))

    async def forkchoice_updated(self, head_hash, safe_hash,
                                 finalized_hash,
                                 payload_attributes=None) -> PayloadStatus:
        state = {"headBlockHash": "0x" + head_hash.hex(),
                 "safeBlockHash": "0x" + safe_hash.hex(),
                 "finalizedBlockHash": "0x" + finalized_hash.hex()}
        result = await self._call("engine_forkchoiceUpdatedV1",
                                  [state, payload_attributes])
        return PayloadStatus(
            status=result.get("payloadStatus", {}).get("status",
                                                       "INVALID"))

    async def get_payload(self, payload_id):
        return await self._call("engine_getPayloadV1",
                                ["0x" + payload_id.hex()])
