"""Schema-driven SSZ <-> standard-API JSON conversion.

One generic walk for every consensus container (string decimals,
0x-hex bytes, hex-encoded SSZ bitfields) — the role of the reference's
SerializableTypeDefinition layer (data/serializer +
ethereum/json-types) without per-type hand coding.  Shared by the REST
API handlers and the Web3Signer request bodies.
"""

from .types import (BitlistType, BitvectorType, ByteListType,
                    ByteVectorType, Container, ListType, UIntType,
                    VectorType, _ContainerSchemaAdapter)


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def ssz_to_json(schema, value):
    """SSZ value -> JSON-able object, driven by its schema."""
    if isinstance(schema, _ContainerSchemaAdapter):
        schema = schema.cls
    if isinstance(schema, type) and issubclass(schema, Container):
        return {name: ssz_to_json(s, getattr(value, name))
                for name, s in schema._ssz_fields.items()}
    if isinstance(schema, UIntType):
        return str(value)
    if isinstance(schema, (ByteVectorType, ByteListType)):
        return _hex(value)
    if isinstance(schema, (BitlistType, BitvectorType)):
        return _hex(schema.serialize(value))
    if isinstance(schema, (ListType, VectorType)):
        return [ssz_to_json(schema.elem, v) for v in value]
    if schema.__class__.__name__ == "BooleanType":
        return bool(value)
    return value


def ssz_from_json(schema, data):
    """Inverse of ssz_to_json; raises ValueError/KeyError/TypeError on
    shape mismatches (REST callers map those to HTTP 400)."""
    if isinstance(schema, _ContainerSchemaAdapter):
        schema = schema.cls
    if isinstance(schema, type) and issubclass(schema, Container):
        if not isinstance(data, dict):
            raise ValueError(f"expected object for {schema.__name__}")
        return schema(**{name: ssz_from_json(s, data[name])
                         for name, s in schema._ssz_fields.items()})
    if isinstance(schema, UIntType):
        return int(data)
    if isinstance(schema, (ByteVectorType, ByteListType)):
        return bytes.fromhex(str(data).removeprefix("0x"))
    if isinstance(schema, (BitlistType, BitvectorType)):
        return schema.deserialize(
            bytes.fromhex(str(data).removeprefix("0x")))
    if isinstance(schema, (ListType, VectorType)):
        return tuple(ssz_from_json(schema.elem, v) for v in data)
    if schema.__class__.__name__ == "BooleanType":
        return bool(data)
    return data
