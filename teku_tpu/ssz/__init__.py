"""SSZ engine: schemas, serialization, hash-tree-root merkleization.

TPU-build equivalent of the reference's SSZ sub-framework (reference:
infrastructure/ssz/ — SszSchema/SszContainer/TreeNode hierarchy).
"""

from .hash import (ZERO_CHUNK, hash_pair, merkle_branch, merkleize,
                   mix_in_length, mix_in_selector, pack_bytes, zero_hash)
from .types import (Bitlist, BitlistType, Bitvector, BitvectorType, boolean,
                    ByteList, ByteListType, Bytes4, Bytes20, Bytes32,
                    Bytes48, Bytes96, ByteVector, ByteVectorType, Container,
                    List, ListType, SszError, SszType, uint8, uint16,
                    uint32, uint64, uint128, uint256, UIntType, Union,
                    UnionType, Vector, VectorType)

__all__ = [
    "ZERO_CHUNK", "hash_pair", "merkle_branch", "merkleize", "mix_in_length",
    "mix_in_selector", "pack_bytes", "zero_hash",
    "Bitlist", "BitlistType", "Bitvector", "BitvectorType", "boolean",
    "ByteList", "ByteListType", "Bytes4", "Bytes20", "Bytes32", "Bytes48",
    "Bytes96", "ByteVector", "ByteVectorType", "Container", "List",
    "ListType", "SszError", "SszType", "uint8", "uint16", "uint32",
    "uint64", "uint128", "uint256", "UIntType", "Union", "UnionType",
    "Vector", "VectorType",
]
