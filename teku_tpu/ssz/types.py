"""SSZ schema system: basic types, vectors, lists, bitfields, containers.

The data substrate for every consensus object — the TPU build's
equivalent of the reference's SSZ sub-framework (reference:
infrastructure/ssz/src/main/java/tech/pegasys/teku/infrastructure/ssz/
schema/SszSchema.java, .../SszContainerSchema.java, view hierarchy in
.../SszContainer.java etc., 18.8k LoC).  Differences are deliberate and
idiomatic-Python:

- schemas are lightweight objects with serialize/deserialize/
  hash_tree_root over PLAIN values (ints, bool, bytes, tuples,
  Container instances) instead of a schema+backing-tree+view triple;
- containers are declared with class annotations and are immutable
  value objects; hash_tree_root is memoized per instance, so unchanged
  subtrees hash once across state copies (the moral equivalent of the
  reference's cached branch nodes);
- deserialization is strict: offset monotonicity, exact consumption,
  bitlist delimiter checks — malformed wire input raises SszError
  (the reference's DeserializeException).
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

from .hash import (ZERO_CHUNK, merkleize, mix_in_length, mix_in_selector,
                   pack_bytes)

OFFSET_SIZE = 4


class SszError(ValueError):
    """Malformed SSZ input (the wire must be rejected, not repaired)."""


# --------------------------------------------------------------------------
# Schema base
# --------------------------------------------------------------------------

class SszType:
    """Base schema: fixed/variable size, ser/de, hash-tree-root."""

    def is_fixed_size(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        """Byte length when fixed-size (raises otherwise)."""
        raise NotImplementedError

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


# --------------------------------------------------------------------------
# Basic types
# --------------------------------------------------------------------------

class UIntType(SszType):
    def __init__(self, bits: int):
        assert bits in (8, 16, 32, 64, 128, 256)
        self.bits = bits
        self.bytes_len = bits // 8

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.bytes_len

    def serialize(self, value) -> bytes:
        value = int(value)
        if not 0 <= value < (1 << self.bits):
            raise SszError(f"uint{self.bits} out of range: {value}")
        return value.to_bytes(self.bytes_len, "little")

    def deserialize(self, data: bytes) -> int:
        if len(data) != self.bytes_len:
            raise SszError(f"uint{self.bits}: want {self.bytes_len} bytes, "
                           f"got {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return self.serialize(value).ljust(32, b"\x00")

    def default(self) -> int:
        return 0

    def __repr__(self):
        return f"uint{self.bits}"


class BooleanType(SszType):
    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return 1

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise SszError(f"invalid boolean byte {data!r}")

    def hash_tree_root(self, value) -> bytes:
        return self.serialize(value).ljust(32, b"\x00")

    def default(self) -> bool:
        return False

    def __repr__(self):
        return "boolean"


uint8 = UIntType(8)
uint16 = UIntType(16)
uint32 = UIntType(32)
uint64 = UIntType(64)
uint128 = UIntType(128)
uint256 = UIntType(256)
boolean = BooleanType()


# --------------------------------------------------------------------------
# Byte vectors / byte lists (bytes-valued fast paths)
# --------------------------------------------------------------------------

class ByteVectorType(SszType):
    def __init__(self, length: int):
        self.length = length

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.length

    def serialize(self, value: bytes) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise SszError(f"ByteVector[{self.length}]: got {len(value)}")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) != self.length:
            raise SszError(f"ByteVector[{self.length}]: got {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)),
                         (self.length + 31) // 32)

    def default(self) -> bytes:
        return b"\x00" * self.length

    def __repr__(self):
        return f"ByteVector[{self.length}]"


class ByteListType(SszType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value: bytes) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise SszError(f"ByteList[{self.limit}]: got {len(value)}")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise SszError(f"ByteList[{self.limit}]: got {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        value = self.serialize(value)
        root = merkleize(pack_bytes(value), (self.limit + 31) // 32)
        return mix_in_length(root, len(value))

    def default(self) -> bytes:
        return b""

    def __repr__(self):
        return f"ByteList[{self.limit}]"


Bytes4 = ByteVectorType(4)
Bytes20 = ByteVectorType(20)
Bytes32 = ByteVectorType(32)
Bytes48 = ByteVectorType(48)
Bytes96 = ByteVectorType(96)


# --------------------------------------------------------------------------
# Homogeneous collections
# --------------------------------------------------------------------------

def _is_basic(t: SszType) -> bool:
    return isinstance(t, (UIntType, BooleanType))


def _pack_basic(elem: SszType, values: Sequence) -> List[bytes]:
    return pack_bytes(b"".join(elem.serialize(v) for v in values))


class VectorType(SszType):
    def __init__(self, elem: SszType, length: int):
        assert length > 0
        self.elem = elem
        self.length = length

    def is_fixed_size(self):
        return self.elem.is_fixed_size()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def serialize(self, value) -> bytes:
        value = tuple(value)
        if len(value) != self.length:
            raise SszError(f"Vector[{self.length}]: got {len(value)}")
        if self.elem.is_fixed_size():
            return b"".join(self.elem.serialize(v) for v in value)
        parts = [self.elem.serialize(v) for v in value]
        return _serialize_offsets(parts)

    def deserialize(self, data: bytes):
        if self.elem.is_fixed_size():
            es = self.elem.fixed_size()
            if len(data) != es * self.length:
                raise SszError("vector size mismatch")
            return tuple(self.elem.deserialize(data[i * es:(i + 1) * es])
                         for i in range(self.length))
        parts = _deserialize_offsets(data)
        if len(parts) != self.length:
            raise SszError("vector element count mismatch")
        return tuple(self.elem.deserialize(p) for p in parts)

    def hash_tree_root(self, value) -> bytes:
        value = tuple(value)
        if len(value) != self.length:
            raise SszError(f"Vector[{self.length}]: got {len(value)}")
        if _is_basic(self.elem):
            chunks = _pack_basic(self.elem, value)
            limit = (self.length * self.elem.fixed_size() + 31) // 32
            return merkleize(chunks, limit)
        return merkleize([self.elem.hash_tree_root(v) for v in value],
                         self.length)

    def default(self):
        return tuple(self.elem.default() for _ in range(self.length))

    def __repr__(self):
        return f"Vector[{self.elem!r}, {self.length}]"


class ListType(SszType):
    def __init__(self, elem: SszType, limit: int):
        self.elem = elem
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        value = tuple(value)
        if len(value) > self.limit:
            raise SszError(f"List[{self.limit}]: got {len(value)}")
        if self.elem.is_fixed_size():
            return b"".join(self.elem.serialize(v) for v in value)
        return _serialize_offsets([self.elem.serialize(v) for v in value])

    def deserialize(self, data: bytes):
        if self.elem.is_fixed_size():
            es = self.elem.fixed_size()
            if len(data) % es:
                raise SszError("list size not a multiple of element size")
            n = len(data) // es
            if n > self.limit:
                raise SszError("list over limit")
            return tuple(self.elem.deserialize(data[i * es:(i + 1) * es])
                         for i in range(n))
        if not data:
            return ()
        parts = _deserialize_offsets(data)
        if len(parts) > self.limit:
            raise SszError("list over limit")
        return tuple(self.elem.deserialize(p) for p in parts)

    def hash_tree_root(self, value) -> bytes:
        value = tuple(value)
        if len(value) > self.limit:
            raise SszError(f"List[{self.limit}]: got {len(value)}")
        if _is_basic(self.elem):
            chunks = _pack_basic(self.elem, value)
            limit = (self.limit * self.elem.fixed_size() + 31) // 32
            root = merkleize(chunks, limit)
        else:
            root = merkleize([self.elem.hash_tree_root(v) for v in value],
                             self.limit)
        return mix_in_length(root, len(value))

    def default(self):
        return ()

    def __repr__(self):
        return f"List[{self.elem!r}, {self.limit}]"


class BitvectorType(SszType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def serialize(self, value) -> bytes:
        bits = tuple(bool(b) for b in value)
        if len(bits) != self.length:
            raise SszError(f"Bitvector[{self.length}]: got {len(bits)}")
        out = bytearray((self.length + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def deserialize(self, data: bytes):
        if len(data) != self.fixed_size():
            raise SszError("bitvector size mismatch")
        if self.length % 8:
            if data[-1] >> (self.length % 8):
                raise SszError("bitvector padding bits set")
        return tuple(bool(data[i // 8] >> (i % 8) & 1)
                     for i in range(self.length))

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)),
                         (self.length + 255) // 256)

    def default(self):
        return tuple(False for _ in range(self.length))

    def __repr__(self):
        return f"Bitvector[{self.length}]"


class BitlistType(SszType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        bits = tuple(bool(b) for b in value)
        if len(bits) > self.limit:
            raise SszError(f"Bitlist[{self.limit}]: got {len(bits)}")
        n = len(bits)
        out = bytearray(n // 8 + 1)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        out[n // 8] |= 1 << (n % 8)          # delimiter bit
        return bytes(out)

    def deserialize(self, data: bytes):
        if not data:
            raise SszError("empty bitlist encoding")
        if data[-1] == 0:
            raise SszError("bitlist missing delimiter bit")
        top = data[-1].bit_length() - 1
        n = (len(data) - 1) * 8 + top
        if n > self.limit:
            raise SszError("bitlist over limit")
        return tuple(bool(data[i // 8] >> (i % 8) & 1) for i in range(n))

    def hash_tree_root(self, value) -> bytes:
        bits = tuple(bool(b) for b in value)
        if len(bits) > self.limit:
            raise SszError(f"Bitlist[{self.limit}]: got {len(bits)}")
        n = len(bits)
        out = bytearray((n + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        root = merkleize(pack_bytes(bytes(out)), (self.limit + 255) // 256)
        return mix_in_length(root, n)

    def default(self):
        return ()

    def __repr__(self):
        return f"Bitlist[{self.limit}]"


class UnionType(SszType):
    """SSZ Union[...]; values are (selector, value) pairs."""

    def __init__(self, options: Sequence[Optional[SszType]]):
        assert 1 <= len(options) <= 128
        if options[0] is None:
            assert len(options) > 1
        self.options = tuple(options)

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        sel, v = value
        opt = self.options[sel]
        if opt is None:
            if v is not None:
                raise SszError("None option carries no value")
            return bytes([sel])
        return bytes([sel]) + opt.serialize(v)

    def deserialize(self, data: bytes):
        if not data:
            raise SszError("empty union")
        sel = data[0]
        if sel >= len(self.options):
            raise SszError("union selector out of range")
        opt = self.options[sel]
        if opt is None:
            if len(data) != 1:
                raise SszError("trailing bytes after None option")
            return (0, None)
        return (sel, opt.deserialize(data[1:]))

    def hash_tree_root(self, value) -> bytes:
        sel, v = value
        opt = self.options[sel]
        root = ZERO_CHUNK if opt is None else opt.hash_tree_root(v)
        return mix_in_selector(root, sel)

    def default(self):
        opt = self.options[0]
        return (0, None if opt is None else opt.default())


# --------------------------------------------------------------------------
# Offset machinery (variable-size element framing)
# --------------------------------------------------------------------------

def _serialize_offsets(parts: List[bytes]) -> bytes:
    head = len(parts) * OFFSET_SIZE
    offsets = []
    pos = head
    for p in parts:
        offsets.append(pos.to_bytes(OFFSET_SIZE, "little"))
        pos += len(p)
    return b"".join(offsets) + b"".join(parts)


def _deserialize_offsets(data: bytes) -> List[bytes]:
    if len(data) < OFFSET_SIZE:
        raise SszError("truncated offset table")
    first = int.from_bytes(data[:OFFSET_SIZE], "little")
    if first % OFFSET_SIZE or first == 0:
        raise SszError("misaligned first offset")
    n = first // OFFSET_SIZE
    if first > len(data):
        raise SszError("first offset beyond input")
    offsets = [int.from_bytes(data[i * OFFSET_SIZE:(i + 1) * OFFSET_SIZE],
                              "little") for i in range(n)]
    offsets.append(len(data))
    parts = []
    for i in range(n):
        if offsets[i] > offsets[i + 1]:
            raise SszError("offsets not monotonic")
        parts.append(data[offsets[i]:offsets[i + 1]])
    return parts


# --------------------------------------------------------------------------
# Containers
# --------------------------------------------------------------------------

class _ContainerMeta(type):
    def __new__(mcs, name, bases, ns):
        cls = super().__new__(mcs, name, bases, ns)
        fields: Dict[str, SszType] = {}
        for base in reversed(cls.__mro__[1:]):
            fields.update(getattr(base, "_ssz_fields", {}))
        for fname, schema in ns.get("__annotations__", {}).items():
            if isinstance(schema, SszType) or (
                    isinstance(schema, type)
                    and issubclass(schema, Container)):
                fields[fname] = schema
        cls._ssz_fields = fields
        return cls


class Container(SszType, metaclass=_ContainerMeta):
    """Declarative SSZ container; the class doubles as its own schema.

    Instances are immutable value objects; `copy_with(**changes)` shares
    unchanged children, and hash_tree_root is memoized per instance so
    state copies re-hash only changed subtrees (the reference caches
    branch hashes in its backing tree for the same reason).
    """

    _ssz_fields: Dict[str, SszType] = {}
    __hash_cache: Optional[bytes]

    def __init__(self, **kwargs):
        cls = type(self)
        for fname, schema in cls._ssz_fields.items():
            if fname in kwargs:
                v = kwargs.pop(fname)
            else:
                v = _schema(schema).default()
            object.__setattr__(self, fname, v)
        if kwargs:
            raise TypeError(f"unknown fields {sorted(kwargs)} for {cls.__name__}")
        object.__setattr__(self, "_Container__hash_cache", None)

    def __setattr__(self, key, value):
        raise AttributeError(
            f"{type(self).__name__} is immutable; use copy_with()")

    def copy_with(self, **changes):
        cls = type(self)
        vals = {f: getattr(self, f) for f in cls._ssz_fields}
        for k, v in changes.items():
            if k not in vals:
                raise TypeError(f"unknown field {k} for {cls.__name__}")
            vals[k] = v
        return cls(**vals)

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        return all(getattr(self, f) == getattr(other, f)
                   for f in self._ssz_fields)

    def __hash__(self):
        return hash(self.hash_tree_root())

    def __repr__(self):
        inner = ", ".join(f"{f}={getattr(self, f)!r}"
                          for f in self._ssz_fields)
        return f"{type(self).__name__}({inner})"

    # ---- schema API (classmethods so the class IS the schema) ----
    @classmethod
    def is_fixed_size(cls) -> bool:
        return all(_schema(s).is_fixed_size()
                   for s in cls._ssz_fields.values())

    @classmethod
    def fixed_size(cls) -> int:
        assert cls.is_fixed_size()
        return sum(_schema(s).fixed_size() for s in cls._ssz_fields.values())

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def serialize(cls, value: "Container") -> bytes:
        fixed_parts: List[Optional[bytes]] = []
        var_parts: List[bytes] = []
        for fname, schema in cls._ssz_fields.items():
            s = _schema(schema)
            v = getattr(value, fname)
            if s.is_fixed_size():
                fixed_parts.append(s.serialize(v))
            else:
                fixed_parts.append(None)
                var_parts.append(s.serialize(v))
        head_len = sum(OFFSET_SIZE if p is None else len(p)
                       for p in fixed_parts)
        out = []
        pos = head_len
        vi = 0
        for p in fixed_parts:
            if p is None:
                out.append(pos.to_bytes(OFFSET_SIZE, "little"))
                pos += len(var_parts[vi])
                vi += 1
            else:
                out.append(p)
        return b"".join(out) + b"".join(var_parts)

    @classmethod
    def deserialize(cls, data: bytes) -> "Container":
        schemas = [(f, _schema(s)) for f, s in cls._ssz_fields.items()]
        pos = 0
        offsets: List[Tuple[str, SszType, int]] = []
        values: Dict[str, Any] = {}
        order: List[str] = []
        for fname, s in schemas:
            order.append(fname)
            if s.is_fixed_size():
                size = s.fixed_size()
                if pos + size > len(data):
                    raise SszError("truncated fixed part")
                values[fname] = s.deserialize(data[pos:pos + size])
                pos += size
            else:
                if pos + OFFSET_SIZE > len(data):
                    raise SszError("truncated offset")
                off = int.from_bytes(data[pos:pos + OFFSET_SIZE], "little")
                offsets.append((fname, s, off))
                pos += OFFSET_SIZE
        if offsets:
            if offsets[0][2] != pos:
                raise SszError("first offset does not follow fixed part")
            bounds = [off for (_, _, off) in offsets] + [len(data)]
            for i, (fname, s, off) in enumerate(offsets):
                end = bounds[i + 1]
                if off > end or end > len(data):
                    raise SszError("offsets not monotonic")
                values[fname] = s.deserialize(data[off:end])
        elif pos != len(data):
            raise SszError("trailing bytes after fixed container")
        return cls(**values)

    @classmethod
    def hash_tree_root(cls, value: "Container" = None) -> bytes:
        # usable both as schema.hash_tree_root(value) and value.hash_tree_root()
        if value is None:
            raise TypeError("hash_tree_root needs a value")
        cached = value.__dict__.get("_Container__hash_cache")
        if cached is not None:
            return cached
        leaves = [
            _schema(s).hash_tree_root(getattr(value, f))
            for f, s in cls._ssz_fields.items()
        ]
        root = merkleize(leaves, len(leaves))
        object.__setattr__(value, "_Container__hash_cache", root)
        return root

    # instance-call sugar
    def ssz_serialize(self) -> bytes:
        return type(self).serialize(self)

    @classmethod
    def ssz_deserialize(cls, data: bytes) -> "Container":
        return cls.deserialize(data)

    def htr(self) -> bytes:
        return type(self).hash_tree_root(self)


def _schema(s) -> SszType:
    """Accept both SszType instances and Container classes as schemas."""
    if isinstance(s, type) and issubclass(s, Container):
        return _ContainerSchemaAdapter(s)
    return s


class _ContainerSchemaAdapter(SszType):
    """Adapter so a Container CLASS can sit in schema positions."""

    def __init__(self, cls: Type[Container]):
        self.cls = cls

    def is_fixed_size(self):
        return self.cls.is_fixed_size()

    def fixed_size(self):
        return self.cls.fixed_size()

    def serialize(self, value):
        return self.cls.serialize(value)

    def deserialize(self, data):
        return self.cls.deserialize(data)

    def hash_tree_root(self, value):
        return self.cls.hash_tree_root(value)

    def default(self):
        return self.cls()

    def __repr__(self):
        return self.cls.__name__


def Vector(elem, length: int) -> VectorType:
    return VectorType(_schema(elem), length)


def List(elem, limit: int) -> ListType:  # noqa: A001 - SSZ naming
    return ListType(_schema(elem), limit)


def Bitvector(length: int) -> BitvectorType:
    return BitvectorType(length)


def Bitlist(limit: int) -> BitlistType:
    return BitlistType(limit)


def ByteVector(length: int) -> ByteVectorType:
    return ByteVectorType(length)


def ByteList(limit: int) -> ByteListType:
    return ByteListType(limit)


def Union(*options) -> UnionType:
    return UnionType([None if o is None else _schema(o) for o in options])
