"""SSZ merkleization: SHA-256 binary merkle trees with virtual zero
subtrees.

The TPU-native analogue of the reference's tree hashing
(reference: infrastructure/ssz/src/main/java/tech/pegasys/teku/
infrastructure/ssz/tree/TreeUtil.java and .../tree/BranchNode.java —
there an incremental persistent tree; here level-by-level hashing with
memoized per-view roots at the schema layer, plus an optional native
C++ level hasher for bulk re-hashes).
"""

import hashlib
from functools import lru_cache
from typing import List, Optional, Sequence

ZERO_CHUNK = b"\x00" * 32

try:  # optional C++ bulk pair-hasher (teku_tpu/native)
    from ..native import hashtree as _native
except Exception:  # pragma: no cover - native build unavailable
    _native = None


@lru_cache(maxsize=64)
def zero_hash(depth: int) -> bytes:
    """Root of an all-zero subtree of the given depth."""
    if depth == 0:
        return ZERO_CHUNK
    h = zero_hash(depth - 1)
    return hashlib.sha256(h + h).digest()


def hash_pair(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _hash_level(level: List[bytes], pad: bytes) -> List[bytes]:
    if len(level) % 2:
        level = level + [pad]
    if _native is not None and len(level) >= 8:
        return _native.hash_pairs(level)
    out = []
    for i in range(0, len(level), 2):
        out.append(hashlib.sha256(level[i] + level[i + 1]).digest())
    return out


def merkleize(chunks: Sequence[bytes], limit: Optional[int] = None) -> bytes:
    """Merkle root of 32-byte chunks, virtually padded to `limit` leaves
    (or to the next power of two when limit is None).

    Mirrors the consensus-spec `merkleize(chunks, limit)`; the all-zero
    right-hand subtrees are folded in via precomputed zero hashes rather
    than materialized.
    """
    count = len(chunks)
    size = max(count, 1) if limit is None else limit
    depth = (size - 1).bit_length() if size > 1 else 0
    if limit is not None and count > limit:
        raise ValueError(f"{count} chunks exceed limit {limit}")
    if count == 0:
        return zero_hash(depth)
    level = list(chunks)
    for d in range(depth):
        level = _hash_level(level, zero_hash(d))
    return level[0]


def merkle_branch(chunks: Sequence[bytes], index: int,
                  limit: Optional[int] = None) -> List[bytes]:
    """Sibling path (bottom-up) proving chunks[index] against
    merkleize(chunks, limit) — the proof-generation dual of merkleize,
    with the same virtual zero-padding (reference: the backing-tree
    branch collection infrastructure/ssz uses for light-client and
    blob-sidecar inclusion proofs)."""
    count = len(chunks)
    size = max(count, 1) if limit is None else limit
    depth = (size - 1).bit_length() if size > 1 else 0
    if limit is not None and count > limit:
        raise ValueError(f"{count} chunks exceed limit {limit}")
    if index >= max(count, 1):
        raise ValueError(f"index {index} out of range for {count} chunks")
    branch = []
    level = list(chunks) if chunks else [ZERO_CHUNK]
    for d in range(depth):
        sib = index ^ 1
        branch.append(level[sib] if sib < len(level) else zero_hash(d))
        level = _hash_level(level, zero_hash(d))
        index >>= 1
    return branch


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_pair(root, length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_pair(root, selector.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> List[bytes]:
    """Right-pad serialized bytes into 32-byte chunks."""
    if not data:
        return []
    if len(data) % 32:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [data[i:i + 32] for i in range(0, len(data), 32)]
