"""The unified causal timeline: every observability surface on one
clock spine, joined by trace id.

The tree records a verification's life four ways — tracing spans
(per-stage durations), flight-recorder events (incident instants),
dispatch-ledger records (per-dispatch cost attribution), and the
capacity model's occupancy intervals — and before this module none of
them could be ORDERED against each other (four timestamp dialects,
see infra/clock.py).  The timeline stitches them:

- a bounded streaming ring of ``interval``/``instant`` events stamped
  with the shared ``(t_wall, t_mono)`` pair — the provider publishes
  device-busy and host_prep intervals, the signature service publishes
  queue-nonempty intervals and coalesce instants, admission publishes
  brownout transitions, the mesh healer publishes reshape/eject marks;
- ``span_tree(trace)`` — the gap-free causal tree for ONE trace id:
  stage spans (now carrying start offsets) nest by containment and
  every hole between siblings becomes an explicit ``unattributed``
  child, so children always tile their parent and unexplained time is
  first-class rather than invisible;
- ``join(trace_id, ...)`` — the three-way join (trace ring + dispatch
  ledger + flight recorder + timeline ring) behind
  ``GET /teku/v1/admin/timeline?trace_id=``;
- ``perfetto(...)`` — Chrome trace-event export (``cli timeline
  --out``): one track per worker/device/admission/flight/mesh, ``X``
  slices for spans, ``i`` instants for flight events, ``b``/``e``
  async arrows for coalesced waiters and enqueue→sync overlap;
- ``attribution(...)`` — the derived bench metrics the roadmap's two
  open items gate on: ``overlap_efficiency`` (device-busy ÷ wall time
  while the queue is nonempty), ``host_prep_serial_share``,
  ``queue_wait_share``, ``compile_wall_share``.

Track and phase vocabularies are CLOSED (``TRACKS`` / ``PHASES``,
enforced both directions by tekulint's closed-registry checker, the
EVENT_KINDS contract).  ``TEKU_TPU_TIMELINE=0`` restores the
instrumentation-free path (emit calls return before touching the
ring); a garbage knob degrades to the default with one WARN, never a
boot failure.  The ring is self-measuring: ``measure_overhead()``
reports the per-event stamp cost bench uses to bound the timeline's
share of the latency phase.
"""

import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import clock, schema
from .env import env_bool, env_int
from .metrics import GLOBAL_REGISTRY

# The closed track vocabulary: one Perfetto track per member, one
# `tid` each.  Adding a track means adding it HERE (the closed-registry
# checker flags undeclared and dynamic track names tree-wide).
TRACKS = frozenset({"worker", "device", "admission", "flight", "mesh"})

# The closed phase vocabulary: every ring event's name.  Same
# both-directions contract — an emitter with a typo'd phase and a
# declared-but-never-emitted phase are both findings.
PHASES = frozenset({
    "busy",             # device executing a dispatch (enqueue→sync)
    "queue_nonempty",   # service queue held work (overlap denominator)
    "host_prep",        # host-side limb packing inside a dispatch
    "compile",          # XLA backend compile (compilecache listener)
    "coalesce",         # duplicate submission joined an in-flight task
    "brownout_enter",   # admission brownout level raised
    "brownout_exit",    # admission brownout cleared
    "brownout_deescalate",  # admission brownout level lowered
    "reshape",          # mesh healer installed a new topology
    "eject",            # mesh healer ejected a device
    "unattributed",     # synthesized span-tree filler (never emitted)
})

_enabled = env_bool("TEKU_TPU_TIMELINE", True)

_M_EVENTS = GLOBAL_REGISTRY.labeled_counter(
    "timeline_events_total",
    "events recorded into the causal-timeline ring, by track",
    labelnames=("track",))


def set_enabled(on: bool) -> None:
    """Test/CLI seam mirroring tracing.set_enabled."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class TimelineRing:
    """Bounded ring of timeline events (newest win), thread-safe."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = env_int("TEKU_TPU_TIMELINE_RING", 4096, lo=1)
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()

    def append(self, event: dict) -> dict:
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
        return event

    def mark(self) -> int:
        """Current seq — bench phases bracket a window with two marks
        and snapshot(since_seq=...) the delta."""
        with self._lock:
            return self._seq

    def snapshot(self, last: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 since_seq: Optional[int] = None) -> List[dict]:
        with self._lock:
            events = list(self._events)
        if since_seq is not None:
            events = [e for e in events if e["seq"] > since_seq]
        if trace_id:
            events = [e for e in events
                      if e.get("trace_id") == trace_id]
        if last is not None:
            events = events[-max(1, last):]
        return [dict(e) for e in events]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


# the process-wide ring every instrumented module records into
RING = TimelineRing()


def _stamp_event(track: str, phase: str, dur_s: float,
                 t_mono: Optional[float], trace_id: str,
                 fields: dict) -> dict:
    t_wall_end, t_mono_end = clock.now()
    start = t_mono_end - dur_s if t_mono is None else t_mono
    ev = {"seq": 0, "track": track, "phase": phase,
          "t_wall": round(t_wall_end - (t_mono_end - start), 6),
          "t_mono": round(start, 6),
          "dur_s": round(dur_s, 6),
          "trace_id": trace_id or ""}
    if fields:
        ev.update(fields)
    return ev


def interval(track: str, phase: str, dur_s: float,
             t_mono: Optional[float] = None, trace_id: str = "",
             **fields) -> Optional[dict]:
    """Record a completed interval.  ``t_mono`` is the start stamp on
    the spine's monotonic base; when omitted the interval is assumed
    to end NOW (the emit-at-completion idiom, which also lets
    ``time.monotonic()`` callers pass a duration without mixing clock
    bases).  Returns None (and does no work) when disabled."""
    if not _enabled:
        return None
    ev = _stamp_event(track, phase, dur_s, t_mono, trace_id, fields)
    RING.append(ev)
    _M_EVENTS.labels(track=track).inc()
    return ev


def instant(track: str, phase: str, trace_id: str = "",
            **fields) -> Optional[dict]:
    """Record a zero-duration mark (state transitions, coalesce
    joins).  Disabled mode returns immediately."""
    if not _enabled:
        return None
    ev = _stamp_event(track, phase, 0.0, None, trace_id, fields)
    RING.append(ev)
    _M_EVENTS.labels(track=track).inc()
    return ev


def measure_overhead(n: int = 2000) -> dict:
    """Self-measurement: the per-event cost of the full stamp path
    (clock pair + dict build + ring append) against a SCRATCH ring, so
    bench can report the timeline's share of a phase without polluting
    the live ring."""
    ring = TimelineRing(capacity=min(n, 4096))
    t0 = clock.mono()
    for _ in range(n):
        ring.append(_stamp_event("worker", "host_prep", 0.0, None,
                                 "", {}))
    total = clock.mono() - t0
    return {"events": n, "total_s": round(total, 6),
            "per_event_us": round(total / n * 1e6, 3)}


# --------------------------------------------------------------------------
# Interval arithmetic (pure; all on the t_mono axis)
# --------------------------------------------------------------------------

def _merge(intervals: Iterable[Tuple[float, float]]
           ) -> List[Tuple[float, float]]:
    """Sorted disjoint union of (t0, t1) intervals."""
    out: List[Tuple[float, float]] = []
    for t0, t1 in sorted(i for i in intervals if i[1] > i[0]):
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _total(intervals: Sequence[Tuple[float, float]]) -> float:
    return sum(t1 - t0 for t0, t1 in intervals)


def _intersect(a: Sequence[Tuple[float, float]],
               b: Sequence[Tuple[float, float]]
               ) -> List[Tuple[float, float]]:
    out = []
    for a0, a1 in a:
        for b0, b1 in b:
            lo, hi = max(a0, b0), min(a1, b1)
            if hi > lo:
                out.append((lo, hi))
    return _merge(out)


def _subtract(a: Sequence[Tuple[float, float]],
              b: Sequence[Tuple[float, float]]
              ) -> List[Tuple[float, float]]:
    """Parts of `a` not covered by `b` (both disjoint-sorted)."""
    out = []
    for a0, a1 in a:
        cur = a0
        for b0, b1 in b:
            if b1 <= cur or b0 >= a1:
                continue
            if b0 > cur:
                out.append((cur, b0))
            cur = max(cur, b1)
            if cur >= a1:
                break
        if cur < a1:
            out.append((cur, a1))
    return out


def _clip(intervals: Sequence[Tuple[float, float]], t0: float,
          t1: float) -> List[Tuple[float, float]]:
    return [(max(a, t0), min(b, t1)) for a, b in intervals
            if min(b, t1) > max(a, t0)]


def _phase_intervals(events: Sequence[dict], phase: str
                     ) -> List[Tuple[float, float]]:
    return _merge((e["t_mono"], e["t_mono"] + e.get("dur_s", 0.0))
                  for e in events if e.get("phase") == phase
                  and e.get("dur_s", 0.0) > 0)


def attribution(events: Sequence[dict], t_mono0: float,
                t_mono1: float,
                stage_sums: Optional[Dict[str, float]] = None,
                compile_s: Optional[float] = None) -> dict:
    """The derived attribution metrics over a [t_mono0, t_mono1)
    window of ring events.  Metrics whose inputs are absent come back
    None (skip-if-missing, the bench_diff gate contract):

    - ``overlap_efficiency``: device-busy time ÷ wall time while the
      queue was nonempty — 1.0 means the device never starved while
      work waited (the async-overlap win), low means host-side serial
      work is the bottleneck;
    - ``host_prep_serial_share``: host_prep time NOT overlapped by
      device-busy, as a share of the window (the zero-copy-ingest
      target);
    - ``queue_wait_share``: queue_wait ÷ complete from the caller's
      stage sums (bench's raw trace samples);
    - ``compile_wall_share``: in-window union of first-class
      ``compile`` ring spans ÷ window.  Clipped interval math — the
      union cannot exceed the window, so the value is a TRUE share
      (the old ledger-seconds ÷ window ratio clamped at a misleading
      1.0 whenever worker-thread compile seconds exceeded the wall
      window; PERF.md documents the regression).  ``compile_s``
      (ledger-attributed seconds) remains the fallback numerator for
      rings too small to still hold the compile spans, and is always
      reported raw as ``compile_attr_s``.
    """
    window_s = max(t_mono1 - t_mono0, 0.0)
    in_window = [e for e in events
                 if e["t_mono"] + e.get("dur_s", 0.0) > t_mono0
                 and e["t_mono"] < t_mono1]
    busy = _clip(_phase_intervals(in_window, "busy"),
                 t_mono0, t_mono1)
    nonempty = _clip(_phase_intervals(in_window, "queue_nonempty"),
                     t_mono0, t_mono1)
    host_prep = _clip(_phase_intervals(in_window, "host_prep"),
                      t_mono0, t_mono1)
    busy_s = _total(busy)
    nonempty_s = _total(nonempty)
    serial_s = _total(_subtract(host_prep, busy))
    out = {
        "window_s": round(window_s, 6),
        "events": len(in_window),
        "device_busy_s": round(busy_s, 6),
        "queue_nonempty_s": round(nonempty_s, 6),
        "host_prep_s": round(_total(host_prep), 6),
        "host_prep_serial_s": round(serial_s, 6),
        "overlap_efficiency": (
            round(min(_total(_intersect(busy, nonempty))
                      / nonempty_s, 1.0), 4)
            if nonempty_s > 0 else None),
        "host_prep_serial_share": (
            round(min(serial_s / window_s, 1.0), 4)
            if window_s > 0 and host_prep else None),
        "queue_wait_share": None,
        "compile_wall_share": None,
    }
    if stage_sums:
        qw = stage_sums.get("queue_wait", 0.0)
        total = stage_sums.get("complete", 0.0)
        if total > 0:
            out["queue_wait_share"] = round(min(qw / total, 1.0), 4)
    compile_iv = _clip(_phase_intervals(in_window, "compile"),
                      t_mono0, t_mono1)
    out["compile_spans_s"] = round(_total(compile_iv), 6)
    out["compile_attr_s"] = (round(max(compile_s, 0.0), 6)
                             if compile_s is not None else None)
    if window_s > 0:
        if compile_iv:
            # interval union, clipped to the window: a true share by
            # construction — no clamp needed or applied
            out["compile_wall_share"] = round(
                _total(compile_iv) / window_s, 4)
        elif compile_s is not None:
            out["compile_wall_share"] = round(
                min(max(compile_s, 0.0) / window_s, 1.0), 4)
    return out


def stalls(events: Sequence[dict]) -> List[Tuple[float, float]]:
    """Gap intervals where the queue was nonempty but the device was
    idle — the overlap_stall doctor finding's evidence."""
    nonempty = _phase_intervals(events, "queue_nonempty")
    busy = _phase_intervals(events, "busy")
    return _subtract(nonempty, busy)


# --------------------------------------------------------------------------
# Span trees
# --------------------------------------------------------------------------

# gaps below the clock spine's resolution are tiling, not holes
RESOLUTION_S = 1e-4


def _node(phase: str, t0: float, t1: float) -> dict:
    return {"phase": phase, "t_mono": round(t0, 6),
            "t_wall": round(clock.wall_of(t0), 6),
            "dur_ms": round((t1 - t0) * 1e3, 3), "children": []}


def _fill_gaps(node: dict, t0: float, t1: float) -> None:
    """Insert explicit `unattributed` children so the node's children
    tile [t0, t1] exactly — unexplained time becomes visible instead
    of being a hole in the tree."""
    children = node["children"]
    if not children:
        return
    tiled: List[dict] = []
    cursor = t0
    for child in children:
        c0 = child["t_mono"]
        c1 = c0 + child["dur_ms"] / 1e3
        if c0 - cursor > RESOLUTION_S:
            tiled.append(_node("unattributed", cursor, c0))
        else:
            # snap the child to the cursor: sub-resolution seams must
            # tile EXACTLY so the gap-free assertion is an equality
            child["t_mono"] = round(cursor, 6)
            child["dur_ms"] = round((c1 - cursor) * 1e3, 3)
        tiled.append(child)
        cursor = max(cursor, c1)
    if t1 - cursor > RESOLUTION_S:
        tiled.append(_node("unattributed", cursor, t1))
    elif tiled:
        last = tiled[-1]
        last["dur_ms"] = round((t1 - last["t_mono"]) * 1e3, 3)
    node["children"] = tiled


def span_tree(trace: dict) -> dict:
    """The gap-free causal tree for one trace dict (the extended
    ``Trace.to_dict()`` form carrying ``t_mono`` and per-stage
    ``stages[].t_mono`` start offsets).  Stage spans nest by interval
    containment; gaps become ``unattributed`` nodes, so at every level
    the children tile the parent within ``RESOLUTION_S``."""
    t0 = float(trace.get("t_mono", 0.0))
    t1 = t0 + float(trace.get("total_ms", 0.0)) / 1e3
    root = _node(trace.get("name", "trace"), t0, t1)
    root["phase"] = trace.get("name", "trace")
    root["trace_id"] = trace.get("trace_id", "")
    root["labels"] = dict(trace.get("labels") or {})
    spans = []
    for st in trace.get("stages", []):
        if "t_mono" not in st:
            continue
        s0 = max(t0, float(st["t_mono"]))
        s1 = min(t1, s0 + float(st.get("ms", 0.0)) / 1e3)
        if s1 > s0:
            spans.append((s0, -(s1 - s0), st["stage"], s1))
    # sort by start, longest-first at equal starts → parents precede
    # the children they contain
    stack = [root]
    for s0, _neg, stage, s1 in sorted(spans):
        while len(stack) > 1:
            top = stack[-1]
            top_end = top["t_mono"] + top["dur_ms"] / 1e3
            if s0 >= top_end - RESOLUTION_S:
                stack.pop()
            else:
                break
        node = _node(stage, s0, s1)
        stack[-1]["children"].append(node)
        stack.append(node)

    def fill(node: dict) -> None:
        n0 = node["t_mono"]
        _fill_gaps(node, n0, n0 + node["dur_ms"] / 1e3)
        for child in node["children"]:
            if child["phase"] != "unattributed":
                fill(child)

    fill(root)
    return root


def join(trace_id: str,
         traces: Optional[Sequence[dict]] = None,
         records: Optional[Sequence[dict]] = None,
         flight_events: Optional[Sequence[dict]] = None,
         ring_events: Optional[Sequence[dict]] = None) -> dict:
    """The three-way join for ONE trace id: its span tree from the
    trace ring, its dispatch-ledger records, its flight-recorder
    events, and its timeline-ring events — the admin endpoint's
    response body (schema v1, versioned by infra/schema.py)."""
    trace = next((t for t in (traces or [])
                  if t.get("trace_id") == trace_id), None)
    recs = [r for r in (records or [])
            if trace_id in (r.get("trace_ids") or [])]
    flight = [e for e in (flight_events or [])
              if e.get("trace_id") == trace_id]
    ring = [e for e in (ring_events or [])
            if e.get("trace_id") == trace_id]
    return schema.envelope("timeline", {
        "anchor": clock.anchor_dict(),
        "trace_id": trace_id,
        "tree": span_tree(trace) if trace is not None else None,
        "records": [dict(r) for r in recs],
        "flight": [dict(e) for e in flight],
        "ring": ring,
    })


# --------------------------------------------------------------------------
# Perfetto / Chrome trace-event export
# --------------------------------------------------------------------------

def _track_tid(track: str) -> int:
    order = sorted(TRACKS)
    return order.index(track) + 1 if track in order else len(order) + 1


def _phase_track(phase: str) -> str:
    if phase in ("device_enqueue", "device_sync", "busy"):
        return "device"
    if phase.startswith("brownout"):
        return "admission"
    if phase in ("reshape", "eject"):
        return "mesh"
    return "worker"


def perfetto(traces: Optional[Sequence[dict]] = None,
             records: Optional[Sequence[dict]] = None,
             flight_events: Optional[Sequence[dict]] = None,
             ring_events: Optional[Sequence[dict]] = None
             ) -> List[dict]:
    """Chrome trace-event list (``chrome://tracing`` / Perfetto's
    legacy JSON importer): thread-name metadata declares one track per
    TRACKS member; trace stages become ``X`` complete slices on the
    worker/device tracks; ledger records become admission-track slices
    (plan mode + compile outcome); flight events become ``i``
    instants; coalesce marks and device-busy intervals become
    ``b``/``e`` async pairs (the arrows for coalesced waiters and
    enqueue→sync overlap).  Timestamps are µs on the wall axis,
    rebased to the earliest event."""
    pid = 1
    events: List[dict] = []
    for track in sorted(TRACKS):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": _track_tid(track), "ts": 0,
                       "cat": "__metadata",
                       "args": {"name": track}})
    events.append({"ph": "M", "name": "process_name", "pid": pid,
                   "tid": 0, "ts": 0, "cat": "__metadata",
                   "args": {"name": "teku-tpu"}})

    def us(t_wall: float) -> float:
        return t_wall * 1e6

    body: List[dict] = []
    for tr in traces or []:
        tree = span_tree(tr)
        tid_root = _track_tid("worker")
        body.append({"ph": "X", "name": tree["phase"],
                     "cat": "trace", "pid": pid, "tid": tid_root,
                     "ts": us(tree["t_wall"]),
                     "dur": tree["dur_ms"] * 1e3,
                     "args": {"trace_id": tree.get("trace_id", "")}})

        def walk(node: dict, trace_id: str) -> None:
            for child in node["children"]:
                body.append({
                    "ph": "X", "name": child["phase"],
                    "cat": "stage", "pid": pid,
                    "tid": _track_tid(_phase_track(child["phase"])),
                    "ts": us(child["t_wall"]),
                    "dur": child["dur_ms"] * 1e3,
                    "args": {"trace_id": trace_id}})
                walk(child, trace_id)

        walk(tree, tree.get("trace_id", ""))
    for rec in records or []:
        t_mono = rec.get("t_mono")
        t_wall = (clock.wall_of(t_mono) if t_mono is not None
                  else rec.get("t_wall", 0.0))
        comp = rec.get("compile") or {}
        dev = rec.get("device") or {}
        dur_s = (comp.get("enqueue_s") or 0.0) + (dev.get("sync_s")
                                                  or 0.0)
        mode = ((rec.get("admission") or {}).get("plan") or {}
                ).get("mode", "steady")
        body.append({"ph": "X", "name": f"dispatch:{mode}",
                     "cat": "admission", "pid": pid,
                     "tid": _track_tid("admission"),
                     "ts": us(t_wall),
                     "dur": max(dur_s, 1e-6) * 1e6,
                     "args": {"seq": rec.get("seq"),
                              "shape": rec.get("shape"),
                              "compile": comp.get("outcome"),
                              "trace_id": (rec.get("trace_ids")
                                           or [""])[0]}})
    for ev in flight_events or []:
        t_mono = ev.get("t_mono")
        t_wall = (clock.wall_of(t_mono) if t_mono is not None
                  else ev.get("t_wall", 0.0))
        body.append({"ph": "i", "s": "t",
                     "name": ev.get("kind", "event"),
                     "cat": "flight", "pid": pid,
                     "tid": _track_tid("flight"),
                     "ts": us(t_wall),
                     "args": {"seq": ev.get("seq"),
                              "trace_id": ev.get("trace_id", "")}})
    for ev in ring_events or []:
        track = ev.get("track", "worker")
        t_wall = ev.get("t_wall", 0.0)
        dur_s = ev.get("dur_s", 0.0)
        phase = ev.get("phase", "")
        base = {"name": phase, "pid": pid,
                "tid": _track_tid(track), "cat": track,
                "args": {"seq": ev.get("seq"),
                         "trace_id": ev.get("trace_id", "")}}
        if dur_s > 0:
            body.append({**base, "ph": "X", "ts": us(t_wall),
                         "dur": dur_s * 1e6})
        else:
            body.append({**base, "ph": "i", "s": "t",
                         "ts": us(t_wall)})
        if phase == "coalesce":
            aid = f"co-{ev.get('seq')}"
            body.append({**base, "ph": "b", "id": aid,
                         "cat": "coalesce", "ts": us(t_wall)})
            body.append({**base, "ph": "e", "id": aid,
                         "cat": "coalesce", "ts": us(t_wall)})
        elif phase == "busy":
            aid = f"ov-{ev.get('seq')}"
            body.append({**base, "ph": "b", "id": aid,
                         "cat": "overlap", "ts": us(t_wall)})
            body.append({**base, "ph": "e", "id": aid,
                         "cat": "overlap",
                         "ts": us(t_wall + dur_s)})
    if body:
        t_base = min(e["ts"] for e in body)
        for e in body:
            e["ts"] = round(e["ts"] - t_base, 3)
            if "dur" in e:
                e["dur"] = round(e["dur"], 3)
    body.sort(key=lambda e: (e["ts"], e["tid"]))
    return events + body
