"""Capacity & occupancy telemetry: can this node carry its load?

ROADMAP item 3 (deadline-aware adaptive batching under SLO feedback
control) needs the node to MEASURE its own capacity before a controller
can act on it: live arrival rate, queue depth, shed rate, and — the
denominator of every utilization claim — per-shape device latency and
true device occupancy.  This module is those signals as first-class,
windowed estimators:

- ``RateEstimator`` — events/sec over a trailing window, coalesced into
  fixed-resolution buckets (O(buckets) memory at any arrival rate, a
  burst's contribution decays out exactly one window later).  The clock
  is injectable so tests are deterministic without sleeps.
- ``QueueDepthSeries`` — a bounded time series of queue-depth samples
  (the batching service stamps enqueue/drain points).
- ``ShapeLatencyModel`` — per-``{shape,path}`` device latency fed from
  real dispatch spans: EWMA + windowed p50/p95 + sample counts,
  exported as ``bls_shape_device_latency_seconds{shape,path,stat}``.
  Label cardinality is BOUNDED: past ``max_shapes`` distinct shapes,
  new ones collapse into ``shape="other"`` (pow-2 bucketing keeps the
  real set tiny; an adversarial shape storm must not grow the scrape).
- ``DeviceOccupancyTracker`` — true device-time accounting under async
  overlap: dispatch N+1 is enqueued while N executes, so wall-clock
  intervals overlap; the device itself serializes programs, so each
  dispatch's TRUE device time is ``sync_end - max(enqueue_end,
  previous_sync_end)``.  Busy seconds accumulate into a windowed
  estimator whose rate IS the occupancy ratio.
- ``CapacityTelemetry`` — the combination: estimated sustainable
  sigs/sec at the CURRENT shape mix (lanes verified / device seconds
  over the window), utilization = demand/capacity, and headroom —
  surfaced via ``/teku/v1/admin/capacity``, the signature service's
  ``health_snapshot()``, and ``capacity_*`` gauges.  Headroom
  exhaustion (utilization crossing 1.0) is EDGE-TRIGGERED into the
  flight recorder with the originating trace id, mirroring the
  breaker/SLO event shapes.

The committee-consensus measurements (PAPERS: EdDSA/BLS in
committee-based consensus) show per-shape, committee-dependent verify
cost — which is why the latency model keys on the padded dispatch
shape, not a scalar average.
"""

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import clock, flightrecorder, tracing
from .env import env_float, env_int
from .metrics import GLOBAL_REGISTRY, MetricsRegistry

DEFAULT_WINDOW_S = env_float("TEKU_TPU_CAPACITY_WINDOW_S", 60.0,
                             lo=1.0)

# distinct `shape` label values before the model folds into "other"
DEFAULT_MAX_SHAPES = env_int("TEKU_TPU_CAPACITY_MAX_SHAPES", 24, lo=1)

# Well-known arrival sources: distinct demand streams the utilization
# model attributes separately (bounded: a handful of named verbs plus
# the per-service names, folding into "other" past MAX_SOURCES).  The
# sync-committee verbs and the KZG blob-batch verb each get their own
# stream so a blob storm or a sync-committee wave is visible as ITS
# demand, not smeared into the gossip service's arrival rate.
SOURCE_SYNC_COMMITTEE = "sync_committee"
SOURCE_KZG = "kzg"


class RateEstimator:
    """Windowed event-rate estimator with an injectable monotonic
    clock.  ``record(amount)`` adds to the current fixed-resolution
    bucket; ``rate()`` is the windowed total divided by the FULL window
    (an empty or half-empty window reads low, never spikes), and
    ``total()`` is the raw windowed sum (the occupancy tracker uses it
    as busy-seconds)."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 buckets: int = 30,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0 or buckets <= 0:
            raise ValueError("window_s and buckets must be positive")
        self.window_s = float(window_s)
        self._res = self.window_s / buckets
        self._span = buckets
        self._clock = clock
        self._buckets: deque = deque()   # [bucket_index, amount]
        self._lock = threading.Lock()

    def _prune(self, idx: int) -> None:
        horizon = idx - self._span
        while self._buckets and self._buckets[0][0] <= horizon:
            self._buckets.popleft()

    def record(self, amount: float = 1.0) -> None:
        idx = int(self._clock() / self._res)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == idx:
                self._buckets[-1][1] += amount
            else:
                self._buckets.append([idx, amount])
                self._prune(idx)

    def total(self) -> float:
        idx = int(self._clock() / self._res)
        with self._lock:
            self._prune(idx)
            return sum(a for _, a in self._buckets)

    def rate(self) -> float:
        return self.total() / self.window_s


class QueueDepthSeries:
    """Bounded (t_wall, depth) time series + current-depth readout.
    Sampled at enqueue/drain points by the batching service — cheap
    enough for the hot path (one deque append under a lock)."""

    def __init__(self, capacity: int = 256,
                 clock: Callable[[], float] = time.time):
        self._samples: deque = deque(maxlen=capacity)
        self._clock = clock
        self._current = 0
        self._lock = threading.Lock()

    def record(self, depth: int) -> None:
        with self._lock:
            self._current = int(depth)
            self._samples.append((round(self._clock(), 3), int(depth)))

    @property
    def current(self) -> int:
        return self._current

    def snapshot(self, last: int = 32) -> List[dict]:
        with self._lock:
            samples = list(self._samples)[-last:]
        return [{"t_wall": t, "depth": d} for t, d in samples]


class _ShapeEntry:
    __slots__ = ("ewma_s", "samples", "count", "lock")

    def __init__(self, window: int):
        self.ewma_s: Optional[float] = None
        self.samples: deque = deque(maxlen=window)
        self.count = 0
        self.lock = threading.Lock()


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


class ShapeLatencyModel:
    """Per-``{shape,path}`` device-latency model fed from real dispatch
    spans: EWMA (alpha-weighted, reacts in a few dispatches), windowed
    p50/p95 (order statistics over the last `window` samples), and a
    cumulative sample count.  Cardinality is bounded at `max_shapes`
    distinct shape strings — later shapes fold into ``"other"`` so a
    shape storm cannot grow the metric family unbounded."""

    OVERFLOW = "other"

    def __init__(self, alpha: float = 0.2, window: int = 128,
                 max_shapes: int = DEFAULT_MAX_SHAPES,
                 registry: MetricsRegistry = GLOBAL_REGISTRY):
        self.alpha = alpha
        self.window = window
        self.max_shapes = max_shapes
        self._entries: Dict[Tuple[str, str], _ShapeEntry] = {}
        self._shapes: set = set()
        self._lock = threading.Lock()
        # live-topology filter (set by retire_mesh_shapes): None = no
        # filter; "" = single-device serving; "@mN" = mesh of N.  The
        # hot-swap lets in-flight dispatches COMPLETE on the old plan
        # after a reshape, and their late observe() must not resurrect
        # the retired series the admission planner just stopped
        # modeling against.
        self._topology: Optional[str] = None
        self._m_latency = registry.labeled_gauge(
            "bls_shape_device_latency_seconds",
            "modeled per-shape device latency (true device time under "
            "overlap): EWMA and windowed p50/p95 per padded dispatch "
            "shape and mont_mul path",
            labelnames=("shape", "path", "stat"))

    def _stale_topology(self, shape: str) -> bool:
        """Does `shape` belong to a topology other than the live one?
        (caller holds the lock; None filter = nothing is stale)"""
        if self._topology is None:
            return False
        if "@m" in shape:
            return not (self._topology
                        and shape.endswith(self._topology))
        return bool(self._topology)

    def observe(self, shape: str, path: str, seconds: float) -> None:
        shape, path = str(shape), str(path)
        with self._lock:
            if self._stale_topology(shape):
                # a dispatch that completed late on a RETIRED topology
                # (the reshape hot-swap lets old-plan dispatches
                # finish): recording it would re-create the dead
                # series and latency_for_lanes' worst-match would keep
                # sizing batches against it — drop the sample
                return
            if shape not in self._shapes:
                if len(self._shapes) >= self.max_shapes:
                    shape = self.OVERFLOW
                self._shapes.add(shape)
            key = (shape, path)
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = _ShapeEntry(self.window)
        with entry.lock:
            entry.count += 1
            entry.samples.append(seconds)
            entry.ewma_s = (seconds if entry.ewma_s is None else
                            self.alpha * seconds
                            + (1 - self.alpha) * entry.ewma_s)
            stats = self._stats_locked(entry)
        for stat, value in (("ewma", stats["ewma_s"]),
                            ("p50", stats["p50_s"]),
                            ("p95", stats["p95_s"])):
            self._m_latency.labels(shape=key[0], path=key[1],
                                   stat=stat).set(value)

    @staticmethod
    def _stats_locked(entry: _ShapeEntry) -> dict:
        ordered = sorted(entry.samples)
        return {"ewma_s": round(entry.ewma_s or 0.0, 6),
                "p50_s": round(_percentile(ordered, 0.50), 6),
                "p95_s": round(_percentile(ordered, 0.95), 6),
                "samples": entry.count,
                "window_samples": len(ordered)}

    def snapshot(self) -> Dict[str, Dict[str, dict]]:
        """{shape: {path: {ewma_s, p50_s, p95_s, samples}}}"""
        with self._lock:
            items = list(self._entries.items())
        out: Dict[str, Dict[str, dict]] = {}
        for (shape, path), entry in items:
            with entry.lock:
                out.setdefault(shape, {})[path] = \
                    self._stats_locked(entry)
        return out

    def latency_s(self, shape: str, path: str,
                  stat: str = "p50_s") -> Optional[float]:
        entry = self._entries.get((shape, path))
        if entry is None:
            return None
        with entry.lock:
            return self._stats_locked(entry)[stat]

    def retire(self, predicate: Callable[[str], bool]) -> int:
        """Drop every series whose SHAPE matches `predicate` and free
        its slot in the bounded shape set.  The exported gauge
        children keep their last value (Prometheus series are
        append-only here); the MODEL — what latency_for_lanes and the
        admission planner read — forgets them.  Returns the number of
        series dropped."""
        with self._lock:
            victims = [k for k in self._entries if predicate(k[0])]
            for k in victims:
                del self._entries[k]
            self._shapes = {k[0] for k in self._entries}
            return len(victims)

    def clear_topology_filter(self) -> None:
        """Forget the live-topology filter: observe() accepts every
        shape family again.  Ops/test seam — the chaos tests drive the
        real self-heal path, which installs the filter on the GLOBAL
        model; without this restore, every later non-mesh test's
        samples would be silently dropped as a retired topology."""
        with self._lock:
            self._topology = None

    def retire_mesh_shapes(self, live_devices: int) -> int:
        """Mesh reshape hook: retire latency series recorded under any
        OTHER topology (a different ``@mN`` suffix, or the no-mesh
        family when a mesh now serves, or any mesh family when the
        healer fell back to single-device), and install the live
        topology as the model's filter so LATE observes from old-plan
        dispatches cannot resurrect them.  Without this, the
        admission controller's worst-match ``latency_for_lanes`` would
        size batches against the dead topology's device times — e.g.
        keep 8-chip batch plans after the mesh shrank to 4."""
        suffix = f"@m{int(live_devices)}" if live_devices else ""
        with self._lock:
            self._topology = suffix

        def stale(shape: str) -> bool:
            if "@m" in shape:
                return not (suffix and shape.endswith(suffix))
            return bool(suffix)   # single-device series, mesh serving
        return self.retire(stale)

    def latency_for_lanes(self, lanes: int, stat: str = "p50_s"
                          ) -> Optional[float]:
        """Modeled device time of a prospective `lanes`-wide dispatch:
        the WORST matching estimate across paths and kmax variants of
        the ``{lanes}x{kmax}`` shape family the provider labels (the
        admission controller sizes batches against this, and a
        conservative bound never talks it into a batch that blows the
        latency budget).  None = no evidence for this width yet."""
        prefix = f"{int(lanes)}x"
        with self._lock:
            keys = [k for k in self._entries if k[0].startswith(prefix)]
        worst: Optional[float] = None
        for shape, path in keys:
            value = self.latency_s(shape, path, stat)
            if value is not None and value > 0 \
                    and (worst is None or value > worst):
                worst = value
        return worst


class DeviceOccupancyTracker:
    """True device-time accounting under async overlap.

    The service enqueues batch N+1 while batch N executes, so
    ``enqueue_end → sync_end`` wall intervals OVERLAP — summing them
    would double-count.  The device serializes programs, so a
    dispatch's true device time is its interval clamped to start no
    earlier than the previous dispatch's sync end.  ``record`` returns
    that clamped duration (the per-shape latency model's sample) and
    accumulates it into a windowed busy-seconds estimator whose rate
    is the occupancy ratio (busy seconds per wall second)."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 clock: Callable[[], float] = time.monotonic):
        self._busy = RateEstimator(window_s=window_s, clock=clock)
        self._last_end: Optional[float] = None
        self._lock = threading.Lock()

    def record(self, start: float, end: float) -> float:
        with self._lock:
            if self._last_end is not None:
                start = max(start, self._last_end)
            if self._last_end is None or end > self._last_end:
                self._last_end = end
        busy = max(0.0, end - start)
        self._busy.record(busy)
        return busy

    def busy_seconds(self) -> float:
        return self._busy.total()

    def occupancy(self) -> float:
        return min(1.0, self._busy.rate())


class CapacityTelemetry:
    """The node's self-measurement: arrival rates per source, queue
    depth, shed rate, per-shape device latency, device occupancy — and
    the derived signals the future batching controller (ROADMAP 3)
    will close its loop on:

    - ``capacity_sustainable_sigs_per_second`` = lanes verified /
      device-busy seconds over the window — what the device can do at
      the CURRENT shape mix;
    - ``capacity_utilization_ratio`` = demand / capacity (falls back
      to measured occupancy before any dispatch evidence exists);
    - ``capacity_headroom_ratio`` = max(0, 1 - utilization).

    Crossing utilization 1.0 records ONE ``capacity_headroom_exhausted``
    flight-recorder event (with the originating trace id, mirroring the
    SLO breach shape); recovery below 1.0 records
    ``capacity_headroom_recovered`` once."""

    MAX_SOURCES = 16

    def __init__(self, registry: MetricsRegistry = GLOBAL_REGISTRY,
                 window_s: float = DEFAULT_WINDOW_S,
                 clock: Callable[[], float] = time.monotonic,
                 recorder: Optional[flightrecorder.FlightRecorder]
                 = None):
        self.window_s = window_s
        self._clock = clock
        self._recorder = recorder or flightrecorder.RECORDER
        self._arrivals: Dict[str, RateEstimator] = {}
        self._arrivals_lock = threading.Lock()
        self._sheds = RateEstimator(window_s, clock=clock)
        self._lanes = RateEstimator(window_s, clock=clock)
        self.queue_depth = QueueDepthSeries()
        self.latency = ShapeLatencyModel(registry=registry)
        self.occupancy = DeviceOccupancyTracker(window_s, clock=clock)
        self._exhausted = False
        self._m_arrival = registry.labeled_gauge(
            "bls_arrival_rate_per_second",
            "verification arrival rate over the trailing window, per "
            "submitting source (triples/sec)",
            labelnames=("source",))
        registry.gauge(
            "bls_queue_depth",
            "current pending verification signatures/triples (capacity "
            "view of the batching queue, in the same unit as the "
            "arrival rate and batch plan)",
            supplier=lambda: float(self.queue_depth.current))
        registry.gauge(
            "bls_device_occupancy_ratio",
            "fraction of wall time the device spent executing "
            "dispatches over the trailing window (overlap-corrected)",
            supplier=self.occupancy.occupancy)
        registry.gauge(
            "capacity_shed_rate_per_second",
            "verification tasks shed at the queue over the trailing "
            "window",
            supplier=self._sheds.rate)
        registry.gauge(
            "capacity_sustainable_sigs_per_second",
            "estimated sustainable verification throughput at the "
            "current shape mix (windowed lanes / device-busy seconds)",
            supplier=self.sustainable_sigs_per_second)
        registry.gauge(
            "capacity_utilization_ratio",
            "demand / sustainable capacity (measured occupancy before "
            "dispatch evidence exists); > 1.0 = over capacity",
            supplier=self.utilization)
        registry.gauge(
            "capacity_headroom_ratio",
            "max(0, 1 - utilization): remaining fraction of capacity",
            supplier=self.headroom)

    # ------------------------------------------------------------------
    # Inputs (hot-path recorders)
    # ------------------------------------------------------------------
    def record_arrival(self, source: str, triples: int = 1) -> None:
        with self._arrivals_lock:
            est = self._arrivals.get(source)
            if est is None:
                if len(self._arrivals) >= self.MAX_SOURCES:
                    source = "other"
                est = self._arrivals.setdefault(
                    source, RateEstimator(self.window_s,
                                          clock=self._clock))
        est.record(triples)
        self._m_arrival.labels(source=source).set(round(est.rate(), 4))

    def record_shed(self, triples: int = 1) -> None:
        self._sheds.record(triples)

    def record_queue_depth(self, depth: int) -> None:
        self.queue_depth.record(depth)

    def record_dispatch(self, shape: str, path: str, lanes: int,
                        enqueue_end: float, sync_end: float) -> float:
        """One completed device dispatch: clamp the interval to true
        device time (overlap-corrected), feed the per-shape latency
        model and the lanes-verified window.  Called from the dispatch
        handle's sync point with ``perf_counter`` stamps."""
        busy = self.occupancy.record(enqueue_end, sync_end)
        self.latency.observe(shape, path, busy)
        self._lanes.record(lanes)
        return busy

    # ------------------------------------------------------------------
    # Derived signals
    # ------------------------------------------------------------------
    def demand_sigs_per_second(self) -> float:
        with self._arrivals_lock:
            ests = list(self._arrivals.values())
        return sum(e.rate() for e in ests)

    def sustainable_sigs_per_second(self) -> float:
        busy = self.occupancy.busy_seconds()
        if busy <= 0:
            return 0.0
        return self._lanes.total() / busy

    def utilization(self) -> float:
        cap = self.sustainable_sigs_per_second()
        if cap <= 0:
            # no dispatch evidence yet: measured occupancy is the only
            # honest utilization statement available
            return self.occupancy.occupancy()
        return self.demand_sigs_per_second() / cap

    def headroom(self) -> float:
        return max(0.0, 1.0 - self.utilization())

    # ------------------------------------------------------------------
    def refresh(self) -> dict:
        """Periodic tick: re-evaluate the derived signals and fire the
        edge-triggered headroom events.  Returns the snapshot (the
        /teku/v1/admin/capacity body)."""
        snap = self.snapshot()
        util = snap["derived"]["utilization"]
        exhausted = util > 1.0 + 1e-9 \
            and snap["derived"]["capacity_sigs_per_second"] > 0
        if exhausted and not self._exhausted:
            trace_id = (tracing.current_trace_id()
                        or self._recorder.last_trace_id())
            self._recorder.record(
                "capacity_headroom_exhausted", trace_id=trace_id,
                utilization=round(util, 3),
                demand_sigs_per_second=snap["derived"][
                    "demand_sigs_per_second"],
                capacity_sigs_per_second=snap["derived"][
                    "capacity_sigs_per_second"],
                detail="demand exceeds sustainable capacity at the "
                       "current shape mix")
        elif self._exhausted and not exhausted:
            self._recorder.record(
                "capacity_headroom_recovered",
                utilization=round(util, 3))
        self._exhausted = exhausted
        snap["derived"]["headroom_exhausted"] = self._exhausted
        return snap

    def snapshot(self) -> dict:
        with self._arrivals_lock:
            arrivals = {s: round(e.rate(), 4)
                        for s, e in self._arrivals.items()}
        # keep the per-source gauges live: record_arrival() sets them
        # on traffic, but a source that goes QUIET would otherwise
        # freeze at its last burst-era rate forever — the health tick
        # and every endpoint read pass through here, so the gauge
        # decays with the window like the supplier-based siblings
        for source, rate in arrivals.items():
            self._m_arrival.labels(source=source).set(rate)
        demand = self.demand_sigs_per_second()
        cap = self.sustainable_sigs_per_second()
        util = self.utilization()
        return {
            "window_s": self.window_s,
            # clock-spine anchor: the occupancy intervals underlying
            # these rates live on the mono axis — remote timeline
            # consumers convert through this pair (infra/clock.py)
            "anchor": clock.anchor_dict(),
            "arrival_rate_per_second": arrivals,
            "queue_depth": {"current": self.queue_depth.current,
                            "series": self.queue_depth.snapshot()},
            "shed_rate_per_second": round(self._sheds.rate(), 4),
            "device": {
                "occupancy_ratio": round(self.occupancy.occupancy(), 4),
                "busy_seconds_window": round(
                    self.occupancy.busy_seconds(), 4),
                "lanes_window": round(self._lanes.total(), 1)},
            "shapes": self.latency.snapshot(),
            "derived": {
                "demand_sigs_per_second": round(demand, 2),
                "capacity_sigs_per_second": round(cap, 2),
                "utilization": round(util, 4),
                "headroom_ratio": round(max(0.0, 1.0 - util), 4),
                "headroom_sigs_per_second": round(
                    max(0.0, cap - demand), 2),
                "headroom_exhausted": self._exhausted}}

    def summary(self) -> dict:
        """The compact derived view health_snapshot()/SLO consumers
        embed (full detail lives on /teku/v1/admin/capacity)."""
        return {
            "arrival_rate_per_second": round(
                self.demand_sigs_per_second(), 2),
            "capacity_sigs_per_second": round(
                self.sustainable_sigs_per_second(), 2),
            "utilization": round(self.utilization(), 4),
            "headroom_ratio": round(self.headroom(), 4),
            "occupancy_ratio": round(self.occupancy.occupancy(), 4)}


# the process-wide telemetry the provider/service/endpoint share (like
# flightrecorder.RECORDER: dispatch handles, worker threads and the
# REST task all contribute, and the value is ONE combined view)
TELEMETRY = CapacityTelemetry()


def swap_default(telemetry: CapacityTelemetry) -> CapacityTelemetry:
    """Swap the process-default telemetry, returning the old one.

    The virtual-clock harnesses (overload sim, loadgen) build their own
    ``CapacityTelemetry`` on an injectable clock; recorders that only
    reach the module-level functions (the KZG facade's arrival
    accounting) must land in THAT instance for the run.  Callers swap
    in a try/finally and restore the original."""
    global TELEMETRY
    old, TELEMETRY = TELEMETRY, telemetry
    return old


def record_arrival(source: str, triples: int = 1) -> None:
    TELEMETRY.record_arrival(source, triples)


def record_shed(triples: int = 1) -> None:
    TELEMETRY.record_shed(triples)


def record_queue_depth(depth: int) -> None:
    TELEMETRY.record_queue_depth(depth)


def record_dispatch(shape: str, path: str, lanes: int,
                    enqueue_end: float, sync_end: float) -> float:
    return TELEMETRY.record_dispatch(shape, path, lanes, enqueue_end,
                                     sync_end)


def snapshot() -> dict:
    return TELEMETRY.snapshot()


def refresh() -> dict:
    return TELEMETRY.refresh()


def summary() -> dict:
    return TELEMETRY.summary()
