"""Typed environment-variable reads shared by the knob-heavy modules.

A malformed value reads as the default instead of raising: a typo in
an operator's unit file must degrade the knob, never the node.
"""

import os


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def ensure_virtual_devices(n) -> bool:
    """Make sure XLA's host (CPU) platform exposes `n` virtual devices
    by appending ``--xla_force_host_platform_device_count=n`` to
    XLA_FLAGS — ONE definition for the CLI's numeric ``--mesh N``, the
    bench mesh phase and the driver's multichip dryrun.

    Must run BEFORE jax imports (the flag is read at backend init);
    an already-present count is left untouched (the caller's
    environment wins).  Harmless on real TPU hosts — the flag only
    affects the host platform.  Returns True when the flag was added.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return False
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()
    return True
