"""Typed environment-variable reads shared by the knob-heavy modules.

A malformed value reads as the default instead of raising: a typo in
an operator's unit file must degrade the knob, never the node.
"""

import os


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default
