"""Typed environment-variable reads shared by the knob-heavy modules.

The ONE definition of the knob-read contract (enforced full-tree by
``cli lint``'s env-knob checker — teku_tpu/analysis/env_knob.py):

- a malformed value DEGRADES to the default with one WARN per knob,
  never raises: a typo in an operator's unit file must cost the knob,
  not the node (the PR 11 ledger-capacity precedent, now universal);
- numeric knobs may declare clamp bounds (`lo`/`hi`); an out-of-range
  value clamps with the same one-WARN contract (a negative
  flush-failsafe once put a wall deadline in the past);
- every read site is statically visible to the analyzer, which
  auto-extracts the knob registry behind ``cli lint --knobs`` and the
  README drift check — reading through these helpers IS the
  registration.

``env_raw`` exists for the CLI's layering seam (CLI > env > YAML needs
the unparsed string to cascade) and ``env_override`` for bench-style
save/set/restore; neither parses, both keep raw ``os.environ`` access
inside this module.
"""

import contextlib
import logging
import os
import threading
from typing import Iterator, Optional, Sequence

_LOG = logging.getLogger(__name__)

# one WARN per (knob, complaint) per process: knob reads run on hot
# paths (dispatch planning, health ticks) and a typo must not flood
_warn_lock = threading.Lock()
_warned = set()


def _warn_once(name: str, complaint: str) -> None:
    with _warn_lock:
        key = (name, complaint)
        if key in _warned:
            return
        _warned.add(key)
    _LOG.warning("%s %s", name, complaint)


def _reset_warnings() -> None:
    """Test seam: let a regression test assert the one-WARN contract."""
    with _warn_lock:
        _warned.clear()


def _clamp(name: str, value, lo, hi):
    if lo is not None and value < lo:
        _warn_once(name, f"={value!r} below minimum {lo}; clamping")
        return lo
    if hi is not None and value > hi:
        _warn_once(name, f"={value!r} above maximum {hi}; clamping")
        return hi
    return value


def env_float(name: str, default: float, lo: Optional[float] = None,
              hi: Optional[float] = None) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return float(default)
    try:
        value = float(raw)
    except (TypeError, ValueError):
        _warn_once(name, f"={raw!r} is not a number; using default "
                         f"{default}")
        return float(default)
    return _clamp(name, value, lo, hi)


def env_int(name: str, default: int, lo: Optional[int] = None,
            hi: Optional[int] = None) -> int:
    raw = os.environ.get(name)
    if raw is None:
        return int(default)
    try:
        value = int(raw)
    except (TypeError, ValueError):
        _warn_once(name, f"={raw!r} is not an integer; using default "
                         f"{default}")
        return int(default)
    return _clamp(name, value, lo, hi)


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """A string knob (paths, modes with site-local validation).  An
    EMPTY value reads as unset — `TEKU_TPU_X=` in a unit file means
    "default", not "empty-string mode"."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw


_TRUE = ("1", "on", "true", "yes")
_FALSE = ("0", "off", "false", "no")


def env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    lowered = raw.strip().lower()
    if lowered in _TRUE:
        return True
    if lowered in _FALSE:
        return False
    _warn_once(name, f"={raw!r} is not a boolean "
                     f"({'/'.join(_TRUE)} | {'/'.join(_FALSE)}); "
                     f"using default {default}")
    return default


def env_choice(name: str, default: str,
               choices: Sequence[str]) -> str:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if raw in choices:
        return raw
    _warn_once(name, f"={raw!r} is not one of {'/'.join(choices)}; "
                     f"using default {default!r}")
    return default


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """The unparsed value (None-able): the CLI layering seam, where
    "unset" must stay distinguishable from every real value so YAML
    and defaults can cascade beneath it."""
    return os.environ.get(name, default)


@contextlib.contextmanager
def env_override(name: str, value: Optional[str]) -> Iterator[None]:
    """Save/set/restore one knob around a scope (bench phases force
    knobs for a measurement and must put the operator's value back;
    ``None`` unsets)."""
    prev = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = str(value)
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


def ensure_virtual_devices(n) -> bool:
    """Make sure XLA's host (CPU) platform exposes `n` virtual devices
    by appending ``--xla_force_host_platform_device_count=n`` to
    XLA_FLAGS — ONE definition for the CLI's numeric ``--mesh N``, the
    bench mesh phase and the driver's multichip dryrun.

    Must run BEFORE jax imports (the flag is read at backend init);
    an already-present count is left untouched (the caller's
    environment wins).  Harmless on real TPU hosts — the flag only
    affects the host platform.  Returns True when the flag was added.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return False
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()
    return True
