"""Service lifecycle: start/stop state machine + ordered controller.

Equivalent of the reference's serviceutils (reference: infrastructure/
serviceutils/src/main/java/tech/pegasys/teku/service/serviceutils/
Service.java and teku/.../services/BeaconNodeServiceController.java:
41-101): a Service moves IDLE → RUNNING → STOPPED exactly once; the
controller starts services in declaration order and stops them in
reverse, so e.g. storage outlives everything that writes to it.
"""

import asyncio
import enum
import logging
from typing import List

_LOG = logging.getLogger(__name__)


class ServiceState(enum.Enum):
    IDLE = "idle"
    RUNNING = "running"
    STOPPED = "stopped"


class Service:
    """Subclasses implement do_start / do_stop."""

    def __init__(self, name: str = None):
        self.name = name or type(self).__name__
        self.state = ServiceState.IDLE

    async def start(self) -> None:
        if self.state is not ServiceState.IDLE:
            raise RuntimeError(f"{self.name} already {self.state.value}")
        await self.do_start()
        self.state = ServiceState.RUNNING
        _LOG.info("service %s started", self.name)

    async def stop(self) -> None:
        if self.state is not ServiceState.RUNNING:
            return
        self.state = ServiceState.STOPPED
        await self.do_stop()
        _LOG.info("service %s stopped", self.name)

    @property
    def is_running(self) -> bool:
        return self.state is ServiceState.RUNNING

    async def do_start(self) -> None:  # pragma: no cover - interface
        pass

    async def do_stop(self) -> None:  # pragma: no cover - interface
        pass


class ServiceController(Service):
    """Starts children in order, stops in reverse (reference
    BeaconNodeServiceController: Storage → ExecutionLayer → BeaconChain
    → Nat → Powchain → ValidatorClient)."""

    def __init__(self, services: List[Service], name: str = "controller"):
        super().__init__(name)
        self.services = list(services)

    async def do_start(self) -> None:
        started = []
        try:
            for svc in self.services:
                await svc.start()
                started.append(svc)
        except Exception:
            for svc in reversed(started):
                try:
                    await svc.stop()
                except Exception:  # best-effort unwind
                    _LOG.exception("unwinding %s failed", svc.name)
            raise

    async def do_stop(self) -> None:
        for svc in reversed(self.services):
            try:
                await svc.stop()
            except Exception:
                _LOG.exception("stopping %s failed", svc.name)
