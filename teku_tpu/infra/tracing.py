"""Hot-path tracing: per-stage verify-latency attribution.

The north-star metric is attestation-gossip p50 verify latency, but an
end-to-end number cannot say WHERE a slow verify spent its time — the
asyncio queue, batch assembly, host-side limb packing, a JAX recompile,
device execute, or an oracle fallback.  This module is the attribution
layer (the reference's analogue is the per-stage labelled timers its
Besu MetricsSystem hangs off the validation pipeline):

- ``span(stage, **labels)`` — a context-manager stopwatch usable from
  asyncio tasks AND worker threads (monotonic ``perf_counter``); on
  exit the duration lands in the per-stage latency histogram
  ``verify_stage_duration_seconds{stage=...}`` and in every trace
  attached to the current context;
- ``trace(name, **labels)`` — opens a ROOT span: creates a `Trace`,
  binds it to the current context (a `ContextVar`, so `asyncio.to_thread`
  carries it into worker threads for free), and on exit completes the
  trace: total duration → the ``complete`` stage histogram, the trace →
  the slow-trace ring (+ the optional sampler);
- ``new_trace``/``attach``/``finish`` — the unbundled form for flows
  whose root outlives one lexical scope (the batching service attaches
  a whole batch's traces around one device dispatch; bench holds a
  trace open across submit→future-resolve);
- a bounded ring of the N slowest complete traces with their stage
  breakdowns, dumped by ``GET /teku/v1/admin/traces``.

Disabled mode (``--tracing off`` / ``set_enabled(False)``) compiles
spans to a shared no-op: ``span()``/``trace()`` return singletons whose
enter/exit do nothing, ``new_trace`` returns None, and record calls
return immediately — no allocation, no lock, no histogram touch.
"""

import itertools
import os
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import clock
from .env import env_int

from .metrics import GLOBAL_REGISTRY, LATENCY_BUCKETS_S

# The canonical hot-path stages (bench reports percentiles for these;
# `complete` is the root span's end-to-end total).  The old combined
# `device_execute` span is split: `device_enqueue` covers the async
# launch (plus XLA compile on a first shape), `device_sync` covers
# only the blocking wait at the handle's result() — so under async
# overlap the sync span no longer absorbs host-prep time the worker
# spent on the NEXT batch (the PERF.md attribution fix).
STAGES = ("queue_wait", "assembly", "dispatch", "host_prep",
          "device_enqueue", "device_sync", "complete")

_enabled = True

# Traces bound to the current execution context.  A tuple (not a single
# trace): one device dispatch serves a whole batch of root traces, and
# its host_prep/device_enqueue/device_sync spans must attribute to
# every one.
_CURRENT: ContextVar[Tuple["Trace", ...]] = ContextVar(
    "teku_tpu_traces", default=())

_STAGE_HIST = GLOBAL_REGISTRY.labeled_histogram(
    "verify_stage_duration_seconds",
    "per-stage latency attribution of the verification pipeline",
    labelnames=("stage",), buckets=LATENCY_BUCKETS_S)

# Called with every completed Trace (bench installs one to compute
# per-stage percentiles from raw samples instead of bucket edges).
_sampler: Optional[Callable[["Trace"], None]] = None


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def set_sampler(fn: Optional[Callable[["Trace"], None]]) -> None:
    global _sampler
    _sampler = fn


# Process-unique trace ids: the ONE key logs, slow traces, and flight-
# recorder events correlate on.  pid-prefixed so ids from a devnet of
# subprocesses (or a bench child) stay distinguishable in merged logs.
_TRACE_SEQ = itertools.count(1)


def _next_trace_id() -> str:
    return f"{os.getpid():x}-{next(_TRACE_SEQ):06x}"


class Trace:
    """One verification's stage breakdown, root-span start to verdict.

    Thread-safe append: the enqueueing asyncio task, the service worker
    task, and the device-dispatch worker thread all contribute stages.
    """

    __slots__ = ("trace_id", "name", "labels", "t_start", "t_wall",
                 "_end", "stages", "spans", "_lock")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.trace_id = _next_trace_id()
        self.name = name
        self.labels = labels
        # the shared clock-spine pair (infra/clock.py): t_wall and
        # t_start (mono) are ONE stamp, so this trace joins the
        # flight-recorder and dispatch-ledger rings on either axis
        self.t_wall, self.t_start = clock.now()
        self._end: Optional[float] = None
        self.stages: List[Tuple[str, float]] = []
        # (stage, t_mono_start, seconds): the stage intervals the
        # timeline's gap-free span tree is built from.  `stages` keeps
        # the historical (stage, seconds) pairs — consumers iterate it
        # as 2-tuples
        self.spans: List[Tuple[str, float, float]] = []
        self._lock = threading.Lock()

    def add_stage(self, stage: str, seconds: float,
                  t0: Optional[float] = None) -> None:
        if t0 is None:
            # recorded at stage end: derive the start offset
            t0 = time.perf_counter() - seconds
        with self._lock:
            self.stages.append((stage, seconds))
            self.spans.append((stage, t0, seconds))

    @property
    def complete(self) -> bool:
        return self._end is not None

    @property
    def total_s(self) -> float:
        end = self._end if self._end is not None else time.perf_counter()
        return end - self.t_start

    def to_dict(self) -> dict:
        with self._lock:
            spans = list(self.spans)
        return {"trace_id": self.trace_id,
                "name": self.name,
                "labels": dict(self.labels),
                "t_wall": round(self.t_wall, 3),
                "t_mono": round(self.t_start, 6),
                "total_ms": round(self.total_s * 1e3, 3),
                "stages": [{"stage": s, "ms": round(d * 1e3, 3),
                            "t_mono": round(t0, 6)}
                           for s, t0, d in spans]}


class _SlowTraceRing:
    """Bounded collection of the N slowest COMPLETE traces."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._traces: List[Trace] = []
        self._lock = threading.Lock()

    def offer(self, trace: Trace) -> None:
        if self.capacity <= 0:   # ring disabled, histograms still live
            return
        with self._lock:
            if len(self._traces) < self.capacity:
                self._traces.append(trace)
                self._traces.sort(key=lambda t: t.total_s, reverse=True)
                return
            if trace.total_s > self._traces[-1].total_s:
                self._traces[-1] = trace
                self._traces.sort(key=lambda t: t.total_s, reverse=True)

    def snapshot(self) -> List[Trace]:
        with self._lock:
            return list(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


_RING = _SlowTraceRing(
    env_int("TEKU_TPU_SLOW_TRACE_RING", 32, lo=1))


def slow_traces() -> List[dict]:
    """Slowest complete traces, slowest first, as JSON-able dicts."""
    return [t.to_dict() for t in _RING.snapshot()]


def clear_slow_traces() -> None:
    _RING.clear()


# --------------------------------------------------------------------------
# Recording primitives
# --------------------------------------------------------------------------

def record_stage(stage: str, seconds: float,
                 traces: Optional[Sequence[Trace]] = None,
                 t0: Optional[float] = None) -> None:
    """Attribute an already-measured duration: stage histogram + the
    given traces (default: the context's current traces).  ``t0`` is
    the stage's start on the mono axis (spans pass it exactly; when
    omitted the stage is assumed to end NOW)."""
    if not _enabled:
        return
    _STAGE_HIST.labels(stage=stage).observe(seconds)
    if t0 is None:
        t0 = time.perf_counter() - seconds
    for t in (traces if traces is not None else _CURRENT.get()):
        t.add_stage(stage, seconds, t0=t0)


class _Span:
    __slots__ = ("stage", "_traces", "_t0")

    def __init__(self, stage: str, traces: Optional[Sequence[Trace]]):
        self.stage = stage
        self._traces = traces

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        record_stage(self.stage, time.perf_counter() - self._t0,
                     self._traces, t0=self._t0)


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        # None, not self: `with trace(...) as tr` callers test
        # `tr is None` to skip label stamping in disabled mode
        return None

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopSpan()


def span(stage: str, traces: Optional[Sequence[Trace]] = None):
    """Stopwatch context manager for one pipeline stage.  Records into
    the stage histogram and into `traces` (default: the context's
    current traces, empty tuple when none — histogram-only)."""
    if not _enabled:
        return _NOOP
    return _Span(stage, traces)


# --------------------------------------------------------------------------
# Root traces
# --------------------------------------------------------------------------

def new_trace(name: str, **labels) -> Optional[Trace]:
    """Create a root trace WITHOUT binding it to the context (use
    `attach` around the calls that should pick it up, `finish` when the
    verdict lands).  None when tracing is disabled — every consumer of
    a trace handle tolerates None."""
    if not _enabled:
        return None
    return Trace(name, labels)


@contextmanager
def attach(traces: Sequence[Optional[Trace]]):
    """Bind `traces` (Nones filtered) as the context's current traces
    for the duration of the block.  `asyncio.to_thread` copies the
    context, so spans inside a worker thread attribute correctly."""
    live = tuple(t for t in traces if t is not None)
    if not live:
        yield
        return
    token = _CURRENT.set(live)
    try:
        yield
    finally:
        _CURRENT.reset(token)


def current_traces() -> Tuple[Trace, ...]:
    """Every trace bound to the current context.  Async dispatch
    handles capture these at enqueue time so the device span can
    attribute at sync time, possibly under a different context."""
    return _CURRENT.get()


def current_trace() -> Optional[Trace]:
    """First trace bound to the current context (the enqueue hot path
    stamps this onto queued tasks), or None."""
    traces = _CURRENT.get()
    return traces[0] if traces else None


def current_trace_id() -> str:
    """Trace id of the context's current trace, or "" — the correlation
    key JSON log records and flight-recorder events carry."""
    traces = _CURRENT.get()
    return traces[0].trace_id if traces else ""


def finish(trace: Optional[Trace]) -> None:
    """Complete a root trace: total → the `complete` stage histogram,
    trace → slow ring + sampler.  No-op for None (disabled mode)."""
    if trace is None or trace.complete:
        return
    end = time.perf_counter()
    trace._end = end
    total = end - trace.t_start
    if _enabled:
        _STAGE_HIST.labels(stage="complete").observe(total)
        _RING.offer(trace)
    sampler = _sampler
    if sampler is not None:
        try:
            sampler(trace)
        except Exception:  # pragma: no cover - observer must not kill
            pass


class _RootSpan:
    __slots__ = ("trace", "_token")

    def __init__(self, trace: Trace):
        self.trace = trace

    def __enter__(self) -> Trace:
        self._token = _CURRENT.set((self.trace,))
        return self.trace

    def __exit__(self, *exc) -> None:
        _CURRENT.reset(self._token)
        finish(self.trace)


def trace(name: str, **labels):
    """Open a root span: the returned context manager yields the Trace,
    binds it as current, and finishes it on exit — one trace covers
    gossip-arrival → verdict."""
    if not _enabled:
        return _NOOP
    return _RootSpan(Trace(name, labels))
