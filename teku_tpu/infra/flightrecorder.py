"""Crash flight recorder: a bounded ring of structured operator events.

When a node degrades in production the operator's question is "what
happened in the last minute, in order?" — and the answer is scattered
across log lines, metric deltas, and (if tracing caught it) a slow
trace.  This module is the ordered record: backend state transitions,
circuit-breaker trips, SLO breaches/recoveries, queue sheds, and health
flips all land in one bounded in-memory ring, each event stamped with
the wall clock and the ACTIVE TRACE ID from `infra/tracing.py`'s
ContextVar, so a breaker trip correlates with the exact verification
that tripped it and with the JSON log lines it emitted.

The ring is dumped three ways:

- ``GET /teku/v1/admin/flight_recorder`` (api/beacon_api.py) for live
  inspection;
- automatically to a JSONL file on circuit-breaker trip
  (`dump_throttled` — at most one file per THROTTLE_S so a flapping
  breaker cannot fill a disk);
- on fatal crash via ``install_crash_hooks()``: `faulthandler` writes
  C-level tracebacks to a file in the dump dir, and a `sys.excepthook`
  wrapper dumps the ring before the interpreter dies (an `atexit` hook
  disables faulthandler so teardown never writes to a closed file).

The recorder is process-global on purpose (like `infra/faults.py`):
events originate in worker threads, breaker dispatch threads, and
asyncio tasks, and the value of the ring IS that they interleave in one
timeline.
"""

import atexit
import json
import logging
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import List, Optional

from . import clock, tracing
from .env import env_float, env_int, env_str
from .metrics import GLOBAL_REGISTRY, MetricsRegistry

_LOG = logging.getLogger(__name__)

# The CLOSED event-kind vocabulary: every `record("kind", ...)` in the
# tree must be declared here — enforced statically by `cli lint`'s
# closed-registry checker (teku_tpu/analysis/registries.py).  The
# doctor and the admin-endpoint consumers key on these LITERAL strings
# (infra/doctor.py analyzers, the bench flight tail), so an undeclared
# kind is an event nothing will ever match.
EVENT_KINDS = frozenset({
    # backend supervision (PR 1)
    "backend_state", "breaker_trip", "breaker_reclose", "warmup_cache",
    # SLO / health (PR 3)
    "slo_breach", "slo_recovery", "health_flip",
    # service shedding + admission control (PRs 1/7)
    "queue_shed", "flush_failsafe",
    "brownout_enter", "brownout_exit", "brownout_deescalate",
    # capacity + profiler (PR 6)
    "capacity_headroom_exhausted", "capacity_headroom_recovered",
    "profiler_capture_start", "profiler_capture_stop",
    "profiler_capture_error",
    # config self-explanation (PR 11)
    "config_demotion",
    # mesh self-healing (PR 12)
    "mesh_eject", "mesh_readmit", "mesh_reshape",
    "mesh_reshape_vetoed", "mesh_heal_unattributed",
    # the recorder's own crash/dump machinery
    "fatal_crash", "dump_header",
})

DEFAULT_CAPACITY = env_int("TEKU_TPU_FLIGHT_RECORDER_CAPACITY", 512,
                           lo=1)

# minimum seconds between automatic dumps (breaker trips can flap)
THROTTLE_S = env_float("TEKU_TPU_FLIGHT_RECORDER_THROTTLE_S", 30.0,
                       lo=0.0)


def default_dump_dir() -> str:
    return env_str("TEKU_TPU_FLIGHT_RECORDER_DIR") or os.path.join(
        tempfile.gettempdir(), "teku_tpu_flightrecorder")


class FlightRecorder:
    """Bounded, thread-safe ring of JSON-able events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: Optional[str] = None,
                 registry: MetricsRegistry = GLOBAL_REGISTRY):
        self.capacity = capacity
        self.dump_dir = dump_dir or default_dump_dir()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()
        self._last_trace_id = ""
        self._last_dump_t = 0.0
        self._m_events = registry.labeled_counter(
            "flight_recorder_events_total",
            "events recorded into the flight-recorder ring, by kind",
            labelnames=("kind",))
        self._m_dumps = registry.counter(
            "flight_recorder_dumps_total",
            "JSONL dumps written (breaker trips, crashes, manual)")

    # ------------------------------------------------------------------
    def record(self, kind: str, trace_id: Optional[str] = None,
               **fields) -> dict:
        """Append one event.  `trace_id` defaults to the context's
        current trace (empty when none) — explicit overrides let the
        SLO engine blame the verification that originated a breach."""
        if trace_id is None:
            trace_id = tracing.current_trace_id()
        # the shared (t_wall, t_mono) clock-spine stamp (infra/clock):
        # t_wall keeps its historical rounding for endpoint schema
        # compatibility, t_mono makes events orderable against trace
        # spans and ledger records on the timeline
        event = clock.stamp({"seq": 0})
        event.update({"kind": kind, "trace_id": trace_id or "",
                      **fields})
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            if trace_id:
                self._last_trace_id = trace_id
        self._m_events.labels(kind=kind).inc()
        return event

    def last_trace_id(self) -> str:
        """Most recent non-empty trace id seen on any event — the
        "originating trace" an untraced observer (the SLO tick) blames
        when degradation was caused by an earlier traced failure."""
        with self._lock:
            return self._last_trace_id

    def snapshot(self, last: Optional[int] = None) -> List[dict]:
        """Events oldest-first (the whole ring, or the `last` N)."""
        with self._lock:
            events = list(self._events)
        return events[-last:] if last else events

    def tail(self, n: int) -> List[dict]:
        return self.snapshot(last=n)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # ------------------------------------------------------------------
    def dump(self, reason: str, path: Optional[str] = None
             ) -> Optional[str]:
        """Write the ring to a JSONL file (header line first); returns
        the path, or None when the write failed or there was nothing to
        write.  Never raises: the dump runs inside failure paths."""
        events = self.snapshot()
        if not events:
            return None
        if path is None:
            path = os.path.join(
                self.dump_dir,
                f"flight_{int(time.time())}_{os.getpid()}.jsonl")
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as fh:
                fh.write(json.dumps(
                    {"kind": "dump_header", "reason": reason,
                     "t_wall": round(time.time(), 3),
                     "pid": os.getpid(), "events": len(events)}) + "\n")
                for event in events:
                    fh.write(json.dumps(event) + "\n")
        except (OSError, TypeError, ValueError):
            _LOG.warning("flight-recorder dump to %s failed", path,
                         exc_info=True)
            return None
        self._m_dumps.inc()
        _LOG.warning("flight recorder dumped %d events to %s (%s)",
                     len(events), path, reason)
        return path

    def dump_throttled(self, reason: str) -> Optional[str]:
        """`dump`, at most once per THROTTLE_S — the automatic
        breaker-trip hook, where a flapping circuit must not turn each
        half-open failure into a fresh file."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump_t < THROTTLE_S:
                return None
            self._last_dump_t = now
        return self.dump(reason)


# the process-wide recorder every subsystem records into
RECORDER = FlightRecorder()


def record(kind: str, trace_id: Optional[str] = None, **fields) -> dict:
    return RECORDER.record(kind, trace_id=trace_id, **fields)


def config_demotion(subsystem: str, requested, resolved,
                    detail: str, **extra) -> dict:
    """ONE definition of the ``config_demotion`` event schema — the
    doctor keys on the literal kind string and reads ``subsystem``/
    ``detail`` from each event, so a hand-rolled copy at a new
    demotion site could silently emit events it ignores."""
    return record("config_demotion", subsystem=subsystem,
                  requested=str(requested), resolved=str(resolved),
                  detail=detail, **extra)


# --------------------------------------------------------------------------
# Fatal-crash hooks (installed by the CLI entry points, NOT on import —
# a library import must never mutate process-global handlers)
# --------------------------------------------------------------------------

_hooks_installed = False
_faulthandler_file = None


def install_crash_hooks(recorder: Optional[FlightRecorder] = None
                        ) -> Optional[str]:
    """Arm the crash path: faulthandler to a file in the dump dir (so a
    segfault/wedge leaves C-level tracebacks), a sys.excepthook wrapper
    that dumps the ring before an unhandled exception kills the
    process, and an atexit hook that disables faulthandler before its
    file closes.  Idempotent; returns the faulthandler path."""
    global _hooks_installed, _faulthandler_file
    rec = recorder or RECORDER
    if _hooks_installed:
        return getattr(_faulthandler_file, "name", None)
    _hooks_installed = True
    fh_path = None
    try:
        import faulthandler
        os.makedirs(rec.dump_dir, exist_ok=True)
        fh_path = os.path.join(rec.dump_dir,
                               f"faulthandler_{os.getpid()}.log")
        _faulthandler_file = open(fh_path, "w")
        faulthandler.enable(_faulthandler_file)

        def _disarm():
            try:
                faulthandler.disable()
                _faulthandler_file.close()
            except Exception:
                pass
        atexit.register(_disarm)
    except OSError:
        _LOG.warning("faulthandler file setup failed", exc_info=True)

    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            rec.record("fatal_crash",
                       error=f"{exc_type.__name__}: {exc}")
            rec.dump("fatal crash (unhandled exception)")
        except Exception:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook
    return fh_path
