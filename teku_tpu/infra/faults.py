"""Fault-injection harness: deterministic failure modes at named sites.

The robustness counterpart of the reference's acceptance-test chaos
hooks (reference: acceptance-tests/.../dsl/TekuNode.java restart/kill
semantics): production code calls `check(site)` / `transform(site, v)`
at its dispatch seams, and tests install faults keyed by site to prove
the supervisor/breaker state machine end to end — dispatch hangs,
dispatch exceptions, wrong results, slow-ramp backend init, and queue
overflow — without ever touching a real accelerator.

The site vocabulary is CLOSED: ``SITES`` below declares every legal
site string, and the static analyzer (`cli lint`, closed-registry
checker) verifies both directions — no undeclared call site, no dead
member.  Keyed sites: ``bls.mesh_shard`` faults may carry a ``key``
(a device name) — the collective dispatch passes the LIVE device set
(a wedged shard wedges the whole collective) while the self-healing
mesh's per-device isolation probes pass one name, so a keyed fault
models exactly one sick chip (teku_tpu/parallel/selfheal.py).
``h2c.cache`` WrongResult(value=slot) poisons a cache hit; the cache
must re-verify by digest and recompute, never flip a verdict
(ops/h2c_cache.py).

The no-fault fast path is one module-global bool check, so production
traffic pays nothing for the instrumentation.  The registry is
process-global on purpose: dispatch sites run inside worker threads and
jitted call stacks where plumbing a context object through would leak
test concerns into kernel signatures.
"""

import threading
import time
from typing import Dict, List, Optional

__all__ = ["Fault", "Hang", "Raise", "WrongResult", "SlowRamp",
           "Overflow", "SITES", "inject", "clear", "active", "check",
           "transform", "fired_count"]

# The CLOSED site vocabulary: every `check(site)` / `transform(site)`
# string in the tree must be declared here, and every member must have
# a live call site — enforced statically by `cli lint`'s
# closed-registry checker (teku_tpu/analysis/registries.py), replacing
# the grep-maintained list this docstring used to carry.  A typo'd
# site would otherwise silently never fire its fault.
SITES = frozenset({
    "backend.init",         # device bring-up probe (SlowRamp/Raise/Hang)
    "bls.dispatch",         # JaxBls12381 device dispatch (begin+result)
    "bls.mesh_shard",       # sharded mesh dispatch; faults may carry a
                            # device-name key (selfheal.FAULT_SITE)
    "bls.batch_verify",     # BLS facade batch entry (WrongResult)
    "h2c.cache",            # H(m) device-cache slot resolution
    "kzg.dispatch",         # device KZG backend calls
    "sigservice.enqueue",   # batching-service queue admission (Overflow)
    "verifiers.dispatch",   # spec-level verifier seam
})


class Fault:
    """One injectable failure.  `times` bounds how often it fires
    (None = every time until cleared).  `kind` decides whether the
    fault spends its budget at check() (entry) or transform() (result)
    — a WrongResult must not be consumed by the entry hook.  `key`
    scopes the fault to one member of a keyed site (e.g. a mesh device
    index): it fires only when the site's check() names that key in
    its ``keys`` — a keyless fault fires on every call, and a keyed
    fault never fires at a call that passes no keys (the caller is
    not key-aware, so a device-scoped fault cannot leak into it)."""

    kind = "check"

    def __init__(self, times: Optional[int] = None, key=None):
        self.times = times
        self.key = key
        self.fired = 0

    def _matches(self, keys) -> bool:
        if self.key is None:
            return True
        return keys is not None and self.key in keys

    def _consume(self) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        self.fired += 1
        return True

    # subclasses override exactly one of these
    def on_check(self) -> None:  # pragma: no cover - interface
        pass

    def on_transform(self, value):
        return value


class Hang(Fault):
    """Dispatch hang: the call blocks for `seconds` (long enough to
    overrun a breaker deadline, short enough for tests)."""

    def __init__(self, seconds: float, times: Optional[int] = None,
                 key=None):
        super().__init__(times, key=key)
        self.seconds = seconds

    def on_check(self) -> None:
        time.sleep(self.seconds)


class Raise(Fault):
    """Dispatch exception: the call raises `exc` (an instance or a
    zero-arg factory)."""

    def __init__(self, exc, times: Optional[int] = None, key=None):
        super().__init__(times, key=key)
        self.exc = exc

    def on_check(self) -> None:
        exc = self.exc() if callable(self.exc) else self.exc
        raise exc


class WrongResult(Fault):
    """Wrong-result: boolean results are inverted (or forced to `value`
    when given) — the fault class the bisect-on-fail path must isolate."""

    kind = "transform"

    def __init__(self, value=None, times: Optional[int] = None,
                 key=None):
        super().__init__(times, key=key)
        self.value = value

    def on_transform(self, result):
        if self.value is not None:
            return self.value
        if isinstance(result, bool):
            return not result
        return result


class SlowRamp(Hang):
    """Slow-ramp init: the site takes `seconds` before succeeding —
    models the ~25-minute TPU plugin bring-up at test timescales.
    Mechanically a Hang; the distinct name marks *bring-up* slowness
    (site succeeds afterwards) vs a *dispatch* wedge."""


class Overflow(Fault):
    """Queue overflow: admission raises the overflow error class the
    site's shed path handles (default asyncio.QueueFull)."""

    def __init__(self, exc=None, times: Optional[int] = None,
                 key=None):
        super().__init__(times, key=key)
        self.exc = exc

    def on_check(self) -> None:
        if self.exc is not None:
            raise self.exc() if callable(self.exc) else self.exc
        import asyncio
        raise asyncio.QueueFull()


_LOCK = threading.Lock()
_FAULTS: Dict[str, List[Fault]] = {}
_ACTIVE = False       # fast-path guard: no dict lookup when quiescent


def inject(site: str, fault: Fault) -> Fault:
    """Install `fault` at `site`; returns it (so tests can read
    .fired)."""
    global _ACTIVE
    with _LOCK:
        _FAULTS.setdefault(site, []).append(fault)
        _ACTIVE = True
    return fault


def clear(site: Optional[str] = None) -> None:
    """Remove faults at `site` (all sites when None)."""
    global _ACTIVE
    with _LOCK:
        if site is None:
            _FAULTS.clear()
        else:
            _FAULTS.pop(site, None)
        _ACTIVE = bool(_FAULTS)


def active() -> bool:
    return _ACTIVE


def fired_count(site: str) -> int:
    with _LOCK:
        return sum(f.fired for f in _FAULTS.get(site, ()))


def check(site: str, keys=None) -> None:
    """Call at a dispatch site BEFORE the real work: installed faults
    may sleep (Hang/SlowRamp) or raise (Raise/Overflow).  ``keys``
    names the site members this call touches (e.g. the live mesh
    device indices): keyed faults fire only when their key is named,
    so a per-device fault wedges the collective dispatch AND that one
    device's isolation probe, and nothing else."""
    if not _ACTIVE:
        return
    with _LOCK:
        faults = [f for f in _FAULTS.get(site, ())
                  if f.kind == "check" and f._matches(keys)
                  and f._consume()]
    for f in faults:
        f.on_check()


def transform(site: str, value, keys=None):
    """Call at a dispatch site on the RESULT: WrongResult faults
    corrupt the value on its way out (same ``keys`` scoping as
    check())."""
    if not _ACTIVE:
        return value
    with _LOCK:
        faults = [f for f in _FAULTS.get(site, ())
                  if f.kind == "transform" and f._matches(keys)
                  and f._consume()]
    for f in faults:
        value = f.on_transform(value)
    return value
