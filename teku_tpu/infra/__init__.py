"""Infrastructure primitives: metrics, async utilities, events.

The analogue of the reference's infrastructure/* modules (async
SafeFuture/AsyncRunner, events EventChannels, metrics MetricsSystem) —
rebuilt on asyncio idioms rather than translated from the JVM design.
"""
