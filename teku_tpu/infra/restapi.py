"""Minimal asyncio HTTP/1.1 server with typed JSON routes.

The REST plumbing role of the reference's Javalin wrapper (reference:
infrastructure/restapi/src/main/java/tech/pegasys/teku/infrastructure/
restapi/RestApi.java:19-34): a route table of (method, path pattern)
→ async handler, path params via {name} segments, JSON in/out, error
mapping.  Deliberately tiny — enough for the beacon API surface and
the Prometheus exposition, with zero third-party dependencies.
"""

import asyncio
import json
import logging
import re
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

_LOG = logging.getLogger(__name__)

Handler = Callable[..., Awaitable]


class SseStream:
    """A handler returns one of these to take over the response as a
    server-sent-events stream: `gen` is an async generator yielding
    (event_name, json_payload) pairs; the connection closes when the
    generator ends or the client disconnects."""

    def __init__(self, gen):
        self.gen = gen


class HttpError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class RestApi:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._routes: List[Tuple[str, "re.Pattern", Handler]] = []
        self._server: Optional[asyncio.AbstractServer] = None
        self._clients: set = set()

    def route(self, method: str, pattern: str, handler: Handler) -> None:
        regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")
        self._routes.append((method.upper(), regex, handler))

    def get(self, pattern: str, handler: Handler) -> None:
        self.route("GET", pattern, handler)

    def post(self, pattern: str, handler: Handler) -> None:
        self.route("POST", pattern, handler)

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # 3.12's wait_closed blocks until every client handler ends
            # — a long-lived SSE stream would hold shutdown forever, so
            # cancel them first
            for task in list(self._clients):
                task.cancel()
            for task in list(self._clients):
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._clients.add(asyncio.current_task())
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                parts = line.decode("latin1").strip().split(" ")
                if len(parts) != 3:
                    break
                method, target, _version = parts
                headers: Dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if not h or h in (b"\r\n", b"\n"):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                n = int(headers.get("content-length", "0") or "0")
                if n > (1 << 22):
                    # can't resync the stream past an unread body:
                    # reject and close
                    await self._respond(writer, 413,
                                        {"code": 413,
                                         "message": "body too large"})
                    break
                if n:
                    body = await reader.readexactly(n)
                keep = headers.get("connection", "").lower() != "close"
                streamed = await self._dispatch(writer, method, target,
                                                body, headers)
                if streamed or not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            _LOG.exception("http client loop failed")
        finally:
            self._clients.discard(asyncio.current_task())
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, writer, method: str, target: str,
                        body: bytes, headers: Optional[Dict[str, str]]
                        = None) -> None:
        path, _, query = target.partition("?")
        params = {}
        from urllib.parse import parse_qsl
        for k, v in parse_qsl(query, keep_blank_values=True):
            # the beacon API's repeatable array form (topics=a&topics=b)
            # folds to the comma-joined value handlers already parse
            # (none of our list-valued params legally contain commas)
            params[k] = params[k] + "," + v if k in params else v
        status, payload, ctype = 404, {"code": 404,
                                       "message": "not found"}, None
        import inspect
        for m, regex, handler in self._routes:
            match = regex.match(path)
            if m == method and match:
                try:
                    kwargs = dict(match.groupdict())
                    accepted = inspect.signature(handler).parameters
                    if body and "raw_body" in accepted:
                        # SSZ/binary endpoints take the bytes verbatim
                        kwargs["raw_body"] = body
                    if body and "body" in accepted:
                        try:
                            kwargs["body"] = json.loads(body)
                        except (json.JSONDecodeError, ValueError,
                                UnicodeDecodeError):
                            if "raw_body" not in accepted:
                                raise HttpError(400, "invalid JSON body")
                    if params and "query" in accepted:
                        kwargs["query"] = params
                    if "headers" in accepted:
                        kwargs["headers"] = headers or {}
                    result = await handler(**kwargs)
                    if isinstance(result, SseStream):
                        await self._stream_sse(writer, result)
                        return True
                    # (payload, ctype) or (payload, ctype, status) —
                    # the health endpoint speaks through its status
                    # code (200/206/503), not its body
                    if isinstance(result, tuple):
                        if len(result) == 3:
                            payload, ctype, status = result
                        else:
                            payload, ctype = result
                            status = 200
                    else:
                        payload = result
                        status = 200
                except HttpError as exc:
                    status = exc.status
                    payload = {"code": exc.status, "message": exc.message}
                except Exception as exc:
                    _LOG.exception("handler failed: %s %s", method, path)
                    status = 500
                    payload = {"code": 500, "message": str(exc)}
                break
        await self._respond(writer, status, payload, ctype)
        return False

    @staticmethod
    async def _stream_sse(writer, stream: SseStream) -> None:
        """SSE per the events-API spec: one `event:`/`data:` block per
        event, connection held open until either side ends it."""
        writer.write(b"HTTP/1.1 200 X\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        nxt = None
        try:
            agen = stream.gen.__aiter__()
            while True:
                if nxt is None:
                    nxt = asyncio.ensure_future(agen.__anext__())
                try:
                    event, data = await asyncio.wait_for(
                        asyncio.shield(nxt), timeout=15.0)
                    nxt = None
                except asyncio.TimeoutError:
                    # SSE comment keepalive — also how a dead client
                    # gets discovered (the write fails)
                    writer.write(b": keepalive\n\n")
                    await writer.drain()
                    continue
                writer.write(f"event: {event}\n"
                             f"data: {json.dumps(data)}\n\n".encode())
                await writer.drain()
        except (StopAsyncIteration, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            if nxt is not None:
                nxt.cancel()
            close = getattr(stream.gen, "aclose", None)
            if close is not None:
                try:
                    await close()
                except Exception:
                    pass

    @staticmethod
    async def _respond(writer, status: int, payload,
                       ctype: Optional[str] = None) -> None:
        if ctype is None:
            data = json.dumps(payload).encode()
            ctype = "application/json"
        else:
            data = payload if isinstance(payload, bytes) else str(
                payload).encode()
        head = (f"HTTP/1.1 {status} X\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(data)}\r\n\r\n")
        writer.write(head.encode() + data)
        await writer.drain()
