"""Minimal asyncio JSON-RPC-over-HTTP client shared by the engine-API
and eth1 clients (reference: both ride the same Web3j/OkHttp plumbing
in ethereum/executionclient).

One implementation of the raw HTTP mechanics — status-line checking,
content-length and chunked transfer decoding, JSON-RPC error
unwrapping — so the two callers cannot drift apart.
"""

import asyncio
import json
from typing import Any, Dict, Optional


class JsonRpcError(RuntimeError):
    pass


def _decode_body(head: bytes, payload: bytes) -> bytes:
    headers = {}
    for line in head.split(b"\r\n")[1:]:
        name, _, value = line.partition(b":")
        headers[name.strip().lower()] = value.strip()
    if headers.get(b"transfer-encoding", b"").lower() == b"chunked":
        out = bytearray()
        pos = 0
        while pos < len(payload):
            eol = payload.find(b"\r\n", pos)
            if eol < 0:
                break
            size = int(payload[pos:eol].split(b";")[0], 16)
            if size == 0:
                break
            out += payload[eol + 2:eol + 2 + size]
            pos = eol + 2 + size + 2
        return bytes(out)
    return payload


async def http_json_rpc(host: str, port: int, method: str, params,
                        request_id: int = 1,
                        headers: Optional[Dict[str, str]] = None,
                        timeout: float = 10.0) -> Any:
    """One JSON-RPC call; raises JsonRpcError on HTTP or RPC errors."""
    body = json.dumps({"jsonrpc": "2.0", "id": request_id,
                       "method": method, "params": params}).encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    req = (f"POST / HTTP/1.1\r\nHost: {host}\r\n{extra}"
           f"Content-Type: application/json\r\n"
           f"Content-Length: {len(body)}\r\nConnection: close\r\n"
           f"\r\n").encode() + body
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout)
    try:
        writer.write(req)
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0]
    parts = status_line.split()
    if len(parts) < 2 or not parts[1].isdigit():
        raise JsonRpcError(f"malformed HTTP response: {status_line!r}")
    if int(parts[1]) != 200:
        raise JsonRpcError(f"HTTP {int(parts[1])} from {method}")
    out = json.loads(_decode_body(head, payload))
    if "error" in out:
        raise JsonRpcError(f"{method} error: {out['error']}")
    return out["result"]
