"""Minimal Prometheus-style metrics registry.

Counterpart of the reference's Besu-backed MetricsSystem (reference:
infrastructure/metrics/src/main/java/tech/pegasys/teku/infrastructure/
metrics/MetricsEndpoint.java, TekuMetricCategory.java) reduced to what
the node needs: counters, gauges (settable or callback-backed),
fixed-bucket histograms, LABELED counter/histogram families (the
reference's LabelledMetric seam — what per-stage / per-backend
breakdowns hang off), and a text exposition for scraping.  No external
dependencies, safe for use from asyncio tasks and worker threads
(operations are simple attribute updates guarded by locks).

Conventions (enforced by the fast-tier naming lint in
tests/test_metrics_exposition.py):
- counters end in ``_total``;
- duration metrics are measured in SECONDS, named ``*_seconds``, and
  use ``LATENCY_BUCKETS_S`` — the old unitless DEFAULT_BUCKETS
  (1…2500) remain only for size/count distributions.
"""

import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_LOG = logging.getLogger(__name__)

# Log-spaced latency buckets: 100 µs … 10 s.  Covers everything from a
# warm single-lane device dispatch (~ms) through an oracle fallback
# pairing (tens of ms) up to a cold XLA compile absorbed on the hot
# path (seconds) — every duration metric in the tree uses these.
LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _label_pairs(names: Sequence[str], values: Sequence[str]) -> str:
    return ",".join(f'{n}="{_escape_label(v)}"'
                    for n, v in zip(names, values))


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    pairs = _label_pairs(names, values)
    return "{" + pairs + "}" if pairs else ""


def _header(name: str, help_: str, type_: str) -> List[str]:
    return [f"# HELP {name} {_escape_help(help_)}",
            f"# TYPE {name} {type_}"]


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def collect(self) -> List[str]:
        return _header(self.name, self.help, "counter") + [
            f"{self.name} {self.value}"]


class Gauge:
    def __init__(self, name: str, help_: str,
                 supplier: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help_
        self._supplier = supplier
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    @property
    def value(self) -> float:
        if self._supplier:
            return self._supplier()
        with self._lock:
            return self._value

    def collect(self) -> List[str]:
        out = _header(self.name, self.help, "gauge")
        try:
            # a raising supplier must cost ONE sample, never the scrape
            out.append(f"{self.name} {self.value}")
        except Exception:
            _LOG.warning("gauge %s supplier failed; omitting sample",
                         self.name, exc_info=True)
        return out


class _HistogramState:
    """Shared bucket accounting used by Histogram and the children of
    LabeledHistogram."""

    __slots__ = ("buckets", "_counts", "_sum", "_total", "_lock")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._total

    def sample_lines(self, name: str, labels: str = "") -> List[str]:
        """`name_bucket{...le=...}` series + sum + count, with `labels`
        an already-formatted `k="v",` prefix (may be empty)."""
        counts, sum_, total = self.snapshot()
        out = []
        cum = 0
        for i, ub in enumerate(self.buckets):
            cum += counts[i]
            out.append(
                f'{name}_bucket{{{labels}le="{ub}"}} {cum}')
        cum += counts[-1]
        out.append(f'{name}_bucket{{{labels}le="+Inf"}} {cum}')
        suffix = "{" + labels.rstrip(",") + "}" if labels else ""
        out.append(f"{name}_sum{suffix} {sum_}")
        out.append(f"{name}_count{suffix} {total}")
        return out


class Histogram:
    """Fixed upper-bound buckets (cumulative, Prometheus-style)."""

    DEFAULT_BUCKETS = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500)

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self._state = _HistogramState(buckets)

    @property
    def buckets(self):
        return self._state.buckets

    def observe(self, value: float) -> None:
        self._state.observe(value)

    @property
    def count(self) -> int:
        return self._state.snapshot()[2]

    @property
    def sum(self) -> float:
        return self._state.snapshot()[1]

    def collect(self) -> List[str]:
        return _header(self.name, self.help, "histogram") + \
            self._state.sample_lines(self.name)


class _LabeledFamily:
    """Shared parent bookkeeping: a dict of children keyed by the label
    value tuple, created on first `labels(**kv)`."""

    def __init__(self, name: str, help_: str, labelnames: Sequence[str]):
        if not labelnames:
            raise ValueError(f"labeled metric {name} needs label names")
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _key(self, kv: Dict[str, str]) -> Tuple[str, ...]:
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        return tuple(str(kv[n]) for n in self.labelnames)

    def _child(self, kv: Dict[str, str], factory):
        key = self._key(kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = factory()
                self._children[key] = child
            return child

    def _items(self):
        with self._lock:
            return sorted(self._children.items())


class LabeledCounter(_LabeledFamily):
    """Counter family with a `labels(**kv)` child API, e.g.
    ``m.labels(backend="device", reason="ok").inc()``."""

    class _Child:
        __slots__ = ("_value", "_lock")

        def __init__(self):
            self._value = 0.0
            self._lock = threading.Lock()

        def inc(self, amount: float = 1.0) -> None:
            with self._lock:
                self._value += amount

        @property
        def value(self) -> float:
            with self._lock:
                return self._value

    def labels(self, **kv) -> "_Child":
        return self._child(kv, LabeledCounter._Child)

    def collect(self) -> List[str]:
        out = _header(self.name, self.help, "counter")
        for key, child in self._items():
            out.append(f"{self.name}"
                       f"{_fmt_labels(self.labelnames, key)} "
                       f"{child.value}")
        return out


class LabeledGauge(_LabeledFamily):
    """Gauge family with a `labels(**kv)` child API, e.g.
    ``m.labels(objective="verify_p50").set(burn)`` — what per-check
    health states and per-objective SLO burn rates hang off."""

    class _Child:
        __slots__ = ("_value", "_lock")

        def __init__(self):
            self._value = 0.0
            self._lock = threading.Lock()

        def set(self, value: float) -> None:
            with self._lock:
                self._value = float(value)

        @property
        def value(self) -> float:
            with self._lock:
                return self._value

    def labels(self, **kv) -> "_Child":
        return self._child(kv, LabeledGauge._Child)

    def collect(self) -> List[str]:
        out = _header(self.name, self.help, "gauge")
        for key, child in self._items():
            out.append(f"{self.name}"
                       f"{_fmt_labels(self.labelnames, key)} "
                       f"{child.value}")
        return out


class LabeledHistogram(_LabeledFamily):
    """Histogram family with per-label-set buckets, e.g.
    ``m.labels(stage="device_sync").observe(dt)``."""

    def __init__(self, name: str, help_: str, labelnames: Sequence[str],
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        super().__init__(name, help_, labelnames)
        self.buckets = tuple(sorted(buckets))

    def labels(self, **kv) -> _HistogramState:
        return self._child(kv, lambda: _HistogramState(self.buckets))

    def collect(self) -> List[str]:
        out = _header(self.name, self.help, "histogram")
        for key, child in self._items():
            prefix = _label_pairs(self.labelnames, key) + ","
            out.extend(child.sample_lines(self.name, prefix))
        return out


class StateGauge:
    """Enum-style gauge: one series per known state, exactly one at 1.0
    (the Prometheus StateSet convention — used for the backend
    supervisor and circuit breaker state machines)."""

    def __init__(self, name: str, help_: str, states: Sequence[str]):
        self.name = name
        self.help = help_
        self.states = tuple(states)
        self._current = self.states[0] if self.states else ""
        self._lock = threading.Lock()

    def set_state(self, state: str) -> None:
        with self._lock:
            if state not in self.states:
                # late-registered states are tolerated: the supervisor
                # may gain states without a redeploy of the dashboards
                self.states = self.states + (state,)
            self._current = state

    @property
    def state(self) -> str:
        return self._current

    def collect(self) -> List[str]:
        with self._lock:
            return _header(self.name, self.help, "gauge") + [
                f'{self.name}{{state="{_escape_label(s)}"}} '
                f'{1.0 if s == self._current else 0.0}'
                for s in self.states]


class MetricsRegistry:
    """Named registry; categories mirror TekuMetricCategory groupings."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "",
              supplier: Optional[Callable[[], float]] = None) -> Gauge:
        return self._get_or_create(
            name, lambda: Gauge(name, help_, supplier), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = Histogram.DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help_, buckets), Histogram)

    def labeled_counter(self, name: str, help_: str = "",
                        labelnames: Sequence[str] = ()) -> LabeledCounter:
        m = self._get_or_create(
            name, lambda: LabeledCounter(name, help_, labelnames),
            LabeledCounter)
        # empty labelnames = retrieval of an existing family
        if labelnames and tuple(labelnames) != m.labelnames:
            raise ValueError(
                f"metric {name} already registered with labels "
                f"{m.labelnames}")
        return m

    def labeled_gauge(self, name: str, help_: str = "",
                      labelnames: Sequence[str] = ()) -> LabeledGauge:
        m = self._get_or_create(
            name, lambda: LabeledGauge(name, help_, labelnames),
            LabeledGauge)
        if labelnames and tuple(labelnames) != m.labelnames:
            raise ValueError(
                f"metric {name} already registered with labels "
                f"{m.labelnames}")
        return m

    def labeled_histogram(self, name: str, help_: str = "",
                          labelnames: Sequence[str] = (),
                          buckets: Sequence[float] = LATENCY_BUCKETS_S
                          ) -> LabeledHistogram:
        m = self._get_or_create(
            name,
            lambda: LabeledHistogram(name, help_, labelnames, buckets),
            LabeledHistogram)
        if labelnames and tuple(labelnames) != m.labelnames:
            raise ValueError(
                f"metric {name} already registered with labels "
                f"{m.labelnames}")
        return m

    def state_gauge(self, name: str, help_: str = "",
                    states: Sequence[str] = ()) -> StateGauge:
        return self._get_or_create(
            name, lambda: StateGauge(name, help_, states), StateGauge)

    def _get_or_create(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name} already registered "
                                 f"as {type(m).__name__}")
            return m

    def metrics(self) -> Dict[str, object]:
        """Snapshot of the registered families (for the naming lint)."""
        with self._lock:
            return dict(self._metrics)

    def expose(self) -> str:
        """Prometheus text exposition of every registered metric.  One
        broken metric (e.g. a raising gauge supplier) loses its own
        samples, never the scrape."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            try:
                lines.extend(m.collect())
            except Exception:
                _LOG.warning("metric %s failed to collect; omitted "
                             "from exposition",
                             getattr(m, "name", m), exc_info=True)
        return "\n".join(lines) + "\n"


GLOBAL_REGISTRY = MetricsRegistry()
