"""Minimal Prometheus-style metrics registry.

Counterpart of the reference's Besu-backed MetricsSystem (reference:
infrastructure/metrics/src/main/java/tech/pegasys/teku/infrastructure/
metrics/MetricsEndpoint.java, TekuMetricCategory.java) reduced to what
the node needs: counters, gauges (settable or callback-backed),
fixed-bucket histograms, and a text exposition for scraping.  No
external dependencies, safe for use from asyncio tasks and worker
threads (operations are simple attribute updates guarded by locks).
"""

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def collect(self) -> List[str]:
        return [f"# TYPE {self.name} counter",
                f"{self.name} {self._value}"]


class Gauge:
    def __init__(self, name: str, help_: str,
                 supplier: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help_
        self._supplier = supplier
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = value

    @property
    def value(self) -> float:
        return self._supplier() if self._supplier else self._value

    def collect(self) -> List[str]:
        return [f"# TYPE {self.name} gauge", f"{self.name} {self.value}"]


class Histogram:
    """Fixed upper-bound buckets (cumulative, Prometheus-style)."""

    DEFAULT_BUCKETS = (1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 2500)

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._total

    @property
    def sum(self) -> float:
        return self._sum

    def collect(self) -> List[str]:
        out = [f"# TYPE {self.name} histogram"]
        cum = 0
        for i, ub in enumerate(self.buckets):
            cum += self._counts[i]
            out.append(f'{self.name}_bucket{{le="{ub}"}} {cum}')
        cum += self._counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {self._sum}")
        out.append(f"{self.name}_count {self._total}")
        return out


class StateGauge:
    """Enum-style gauge: one series per known state, exactly one at 1.0
    (the Prometheus StateSet convention — used for the backend
    supervisor and circuit breaker state machines)."""

    def __init__(self, name: str, help_: str, states: Sequence[str]):
        self.name = name
        self.help = help_
        self.states = tuple(states)
        self._current = self.states[0] if self.states else ""
        self._lock = threading.Lock()

    def set_state(self, state: str) -> None:
        with self._lock:
            if state not in self.states:
                # late-registered states are tolerated: the supervisor
                # may gain states without a redeploy of the dashboards
                self.states = self.states + (state,)
            self._current = state

    @property
    def state(self) -> str:
        return self._current

    def collect(self) -> List[str]:
        with self._lock:
            return [f"# TYPE {self.name} gauge"] + [
                f'{self.name}{{state="{s}"}} '
                f'{1.0 if s == self._current else 0.0}'
                for s in self.states]


class MetricsRegistry:
    """Named registry; categories mirror TekuMetricCategory groupings."""

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "",
              supplier: Optional[Callable[[], float]] = None) -> Gauge:
        return self._get_or_create(
            name, lambda: Gauge(name, help_, supplier), Gauge)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = Histogram.DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help_, buckets), Histogram)

    def state_gauge(self, name: str, help_: str = "",
                    states: Sequence[str] = ()) -> StateGauge:
        return self._get_or_create(
            name, lambda: StateGauge(name, help_, states), StateGauge)

    def _get_or_create(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name} already registered "
                                 f"as {type(m).__name__}")
            return m

    def expose(self) -> str:
        """Prometheus text exposition of every registered metric."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


GLOBAL_REGISTRY = MetricsRegistry()
