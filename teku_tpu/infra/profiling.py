"""Triggered ``jax.profiler`` trace capture.

When the p50 SLO starts burning the operator's next question is "what
is the device DOING right now?" — and by the time someone attaches a
profiler by hand the incident is over.  This module makes capture a
runtime property:

- on demand via ``GET /teku/v1/admin/profile?start=1`` / ``?stop=1``
  (api/beacon_api.py) — one bounded capture at a time;
- automatically when the ``attestation_verify_p50`` burn rate crosses
  the trigger threshold: ONE capture per cooldown window (a sustained
  breach must not fill a disk with traces), stopped after a bounded
  duration by the node's health tick calling ``poll()``.

Every start/stop lands in the flight recorder
(``profiler_capture_start`` / ``profiler_capture_stop``) with the
originating trace id — mirroring the breaker/SLO event shapes — and in
``profiler_captures_total{trigger="manual"|"burn_rate"}``.

The actual profiler is an injectable backend: the default lazily
imports ``jax.profiler`` (so importing this module never drags jax in,
and a CPU-only or jax-less process degrades to a recorded error, never
a crash); tests inject a fake.
"""

import logging
import os
import tempfile
import threading
import time
from typing import Callable, Optional

from . import flightrecorder, tracing
from .env import env_float as _env_float
from .env import env_str as _env_str
from .metrics import GLOBAL_REGISTRY, MetricsRegistry

_LOG = logging.getLogger(__name__)


def default_profile_dir() -> str:
    return _env_str("TEKU_TPU_PROFILE_DIR") or os.path.join(
        tempfile.gettempdir(), "teku_tpu_profiles")


class JaxProfilerBackend:
    """The real thing: ``jax.profiler.start_trace``/``stop_trace``
    writing a TensorBoard-readable trace directory."""

    def start(self, log_dir: str) -> None:
        import jax.profiler
        os.makedirs(log_dir, exist_ok=True)
        jax.profiler.start_trace(log_dir)

    def stop(self) -> None:
        import jax.profiler
        jax.profiler.stop_trace()


class ProfilerController:
    """One capture at a time, cooldown-gated auto-trigger, flight-
    recorder evidence.  All public methods are thread-safe (the REST
    task and the health tick may race a stop)."""

    WATCH_OBJECTIVE = "attestation_verify_p50"

    def __init__(self, backend=None, out_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 recorder: Optional[flightrecorder.FlightRecorder]
                 = None,
                 cooldown_s: Optional[float] = None,
                 auto_duration_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None):
        self._backend = backend or JaxProfilerBackend()
        self.out_dir = out_dir or default_profile_dir()
        self._clock = clock
        self._recorder = recorder or flightrecorder.RECORDER
        self.cooldown_s = (cooldown_s if cooldown_s is not None else
                           _env_float("TEKU_TPU_PROFILE_COOLDOWN_S",
                                      600.0))
        self.auto_duration_s = (
            auto_duration_s if auto_duration_s is not None else
            _env_float("TEKU_TPU_PROFILE_AUTO_DURATION_S", 5.0))
        self.burn_threshold = (
            burn_threshold if burn_threshold is not None else
            _env_float("TEKU_TPU_PROFILE_BURN_THRESHOLD", 1.0))
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._last: Optional[dict] = None
        self._last_auto_t: Optional[float] = None
        self._m_captures = registry.labeled_counter(
            "profiler_captures_total",
            "jax.profiler trace captures started, by trigger "
            "(manual | burn_rate)",
            labelnames=("trigger",))

    # ------------------------------------------------------------------
    def start(self, trigger: str = "manual",
              duration_s: Optional[float] = None) -> dict:
        """Begin a capture.  ``duration_s`` arms an auto-stop deadline
        enforced by ``poll()`` (every auto capture gets one; manual
        captures run until ``stop()`` unless bounded explicitly)."""
        now = self._clock()
        with self._lock:
            if self._active is not None:
                return {"error": "capture already active",
                        "capture": dict(self._active)}
            path = os.path.join(
                self.out_dir,
                f"profile_{int(time.time())}_{os.getpid()}_{trigger}")
            capture = {"trigger": trigger, "path": path,
                       "t_wall": round(time.time(), 3),
                       "_t0": now,
                       "stop_after_s": duration_s}
            self._active = capture
        try:
            self._backend.start(path)
        except Exception as exc:  # noqa: BLE001 - degrade, don't crash
            with self._lock:
                self._active = None
            _LOG.warning("profiler capture failed to start",
                         exc_info=True)
            self._recorder.record(
                "profiler_capture_error", trigger=trigger,
                error=f"{type(exc).__name__}: {exc}")
            return {"error": f"profiler start failed: {exc}"}
        self._m_captures.labels(trigger=trigger).inc()
        trace_id = (tracing.current_trace_id()
                    or self._recorder.last_trace_id())
        self._recorder.record(
            "profiler_capture_start", trace_id=trace_id,
            trigger=trigger, path=path,
            stop_after_s=duration_s)
        _LOG.warning("profiler capture started (%s) -> %s", trigger,
                     path)
        return {k: v for k, v in capture.items()
                if not k.startswith("_")}

    def stop(self) -> dict:
        with self._lock:
            capture = self._active
            self._active = None
        if capture is None:
            return {"error": "no capture active"}
        try:
            self._backend.stop()
        except Exception as exc:  # noqa: BLE001
            # the trace is still running: keep the capture active so a
            # retry can stop it — clearing it here would orphan a
            # live profiler that can then never be stopped (and block
            # every future start())
            with self._lock:
                if self._active is None:
                    self._active = capture
            _LOG.warning("profiler capture failed to stop",
                         exc_info=True)
            self._recorder.record(
                "profiler_capture_error",
                trigger=capture["trigger"],
                error=f"{type(exc).__name__}: {exc}")
            return {"error": f"profiler stop failed: {exc}"}
        duration = round(self._clock() - capture["_t0"], 3)
        done = {"trigger": capture["trigger"],
                "path": capture["path"],
                "t_wall": capture["t_wall"],
                "duration_s": duration}
        with self._lock:
            self._last = done
        self._recorder.record(
            "profiler_capture_stop", trigger=capture["trigger"],
            path=capture["path"], duration_s=duration)
        _LOG.info("profiler capture stopped after %.1fs -> %s",
                  duration, capture["path"])
        return done

    def status(self) -> dict:
        with self._lock:
            active = ({k: v for k, v in self._active.items()
                       if not k.startswith("_")}
                      if self._active is not None else None)
            last = dict(self._last) if self._last else None
        return {"active": active is not None,
                "capture": active,
                "last": last,
                "cooldown_s": self.cooldown_s,
                "burn_threshold": self.burn_threshold,
                "auto_duration_s": self.auto_duration_s,
                "out_dir": self.out_dir}

    # ------------------------------------------------------------------
    def maybe_trigger(self, objective: str, burn: float) -> bool:
        """Burn-rate trigger: start ONE auto capture when the watched
        objective's burn crosses the threshold, at most once per
        cooldown.  Returns True when a capture was started."""
        if objective != self.WATCH_OBJECTIVE:
            return False
        if burn <= self.burn_threshold:
            return False
        now = self._clock()
        with self._lock:
            if self._active is not None:
                return False
            if self._last_auto_t is not None \
                    and now - self._last_auto_t < self.cooldown_s:
                return False
            self._last_auto_t = now
        out = self.start(trigger="burn_rate",
                         duration_s=self.auto_duration_s)
        return "error" not in out

    def poll(self, slo_snapshot: Optional[dict] = None) -> None:
        """The health tick's hook: stop an overdue auto capture, then
        evaluate the burn trigger from an SloEngine snapshot
        (``{objective: {"burn_rate": ...}}``)."""
        with self._lock:
            capture = self._active
            overdue = (capture is not None
                       and capture.get("stop_after_s") is not None
                       and self._clock() - capture["_t0"]
                       >= capture["stop_after_s"])
        if overdue:
            self.stop()
        if slo_snapshot:
            for name, obj in slo_snapshot.items():
                if isinstance(obj, dict):
                    self.maybe_trigger(name,
                                       float(obj.get("burn_rate", 0.0)))


# the process-wide controller the REST endpoint and node tick share
CONTROLLER = ProfilerController()
