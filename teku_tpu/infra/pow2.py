"""Pow-2 bucket arithmetic — ONE definition for every padding rule.

The provider's dispatch bucketing, the admission controller's batch
planner and the mesh shard planner all pad to powers of two so jitted
shapes stay static; a future change to the rule (e.g. an upper clamp)
must change in one place or the planners silently disagree on bucket
widths (the same hoisting argument as infra/env.py's shared readers).
"""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (1 for n <= 1)."""
    return 1 << max(0, (int(n) - 1).bit_length())


def floor_pow2(n: int) -> int:
    """Largest power of two <= n (1 for n <= 1)."""
    n = int(n)
    return 1 << max(0, n.bit_length() - 1) if n >= 1 else 1
