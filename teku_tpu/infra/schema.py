"""Shared schema versioning for machine-readable export envelopes.

``cli doctor --json`` and the timeline exports each need a version
field so downstream tooling (bench_diff-style gates, dashboards) can
detect incompatible payloads.  Hand-rolling ``{"version": 1, ...}``
per exporter is how the analysis report and the doctor diverged once
already; this module is the one edit point for a bump.

An envelope is additive: ``envelope(name, body)`` prefixes the body
with ``schema`` + ``version`` keys and never removes anything, so
existing consumers keyed on body fields keep working.
"""

from typing import Dict

# One row per versioned export surface.  Bumping a version here is THE
# schema-change commit — tests pin these values.
VERSIONS: Dict[str, int] = {
    "doctor": 1,
    "timeline": 1,
    "perfetto": 1,
}


def envelope(schema: str, body: Dict) -> Dict:
    """Wrap ``body`` in the versioned envelope for ``schema``.  An
    unknown schema name is a programming error, not an operator input —
    raise so the test suite catches it."""
    return {"schema": schema, "version": VERSIONS[schema], **body}
