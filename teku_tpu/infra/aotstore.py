"""AOT executable store: serialized XLA executables next to the cache.

The compile wall is the last cold-start cost the persistent compile
cache does not remove: a cache LOAD still re-runs XLA's deserialize +
link inside the first dispatch of every shape, and an empty cache pays
the full 314-357 s/shape compile on the serving path.  This module
stores the COMPILED executables themselves — ``jax.jit(...).lower()
.compile()`` once (``cli precompile``), ``jax.experimental
.serialize_executable`` the result to disk, and every later process
deserializes straight to a callable, skipping tracing, lowering and
XLA entirely.

Entries are keyed by (kernel name, extra key, argument signature) in
the file name and carry an identity header — jax version, backend
platform, device kind, device count, and a fingerprint of the kernel
source tree — checked at load: a mismatched or corrupt entry degrades
to a fresh compile with ONE WARN per complaint (the infra/env.py knob
contract, applied to blobs).

``wrap()`` is the serving seam: it decorates a jitted callable so each
argument signature resolves ONCE per process — to the deserialized
store executable when present, to the wrapped jit otherwise — and the
load/miss counters let ``ops/provider.py`` classify a first dispatch
as ``aot_load`` alongside compile/cache_load.
"""

import hashlib
import logging
import os
import pickle
import threading
from typing import Callable, Optional, Sequence, Tuple

from .env import env_int, env_str
from .metrics import GLOBAL_REGISTRY

_LOG = logging.getLogger(__name__)

ENV_DIR = "TEKU_TPU_AOT_STORE_DIR"
ENV_ON = "TEKU_TPU_AOT_STORE"
ENV_MAX_MB = "TEKU_TPU_AOT_STORE_MAX_MB"
_OFF_VALUES = ("off", "0", "none", "disabled")

# bump when the blob layout changes: old-format entries must read as
# a mismatch (one WARN + fresh compile), never unpickle garbage
FORMAT = 1

_lock = threading.Lock()
_counts = {"load": 0, "miss": 0, "save": 0, "error": 0}
# one WARN per complaint kind per process (corrupt / identity
# mismatch / unwritable store) — a stale store must not flood boot logs
_warned: set = set()
_fingerprint_memo: list = []

_M_STORE = GLOBAL_REGISTRY.labeled_counter(
    "aot_store_total",
    "AOT executable-store lookups and writes by outcome "
    "(load|miss|save|error)",
    labelnames=("outcome",))


def _count(outcome: str) -> None:
    with _lock:
        _counts[outcome] += 1
    _M_STORE.labels(outcome=outcome).inc()


def _warn_once(kind: str, message: str) -> None:
    with _lock:
        if kind in _warned:
            return
        _warned.add(kind)
    _LOG.warning("%s", message)


def default_dir() -> str:
    """Repo-adjacent default, next to compilecache's ``.jax_cache``."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, ".jax_aot")


def store_dir() -> Optional[str]:
    """The resolved store dir, or None when the store is off
    (TEKU_TPU_AOT_STORE=0 or TEKU_TPU_AOT_STORE_DIR=off)."""
    from .env import env_bool
    if not env_bool(ENV_ON, True):
        return None
    configured = env_str(ENV_DIR)
    if configured is not None and configured.lower() in _OFF_VALUES:
        return None
    return configured or default_dir()


def fingerprint() -> str:
    """Hash of the kernel source tree (ops + parallel + the bls
    constants): any edit to the code an executable was traced from
    invalidates the store entry (identity mismatch -> fresh compile),
    so a stale store can never serve an executable whose math the
    tree no longer agrees with."""
    with _lock:
        if _fingerprint_memo:
            return _fingerprint_memo[0]
    here = os.path.dirname(os.path.abspath(__file__))
    pkg = os.path.dirname(here)
    h = hashlib.sha256()
    for rel in ("ops", "parallel"):
        root = os.path.join(pkg, rel)
        for dirpath, _dirs, files in sorted(os.walk(root)):
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                h.update(os.path.relpath(path, pkg).encode())
                with open(path, "rb") as fh:
                    h.update(fh.read())
    digest = h.hexdigest()[:16]
    with _lock:
        if not _fingerprint_memo:
            _fingerprint_memo.append(digest)
    return _fingerprint_memo[0]


def identity() -> dict:
    """The environment an executable is only valid in: serialized XLA
    programs bind the compiler version and the device they were
    compiled for."""
    import jax
    dev = jax.devices()[0]
    return {"format": FORMAT, "jax": jax.__version__,
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", ""),
            "device_count": jax.device_count(),
            "fingerprint": fingerprint()}


def shape_sig(args: Sequence) -> tuple:
    """Canonical hashable signature of one positional-argument tuple:
    the flattened pytree structure plus each leaf's (shape, dtype).
    Works on concrete arrays AND jax.ShapeDtypeStruct avals, so the
    precompiler and the serving wrapper derive the SAME key."""
    import jax
    import numpy as np
    leaves, treedef = jax.tree_util.tree_flatten(tuple(args))
    sig = []
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            dtype = np.asarray(leaf).dtype
        sig.append((shape, jax.dtypes.canonicalize_dtype(dtype).name))
    return (str(treedef), tuple(sig))


def entry_key(kernel: str, sig: tuple) -> str:
    """Stable file stem for one (kernel, signature) pair.  The
    identity header is NOT part of the stem: a jax upgrade or code
    edit must find the file and read a MISMATCH (one WARN), not
    silently re-key the store and leak stale blobs forever."""
    h = hashlib.sha256(repr((kernel, sig)).encode()).hexdigest()[:24]
    safe = "".join(c if c.isalnum() or c in "._-" else "_"
                   for c in kernel)[:40]
    return f"{safe}-{h}"


def _entry_path(base: str, kernel: str, sig: tuple) -> str:
    return os.path.join(base, entry_key(kernel, sig) + ".aotx")


def _enforce_cap(base: str) -> None:
    """Evict oldest entries until the store fits the size cap."""
    cap_mb = env_int(ENV_MAX_MB, 2048, lo=1)
    try:
        entries = []
        for name in os.listdir(base):
            if not name.endswith(".aotx"):
                continue
            path = os.path.join(base, name)
            st = os.stat(path)
            entries.append((st.st_mtime, st.st_size, path))
        total = sum(size for _, size, _ in entries)
        entries.sort()
        while total > cap_mb * 1024 * 1024 and entries:
            _mtime, size, path = entries.pop(0)
            os.unlink(path)
            total -= size
            _LOG.info("aot store: evicted %s (size cap %d MB)",
                      os.path.basename(path), cap_mb)
    except OSError as exc:  # pragma: no cover - fs races
        _warn_once("cap", f"aot store: size-cap sweep failed: {exc}")


def save(kernel: str, sig: tuple, compiled) -> Optional[str]:
    """Serialize one compiled executable into the store (atomic
    tmp+rename).  Returns the entry path, or None when the store is
    off or the write failed (one WARN — an unwritable store must cost
    the store, not the precompiler)."""
    base = store_dir()
    if base is None:
        return None
    from jax.experimental import serialize_executable
    try:
        payload, in_tree, out_tree = serialize_executable.serialize(
            compiled)
        blob = pickle.dumps(
            {"identity": identity(), "kernel": kernel, "sig": sig,
             "triple": (payload, in_tree, out_tree)},
            protocol=pickle.HIGHEST_PROTOCOL)
        os.makedirs(base, exist_ok=True)
        path = _entry_path(base, kernel, sig)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, path)
    except Exception as exc:
        _count("error")
        _warn_once("save", f"aot store: write failed ({exc}); "
                           "executables stay process-local")
        return None
    _count("save")
    _enforce_cap(base)
    return path


def load(kernel: str, sig: tuple) -> Optional[Callable]:
    """Deserialize the stored executable for (kernel, sig), or None —
    missing entries count a miss; corrupt blobs and identity
    mismatches (jax version / device / code fingerprint) degrade to
    None with ONE WARN per complaint, and the caller compiles fresh."""
    base = store_dir()
    if base is None:
        return None
    path = _entry_path(base, kernel, sig)
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError:
        _count("miss")
        return None
    from jax.experimental import serialize_executable
    try:
        entry = pickle.loads(blob)
        stored = entry["identity"]
    except Exception:
        _count("error")
        _warn_once("corrupt",
                   f"aot store: corrupt entry {os.path.basename(path)}"
                   " (unreadable blob); compiling fresh")
        return None
    want = identity()
    if stored != want:
        _count("error")
        drift = sorted(k for k in want
                       if stored.get(k) != want[k])
        _warn_once("identity",
                   "aot store: entries were built for a different "
                   f"environment ({', '.join(drift)} changed); "
                   "compiling fresh — re-run `cli precompile`")
        return None
    try:
        payload, in_tree, out_tree = entry["triple"]
        fn = serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree)
    except Exception as exc:
        _count("error")
        _warn_once("corrupt",
                   f"aot store: entry {os.path.basename(path)} failed "
                   f"to deserialize ({exc}); compiling fresh")
        return None
    _count("load")
    return fn


def stats() -> dict:
    """Process-local store counters (one JSON-able dict)."""
    with _lock:
        return {"dir": store_dir(), "loads": _counts["load"],
                "misses": _counts["miss"], "saves": _counts["save"],
                "errors": _counts["error"]}


def delta(before: dict, after=None) -> dict:
    """Counter movement between two stats() snapshots."""
    if after is None:
        after = stats()
    return {key: after[key] - before[key]
            for key in ("loads", "misses", "saves", "errors")}


class AotDispatcher:
    """The serving seam around one jitted callable.

    Each argument signature resolves ONCE per process: the store
    executable when a valid entry exists, the wrapped jit otherwise —
    after which calls go straight to the resolved callable (the memo
    is the AOT twin of jax's in-memory jit cache).  A store
    executable that rejects its arguments at call time (an aval
    corner the signature missed) permanently falls back to the jit
    for that signature: correctness never depends on the store."""

    def __init__(self, kernel: str, jit_fn: Callable):
        self.kernel = kernel
        self._jit = jit_fn
        self._memo: dict = {}
        self._memo_lock = threading.Lock()

    def _resolve(self, sig: tuple, args: Sequence) -> Callable:
        fn = load(self.kernel, sig)
        if fn is not None:
            return fn
        if store_dir() is not None:
            # self-populating miss: compile through the explicit AOT
            # path (same XLA work the jit would do, and the persistent
            # compile cache still applies) so the NEXT process loads
            # this signature instead of compiling it
            try:
                compiled = self._jit.lower(*args).compile()
                save(self.kernel, sig, compiled)
                return compiled
            except Exception as exc:
                _warn_once(f"aotpath:{self.kernel}",
                           f"aot store: {self.kernel} cannot take the "
                           f"AOT lowering path ({exc}); serving from "
                           "jit")
        return self._jit

    def __call__(self, *args):
        sig = shape_sig(args)
        with self._memo_lock:
            fn = self._memo.get(sig)
        if fn is None:
            fn = self._resolve(sig, args)
            with self._memo_lock:
                fn = self._memo.setdefault(sig, fn)
        if fn is self._jit:
            return fn(*args)
        try:
            return fn(*args)
        except TypeError:
            # signature drift between the store entry and jit's aval
            # canonicalization: serve from the jit from now on
            with self._memo_lock:
                self._memo[sig] = self._jit
            _warn_once(f"calldrift:{self.kernel}",
                       f"aot store: {self.kernel} executable rejected "
                       "its arguments; serving that signature from "
                       "jit")
            return self._jit(*args)

    def precompile(self, avals: Sequence) -> str:
        """Lower + compile this kernel at `avals` and persist it.
        Returns 'load' when the store already held a valid entry,
        else 'compile' (fresh XLA work, now saved)."""
        sig = shape_sig(avals)
        if load(self.kernel, sig) is not None:
            return "load"
        compiled = self._jit.lower(*avals).compile()
        save(self.kernel, sig, compiled)
        return "compile"

    def reset_memo(self) -> None:
        """Test seam: drop resolved signatures so the next call
        re-checks the disk store (a fresh process in miniature)."""
        with self._memo_lock:
            self._memo.clear()


_DISPATCHERS: dict = {}
_DISPATCHERS_LOCK = threading.Lock()


def wrap(kernel: str, jit_fn: Callable) -> AotDispatcher:
    """Wrap one jitted callable behind the store (idempotent per
    kernel name — the registry lets tests and the precompiler reach
    every serving dispatcher)."""
    # a jit fn exists, so jax is loaded: install the backend-compile
    # listener NOW, before this kernel's first compile can slip by it
    from . import compilecache
    compilecache.ensure_instrumented()
    with _DISPATCHERS_LOCK:
        disp = _DISPATCHERS.get(kernel)
        if disp is None or disp._jit is not jit_fn:
            disp = AotDispatcher(kernel, jit_fn)
            _DISPATCHERS[kernel] = disp
    return disp


def dispatchers() -> dict:
    """The live kernel-name -> AotDispatcher registry (snapshot)."""
    with _DISPATCHERS_LOCK:
        return dict(_DISPATCHERS)


def reset_memos() -> None:
    """Test seam: make every wrapped kernel re-check the disk store."""
    for disp in dispatchers().values():
        disp.reset_memo()


def _reset_warnings() -> None:
    """Test seam mirroring infra/env.py: re-arm the one-WARN guards."""
    with _lock:
        _warned.clear()
