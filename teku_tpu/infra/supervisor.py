"""Backend supervisor: background bring-up, hot-swap, circuit breaker.

Round 5's verdict was blunt: the TPU plugin can take ~25 minutes to
initialize, short serial probes can never win that race, and on timeout
the node silently served the pure oracle forever (46 sigs/sec against
the 50k target).  This module changes the shape of bring-up instead of
its timeout values:

- the node boots IMMEDIATELY on the pure oracle (correctness first);
- a supervised background task drives device bring-up with
  unbounded-but-observable patience — state machine
  ``COLD → PROBING → WARMING → READY → DEGRADED/TRIPPED``, each probe
  round an `infra/aio.py:retry_with_backoff` with exponential backoff
  and jitter, every attempt and transition metered;
- on READY the caller-supplied install hook hot-swaps the facade to the
  device provider atomically (one reference assignment; in-flight
  verifications keep the implementation they grabbed);
- after READY every device dispatch runs under a CircuitBreaker:
  per-dispatch deadline, consecutive-failure/timeout threshold trips
  back to the oracle (correctness never degrades — only latency), and
  half-open probing re-closes the circuit.

The reference's analogue is the hard preflight (Teku.java:74) plus
BlstLoader's graceful degradation — but the reference's blst loads in
milliseconds, so it never needed this machine.  A 25-minute bring-up
does.  The design follows outsourced-verification systems where the
fast path is assumed to fail and the system must degrade gracefully
rather than hang (2G2T, arXiv:2602.23464).
"""

import asyncio
import contextvars
import enum
import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from . import aotstore, compilecache, faults, flightrecorder
from .aio import retry_with_backoff
from .metrics import GLOBAL_REGISTRY, MetricsRegistry
from .service import Service

_LOG = logging.getLogger(__name__)


class BackendState(enum.Enum):
    COLD = "cold"            # oracle serving, bring-up not started
    PROBING = "probing"      # oracle serving, background probe running
    WARMING = "warming"      # probe succeeded, warmup compiles running
    READY = "ready"          # device provider installed and serving
    DEGRADED = "degraded"    # bring-up abandoned: oracle is permanent
    TRIPPED = "tripped"      # breaker open: oracle serving, will retry


class CircuitOpenError(RuntimeError):
    """Dispatch refused: the circuit is open (cooldown not elapsed)."""


class WarmupVetoError(RuntimeError):
    """Raised by a warmup hook to VETO installation: the backend came
    up but produced a wrong verdict on known input.  A device that
    cannot be trusted must never be hot-swapped in — correctness over
    speed, always — so the supervisor goes DEGRADED instead of READY.
    (Ordinary warmup exceptions — e.g. a compile hiccup — still
    install: the first real batch compiles lazily.)"""


class DispatchTimeoutError(RuntimeError):
    """A device dispatch overran its per-dispatch deadline."""


class CircuitBreaker:
    """Per-dispatch deadline + consecutive-failure trip + half-open.

    ``call(fn, *args)`` runs `fn` in a daemon worker thread and waits at
    most `deadline_s`: a wedged device runtime blocks inside C where no
    Python signal can reach it (bench round 3 lost 3×25 minutes to
    exactly that), so the only safe containment is to abandon the wait
    and let the orphaned thread die with the process.  `failure_threshold`
    consecutive failures/timeouts OPEN the circuit; after `cooldown_s`
    one probe call is allowed through (HALF_OPEN) and success re-closes
    it.  The cooldown doubles per consecutive trip up to `max_cooldown_s`
    so a persistently sick device is probed ever more rarely.

    Thread-safe: dispatch sites call from asyncio worker threads.  A
    fresh thread per guarded call is deliberate: it keeps
    abandon-on-timeout trivially correct, and its ~0.1 ms cost is noise
    next to a batched device dispatch (ms) or an oracle verification
    (tens of ms) — revisit only if per-call dispatches ever dominate.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 3, deadline_s: float = 30.0,
                 cooldown_s: float = 30.0, max_cooldown_s: float = 600.0,
                 name: str = "bls_device",
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 clock: Callable[[], float] = time.monotonic,
                 on_state_change: Optional[Callable[[str], None]] = None):
        self.name = name
        self.failure_threshold = failure_threshold
        self.deadline_s = deadline_s
        self.base_cooldown_s = cooldown_s
        self.max_cooldown_s = max_cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._trips = 0
        self._open_until = 0.0
        self.on_state_change = on_state_change
        # when True (set by a supervisor that runs its own synthetic
        # reprobe), the half-open slot is reserved for probe=True calls
        # so live traffic never absorbs the deadline_s probe cost
        self.probe_reserved = False
        self._m_state = registry.state_gauge(
            f"{name}_circuit_state", "circuit breaker state",
            states=(self.CLOSED, self.OPEN, self.HALF_OPEN))
        self._m_state.set_state(self.CLOSED)
        self._m_trips = registry.counter(
            f"{name}_circuit_trips_total", "circuit open transitions")
        self._m_timeouts = registry.counter(
            f"{name}_dispatch_timeouts_total",
            "device dispatches that overran the deadline")
        self._m_failures = registry.counter(
            f"{name}_dispatch_failures_total",
            "device dispatches that raised")

    @property
    def state(self) -> str:
        return self._state

    def _set_state(self, new: str) -> None:
        if new == self._state:
            return
        self._state = new
        self._m_state.set_state(new)
        cb = self.on_state_change
        if cb is not None:
            try:
                cb(new)
            except Exception:  # pragma: no cover - observer must not kill
                _LOG.exception("breaker state observer failed")

    # ------------------------------------------------------------------
    def allow(self, probe: bool = False) -> bool:
        """Admission check: False while OPEN and cooling down; flips to
        HALF_OPEN (admitting ONE probe call) once the cooldown elapses.
        With `probe_reserved`, only probe=True callers may take the
        half-open slot."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() >= self._open_until and (
                        probe or not self.probe_reserved):
                    self._set_state(self.HALF_OPEN)
                    return True
                return False
            # HALF_OPEN: one probe already in flight; hold the rest back
            return False

    def record_success(self) -> None:
        reclosed = False
        with self._lock:
            self._consecutive_failures = 0
            if self._state != self.CLOSED:
                _LOG.info("circuit %s: probe succeeded, re-closing",
                          self._m_state.name)
                self._trips = 0
                reclosed = True
            self._set_state(self.CLOSED)
        if reclosed:
            flightrecorder.record("breaker_reclose", breaker=self.name)

    def record_failure(self, timeout: bool = False) -> None:
        (self._m_timeouts if timeout else self._m_failures).inc()
        with self._lock:
            self._consecutive_failures += 1
            should_trip = (
                self._state == self.HALF_OPEN
                or self._consecutive_failures >= self.failure_threshold)
            if should_trip:
                self._trips += 1
                self._m_trips.inc()
                cooldown = min(
                    self.base_cooldown_s * (2 ** (self._trips - 1)),
                    self.max_cooldown_s)
                self._open_until = self._clock() + cooldown
                newly_open = self._state != self.OPEN
                if newly_open:
                    _LOG.warning(
                        "circuit %s OPEN after %d consecutive "
                        "failures (cooldown %.1fs)", self._m_state.name,
                        self._consecutive_failures, cooldown)
                consecutive = self._consecutive_failures
                self._consecutive_failures = 0
                self._set_state(self.OPEN)
            else:
                return
        # outside the lock: the trip event (with the tripping verify's
        # trace id — dispatch runs under the caller's copied context)
        # and the automatic JSONL dump must not hold the breaker
        flightrecorder.record(
            "breaker_trip", breaker=self.name,
            consecutive_failures=consecutive,
            timeout=timeout, cooldown_s=round(cooldown, 1),
            reopened=not newly_open)
        flightrecorder.RECORDER.dump_throttled(
            f"breaker trip: {self.name}")

    # ------------------------------------------------------------------
    def call(self, fn: Callable, *args, probe: bool = False, **kwargs):
        """Run one guarded dispatch.  Raises CircuitOpenError without
        touching the device while the circuit is open; otherwise
        enforces the per-dispatch deadline and feeds the verdict back
        into the trip counters."""
        if not self.allow(probe=probe):
            raise CircuitOpenError(
                f"circuit open ({self._open_until - self._clock():.1f}s "
                "cooldown remaining)")
        box: dict = {}
        done = threading.Event()
        # carry the caller's context (tracing's current traces) into
        # the dispatch thread — a raw Thread drops contextvars, which
        # would detach device spans from the traces awaiting them
        ctx = contextvars.copy_context()

        def run():
            try:
                box["ok"] = ctx.run(fn, *args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 - reported below
                box["err"] = exc
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="breaker-dispatch")
        t.start()
        if not done.wait(self.deadline_s):
            self.record_failure(timeout=True)
            raise DispatchTimeoutError(
                f"dispatch exceeded {self.deadline_s:.1f}s deadline "
                "(wedged device runtime?)")
        if "err" in box:
            self.record_failure()
            raise box["err"]
        self.record_success()
        return box["ok"]


class BackendSupervisor(Service):
    """Owns backend bring-up and the READY/TRIPPED lifecycle.

    Pluggable hooks keep this module accelerator-agnostic (and make the
    fault-injection tests hermetic):

    - ``probe()``   (thread context) build + prove the device provider;
      returns an opaque backend handle.  Raises on failure.  The
      ``backend.init`` fault site fires here.
    - ``warmup(backend)`` (thread context, optional) pre-compile the hot
      programs so the first real batch doesn't stall (VERDICT round 5
      weak #3).
    - ``install(backend)`` hot-swap the facades to the device provider.
    - ``uninstall()`` (optional) restore the oracle on stop.

    The supervisor records every state transition with a timestamp in
    ``self.transitions`` — bench.py copies them into the heartbeat JSON
    so BENCH_*.json finally shows WHY a run served which backend.
    """

    _STATE_ORDER = (BackendState.COLD, BackendState.PROBING,
                    BackendState.WARMING, BackendState.READY,
                    BackendState.DEGRADED, BackendState.TRIPPED)

    def __init__(self, probe: Callable, install: Callable,
                 warmup: Optional[Callable] = None,
                 uninstall: Optional[Callable] = None,
                 reprobe: Optional[Callable] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 name: str = "bls_backend",
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 probe_attempts_per_round: int = 3,
                 probe_base_delay_s: float = 1.0,
                 round_delay_s: float = 5.0,
                 max_round_delay_s: float = 600.0,
                 max_rounds: Optional[int] = None,
                 warmup_deadline_s: float = 3600.0):
        super().__init__(name)
        self._probe = probe
        self._warmup = warmup
        self._install = install
        self._uninstall = uninstall
        # optional synthetic known-good device dispatch: when TRIPPED,
        # the supervisor drives half-open probing itself so no live
        # request is ever held hostage for a deadline_s probe
        self._reprobe = reprobe
        self._reprobe_task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if reprobe is not None and breaker is not None:
            # the supervisor owns half-open probing: reserve the slot
            # so live traffic is never held for a deadline_s probe
            breaker.probe_reserved = True
        self.breaker = breaker
        if breaker is not None:
            breaker.on_state_change = self._on_breaker_state
        self.probe_attempts_per_round = probe_attempts_per_round
        self.probe_base_delay_s = probe_base_delay_s
        self.round_delay_s = round_delay_s
        self.max_round_delay_s = max_round_delay_s
        self.max_rounds = max_rounds
        self.warmup_deadline_s = warmup_deadline_s
        self.backend = None
        self.backend_detail: str = ""
        # optional mesh self-description ({devices, n_devices, axis})
        # the loader's install hook stamps for multi-chip backends —
        # surfaced in snapshot() so readiness self-describes the mesh
        self.mesh: Optional[dict] = None
        # WARMING's compile-cache verdict ({"hits", "misses", "s"}):
        # a warm boot shows hits>0, misses==0 — the multi-minute
        # per-shape compiles were served from disk
        self.warmup_cache: dict = {}
        self.transitions: List[Tuple[str, float]] = []
        self._task: Optional[asyncio.Task] = None
        self._ready_event = asyncio.Event()
        self._m_state = registry.state_gauge(
            f"{name}_state", "backend supervisor state",
            states=tuple(s.value for s in self._STATE_ORDER))
        self._m_transitions = registry.counter(
            f"{name}_state_transitions_total",
            "supervisor state transitions")
        self._m_probe_failures = registry.counter(
            f"{name}_probe_failures_total", "failed bring-up probes")
        self._m_probe_seconds = registry.gauge(
            f"{name}_last_probe_seconds",
            "wall seconds of the last probe attempt")
        self.state_b = BackendState.COLD
        self._record(BackendState.COLD)

    # ------------------------------------------------------------------
    def _record(self, state: BackendState) -> None:
        self.state_b = state
        self.transitions.append((state.value, time.time()))
        self._m_state.set_state(state.value)
        self._m_transitions.inc()
        flightrecorder.record("backend_state", supervisor=self.name,
                              state=state.value,
                              detail=self.backend_detail)
        _LOG.info("backend supervisor %s: %s", self.name, state.value)

    def _on_breaker_state(self, breaker_state: str) -> None:
        """Breaker observer: OPEN ⇒ TRIPPED (oracle serving), re-CLOSED
        after READY ⇒ READY again.  Runs on whatever thread dispatched."""
        # edge-triggered: repeated HALF_OPEN→OPEN cycles of a
        # persistently sick device must not append duplicate 'tripped'
        # entries (transitions feed every heartbeat snapshot)
        if (breaker_state == CircuitBreaker.OPEN
                and self.state_b is BackendState.READY):
            self._record(BackendState.TRIPPED)
            if self._reprobe is not None and self._loop is not None:
                try:
                    self._loop.call_soon_threadsafe(
                        self._ensure_reprobe_task)
                except RuntimeError:  # pragma: no cover - shutdown
                    pass
        elif (breaker_state == CircuitBreaker.CLOSED
                and self.state_b is BackendState.TRIPPED):
            self._record(BackendState.READY)

    def _ensure_reprobe_task(self) -> None:
        if self._reprobe_task is None or self._reprobe_task.done():
            self._reprobe_task = asyncio.create_task(
                self._reprobe_loop(), name=f"{self.name}-reprobe")

    async def _reprobe_loop(self) -> None:
        """Half-open probing off the hot path: once the cooldown
        elapses, dispatch a synthetic known-good batch instead of
        letting a live verification absorb the deadline_s probe cost.
        Success re-closes the circuit (READY); failure re-opens it with
        the doubled cooldown and this loop waits again."""
        br = self.breaker
        while self.state_b is BackendState.TRIPPED:
            await asyncio.sleep(
                max(br._open_until - br._clock(), 0.2))
            if self.state_b is not BackendState.TRIPPED:
                break
            try:
                await self._in_daemon_thread(
                    lambda: br.call(self._reprobe, probe=True),
                    f"{self.name}-reprobe")
                _LOG.info("backend %s reprobe succeeded", self.name)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                _LOG.info("backend %s reprobe failed (%s: %s); circuit "
                          "stays open", self.name,
                          type(exc).__name__, exc)

    @property
    def backend_state(self) -> str:
        return self.state_b.value

    def snapshot(self) -> dict:
        """One JSON-able dict for heartbeats / the bench harness."""
        out = {"state": self.state_b.value,
               "detail": self.backend_detail,
               "transitions": [{"state": s, "t": round(t, 2)}
                               for s, t in self.transitions]}
        if self.breaker is not None:
            out["circuit"] = self.breaker.state
        if self.warmup_cache:
            out["warmup_cache"] = self.warmup_cache
        if self.mesh:
            out["mesh"] = self.mesh
        return out

    async def wait_ready(self, timeout_s: Optional[float] = None) -> bool:
        """Test/bench convenience: block until READY (or timeout)."""
        try:
            await asyncio.wait_for(self._ready_event.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            return False

    # ------------------------------------------------------------------
    async def do_start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.create_task(self._run(),
                                         name=f"{self.name}-supervisor")

    async def do_stop(self) -> None:
        for task_attr in ("_task", "_reprobe_task"):
            task = getattr(self, task_attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, task_attr, None)
        if self._uninstall is not None:
            try:
                self._uninstall()
            except Exception:  # pragma: no cover - best-effort restore
                _LOG.exception("backend uninstall failed")

    # ------------------------------------------------------------------
    @staticmethod
    async def _in_daemon_thread(fn: Callable, name: str):
        """Run `fn` in an explicit DAEMON thread (same containment as
        CircuitBreaker.call): asyncio.to_thread would use the default
        executor, whose non-daemon workers block process shutdown for
        as long as a wedged device init hangs — the exact ~25-minute
        wedge this module exists to contain."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def deliver(outcome, value):
            if fut.cancelled():
                return
            if outcome == "ok":
                fut.set_result(value)
            else:
                fut.set_exception(value)

        def run():
            try:
                result = ("ok", fn())
            except BaseException as exc:  # noqa: BLE001 - delivered
                result = ("err", exc)
            try:
                loop.call_soon_threadsafe(deliver, *result)
            except RuntimeError:  # pragma: no cover - loop shut down
                pass              # mid-hang: nobody left to deliver to
        threading.Thread(target=run, daemon=True, name=name).start()
        return await fut

    async def _probe_once(self):
        def run():
            # `backend.init` fault site runs IN the probe thread so a
            # SlowRamp models a slow plugin without blocking the loop
            faults.check("backend.init")
            return self._probe()

        t0 = time.monotonic()
        try:
            return await self._in_daemon_thread(
                run, f"{self.name}-probe")
        finally:
            self._m_probe_seconds.set(round(time.monotonic() - t0, 3))

    async def _run(self) -> None:
        self._record(BackendState.PROBING)
        rounds = 0
        delay = self.round_delay_s
        backend = None
        while True:
            try:
                # one bounded retry_with_backoff round; the OUTER loop is
                # the unbounded patience, each round observable via logs
                # and the probe-failure counter
                backend = await retry_with_backoff(
                    self._probe_once,
                    attempts=self.probe_attempts_per_round,
                    base_delay_s=self.probe_base_delay_s,
                    jitter=0.25, what=f"{self.name} probe",
                    giveup=lambda e: isinstance(
                        e, (ImportError, ModuleNotFoundError)))
                break
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                rounds += 1
                self._m_probe_failures.inc()
                non_retryable = isinstance(
                    exc.__cause__, (ImportError, ModuleNotFoundError))
                if non_retryable or (self.max_rounds is not None
                                     and rounds >= self.max_rounds):
                    self.backend_detail = (
                        f"bring-up abandoned after {rounds} round(s): "
                        f"{exc.__cause__ or exc}")
                    _LOG.warning(
                        "backend %s DEGRADED (oracle is permanent): %s",
                        self.name, self.backend_detail)
                    self._record(BackendState.DEGRADED)
                    return
                _LOG.warning(
                    "backend %s probe round %d failed (%s); retrying "
                    "in %.1fs", self.name, rounds, exc.__cause__ or exc,
                    delay)
                await asyncio.sleep(delay)
                delay = min(delay * 2, self.max_round_delay_s)
        self._record(BackendState.WARMING)
        if self._warmup is not None:
            # WARMING pays the hot-program compiles off-path; the
            # persistent compile cache decides whether that costs
            # minutes (fresh compiles) or seconds (cache loads) —
            # report which, so a slow bring-up explains itself
            cache_before = compilecache.stats()
            aot_before = aotstore.stats()
            warm_t0 = time.monotonic()
            try:
                # bounded: WARMING must not become the one phase that
                # can wedge forever (probing retries, READY has the
                # breaker).  On deadline the orphaned thread keeps
                # compiling and we install anyway — a still-wedged
                # device then trips the breaker, whose reprobe cycle
                # owns recovery from there
                await asyncio.wait_for(
                    self._in_daemon_thread(
                        lambda: self._warmup(backend),
                        f"{self.name}-warmup"),
                    self.warmup_deadline_s)
            except asyncio.TimeoutError:
                _LOG.warning(
                    "backend %s warmup exceeded %.0fs; installing "
                    "anyway (breaker owns a wedged device)",
                    self.name, self.warmup_deadline_s)
            except asyncio.CancelledError:
                raise
            except WarmupVetoError as exc:
                # the device executed but got a KNOWN answer wrong:
                # installing it would degrade correctness, not latency
                self.backend_detail = f"warmup veto: {exc}"
                _LOG.error("backend %s DEGRADED (untrusted device, "
                           "oracle is permanent): %s", self.name, exc)
                self._record(BackendState.DEGRADED)
                return
            except Exception:
                _LOG.exception("backend warmup failed; installing "
                               "anyway (first batch compiles lazily)")
            moved = compilecache.delta(cache_before)
            aot_moved = aotstore.delta(aot_before)
            self.warmup_cache = {
                "hits": moved["hits"], "misses": moved["misses"],
                # AOT-store loads skip XLA entirely; kernel_compiles
                # counts the backend compiles above the kernel-grade
                # threshold this warmup actually performed — the
                # "warm boot does zero fresh compiles" observable
                "aot_loads": aot_moved["loads"],
                "backend_compiles": moved["backend_compiles"],
                "kernel_compiles": moved["kernel_compiles"],
                "s": round(time.monotonic() - warm_t0, 1)}
            flightrecorder.record("warmup_cache", supervisor=self.name,
                                  **self.warmup_cache)
            _LOG.info(
                "backend %s warmup in %.1fs: %d AOT load(s), %d "
                "compile-cache load(s), %d fresh compile(s) (%d "
                "kernel-grade)%s", self.name,
                self.warmup_cache["s"], aot_moved["loads"],
                moved["hits"], moved["misses"],
                moved["kernel_compiles"],
                "" if compilecache.cache_dir() else
                " (persistent cache not configured)")
        self.backend = backend
        self._install(backend)
        self._record(BackendState.READY)
        self._ready_event.set()
