"""Curated operator-facing loggers.

Equivalent of the reference's logging module (reference: infrastructure/
logging/src/main/java/tech/pegasys/teku/infrastructure/logging/
StatusLogger.java, EventLogger.java, ValidatorLogger.java): named
channels with consistent, human-scannable slot/epoch event lines, on
top of stdlib logging so operators configure handlers normally.

``--log-format json`` switches every record to one JSON object per
line, each carrying the ACTIVE TRACE ID from `infra/tracing.py`'s
ContextVar — so log lines, slow traces (`/teku/v1/admin/traces`), and
flight-recorder events all correlate on one id without any call-site
changes.
"""

import json
import logging
import time

STATUS = logging.getLogger("teku_tpu.status")
EVENTS = logging.getLogger("teku_tpu.events")
VALIDATOR = logging.getLogger("teku_tpu.validator")
P2P = logging.getLogger("teku_tpu.p2p")


class JsonFormatter(logging.Formatter):
    """One JSON object per record, trace-correlated.

    The trace id is read at FORMAT time from the emitting context, so a
    WARN inside a gossip validator's root span (or inside the breaker's
    dispatch thread, which copies the context) carries the id of the
    verification that logged it."""

    def format(self, record: logging.LogRecord) -> str:
        from . import tracing
        out = {
            "t": round(record.created, 3),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                 time.localtime(record.created)),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        trace_id = tracing.current_trace_id()
        if trace_id:
            out["trace_id"] = trace_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def _make_formatter(fmt: str) -> logging.Formatter:
    if fmt == "json":
        return JsonFormatter()
    return logging.Formatter(
        "%(asctime)s | %(levelname)-5s | %(name)s | %(message)s",
        datefmt="%H:%M:%S")


def configure(level: int = logging.INFO, fmt: str = "text") -> None:
    """Console setup with the reference's log line flavor, or one JSON
    object per line when ``fmt == "json"``.  Re-invoking with a new
    format reformats in place — but ONLY the handlers this function
    created (marked): an embedding application's own handlers keep
    their formatters (and an embedder that owns every handler simply
    isn't reformatted — it owns its log config)."""
    if fmt not in ("text", "json"):
        raise ValueError(f"unknown log format {fmt!r} (text or json)")
    root = logging.getLogger()
    if root.handlers:
        root.setLevel(level)
        for handler in root.handlers:
            if getattr(handler, "_teku_tpu_managed", False):
                handler.setFormatter(_make_formatter(fmt))
        return
    handler = logging.StreamHandler()
    handler._teku_tpu_managed = True
    handler.setFormatter(_make_formatter(fmt))
    root.addHandler(handler)
    root.setLevel(level)


def log_slot_event(slot: int, epoch: int, head_root: bytes,
                   justified_epoch: int, finalized_epoch: int,
                   peers: int = 0) -> None:
    """reference EventLogger.epochEvent/slotEvent format."""
    EVENTS.info(
        "Slot Event  *** Slot: %d, Block: %s, Justified: %d, "
        "Finalized: %d, Peers: %d (epoch %d)",
        slot, head_root.hex()[:16], justified_epoch, finalized_epoch,
        peers, epoch)


def log_finalized(epoch: int, root: bytes) -> None:
    EVENTS.info("Finalized checkpoint updated *** Epoch: %d, Root: %s",
                epoch, root.hex()[:16])
