"""Curated operator-facing loggers.

Equivalent of the reference's logging module (reference: infrastructure/
logging/src/main/java/tech/pegasys/teku/infrastructure/logging/
StatusLogger.java, EventLogger.java, ValidatorLogger.java): named
channels with consistent, human-scannable slot/epoch event lines, on
top of stdlib logging so operators configure handlers normally.
"""

import logging

STATUS = logging.getLogger("teku_tpu.status")
EVENTS = logging.getLogger("teku_tpu.events")
VALIDATOR = logging.getLogger("teku_tpu.validator")
P2P = logging.getLogger("teku_tpu.p2p")


def configure(level: int = logging.INFO) -> None:
    """Console setup with the reference's log line flavor."""
    root = logging.getLogger()
    if root.handlers:
        root.setLevel(level)
        return
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(
        "%(asctime)s | %(levelname)-5s | %(name)s | %(message)s",
        datefmt="%H:%M:%S"))
    root.addHandler(handler)
    root.setLevel(level)


def log_slot_event(slot: int, epoch: int, head_root: bytes,
                   justified_epoch: int, finalized_epoch: int,
                   peers: int = 0) -> None:
    """reference EventLogger.epochEvent/slotEvent format."""
    EVENTS.info(
        "Slot Event  *** Slot: %d, Block: %s, Justified: %d, "
        "Finalized: %d, Peers: %d (epoch %d)",
        slot, head_root.hex()[:16], justified_epoch, finalized_epoch,
        peers, epoch)


def log_finalized(epoch: int, root: bytes) -> None:
    EVENTS.info("Finalized checkpoint updated *** Epoch: %d, Root: %s",
                epoch, root.hex()[:16])
