"""Step-timer performance trackers for hot paths.

Equivalent of the reference's per-operation performance objects
(reference: ethereum/statetransition/.../block/
BlockImportPerformance.java and ethereum/performance-trackers/
BlockProductionPerformanceImpl.java — lazy flows of named timestamps,
logged only when over threshold): cheap monotonic checkpoints threaded
through an operation, one log line when the total breaches the budget.
"""

import logging
import time
from typing import List, Optional, Tuple

_LOG = logging.getLogger("teku_tpu.perf")


class StepTimer:
    """`timer.mark("name")` after each stage; `complete()` logs a
    breakdown if the total exceeded `threshold_ms`."""

    def __init__(self, what: str, threshold_ms: float = 500.0,
                 enabled: bool = True):
        self.what = what
        self.threshold_ms = threshold_ms
        self.enabled = enabled
        self._t0 = time.perf_counter() if enabled else 0.0
        self._marks: List[Tuple[str, float]] = []

    def mark(self, name: str) -> None:
        if self.enabled:
            self._marks.append((name, time.perf_counter()))

    def complete(self) -> Optional[float]:
        """Returns total ms (None when disabled)."""
        if not self.enabled:
            return None
        end = time.perf_counter()
        total_ms = (end - self._t0) * 1e3
        if total_ms >= self.threshold_ms:
            prev = self._t0
            parts = []
            for name, t in self._marks:
                parts.append(f"{name}={((t - prev) * 1e3):.0f}ms")
                prev = t
            _LOG.warning("%s slow: total=%.0fms %s", self.what, total_ms,
                         " ".join(parts))
        return total_ms
