"""Persistent XLA compile cache: wire-up + hit/miss observability.

Cold XLA compiles of the staged verify kernel cost 314-357 s PER
BUCKET SHAPE on CPU (tens of minutes projected on TPU) and were paid
again on every boot.  The compiles are deterministic in (program,
shape, flags), so JAX's persistent compilation cache
(``jax_compilation_cache_dir``) turns every boot after the first into
cache LOADS — warm boots skip the compile entirely.

``configure()`` is called by ``cli node`` / ``cli devnet`` and bench.py
(ON BY DEFAULT; opt out with TEKU_TPU_XLA_CACHE_DIR=off).  It is safe
in both import orders: before jax is imported it sets the JAX_* env
vars the config reads at definition time; after, it updates jax.config
directly.  Nothing here initializes a backend — boot stays O(1).

Observability: a jax.monitoring listener counts the runtime's
``/jax/compilation_cache/cache_hits|cache_misses`` events into
``xla_compile_cache_total{outcome="hit"|"miss"}`` and a process-local
snapshot API — ``ops/provider.py`` diffs snapshots around the first
dispatch of a bucket shape to split its jit outcome into ``compile``
(fresh XLA work) vs ``cache_load`` (served from disk), and the backend
supervisor's WARMING stage reports how much of the warmup was cache
hits vs fresh compiles.
"""

import logging
import os
import sys
import threading

from . import clock
from .env import env_float, env_str
from .metrics import GLOBAL_REGISTRY

_LOG = logging.getLogger(__name__)

ENV_DIR = "TEKU_TPU_XLA_CACHE_DIR"
ENV_MIN_COMPILE_S = "TEKU_TPU_XLA_CACHE_MIN_COMPILE_S"
ENV_KERNEL_COMPILE_S = "TEKU_TPU_KERNEL_COMPILE_MIN_S"
ENV_COMPILE_SPAN_MIN_S = "TEKU_TPU_COMPILE_SPAN_MIN_S"
_OFF_VALUES = ("off", "0", "none", "disabled")

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_counts = {"hit": 0, "miss": 0}
# backend compiles: EVERY XLA backend_compile this process performed,
# with durations.  `kernel` counts only compiles >= the kernel-grade
# threshold — a fresh process op-by-op-dispatches a handful of
# millisecond micro programs (jnp.asarray, arena scatter) no store
# can eliminate, so "zero fresh compiles at warm boot" is defined over
# KERNEL-grade compiles (PERF.md documents the definition); raw counts
# stay visible alongside.
_compiles = {"count": 0, "seconds": 0.0, "kernel": 0}
_installed = {"listener": False, "dir": None}
# clock-spine stamp of the most recent cache event: the timeline
# orders "which dispatch paid that cache load" against trace spans
_last_event = {"outcome": None, "t_wall": None, "t_mono": None}

_M_CACHE = GLOBAL_REGISTRY.labeled_counter(
    "xla_compile_cache_total",
    "persistent XLA compile cache lookups by outcome",
    labelnames=("outcome",))
_M_BACKEND = GLOBAL_REGISTRY.labeled_counter(
    "xla_backend_compile_total",
    "XLA backend compiles this process performed, by grade "
    "(kernel = duration >= TEKU_TPU_KERNEL_COMPILE_MIN_S, micro = "
    "op-by-op dispatch of trivial host programs)",
    labelnames=("grade",))


def default_dir() -> str:
    """Repo-adjacent default (shared with the driver entry hooks)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, ".jax_cache")


def _on_event(event: str, **_kw) -> None:
    if event == _HIT_EVENT:
        key = "hit"
    elif event == _MISS_EVENT:
        key = "miss"
    else:
        return
    stamp = clock.stamp({"outcome": key})
    with _lock:
        _counts[key] += 1
        _last_event.update(stamp)
    _M_CACHE.labels(outcome=key).inc()


_cfg: dict = {}


def _compile_cfg() -> dict:
    """Lazy knob reads (memoized; tests clear _cfg around
    env_override).  kernel_s splits kernel-grade compiles from
    micro-op dispatch; span_s floors timeline compile spans so
    micro compiles don't flood the ring."""
    if not _cfg:
        _cfg["kernel_s"] = env_float(ENV_KERNEL_COMPILE_S, 1.0,
                                     lo=0.0)
        _cfg["span_s"] = env_float(ENV_COMPILE_SPAN_MIN_S, 0.05,
                                   lo=0.0)
    return _cfg


def _on_compile_duration(event: str, duration: float, **_kw) -> None:
    if event != _COMPILE_EVENT:
        return
    cfg = _compile_cfg()
    kernel = duration >= cfg["kernel_s"]
    with _lock:
        _compiles["count"] += 1
        _compiles["seconds"] += duration
        if kernel:
            _compiles["kernel"] += 1
    _M_BACKEND.labels(grade="kernel" if kernel else "micro").inc()
    if duration >= cfg["span_s"]:
        # first-class compile span on the shared clock spine: the
        # attribution window sees the TRUE in-window compile overlap
        # instead of clamping ledger-side enqueue seconds at 1.0
        from . import timeline, tracing
        # emit-at-completion: the listener fires when the backend
        # compile returns, so the interval ends NOW
        timeline.interval("worker", "compile", duration,
                          trace_id=tracing.current_trace_id())


def ensure_instrumented() -> bool:
    """Register the monitoring listeners (idempotent).  Imports jax,
    so callers on the boot path defer this until jax is loaded
    anyway."""
    with _lock:
        if _installed["listener"]:
            return True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - jax-less host tooling
        return False
    with _lock:
        if not _installed["listener"]:
            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(
                _on_compile_duration)
            _installed["listener"] = True
    return True


def configure(cache_dir=None, min_compile_s=None, enabled=True):
    """Wire the persistent cache; returns the cache dir or None (off).

    Precedence: explicit args > env (TEKU_TPU_XLA_CACHE_DIR /
    TEKU_TPU_XLA_CACHE_MIN_COMPILE_S) > defaults (on, repo-adjacent
    dir, 2 s minimum compile time so trivial programs don't churn the
    disk).  TEKU_TPU_XLA_CACHE_DIR=off disables.
    """
    env_dir = env_str(ENV_DIR)
    if cache_dir is None:
        cache_dir = env_dir
    if (not enabled or (cache_dir is not None
                        and str(cache_dir).lower() in _OFF_VALUES)):
        # the off switch must actually turn a previously-enabled cache
        # OFF, not just stop reporting it
        if "jax" in sys.modules:
            import jax
            try:
                if getattr(jax.config, "jax_compilation_cache_dir",
                           None):
                    jax.config.update("jax_compilation_cache_dir", None)
                    from jax._src import compilation_cache as _cc
                    _cc.reset_cache()
            except Exception:  # pragma: no cover - internal API drift
                pass
        else:
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        _installed["dir"] = None
        return None
    if cache_dir is None:
        cache_dir = default_dir()
    if min_compile_s is None:
        min_compile_s = env_float(ENV_MIN_COMPILE_S, 2.0, lo=0.0)
    settings = {
        "jax_compilation_cache_dir": str(cache_dir),
        "jax_persistent_cache_min_compile_time_secs": min_compile_s,
        "jax_persistent_cache_min_entry_size_bytes": -1,
    }
    if "jax" in sys.modules:
        import jax
        dir_changed = (
            getattr(jax.config, "jax_compilation_cache_dir", None)
            != str(cache_dir))
        for key, value in settings.items():
            try:
                jax.config.update(key, value)
            except Exception:  # pragma: no cover - old/new jax drift
                _LOG.warning("compile cache: jax has no config %s", key)
        if dir_changed:
            # jax binds its cache OBJECT to the dir at first use; a
            # config update alone leaves reads/writes on the old dir
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:  # pragma: no cover - internal API drift
                pass
        ensure_instrumented()
    else:
        # jax not imported yet (cli boot path): the env vars are read
        # when jax.config defines these options, so this wires the
        # cache without paying the jax import here.  The listener is
        # installed by whichever component imports jax first and asks
        # for stats (provider module import / bench / supervisor).
        os.environ["JAX_COMPILATION_CACHE_DIR"] = str(cache_dir)
        os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = \
            str(min_compile_s)
        os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "-1"
    _installed["dir"] = str(cache_dir)
    _LOG.info("persistent XLA compile cache: %s", cache_dir)
    return str(cache_dir)


def cache_dir():
    """The configured dir (None when off/unconfigured)."""
    return _installed["dir"]


def stats() -> dict:
    """Process-local cache counters (one JSON-able dict)."""
    if "jax" in sys.modules:
        ensure_instrumented()
    with _lock:
        return {"dir": _installed["dir"], "hits": _counts["hit"],
                "misses": _counts["miss"],
                "backend_compiles": _compiles["count"],
                "backend_compile_s": round(_compiles["seconds"], 6),
                "kernel_compiles": _compiles["kernel"],
                "last_event": dict(_last_event)}


def delta(before: dict, after=None) -> dict:
    """Counter movement between two stats() snapshots (``.get`` so
    pre-existing snapshots without the backend-compile keys diff)."""
    if after is None:
        after = stats()
    out = {"hits": after["hits"] - before["hits"],
           "misses": after["misses"] - before["misses"]}
    for key in ("backend_compiles", "kernel_compiles"):
        out[key] = after.get(key, 0) - before.get(key, 0)
    out["backend_compile_s"] = round(
        after.get("backend_compile_s", 0.0)
        - before.get("backend_compile_s", 0.0), 6)
    return out


def classify_first_dispatch(d: dict, aot=None) -> str:
    """Jit outcome for the FIRST dispatch of a shape, from the cache
    delta observed around it (and optionally the AOT-store delta):
    pure disk hits -> ``cache_load``; serialized-executable loads
    with NO persistent-cache traffic at all -> ``aot_load``; any
    fresh XLA work (or no cache/store) -> ``compile``."""
    if d["hits"] > 0 and d["misses"] == 0:
        return "cache_load"
    if (aot and aot.get("loads", 0) > 0 and d["hits"] == 0
            and d["misses"] == 0):
        return "aot_load"
    return "compile"
