"""Typed in-process event channels — the node's message bus.

Equivalent of the reference's EventChannels dynamic-proxy pub/sub
(reference: infrastructure/events/src/main/java/tech/pegasys/teku/
infrastructure/events/EventChannels.java and EventChannel.java:58-142):
a channel is declared as a Python Protocol-style class; `publisher()`
returns a proxy whose method calls fan out to every subscriber, either
synchronously (DirectEventDeliverer) or queued onto the event loop
(AsyncEventDeliverer).  Errors in one subscriber never break the
publisher or other subscribers.
"""

import asyncio
import inspect
import logging
from typing import Callable, Dict, List, Optional, Type, TypeVar

_LOG = logging.getLogger(__name__)

T = TypeVar("T")


class _Proxy:
    def __init__(self, channels: "EventChannels", iface: type,
                 async_delivery: bool):
        self._channels = channels
        self._iface = iface
        self._async = async_delivery

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if not hasattr(self._iface, name):
            raise AttributeError(
                f"{self._iface.__name__} has no event {name}")

        def dispatch(*args, **kwargs):
            subs = self._channels._subscribers.get(self._iface, [])
            for sub in list(subs):
                fn = getattr(sub, name, None)
                if fn is None:
                    continue
                if self._async:
                    loop = self._channels._loop or asyncio.get_event_loop()
                    loop.call_soon_threadsafe(
                        _safe_call, fn, args, kwargs)
                else:
                    _safe_call(fn, args, kwargs)
        return dispatch


def _safe_call(fn: Callable, args, kwargs) -> None:
    try:
        result = fn(*args, **kwargs)
        if inspect.iscoroutine(result):
            task = asyncio.ensure_future(result)
            task.add_done_callback(lambda t: _log_task_error(t, fn))
    except Exception:
        _LOG.exception("event subscriber %r failed", fn)


def _log_task_error(task: "asyncio.Task", fn: Callable) -> None:
    if not task.cancelled() and task.exception() is not None:
        _LOG.error("async event subscriber %r failed", fn,
                   exc_info=task.exception())


class EventChannels:
    """Registry of channel interfaces → subscriber lists."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        self._subscribers: Dict[type, List[object]] = {}
        self._loop = loop

    def subscribe(self, iface: Type[T], subscriber: T) -> "EventChannels":
        self._subscribers.setdefault(iface, []).append(subscriber)
        return self

    def unsubscribe(self, iface: Type[T], subscriber: T) -> None:
        """Detach a subscriber (SSE connections come and go; a
        permanent registration would leak one sink per client)."""
        subs = self._subscribers.get(iface)
        if subs is not None and subscriber in subs:
            subs.remove(subscriber)

    def publisher(self, iface: Type[T], async_delivery: bool = False) -> T:
        return _Proxy(self, iface, async_delivery)  # type: ignore


# ---- standard channel interfaces (reference: *Channel interfaces) ----

class SlotEventsChannel:
    """reference: ethereum/statetransition SlotEventsChannel."""

    def on_slot(self, slot: int) -> None: ...


class FinalizedCheckpointChannel:
    def on_new_finalized_checkpoint(self, checkpoint, from_optimistic_api=False) -> None: ...


class ChainHeadChannel:
    def on_chain_head_updated(self, slot: int, root: bytes,
                              reorg: bool) -> None: ...


class BlockImportChannel:
    def on_block_imported(self, signed_block, post_state) -> None: ...


class AttestationReceivedChannel:
    def on_attestation(self, attestation) -> None: ...
