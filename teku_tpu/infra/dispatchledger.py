"""Dispatch decision ledger: per-dispatch cost attribution.

Every verify dispatch is the product of a stack of runtime decisions —
the admission controller's batch plan and brownout level, the dedup
grouping and H(m) cache state, the MSM path resolution, the mesh shard
plan, pow-2 bucket padding, and the compile-vs-cache outcome — but
until this module nothing tied them together: when the
``attestation_verify_p50`` budget burns, the SLO engine blames a trace
id while the REASONS (a new shape compiled cold, a shard's makespan
skewed, padding waste spiked, msm auto demoted) were scattered across
logs, gauges, and WARNs.

This is the ordered record: a process-global bounded ring of
structured per-dispatch records, populated by
``ops/provider.py:_begin_dispatch`` (decision context) and completed
by ``_DispatchHandle.result()`` (sync duration, overlap-corrected
device time, verdict).  Each record captures:

- the originating trace ids (slow-trace ring entries and SLO breach
  events link to the exact record on this key);
- lanes real/padded and rows real/padded: padding waste SPLIT BY
  STAGE BUCKET (the lane bucket the scalars/finish stages pay vs the
  unique-h2c/Miller row bucket the dedup pipeline pays) plus the
  per-dispatch dedup ratio;
- H(m) arena hits/misses and the h2c dispatch bucket actually paid;
- the resolved msm path AND why (``ops/msm.py:explain`` — the auto
  rule's inputs);
- the resolved mesh plan (device count, per-shard row/lane loads,
  makespan ratio = max shard lane load / mean);
- the compile outcome (compile | cache_load | cache_hit) with the
  enqueue duration that paid it;
- the admission context the service annotated (plan mode, brownout
  level, verify-class mix, flush-failsafe firing) via the
  ``annotate()`` ContextVar — ``asyncio.to_thread`` copies the
  context, so the worker-thread dispatch sees the event-loop's plan.

Derived bounded-label metrics (linted in test_metrics_exposition):

- ``bls_dispatch_padding_waste_ratio{stage}`` — cumulative dead
  fraction per stage bucket (``stage`` in the closed {lane, h2c} set;
  the lane series is the pre-PR-13 unlabeled gauge's semantics);
- ``bls_mesh_shard_imbalance_ratio`` — the most recent mesh
  dispatch's makespan ratio (1.0 = perfectly balanced shards);
- ``bls_dispatch_decision_total{msm_path,mesh,plan_mode}`` — the
  decision histogram (closed vocabularies: {ladder, pippenger} x
  {0, pow-2 device counts} x {none, latency, throughput, brownout1,
  brownout2}).

The ring is served by ``GET /teku/v1/admin/dispatches`` (``?last=N``,
``?trace_id=``, ``?slow=1``), summarized per bench phase into
``BENCH_*.json``, and read by the ``cli doctor`` explainability engine
(infra/doctor.py).  Like the flight recorder, the ledger is
process-global on purpose: dispatches originate in worker threads and
breaker dispatch threads, and the value of the ring IS one timeline.
"""

import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Dict, List, Optional, Tuple

from . import clock
from .env import env_int
from .metrics import GLOBAL_REGISTRY, MetricsRegistry

# degrade-never-fail: this module imports on every node boot (via the
# provider and the batching service) — a typo'd capacity must fall
# back to the default, not refuse to start the node
DEFAULT_CAPACITY = max(
    1, env_int("TEKU_TPU_DISPATCH_LEDGER_CAPACITY", 256))

# the closed {stage} vocabulary of the padding-waste family: `lane` is
# the batch-lane bucket (scalars/finish stages), `h2c` the unique-
# message row bucket (hash-to-curve + Miller stages)
WASTE_STAGES = ("lane", "h2c")

# the closed {plan_mode} vocabulary: the admission controller's batch
# mode, with an active brownout superseding (brownout level N implies
# the controller is in throughput mode by construction)
PLAN_MODES = ("none", "latency", "throughput", "brownout1", "brownout2")


# --------------------------------------------------------------------------
# Service-side annotation: how the admission plan reaches the record
# --------------------------------------------------------------------------

_ANNOTATIONS: ContextVar[dict] = ContextVar(
    "teku_tpu_dispatch_annotations", default={})


@contextmanager
def annotate(**fields):
    """Bind dispatch-record annotations to the current context for the
    duration of the block (the batching service wraps each dispatch
    with its plan mode / brownout level / class mix; the provider's
    ``_begin_dispatch`` merges ``current_annotations()`` into the
    record it opens).  ``asyncio.to_thread`` copies the context, so
    the worker-thread dispatch still sees the annotations."""
    token = _ANNOTATIONS.set({**_ANNOTATIONS.get(), **fields})
    try:
        yield
    finally:
        _ANNOTATIONS.reset(token)


def current_annotations() -> dict:
    return dict(_ANNOTATIONS.get())


def plan_mode_label(mode: Optional[str], brownout_level) -> str:
    """Fold (plan mode, brownout level) into the closed {plan_mode}
    label vocabulary — an active brownout supersedes the batch mode."""
    try:
        level = int(brownout_level or 0)
    except (TypeError, ValueError):
        level = 0
    if level >= 1:
        return f"brownout{min(level, 2)}"
    return mode if mode in ("latency", "throughput") else "none"


def decision_key(rec: dict) -> Tuple[str, str, str]:
    """ONE definition of a record's (msm_path, mesh devices,
    plan_mode) decision tuple — the bls_dispatch_decision_total label
    set AND the summarize() decisions histogram key; a second
    hand-rolled copy would let the Prometheus series and the
    endpoint/bench histograms silently diverge."""
    return (str((rec.get("msm") or {}).get("path", "ladder")),
            str((rec.get("mesh") or {}).get("devices", 0) or 0),
            plan_mode_label(
                (rec.get("admission") or {}).get("plan_mode"),
                (rec.get("admission") or {}).get("brownout_level")))


# --------------------------------------------------------------------------
# The ledger
# --------------------------------------------------------------------------

class DispatchLedger:
    """Bounded, thread-safe ring of JSON-able per-dispatch records."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: MetricsRegistry = GLOBAL_REGISTRY):
        self.capacity = capacity
        self._records: deque = deque(maxlen=capacity)
        self._seq = 0
        self._lock = threading.Lock()
        # cumulative stage-bucket padding accounting: the gauge must be
        # the all-time ratio (like the pre-ledger unlabeled gauge), not
        # the ring-window ratio, so long-running dashboards keep their
        # semantics while the ring stays bounded
        self._real = {s: 0 for s in WASTE_STAGES}
        self._padded = {s: 0 for s in WASTE_STAGES}
        self._last_imbalance = 0.0
        self._m_waste = registry.labeled_gauge(
            "bls_dispatch_padding_waste_ratio",
            "fraction of dispatched slots that were pow-2 padding, by "
            "stage bucket (lane = batch lanes, h2c = unique-message "
            "rows)", labelnames=("stage",))
        for s in WASTE_STAGES:        # complete family from scrape 1
            self._m_waste.labels(stage=s).set(0.0)
        self._m_imbalance = registry.gauge(
            "bls_mesh_shard_imbalance_ratio",
            "makespan ratio (max shard lane load / mean) of the most "
            "recent mesh dispatch; 1.0 = balanced, 0 = no mesh "
            "dispatch yet", supplier=lambda: self._last_imbalance)
        self._m_decision = registry.labeled_counter(
            "bls_dispatch_decision_total",
            "verify dispatches by resolved decision tuple: scalars "
            "path x mesh device count x admission plan mode",
            labelnames=("msm_path", "mesh", "plan_mode"))

    # ------------------------------------------------------------------
    def record(self, rec: dict) -> dict:
        """Append one COMPLETED dispatch record (the provider assembles
        it across _begin_dispatch and the handle's result()) and update
        the derived metrics.  Returns the record with its seq."""
        waste = rec.get("waste") or {}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._records.append(rec)
            for stage in WASTE_STAGES:
                w = waste.get(stage) or {}
                real, padded = w.get("real"), w.get("padded")
                if isinstance(real, (int, float)) \
                        and isinstance(padded, (int, float)) \
                        and padded > 0:
                    self._real[stage] += real
                    self._padded[stage] += padded
                    self._m_waste.labels(stage=stage).set(round(
                        (self._padded[stage] - self._real[stage])
                        / self._padded[stage], 6))
            mesh = rec.get("mesh") or {}
            if mesh.get("devices"):
                ratio = mesh.get("makespan_ratio")
                if isinstance(ratio, (int, float)) and ratio > 0:
                    self._last_imbalance = float(ratio)
        msm_path, mesh_devices, plan_mode = decision_key(rec)
        self._m_decision.labels(
            msm_path=msm_path, mesh=mesh_devices,
            plan_mode=plan_mode).inc()
        return rec

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._seq

    def snapshot(self, last: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 slow: bool = False) -> List[dict]:
        """Records oldest-first.  ``trace_id`` filters to records whose
        dispatch carried that trace; ``slow`` filters to records linked
        to the slow-trace ring's current entries; ``last`` tails the
        (filtered) list."""
        with self._lock:
            records = list(self._records)
        if trace_id:
            records = [r for r in records
                       if trace_id in (r.get("trace_ids") or ())]
        if slow:
            from . import tracing
            slow_ids = {t["trace_id"] for t in tracing.slow_traces()}
            records = [r for r in records
                       if slow_ids & set(r.get("trace_ids") or ())]
        return records[-last:] if last else records

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def summary(self, since_seq: int = 0) -> dict:
        """Aggregate view of the ring (records with seq > since_seq) —
        what bench embeds per phase and the doctor reads first.  A
        window that outgrew the ring is flagged: ``evicted`` counts
        the records whose seq is in range but which the bounded ring
        already dropped, so a bench phase summary (and the bench_diff
        gates on it) can never silently claim full coverage."""
        with self._lock:
            # one lock: a dispatch recorded between a snapshot and a
            # separate _seq read would be falsely reported as evicted
            records = list(self._records)
            seq = self._seq
        out = summarize(records, since_seq=since_seq)
        evicted = (seq - since_seq) - out["records"]
        if evicted > 0:
            out["evicted"] = evicted
        return out


def summarize(records: List[dict], since_seq: int = 0) -> dict:
    """Pure aggregation over ledger records: per-stage waste, dedup,
    shard imbalance, decision/compile histograms, h2c cache totals.
    Shared by the bench per-phase summaries, the admin endpoint, and
    the doctor engine (which also gets it for REMOTE records fetched
    over the admin API)."""
    records = [r for r in records if r.get("seq", 0) > since_seq]
    out: dict = {"records": len(records)}
    real = {s: 0 for s in WASTE_STAGES}
    padded = {s: 0 for s in WASTE_STAGES}
    lanes = uniq = 0
    decisions: Dict[str, int] = {}
    compile_hist: Dict[str, int] = {}
    compile_s = 0.0
    h2c_hits = h2c_misses = 0
    imb: List[float] = []
    by_bucket: Dict[int, List[int]] = {}
    for r in records:
        for stage in WASTE_STAGES:
            w = (r.get("waste") or {}).get(stage) or {}
            if isinstance(w.get("padded"), (int, float)) \
                    and w["padded"] > 0:
                real[stage] += w.get("real", 0)
                padded[stage] += w["padded"]
        lanes += r.get("lanes", 0)
        uniq += r.get("unique_messages", 0)
        lane_w = (r.get("waste") or {}).get("lane") or {}
        if lane_w.get("padded"):
            by_bucket.setdefault(int(lane_w["padded"]), []).append(
                int(lane_w.get("real", 0)))
        key = "|".join(decision_key(r))
        decisions[key] = decisions.get(key, 0) + 1
        comp = r.get("compile") or {}
        outcome = comp.get("outcome")
        if outcome:
            compile_hist[outcome] = compile_hist.get(outcome, 0) + 1
            if outcome in ("compile", "cache_load", "aot_load"):
                compile_s += comp.get("enqueue_s", 0.0)
        h2c = r.get("h2c") or {}
        h2c_hits += h2c.get("cache_hits", 0)
        h2c_misses += h2c.get("cache_misses", 0)
        ratio = (r.get("mesh") or {}).get("makespan_ratio")
        if isinstance(ratio, (int, float)) and ratio > 0:
            imb.append(float(ratio))
    out["padding_waste"] = {
        s: (round((padded[s] - real[s]) / padded[s], 4)
            if padded[s] else None) for s in WASTE_STAGES}
    out["padding_waste_by_lane_bucket"] = {
        str(b): round((b * len(rs) - sum(rs)) / (b * len(rs)), 4)
        for b, rs in sorted(by_bucket.items())}
    out["dedup_ratio"] = (round((lanes - uniq) / lanes, 4)
                          if lanes else None)
    out["decisions"] = dict(sorted(decisions.items()))
    out["compile"] = dict(sorted(compile_hist.items()))
    out["compile_s"] = round(compile_s, 3)
    out["h2c_cache"] = {"hits": h2c_hits, "misses": h2c_misses}
    out["mesh_imbalance"] = {
        "max": round(max(imb), 4) if imb else None,
        "mean": round(sum(imb) / len(imb), 4) if imb else None,
        "dispatches": len(imb)}
    return out


# the process-wide ledger every provider instance records into
LEDGER = DispatchLedger()


def record(rec: dict) -> dict:
    return LEDGER.record(rec)


def open_record(**fields) -> dict:
    """Start a record at dispatch-begin time: wall stamp + the
    service-side annotations active in the calling context."""
    ann = current_annotations()
    # the shared (t_wall, t_mono) clock-spine stamp (infra/clock.py):
    # t_wall keeps its historical form, t_mono joins the record to the
    # timeline's mono axis
    rec = clock.stamp({})
    rec["admission"] = ann
    rec.update(fields)
    return rec
