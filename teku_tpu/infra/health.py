"""Node health & SLO engine: "is this node healthy, and if not, WHERE?"

PR 2 gave the node per-stage latency attribution; this module turns
those histograms (plus the supervisor/breaker/queue state PR 1 built)
into something an operator or autoscaler can act on:

- ``HealthRegistry``: named per-subsystem ``HealthCheck``s, each
  returning UP/DEGRADED/DOWN with a human detail line, aggregated
  worst-wins to the node verdict behind ``/eth/v1/node/health``
  (200/206/503) and ``/teku/v1/admin/readiness``.  Status changes are
  EDGE-TRIGGERED: one flip = one log line + one flight-recorder event
  + one ``health_transitions_total`` increment, never a per-tick spam.
- ``EventLoopLagWatchdog``: measures asyncio scheduling delay (sleep
  `interval_s`, compare the loop clock) — a blocked event loop is the
  one failure every other check silently shares.
- ``SloEngine``: declared objectives evaluated on a periodic tick from
  the LIVE metrics registry (no offline bench needed).  Every objective
  reduces to cumulative ``(good, total)`` event counts; per tick the
  engine takes the delta and computes the burn rate

      burn = (bad_fraction_this_window) / (1 - target_ratio)

  — the standard error-budget form: 1.0 means exactly consuming
  budget, >1.0 is a breach.  A p50-latency objective is the same
  arithmetic with target_ratio=0.5 and good = "samples ≤ target
  latency" read from the histogram buckets, so "verify p50 over
  target" and "success ratio under target" share one code path.

The reference's analogue is external (Grafana burn-rate alerts over the
Besu metrics); committee-based-consensus measurements (PAPERS: EdDSA/
BLS in committee consensus) show verify-latency tails gate attestation
inclusion directly, which is why these SLOs run *inside* the node.

Thresholds are env-tunable (documented in README/PERF):
``TEKU_TPU_SLO_VERIFY_P50_MS``, ``TEKU_TPU_SLO_VERIFY_SUCCESS_RATIO``,
``TEKU_TPU_SLO_DEVICE_RATIO``, ``TEKU_TPU_LOOP_LAG_DEGRADED_S``,
``TEKU_TPU_LOOP_LAG_DOWN_S``, ``TEKU_TPU_HEALTH_UTIL_DEGRADED``
(capacity-model utilization; defaults to the brownout entry
threshold), ``TEKU_TPU_HEALTH_QUEUE_SAT_DEGRADED`` (raw full-queue
backstop), ``TEKU_TPU_HEALTH_WORKER_STALL_S``,
``TEKU_TPU_HEALTH_TICK_S``.
"""

import asyncio
import enum
import logging
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from . import flightrecorder, tracing
from .env import env_float as _env_float
from .metrics import GLOBAL_REGISTRY, MetricsRegistry

_LOG = logging.getLogger(__name__)


class HealthStatus(enum.Enum):
    UP = "up"
    DEGRADED = "degraded"
    DOWN = "down"


_SEVERITY = {HealthStatus.UP: 0, HealthStatus.DEGRADED: 1,
             HealthStatus.DOWN: 2}


@dataclass
class CheckResult:
    status: HealthStatus
    detail: str = ""


# --------------------------------------------------------------------------
# Health registry
# --------------------------------------------------------------------------

class HealthRegistry:
    """Named subsystem checks aggregated worst-wins to one verdict.

    A check is any zero-arg callable returning a CheckResult (or a bare
    HealthStatus).  A RAISING check reads as DOWN — a prober that
    cannot even run is evidence of sickness, not a reason to 500 the
    health endpoint."""

    STATES = tuple(s.value for s in HealthStatus)

    def __init__(self, name: str = "node",
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 recorder: Optional[flightrecorder.FlightRecorder] = None):
        self.name = name
        self._checks: Dict[str, Callable[[], CheckResult]] = {}
        self._last: Dict[str, CheckResult] = {}
        self._last_aggregate: Optional[HealthStatus] = None
        self._recorder = recorder or flightrecorder.RECORDER
        # fn(subject, old_status_or_None, result) on every edge
        self.listeners: List[Callable] = []
        # every family carries a `node` label: the families are
        # process-global (get_or_create) but devnets run N nodes in
        # one process, and node B's DOWN must not be overwritten by
        # node A's next evaluate()
        self._m_state = registry.labeled_gauge(
            "health_node_state", "aggregate node health (worst check "
            "wins): 1 on the series matching the current state",
            labelnames=("node", "state"))
        self._m_checks = registry.labeled_gauge(
            "health_check_state",
            "per-subsystem health: 1 on the series matching the "
            "check's current state",
            labelnames=("node", "check", "state"))
        self._m_flips = registry.labeled_counter(
            "health_transitions_total",
            "edge-triggered health state changes per check "
            "('node' = the aggregate)",
            labelnames=("node", "check"))

    def register(self, name: str,
                 fn: Callable[[], CheckResult]) -> None:
        if name in self._checks:
            raise ValueError(f"health check {name!r} already registered")
        self._checks[name] = fn

    def check_names(self) -> List[str]:
        return list(self._checks)

    # ------------------------------------------------------------------
    def _run_check(self, name: str, fn) -> CheckResult:
        try:
            res = fn()
        except Exception as exc:  # noqa: BLE001 - sick prober = sick
            return CheckResult(
                HealthStatus.DOWN,
                f"check raised {type(exc).__name__}: {exc}")
        if isinstance(res, HealthStatus):
            return CheckResult(res)
        return res

    def _flip(self, subject: str, old: Optional[HealthStatus],
              result: CheckResult) -> None:
        # first evaluation establishing UP is not an event; booting
        # straight into DEGRADED/DOWN is
        if old is None and result.status is HealthStatus.UP:
            return
        self._m_flips.labels(node=self.name, check=subject).inc()
        level = (logging.WARNING
                 if _SEVERITY[result.status] > _SEVERITY.get(old, 0)
                 else logging.INFO)
        _LOG.log(level, "health %s/%s: %s -> %s (%s)", self.name,
                 subject, old.value if old else "?",
                 result.status.value, result.detail or "no detail")
        self._recorder.record(
            "health_flip", subject=subject,
            **{"from": old.value if old else None,
               "to": result.status.value, "detail": result.detail})
        for listener in self.listeners:
            try:
                listener(subject, old, result)
            except Exception:  # pragma: no cover - observer must not kill
                _LOG.exception("health listener failed")

    def evaluate(self) -> HealthStatus:
        """Run every check, update metrics, fire edges; returns the
        aggregate.  Cheap enough for on-request use by the REST layer
        AND the periodic tick — edges are idempotent across both."""
        results = {name: self._run_check(name, fn)
                   for name, fn in self._checks.items()}
        aggregate = HealthStatus.UP
        for name, res in results.items():
            if _SEVERITY[res.status] > _SEVERITY[aggregate]:
                aggregate = res.status
            for state in self.STATES:
                self._m_checks.labels(
                    node=self.name, check=name, state=state).set(
                    1.0 if state == res.status.value else 0.0)
            prev = self._last.get(name)
            if prev is None or prev.status is not res.status:
                self._flip(name, prev.status if prev else None, res)
        self._last = results
        for state in self.STATES:
            self._m_state.labels(node=self.name, state=state).set(
                1.0 if state == aggregate.value else 0.0)
        if aggregate is not self._last_aggregate:
            detail = "; ".join(
                f"{n}: {r.detail or r.status.value}"
                for n, r in results.items()
                if r.status is not HealthStatus.UP) or "all checks up"
            self._flip("node", self._last_aggregate,
                       CheckResult(aggregate, detail))
            self._last_aggregate = aggregate
        return aggregate

    def snapshot(self) -> dict:
        """Last evaluation as JSON (the /teku/v1/admin/readiness body)."""
        return {
            "status": (self._last_aggregate or HealthStatus.UP).value,
            "checks": {name: {"status": res.status.value,
                              "detail": res.detail}
                       for name, res in self._last.items()}}


# --------------------------------------------------------------------------
# Event-loop-lag watchdog
# --------------------------------------------------------------------------

class EventLoopLagWatchdog:
    """Scheduling-delay sampler: sleep `interval_s` on the loop and
    measure the overshoot.  A CPU-bound handler (or a device call that
    escaped its to_thread) shows up as lag here before it shows up
    anywhere else.  The health verdict reads the WORST lag over the
    last `window` samples, so one long stall stays visible for a few
    seconds instead of vanishing at the next good tick."""

    def __init__(self, interval_s: float = 0.25,
                 degraded_s: Optional[float] = None,
                 down_s: Optional[float] = None, window: int = 8,
                 name: str = "node",
                 registry: MetricsRegistry = GLOBAL_REGISTRY):
        self.interval_s = interval_s
        self.degraded_s = (degraded_s if degraded_s is not None else
                           _env_float("TEKU_TPU_LOOP_LAG_DEGRADED_S",
                                      0.2))
        self.down_s = (down_s if down_s is not None else
                       _env_float("TEKU_TPU_LOOP_LAG_DOWN_S", 2.0))
        self._samples: deque = deque(maxlen=window)
        self._task: Optional[asyncio.Task] = None
        # a labeled child updated per sample, NOT a supplier gauge:
        # get_or_create would pin the family to the FIRST watchdog's
        # supplier, silently never exporting later nodes' lag
        self._m_lag = registry.labeled_gauge(
            "health_event_loop_lag_seconds",
            "worst recent asyncio scheduling lag",
            labelnames=("node",)).labels(node=name)

    @property
    def lag_s(self) -> float:
        return max(self._samples, default=0.0)

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval_s)
            self._samples.append(
                max(0.0, loop.time() - t0 - self.interval_s))
            self._m_lag.set(self.lag_s)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(
                self._run(), name="event-loop-lag-watchdog")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def check(self) -> CheckResult:
        if self._task is None:
            return CheckResult(HealthStatus.UP, "watchdog not running")
        if self._task.done():
            # started but died (external cancel sweep, sampler bug):
            # frozen samples must not keep reporting a green loop
            return CheckResult(HealthStatus.DEGRADED,
                               "watchdog task died; lag unknown")
        lag = self.lag_s
        if lag >= self.down_s:
            return CheckResult(HealthStatus.DOWN,
                               f"event loop lag {lag:.3f}s")
        if lag >= self.degraded_s:
            return CheckResult(HealthStatus.DEGRADED,
                               f"event loop lag {lag:.3f}s")
        return CheckResult(HealthStatus.UP, f"lag {lag * 1e3:.1f}ms")


# --------------------------------------------------------------------------
# SLO engine
# --------------------------------------------------------------------------

@dataclass
class SloObjective:
    """One declared objective.  `sample()` returns CUMULATIVE
    (good_events, total_events); the engine windows by delta between
    ticks.  `target_ratio` is the fraction that must be good — 0.5 for
    a p50-latency objective, 0.99 for a success ratio."""

    name: str
    description: str
    target_ratio: float
    sample: Callable[[], Tuple[float, float]]


def histogram_good_total(child_getter: Callable, le_s: float
                         ) -> Tuple[float, float]:
    """(samples ≤ le_s, total) from a histogram child's cumulative
    buckets — the bucket boundary at or below `le_s` bounds `good`
    conservatively (a mid-bucket target under-counts good, never
    over-counts)."""
    child = child_getter()
    counts, _sum, total = child.snapshot()
    good = 0
    cum = 0
    for i, ub in enumerate(child.buckets):
        cum += counts[i]
        if ub <= le_s:
            good = cum
    return float(good), float(total)


def labeled_counter_good_total(family, good_pred) -> Tuple[float, float]:
    """(sum of children matching good_pred(labels_dict), sum of all)
    over a LabeledCounter family."""
    good = total = 0.0
    for key, child in family._items():
        labels = dict(zip(family.labelnames, key))
        total += child.value
        if good_pred(labels):
            good += child.value
    return good, total


def default_slo_objectives(registry: MetricsRegistry = GLOBAL_REGISTRY
                           ) -> List[SloObjective]:
    """The ROADMAP's north-star objectives, read from the metrics the
    hot path already populates (tracing + the guarded BLS facade).

    Caveat: these source families are process-global (the hot path
    carries no node label), so in a multi-node process (devnet) every
    node's engine windows the COMBINED traffic — one node's failures
    raise every node's burn.  Production topology is one node per
    process, where the families and the node are the same thing; the
    per-node `node` label on the slo_* output series exists so the
    devnet case at least stays distinguishable per engine."""
    p50_target_s = _env_float("TEKU_TPU_SLO_VERIFY_P50_MS", 100.0) / 1e3
    success_target = _env_float("TEKU_TPU_SLO_VERIFY_SUCCESS_RATIO",
                                0.99)
    device_target = _env_float("TEKU_TPU_SLO_DEVICE_RATIO", 0.0)
    stage_hist = registry.labeled_histogram(
        "verify_stage_duration_seconds",
        "per-stage latency attribution of the verification pipeline",
        labelnames=("stage",))
    requests = registry.labeled_counter(
        "bls_verify_requests_total",
        "guarded BLS dispatches by serving backend and reason",
        labelnames=("backend", "reason"))
    return [
        SloObjective(
            name="attestation_verify_p50",
            description=f"p50 end-to-end verify latency ≤ "
                        f"{p50_target_s * 1e3:.0f}ms",
            target_ratio=0.5,
            sample=lambda: histogram_good_total(
                lambda: stage_hist.labels(stage="complete"),
                p50_target_s)),
        SloObjective(
            name="verify_success_ratio",
            description=f"≥ {success_target:.2%} of guarded verifies "
                        "served without breaker/fallback",
            target_ratio=success_target,
            sample=lambda: labeled_counter_good_total(
                requests, lambda l: l.get("reason") == "ok")),
        SloObjective(
            name="device_serving_ratio",
            description=f"≥ {device_target:.0%} of guarded verifies "
                        "served by the device backend",
            target_ratio=device_target,
            sample=lambda: labeled_counter_good_total(
                requests, lambda l: l.get("backend") == "device")),
    ]


class SloEngine:
    """Periodic burn-rate evaluation with edge-triggered breach events.

    Each tick windows every objective's cumulative (good, total) by
    delta, computes burn = bad_fraction / (1 - target_ratio), exports
    ``slo_burn_rate{objective=...}``, and on a breach EDGE records an
    ``slo_breach`` flight-recorder event carrying the originating trace
    id (the context's current trace, else the last traced failure the
    recorder saw — e.g. the verify whose dispatch tripped the breaker).
    A window with fewer than `min_samples` new events holds the
    previous verdict instead of swinging on noise."""

    def __init__(self, objectives: Optional[List[SloObjective]] = None,
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 recorder: Optional[flightrecorder.FlightRecorder] = None,
                 min_samples: int = 1, name: str = "node"):
        self.objectives = (objectives if objectives is not None
                           else default_slo_objectives(registry))
        self.name = name
        self._recorder = recorder or flightrecorder.RECORDER
        self.min_samples = max(1, min_samples)
        self._prev: Dict[str, Tuple[float, float]] = {}
        self._burn: Dict[str, float] = {}
        self._in_breach: Dict[str, bool] = {}
        # windows evaluated per objective: 0 means the objective has
        # never had evidence (e.g. the latency objective with
        # --tracing off) — surfaced in snapshot() so a dark objective
        # cannot masquerade as a green one
        self._windows: Dict[str, int] = {}
        self._m_burn = registry.labeled_gauge(
            "slo_burn_rate",
            "error-budget burn rate per objective over the last tick "
            "window (1.0 = consuming exactly the budget, >1 = breach)",
            labelnames=("node", "objective"))
        self._m_breached = registry.labeled_gauge(
            "slo_breached", "1 while the objective is in breach",
            labelnames=("node", "objective"))
        self._m_breaches = registry.labeled_counter(
            "slo_breaches_total", "edge-triggered SLO breach events",
            labelnames=("node", "objective"))

    # ------------------------------------------------------------------
    def tick(self) -> dict:
        for obj in self.objectives:
            try:
                good, total = obj.sample()
            except Exception:
                _LOG.warning("SLO %s sample failed", obj.name,
                             exc_info=True)
                continue
            prev_good, prev_total = self._prev.get(obj.name, (0.0, 0.0))
            d_good = good - prev_good
            d_total = total - prev_total
            self._prev[obj.name] = (good, total)
            if d_total < self.min_samples:
                continue        # no new evidence: hold the last verdict
            self._windows[obj.name] = self._windows.get(obj.name, 0) + 1
            bad_fraction = min(1.0, max(0.0, 1.0 - d_good / d_total))
            budget = max(1e-9, 1.0 - obj.target_ratio)
            burn = bad_fraction / budget
            self._burn[obj.name] = burn
            self._m_burn.labels(node=self.name, objective=obj.name
                                ).set(round(burn, 6))
            # strict >: a zero-budget-headroom objective (target 0)
            # reads fully-bad traffic as burn == 1.0, not a breach
            breached = burn > 1.0 + 1e-9
            self._m_breached.labels(node=self.name, objective=obj.name
                                    ).set(1.0 if breached else 0.0)
            was = self._in_breach.get(obj.name, False)
            if breached and not was:
                self._m_breaches.labels(node=self.name,
                                        objective=obj.name).inc()
                trace_id = (tracing.current_trace_id()
                            or self._recorder.last_trace_id())
                self._recorder.record(
                    "slo_breach", trace_id=trace_id,
                    objective=obj.name, burn_rate=round(burn, 3),
                    detail=obj.description)
                _LOG.warning("SLO BREACH %s: burn %.2f (%s)",
                             obj.name, burn, obj.description)
            elif was and not breached:
                self._recorder.record(
                    "slo_recovery", objective=obj.name,
                    burn_rate=round(burn, 3))
                _LOG.info("SLO recovered %s: burn %.2f", obj.name, burn)
            self._in_breach[obj.name] = breached
        return self.snapshot()

    def burn_rate(self, objective: str) -> float:
        """Last evaluated burn for one objective (0.0 before evidence)
        — the admission controller's feedback input."""
        return self._burn.get(objective, 0.0)

    def snapshot(self) -> dict:
        return {obj.name: {
            "description": obj.description,
            "target_ratio": obj.target_ratio,
            "burn_rate": round(self._burn.get(obj.name, 0.0), 4),
            "breached": self._in_breach.get(obj.name, False),
            # 0 = the objective has never seen a data window (dark,
            # not green) — e.g. the latency objective under
            # --tracing off, whose source histogram never observes
            "windows": self._windows.get(obj.name, 0)}
            for obj in self.objectives}

    def check(self) -> CheckResult:
        """Health-check adapter: any in-breach objective degrades."""
        breached = [n for n, b in self._in_breach.items() if b]
        if breached:
            return CheckResult(
                HealthStatus.DEGRADED,
                "SLO breach: " + ", ".join(sorted(breached)))
        return CheckResult(HealthStatus.UP, "all objectives within "
                                            "budget")


# --------------------------------------------------------------------------
# Check factories for the node's subsystems
# --------------------------------------------------------------------------

def supervisor_check(supervisor_getter: Callable) -> Callable[[], CheckResult]:
    """Backend supervisor + circuit breaker as one check: TRIPPED /
    DEGRADED (oracle-permanent) / an open breaker all read DEGRADED —
    the node stays CORRECT on the oracle, only latency degrades, which
    maps to 206 on the health endpoint, never 503."""
    def check() -> CheckResult:
        sup = supervisor_getter()
        if sup is None:
            return CheckResult(HealthStatus.UP,
                               "no supervisor (static backend)")
        state = sup.backend_state
        if state == "tripped":
            return CheckResult(
                HealthStatus.DEGRADED,
                "circuit open, oracle serving "
                f"({sup.backend_detail or 'breaker trip'})")
        if state == "degraded":
            return CheckResult(
                HealthStatus.DEGRADED,
                f"bring-up abandoned, oracle permanent "
                f"({sup.backend_detail or 'no detail'})")
        breaker = getattr(sup, "breaker", None)
        if breaker is not None and breaker.state != "closed" \
                and state == "ready":
            return CheckResult(HealthStatus.DEGRADED,
                               f"breaker {breaker.state}")
        return CheckResult(HealthStatus.UP, f"backend {state}")
    return check


def signature_service_check(service,
                            utilization_degraded: Optional[float] = None,
                            stall_down_s: Optional[float] = None
                            ) -> Callable[[], CheckResult]:
    """Signature-pipeline saturation + worker stall.

    Saturation is read from the CAPACITY MODEL embedded in the
    service's health snapshot (``capacity_model.utilization`` —
    demand / sustainable throughput), not the raw queue depth: depth
    lags the overload it signals (a queue only backs up after capacity
    is already exhausted), and the brownout controller keys on the
    same utilization signal — so DEGRADED here and brownout there flip
    on ONE measurement instead of two drifting ones.  The default
    threshold IS the brownout entry threshold
    (``TEKU_TPU_BROWNOUT_UTIL_ENTER``, override with
    ``TEKU_TPU_HEALTH_UTIL_DEGRADED``).  A physically full queue still
    degrades as a backstop (utilization can read low before dispatch
    evidence exists; ``TEKU_TPU_HEALTH_QUEUE_SAT_DEGRADED``, default
    0.95).  Queued work with no worker progress for
    `stall_down_s` means verdicts are not being produced (DOWN)."""
    util_limit = (utilization_degraded
                  if utilization_degraded is not None
                  else _env_float(
                      "TEKU_TPU_HEALTH_UTIL_DEGRADED",
                      _env_float("TEKU_TPU_BROWNOUT_UTIL_ENTER", 1.0)))
    sat_limit = _env_float("TEKU_TPU_HEALTH_QUEUE_SAT_DEGRADED", 0.95)
    stall_limit = (stall_down_s if stall_down_s is not None
                   else _env_float("TEKU_TPU_HEALTH_WORKER_STALL_S",
                                   30.0))

    def check() -> CheckResult:
        snap = service.health_snapshot()
        if snap["stalled_s"] >= stall_limit:
            return CheckResult(
                HealthStatus.DOWN,
                f"workers stalled {snap['stalled_s']:.1f}s with "
                f"{snap['queue_size']} tasks queued")
        model = snap.get("capacity_model") or {}
        util = model.get("utilization", 0.0)
        headroom = model.get("headroom_ratio", 1.0)
        if util >= util_limit:
            return CheckResult(
                HealthStatus.DEGRADED,
                f"demand at {util:.0%} of sustainable capacity "
                f"(headroom {headroom:.0%}, queue "
                f"{snap['queue_size']}/{snap['capacity']})")
        if snap["saturation"] >= sat_limit:
            # backstop: a queue at its hard bound is shedding-imminent
            # even while the model is still gathering evidence
            return CheckResult(
                HealthStatus.DEGRADED,
                f"queue {snap['queue_size']}/{snap['capacity']} "
                f"({snap['saturation']:.0%} full)")
        return CheckResult(
            HealthStatus.UP,
            f"utilization {util:.0%}, queue "
            f"{snap['queue_size']}/{snap['capacity']}")
    return check


def admission_controller_check(controller_getter: Callable
                               ) -> Callable[[], CheckResult]:
    """Overload-controller health: brownout (any level) reads DEGRADED
    — the node is still correct, it is deliberately shedding the
    lowest classes to protect BLOCK_IMPORT latency — with the level,
    shed classes, and the driving signals in the detail line."""
    def check() -> CheckResult:
        ctl = controller_getter()
        if ctl is None:
            return CheckResult(HealthStatus.UP,
                               "no admission controller (fixed policy)")
        snap = ctl.snapshot()
        brown = snap["brownout"]
        if brown["level"] >= 1:
            return CheckResult(
                HealthStatus.DEGRADED,
                f"brownout level {brown['level']}: shedding "
                f"{'+'.join(brown['shedding']) or 'nothing'} "
                f"(util {snap['inputs']['utilization']:.2f}, burn "
                f"{snap['inputs']['burn_rate']:.2f})")
        return CheckResult(
            HealthStatus.UP,
            f"batch {snap['plan']['batch_size']}, util "
            f"{snap['inputs']['utilization']:.2f}")
    return check


def staleness_check(last_seen_getter: Callable[[], Optional[float]],
                    degraded_s: float, what: str,
                    clock: Callable[[], float] = time.monotonic
                    ) -> Callable[[], CheckResult]:
    """Generic freshness check: DEGRADED once `what` has not been seen
    for `degraded_s` (None = never seen yet, reads UP with detail —
    silence before the first event is boot, not sickness)."""
    def check() -> CheckResult:
        last = last_seen_getter()
        if last is None:
            return CheckResult(HealthStatus.UP, f"no {what} yet")
        age = clock() - last
        if age >= degraded_s:
            return CheckResult(HealthStatus.DEGRADED,
                               f"last {what} {age:.0f}s ago")
        return CheckResult(HealthStatus.UP,
                           f"last {what} {age:.1f}s ago")
    return check
