"""Async primitives: the node's concurrency discipline.

Equivalent of the reference's infrastructure/async module (reference:
infrastructure/async/src/main/java/tech/pegasys/teku/infrastructure/
async/SafeFuture.java, ThrottlingTaskQueue.java, eventthread/
EventThread.java): everything runs as awaitables on ONE asyncio loop
(the analogue of the reference's named runners + event-thread
confinement), with throttling queues for bounded concurrency and an
ordered queue for single-writer subsystems like fork choice.
"""

import asyncio
import logging
import random
import time
from typing import Awaitable, Callable, Optional, TypeVar

_LOG = logging.getLogger(__name__)

T = TypeVar("T")


def finish(awaitable: Awaitable, error_msg: str = "task failed") -> asyncio.Task:
    """Fire-and-forget with error channeling — the reference's
    SafeFuture.finish(err -> LOG) idiom: failures are logged, never
    silently dropped."""
    task = asyncio.ensure_future(awaitable)

    def _done(t: asyncio.Task):
        if not t.cancelled() and t.exception() is not None:
            _LOG.error("%s: %r", error_msg, t.exception())
    task.add_done_callback(_done)
    return task


class ThrottlingTaskQueue:
    """At most `limit` tasks in flight; the rest queue (reference
    ThrottlingTaskQueue.java — used to bound state regeneration etc.)."""

    def __init__(self, limit: int, name: str = "queue"):
        self._sem = asyncio.Semaphore(limit)
        self.name = name
        self.queued = 0

    async def run(self, fn: Callable[[], Awaitable[T]]) -> T:
        self.queued += 1
        try:
            async with self._sem:
                return await fn()
        finally:
            self.queued -= 1


class OrderedTaskQueue:
    """Strictly serialized execution — the single-writer discipline the
    reference enforces with its fork-choice EventThread (reference:
    infrastructure/async/eventthread/EventThread.java); here a lock on
    the one loop plus an owner assert for checkOnEventThread parity."""

    def __init__(self, name: str = "ordered"):
        self._lock = asyncio.Lock()
        self.name = name
        self._owner: Optional[asyncio.Task] = None

    async def run(self, fn: Callable[[], Awaitable[T]]) -> T:
        async with self._lock:
            self._owner = asyncio.current_task()
            try:
                return await fn()
            finally:
                self._owner = None

    def check_in_queue(self) -> None:
        assert self._owner is asyncio.current_task(), (
            f"not running inside ordered queue {self.name}")


class RepeatingTask:
    """Fixed-interval async timer (reference: RepeatingTaskScheduler /
    the quartz TimerService driving slot events)."""

    def __init__(self, interval_s: float,
                 fn: Callable[[], Awaitable[None]],
                 name: str = "repeating"):
        self.interval_s = interval_s
        self.fn = fn
        self.name = name
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop(), name=self.name)

    async def _loop(self) -> None:
        while True:
            t0 = time.monotonic()
            try:
                await self.fn()
            except asyncio.CancelledError:
                raise
            except Exception:
                _LOG.exception("%s tick failed", self.name)
            elapsed = time.monotonic() - t0
            await asyncio.sleep(max(0.0, self.interval_s - elapsed))

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


async def retry_with_backoff(fn: Callable[[], Awaitable[T]],
                             attempts: int = 3, base_delay_s: float = 0.5,
                             what: str = "operation",
                             jitter: float = 0.0,
                             max_delay_s: float = 60.0,
                             retry_on: tuple = (Exception,),
                             giveup: Optional[Callable[
                                 [BaseException], bool]] = None) -> T:
    """Bounded exponential-backoff retry (the reference's
    FailedExecutionPool / RetryingStorageUpdateChannel pattern).

    `jitter` adds up to that fraction of random extra delay so fleets
    of retriers don't synchronize; `retry_on` narrows which exceptions
    are transient — anything else propagates immediately (a malformed
    response must fail loudly, not get three more chances); `giveup`
    inspects a caught exception and aborts the remaining attempts when
    it returns True (e.g. ImportError: no amount of retrying installs
    a missing package)."""
    last: Optional[BaseException] = None
    made = 0
    for i in range(attempts):
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except retry_on as exc:
            last = exc
            made = i + 1
            if giveup is not None and giveup(exc):
                break
            if i + 1 < attempts:
                delay = min(base_delay_s * (2 ** i), max_delay_s)
                if jitter:
                    delay *= 1.0 + random.random() * jitter
                await asyncio.sleep(delay)
    raise RuntimeError(
        f"{what} failed after {made} attempt(s)") from last
