"""The ONE clock spine for every observability surface.

The tree grew four timestamp dialects — tracing spans on
``perf_counter``, flight-recorder events on ``time.time()``,
dispatch-ledger records on a rounded ``t_wall``, capacity/occupancy
intervals on raw ``perf_counter`` stamps — and records from different
surfaces could not be ordered against each other: wall clocks step
(NTP), monotonic clocks have an arbitrary epoch, and each module
rounded differently.  This module is the single definition:

- ``now()`` returns the shared ``(t_wall, t_mono)`` pair — one wall
  read and one monotonic read taken back-to-back.  Every record that
  wants to be joinable on the causal timeline carries BOTH: ``t_wall``
  for humans and cross-process merge, ``t_mono`` for intra-process
  ordering and gap-free interval arithmetic;
- ``stamp(rec)`` writes the pair into a dict with the tree's
  established rounding (wall ms, mono µs) — the helper tracing,
  flightrecorder, dispatchledger, capacity, selfheal and compilecache
  all stamp through, so the rounding contract has one home;
- ``wall_of(t_mono)`` / ``mono_of(t_wall)`` convert through the
  process anchor (the pair captured at import): exporters place
  monotonic-only stamps (trace stage offsets, occupancy intervals)
  on the wall axis with at-import skew, which is exact for ordering
  within one process — the only join this module promises.

``t_mono`` is ``time.perf_counter()`` — the SAME base tracing and the
capacity occupancy tracker already use, so adopting the spine did not
re-base any existing stamp.  ``time.monotonic()`` callers (the mesh
healer's recovery stopwatch) must convert by duration, never by
subtracting across bases.
"""

import time
from typing import Dict, Tuple

# Process anchor: the (t_wall, t_mono) correspondence every
# mono<->wall conversion routes through.  Captured once at import —
# a stable mapping matters more than tracking NTP steps, because the
# timeline orders records by t_mono and only LABELS them with wall
# time.
ANCHOR_WALL = time.time()
ANCHOR_MONO = time.perf_counter()


def now() -> Tuple[float, float]:
    """The shared ``(t_wall, t_mono)`` stamp pair, read back-to-back."""
    return time.time(), time.perf_counter()


def mono() -> float:
    """The spine's monotonic clock (``perf_counter`` base)."""
    return time.perf_counter()


def stamp(rec: Dict) -> Dict:
    """Stamp ``rec`` in place with the shared pair — ``t_wall``
    rounded to ms (the ledger/flight-recorder precedent, human-facing)
    and ``t_mono`` rounded to µs (interval arithmetic)."""
    t_wall, t_mono = now()
    rec["t_wall"] = round(t_wall, 3)
    rec["t_mono"] = round(t_mono, 6)
    return rec


def wall_of(t_mono: float) -> float:
    """Place a monotonic stamp on the wall axis via the anchor."""
    return ANCHOR_WALL + (t_mono - ANCHOR_MONO)


def mono_of(t_wall: float) -> float:
    """Place a wall stamp on the monotonic axis via the anchor."""
    return ANCHOR_MONO + (t_wall - ANCHOR_WALL)


def anchor_dict() -> Dict[str, float]:
    """The process anchor as a JSON-able block — snapshots carry it so
    remote consumers can convert the payload's ``t_mono`` stamps."""
    return {"t_wall": round(ANCHOR_WALL, 6),
            "t_mono": round(ANCHOR_MONO, 6)}
