"""Bounded collections: LRU-limited set and map.

Equivalent of the reference's LimitedSet/LimitedMap (reference:
infrastructure/collections/src/main/java/tech/pegasys/teku/
infrastructure/collections/LimitedSet.java, LimitedMap.java) — the
containers behind every seen-message cache, sized so long-running nodes
cannot grow without bound.
"""

from collections import OrderedDict
from typing import Generic, Hashable, Iterator, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class LimitedSet(Generic[K]):
    def __init__(self, max_size: int):
        assert max_size > 0
        self._max = max_size
        self._items: "OrderedDict[K, None]" = OrderedDict()

    def add(self, item: K) -> bool:
        """Returns True if newly added (touches LRU order either way)."""
        if item in self._items:
            self._items.move_to_end(item)
            return False
        self._items[item] = None
        if len(self._items) > self._max:
            self._items.popitem(last=False)
        return True

    def __contains__(self, item: K) -> bool:
        return item in self._items

    def __len__(self) -> int:
        return len(self._items)

    def discard(self, item: K) -> None:
        self._items.pop(item, None)


class LimitedMap(Generic[K, V]):
    def __init__(self, max_size: int):
        assert max_size > 0
        self._max = max_size
        self._items: "OrderedDict[K, V]" = OrderedDict()

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        if key in self._items:
            self._items.move_to_end(key)
            return self._items[key]
        return default

    def put(self, key: K, value: V) -> None:
        self._items[key] = value
        self._items.move_to_end(key)
        if len(self._items) > self._max:
            self._items.popitem(last=False)

    def __contains__(self, key: K) -> bool:
        return key in self._items

    def pop(self, key: K, default: Optional[V] = None) -> Optional[V]:
        return self._items.pop(key, default)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator[K]:
        return iter(self._items)
