"""Bounded collections: LRU-limited set and map.

Equivalent of the reference's LimitedSet/LimitedMap (reference:
infrastructure/collections/src/main/java/tech/pegasys/teku/
infrastructure/collections/LimitedSet.java, LimitedMap.java) — the
containers behind every seen-message cache, sized so long-running nodes
cannot grow without bound.
"""

import threading
from collections import OrderedDict
from typing import Generic, Hashable, Iterator, Optional, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class LimitedSet(Generic[K]):
    def __init__(self, max_size: int):
        assert max_size > 0
        self._max = max_size
        self._items: "OrderedDict[K, None]" = OrderedDict()

    def add(self, item: K) -> bool:
        """Returns True if newly added (touches LRU order either way)."""
        if item in self._items:
            self._items.move_to_end(item)
            return False
        self._items[item] = None
        if len(self._items) > self._max:
            self._items.popitem(last=False)
        return True

    def __contains__(self, item: K) -> bool:
        return item in self._items

    def __len__(self) -> int:
        return len(self._items)

    def discard(self, item: K) -> None:
        self._items.pop(item, None)


class LimitedMap(Generic[K, V]):
    def __init__(self, max_size: int, on_evict=None):
        """`on_evict(key, value)` fires per LRU eviction (NOT explicit
        pops) — the hook the verify-path caches hang their shared
        eviction-counter metric on, so a hot cache evicting warm
        entries one by one is observable instead of silently churning
        (the old wholesale `.clear()` at the bound was worse: it dumped
        every warm entry at once and caused re-validation storms)."""
        assert max_size > 0
        self._max = max_size
        self._on_evict = on_evict
        # get/put are compound (lookup + move_to_end + popitem): the
        # verify-path caches are hit from concurrent dispatch worker
        # threads, where an unlocked interleaving can move_to_end a key
        # another thread just evicted (KeyError) or double-evict
        self._lock = threading.Lock()
        self._items: "OrderedDict[K, V]" = OrderedDict()

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        with self._lock:
            if key in self._items:
                self._items.move_to_end(key)
                return self._items[key]
            return default

    def put(self, key: K, value: V) -> None:
        evicted = None
        with self._lock:
            self._items[key] = value
            self._items.move_to_end(key)
            if len(self._items) > self._max:
                evicted = self._items.popitem(last=False)
        # the eviction hook (a metrics counter) fires outside the lock
        if evicted is not None and self._on_evict is not None:
            self._on_evict(*evicted)

    def __contains__(self, key: K) -> bool:
        with self._lock:
            return key in self._items

    def pop(self, key: K, default: Optional[V] = None) -> Optional[V]:
        with self._lock:
            return self._items.pop(key, default)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator[K]:
        return iter(self._items)
