"""`cli doctor` — the latency-budget explainability engine.

The observability stack now records everything a diagnosis needs: the
dispatch decision ledger (infra/dispatchledger.py — per-dispatch cost
attribution), the capacity model (infra/capacity.py — per-shape device
latency, utilization/headroom), the SLO engine (infra/health.py — burn
rates and breach events blaming trace ids), and the flight recorder
(infra/flightrecorder.py — the ordered incident timeline).  What was
missing is the JOIN: when ``attestation_verify_p50`` burns, an operator
still had to correlate four endpoints by hand.

``diagnose()`` is that join as a pure function over the four snapshots
(so the same engine serves the in-process CLI probe, the remote
``--url`` mode reading a live node's admin endpoints, and the tests):
it emits a RANKED list of findings — "p50 driven by cold compile of
shape 512x8: 3 dispatches, 41 s", "shard 3 makespan 1.8x mean",
"padding waste 0.43 at lane bucket 64" — each citing its evidence:
ledger records by seq + trace id, flight-recorder events by seq, SLO
objectives by name.  ``render_text()`` prints the human form; the raw
dict is the machine form (``cli doctor --json``).
"""

from typing import Dict, List, Optional

from . import dispatchledger, schema, timeline as timeline_mod
from .env import env_float

# findings below this severity are listed but don't flip `healthy`
ATTENTION_SEVERITY = 40.0


def _finding(kind: str, severity: float, title: str, detail: str,
             evidence: Optional[List[dict]] = None,
             metrics: Optional[dict] = None) -> dict:
    return {"kind": kind, "severity": round(min(severity, 100.0), 1),
            "title": title, "detail": detail,
            "evidence": evidence or [], "metrics": metrics or {}}


def _cite(rec: dict) -> dict:
    trace_ids = rec.get("trace_ids") or []
    return {"type": "dispatch", "seq": rec.get("seq"),
            "trace_id": trace_ids[0] if trace_ids else "",
            "shape": rec.get("shape")}


def _cite_event(ev: dict) -> dict:
    return {"type": "flight_event", "seq": ev.get("seq"),
            "kind": ev.get("kind"),
            "trace_id": ev.get("trace_id", "")}


# --------------------------------------------------------------------------
# Individual analyzers (each: records/snapshots -> findings)
# --------------------------------------------------------------------------

def _compile_findings(records: List[dict]) -> List[dict]:
    out = []
    for outcome, base, name in (("compile", 40.0, "cold compile"),
                                ("cache_load", 15.0, "cache load"),
                                ("aot_load", 5.0, "AOT store load")):
        by_shape: Dict[str, List[dict]] = {}
        for r in records:
            comp = r.get("compile") or {}
            if comp.get("outcome") == outcome:
                by_shape.setdefault(str(r.get("shape")), []).append(r)
        for shape, recs in sorted(by_shape.items()):
            total_s = sum((r.get("compile") or {}).get("enqueue_s", 0)
                          for r in recs)
            if total_s < 0.5:
                continue
            out.append(_finding(
                f"{outcome}_latency", base + min(total_s, 55),
                f"{name} of shape {shape}: {len(recs)} dispatch(es), "
                f"{total_s:.1f} s",
                "first dispatch of a shape pays the XLA work "
                "synchronously inside device_enqueue — every lane in "
                "those batches (and everything queued behind them) "
                "absorbed it; precompiling the shape set at install "
                "time (supervisor warmup) or keeping the persistent "
                "cache warm removes this from the serving path",
                evidence=[_cite(r) for r in recs[:5]],
                metrics={"shape": shape, "dispatches": len(recs),
                         "total_s": round(total_s, 2),
                         "outcome": outcome}))
    return out


def _precompile_findings(records: List[dict]) -> List[dict]:
    """``cold_compile_on_hot_path``: a serving dispatch paid a FRESH
    XLA compile for a shape the shapeset registry covers — ``cli
    precompile`` (or a prior boot's self-populated AOT store) would
    have had the executable on disk.  Distinct from the generic
    compile_latency finding: this one names the fix."""
    by_shape: Dict[str, List[dict]] = {}
    for r in records:
        comp = r.get("compile") or {}
        if comp.get("outcome") == "compile":
            by_shape.setdefault(str(r.get("shape")), []).append(r)
    if not by_shape:
        return []
    covered_memo: Dict[int, set] = {}

    def _covered(shape: str) -> bool:
        mesh_n = 0
        if "@m" in shape:
            try:
                mesh_n = int(shape.split("@m", 1)[1])
            except ValueError:
                return False
        if mesh_n not in covered_memo:
            try:
                from ..ops import shapeset
                covered_memo[mesh_n] = shapeset.serving_shapes(
                    mesh_devices=mesh_n)
            except Exception:  # pragma: no cover - odd mesh widths
                covered_memo[mesh_n] = set()
        return shape in covered_memo[mesh_n]

    out = []
    for shape, recs in sorted(by_shape.items()):
        if not _covered(shape):
            continue
        total_s = sum((r.get("compile") or {}).get("enqueue_s", 0)
                      for r in recs)
        out.append(_finding(
            "cold_compile_on_hot_path", 50.0 + min(total_s, 50),
            f"shape {shape} compiled on the serving path "
            f"({len(recs)} dispatch(es), {total_s:.1f} s) — the "
            "shapeset registry covers it",
            "this shape is in the default serving set "
            "(ops/shapeset.py), so the compile was avoidable: `cli "
            "precompile` serializes the whole set into the AOT store "
            "at install time, after which boots and first dispatches "
            "deserialize in seconds (outcome aot_load) instead of "
            "paying XLA synchronously under live traffic",
            evidence=[_cite(r) for r in recs[:5]],
            metrics={"shape": shape, "dispatches": len(recs),
                     "total_s": round(total_s, 2)}))
    return out


def _imbalance_findings(records: List[dict]) -> List[dict]:
    worst = None
    for r in records:
        mesh = r.get("mesh") or {}
        ratio = mesh.get("makespan_ratio")
        if mesh.get("devices") and isinstance(ratio, (int, float)) \
                and ratio >= 1.25:
            if worst is None or ratio > worst[0]:
                worst = (ratio, r)
    if worst is None:
        return []
    ratio, rec = worst
    mesh = rec["mesh"]
    loads = mesh.get("shard_lanes") or []
    shard = loads.index(max(loads)) if loads else -1
    n_bad = sum(1 for r in records
                if (r.get("mesh") or {}).get("makespan_ratio", 0)
                >= 1.25)
    return [_finding(
        "mesh_shard_imbalance", 30 + 40 * (min(ratio, 2.5) - 1.0),
        f"shard {shard} makespan {ratio:.2f}x mean under group-cap "
        f"rows ({mesh.get('devices')}-device mesh, {n_bad} "
        f"dispatch(es) >= 1.25x)",
        "the sharded dispatch's wall time is the slowest shard's, so "
        "the makespan ratio IS the lost scaling; whole message-group "
        "rows cannot split across shards — oversized committees "
        "(group-cap row chains) pin lanes together.  Lowering "
        "TEKU_TPU_H2C_GROUP_CAP splits committees across more, "
        "smaller rows the LPT packer can balance",
        evidence=[_cite(rec)],
        metrics={"makespan_ratio": round(ratio, 3),
                 "shard_lanes": loads, "worst_shard": shard})]


def _padding_findings(records: List[dict], summary: dict) -> List[dict]:
    out = []
    for bucket, waste in (summary.get("padding_waste_by_lane_bucket")
                          or {}).items():
        if waste < 0.3:
            continue
        recs = [r for r in records
                if ((r.get("waste") or {}).get("lane") or {}).get(
                    "padded") == int(bucket)]
        out.append(_finding(
            "padding_waste", 20 + 60 * waste,
            f"padding waste {waste:.2f} at lane bucket {bucket} "
            f"({len(recs)} dispatch(es))",
            "pow-2 bucket padding dispatched dead lanes — committee "
            "tail shapes landing just past a bucket edge pay nearly "
            "the next bucket's device time; the admission planner's "
            "latency mode (smallest covering pow-2) and flush holds "
            "that fill batches both shrink this",
            evidence=[_cite(r) for r in recs[:5]],
            metrics={"lane_bucket": int(bucket),
                     "waste_ratio": waste,
                     "dispatches": len(recs)}))
    h2c_waste = (summary.get("padding_waste") or {}).get("h2c")
    if isinstance(h2c_waste, (int, float)) and h2c_waste >= 0.5:
        out.append(_finding(
            "padding_waste_h2c", 15 + 40 * h2c_waste,
            f"unique-row padding waste {h2c_waste:.2f} at the h2c/"
            "Miller bucket",
            "the unique-message row bucket (h2c + Miller stages) is "
            "padding far past the real row count — tiny or highly "
            "deduplicated batches under a large TEKU_TPU_H2C_MIN_"
            "BUCKET floor",
            metrics={"waste_ratio": h2c_waste}))
    return out


def _h2c_findings(records: List[dict], summary: dict) -> List[dict]:
    cache = summary.get("h2c_cache") or {}
    hits, misses = cache.get("hits", 0), cache.get("misses", 0)
    dedup = summary.get("dedup_ratio")
    if misses <= hits or misses < 4:
        return []
    cold = [r for r in records
            if (r.get("h2c") or {}).get("cache_misses", 0)
            > (r.get("h2c") or {}).get("cache_hits", 0)]
    sev = 20 + 25 * (misses / max(hits + misses, 1))
    if isinstance(dedup, (int, float)) and dedup > 0.3:
        sev += 10   # committee traffic SHOULD be warm
    return [_finding(
        "h2c_cache_cold", sev,
        f"H(m) arena cold: {misses} misses vs {hits} hits over "
        f"{len(records)} dispatch(es)",
        "hash-to-curve is the largest per-unique-message stage; a "
        "cold arena pays it per dispatch instead of per distinct "
        "AttestationData.  Expected right after boot — persistent "
        "coldness under committee traffic means the arena is too "
        "small (TEKU_TPU_H2C_CACHE_CAP) or messages never repeat",
        evidence=[_cite(r) for r in cold[:3]],
        metrics={"hits": hits, "misses": misses,
                 "dedup_ratio": dedup})]


def _msm_findings(records: List[dict]) -> List[dict]:
    demoted = []
    for r in records:
        msm = r.get("msm") or {}
        why = msm.get("why") or {}
        if msm.get("path") != "ladder" \
                or not str(why.get("rule", "")).startswith("auto:"):
            continue
        dup = why.get("dup")
        min_dup = why.get("auto_min_dup", 2.0)
        if why.get("tpu") is False or (
                isinstance(dup, (int, float)) and dup >= min_dup):
            demoted.append(r)
    if not demoted:
        return []
    why = (demoted[-1].get("msm") or {}).get("why") or {}
    sev = 25 + min(len(demoted), 15)
    if why.get("tpu") is False:
        # the finding's own detail calls this the TUNED default off
        # TPU — it must inform, never flip the diagnosis unhealthy
        sev = min(sev, ATTENTION_SEVERITY - 1)
    return [_finding(
        "msm_auto_demotion", sev,
        f"msm auto resolved to the ladder on {len(demoted)} "
        f"dispatch(es) ({why.get('rule')})",
        "the GLV+Pippenger bucketed MSM was measured ~1.8x faster on "
        "the scalars stage at committee shapes, but the auto rule "
        "declined it — on non-TPU devices that is the tuned default "
        "(bucket-select memory traffic), on TPU it means the batches "
        "are below the lanes/duplication crossover "
        "(TEKU_TPU_MSM_AUTO_MIN_LANES / _MIN_DUP)",
        evidence=[_cite(r) for r in demoted[:3]],
        metrics={"dispatches": len(demoted), "why": why})]


def _mesh_health_findings(events: List[dict],
                          records: List[dict],
                          mesh: Optional[dict] = None) -> List[dict]:
    """Self-healing mesh diagnosis:

    - ``mesh_degraded``: the mesh is serving below its configured
      width — 1/N-reduced device capacity right now.  The CURRENT
      state comes from the supervisor's mesh snapshot (``self_heal``
      block on the readiness body) when available: the bounded flight
      ring can roll the reshape event off while the mesh is still
      degraded (the same bug class the brownout_active finding fixed
      in PR 11); the flight events remain the evidence citations —
      the ejection carries the trace id of the dispatch that killed
      the chip — and the fallback source when no snapshot was given.
    - ``mesh_flap``: repeated eject↔readmit cycles of the same device
      — a chip that keeps passing the readmit probe and then wedging
      again under real load (marginal interconnect, thermal) costs a
      reshape + AOT warm per cycle and should be held out manually.
    """
    out = []
    ejects = [e for e in events or [] if e.get("kind") == "mesh_eject"]
    reshapes = [e for e in events or []
                if e.get("kind") == "mesh_reshape"]
    readmits = [e for e in events or []
                if e.get("kind") == "mesh_readmit"]

    def linked(evs):
        cites = [_cite_event(e) for e in evs[-3:]]
        ids = {e.get("trace_id") for e in evs if e.get("trace_id")}
        for r in records:
            if ids & set(r.get("trace_ids") or ()):
                cites.append(_cite(r))
        return cites

    # current degraded state: snapshot first (authoritative), last
    # reshape event as the fallback
    to_n = configured = epoch = None
    heal = (mesh or {}).get("self_heal") or {}
    if isinstance(heal.get("live"), (int, float)) \
            and isinstance(heal.get("configured"), (int, float)):
        to_n, configured = heal["live"], heal["configured"]
        epoch = heal.get("epoch")
    elif reshapes:
        last = reshapes[-1]
        to_n = last.get("to_devices")
        configured = last.get("configured")
        epoch = last.get("epoch")
    if isinstance(to_n, (int, float)) \
            and isinstance(configured, (int, float)) \
            and to_n < configured:
        lost = 1.0 - (to_n / configured if configured else 0.0)
        out.append(_finding(
            "mesh_degraded", 45 + 30 * lost,
            f"mesh running at {int(to_n)}/{int(configured)} "
            f"configured device(s) (epoch {epoch}, "
            f"{len(ejects)} ejection(s) in the event window)",
            "the self-healer ejected sick device(s) and reshaped "
            "onto the largest surviving pow-2 subset — serving "
            "continues on-device at reduced capacity while the "
            "background reprobe waits for the chip to recover; "
            "the cited ejections name the dispatch that killed "
            "each device.  Expect capacity to step back up 1/N "
            "at a time on readmit (PERF.md 'Mesh self-healing')",
            evidence=linked(ejects[-2:] + reshapes[-1:]),
            metrics={"live_devices": to_n,
                     "configured_devices": configured,
                     "epoch": epoch,
                     "ejects": len(ejects),
                     "recovery_s": (reshapes[-1].get("recovery_s")
                                    if reshapes else None)}))
    by_device: Dict[str, int] = {}
    for e in ejects:
        d = str(e.get("device", "?"))
        by_device[d] = by_device.get(d, 0) + 1
    flappers = {d: n for d, n in by_device.items() if n >= 2}
    if flappers:
        worst = max(flappers, key=flappers.get)
        out.append(_finding(
            "mesh_flap", 55 + 5 * min(flappers[worst], 5),
            f"device {worst} ejected {flappers[worst]}x "
            f"({len(readmits)} readmit(s) in the window)",
            "eject↔readmit cycling: the chip passes the synthetic "
            "readmit probe, rejoins the mesh, then wedges again under "
            "real load — every cycle pays a reshape + AOT warm of the "
            "sharded shape set.  A marginal device should be held out "
            "of TEKU_TPU_MESH explicitly until serviced; raising "
            "TEKU_TPU_MESH_REPROBE_S slows the flapping meanwhile",
            evidence=linked([e for e in ejects
                             if str(e.get("device")) == worst]),
            metrics={"by_device": by_device,
                     "readmits": len(readmits)}))
    return out


def _flight_findings(events: List[dict],
                     records: List[dict]) -> List[dict]:
    out = []
    by_kind: Dict[str, List[dict]] = {}
    for ev in events or []:
        by_kind.setdefault(ev.get("kind", ""), []).append(ev)

    def linked(evs):
        cites = [_cite_event(e) for e in evs[-3:]]
        ids = {e.get("trace_id") for e in evs if e.get("trace_id")}
        for r in records:
            if ids & set(r.get("trace_ids") or ()):
                cites.append(_cite(r))
        return cites

    demotions = by_kind.get("config_demotion") or []
    if demotions:
        subs = sorted({str(e.get("subsystem")) for e in demotions})
        out.append(_finding(
            "config_demotion", 45,
            f"configured path(s) demoted at boot: {', '.join(subs)}",
            "; ".join(str(e.get("detail", e.get("subsystem")))
                      for e in demotions[-3:]) +
            " — the node is NOT running the configuration it was "
            "asked for (it degraded rather than fail boot)",
            evidence=linked(demotions),
            metrics={"count": len(demotions), "subsystems": subs}))
    breaches = by_kind.get("slo_breach") or []
    if breaches:
        last = breaches[-1]
        out.append(_finding(
            "slo_breach", 80,
            f"SLO breach: {last.get('objective')} burn "
            f"{last.get('burn_rate')}",
            "the error budget is burning faster than it accrues; the "
            "cited dispatch records show what the breaching "
            "verifications actually paid for",
            evidence=linked(breaches),
            metrics={"count": len(breaches),
                     "objective": last.get("objective")}))
    brownouts = by_kind.get("brownout_enter") or []
    if brownouts:
        last = brownouts[-1]
        out.append(_finding(
            "brownout", 70,
            f"brownout entered (level {last.get('level')}): "
            f"{last.get('detail')}",
            f"utilization {last.get('utilization')}, burn "
            f"{last.get('burn_rate')} at entry — the controller is "
            "deliberately shedding to protect BLOCK_IMPORT/VIP",
            evidence=linked(brownouts),
            metrics={"enters": len(brownouts),
                     "exits": len(by_kind.get("brownout_exit") or [])}))
    failsafes = by_kind.get("flush_failsafe") or []
    if failsafes:
        last = failsafes[-1]
        out.append(_finding(
            "flush_failsafe", 50,
            f"real-time flush failsafe fired {len(failsafes)} "
            f"time(s) (failsafe {last.get('failsafe_ms')} ms)",
            "the wall clock beat the service clock during batch-fill "
            "holds — on starved hosts this silently turns flush "
            "deadlines into added latency (the r10 3.6 s block-import "
            "p50); tune TEKU_TPU_FLUSH_FAILSAFE_MS",
            evidence=linked(failsafes),
            metrics={"count": len(failsafes)}))
    sheds = by_kind.get("queue_shed") or []
    if sheds:
        classes: Dict[str, int] = {}
        for e in sheds:
            c = str(e.get("class", "?"))
            classes[c] = classes.get(c, 0) + 1 \
                + int(e.get("suppressed_since_last", 0))
        out.append(_finding(
            "queue_sheds", 55,
            f"verification tasks shed: {classes}",
            "arrivals were rejected or evicted (overflow, preemption "
            "or brownout) — offered load exceeded what the queue/"
            "device could carry",
            evidence=linked(sheds), metrics={"by_class": classes}))
    return out


def _capacity_findings(cap: Optional[dict]) -> List[dict]:
    if not cap:
        return []
    derived = cap.get("derived") or cap   # full snapshot or summary()
    util = derived.get("utilization")
    if not isinstance(util, (int, float)) or util < 0.8:
        return []
    return [_finding(
        "capacity_pressure", 40 + 40 * min(util, 1.5),
        f"utilization {util:.2f} of sustainable capacity"
        + (" (over capacity)" if util > 1.0 else ""),
        "demand is at or beyond the measured sustainable sigs/sec at "
        "the current shape mix; expect queueing (then brownout) "
        "unless the shape mix improves (bigger batches, more dedup) "
        "or capacity grows (mesh devices)",
        metrics={"utilization": util,
                 "demand_sigs_per_second": derived.get(
                     "demand_sigs_per_second"),
                 "capacity_sigs_per_second": derived.get(
                     "capacity_sigs_per_second")})]


def _admission_findings(admission: Optional[dict]) -> List[dict]:
    """The controller's CURRENT state: the flight ring shows brownout
    TRANSITIONS, but the bounded ring can roll past the enter event
    while the brownout is still on — the snapshot says what is true
    now."""
    brown = (admission or {}).get("brownout") or {}
    try:
        level = int(brown.get("level") or 0)
    except (TypeError, ValueError):
        level = 0
    if level < 1:
        return []
    inputs = admission.get("inputs") or {}
    shedding = ", ".join(brown.get("shedding") or []) or "?"
    return [_finding(
        "brownout_active", 65 + 5 * min(level, 2),
        f"brownout level {level} ACTIVE: shedding {shedding}",
        f"utilization {inputs.get('utilization')}, burn "
        f"{inputs.get('burn_rate')}, queue depth "
        f"{inputs.get('queue_depth')} right now — ledger records "
        f"stamped plan_mode=brownout{min(level, 2)} show what the "
        "surviving traffic paid while this sheds",
        metrics={"level": level, "enters": brown.get("enters"),
                 "exits": brown.get("exits"),
                 "plan": admission.get("plan")})]


def _slo_findings(slo: Optional[dict]) -> List[dict]:
    """``SloEngine.snapshot()`` (served verbatim on the readiness
    endpoint) is a mapping keyed by objective name — NOT a list."""
    out = []
    for name, obj in sorted((slo or {}).items()):
        if not isinstance(obj, dict):
            continue
        burn = obj.get("burn_rate")
        if not isinstance(burn, (int, float)) or burn <= 1.0:
            continue
        out.append(_finding(
            "slo_burn", 60 + min(30, 10 * burn),
            f"{name} burn rate {burn:.2f}",
            str(obj.get("description", "")) + " — burning error "
            "budget faster than it accrues",
            metrics={"objective": name, "burn_rate": burn,
                     "breached": obj.get("breached")}))
    return out


def _timeline_findings(timeline: Optional[dict],
                       records: List[dict]) -> List[dict]:
    """Causal-timeline analyzers: the two evidence gates the roadmap's
    open items (stage-graph executor, zero-copy ingest) need.

    - ``host_prep_serial``: at production batch sizes (>= 256 lanes)
      host-side packing dominates the end-to-end trace — the serial
      term zero-copy ingest must remove.  Cites the worst dispatch.
    - ``overlap_stall``: the device sat idle while the queue held
      work — the async-overlap machinery is NOT hiding host time.
      Cites the gap interval and the dispatch that followed it.
    """
    if not timeline:
        return []
    out = []
    traces = timeline.get("traces") or []
    events = timeline.get("events") or []
    by_trace = {t.get("trace_id"): t for t in traces}
    share_thr = env_float("TEKU_TPU_DOCTOR_HOST_PREP_SHARE", 0.35,
                          lo=0.0, hi=1.0)
    worst = None     # (share, host_prep_ms, total_ms, rec)
    for rec in records:
        if (rec.get("lanes") or 0) < 256:
            continue
        for tid in rec.get("trace_ids") or []:
            tr = by_trace.get(tid)
            if tr is None or not tr.get("total_ms"):
                continue
            hp = sum(s.get("ms", 0.0) for s in tr.get("stages", [])
                     if s.get("stage") == "host_prep")
            share = hp / tr["total_ms"]
            if share >= share_thr and (worst is None
                                       or share > worst[0]):
                worst = (share, hp, tr["total_ms"], rec)
    if worst is not None:
        share, hp, total, rec = worst
        out.append(_finding(
            "host_prep_serial", 35 + 40 * min(share, 1.0),
            f"host_prep is {share:.0%} of a {rec.get('lanes')}-lane "
            f"verify ({hp:.1f} of {total:.1f} ms)",
            "at production batch sizes the host-side limb packing is "
            "the serial term on the verify path — device overlap "
            "cannot hide work that happens before the enqueue; "
            "zero-copy ingest (packing into pinned buffers at gossip "
            "decode time) removes it",
            evidence=[_cite(rec)],
            metrics={"share": round(share, 4),
                     "host_prep_ms": round(hp, 3),
                     "total_ms": round(total, 3),
                     "lanes": rec.get("lanes"),
                     "threshold": share_thr}))
    stall_thr = env_float("TEKU_TPU_DOCTOR_OVERLAP_STALL", 0.25,
                          lo=0.0, hi=1.0)
    nonempty_s = timeline_mod._total(
        timeline_mod._phase_intervals(events, "queue_nonempty"))
    gaps = timeline_mod.stalls(events)
    gap_s = timeline_mod._total(gaps)
    if nonempty_s > 0 and gaps and gap_s / nonempty_s >= stall_thr:
        g0, g1 = max(gaps, key=lambda g: g[1] - g[0])
        # the dispatch that eventually followed the worst gap — the
        # one whose host_prep/assembly the device idled behind
        after = [r for r in records
                 if isinstance(r.get("t_mono"), (int, float))
                 and r["t_mono"] >= g0]
        evidence = ([_cite(min(after, key=lambda r: r["t_mono"]))]
                    if after else [])
        out.append(_finding(
            "overlap_stall", 30 + 50 * min(gap_s / nonempty_s, 1.0),
            f"device idle {gap_s:.3f} s of {nonempty_s:.3f} s with a "
            "nonempty queue "
            f"({gap_s / nonempty_s:.0%}, worst gap {g1 - g0:.3f} s)",
            "queued work waited while no dispatch occupied the "
            "device: batch assembly, host_prep or the enqueue path "
            "is serializing ahead of the device instead of "
            "overlapping with it",
            evidence=evidence,
            metrics={"stall_share": round(gap_s / nonempty_s, 4),
                     "stall_s": round(gap_s, 4),
                     "queue_nonempty_s": round(nonempty_s, 4),
                     "worst_gap": {"t_mono": round(g0, 6),
                                   "dur_s": round(g1 - g0, 4)},
                     "threshold": stall_thr}))
    return out


# --------------------------------------------------------------------------
# The engine
# --------------------------------------------------------------------------

def diagnose(records: List[dict],
             capacity: Optional[dict] = None,
             slo: Optional[dict] = None,
             flight_events: Optional[List[dict]] = None,
             admission: Optional[dict] = None,
             mesh: Optional[dict] = None,
             timeline: Optional[dict] = None) -> dict:
    """Rank everything the ledger + sensors can explain about the
    current latency budget.  All inputs are plain JSON-able snapshots
    (local globals or fetched from a remote node's admin endpoints);
    ``mesh`` is the supervisor's mesh self-description (the readiness
    body's ``backend.mesh``, carrying the healer's ``self_heal``
    block) so a degraded mesh stays diagnosable after its events roll
    off the bounded flight ring; ``timeline`` is the causal-timeline
    snapshot (``{"traces": [...], "events": [...]}`` — slow traces
    plus the timeline ring) powering the host_prep_serial and
    overlap_stall analyzers.  The result is a schema-versioned
    envelope (shared with the timeline export)."""
    records = list(records or [])
    summary = dispatchledger.summarize(records)
    findings: List[dict] = []
    findings += _compile_findings(records)
    findings += _precompile_findings(records)
    findings += _imbalance_findings(records)
    findings += _padding_findings(records, summary)
    findings += _h2c_findings(records, summary)
    findings += _msm_findings(records)
    findings += _mesh_health_findings(flight_events or [], records,
                                      mesh=mesh)
    findings += _flight_findings(flight_events or [], records)
    findings += _capacity_findings(capacity)
    findings += _admission_findings(admission)
    findings += _slo_findings(slo)
    findings += _timeline_findings(timeline, records)
    findings.sort(key=lambda f: -f["severity"])
    for rank, f in enumerate(findings, 1):
        f["rank"] = rank
    attention = [f for f in findings
                 if f["severity"] >= ATTENTION_SEVERITY]
    return schema.envelope("doctor", {
        "healthy": not attention,
        "findings": findings,
        "attention": len(attention),
        "ledger_summary": summary,
        "inputs": {
            "dispatch_records": len(records),
            "flight_events": len(flight_events or []),
            "capacity": bool(capacity),
            "slo": bool(slo),
            "admission": bool(admission),
            "timeline": bool(timeline),
        },
    })


def render_text(diagnosis: dict) -> str:
    """The human form of a diagnosis: ranked findings with their
    evidence citations (dispatch seq + trace id — the keys that join
    to /teku/v1/admin/dispatches, /traces and /flight_recorder)."""
    lines = []
    inputs = diagnosis.get("inputs", {})
    lines.append(
        f"doctor: {inputs.get('dispatch_records', 0)} dispatch "
        f"record(s), {inputs.get('flight_events', 0)} flight "
        f"event(s)")
    summary = diagnosis.get("ledger_summary") or {}
    waste = summary.get("padding_waste") or {}
    lines.append(
        f"ledger: dedup {summary.get('dedup_ratio')}, waste "
        f"lane={waste.get('lane')} h2c={waste.get('h2c')}, "
        f"compile {summary.get('compile')}, decisions "
        f"{summary.get('decisions')}")
    findings = diagnosis.get("findings") or []
    if not findings:
        lines.append("no findings — the latency budget is clean")
        return "\n".join(lines)
    verdict = ("HEALTHY (informational findings only)"
               if diagnosis.get("healthy")
               else f"{diagnosis.get('attention')} finding(s) need "
                    "attention")
    lines.append(verdict)
    for f in findings:
        lines.append(f"  #{f['rank']} [{f['severity']:5.1f}] "
                     f"{f['kind']}: {f['title']}")
        detail = f.get("detail", "")
        if detail:
            lines.append(f"       {detail}")
        for ev in f.get("evidence", []):
            if ev.get("type") == "dispatch":
                lines.append(
                    f"       evidence: dispatch seq {ev.get('seq')} "
                    f"shape {ev.get('shape')} trace "
                    f"{ev.get('trace_id') or '-'}")
            else:
                lines.append(
                    f"       evidence: flight event seq "
                    f"{ev.get('seq')} kind {ev.get('kind')} trace "
                    f"{ev.get('trace_id') or '-'}")
    return "\n".join(lines)
