"""Phase0 spec helper functions: math, shuffling, committees, domains,
state accessors/mutators, predicates.

Equivalent of the reference's helper layer (reference: ethereum/spec/src/
main/java/tech/pegasys/teku/spec/logic/common/helpers/MiscHelpers.java,
BeaconStateAccessors.java, BeaconStateMutators.java, Predicates.java,
MathHelpers.java) — here plain functions over the immutable SSZ
containers, with the swap-or-not shuffle vectorized over the whole index
list in numpy (one pass per round for every index at once) instead of
the reference's per-index loop, because committee computation is the
per-epoch hot loop and whole-list batching is the TPU-first shape.
"""

import hashlib
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ssz import Container
from .config import (DOMAIN_BEACON_ATTESTER, FAR_FUTURE_EPOCH,
                     GENESIS_EPOCH, SpecConfig)
from .datastructures import (AttestationData, Checkpoint, Fork, ForkData,
                             SigningData, Validator)


def hash32(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def integer_squareroot(n: int) -> int:
    return math.isqrt(n)


def xor32(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def uint_to_bytes(n: int, length: int = 8) -> bytes:
    return n.to_bytes(length, "little")


def bytes_to_uint64(data: bytes) -> int:
    return int.from_bytes(data, "little")


# --------------------------------------------------------------------------
# Epoch / slot math
# --------------------------------------------------------------------------

def compute_epoch_at_slot(cfg: SpecConfig, slot: int) -> int:
    return slot // cfg.SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(cfg: SpecConfig, epoch: int) -> int:
    return epoch * cfg.SLOTS_PER_EPOCH


def compute_activation_exit_epoch(cfg: SpecConfig, epoch: int) -> int:
    return epoch + 1 + cfg.MAX_SEED_LOOKAHEAD


def get_current_epoch(cfg: SpecConfig, state) -> int:
    return compute_epoch_at_slot(cfg, state.slot)


def get_previous_epoch(cfg: SpecConfig, state) -> int:
    cur = get_current_epoch(cfg, state)
    return GENESIS_EPOCH if cur == GENESIS_EPOCH else cur - 1


# --------------------------------------------------------------------------
# Shuffling (swap-or-not, vectorized)
# --------------------------------------------------------------------------

def compute_shuffled_index(cfg: SpecConfig, index: int, index_count: int,
                           seed: bytes) -> int:
    """Single-index forward shuffle (spec-literal, for spot checks)."""
    assert index < index_count
    for r in range(cfg.SHUFFLE_ROUND_COUNT):
        pivot = bytes_to_uint64(
            hash32(seed + bytes([r]))[:8]) % index_count
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = hash32(seed + bytes([r])
                        + uint_to_bytes(position // 256, 8)[:4])
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) % 2:
            index = flip
    return index


def shuffle_list(cfg: SpecConfig, indices: np.ndarray, seed: bytes,
                 ) -> np.ndarray:
    """Shuffle the WHOLE list at once: per round, one vectorized
    swap-or-not pass over every position (the reference shuffles lists
    via the same inverted-round trick in
    spec/logic/common/helpers/MiscHelpers.java shuffleList)."""
    n = len(indices)
    if n == 0:
        return indices
    out = indices.copy()
    # list-shuffle applies rounds in reverse to match per-index forward
    for r in range(cfg.SHUFFLE_ROUND_COUNT - 1, -1, -1):
        rb = bytes([r])
        pivot = bytes_to_uint64(hash32(seed + rb)[:8]) % n
        pos = np.arange(n, dtype=np.int64)
        flip = (pivot + n - pos) % n
        position = np.maximum(pos, flip)
        # one source hash per 256 positions
        n_words = int(position.max()) // 256 + 1
        srcs = np.frombuffer(
            b"".join(hash32(seed + rb + uint_to_bytes(w, 8)[:4])
                     for w in range(n_words)), dtype=np.uint8,
        ).reshape(n_words, 32)
        byte = srcs[position // 256, (position % 256) // 8]
        bit = (byte >> (position % 8).astype(np.uint8)) & 1
        swapped = np.where(bit.astype(bool), out[flip], out)
        out = swapped
    return out


def compute_proposer_index(cfg: SpecConfig, state, indices: Sequence[int],
                           seed: bytes) -> int:
    """Balance-weighted proposer sampling (spec compute_proposer_index)."""
    assert len(indices) > 0
    MAX_RANDOM_BYTE = 2 ** 8 - 1
    i = 0
    total = len(indices)
    validators = state.validators
    while True:
        candidate = indices[compute_shuffled_index(
            cfg, i % total, total, seed)]
        random_byte = hash32(seed + uint_to_bytes(i // 32, 8))[i % 32]
        eff = validators[candidate].effective_balance
        if eff * MAX_RANDOM_BYTE >= cfg.MAX_EFFECTIVE_BALANCE * random_byte:
            return candidate
        i += 1


# --------------------------------------------------------------------------
# Accessors
# --------------------------------------------------------------------------

def is_active_validator(v: Validator, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def get_active_validator_indices(state, epoch: int) -> List[int]:
    return [i for i, v in enumerate(state.validators)
            if is_active_validator(v, epoch)]


def get_validator_churn_limit(cfg: SpecConfig, state) -> int:
    active = get_active_validator_indices(
        state, get_current_epoch(cfg, state))
    return max(cfg.MIN_PER_EPOCH_CHURN_LIMIT,
               len(active) // cfg.CHURN_LIMIT_QUOTIENT)


def get_randao_mix(cfg: SpecConfig, state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % cfg.EPOCHS_PER_HISTORICAL_VECTOR]


def get_seed(cfg: SpecConfig, state, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(
        cfg, state,
        epoch + cfg.EPOCHS_PER_HISTORICAL_VECTOR
        - cfg.MIN_SEED_LOOKAHEAD - 1)
    return hash32(domain_type + uint_to_bytes(epoch, 8) + mix)


def get_block_root_at_slot(cfg: SpecConfig, state, slot: int) -> bytes:
    assert slot < state.slot <= slot + cfg.SLOTS_PER_HISTORICAL_ROOT
    return state.block_roots[slot % cfg.SLOTS_PER_HISTORICAL_ROOT]


def get_block_root(cfg: SpecConfig, state, epoch: int) -> bytes:
    return get_block_root_at_slot(
        cfg, state, compute_start_slot_at_epoch(cfg, epoch))


def get_committee_count_per_slot(cfg: SpecConfig, state, epoch: int) -> int:
    active = len(get_active_validator_indices(state, epoch))
    return max(1, min(
        cfg.MAX_COMMITTEES_PER_SLOT,
        active // cfg.SLOTS_PER_EPOCH // cfg.TARGET_COMMITTEE_SIZE))


class ShufflingCache:
    """Per-(seed, epoch) active-index shuffling, computed once.

    The reference keeps the same data in TransitionCaches/epoch caches
    (reference: ethereum/spec/.../spec/cache/); committee queries per
    slot slice the one shuffled array.
    """

    def __init__(self):
        self._cache: dict = {}

    def get(self, cfg: SpecConfig, state, epoch: int) -> np.ndarray:
        seed = get_seed(cfg, state, epoch, DOMAIN_BEACON_ATTESTER)
        indices = np.asarray(
            get_active_validator_indices(state, epoch), dtype=np.int64)
        # seed alone can collide across deep conflicting forks whose
        # activation sets diverged; the active-index digest pins the key
        # to the exact membership (the O(n) scan is unavoidable anyway,
        # the cache exists to skip the shuffle rounds).
        key = (seed, epoch, hash(indices.tobytes()))
        hit = self._cache.get(key)
        if hit is None:
            hit = shuffle_list(cfg, indices, seed)
            if len(self._cache) > 64:
                self._cache.clear()
            self._cache[key] = hit
        return hit


_SHUFFLING = ShufflingCache()


def get_beacon_committee(cfg: SpecConfig, state, slot: int,
                         index: int) -> List[int]:
    epoch = compute_epoch_at_slot(cfg, slot)
    per_slot = get_committee_count_per_slot(cfg, state, epoch)
    committees_per_epoch = per_slot * cfg.SLOTS_PER_EPOCH
    committee_index = (slot % cfg.SLOTS_PER_EPOCH) * per_slot + index
    shuffled = _SHUFFLING.get(cfg, state, epoch)
    n = len(shuffled)
    start = n * committee_index // committees_per_epoch
    end = n * (committee_index + 1) // committees_per_epoch
    return [int(x) for x in shuffled[start:end]]


def get_beacon_proposer_index(cfg: SpecConfig, state,
                              slot: Optional[int] = None) -> int:
    """Proposer for `slot` (default: the state's own slot).  An
    explicit slot must be in the state's current epoch — the randao
    seed is epoch-scoped, so gossip validators can check a claimed
    proposer with any same-epoch state (reference
    BeaconStateAccessors.getBeaconProposerIndex)."""
    slot = state.slot if slot is None else slot
    epoch = compute_epoch_at_slot(cfg, slot)
    if epoch != get_current_epoch(cfg, state):
        # a real exception (not assert): callers route on it, and -O
        # must not turn a wrong-epoch lookup into a wrong answer
        raise ValueError("proposer lookup needs a state in the "
                         "slot's epoch")
    from .config import DOMAIN_BEACON_PROPOSER
    seed = hash32(get_seed(cfg, state, epoch, DOMAIN_BEACON_PROPOSER)
                  + uint_to_bytes(slot, 8))
    indices = get_active_validator_indices(state, epoch)
    return compute_proposer_index(cfg, state, indices, seed)


def get_total_balance(cfg: SpecConfig, state, indices) -> int:
    return max(cfg.EFFECTIVE_BALANCE_INCREMENT,
               sum(state.validators[i].effective_balance for i in indices))


def get_total_active_balance(cfg: SpecConfig, state) -> int:
    return get_total_balance(
        cfg, state,
        get_active_validator_indices(state, get_current_epoch(cfg, state)))


# --------------------------------------------------------------------------
# Domains / signing roots
# --------------------------------------------------------------------------

def compute_fork_data_root(current_version: bytes,
                           genesis_validators_root: bytes) -> bytes:
    return ForkData(current_version=current_version,
                    genesis_validators_root=genesis_validators_root).htr()


def compute_fork_digest(current_version: bytes,
                        genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(
        current_version, genesis_validators_root)[:4]


def compute_domain(domain_type: bytes, fork_version: bytes = bytes(4),
                   genesis_validators_root: bytes = bytes(32)) -> bytes:
    root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + root[:28]


def get_domain(cfg: SpecConfig, state, domain_type: bytes,
               epoch: Optional[int] = None) -> bytes:
    epoch = get_current_epoch(cfg, state) if epoch is None else epoch
    fork: Fork = state.fork
    version = (fork.previous_version if epoch < fork.epoch
               else fork.current_version)
    return compute_domain(domain_type, version,
                          state.genesis_validators_root)


def compute_signing_root(obj, domain: bytes) -> bytes:
    root = obj if isinstance(obj, bytes) else obj.htr()
    return SigningData(object_root=root, domain=domain).htr()


def uint64_signing_root(value: int, domain: bytes) -> bytes:
    """Signing root of a bare uint64 (epoch for RANDAO, slot for
    selection proofs): HTR of uint64 is its LE bytes zero-padded to 32.
    One definition shared by producers AND verifiers so the encoding
    can never silently diverge."""
    return compute_signing_root(
        value.to_bytes(8, "little").ljust(32, b"\x00"), domain)


def randao_signing_root(cfg: SpecConfig, state, epoch: int) -> bytes:
    from .config import DOMAIN_RANDAO
    return uint64_signing_root(
        epoch, get_domain(cfg, state, DOMAIN_RANDAO, epoch))


def selection_proof_signing_root(cfg: SpecConfig, state,
                                 slot: int) -> bytes:
    from .config import DOMAIN_SELECTION_PROOF
    return uint64_signing_root(
        slot, get_domain(cfg, state, DOMAIN_SELECTION_PROOF,
                         compute_epoch_at_slot(cfg, slot)))


# --------------------------------------------------------------------------
# Predicates
# --------------------------------------------------------------------------

def is_slashable_validator(v: Validator, epoch: int) -> bool:
    return (not v.slashed
            and v.activation_epoch <= epoch < v.withdrawable_epoch)


def is_slashable_attestation_data(d1: AttestationData,
                                  d2: AttestationData) -> bool:
    double = d1 != d2 and d1.target.epoch == d2.target.epoch
    surround = (d1.source.epoch < d2.source.epoch
                and d2.target.epoch < d1.target.epoch)
    return double or surround


def is_eligible_for_activation_queue(cfg: SpecConfig, v: Validator) -> bool:
    return (v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance == cfg.MAX_EFFECTIVE_BALANCE)


def is_eligible_for_activation(state, v: Validator) -> bool:
    return (v.activation_eligibility_epoch
            <= state.finalized_checkpoint.epoch
            and v.activation_epoch == FAR_FUTURE_EPOCH)


def is_valid_merkle_branch(leaf: bytes, branch: Sequence[bytes], depth: int,
                           index: int, root: bytes) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = hash32(branch[i] + value)
        else:
            value = hash32(value + branch[i])
    return value == root


# --------------------------------------------------------------------------
# Attestation helpers
# --------------------------------------------------------------------------

def get_attesting_indices(cfg: SpecConfig, state, data: AttestationData,
                          bits) -> List[int]:
    committee = get_beacon_committee(cfg, state, data.slot, data.index)
    assert len(bits) == len(committee)
    return sorted(i for i, b in zip(committee, bits) if b)


def get_indexed_attestation(cfg: SpecConfig, state, attestation):
    if hasattr(attestation, "committee_bits"):
        # electra shape: bits span the committees in committee_bits
        from .electra.block import get_indexed_attestation as _electra
        return _electra(cfg, state, attestation)
    from .datastructures import get_schemas
    S = get_schemas(cfg)
    indices = get_attesting_indices(
        cfg, state, attestation.data, attestation.aggregation_bits)
    return S.IndexedAttestation(
        attesting_indices=tuple(indices),
        data=attestation.data,
        signature=attestation.signature)


# --------------------------------------------------------------------------
# Mutators (return new states — containers are immutable)
# --------------------------------------------------------------------------

def increase_balance(state, index: int, delta: int):
    balances = list(state.balances)
    balances[index] += delta
    return state.copy_with(balances=tuple(balances))


def decrease_balance(state, index: int, delta: int):
    balances = list(state.balances)
    balances[index] = max(0, balances[index] - delta)
    return state.copy_with(balances=tuple(balances))


def compute_exit_epoch_and_update(cfg: SpecConfig, state):
    """(exit_queue_epoch, churn) for initiate_validator_exit."""
    exit_epochs = [v.exit_epoch for v in state.validators
                   if v.exit_epoch != FAR_FUTURE_EPOCH]
    exit_queue_epoch = max(
        exit_epochs
        + [compute_activation_exit_epoch(
            cfg, get_current_epoch(cfg, state))])
    exit_queue_churn = sum(
        1 for v in state.validators if v.exit_epoch == exit_queue_epoch)
    if exit_queue_churn >= get_validator_churn_limit(cfg, state):
        exit_queue_epoch += 1
    return exit_queue_epoch


def initiate_validator_exit(cfg: SpecConfig, state, index: int):
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return state
    exit_queue_epoch = compute_exit_epoch_and_update(cfg, state)
    v = v.copy_with(
        exit_epoch=exit_queue_epoch,
        withdrawable_epoch=(exit_queue_epoch
                            + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY))
    validators = list(state.validators)
    validators[index] = v
    return state.copy_with(validators=tuple(validators))


def _is_altair(cfg: SpecConfig, state) -> bool:
    return get_current_epoch(cfg, state) >= cfg.ALTAIR_FORK_EPOCH


def _is_electra(cfg: SpecConfig, state) -> bool:
    return get_current_epoch(cfg, state) >= cfg.ELECTRA_FORK_EPOCH


def slash_validator(cfg: SpecConfig, state, slashed_index: int,
                    whistleblower_index: Optional[int] = None):
    epoch = get_current_epoch(cfg, state)
    electra = _is_electra(cfg, state)
    if electra:
        from .electra.helpers import initiate_validator_exit as _init
        state = _init(cfg, state, slashed_index)
    else:
        state = initiate_validator_exit(cfg, state, slashed_index)
    v = state.validators[slashed_index]
    v = v.copy_with(
        slashed=True,
        withdrawable_epoch=max(
            v.withdrawable_epoch, epoch + cfg.EPOCHS_PER_SLASHINGS_VECTOR))
    validators = list(state.validators)
    validators[slashed_index] = v
    slashings = list(state.slashings)
    slashings[epoch % cfg.EPOCHS_PER_SLASHINGS_VECTOR] += v.effective_balance
    state = state.copy_with(validators=tuple(validators),
                            slashings=tuple(slashings))
    altair = _is_altair(cfg, state)
    if electra:
        penalty_quotient = cfg.MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA
    elif get_current_epoch(cfg, state) >= cfg.BELLATRIX_FORK_EPOCH:
        penalty_quotient = cfg.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    elif altair:
        penalty_quotient = cfg.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    else:
        penalty_quotient = cfg.MIN_SLASHING_PENALTY_QUOTIENT
    state = decrease_balance(
        state, slashed_index, v.effective_balance // penalty_quotient)

    proposer_index = get_beacon_proposer_index(cfg, state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_quotient = (cfg.WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA
                              if electra
                              else cfg.WHISTLEBLOWER_REWARD_QUOTIENT)
    whistleblower_reward = (v.effective_balance // whistleblower_quotient)
    if altair:
        from .config import PROPOSER_WEIGHT, WEIGHT_DENOMINATOR
        proposer_reward = (whistleblower_reward * PROPOSER_WEIGHT
                           // WEIGHT_DENOMINATOR)
    else:
        proposer_reward = (whistleblower_reward
                           // cfg.PROPOSER_REWARD_QUOTIENT)
    state = increase_balance(state, proposer_index, proposer_reward)
    state = increase_balance(state, whistleblower_index,
                             whistleblower_reward - proposer_reward)
    return state
