"""Consensus spec engine: config, datastructures, helpers, transition.

The TPU build's equivalent of the reference's ethereum/spec module
(reference: ethereum/spec/src/main/java/tech/pegasys/teku/spec/
Spec.java:108 facade).  `Spec` bundles a SpecConfig with its schema
family and the transition entry points — the one object the node wires
everywhere.
"""

from .config import get_config, MAINNET, MINIMAL, SpecConfig
from .datastructures import get_schemas, Schemas


class Spec:
    """Config + schemas + transition functions in one handle, with the
    milestone routing seam (reference Spec.atSlot/forMilestone)."""

    def __init__(self, cfg: SpecConfig):
        self.config = cfg
        self.schemas = get_schemas(cfg)
        from .milestones import build_fork_schedule
        self.fork_schedule = build_fork_schedule(cfg)

    def milestone_at_slot(self, slot: int):
        return self.fork_schedule.milestone_at_slot(slot)

    def at_slot(self, slot: int):
        """The SpecVersion governing `slot`."""
        return self.fork_schedule.version_at_slot(slot)

    # -- delegation to the functional engine --
    def state_transition(self, state, signed_block, validate_result=True):
        from .transition import state_transition
        return state_transition(self.config, state, signed_block,
                                validate_result)

    def process_slots(self, state, slot):
        from .transition import process_slots
        return process_slots(self.config, state, slot)

    def interop_genesis(self, n_validators, genesis_time=1578009600):
        from .genesis import interop_genesis
        return interop_genesis(self.config, n_validators, genesis_time)

    def get_beacon_committee(self, state, slot, index):
        from . import helpers as H
        return H.get_beacon_committee(self.config, state, slot, index)

    def get_beacon_proposer_index(self, state):
        from . import helpers as H
        return H.get_beacon_proposer_index(self.config, state)

    def compute_epoch_at_slot(self, slot):
        return slot // self.config.SLOTS_PER_EPOCH


def create_spec(network: str = "minimal") -> Spec:
    """Build a Spec for a named network: full bundles (mainnet,
    sepolia, holesky, gnosis — real fork schedules) from
    spec/networks.py, else the bare presets."""
    from .networks import BUNDLES
    bundle = BUNDLES.get(network)
    if bundle is not None:
        return Spec(bundle.config)
    return Spec(get_config(network))
