"""Official reference-vector loader: runs the real eth consensus-spec
and BLS test archives whenever a local copy exists.

Equivalent of the reference's reference-test harness (reference:
eth-reference-tests/src/referenceTest/java/tech/pegasys/teku/reference/
Eth2ReferenceTestCase.java:41-86 — one dispatcher keyed on
(fork, runner, handler) walking the consensus-spec-tests layout; the
BLS suites per BlsTests.java:23-36).

Point TEKU_TPU_VECTORS at a directory containing either/both:
  bls/<suite>/*.json                      (ethereum/bls12-381-tests)
  tests/<preset>/<fork>/<runner>/...      (consensus-spec-tests)
and tests/test_official_vectors.py turns every discovered case into a
pytest case.  Without the env var those tests skip — the constructed
acceptance suites (test_bls_acceptance.py etc.) remain the offline
gate.
"""

import dataclasses
import functools
import inspect
import json
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from ..infra.env import env_str
from ..native import snappyc
from . import config as C
from .milestones import build_fork_schedule, SpecMilestone

FORK_NAMES = {
    "phase0": SpecMilestone.PHASE0,
    "altair": SpecMilestone.ALTAIR,
    "bellatrix": SpecMilestone.BELLATRIX,
    "capella": SpecMilestone.CAPELLA,
    "deneb": SpecMilestone.DENEB,
    "electra": SpecMilestone.ELECTRA,
}


def vectors_root() -> Optional[Path]:
    path = env_str("TEKU_TPU_VECTORS")
    if not path:
        return None
    root = Path(path)
    return root if root.is_dir() else None


@functools.lru_cache(maxsize=16)
def fork_config(preset: str, fork: str) -> C.SpecConfig:
    """A config with every milestone up to `fork` live at genesis —
    how the spec test generators configure their states."""
    base = C.MAINNET if preset == "mainnet" else C.MINIMAL
    order = list(FORK_NAMES)
    fields = {}
    for name in order[1:order.index(fork) + 1]:
        fields[f"{name.upper()}_FORK_EPOCH"] = 0
    return dataclasses.replace(base, **fields)


@functools.lru_cache(maxsize=16)
def schemas_for(cfg: C.SpecConfig, fork: str):
    return build_fork_schedule(cfg).version_for(
        FORK_NAMES[fork]).schemas


def _lineage_modules(fork: str, kind: str):
    """The fork's module then every ANCESTOR fork's, newest first —
    a handler a fork doesn't override resolves to the nearest ancestor
    that defines it (exactly the reference's per-version logic
    inheritance), never by skipping straight to phase0."""
    import importlib
    order = list(FORK_NAMES)
    out = []
    for name in reversed(order[:order.index(fork) + 1]):
        if name == "phase0":
            out.append(importlib.import_module(f"teku_tpu.spec.{kind}"))
        else:
            out.append(importlib.import_module(
                f"teku_tpu.spec.{name}.{kind}"))
    return out


def _resolve_handler(fork: str, kind: str, name: str):
    for module in _lineage_modules(fork, kind):
        fn = getattr(module, name, None)
        if fn is not None:
            return fn
    return None


def load_ssz_snappy(path: Path, schema):
    return schema.deserialize(snappyc.uncompress(path.read_bytes()))


def _load_yaml(path: Path):
    import yaml
    return yaml.safe_load(path.read_text())


# -- BLS suites -------------------------------------------------------------

def iter_bls_cases(root: Path) -> Iterator[Tuple[str, str, dict]]:
    bls_dir = root / "bls"
    if not bls_dir.is_dir():
        return
    for suite_dir in sorted(p for p in bls_dir.iterdir() if p.is_dir()):
        for case in sorted(suite_dir.rglob("*.json")):
            yield suite_dir.name, case.stem, json.loads(
                case.read_text())
        for case in sorted(suite_dir.rglob("data.yaml")):
            yield (suite_dir.name, case.parent.name,
                   _load_yaml(case))


def _hx(value: str) -> bytes:
    return bytes.fromhex(value[2:] if value.startswith("0x") else value)


def run_bls_case(suite: str, case: dict) -> Optional[bool]:
    """True=pass, False=fail, None=suite not recognised.  'pass' means
    our implementation reproduces the vector's expected output,
    including expected rejections (output null)."""
    from ..crypto import bls
    inp = case["input"]
    out = case.get("output")
    try:
        if suite == "sign":
            got = bls.sign(int.from_bytes(_hx(inp["privkey"]), "big"),
                           _hx(inp["message"]))
            return out is not None and got == _hx(out)
        if suite == "verify":
            got = bls.verify(_hx(inp["pubkey"]), _hx(inp["message"]),
                             _hx(inp["signature"]))
            return got == out
        if suite == "aggregate":
            try:
                got = bls.aggregate_signatures(
                    [_hx(s) for s in inp])
            except Exception:
                return out is None
            return out is not None and got == _hx(out)
        if suite == "aggregate_verify":
            got = bls.aggregate_verify(
                [_hx(p) for p in inp["pubkeys"]],
                [_hx(m) for m in inp["messages"]],
                _hx(inp["signature"]))
            return got == out
        if suite == "fast_aggregate_verify":
            got = bls.fast_aggregate_verify(
                [_hx(p) for p in inp["pubkeys"]],
                _hx(inp["message"]), _hx(inp["signature"]))
            return got == out
        if suite == "batch_verify":
            got = bls.batch_verify(list(zip(
                [[_hx(p)] for p in inp["pubkeys"]],
                [_hx(m) for m in inp["messages"]],
                [_hx(s) for s in inp["signatures"]])))
            return got == out
        if suite in ("deserialization_G1", "deserialization_G2"):
            blob = _hx(inp.get("pubkey") or inp.get("signature"))
            if suite == "deserialization_G1":
                ok = bls.public_key_is_valid(blob)
            else:
                ok = bls.signature_is_valid(blob)
            return ok == case["output"]
        if suite == "hash_to_G2":
            from ..crypto.bls import hash_to_curve as H2C
            from ..crypto.bls import curve as CV
            msg = _hx(inp["msg"])
            point = H2C.hash_to_g2(msg)
            px, py = CV.to_affine(CV.FQ2_OPS, point)
            want_x = tuple(int(v, 16) for v in
                           case["output"]["x"].split(","))
            want_y = tuple(int(v, 16) for v in
                           case["output"]["y"].split(","))
            return (tuple(px), tuple(py)) == (want_x, want_y)
        if suite == "eth_aggregate_pubkeys":
            try:
                got = bls.eth_aggregate_pubkeys(
                    [_hx(p) for p in inp])
            except Exception:
                return out is None
            return out is not None and got == _hx(out)
        if suite == "eth_fast_aggregate_verify":
            got = bls.eth_fast_aggregate_verify(
                [_hx(p) for p in inp["pubkeys"]],
                _hx(inp["message"]), _hx(inp["signature"]))
            return got == out
    except Exception:
        # an implementation crash on a vector input = failure unless
        # the vector expects rejection
        return out is None
    return None


# -- consensus-spec-tests ----------------------------------------------------

def iter_consensus_cases(root: Path, runner: str,
                         preset: str = "minimal"
                         ) -> Iterator[Tuple[str, str, Path]]:
    """Yields (fork, handler, case_dir) for every case of a runner."""
    base = root / "tests" / preset
    if not base.is_dir():
        return
    for fork_dir in sorted(p for p in base.iterdir() if p.is_dir()):
        if fork_dir.name not in FORK_NAMES:
            continue
        runner_dir = fork_dir / runner
        if not runner_dir.is_dir():
            continue
        for handler_dir in sorted(runner_dir.iterdir()):
            for suite_dir in sorted(handler_dir.iterdir()):
                for case_dir in sorted(suite_dir.iterdir()):
                    if case_dir.is_dir():
                        yield (fork_dir.name, handler_dir.name,
                               case_dir)


def _load_state(cfg, fork, path: Path):
    return load_ssz_snappy(path, schemas_for(cfg, fork).BeaconState)


def run_epoch_processing_case(preset: str, fork: str, handler: str,
                              case_dir: Path) -> Optional[bool]:
    cfg = fork_config(preset, fork)
    fn = _resolve_handler(fork, "epoch", f"process_{handler}")
    if fn is None:
        return None
    pre = _load_state(cfg, fork, case_dir / "pre.ssz_snappy")
    post_path = case_dir / "post.ssz_snappy"
    try:
        result = fn(cfg, pre)
    except Exception:
        return not post_path.exists()
    if not post_path.exists():
        return False                      # expected rejection
    post = _load_state(cfg, fork, post_path)
    return result.htr() == post.htr()


_OPERATION_FILES = {
    "attestation": ("attestation", "Attestation"),
    "attester_slashing": ("attester_slashing", "AttesterSlashing"),
    "block_header": ("block", "BeaconBlock"),
    "deposit": ("deposit", "Deposit"),
    "proposer_slashing": ("proposer_slashing", "ProposerSlashing"),
    "voluntary_exit": ("voluntary_exit", "SignedVoluntaryExit"),
    "sync_aggregate": ("sync_aggregate", "SyncAggregate"),
    "bls_to_execution_change": ("address_change",
                                "SignedBLSToExecutionChange"),
    "withdrawals": ("execution_payload", "ExecutionPayload"),
}


def run_operations_case(preset: str, fork: str, handler: str,
                        case_dir: Path) -> Optional[bool]:
    if handler not in _OPERATION_FILES:
        return None
    cfg = fork_config(preset, fork)
    S = schemas_for(cfg, fork)
    file_stem, schema_name = _OPERATION_FILES[handler]
    schema = getattr(S, schema_name, None)
    if schema is None:
        return None
    fn = _resolve_handler(fork, "block", f"process_{handler}")
    if fn is None:
        return None
    pre = _load_state(cfg, fork, case_dir / "pre.ssz_snappy")
    op = load_ssz_snappy(case_dir / f"{file_stem}.ssz_snappy", schema)
    post_path = case_dir / "post.ssz_snappy"
    args = [cfg, pre, op]
    if "verifier" in inspect.signature(fn).parameters:
        from .verifiers import SIMPLE
        args.append(SIMPLE)
    try:
        result = fn(*args)
    except Exception:
        return not post_path.exists()
    if not post_path.exists():
        return False
    post = _load_state(cfg, fork, post_path)
    return result.htr() == post.htr()


def run_sanity_slots_case(preset: str, fork: str,
                          case_dir: Path) -> bool:
    from .transition import process_slots
    cfg = fork_config(preset, fork)
    pre = _load_state(cfg, fork, case_dir / "pre.ssz_snappy")
    n_slots = _load_yaml(case_dir / "slots.yaml")
    post = _load_state(cfg, fork, case_dir / "post.ssz_snappy")
    result = process_slots(cfg, pre, pre.slot + int(n_slots))
    return result.htr() == post.htr()


def run_sanity_blocks_case(preset: str, fork: str,
                           case_dir: Path) -> bool:
    from .transition import state_transition
    cfg = fork_config(preset, fork)
    S = schemas_for(cfg, fork)
    meta = _load_yaml(case_dir / "meta.yaml") \
        if (case_dir / "meta.yaml").exists() else {}
    n_blocks = int(meta.get("blocks_count", 0))
    pre = _load_state(cfg, fork, case_dir / "pre.ssz_snappy")
    post_path = case_dir / "post.ssz_snappy"
    state = pre
    try:
        for i in range(n_blocks):
            signed = load_ssz_snappy(
                case_dir / f"blocks_{i}.ssz_snappy",
                S.SignedBeaconBlock)
            state = state_transition(cfg, state, signed,
                                     validate_result=True)
    except Exception:
        return not post_path.exists()
    if not post_path.exists():
        return False
    post = _load_state(cfg, fork, post_path)
    return state.htr() == post.htr()


def run_shuffling_case(preset: str, fork: str,
                       case_dir: Path) -> bool:
    """tests/<preset>/<fork>/shuffling/core/shuffle: full-mapping check
    of the swap-or-not shuffle (reference ShufflingTestExecutor)."""
    from . import helpers as H
    cfg = fork_config(preset, fork)
    data = _load_yaml(case_dir / "mapping.yaml")
    seed = _hx(data["seed"])
    count = int(data["count"])
    mapping = [int(v) for v in data["mapping"]]
    got = [H.compute_shuffled_index(cfg, i, count, seed)
           for i in range(count)]
    return got == mapping


def _deltas_schema():
    from ..ssz.types import Container, List, uint64

    class Deltas(Container):
        rewards: List(uint64, 2 ** 40)
        penalties: List(uint64, 2 ** 40)
    return Deltas


def run_rewards_case(preset: str, fork: str,
                     case_dir: Path) -> Optional[bool]:
    """tests/<preset>/<fork>/rewards/{basic,leak,random}: per-component
    attestation reward/penalty deltas (reference RewardsTestExecutor).
    Altair+ only — phase0 keeps its own aggregate path."""
    if fork == "phase0":
        return None
    from .altair import epoch as AE
    cfg = fork_config(preset, fork)
    pre = _load_state(cfg, fork, case_dir / "pre.ssz_snappy")
    Deltas = _deltas_schema()
    quotients = {
        "altair": cfg.INACTIVITY_PENALTY_QUOTIENT_ALTAIR,
    }
    inactivity_q = quotients.get(
        fork, cfg.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
    components = {
        "source_deltas": lambda: AE.get_flag_index_deltas(cfg, pre, 0),
        "target_deltas": lambda: AE.get_flag_index_deltas(cfg, pre, 1),
        "head_deltas": lambda: AE.get_flag_index_deltas(cfg, pre, 2),
        "inactivity_penalty_deltas": lambda:
            AE.get_inactivity_penalty_deltas(cfg, pre, inactivity_q),
    }
    for name, compute in components.items():
        path = case_dir / f"{name}.ssz_snappy"
        if not path.exists():
            continue
        want = load_ssz_snappy(path, Deltas)
        rewards, penalties = compute()
        if (tuple(rewards) != tuple(want.rewards)
                or tuple(penalties) != tuple(want.penalties)):
            return False
    return True


_UPGRADES = {
    "altair": ("phase0", "altair.fork", "upgrade_to_altair"),
    "bellatrix": ("altair", "bellatrix.fork", "upgrade_to_bellatrix"),
    "capella": ("bellatrix", "capella.fork", "upgrade_to_capella"),
    "deneb": ("capella", "deneb.fork", "upgrade_to_deneb"),
    "electra": ("deneb", "electra.fork", "upgrade_to_electra"),
}


def run_fork_upgrade_case(preset: str, fork: str,
                          case_dir: Path) -> Optional[bool]:
    """tests/<preset>/<fork>/fork/fork: the state upgrade at a fork
    boundary (reference ForkUpgradeTestExecutor)."""
    import importlib
    meta = _load_yaml(case_dir / "meta.yaml")
    target = meta.get("fork", fork)
    if target not in _UPGRADES:
        return None
    prev_fork, mod_name, fn_name = _UPGRADES[target]
    cfg = fork_config(preset, target)
    fn = getattr(importlib.import_module(f"teku_tpu.spec.{mod_name}"),
                 fn_name)
    pre = _load_state(cfg, prev_fork, case_dir / "pre.ssz_snappy")
    post = _load_state(cfg, target, case_dir / "post.ssz_snappy")
    return fn(cfg, pre).htr() == post.htr()


def run_transition_case(preset: str, fork: str,
                        case_dir: Path) -> Optional[bool]:
    """tests/<preset>/<fork>/transition/core: blocks crossing a fork
    boundary (reference TransitionTestExecutor); `fork` names the
    POST fork, meta gives the activation epoch."""
    from .transition import state_transition
    meta = _load_yaml(case_dir / "meta.yaml")
    post_fork = meta.get("post_fork", fork)
    if post_fork not in _UPGRADES:
        return None
    prev_fork = _UPGRADES[post_fork][0]
    fork_epoch = int(meta["fork_epoch"])
    cfg = dataclasses.replace(
        fork_config(preset, prev_fork),
        **{f"{post_fork.upper()}_FORK_EPOCH": fork_epoch})
    pre = _load_state(cfg, prev_fork, case_dir / "pre.ssz_snappy")
    n_blocks = int(meta["blocks_count"])
    fork_block = meta.get("fork_block")
    state = pre
    for i in range(n_blocks):
        src = prev_fork if (fork_block is not None
                            and i <= int(fork_block)) else post_fork
        signed = load_ssz_snappy(
            case_dir / f"blocks_{i}.ssz_snappy",
            schemas_for(cfg, src).SignedBeaconBlock)
        state = state_transition(cfg, state, signed,
                                 validate_result=True)
    post = _load_state(cfg, post_fork, case_dir / "post.ssz_snappy")
    return state.htr() == post.htr()


def run_fork_choice_case(preset: str, fork: str,
                         case_dir: Path) -> Optional[bool]:
    """tests/<preset>/<fork>/fork_choice/*: drive the real Store
    through the official step script and verify every `checks` block
    (reference ForkChoiceTestExecutor).  Returns None on steps this
    build doesn't model (merge pow_block / blob availability)."""
    from ..storage import ForkChoiceError, Store
    cfg = fork_config(preset, fork)
    S = schemas_for(cfg, fork)
    anchor_state = _load_state(cfg, fork,
                               case_dir / "anchor_state.ssz_snappy")
    anchor_block = load_ssz_snappy(case_dir / "anchor_block.ssz_snappy",
                                   S.BeaconBlock)
    store = Store(cfg, anchor_state, anchor_block)
    steps = _load_yaml(case_dir / "steps.yaml")
    for step in steps:
        if "tick" in step:
            store.on_tick(int(step["tick"]))
        elif "block" in step:
            if "blobs" in step:
                return None            # DA-gated import not modeled here
            signed = load_ssz_snappy(
                case_dir / f"{step['block']}.ssz_snappy",
                S.SignedBeaconBlock)
            valid = step.get("valid", True)
            from .block import BlockProcessingError
            try:
                store.on_block(signed)
                if not valid:
                    return False
            except (ForkChoiceError, BlockProcessingError):
                # PROTOCOL rejections only: an implementation crash
                # (AttributeError etc.) must propagate, not pass as an
                # expected-invalid verdict
                if valid:
                    return False
        elif "attestation" in step:
            att = load_ssz_snappy(
                case_dir / f"{step['attestation']}.ssz_snappy",
                S.Attestation)
            valid = step.get("valid", True)
            from .block import BlockProcessingError
            try:
                store.on_attestation(att)
                if not valid:
                    return False
            except (ForkChoiceError, BlockProcessingError, ValueError):
                if valid:
                    return False
        elif "checks" in step:
            checks = step["checks"]
            head = store.get_head()
            if "head" in checks:
                want = checks["head"]
                if head != _hx(want["root"]) \
                        or store.blocks[head].slot != int(want["slot"]):
                    return False
            if "time" in checks and store.time != int(checks["time"]):
                return False
            if "justified_checkpoint" in checks:
                want = checks["justified_checkpoint"]
                cp = store.justified_checkpoint
                if cp.epoch != int(want["epoch"]) \
                        or cp.root != _hx(want["root"]):
                    return False
            if "finalized_checkpoint" in checks:
                want = checks["finalized_checkpoint"]
                cp = store.finalized_checkpoint
                if cp.epoch != int(want["epoch"]) \
                        or cp.root != _hx(want["root"]):
                    return False
            if "proposer_boost_root" in checks:
                if store.proto.proposer_boost_root != _hx(
                        checks["proposer_boost_root"]):
                    return False
        else:
            return None                # pow_block / unmodeled step
    return True


def run_kzg_case(handler: str, case: dict, setup=None) -> Optional[bool]:
    """tests/general/deneb/kzg/<handler> data.yaml cases against the
    vendored REAL ceremony setup by default (reference KzgTests)."""
    from ..crypto import kzg
    inp = case["input"]
    out = case.get("output")
    setup = setup or kzg.get_setup()
    try:
        if handler == "blob_to_kzg_commitment":
            got = kzg.blob_to_kzg_commitment(_hx(inp["blob"]), setup)
            return out is not None and got == _hx(out)
        if handler == "compute_blob_kzg_proof":
            got = kzg.compute_blob_kzg_proof(
                _hx(inp["blob"]), _hx(inp["commitment"]), setup)
            return out is not None and got == _hx(out)
        if handler == "verify_blob_kzg_proof":
            got = kzg.verify_blob_kzg_proof(
                _hx(inp["blob"]), _hx(inp["commitment"]),
                _hx(inp["proof"]), setup)
            # output null = malformed input: the facade REJECTS
            # (returns False) where the vector expects an error
            return got is False if out is None else got == out
        if handler == "verify_blob_kzg_proof_batch":
            got = kzg.verify_blob_kzg_proof_batch(
                [_hx(b) for b in inp["blobs"]],
                [_hx(c) for c in inp["commitments"]],
                [_hx(p) for p in inp["proofs"]], setup)
            return got is False if out is None else got == out
        if handler == "compute_kzg_proof":
            poly = kzg.blob_to_polynomial(_hx(inp["blob"]))
            proof, y = kzg.compute_kzg_proof_impl(
                poly, kzg.bytes_to_bls_field(_hx(inp["z"])), setup)
            return (out is not None and proof == _hx(out[0])
                    and y == int.from_bytes(_hx(out[1]), "big"))
        if handler == "verify_kzg_proof":
            from ..crypto.bls import curve as CV
            c_pt = CV.g1_decompress(_hx(inp["commitment"]))
            p_pt = CV.g1_decompress(_hx(inp["proof"]))
            got = kzg.verify_kzg_proof_impl(
                c_pt, kzg.bytes_to_bls_field(_hx(inp["z"])),
                kzg.bytes_to_bls_field(_hx(inp["y"])), p_pt, setup)
            return got == out
    except Exception:
        return out is None
    return None


def run_merkle_proof_case(preset: str, fork: str,
                          case_dir: Path) -> Optional[bool]:
    """light_client/single_merkle_proof: branch verification against
    the object's hash tree root (reference MerkleProofTests).  The
    object's type is the SUITE directory name in the official layout
    (…/single_merkle_proof/<TypeName>/<case>)."""
    from . import helpers as H
    cfg = fork_config(preset, fork)
    S = schemas_for(cfg, fork)
    type_name = case_dir.parent.name
    schema = getattr(S, type_name, None)
    if schema is None:
        return None
    obj = load_ssz_snappy(case_dir / "object.ssz_snappy", schema)
    proof = _load_yaml(case_dir / "proof.yaml")
    gindex = int(proof["leaf_index"])
    depth = gindex.bit_length() - 1
    index = gindex - (1 << depth)
    return H.is_valid_merkle_branch(
        _hx(proof["leaf"]), [_hx(b) for b in proof["branch"]],
        depth, index, obj.htr())


def run_ssz_static_case(preset: str, fork: str, type_name: str,
                        case_dir: Path) -> Optional[bool]:
    cfg = fork_config(preset, fork)
    S = schemas_for(cfg, fork)
    schema = getattr(S, type_name, None)
    if schema is None:
        return None
    raw = snappyc.uncompress(
        (case_dir / "serialized.ssz_snappy").read_bytes())
    roots = _load_yaml(case_dir / "roots.yaml")
    value = schema.deserialize(raw)
    if value.htr() != _hx(roots["root"]):
        return False
    return schema.serialize(value) == raw
