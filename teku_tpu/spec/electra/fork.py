"""Deneb → electra fork upgrade (spec upgrade_to_electra): initialize
the churn cursors from the live exit queue, convert not-yet-activated
validators into pending deposits, queue compounding validators'
excess balance."""

from .. import helpers as H
from ..config import (FAR_FUTURE_EPOCH, SpecConfig,
                      UNSET_DEPOSIT_REQUESTS_START_INDEX)
from ..datastructures import Fork
from . import helpers as EH
from .datastructures import PendingDeposit, get_electra_schemas


def upgrade_to_electra(cfg: SpecConfig, pre):
    from ...crypto.bls.pure_impl import G2_INFINITY
    S = get_electra_schemas(cfg)
    epoch = H.get_current_epoch(cfg, pre)
    earliest_exit_epoch = H.compute_activation_exit_epoch(cfg, epoch)
    for v in pre.validators:
        if v.exit_epoch != FAR_FUTURE_EPOCH:
            earliest_exit_epoch = max(earliest_exit_epoch, v.exit_epoch)
    earliest_exit_epoch += 1

    fields = {name: getattr(pre, name)
              for name in type(pre)._ssz_fields}
    fields["fork"] = Fork(previous_version=pre.fork.current_version,
                          current_version=cfg.ELECTRA_FORK_VERSION,
                          epoch=epoch)
    post = S.BeaconState(
        **fields,
        deposit_requests_start_index=UNSET_DEPOSIT_REQUESTS_START_INDEX,
        deposit_balance_to_consume=0,
        exit_balance_to_consume=0,
        earliest_exit_epoch=earliest_exit_epoch,
        consolidation_balance_to_consume=0,
        earliest_consolidation_epoch=H.compute_activation_exit_epoch(
            cfg, epoch),
        pending_deposits=(), pending_partial_withdrawals=(),
        pending_consolidations=())
    post = post.copy_with(
        exit_balance_to_consume=EH.get_activation_exit_churn_limit(
            cfg, post),
        consolidation_balance_to_consume=EH.get_consolidation_churn_limit(
            cfg, post))

    # validators still waiting for activation re-enter via the queue
    pre_activation = sorted(
        (i for i, v in enumerate(post.validators)
         if v.activation_epoch == FAR_FUTURE_EPOCH),
        key=lambda i: (post.validators[i].activation_eligibility_epoch,
                       i))
    if pre_activation:
        validators = list(post.validators)
        balances = list(post.balances)
        pending = list(post.pending_deposits)
        for i in pre_activation:
            v = validators[i]
            pending.append(PendingDeposit(
                pubkey=v.pubkey,
                withdrawal_credentials=v.withdrawal_credentials,
                amount=balances[i], signature=G2_INFINITY, slot=0))
            balances[i] = 0
            validators[i] = v.copy_with(
                effective_balance=0,
                activation_eligibility_epoch=FAR_FUTURE_EPOCH)
        post = post.copy_with(validators=tuple(validators),
                              balances=tuple(balances),
                              pending_deposits=tuple(pending))
    for i, v in enumerate(post.validators):
        if EH.has_compounding_withdrawal_credential(v):
            post = EH.queue_excess_active_balance(cfg, post, i)
    return post
