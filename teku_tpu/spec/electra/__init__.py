"""Electra milestone: EIP-7251 max-effective-balance increase with
balance-denominated churn, EIP-7002 execution-layer withdrawal
requests, EIP-6110 in-protocol deposit requests, EIP-7549 committee
bits on attestations.

reference: ethereum/spec/src/main/java/tech/pegasys/teku/spec/logic/
versions/electra/ and datastructures/.../versions/electra/.
"""
