"""Electra helper functions: compounding credentials, gwei-denominated
churn, balance-scheduled exits and consolidations.

reference: ethereum/spec/.../logic/versions/electra/helpers/
{PredicatesElectra,BeaconStateAccessorsElectra,BeaconStateMutatorsElectra,
MiscHelpersElectra}.java.
"""

from .. import helpers as H
from ..config import (COMPOUNDING_WITHDRAWAL_PREFIX,
                      ETH1_ADDRESS_WITHDRAWAL_PREFIX, FAR_FUTURE_EPOCH,
                      SpecConfig)


# ---- credential predicates ----

def is_compounding_withdrawal_credential(creds: bytes) -> bool:
    return creds[:1] == COMPOUNDING_WITHDRAWAL_PREFIX


def has_compounding_withdrawal_credential(validator) -> bool:
    return is_compounding_withdrawal_credential(
        validator.withdrawal_credentials)


def has_eth1_withdrawal_credential(validator) -> bool:
    return validator.withdrawal_credentials[:1] \
        == ETH1_ADDRESS_WITHDRAWAL_PREFIX


def has_execution_withdrawal_credential(validator) -> bool:
    """0x01 or 0x02 credential: the validator can be reached by
    execution-layer triggered operations."""
    return (has_compounding_withdrawal_credential(validator)
            or has_eth1_withdrawal_credential(validator))


def get_max_effective_balance(cfg: SpecConfig, validator) -> int:
    return (cfg.MAX_EFFECTIVE_BALANCE_ELECTRA
            if has_compounding_withdrawal_credential(validator)
            else cfg.MIN_ACTIVATION_BALANCE)


# ---- gwei-denominated churn (replaces the validator-count churn) ----

def get_balance_churn_limit(cfg: SpecConfig, state) -> int:
    churn = max(cfg.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA,
                H.get_total_active_balance(cfg, state)
                // cfg.CHURN_LIMIT_QUOTIENT)
    return churn - churn % cfg.EFFECTIVE_BALANCE_INCREMENT


def get_activation_exit_churn_limit(cfg: SpecConfig, state) -> int:
    return min(cfg.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT,
               get_balance_churn_limit(cfg, state))


def get_consolidation_churn_limit(cfg: SpecConfig, state) -> int:
    return (get_balance_churn_limit(cfg, state)
            - get_activation_exit_churn_limit(cfg, state))


def get_pending_balance_to_withdraw(state, validator_index: int) -> int:
    return sum(w.amount for w in state.pending_partial_withdrawals
               if w.validator_index == validator_index)


# ---- balance-scheduled exits / consolidations ----

def compute_exit_epoch_and_update_churn(cfg: SpecConfig, state,
                                        exit_balance: int):
    """(state', exit_epoch): schedule `exit_balance` gwei of exits,
    rolling the queue forward by whole epochs of churn (spec
    compute_exit_epoch_and_update_churn — the state carries the
    running earliest_exit_epoch / exit_balance_to_consume pair)."""
    earliest = max(state.earliest_exit_epoch,
                   H.compute_activation_exit_epoch(
                       cfg, H.get_current_epoch(cfg, state)))
    per_epoch = get_activation_exit_churn_limit(cfg, state)
    if state.earliest_exit_epoch < earliest:
        to_consume = per_epoch
    else:
        to_consume = state.exit_balance_to_consume
    if exit_balance > to_consume:
        extra = exit_balance - to_consume
        additional_epochs = (extra - 1) // per_epoch + 1
        earliest += additional_epochs
        to_consume += additional_epochs * per_epoch
    state = state.copy_with(exit_balance_to_consume=to_consume
                            - exit_balance,
                            earliest_exit_epoch=earliest)
    return state, earliest


def compute_consolidation_epoch_and_update_churn(cfg: SpecConfig, state,
                                                 balance: int):
    earliest = max(state.earliest_consolidation_epoch,
                   H.compute_activation_exit_epoch(
                       cfg, H.get_current_epoch(cfg, state)))
    per_epoch = get_consolidation_churn_limit(cfg, state)
    if state.earliest_consolidation_epoch < earliest:
        to_consume = per_epoch
    else:
        to_consume = state.consolidation_balance_to_consume
    if balance > to_consume:
        extra = balance - to_consume
        additional_epochs = (extra - 1) // per_epoch + 1
        earliest += additional_epochs
        to_consume += additional_epochs * per_epoch
    state = state.copy_with(
        consolidation_balance_to_consume=to_consume - balance,
        earliest_consolidation_epoch=earliest)
    return state, earliest


def initiate_validator_exit(cfg: SpecConfig, state, index: int):
    """Electra initiate_validator_exit: the exit epoch comes from the
    balance churn, not the per-validator-count queue."""
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return state
    state, exit_epoch = compute_exit_epoch_and_update_churn(
        cfg, state, v.effective_balance)
    validators = list(state.validators)
    validators[index] = v.copy_with(
        exit_epoch=exit_epoch,
        withdrawable_epoch=exit_epoch
        + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
    return state.copy_with(validators=tuple(validators))


def switch_to_compounding_validator(cfg: SpecConfig, state, index: int):
    v = state.validators[index]
    validators = list(state.validators)
    validators[index] = v.copy_with(
        withdrawal_credentials=COMPOUNDING_WITHDRAWAL_PREFIX
        + v.withdrawal_credentials[1:])
    state = state.copy_with(validators=tuple(validators))
    return queue_excess_active_balance(cfg, state, index)


def queue_excess_active_balance(cfg: SpecConfig, state, index: int):
    """Balance above MIN_ACTIVATION_BALANCE re-enters via the pending
    deposit queue when a validator turns compounding."""
    balance = state.balances[index]
    if balance <= cfg.MIN_ACTIVATION_BALANCE:
        return state
    from .datastructures import PendingDeposit
    from ...crypto.bls.pure_impl import G2_INFINITY
    excess = balance - cfg.MIN_ACTIVATION_BALANCE
    v = state.validators[index]
    balances = list(state.balances)
    balances[index] = cfg.MIN_ACTIVATION_BALANCE
    return state.copy_with(
        balances=tuple(balances),
        pending_deposits=tuple(state.pending_deposits) + (PendingDeposit(
            pubkey=v.pubkey,
            withdrawal_credentials=v.withdrawal_credentials,
            amount=excess, signature=G2_INFINITY, slot=0),))


def get_committee_indices(committee_bits) -> list:
    return [i for i, bit in enumerate(committee_bits) if bit]


def get_attesting_indices(cfg: SpecConfig, state, attestation) -> set:
    """EIP-7549: aggregation bits span the concatenation of the slot's
    committees selected in committee_bits."""
    out = set()
    offset = 0
    bits = attestation.aggregation_bits
    for ci in get_committee_indices(attestation.committee_bits):
        committee = H.get_beacon_committee(cfg, state,
                                           attestation.data.slot, ci)
        for j, validator_index in enumerate(committee):
            if bits[offset + j]:
                out.add(validator_index)
        offset += len(committee)
    return out
