"""Electra containers: pending queues on the state, execution
requests in the body, committee-bits attestations.

reference: ethereum/spec/.../spec/datastructures/ — operations/versions/
electra/AttestationElectra.java, execution/versions/electra/
{DepositRequest,WithdrawalRequest,ConsolidationRequest,ExecutionRequests}
.java, state/versions/electra/BeaconStateElectra.java (pending_deposits /
pending_partial_withdrawals / pending_consolidations + churn cursors).
"""

from functools import lru_cache

from ...ssz import (Bitlist, Bitvector, Bytes20, Bytes32, Bytes48,
                    Bytes96, Container, List, uint64)
from ..config import SpecConfig
from ..datastructures import AttestationData, Checkpoint
from ..bellatrix.datastructures import _container
from ..deneb.datastructures import get_deneb_schemas


class PendingDeposit(Container):
    pubkey: Bytes48
    withdrawal_credentials: Bytes32
    amount: uint64
    signature: Bytes96
    slot: uint64


class PendingPartialWithdrawal(Container):
    validator_index: uint64
    amount: uint64
    withdrawable_epoch: uint64


class PendingConsolidation(Container):
    source_index: uint64
    target_index: uint64


class DepositRequest(Container):
    pubkey: Bytes48
    withdrawal_credentials: Bytes32
    amount: uint64
    signature: Bytes96
    index: uint64


class WithdrawalRequest(Container):
    source_address: Bytes20
    validator_pubkey: Bytes48
    amount: uint64


class ConsolidationRequest(Container):
    source_address: Bytes20
    source_pubkey: Bytes48
    target_pubkey: Bytes48


class ElectraSchemas:
    def __getattr__(self, name):
        if name == "deneb":
            raise AttributeError(name)
        return getattr(self.deneb, name)

    def __init__(self, cfg: SpecConfig):
        self.config = cfg
        self.deneb = get_deneb_schemas(cfg)
        D = self.deneb
        self.PendingDeposit = PendingDeposit
        self.PendingPartialWithdrawal = PendingPartialWithdrawal
        self.PendingConsolidation = PendingConsolidation
        self.DepositRequest = DepositRequest
        self.WithdrawalRequest = WithdrawalRequest
        self.ConsolidationRequest = ConsolidationRequest
        self.ExecutionRequests = _container("ExecutionRequests", [
            ("deposits", List(DepositRequest,
                              cfg.MAX_DEPOSIT_REQUESTS_PER_PAYLOAD)),
            ("withdrawals", List(WithdrawalRequest,
                                 cfg.MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD)),
            ("consolidations", List(
                ConsolidationRequest,
                cfg.MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD)),
        ])

        # EIP-7549 attestation shapes: bits span all selected committees
        max_agg_bits = (cfg.MAX_VALIDATORS_PER_COMMITTEE
                        * cfg.MAX_COMMITTEES_PER_SLOT)
        self.Attestation = _container("AttestationElectra", [
            ("aggregation_bits", Bitlist(max_agg_bits)),
            ("data", AttestationData),
            ("signature", Bytes96),
            ("committee_bits", Bitvector(cfg.MAX_COMMITTEES_PER_SLOT)),
        ])
        self.IndexedAttestation = _container("IndexedAttestationElectra", [
            ("attesting_indices", List(uint64, max_agg_bits)),
            ("data", AttestationData),
            ("signature", Bytes96),
        ])
        self.AggregateAndProof = _container("AggregateAndProofElectra", [
            ("aggregator_index", uint64),
            ("aggregate", self.Attestation),
            ("selection_proof", Bytes96),
        ])
        self.SignedAggregateAndProof = _container(
            "SignedAggregateAndProofElectra", [
                ("message", self.AggregateAndProof),
                ("signature", Bytes96),
            ])
        # gossip-only single attestation (replaces the one-bit
        # aggregate on attestation subnets)
        self.SingleAttestation = _container("SingleAttestation", [
            ("committee_index", uint64),
            ("attester_index", uint64),
            ("data", AttestationData),
            ("signature", Bytes96),
        ])

        body_fields = dict(D.BeaconBlockBody._ssz_fields.items())
        body_fields["attestations"] = List(self.Attestation,
                                           cfg.MAX_ATTESTATIONS_ELECTRA)
        body_fields["attester_slashings"] = List(
            _container("AttesterSlashingElectra", [
                ("attestation_1", self.IndexedAttestation),
                ("attestation_2", self.IndexedAttestation),
            ]), cfg.MAX_ATTESTER_SLASHINGS_ELECTRA)
        body_fields["execution_requests"] = self.ExecutionRequests
        self.BeaconBlockBody = _container("BeaconBlockBodyElectra",
                                          body_fields.items())
        self.BeaconBlock = _container("BeaconBlockElectra", [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = _container("SignedBeaconBlockElectra", [
            ("message", self.BeaconBlock),
            ("signature", Bytes96),
        ])

        state_fields = dict(D.BeaconState._ssz_fields.items())
        state_fields.update([
            ("deposit_requests_start_index", uint64),
            ("deposit_balance_to_consume", uint64),
            ("exit_balance_to_consume", uint64),
            ("earliest_exit_epoch", uint64),
            ("consolidation_balance_to_consume", uint64),
            ("earliest_consolidation_epoch", uint64),
            ("pending_deposits", List(PendingDeposit,
                                      cfg.PENDING_DEPOSITS_LIMIT)),
            ("pending_partial_withdrawals", List(
                PendingPartialWithdrawal,
                cfg.PENDING_PARTIAL_WITHDRAWALS_LIMIT)),
            ("pending_consolidations", List(
                PendingConsolidation, cfg.PENDING_CONSOLIDATIONS_LIMIT)),
        ])
        self.BeaconState = _container("BeaconStateElectra",
                                      state_fields.items())


@lru_cache(maxsize=8)
def get_electra_schemas(cfg: SpecConfig) -> ElectraSchemas:
    return ElectraSchemas(cfg)
