"""Electra block processing: committee-bits attestations (EIP-7549),
pending-queue deposits (EIP-6110/7251), execution-layer withdrawal and
consolidation requests (EIP-7002/7251), partial-withdrawal-aware sweep.

reference: ethereum/spec/.../logic/versions/electra/block/
BlockProcessorElectra.java (processDepositRequest,
processWithdrawalRequest, processConsolidationRequest,
processAttestation with committee bits) and util/AttestationUtilElectra.
"""

from ...crypto import bls
from .. import block as B0
from .. import helpers as H
from ..altair import block as AB
from ..bellatrix import block as BB
from ..capella import block as CB
from ..capella.datastructures import Withdrawal
from ..config import (DOMAIN_BEACON_ATTESTER, DOMAIN_DEPOSIT,
                      DOMAIN_VOLUNTARY_EXIT, FAR_FUTURE_EPOCH,
                      FULL_EXIT_REQUEST_AMOUNT, GENESIS_SLOT,
                      UNSET_DEPOSIT_REQUESTS_START_INDEX, SpecConfig)
from ..datastructures import DepositMessage
from ..deneb import block as DB
from ..deneb.datastructures import payload_to_header_deneb
from ..verifiers import SignatureVerifier, SIMPLE
from . import helpers as EH
from .datastructures import PendingDeposit, PendingPartialWithdrawal, \
    PendingConsolidation, get_electra_schemas

_require = B0._require


# ---- attestations (EIP-7549) ----

def process_attestation(cfg: SpecConfig, state, attestation,
                        verifier: SignatureVerifier):
    """Committee-bits shape checks, then altair's flag accounting with
    the electra-resolved attesting set."""
    data = attestation.data
    _require(data.index == 0, "electra attestations carry index 0")
    _require(data.target.epoch in (H.get_previous_epoch(cfg, state),
                                   H.get_current_epoch(cfg, state)),
             "target epoch out of range")
    _require(data.target.epoch == H.compute_epoch_at_slot(cfg, data.slot),
             "target/slot mismatch")
    _require(data.slot + cfg.MIN_ATTESTATION_INCLUSION_DELAY
             <= state.slot, "inclusion delay")
    committee_indices = EH.get_committee_indices(
        attestation.committee_bits)
    _require(committee_indices, "no committee bit set")
    per_slot = H.get_committee_count_per_slot(cfg, state,
                                              data.target.epoch)
    offset = 0
    for ci in committee_indices:
        _require(ci < per_slot, "committee index out of range")
        committee = H.get_beacon_committee(cfg, state, data.slot, ci)
        bits = attestation.aggregation_bits
        _require(any(bits[offset + j] for j in range(len(committee))),
                 "a selected committee has no attester")
        offset += len(committee)
    _require(len(attestation.aggregation_bits) == offset,
             "bits length != sum of selected committees")

    justified = (state.current_justified_checkpoint
                 if data.target.epoch == H.get_current_epoch(cfg, state)
                 else state.previous_justified_checkpoint)
    _require(data.source == justified, "wrong source checkpoint")

    indexed = get_indexed_attestation(cfg, state, attestation)
    _require(B0.is_valid_indexed_attestation(cfg, state, indexed,
                                             verifier),
             "bad attestation signature")
    return AB._apply_participation_rewards(
        cfg, state, data, EH.get_attesting_indices(cfg, state,
                                                   attestation),
        cap_target_delay=False)


def get_indexed_attestation(cfg: SpecConfig, state, attestation):
    S = get_electra_schemas(cfg)
    indices = sorted(EH.get_attesting_indices(cfg, state, attestation))
    return S.IndexedAttestation(attesting_indices=tuple(indices),
                                data=attestation.data,
                                signature=attestation.signature)


# ---- deposits: the pending queue (EIP-6110 + EIP-7251) ----

def add_validator_to_registry(cfg: SpecConfig, state, pubkey: bytes,
                              withdrawal_credentials: bytes, amount: int):
    """New registry row (+ the altair participation/inactivity rows)."""
    state = state.copy_with(
        validators=tuple(state.validators)
        + (B0.get_validator_from_deposit(
            cfg, pubkey, withdrawal_credentials, amount),),
        balances=tuple(state.balances) + (amount,),
        previous_epoch_participation=(
            tuple(state.previous_epoch_participation) + (0,)),
        current_epoch_participation=(
            tuple(state.current_epoch_participation) + (0,)),
        inactivity_scores=tuple(state.inactivity_scores) + (0,))
    return state


def is_valid_deposit_signature(cfg: SpecConfig, pubkey, creds, amount,
                               signature,
                               deposit_verifier: SignatureVerifier) -> bool:
    msg = DepositMessage(pubkey=pubkey, withdrawal_credentials=creds,
                         amount=amount)
    domain = H.compute_domain(DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION,
                              bytes(32))
    root = H.compute_signing_root(msg, domain)
    return deposit_verifier.verify([pubkey], root, signature)


def apply_deposit(cfg: SpecConfig, state, pubkey, creds, amount,
                  signature,
                  deposit_verifier: SignatureVerifier = SIMPLE):
    """Electra apply_deposit: balances only ever move through the
    pending-deposit queue; a brand-new pubkey still needs its eager
    proof-of-possession before a zero-balance registry row is added."""
    pubkeys = [v.pubkey for v in state.validators]
    if pubkey not in pubkeys:
        if not is_valid_deposit_signature(cfg, pubkey, creds, amount,
                                          signature, deposit_verifier):
            return state
        state = add_validator_to_registry(cfg, state, pubkey, creds, 0)
    return state.copy_with(
        pending_deposits=tuple(state.pending_deposits)
        + (PendingDeposit(pubkey=pubkey, withdrawal_credentials=creds,
                          amount=amount, signature=signature,
                          slot=GENESIS_SLOT),))


def process_deposit(cfg: SpecConfig, state, deposit,
                    deposit_verifier: SignatureVerifier = SIMPLE):
    _require(H.is_valid_merkle_branch(
        deposit.data.htr(), deposit.proof,
        cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1, state.eth1_deposit_index,
        state.eth1_data.deposit_root), "bad deposit proof")
    state = state.copy_with(
        eth1_deposit_index=state.eth1_deposit_index + 1)
    return apply_deposit(cfg, state, deposit.data.pubkey,
                         deposit.data.withdrawal_credentials,
                         deposit.data.amount, deposit.data.signature,
                         deposit_verifier)


def process_deposit_request(cfg: SpecConfig, state, request):
    """EIP-6110: deposits surface straight from the payload."""
    if state.deposit_requests_start_index \
            == UNSET_DEPOSIT_REQUESTS_START_INDEX:
        state = state.copy_with(
            deposit_requests_start_index=request.index)
    return state.copy_with(
        pending_deposits=tuple(state.pending_deposits)
        + (PendingDeposit(pubkey=request.pubkey,
                          withdrawal_credentials=request
                          .withdrawal_credentials,
                          amount=request.amount,
                          signature=request.signature,
                          slot=state.slot),))


# ---- EL-triggered withddrawals / consolidations ----

def _pubkey_index_map(state):
    """One pubkey→index map per block's request batch; request handlers
    take it instead of scanning the registry per request."""
    return {v.pubkey: i for i, v in enumerate(state.validators)}


def process_withdrawal_request(cfg: SpecConfig, state, request,
                               index_by_pubkey=None):
    """EIP-7002: the EL can exit (amount=0) or skim (amount>0) any
    validator whose 0x01/0x02 credential commits to the caller."""
    amount = request.amount
    is_full_exit = amount == FULL_EXIT_REQUEST_AMOUNT
    # partial withdrawals only for compounding validators
    if index_by_pubkey is None:
        index_by_pubkey = _pubkey_index_map(state)
    index = index_by_pubkey.get(request.validator_pubkey)
    if index is None:
        return state
    v = state.validators[index]
    if not (is_full_exit
            or EH.has_compounding_withdrawal_credential(v)):
        return state
    if len(state.pending_partial_withdrawals) \
            >= cfg.PENDING_PARTIAL_WITHDRAWALS_LIMIT and not is_full_exit:
        return state
    if not EH.has_execution_withdrawal_credential(v):
        return state
    if v.withdrawal_credentials[12:] != request.source_address:
        return state
    now = H.get_current_epoch(cfg, state)
    if not H.is_active_validator(v, now):
        return state
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return state
    if now < v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD:
        return state

    pending_balance = EH.get_pending_balance_to_withdraw(state, index)
    if is_full_exit:
        # only exit when nothing is still queued to withdraw
        if pending_balance == 0:
            state = EH.initiate_validator_exit(cfg, state, index)
        return state
    has_sufficient = v.effective_balance >= cfg.MIN_ACTIVATION_BALANCE
    has_excess = (state.balances[index]
                  > cfg.MIN_ACTIVATION_BALANCE + pending_balance)
    if not (has_sufficient and has_excess):
        return state
    to_withdraw = min(state.balances[index]
                      - cfg.MIN_ACTIVATION_BALANCE - pending_balance,
                      amount)
    state, withdrawable_epoch = EH.compute_exit_epoch_and_update_churn(
        cfg, state, to_withdraw)
    withdrawable_epoch += cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    return state.copy_with(
        pending_partial_withdrawals=tuple(
            state.pending_partial_withdrawals)
        + (PendingPartialWithdrawal(validator_index=index,
                                    amount=to_withdraw,
                                    withdrawable_epoch=withdrawable_epoch),))


def process_consolidation_request(cfg: SpecConfig, state, request,
                                  index_by_pubkey=None):
    if index_by_pubkey is None:
        index_by_pubkey = _pubkey_index_map(state)
    if _is_valid_switch_to_compounding(cfg, state, request,
                                       index_by_pubkey):
        index = index_by_pubkey[request.source_pubkey]
        return EH.switch_to_compounding_validator(cfg, state, index)
    # churn must leave room for at least one increment
    if EH.get_consolidation_churn_limit(cfg, state) \
            <= cfg.MIN_ACTIVATION_BALANCE:
        return state
    if len(state.pending_consolidations) \
            >= cfg.PENDING_CONSOLIDATIONS_LIMIT:
        return state
    source_index = index_by_pubkey.get(request.source_pubkey)
    target_index = index_by_pubkey.get(request.target_pubkey)
    if source_index is None or target_index is None:
        return state
    if source_index == target_index:
        return state
    source = state.validators[source_index]
    target = state.validators[target_index]
    if not EH.has_execution_withdrawal_credential(source):
        return state
    if not EH.has_compounding_withdrawal_credential(target):
        return state
    if source.withdrawal_credentials[12:] != request.source_address:
        return state
    now = H.get_current_epoch(cfg, state)
    if not (H.is_active_validator(source, now)
            and H.is_active_validator(target, now)):
        return state
    if source.exit_epoch != FAR_FUTURE_EPOCH \
            or target.exit_epoch != FAR_FUTURE_EPOCH:
        return state
    # the source must have been active a full shard-committee period,
    # like any exit
    if now < source.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD:
        return state
    if EH.get_pending_balance_to_withdraw(state, source_index) > 0:
        return state
    state, exit_epoch = EH.compute_consolidation_epoch_and_update_churn(
        cfg, state, source.effective_balance)
    validators = list(state.validators)
    validators[source_index] = validators[source_index].copy_with(
        exit_epoch=exit_epoch,
        withdrawable_epoch=exit_epoch
        + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
    return state.copy_with(
        validators=tuple(validators),
        pending_consolidations=tuple(state.pending_consolidations)
        + (PendingConsolidation(source_index=source_index,
                                target_index=target_index),))


def _is_valid_switch_to_compounding(cfg, state, request,
                                    index_by_pubkey) -> bool:
    """Self-consolidation = credential upgrade in place."""
    if request.source_pubkey != request.target_pubkey:
        return False
    index = index_by_pubkey.get(request.source_pubkey)
    if index is None:
        return False
    source = state.validators[index]
    if not EH.has_eth1_withdrawal_credential(source):
        return False
    if source.withdrawal_credentials[12:] != request.source_address:
        return False
    now = H.get_current_epoch(cfg, state)
    return (H.is_active_validator(source, now)
            and source.exit_epoch == FAR_FUTURE_EPOCH)


# ---- exits ----

def process_voluntary_exit(cfg: SpecConfig, state, signed_exit,
                           verifier: SignatureVerifier):
    exit_msg = signed_exit.message
    _require(exit_msg.validator_index < len(state.validators),
             "exit: unknown validator")
    v = state.validators[exit_msg.validator_index]
    now = H.get_current_epoch(cfg, state)
    _require(H.is_active_validator(v, now), "exit: not active")
    _require(v.exit_epoch == FAR_FUTURE_EPOCH, "exit: already exiting")
    _require(now >= exit_msg.epoch, "exit: future epoch")
    _require(now >= v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD,
             "exit: too young")
    # EIP-7251: nothing may still be queued for partial withdrawal
    _require(EH.get_pending_balance_to_withdraw(
        state, exit_msg.validator_index) == 0,
        "exit: pending partial withdrawals")
    # EIP-7044 pinned domain, carried over from deneb
    domain = H.compute_domain(DOMAIN_VOLUNTARY_EXIT,
                              cfg.CAPELLA_FORK_VERSION,
                              state.genesis_validators_root)
    root = H.compute_signing_root(exit_msg, domain)
    _require(verifier.verify([v.pubkey], root, signed_exit.signature),
             "exit: bad signature")
    return EH.initiate_validator_exit(cfg, state,
                                      exit_msg.validator_index)


# ---- withdrawals (partial queue + electra-predicate sweep) ----

def is_fully_withdrawable_validator(cfg, validator, balance, epoch):
    return (EH.has_execution_withdrawal_credential(validator)
            and validator.withdrawable_epoch <= epoch and balance > 0)


def is_partially_withdrawable_validator(cfg, validator, balance):
    max_eb = EH.get_max_effective_balance(cfg, validator)
    return (EH.has_execution_withdrawal_credential(validator)
            and validator.effective_balance == max_eb
            and balance > max_eb)


def get_expected_withdrawals(cfg: SpecConfig, state):
    """(withdrawals, processed_partials_count): the pending partial
    queue drains first (bounded), then the capella-style sweep with
    electra balance predicates."""
    epoch = H.get_current_epoch(cfg, state)
    withdrawal_index = state.next_withdrawal_index
    withdrawals = []
    processed_partials = 0
    for w in state.pending_partial_withdrawals:
        if (w.withdrawable_epoch > epoch
                or len(withdrawals)
                == cfg.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP):
            break
        v = state.validators[w.validator_index]
        balance = state.balances[w.validator_index]
        if (v.exit_epoch == FAR_FUTURE_EPOCH
                and v.effective_balance >= cfg.MIN_ACTIVATION_BALANCE
                and balance > cfg.MIN_ACTIVATION_BALANCE):
            withdrawals.append(Withdrawal(
                index=withdrawal_index,
                validator_index=w.validator_index,
                address=v.withdrawal_credentials[12:],
                amount=min(balance - cfg.MIN_ACTIVATION_BALANCE,
                           w.amount)))
            withdrawal_index += 1
        processed_partials += 1

    validator_index = state.next_withdrawal_validator_index
    n = len(state.validators)
    from .. import vectorized as _V
    if n >= _V.VECTOR_THRESHOLD:
        skip = {}
        for w in withdrawals:
            skip[w.validator_index] = skip.get(w.validator_index, 0) \
                + w.amount
        cap = cfg.MAX_WITHDRAWALS_PER_PAYLOAD - len(withdrawals)
        for vi, amount in _V.sweep_withdrawal_hits(
                cfg, state, electra=True, skip_amounts=skip)[:cap]:
            withdrawals.append(Withdrawal(
                index=withdrawal_index, validator_index=vi,
                address=state.validators[vi]
                .withdrawal_credentials[12:], amount=amount))
            withdrawal_index += 1
        return withdrawals, processed_partials
    for _ in range(min(n, cfg.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        v = state.validators[validator_index]
        partially_withdrawn = sum(
            w.amount for w in withdrawals
            if w.validator_index == validator_index)
        balance = state.balances[validator_index] - partially_withdrawn
        address = v.withdrawal_credentials[12:]
        if is_fully_withdrawable_validator(cfg, v, balance, epoch):
            withdrawals.append(Withdrawal(
                index=withdrawal_index, validator_index=validator_index,
                address=address, amount=balance))
            withdrawal_index += 1
        elif is_partially_withdrawable_validator(cfg, v, balance):
            withdrawals.append(Withdrawal(
                index=withdrawal_index, validator_index=validator_index,
                address=address,
                amount=balance - EH.get_max_effective_balance(cfg, v)))
            withdrawal_index += 1
        if len(withdrawals) == cfg.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = (validator_index + 1) % n
    return withdrawals, processed_partials


def process_withdrawals(cfg: SpecConfig, state, payload):
    expected, processed_partials = get_expected_withdrawals(cfg, state)
    _require(len(payload.withdrawals) == len(expected),
             "withdrawals: wrong count in payload")
    for got, want in zip(payload.withdrawals, expected):
        _require(got == want, "withdrawals: payload/sweep mismatch")
        state = H.decrease_balance(state, want.validator_index,
                                   want.amount)
    state = state.copy_with(
        pending_partial_withdrawals=tuple(
            state.pending_partial_withdrawals)[processed_partials:])
    n = len(state.validators)
    updates = {}
    if expected:
        updates["next_withdrawal_index"] = expected[-1].index + 1
    if len(expected) == cfg.MAX_WITHDRAWALS_PER_PAYLOAD:
        updates["next_withdrawal_validator_index"] = \
            (expected[-1].validator_index + 1) % n
    else:
        updates["next_withdrawal_validator_index"] = \
            (state.next_withdrawal_validator_index
             + cfg.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP) % n
    return state.copy_with(**updates)


# ---- execution payload / operations / block ----

def process_execution_payload(cfg: SpecConfig, state, body,
                              execution_engine=BB.ACCEPT_ALL_ENGINE):
    _require(len(body.blob_kzg_commitments)
             <= cfg.MAX_BLOBS_PER_BLOCK_ELECTRA,
             "too many blob commitments")
    versioned_hashes = [DB.kzg_commitment_to_versioned_hash(c)
                        for c in body.blob_kzg_commitments]
    engine = DB._VersionedHashEngine(execution_engine, versioned_hashes)
    return BB.process_execution_payload(
        cfg, state, body, engine,
        to_header=payload_to_header_deneb, transition_guard=False)


def _process_operations(cfg, state, body, verifier, deposit_verifier):
    # EIP-6110 transition formula: eth1-bridge deposits stop at
    # deposit_requests_start_index
    limit = min(state.eth1_data.deposit_count,
                state.deposit_requests_start_index)
    if state.eth1_deposit_index < limit:
        expected = min(cfg.MAX_DEPOSITS,
                       limit - state.eth1_deposit_index)
    else:
        expected = 0
    _require(len(body.deposits) == expected, "wrong deposit count")

    for op in body.proposer_slashings:
        state = B0.process_proposer_slashing(cfg, state, op, verifier)
    for op in body.attester_slashings:
        state = B0.process_attester_slashing(cfg, state, op, verifier)
    for op in body.attestations:
        state = process_attestation(cfg, state, op, verifier)
    for op in body.deposits:
        state = process_deposit(cfg, state, op, deposit_verifier)
    for op in body.voluntary_exits:
        state = process_voluntary_exit(cfg, state, op, verifier)
    for op in body.bls_to_execution_changes:
        state = CB.process_bls_to_execution_change(cfg, state, op,
                                                   verifier)
    requests = body.execution_requests
    for op in requests.deposits:
        state = process_deposit_request(cfg, state, op)
    if requests.withdrawals or requests.consolidations:
        # registry scan once per batch, not per request (deposit
        # requests don't consult it, so build only when needed)
        index_by_pubkey = _pubkey_index_map(state)
        for op in requests.withdrawals:
            state = process_withdrawal_request(cfg, state, op,
                                               index_by_pubkey)
        for op in requests.consolidations:
            state = process_consolidation_request(cfg, state, op,
                                                  index_by_pubkey)
    return state


def process_block(cfg: SpecConfig, state, block,
                  verifier: SignatureVerifier,
                  deposit_verifier: SignatureVerifier = SIMPLE,
                  execution_engine=BB.ACCEPT_ALL_ENGINE):
    state = B0.process_block_header(cfg, state, block)
    state = process_withdrawals(cfg, state, block.body.execution_payload)
    state = process_execution_payload(cfg, state, block.body,
                                      execution_engine)
    state = B0.process_randao(cfg, state, block.body, verifier)
    state = B0.process_eth1_data(cfg, state, block.body)
    state = _process_operations(cfg, state, block.body, verifier,
                                deposit_verifier)
    state = AB.process_sync_aggregate(cfg, state,
                                      block.body.sync_aggregate, verifier)
    return state
