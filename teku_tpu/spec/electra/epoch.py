"""Electra epoch processing: churn-free activations (deposits are the
churned resource), pending deposit/consolidation queues, per-validator
max effective balances.

reference: ethereum/spec/.../logic/versions/electra/statetransition/
epoch/EpochProcessorElectra.java (processPendingDeposits,
processPendingConsolidations, registry updates without the activation
queue cap).
"""

from .. import epoch as E0
from .. import helpers as H
from ..altair import epoch as AE
from ..capella import epoch as CE
from ..config import FAR_FUTURE_EPOCH, SpecConfig
from . import block as EB
from . import helpers as EH


def process_registry_updates(cfg: SpecConfig, state):
    """Electra: eligibility needs MIN_ACTIVATION_BALANCE; ejections use
    the balance-churn exit; every finalized-eligible validator
    activates (the churn was already paid at deposit time)."""
    from .. import vectorized as _V
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_registry_updates_electra(cfg, state)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    current_epoch = H.get_current_epoch(cfg, state)
    validators = list(state.validators)
    changed = False
    for i, v in enumerate(validators):
        if (v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
                and v.effective_balance >= cfg.MIN_ACTIVATION_BALANCE):
            validators[i] = v.copy_with(
                activation_eligibility_epoch=current_epoch + 1)
            changed = True
    if changed:
        state = state.copy_with(validators=tuple(validators))
    for i, v in enumerate(state.validators):
        if (H.is_active_validator(v, current_epoch)
                and v.effective_balance <= cfg.EJECTION_BALANCE):
            state = EH.initiate_validator_exit(cfg, state, i)
    target_epoch = H.compute_activation_exit_epoch(cfg, current_epoch)
    validators = list(state.validators)
    changed = False
    for i, v in enumerate(validators):
        if H.is_eligible_for_activation(state, v):
            validators[i] = v.copy_with(activation_epoch=target_epoch)
            changed = True
    if changed:
        state = state.copy_with(validators=tuple(validators))
    return state


def apply_pending_deposit(cfg: SpecConfig, state, deposit,
                          index_by_pubkey):
    """Add a finalized pending deposit to its validator (creating the
    registry row for an unknown pubkey after the eager signature
    check).  `index_by_pubkey` is the caller's pubkey→index map,
    updated in place when a validator is added."""
    index = index_by_pubkey.get(deposit.pubkey)
    if index is None:
        from ..verifiers import SIMPLE
        if EB.is_valid_deposit_signature(
                cfg, deposit.pubkey, deposit.withdrawal_credentials,
                deposit.amount, deposit.signature, SIMPLE):
            state = EB.add_validator_to_registry(
                cfg, state, deposit.pubkey,
                deposit.withdrawal_credentials, deposit.amount)
            index_by_pubkey[deposit.pubkey] = len(state.validators) - 1
        return state
    return H.increase_balance(state, index, deposit.amount)


def process_pending_deposits(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    available = (state.deposit_balance_to_consume
                 + EH.get_activation_exit_churn_limit(cfg, state))
    processed_amount = 0
    next_index = 0
    postponed = []
    churn_reached = False
    finalized_slot = H.compute_start_slot_at_epoch(
        cfg, state.finalized_checkpoint.epoch)
    # one pubkey→index map for the whole queue, identity-cached per
    # registry (epoch cost stays O(D) when the registry is unchanged);
    # the overlay keeps writes out of the shared cached map
    from collections import ChainMap
    from .. import vectorized as _V
    index_by_pubkey = ChainMap({}, _V.pubkey_index_map(state))

    for deposit in state.pending_deposits:
        # eth1-bridge deposits drain before any request-sourced ones
        if (deposit.slot > 0 and state.eth1_deposit_index
                < state.deposit_requests_start_index):
            break
        if deposit.slot > finalized_slot:
            break
        if next_index >= cfg.MAX_PENDING_DEPOSITS_PER_EPOCH:
            break
        exited = withdrawn = False
        known = index_by_pubkey.get(deposit.pubkey)
        if known is not None:
            v = state.validators[known]
            exited = v.exit_epoch < FAR_FUTURE_EPOCH
            withdrawn = v.withdrawable_epoch < next_epoch
        if withdrawn:
            # never becomes active again: pay out without churn
            state = apply_pending_deposit(cfg, state, deposit,
                                          index_by_pubkey)
        elif exited:
            postponed.append(deposit)
        else:
            churn_reached = (processed_amount + deposit.amount
                             > available)
            if churn_reached:
                break
            processed_amount += deposit.amount
            state = apply_pending_deposit(cfg, state, deposit,
                                          index_by_pubkey)
        next_index += 1

    remaining = tuple(state.pending_deposits)[next_index:]
    state = state.copy_with(
        pending_deposits=remaining + tuple(postponed),
        deposit_balance_to_consume=(available - processed_amount
                                    if churn_reached else 0))
    return state


def process_pending_consolidations(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    done = 0
    for pc in state.pending_consolidations:
        source = state.validators[pc.source_index]
        if source.slashed:
            done += 1
            continue
        if source.withdrawable_epoch > next_epoch:
            break
        # move the active balance (not the skimmed excess)
        balance = min(state.balances[pc.source_index],
                      source.effective_balance)
        state = H.decrease_balance(state, pc.source_index, balance)
        state = H.increase_balance(state, pc.target_index, balance)
        done += 1
    return state.copy_with(
        pending_consolidations=tuple(state.pending_consolidations)[done:])


def process_effective_balance_updates(cfg: SpecConfig, state):
    """Hysteresis against the per-validator (compounding-aware) cap."""
    from .. import vectorized as _V
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_effective_balance_updates(
                cfg, state, max_eb_fn=EH.get_max_effective_balance)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    validators = list(state.validators)
    changed = False
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    down = inc * cfg.HYSTERESIS_DOWNWARD_MULTIPLIER // cfg.HYSTERESIS_QUOTIENT
    up = inc * cfg.HYSTERESIS_UPWARD_MULTIPLIER // cfg.HYSTERESIS_QUOTIENT
    for i, v in enumerate(validators):
        balance = state.balances[i]
        max_eb = EH.get_max_effective_balance(cfg, v)
        if (balance + down < v.effective_balance
                or v.effective_balance + up < balance):
            validators[i] = v.copy_with(effective_balance=min(
                balance - balance % inc, max_eb))
            changed = True
    if changed:
        return state.copy_with(validators=tuple(validators))
    return state


def process_slashings(cfg: SpecConfig, state):
    """EIP-7251 slashing penalty: quantise the correlation penalty to a
    per-effective-balance-increment rate first, then scale by the
    validator's increments.  Rounds differently from the altair formula
    (eb//inc * adjusted // total * inc), so electra must not reuse it.

    reference: ethereum/spec/.../logic/versions/electra/statetransition/
    epoch/EpochProcessorElectra.java (processSlashings override).
    """
    from .. import vectorized as _V
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_slashings(
                cfg, state,
                cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
                per_increment=True)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    epoch = H.get_current_epoch(cfg, state)
    total = H.get_total_active_balance(cfg, state)
    adjusted = min(
        sum(state.slashings) * cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
        total)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    penalty_per_increment = adjusted // (total // inc)
    balances = list(state.balances)
    for i, v in enumerate(state.validators):
        if (v.slashed and epoch + cfg.EPOCHS_PER_SLASHINGS_VECTOR // 2
                == v.withdrawable_epoch):
            penalty = penalty_per_increment * (v.effective_balance // inc)
            balances[i] = max(0, balances[i] - penalty)
    return state.copy_with(balances=tuple(balances))


def process_epoch(cfg: SpecConfig, state):
    state = AE.process_justification_and_finalization(cfg, state)
    state = AE.process_inactivity_updates(cfg, state)
    state = AE.process_rewards_and_penalties(
        cfg, state,
        inactivity_quotient=cfg.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
    state = process_registry_updates(cfg, state)
    state = process_slashings(cfg, state)
    state = E0.process_eth1_data_reset(cfg, state)
    state = process_pending_deposits(cfg, state)
    state = process_pending_consolidations(cfg, state)
    state = process_effective_balance_updates(cfg, state)
    state = E0.process_slashings_reset(cfg, state)
    state = E0.process_randao_mixes_reset(cfg, state)
    state = CE.process_historical_summaries_update(cfg, state)
    state = AE.process_participation_flag_updates(cfg, state)
    state = AE.process_sync_committee_updates(cfg, state)
    return state
