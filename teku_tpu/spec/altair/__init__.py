"""Altair milestone: sync committees + participation-flag accounting.

Equivalent of the reference's altair logic tree (reference: ethereum/
spec/src/main/java/tech/pegasys/teku/spec/logic/versions/altair/ —
BlockProcessorAltair, EpochProcessorAltair, helpers/
BeaconStateAccessorsAltair, util/SyncCommitteeUtil, and the fork
upgrade in statetransition).  Implements the public altair consensus
spec on this repo's SSZ engine.
"""

from .datastructures import get_altair_schemas
from .fork import upgrade_to_altair
