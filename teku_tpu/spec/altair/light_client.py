"""Altair light-client sync protocol: bootstrap, updates, and the
minimal verifying store.

Equivalent of the reference's light-client support (reference:
ethereum/spec/.../logic/common/util/LightClientUtil.java and
spec/datastructures/lightclient/ — LightClientBootstrap,
LightClientUpdate, the beacon REST light_client handlers): a light
client trusts one block root, verifies the current sync committee
against it, then follows finality by checking sync-committee
supermajority signatures plus two merkle proofs per update.

Proof generation uses the SSZ engine's merkle_branch over the state's
field roots, so the generalized indices adapt to every fork's state
shape automatically (electra's larger state gets depth-6 branches, the
reference's FINALIZED_ROOT_GINDEX_ELECTRA split handled structurally).
"""

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ...crypto import bls
from ...ssz import Bytes32, Container, merkle_branch
from ...ssz.hash import hash_pair
from ...ssz.types import _schema
from .. import helpers as H
from ..config import DOMAIN_SYNC_COMMITTEE, SpecConfig
from ..datastructures import BeaconBlockHeader


# ---- proof plumbing ------------------------------------------------------

def _state_field_roots(state) -> List[bytes]:
    fields = type(state)._ssz_fields
    return [_schema(schema).hash_tree_root(getattr(state, name))
            for name, schema in fields.items()]


def _field_position(state, name: str) -> int:
    for i, fname in enumerate(type(state)._ssz_fields):
        if fname == name:
            return i
    raise KeyError(name)


def sync_committee_branch(state, which: str,
                          roots: Optional[List[bytes]] = None
                          ) -> Tuple[List[bytes], int]:
    """(branch, gindex) proving state.{current,next}_sync_committee
    against the state root."""
    roots = _state_field_roots(state) if roots is None else roots
    idx = _field_position(state, f"{which}_sync_committee")
    branch = merkle_branch(roots, idx)
    return branch, (1 << len(branch)) + idx


def finality_branch(state, roots: Optional[List[bytes]] = None
                    ) -> Tuple[List[bytes], int]:
    """(branch, gindex) proving state.finalized_checkpoint.root: the
    checkpoint's epoch chunk, then the state-level siblings."""
    roots = _state_field_roots(state) if roots is None else roots
    idx = _field_position(state, "finalized_checkpoint")
    outer = merkle_branch(roots, idx)
    epoch_chunk = state.finalized_checkpoint.epoch.to_bytes(32, "little")
    branch = [epoch_chunk] + outer
    # root is leaf 1 inside the 2-leaf checkpoint subtree
    gindex = ((1 << len(outer)) + idx) * 2 + 1
    return branch, gindex


def expected_gindices(cfg: SpecConfig, slot: int) -> Tuple[int, int, int]:
    """(current_committee, next_committee, finalized_root) generalized
    indices for the fork governing `slot`, derived from that fork's
    OWN state schema — the verifier-side pins (spec
    CURRENT_SYNC_COMMITTEE_GINDEX / NEXT / FINALIZED_ROOT_GINDEX and
    their _ELECTRA variants).  A prover cannot choose where in the
    tree its leaf is checked."""
    from ..milestones import build_fork_schedule
    schema = build_fork_schedule(cfg).version_at_slot(
        slot).schemas.BeaconState
    fields = list(schema._ssz_fields)
    depth = (len(fields) - 1).bit_length()
    base = 1 << depth
    cur = base + fields.index("current_sync_committee")
    nxt = base + fields.index("next_sync_committee")
    fin = (base + fields.index("finalized_checkpoint")) * 2 + 1
    return cur, nxt, fin


def verify_merkle_proof(leaf: bytes, branch, gindex: int,
                        root: bytes) -> bool:
    value = leaf
    idx = gindex
    for sibling in branch:
        if idx & 1:
            value = hash_pair(sibling, value)
        else:
            value = hash_pair(value, sibling)
        idx >>= 1
    return idx == 1 and value == root


# ---- containers (dataclasses: these never ride consensus gossip) ---------

@dataclass
class LightClientBootstrap:
    header: BeaconBlockHeader
    current_sync_committee: object
    current_sync_committee_branch: list
    current_sync_committee_gindex: int


@dataclass
class LightClientUpdate:
    attested_header: BeaconBlockHeader
    next_sync_committee: Optional[object]
    next_sync_committee_branch: list
    next_sync_committee_gindex: int
    finalized_header: Optional[BeaconBlockHeader]
    finality_branch: list
    finality_gindex: int
    sync_aggregate: object
    signature_slot: int


# ---- producer side (the beacon node serving light clients) ---------------

def block_to_header(block) -> BeaconBlockHeader:
    return BeaconBlockHeader(
        slot=block.slot, proposer_index=block.proposer_index,
        parent_root=block.parent_root, state_root=block.state_root,
        body_root=block.body.htr())


def create_bootstrap(cfg: SpecConfig, state, block) -> LightClientBootstrap:
    branch, gindex = sync_committee_branch(state, "current")
    return LightClientBootstrap(
        header=block_to_header(block),
        current_sync_committee=state.current_sync_committee,
        current_sync_committee_branch=branch,
        current_sync_committee_gindex=gindex)


def create_update(cfg: SpecConfig, attested_state, attested_block,
                  finalized_block_header, sync_aggregate,
                  signature_slot: int,
                  include_next_committee: bool = True
                  ) -> LightClientUpdate:
    """An update proving the attested block's view: its finalized
    checkpoint (finality branch) and, at period boundaries, the next
    sync committee.  `sync_aggregate` is the aggregate a LATER block
    carried over the attested root; signature_slot is that block's
    slot."""
    roots = _state_field_roots(attested_state)   # hashed ONCE, shared
    next_branch: list = []
    next_gindex = 0
    next_committee = None
    if include_next_committee:
        next_branch, next_gindex = sync_committee_branch(
            attested_state, "next", roots)
        next_committee = attested_state.next_sync_committee
    fin_branch, fin_gindex = finality_branch(attested_state, roots)
    return LightClientUpdate(
        attested_header=block_to_header(attested_block),
        next_sync_committee=next_committee,
        next_sync_committee_branch=next_branch,
        next_sync_committee_gindex=next_gindex,
        finalized_header=finalized_block_header,
        finality_branch=fin_branch,
        finality_gindex=fin_gindex,
        sync_aggregate=sync_aggregate,
        signature_slot=signature_slot)


# ---- verifying store (the light client itself) ---------------------------

class LightClientError(ValueError):
    pass


@dataclass
class LightClientStore:
    finalized_header: BeaconBlockHeader
    current_sync_committee: object
    next_sync_committee: Optional[object]
    optimistic_header: BeaconBlockHeader


def initialize_light_client_store(cfg: SpecConfig, trusted_root: bytes,
                                  bootstrap: LightClientBootstrap
                                  ) -> LightClientStore:
    if bootstrap.header.htr() != trusted_root:
        raise LightClientError("bootstrap header != trusted root")
    committee_root = bootstrap.current_sync_committee.htr()
    # the gindex is PINNED by the verifier from the fork schedule —
    # a server-chosen position could prove a different field
    expected_cur, _, _ = expected_gindices(cfg, bootstrap.header.slot)
    if not verify_merkle_proof(
            committee_root, bootstrap.current_sync_committee_branch,
            expected_cur, bootstrap.header.state_root):
        raise LightClientError("bad current sync committee proof")
    return LightClientStore(
        finalized_header=bootstrap.header,
        current_sync_committee=bootstrap.current_sync_committee,
        next_sync_committee=None,
        optimistic_header=bootstrap.header)


def sync_committee_period(cfg: SpecConfig, slot: int) -> int:
    return (slot // cfg.SLOTS_PER_EPOCH
            // cfg.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)


def process_light_client_update(cfg: SpecConfig,
                                store: LightClientStore,
                                update: LightClientUpdate,
                                genesis_validators_root: bytes
                                ) -> LightClientStore:
    """Spec validate_light_client_update + apply, for the happy path a
    finality-following client needs (no force-update timeout logic)."""
    attested = update.attested_header
    if not update.signature_slot > attested.slot:
        raise LightClientError("signature slot must follow attested")
    # which committee signed?
    sig_period = sync_committee_period(cfg, update.signature_slot)
    store_period = sync_committee_period(cfg,
                                         store.finalized_header.slot)
    if sig_period == store_period:
        committee = store.current_sync_committee
    elif sig_period == store_period + 1 \
            and store.next_sync_committee is not None:
        committee = store.next_sync_committee
    else:
        raise LightClientError("update outside known committee periods")

    bits = update.sync_aggregate.sync_committee_bits
    participants = [pk for pk, b in zip(committee.pubkeys, bits) if b]
    if len(participants) < cfg.MIN_SYNC_COMMITTEE_PARTICIPANTS:
        raise LightClientError("insufficient participation")

    # gindices PINNED by the verifier from the attested slot's fork
    _, expected_next, expected_fin = expected_gindices(cfg,
                                                       attested.slot)
    # finality proof: the attested state really finalizes this header
    if update.finalized_header is not None:
        if not verify_merkle_proof(
                update.finalized_header.htr(), update.finality_branch,
                expected_fin, attested.state_root):
            raise LightClientError("bad finality proof")
    # next-committee proof
    if update.next_sync_committee is not None:
        if not verify_merkle_proof(
                update.next_sync_committee.htr(),
                update.next_sync_committee_branch,
                expected_next, attested.state_root):
            raise LightClientError("bad next sync committee proof")

    # the signature: the committee signed the attested block root at
    # signature_slot - 1's domain
    epoch = H.compute_epoch_at_slot(cfg,
                                    max(update.signature_slot, 1) - 1)
    # fork version at that epoch (the light client knows the schedule)
    from ..milestones import build_fork_schedule
    schedule = build_fork_schedule(cfg)
    version = schedule.version_for(schedule.milestone_at_epoch(epoch))
    domain = H.compute_domain(DOMAIN_SYNC_COMMITTEE,
                              version.fork_version,
                              genesis_validators_root)
    signing_root = H.compute_signing_root(attested.htr(), domain)
    if not bls.fast_aggregate_verify(
            participants, signing_root,
            update.sync_aggregate.sync_committee_signature):
        raise LightClientError("bad sync committee signature")

    # apply: supermajority advances finality, any participation
    # advances the optimistic head
    if attested.slot > store.optimistic_header.slot:
        store.optimistic_header = attested
    if update.finalized_header is not None \
            and 3 * len(participants) >= 2 * len(bits):
        if update.finalized_header.slot > store.finalized_header.slot:
            old_period = sync_committee_period(
                cfg, store.finalized_header.slot)
            new_period = sync_committee_period(
                cfg, update.finalized_header.slot)
            if new_period > old_period \
                    and store.next_sync_committee is not None:
                store.current_sync_committee = store.next_sync_committee
                store.next_sync_committee = None
            store.finalized_header = update.finalized_header
    if update.next_sync_committee is not None \
            and store.next_sync_committee is None \
            and sync_committee_period(cfg, attested.slot) \
            == sync_committee_period(cfg, store.finalized_header.slot):
        # spec guard: only a SAME-period attested view names the next
        # committee correctly; a period-boundary update would smuggle
        # the current committee in as "next" and wedge rotation
        store.next_sync_committee = update.next_sync_committee
    return store
