"""Altair helper functions: participation flags, sync committees,
per-increment base rewards.

reference: ethereum/spec/.../logic/versions/altair/helpers/
BeaconStateAccessorsAltair.java, MiscHelpersAltair.java and util/
SyncCommitteeUtil.java — the math follows the public altair spec.
"""

from typing import List, Set

from ...crypto import bls
from .. import helpers as H
from ..config import (DOMAIN_SYNC_COMMITTEE, SpecConfig,
                      TIMELY_HEAD_FLAG_INDEX, TIMELY_SOURCE_FLAG_INDEX,
                      TIMELY_TARGET_FLAG_INDEX)

def add_flag(flags: int, index: int) -> int:
    return flags | (1 << index)


def has_flag(flags: int, index: int) -> bool:
    return bool(flags & (1 << index))


def get_base_reward_per_increment(cfg: SpecConfig, state) -> int:
    return (cfg.EFFECTIVE_BALANCE_INCREMENT * cfg.BASE_REWARD_FACTOR
            // H.integer_squareroot(H.get_total_active_balance(cfg, state)))


def get_base_reward(cfg: SpecConfig, state, index: int,
                    base_per_increment: int = None) -> int:
    """`base_per_increment` lets per-validator loops hoist the
    total-active-balance scan (O(V)) out of the loop — without it an
    epoch's reward pass is O(V^2)."""
    if base_per_increment is None:
        base_per_increment = get_base_reward_per_increment(cfg, state)
    increments = (state.validators[index].effective_balance
                  // cfg.EFFECTIVE_BALANCE_INCREMENT)
    return increments * base_per_increment


def get_attestation_participation_flag_indices(
        cfg: SpecConfig, state, data, inclusion_delay: int,
        cap_target_delay: bool = True) -> List[int]:
    """Spec get_attestation_participation_flag_indices.  Deneb
    (EIP-7045) drops the SLOTS_PER_EPOCH cap on the target flag —
    `cap_target_delay=False` selects that behavior."""
    justified = (state.current_justified_checkpoint
                 if data.target.epoch == H.get_current_epoch(cfg, state)
                 else state.previous_justified_checkpoint)
    is_matching_source = data.source == justified
    is_matching_target = (
        is_matching_source
        and data.target.root == H.get_block_root(cfg, state,
                                                 data.target.epoch))
    is_matching_head = (
        is_matching_target
        and data.beacon_block_root == H.get_block_root_at_slot(
            cfg, state, data.slot))
    out = []
    if (is_matching_source
            and inclusion_delay
            <= H.integer_squareroot(cfg.SLOTS_PER_EPOCH)):
        out.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and (not cap_target_delay
                               or inclusion_delay <= cfg.SLOTS_PER_EPOCH):
        out.append(TIMELY_TARGET_FLAG_INDEX)
    if (is_matching_head
            and inclusion_delay == cfg.MIN_ATTESTATION_INCLUSION_DELAY):
        out.append(TIMELY_HEAD_FLAG_INDEX)
    return out


def get_unslashed_participating_indices(cfg: SpecConfig, state,
                                        flag_index: int,
                                        epoch: int) -> Set[int]:
    assert epoch in (H.get_previous_epoch(cfg, state),
                     H.get_current_epoch(cfg, state))
    participation = (state.current_epoch_participation
                     if epoch == H.get_current_epoch(cfg, state)
                     else state.previous_epoch_participation)
    active = H.get_active_validator_indices(state, epoch)
    return {i for i in active
            if has_flag(participation[i], flag_index)
            and not state.validators[i].slashed}


# -- sync committees -------------------------------------------------------

def get_next_sync_committee_indices(cfg: SpecConfig, state) -> List[int]:
    """Balance-weighted sampling with the sync-committee domain seed
    (spec get_next_sync_committee_indices)."""
    epoch = H.get_current_epoch(cfg, state) + 1
    MAX_RANDOM_BYTE = 2 ** 8 - 1
    active = H.get_active_validator_indices(state, epoch)
    seed = H.get_seed(cfg, state, epoch, DOMAIN_SYNC_COMMITTEE)
    out: List[int] = []
    i = 0
    n = len(active)
    while len(out) < cfg.SYNC_COMMITTEE_SIZE:
        shuffled = H.compute_shuffled_index(cfg, i % n, n, seed)
        candidate = active[shuffled]
        random_byte = H.hash32(
            seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eff = state.validators[candidate].effective_balance
        if eff * MAX_RANDOM_BYTE >= cfg.MAX_EFFECTIVE_BALANCE * random_byte:
            out.append(candidate)
        i += 1
    return out


def get_next_sync_committee(cfg: SpecConfig, state):
    from .datastructures import get_altair_schemas
    S = get_altair_schemas(cfg)
    indices = get_next_sync_committee_indices(cfg, state)
    pubkeys = [state.validators[i].pubkey for i in indices]
    return S.SyncCommittee(
        pubkeys=tuple(pubkeys),
        aggregate_pubkey=bls.eth_aggregate_pubkeys(pubkeys))


def sync_message_signing_root(cfg: SpecConfig, state, slot: int,
                              block_root: bytes) -> bytes:
    """THE sync-message signing root — one definition shared by the
    signer, the gossip validator and sync-aggregate verification so
    they can never drift apart."""
    domain = H.get_domain(cfg, state, DOMAIN_SYNC_COMMITTEE,
                          H.compute_epoch_at_slot(cfg, slot))
    return H.compute_signing_root(block_root, domain)


def sync_selection_proof_signing_root(cfg: SpecConfig, state, slot: int,
                                      subcommittee_index: int) -> bytes:
    """SyncAggregatorSelectionData under
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF (validator spec
    get_sync_committee_selection_proof)."""
    from ..config import DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF
    from .datastructures import get_altair_schemas
    S = get_altair_schemas(cfg)
    data = S.SyncAggregatorSelectionData(
        slot=slot, subcommittee_index=subcommittee_index)
    domain = H.get_domain(cfg, state,
                          DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
                          H.compute_epoch_at_slot(cfg, slot))
    return H.compute_signing_root(data, domain)


def is_sync_committee_aggregator(cfg: SpecConfig, proof: bytes) -> bool:
    """Validator spec is_sync_committee_aggregator."""
    modulo = max(1, cfg.SYNC_COMMITTEE_SIZE
                 // cfg.SYNC_COMMITTEE_SUBNET_COUNT
                 // cfg.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE)
    return int.from_bytes(H.hash32(proof)[:8], "little") % modulo == 0


def contribution_signature_set(cfg: SpecConfig, state, signed,
                               pubkeys: List[bytes]):
    """The THREE (pubkeys, root, signature) triples of one
    SignedContributionAndProof — selection proof, envelope, and the
    aggregated contribution over its participants — as ONE signature
    set for the batched device provider.

    One definition shared by the gossip validator, the load generator
    and the device/oracle parity tests, so the device path can never
    drift from the per-signature oracle semantics (reference: the
    SignatureVerificationService set built in
    SignedContributionAndProofValidator.java).  ``pubkeys`` is the
    aggregator's subcommittee (``sync_subcommittee_members``); returns
    None when the contribution names no participants (REJECT — an
    empty fast-aggregate set never verifies)."""
    msg = signed.message
    contribution = msg.contribution
    agg_pubkey = state.validators[msg.aggregator_index].pubkey
    participants = [pk for pk, b in zip(
        pubkeys, contribution.aggregation_bits) if b]
    if not participants:
        return None
    return [
        ([agg_pubkey],
         sync_selection_proof_signing_root(
             cfg, state, contribution.slot,
             contribution.subcommittee_index),
         msg.selection_proof),
        ([agg_pubkey],
         contribution_and_proof_signing_root(cfg, state, msg),
         signed.signature),
        (participants,
         sync_message_signing_root(cfg, state, contribution.slot,
                                   contribution.beacon_block_root),
         contribution.signature),
    ]


def contribution_and_proof_signing_root(cfg: SpecConfig, state,
                                        message) -> bytes:
    from ..config import DOMAIN_CONTRIBUTION_AND_PROOF
    domain = H.get_domain(cfg, state, DOMAIN_CONTRIBUTION_AND_PROOF,
                          H.compute_epoch_at_slot(
                              cfg, message.contribution.slot))
    return H.compute_signing_root(message, domain)


def sync_subcommittee_size(cfg: SpecConfig) -> int:
    """Members per sync subnet — THE definition, shared by schemas,
    pools, duties and validators."""
    return cfg.SYNC_COMMITTEE_SIZE // cfg.SYNC_COMMITTEE_SUBNET_COUNT


def sync_subcommittee_members(cfg: SpecConfig, state,
                              subcommittee_index: int):
    """The committee POSITIONS covered by one subcommittee, with their
    pubkeys (duplicate pubkeys possible on tiny sets — positions are
    the unit of participation)."""
    sub_size = sync_subcommittee_size(cfg)
    start = subcommittee_index * sub_size
    pubkeys = state.current_sync_committee.pubkeys[start:start + sub_size]
    return list(range(start, start + sub_size)), list(pubkeys)


def sync_committee_signing_root(cfg: SpecConfig, state, slot: int) -> bytes:
    """Signing root for the previous slot's block root (the aggregate
    included at `slot`)."""
    prev = max(slot, 1) - 1
    return sync_message_signing_root(
        cfg, state, prev, H.get_block_root_at_slot(cfg, state, prev))
