"""Altair block processing: flag-based attestations + sync aggregate.

reference: ethereum/spec/.../logic/versions/altair/block/
BlockProcessorAltair.java (processAttestation flag accounting,
processSyncAggregate with the proposer/participant reward split).
"""

from ...crypto import bls
from .. import block as B0
from .. import helpers as H
from ..config import (PARTICIPATION_FLAG_WEIGHTS, PROPOSER_WEIGHT,
                      SpecConfig, SYNC_REWARD_WEIGHT, WEIGHT_DENOMINATOR)
from ..verifiers import SignatureVerifier, SIMPLE
from . import helpers as AH

_require = B0._require


def process_attestation(cfg: SpecConfig, state, attestation,
                        verifier: SignatureVerifier,
                        enforce_upper_window: bool = True):
    data = attestation.data
    _require(data.target.epoch in (H.get_previous_epoch(cfg, state),
                                   H.get_current_epoch(cfg, state)),
             "target epoch out of range")
    _require(data.target.epoch == H.compute_epoch_at_slot(cfg, data.slot),
             "target/slot mismatch")
    # the upper window bound applies through capella; deneb (EIP-7045)
    # keeps only the min-delay lower bound — the target-epoch check
    # above then caps staleness at ~2 epochs
    _require(data.slot + cfg.MIN_ATTESTATION_INCLUSION_DELAY
             <= state.slot, "inclusion delay")
    _require(not enforce_upper_window
             or state.slot <= data.slot + cfg.SLOTS_PER_EPOCH,
             "inclusion window")
    _require(data.index < H.get_committee_count_per_slot(
        cfg, state, data.target.epoch), "committee index out of range")
    committee = H.get_beacon_committee(cfg, state, data.slot, data.index)
    _require(len(attestation.aggregation_bits) == len(committee),
             "bits/committee size mismatch")
    # altair checks source matching inside the flag computation
    justified = (state.current_justified_checkpoint
                 if data.target.epoch == H.get_current_epoch(cfg, state)
                 else state.previous_justified_checkpoint)
    _require(data.source == justified, "wrong source checkpoint")

    indexed = H.get_indexed_attestation(cfg, state, attestation)
    _require(B0.is_valid_indexed_attestation(cfg, state, indexed,
                                             verifier),
             "bad attestation signature")

    attesting = H.get_attesting_indices(cfg, state, data,
                                        attestation.aggregation_bits)
    return _apply_participation_rewards(
        cfg, state, data, attesting,
        cap_target_delay=enforce_upper_window)


def _apply_participation_rewards(cfg: SpecConfig, state, data,
                                 attesting_indices,
                                 cap_target_delay: bool = True):
    """The flag-accounting tail of process_attestation, shared with
    electra (which resolves the attesting set via committee bits)."""
    flag_indices = AH.get_attestation_participation_flag_indices(
        cfg, state, data, state.slot - data.slot,
        cap_target_delay=cap_target_delay)
    in_current = data.target.epoch == H.get_current_epoch(cfg, state)
    participation = list(state.current_epoch_participation if in_current
                         else state.previous_epoch_participation)
    base_per_inc = AH.get_base_reward_per_increment(cfg, state)
    proposer_reward_numerator = 0
    for index in attesting_indices:
        increments = (state.validators[index].effective_balance
                      // cfg.EFFECTIVE_BALANCE_INCREMENT)
        base_reward = increments * base_per_inc
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if (flag_index in flag_indices
                    and not AH.has_flag(participation[index], flag_index)):
                participation[index] = AH.add_flag(participation[index],
                                                   flag_index)
                proposer_reward_numerator += base_reward * weight

    proposer_reward = (proposer_reward_numerator
                       // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
                       * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR)
    state = state.copy_with(**{
        ("current_epoch_participation" if in_current
         else "previous_epoch_participation"): tuple(participation)})
    return H.increase_balance(
        state, H.get_beacon_proposer_index(cfg, state), proposer_reward)


def process_deposit(cfg: SpecConfig, state, deposit,
                    deposit_verifier: SignatureVerifier = SIMPLE):
    n_before = len(state.validators)
    state = B0.process_deposit(cfg, state, deposit, deposit_verifier)
    if len(state.validators) > n_before:
        # fresh validator: zeroed participation + inactivity rows
        state = state.copy_with(
            previous_epoch_participation=(
                tuple(state.previous_epoch_participation) + (0,)),
            current_epoch_participation=(
                tuple(state.current_epoch_participation) + (0,)),
            inactivity_scores=tuple(state.inactivity_scores) + (0,))
    return state


def process_sync_aggregate(cfg: SpecConfig, state, sync_aggregate,
                           verifier: SignatureVerifier):
    """Spec process_sync_aggregate: previous-slot root signed by the
    current sync committee; participants earn, absentees pay."""
    committee_pubkeys = state.current_sync_committee.pubkeys
    bits = sync_aggregate.sync_committee_bits
    participant_pubkeys = [pk for pk, b in zip(committee_pubkeys, bits)
                           if b]
    signing_root = AH.sync_committee_signing_root(cfg, state, state.slot)
    if participant_pubkeys:
        _require(verifier.verify(participant_pubkeys, signing_root,
                                 sync_aggregate.sync_committee_signature),
                 "bad sync committee signature")
    else:
        _require(bls.eth_fast_aggregate_verify(
            [], signing_root, sync_aggregate.sync_committee_signature),
            "empty sync aggregate must carry the infinity signature")

    total_active_increments = (H.get_total_active_balance(cfg, state)
                               // cfg.EFFECTIVE_BALANCE_INCREMENT)
    base_per_inc = AH.get_base_reward_per_increment(cfg, state)
    total_base_rewards = base_per_inc * total_active_increments
    max_participant_rewards = (total_base_rewards * SYNC_REWARD_WEIGHT
                               // WEIGHT_DENOMINATOR
                               // cfg.SLOTS_PER_EPOCH)
    participant_reward = (max_participant_rewards
                          // cfg.SYNC_COMMITTEE_SIZE)
    proposer_reward = (participant_reward * PROPOSER_WEIGHT
                       // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT))

    pubkey_to_index = {v.pubkey: i
                       for i, v in enumerate(state.validators)}
    proposer_index = H.get_beacon_proposer_index(cfg, state)
    balances = list(state.balances)
    for pk, participated in zip(committee_pubkeys, bits):
        index = pubkey_to_index[pk]
        if participated:
            balances[index] += participant_reward
            balances[proposer_index] += proposer_reward
        else:
            balances[index] = max(0, balances[index] - participant_reward)
    return state.copy_with(balances=tuple(balances))


def process_block(cfg: SpecConfig, state, block,
                  verifier: SignatureVerifier,
                  deposit_verifier: SignatureVerifier = SIMPLE):
    state = B0.process_block_header(cfg, state, block)
    state = B0.process_randao(cfg, state, block.body, verifier)
    state = B0.process_eth1_data(cfg, state, block.body)
    state = _process_operations(cfg, state, block.body, verifier,
                                deposit_verifier)
    state = process_sync_aggregate(cfg, state, block.body.sync_aggregate,
                                   verifier)
    return state


def _process_operations(cfg, state, body, verifier, deposit_verifier,
                        enforce_attestation_window: bool = True,
                        exit_fork_version=None):
    expected = min(cfg.MAX_DEPOSITS,
                   state.eth1_data.deposit_count
                   - state.eth1_deposit_index)
    _require(len(body.deposits) == expected, "wrong deposit count")
    for op in body.proposer_slashings:
        state = B0.process_proposer_slashing(cfg, state, op, verifier)
    for op in body.attester_slashings:
        state = B0.process_attester_slashing(cfg, state, op, verifier)
    for op in body.attestations:
        state = process_attestation(
            cfg, state, op, verifier,
            enforce_upper_window=enforce_attestation_window)
    for op in body.deposits:
        state = process_deposit(cfg, state, op, deposit_verifier)
    for op in body.voluntary_exits:
        state = B0.process_voluntary_exit(
            cfg, state, op, verifier,
            exit_fork_version=exit_fork_version)
    return state
