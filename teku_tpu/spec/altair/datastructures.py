"""Altair containers: sync committees, participation-flag state.

reference: ethereum/spec/.../spec/datastructures/state/beaconstate/
versions/altair/BeaconStateAltair.java + blocks/versions/altair/.
"""

from functools import lru_cache

from ...ssz import (Bitvector, Bytes4, Bytes32, Bytes48, Bytes96,
                    Container, List, uint8, uint64, Vector)
from ..config import SpecConfig
from ..datastructures import (_container, BeaconBlockHeader, Checkpoint,
                              Eth1Data, Fork, get_schemas, Validator)


class AltairSchemas:
    """One object per config, like the phase0 Schemas family."""

    def __getattr__(self, name):
        # anything altair doesn't redefine (Attestation, Deposit, ...)
        # is the phase0 container
        if name == "phase0":     # not set yet during __init__
            raise AttributeError(name)
        return getattr(self.phase0, name)

    def __init__(self, cfg: SpecConfig):
        self.config = cfg
        base = get_schemas(cfg)
        self.phase0 = base

        self.SyncCommittee = _container("SyncCommittee", [
            ("pubkeys", Vector(Bytes48, cfg.SYNC_COMMITTEE_SIZE)),
            ("aggregate_pubkey", Bytes48),
        ])
        self.SyncAggregate = _container("SyncAggregate", [
            ("sync_committee_bits", Bitvector(cfg.SYNC_COMMITTEE_SIZE)),
            ("sync_committee_signature", Bytes96),
        ])
        self.SyncCommitteeMessage = _container("SyncCommitteeMessage", [
            ("slot", uint64),
            ("beacon_block_root", Bytes32),
            ("validator_index", uint64),
            ("signature", Bytes96),
        ])
        # per-subcommittee aggregation (validator spec: 4 sync subnets)
        sub_size = (cfg.SYNC_COMMITTEE_SIZE
                    // cfg.SYNC_COMMITTEE_SUBNET_COUNT)
        self.SyncCommitteeContribution = _container(
            "SyncCommitteeContribution", [
                ("slot", uint64),
                ("beacon_block_root", Bytes32),
                ("subcommittee_index", uint64),
                ("aggregation_bits", Bitvector(sub_size)),
                ("signature", Bytes96),
            ])
        self.ContributionAndProof = _container("ContributionAndProof", [
            ("aggregator_index", uint64),
            ("contribution", self.SyncCommitteeContribution),
            ("selection_proof", Bytes96),
        ])
        self.SignedContributionAndProof = _container(
            "SignedContributionAndProof", [
                ("message", self.ContributionAndProof),
                ("signature", Bytes96),
            ])
        self.SyncAggregatorSelectionData = _container(
            "SyncAggregatorSelectionData", [
                ("slot", uint64),
                ("subcommittee_index", uint64),
            ])
        self.BeaconBlockBody = _container("BeaconBlockBodyAltair", [
            ("randao_reveal", Bytes96),
            ("eth1_data", Eth1Data),
            ("graffiti", Bytes32),
            ("proposer_slashings",
             base.BeaconBlockBody._ssz_fields["proposer_slashings"]),
            ("attester_slashings",
             base.BeaconBlockBody._ssz_fields["attester_slashings"]),
            ("attestations",
             base.BeaconBlockBody._ssz_fields["attestations"]),
            ("deposits", base.BeaconBlockBody._ssz_fields["deposits"]),
            ("voluntary_exits",
             base.BeaconBlockBody._ssz_fields["voluntary_exits"]),
            ("sync_aggregate", self.SyncAggregate),
        ])
        self.BeaconBlock = _container("BeaconBlockAltair", [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = _container("SignedBeaconBlockAltair", [
            ("message", self.BeaconBlock),
            ("signature", Bytes96),
        ])
        self.BeaconState = _container("BeaconStateAltair", [
            ("genesis_time", uint64),
            ("genesis_validators_root", Bytes32),
            ("slot", uint64),
            ("fork", Fork),
            ("latest_block_header", BeaconBlockHeader),
            ("block_roots", Vector(Bytes32, cfg.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Bytes32, cfg.SLOTS_PER_HISTORICAL_ROOT)),
            ("historical_roots", List(Bytes32, cfg.HISTORICAL_ROOTS_LIMIT)),
            ("eth1_data", Eth1Data),
            ("eth1_data_votes",
             List(Eth1Data, cfg.EPOCHS_PER_ETH1_VOTING_PERIOD
                  * cfg.SLOTS_PER_EPOCH)),
            ("eth1_deposit_index", uint64),
            ("validators", List(Validator, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("balances", List(uint64, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("randao_mixes",
             Vector(Bytes32, cfg.EPOCHS_PER_HISTORICAL_VECTOR)),
            ("slashings", Vector(uint64, cfg.EPOCHS_PER_SLASHINGS_VECTOR)),
            ("previous_epoch_participation",
             List(uint8, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("current_epoch_participation",
             List(uint8, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("justification_bits", Bitvector(4)),
            ("previous_justified_checkpoint", Checkpoint),
            ("current_justified_checkpoint", Checkpoint),
            ("finalized_checkpoint", Checkpoint),
            ("inactivity_scores",
             List(uint64, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("current_sync_committee", self.SyncCommittee),
            ("next_sync_committee", self.SyncCommittee),
        ])


@lru_cache(maxsize=8)
def get_altair_schemas(cfg: SpecConfig) -> AltairSchemas:
    return AltairSchemas(cfg)
