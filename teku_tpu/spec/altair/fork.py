"""Phase0 → altair fork upgrade.

reference: the upgrade path the reference applies at the altair
activation epoch (spec upgrade_to_altair): carry all phase0 fields,
zero the participation/inactivity tracks, TRANSLATE pending phase0
attestations into participation flags, and bootstrap both sync
committees.
"""

from .. import helpers as H
from ..config import SpecConfig
from ..datastructures import Fork
from . import helpers as AH
from .datastructures import get_altair_schemas


def translate_participation(cfg: SpecConfig, post, pending_attestations):
    participation = list(post.previous_epoch_participation)
    for a in pending_attestations:
        data = a.data
        flags = AH.get_attestation_participation_flag_indices(
            cfg, post, data, a.inclusion_delay)
        for index in H.get_attesting_indices(cfg, post, data,
                                             a.aggregation_bits):
            for f in flags:
                participation[index] = AH.add_flag(participation[index], f)
    return post.copy_with(previous_epoch_participation=tuple(participation))


def upgrade_to_altair(cfg: SpecConfig, pre):
    S = get_altair_schemas(cfg)
    epoch = H.get_current_epoch(cfg, pre)
    n = len(pre.validators)
    post = S.BeaconState(
        genesis_time=pre.genesis_time,
        genesis_validators_root=pre.genesis_validators_root,
        slot=pre.slot,
        fork=Fork(previous_version=pre.fork.current_version,
                  current_version=cfg.ALTAIR_FORK_VERSION,
                  epoch=epoch),
        latest_block_header=pre.latest_block_header,
        block_roots=pre.block_roots,
        state_roots=pre.state_roots,
        historical_roots=pre.historical_roots,
        eth1_data=pre.eth1_data,
        eth1_data_votes=pre.eth1_data_votes,
        eth1_deposit_index=pre.eth1_deposit_index,
        validators=pre.validators,
        balances=pre.balances,
        randao_mixes=pre.randao_mixes,
        slashings=pre.slashings,
        previous_epoch_participation=tuple(0 for _ in range(n)),
        current_epoch_participation=tuple(0 for _ in range(n)),
        justification_bits=pre.justification_bits,
        previous_justified_checkpoint=pre.previous_justified_checkpoint,
        current_justified_checkpoint=pre.current_justified_checkpoint,
        finalized_checkpoint=pre.finalized_checkpoint,
        inactivity_scores=tuple(0 for _ in range(n)),
    )
    post = translate_participation(cfg, post,
                                   pre.previous_epoch_attestations)
    # the spec assigns get_next_sync_committee(post) to BOTH fields;
    # the state is identical between the two calls, so compute once
    committee = AH.get_next_sync_committee(cfg, post)
    return post.copy_with(current_sync_committee=committee,
                          next_sync_committee=committee)
