"""Altair epoch processing: flag-based justification, inactivity
scores, flag-deltas rewards, participation rotation, sync-committee
period rollover.

reference: ethereum/spec/.../logic/versions/altair/statetransition/
epoch/EpochProcessorAltair.java — math follows the public altair spec.
"""

from ..config import (GENESIS_EPOCH, PARTICIPATION_FLAG_WEIGHTS,
                      SpecConfig, TIMELY_HEAD_FLAG_INDEX,
                      TIMELY_TARGET_FLAG_INDEX, WEIGHT_DENOMINATOR)
from .. import epoch as E0
from .. import helpers as H
from .. import vectorized as _V
from . import helpers as AH


def process_justification_and_finalization(cfg: SpecConfig, state):
    if H.get_current_epoch(cfg, state) <= GENESIS_EPOCH + 1:
        return state
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            prev_bal, cur_bal = _V.target_participation_balances(
                cfg, state)
            return E0.weigh_justification_and_finalization(
                cfg, state, _V.total_active_balance(cfg, state),
                prev_bal, cur_bal)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    total = H.get_total_active_balance(cfg, state)
    prev = AH.get_unslashed_participating_indices(
        cfg, state, TIMELY_TARGET_FLAG_INDEX,
        H.get_previous_epoch(cfg, state))
    cur = AH.get_unslashed_participating_indices(
        cfg, state, TIMELY_TARGET_FLAG_INDEX,
        H.get_current_epoch(cfg, state))
    return E0.weigh_justification_and_finalization(
        cfg, state, total,
        H.get_total_balance(cfg, state, prev),
        H.get_total_balance(cfg, state, cur))


def process_inactivity_updates(cfg: SpecConfig, state):
    if H.get_current_epoch(cfg, state) == GENESIS_EPOCH:
        return state
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_inactivity_updates(cfg, state)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    scores = list(state.inactivity_scores)
    target_idx = AH.get_unslashed_participating_indices(
        cfg, state, TIMELY_TARGET_FLAG_INDEX,
        H.get_previous_epoch(cfg, state))
    leaking = E0.is_in_inactivity_leak(cfg, state)
    for i in E0.get_eligible_validator_indices(cfg, state):
        if i in target_idx:
            scores[i] -= min(1, scores[i])
        else:
            scores[i] += cfg.INACTIVITY_SCORE_BIAS
        if not leaking:
            scores[i] -= min(cfg.INACTIVITY_SCORE_RECOVERY_RATE,
                             scores[i])
    return state.copy_with(inactivity_scores=tuple(scores))


def get_flag_index_deltas(cfg: SpecConfig, state, flag_index: int):
    """(rewards, penalties) for one flag (spec get_flag_index_deltas)."""
    n = len(state.validators)
    rewards = [0] * n
    penalties = [0] * n
    previous_epoch = H.get_previous_epoch(cfg, state)
    unslashed = AH.get_unslashed_participating_indices(
        cfg, state, flag_index, previous_epoch)
    weight = PARTICIPATION_FLAG_WEIGHTS[flag_index]
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    unslashed_increments = H.get_total_balance(cfg, state,
                                               unslashed) // inc
    active_increments = H.get_total_active_balance(cfg, state) // inc
    leaking = E0.is_in_inactivity_leak(cfg, state)
    base_per_inc = AH.get_base_reward_per_increment(cfg, state)
    for index in E0.get_eligible_validator_indices(cfg, state):
        base_reward = AH.get_base_reward(cfg, state, index,
                                         base_per_inc)
        if index in unslashed:
            if not leaking:
                numer = base_reward * weight * unslashed_increments
                rewards[index] += (numer
                                   // (active_increments
                                       * WEIGHT_DENOMINATOR))
        elif flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties[index] += (base_reward * weight
                                 // WEIGHT_DENOMINATOR)
    return rewards, penalties


def get_inactivity_penalty_deltas(cfg: SpecConfig, state,
                                  inactivity_quotient=None):
    n = len(state.validators)
    penalties = [0] * n
    quotient = (cfg.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
                if inactivity_quotient is None else inactivity_quotient)
    previous_epoch = H.get_previous_epoch(cfg, state)
    target_idx = AH.get_unslashed_participating_indices(
        cfg, state, TIMELY_TARGET_FLAG_INDEX, previous_epoch)
    for index in E0.get_eligible_validator_indices(cfg, state):
        if index not in target_idx:
            numer = (state.validators[index].effective_balance
                     * state.inactivity_scores[index])
            penalties[index] += numer // (
                cfg.INACTIVITY_SCORE_BIAS * quotient)
    return [0] * n, penalties


def process_rewards_and_penalties(cfg: SpecConfig, state,
                                  inactivity_quotient=None):
    if H.get_current_epoch(cfg, state) == GENESIS_EPOCH:
        return state
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_rewards_and_penalties(
                cfg, state, inactivity_quotient)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    deltas = [get_flag_index_deltas(cfg, state, f)
              for f in range(len(PARTICIPATION_FLAG_WEIGHTS))]
    deltas.append(get_inactivity_penalty_deltas(cfg, state,
                                                inactivity_quotient))
    balances = list(state.balances)
    for rewards, penalties in deltas:
        for i in range(len(balances)):
            balances[i] = max(0, balances[i] + rewards[i] - penalties[i])
    return state.copy_with(balances=tuple(balances))


def process_slashings(cfg: SpecConfig, state, multiplier=None):
    """Altair: proportional multiplier 2 (spec process_slashings);
    bellatrix overrides the multiplier to 3."""
    if multiplier is None:
        multiplier = cfg.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_slashings(cfg, state, multiplier)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    epoch = H.get_current_epoch(cfg, state)
    total = H.get_total_active_balance(cfg, state)
    adjusted = min(sum(state.slashings) * multiplier, total)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    balances = list(state.balances)
    for i, v in enumerate(state.validators):
        if (v.slashed and epoch + cfg.EPOCHS_PER_SLASHINGS_VECTOR // 2
                == v.withdrawable_epoch):
            penalty = (v.effective_balance // inc * adjusted
                       // total * inc)
            balances[i] = max(0, balances[i] - penalty)
    return state.copy_with(balances=tuple(balances))


def process_participation_flag_updates(cfg: SpecConfig, state):
    return state.copy_with(
        previous_epoch_participation=state.current_epoch_participation,
        current_epoch_participation=tuple(
            0 for _ in state.validators))


def process_sync_committee_updates(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    if next_epoch % cfg.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        return state.copy_with(
            current_sync_committee=state.next_sync_committee,
            next_sync_committee=AH.get_next_sync_committee(cfg, state))
    return state


def process_epoch(cfg: SpecConfig, state):
    state = process_justification_and_finalization(cfg, state)
    state = process_inactivity_updates(cfg, state)
    state = process_rewards_and_penalties(cfg, state)
    state = E0.process_registry_updates(cfg, state)
    state = process_slashings(cfg, state)
    state = E0.process_eth1_data_reset(cfg, state)
    state = E0.process_effective_balance_updates(cfg, state)
    state = E0.process_slashings_reset(cfg, state)
    state = E0.process_randao_mixes_reset(cfg, state)
    state = E0.process_historical_roots_update(cfg, state)
    state = process_participation_flag_updates(cfg, state)
    state = process_sync_committee_updates(cfg, state)
    return state
