"""Phase0 block processing over immutable SSZ states.

Equivalent of the reference's AbstractBlockProcessor (reference:
ethereum/spec/src/main/java/tech/pegasys/teku/spec/logic/common/block/
AbstractBlockProcessor.java:84-890): process_block_header → randao →
eth1 data → operations, with every signature routed through the
SignatureVerifier seam so block import can collect-then-batch.  Deposit
signatures are the one deliberate exception: they verify EAGERLY with
their own verifier because an invalid deposit signature means "skip the
deposit", not "invalid block" (AbstractBlockProcessor.java:84-93).
"""

from typing import Optional

from .config import (DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER,
                     DOMAIN_DEPOSIT, DOMAIN_RANDAO, DOMAIN_VOLUNTARY_EXIT,
                     FAR_FUTURE_EPOCH, SpecConfig)
from .datastructures import DepositMessage, get_schemas
from . import helpers as H
from .verifiers import SIMPLE, SignatureVerifier


class BlockProcessingError(Exception):
    """Invalid block content (the reference's BlockProcessingException)."""


def _require(cond: bool, what: str):
    if not cond:
        raise BlockProcessingError(what)


# --------------------------------------------------------------------------
# Signature checks (all via the seam)
# --------------------------------------------------------------------------

def verify_block_signature(cfg: SpecConfig, state, signed_block,
                           verifier: SignatureVerifier) -> bool:
    proposer_index = signed_block.message.proposer_index
    if proposer_index >= len(state.validators):
        # wire-controlled u64: indexing it unchecked is a remote crash
        # (found by the fuzz harness), not a typed rejection
        return False
    proposer = state.validators[proposer_index]
    domain = H.get_domain(cfg, state, DOMAIN_BEACON_PROPOSER)
    root = H.compute_signing_root(signed_block.message, domain)
    return verifier.verify([proposer.pubkey], root, signed_block.signature)


def verify_randao_reveal(cfg: SpecConfig, state, body,
                         verifier: SignatureVerifier) -> bool:
    epoch = H.get_current_epoch(cfg, state)
    proposer = state.validators[H.get_beacon_proposer_index(cfg, state)]
    root = H.randao_signing_root(cfg, state, epoch)
    return verifier.verify([proposer.pubkey], root, body.randao_reveal)


def is_valid_indexed_attestation(cfg: SpecConfig, state, indexed,
                                 verifier: SignatureVerifier) -> bool:
    """Spec is_valid_indexed_attestation via the seam (reference:
    AttestationUtil.java:162-291)."""
    indices = list(indexed.attesting_indices)
    if not indices or indices != sorted(set(indices)):
        return False
    if any(i >= len(state.validators) for i in indices):
        return False
    pubkeys = [state.validators[i].pubkey for i in indices]
    domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER,
                          indexed.data.target.epoch)
    root = H.compute_signing_root(indexed.data, domain)
    return verifier.verify(pubkeys, root, indexed.signature)


# --------------------------------------------------------------------------
# Per-operation processing
# --------------------------------------------------------------------------

def process_block_header(cfg: SpecConfig, state, block):
    _require(block.slot == state.slot, "block slot mismatch")
    _require(block.slot > state.latest_block_header.slot,
             "block older than latest header")
    _require(block.proposer_index == H.get_beacon_proposer_index(cfg, state),
             "wrong proposer")
    _require(block.parent_root == state.latest_block_header.htr(),
             "parent root mismatch")
    proposer = state.validators[block.proposer_index]
    _require(not proposer.slashed, "proposer slashed")
    from .datastructures import BeaconBlockHeader
    header = BeaconBlockHeader(
        slot=block.slot, proposer_index=block.proposer_index,
        parent_root=block.parent_root, state_root=bytes(32),
        body_root=block.body.htr())
    return state.copy_with(latest_block_header=header)


def process_randao(cfg: SpecConfig, state, body,
                   verifier: SignatureVerifier):
    _require(verify_randao_reveal(cfg, state, body, verifier),
             "bad randao reveal")
    epoch = H.get_current_epoch(cfg, state)
    mix = H.xor32(H.get_randao_mix(cfg, state, epoch),
                  H.hash32(body.randao_reveal))
    mixes = list(state.randao_mixes)
    mixes[epoch % cfg.EPOCHS_PER_HISTORICAL_VECTOR] = mix
    return state.copy_with(randao_mixes=tuple(mixes))


def eth1_vote_outcome(cfg: SpecConfig, state, vote):
    """The eth1_data in force AFTER a block carrying `vote` processes —
    the ONE statement of the adoption rule, shared by the transition
    and by block production (which must anticipate same-block adoption
    when selecting deposits)."""
    votes = list(state.eth1_data_votes) + [vote]
    period = cfg.EPOCHS_PER_ETH1_VOTING_PERIOD * cfg.SLOTS_PER_EPOCH
    return vote if votes.count(vote) * 2 > period else state.eth1_data


def process_eth1_data(cfg: SpecConfig, state, body):
    outcome = eth1_vote_outcome(cfg, state, body.eth1_data)
    return state.copy_with(
        eth1_data_votes=tuple(state.eth1_data_votes)
        + (body.eth1_data,),
        eth1_data=outcome)


def process_proposer_slashing(cfg: SpecConfig, state, slashing,
                              verifier: SignatureVerifier):
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    _require(h1.slot == h2.slot, "slashing: slots differ")
    _require(h1.proposer_index == h2.proposer_index,
             "slashing: proposers differ")
    _require(h1 != h2, "slashing: identical headers")
    _require(h1.proposer_index < len(state.validators),
             "slashing: unknown proposer")
    proposer = state.validators[h1.proposer_index]
    _require(H.is_slashable_validator(
        proposer, H.get_current_epoch(cfg, state)), "not slashable")
    for signed in (slashing.signed_header_1, slashing.signed_header_2):
        domain = H.get_domain(
            cfg, state, DOMAIN_BEACON_PROPOSER,
            H.compute_epoch_at_slot(cfg, signed.message.slot))
        root = H.compute_signing_root(signed.message, domain)
        _require(verifier.verify([proposer.pubkey], root, signed.signature),
                 "slashing: bad header signature")
    return H.slash_validator(cfg, state, h1.proposer_index)


def process_attester_slashing(cfg: SpecConfig, state, slashing,
                              verifier: SignatureVerifier):
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    _require(H.is_slashable_attestation_data(a1.data, a2.data),
             "attestations not slashable")
    _require(is_valid_indexed_attestation(cfg, state, a1, verifier),
             "attestation_1 invalid")
    _require(is_valid_indexed_attestation(cfg, state, a2, verifier),
             "attestation_2 invalid")
    slashed_any = False
    now = H.get_current_epoch(cfg, state)
    common = sorted(set(a1.attesting_indices) & set(a2.attesting_indices))
    for idx in common:
        if H.is_slashable_validator(state.validators[idx], now):
            state = H.slash_validator(cfg, state, idx)
            slashed_any = True
    _require(slashed_any, "nobody slashed")
    return state


def process_attestation(cfg: SpecConfig, state, attestation,
                        verifier: SignatureVerifier):
    data = attestation.data
    _require(data.target.epoch in (H.get_previous_epoch(cfg, state),
                                   H.get_current_epoch(cfg, state)),
             "target epoch out of range")
    _require(data.target.epoch == H.compute_epoch_at_slot(cfg, data.slot),
             "target/slot mismatch")
    _require(data.slot + cfg.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot
             <= data.slot + cfg.SLOTS_PER_EPOCH, "inclusion window")
    _require(data.index < H.get_committee_count_per_slot(
        cfg, state, data.target.epoch), "committee index out of range")
    committee = H.get_beacon_committee(cfg, state, data.slot, data.index)
    _require(len(attestation.aggregation_bits) == len(committee),
             "bits/committee size mismatch")

    S = get_schemas(cfg)
    pending = S.PendingAttestation(
        aggregation_bits=attestation.aggregation_bits, data=data,
        inclusion_delay=state.slot - data.slot,
        proposer_index=H.get_beacon_proposer_index(cfg, state))
    if data.target.epoch == H.get_current_epoch(cfg, state):
        _require(data.source == state.current_justified_checkpoint,
                 "wrong source (current)")
        state = state.copy_with(
            current_epoch_attestations=(
                tuple(state.current_epoch_attestations) + (pending,)))
    else:
        _require(data.source == state.previous_justified_checkpoint,
                 "wrong source (previous)")
        state = state.copy_with(
            previous_epoch_attestations=(
                tuple(state.previous_epoch_attestations) + (pending,)))
    indexed = H.get_indexed_attestation(cfg, state, attestation)
    _require(is_valid_indexed_attestation(cfg, state, indexed, verifier),
             "bad attestation signature")
    return state


def get_validator_from_deposit(cfg: SpecConfig, pubkey: bytes,
                               withdrawal_credentials: bytes, amount: int):
    from .datastructures import Validator
    effective = min(amount - amount % cfg.EFFECTIVE_BALANCE_INCREMENT,
                    cfg.MAX_EFFECTIVE_BALANCE)
    return Validator(
        pubkey=pubkey, withdrawal_credentials=withdrawal_credentials,
        effective_balance=effective, slashed=False,
        activation_eligibility_epoch=FAR_FUTURE_EPOCH,
        activation_epoch=FAR_FUTURE_EPOCH, exit_epoch=FAR_FUTURE_EPOCH,
        withdrawable_epoch=FAR_FUTURE_EPOCH)


def apply_deposit(cfg: SpecConfig, state, pubkey: bytes,
                  withdrawal_credentials: bytes, amount: int,
                  signature: bytes,
                  deposit_verifier: SignatureVerifier = SIMPLE):
    pubkeys = [v.pubkey for v in state.validators]
    if pubkey not in pubkeys:
        # EAGER proof-of-possession check — its own verifier, never the
        # block batch (AbstractBlockProcessor.java:84-93): failure skips
        # the deposit rather than invalidating the block.
        msg = DepositMessage(pubkey=pubkey,
                             withdrawal_credentials=withdrawal_credentials,
                             amount=amount)
        domain = H.compute_domain(
            DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION, bytes(32))
        root = H.compute_signing_root(msg, domain)
        if not deposit_verifier.verify([pubkey], root, signature):
            return state
        state = state.copy_with(
            validators=tuple(state.validators)
            + (get_validator_from_deposit(
                cfg, pubkey, withdrawal_credentials, amount),),
            balances=tuple(state.balances) + (amount,))
        return state
    index = pubkeys.index(pubkey)
    return H.increase_balance(state, index, amount)


def process_deposit(cfg: SpecConfig, state, deposit,
                    deposit_verifier: SignatureVerifier = SIMPLE):
    _require(H.is_valid_merkle_branch(
        deposit.data.htr(), deposit.proof,
        cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1, state.eth1_deposit_index,
        state.eth1_data.deposit_root), "bad deposit proof")
    state = state.copy_with(eth1_deposit_index=state.eth1_deposit_index + 1)
    return apply_deposit(
        cfg, state, deposit.data.pubkey,
        deposit.data.withdrawal_credentials, deposit.data.amount,
        deposit.data.signature, deposit_verifier)


def process_voluntary_exit(cfg: SpecConfig, state, signed_exit,
                           verifier: SignatureVerifier,
                           exit_fork_version=None):
    exit_msg = signed_exit.message
    _require(exit_msg.validator_index < len(state.validators),
             "exit: unknown validator")
    v = state.validators[exit_msg.validator_index]
    now = H.get_current_epoch(cfg, state)
    _require(H.is_active_validator(v, now), "exit: not active")
    _require(v.exit_epoch == FAR_FUTURE_EPOCH, "exit: already exiting")
    _require(now >= exit_msg.epoch, "exit: future epoch")
    _require(now >= v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD,
             "exit: too young")
    if exit_fork_version is not None:
        # deneb+ (EIP-7044): exits verify against a PINNED fork version
        # so a signed exit never goes stale across future forks
        domain = H.compute_domain(DOMAIN_VOLUNTARY_EXIT,
                                  exit_fork_version,
                                  state.genesis_validators_root)
    else:
        domain = H.get_domain(cfg, state, DOMAIN_VOLUNTARY_EXIT,
                              exit_msg.epoch)
    root = H.compute_signing_root(exit_msg, domain)
    _require(verifier.verify([v.pubkey], root, signed_exit.signature),
             "exit: bad signature")
    return H.initiate_validator_exit(cfg, state, exit_msg.validator_index)


# --------------------------------------------------------------------------
# process_block
# --------------------------------------------------------------------------

def process_operations(cfg: SpecConfig, state, body,
                       verifier: SignatureVerifier,
                       deposit_verifier: SignatureVerifier = SIMPLE):
    expected_deposits = min(
        cfg.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index)
    _require(len(body.deposits) == expected_deposits,
             "wrong deposit count")
    for op in body.proposer_slashings:
        state = process_proposer_slashing(cfg, state, op, verifier)
    for op in body.attester_slashings:
        state = process_attester_slashing(cfg, state, op, verifier)
    for op in body.attestations:
        state = process_attestation(cfg, state, op, verifier)
    for op in body.deposits:
        state = process_deposit(cfg, state, op, deposit_verifier)
    for op in body.voluntary_exits:
        state = process_voluntary_exit(cfg, state, op, verifier)
    return state


def process_block(cfg: SpecConfig, state, block,
                  verifier: SignatureVerifier,
                  deposit_verifier: SignatureVerifier = SIMPLE):
    state = process_block_header(cfg, state, block)
    state = process_randao(cfg, state, block.body, verifier)
    state = process_eth1_data(cfg, state, block.body)
    state = process_operations(cfg, state, block.body, verifier,
                               deposit_verifier)
    return state
