"""Genesis state construction, including the interop/devnet path.

Equivalent of the reference's genesis machinery (reference: ethereum/
spec/src/main/java/tech/pegasys/teku/spec/logic/common/util/
BeaconStateUtil / genesis generators used by the interop feature and
statetransition/genesis/) — here the deterministic interop path: keys
derived per the interop scheme, deposits applied without proofs, the
eth1 block hash fixed, matching what the acceptance-test devnets use.
"""

import hashlib
from typing import List, Sequence, Tuple

from .config import FAR_FUTURE_EPOCH, GENESIS_EPOCH, SpecConfig
from .datastructures import (BeaconBlockHeader, Eth1Data, Fork,
                             get_schemas, Validator)
from . import block as B
from . import helpers as H

# curve order for interop key derivation
_R = 0x73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001


def interop_secret_keys(n: int) -> List[int]:
    """The standardized interop secret keys:
    sk_i = int(sha256(uint_to_bytes(uint64(i)))) mod r."""
    out = []
    for i in range(n):
        h = hashlib.sha256(i.to_bytes(32, "little")).digest()
        out.append(int.from_bytes(h, "little") % _R)
    return out


def interop_credentials(pubkey: bytes) -> bytes:
    return b"\x00" + hashlib.sha256(pubkey).digest()[1:]


def initialize_beacon_state(cfg: SpecConfig,
                            genesis_time: int,
                            deposits: Sequence[Tuple[bytes, bytes, int]],
                            eth1_block_hash: bytes = b"\x42" * 32):
    """Build a genesis state from (pubkey, withdrawal_credentials,
    amount) tuples — the interop path skips deposit proofs/signatures
    (keys are trusted at genesis)."""
    S = get_schemas(cfg)
    state = S.BeaconState(
        genesis_time=genesis_time,
        fork=Fork(previous_version=cfg.GENESIS_FORK_VERSION,
                  current_version=cfg.GENESIS_FORK_VERSION,
                  epoch=GENESIS_EPOCH),
        eth1_data=Eth1Data(deposit_root=bytes(32),
                           deposit_count=len(deposits),
                           block_hash=eth1_block_hash),
        latest_block_header=BeaconBlockHeader(
            body_root=S.BeaconBlockBody().htr()),
        randao_mixes=tuple(
            eth1_block_hash
            for _ in range(cfg.EPOCHS_PER_HISTORICAL_VECTOR)),
    )
    validators = []
    balances = []
    for pubkey, creds, amount in deposits:
        validators.append(B.get_validator_from_deposit(
            cfg, pubkey, creds, amount))
        balances.append(amount)
    # genesis activations
    for i, v in enumerate(validators):
        if v.effective_balance == cfg.MAX_EFFECTIVE_BALANCE:
            validators[i] = v.copy_with(
                activation_eligibility_epoch=GENESIS_EPOCH,
                activation_epoch=GENESIS_EPOCH)
    state = state.copy_with(
        validators=tuple(validators), balances=tuple(balances),
        eth1_deposit_index=len(deposits),
        genesis_validators_root=_validators_root(cfg, validators))
    # networks may schedule later forks AT genesis (devnets routinely
    # start in altair/capella/…): apply the upgrade chain for every
    # milestone active at epoch 0, as the reference does when building
    # genesis for a config whose fork epochs are 0
    from .milestones import build_fork_schedule
    upgraded = False
    for version in build_fork_schedule(cfg).versions:
        if version.fork_epoch == 0 and version.upgrade_state is not None:
            state = version.upgrade_state(state)
            upgraded = True
    if upgraded:
        # at genesis the spec sets previous == current (there was no
        # prior fork on this chain), unlike a live upgrade — and the
        # empty-body header root must be the ACTIVE fork's body shape
        active = build_fork_schedule(cfg).version_at_slot(0)
        state = state.copy_with(
            fork=Fork(
                previous_version=state.fork.current_version,
                current_version=state.fork.current_version,
                epoch=GENESIS_EPOCH),
            latest_block_header=BeaconBlockHeader(
                body_root=active.schemas.BeaconBlockBody().htr()))
    return state


def _validators_root(cfg: SpecConfig, validators) -> bytes:
    from ..ssz import List as SszList
    return SszList(Validator, cfg.VALIDATOR_REGISTRY_LIMIT
                   ).hash_tree_root(tuple(validators))


def interop_genesis(cfg: SpecConfig, n_validators: int,
                    genesis_time: int = 1578009600):
    """(state, secret_keys) for an n-validator interop devnet."""
    from ..crypto import bls
    sks = interop_secret_keys(n_validators)
    deposits = []
    for sk in sks:
        pk = bls.secret_to_public_key(sk)
        deposits.append((pk, interop_credentials(pk),
                         cfg.MAX_EFFECTIVE_BALANCE))
    state = initialize_beacon_state(cfg, genesis_time, deposits)
    return state, sks


def is_valid_genesis_state(cfg: SpecConfig, state) -> bool:
    if state.genesis_time < cfg.MIN_GENESIS_TIME:
        return False
    active = H.get_active_validator_indices(state, GENESIS_EPOCH)
    return len(active) >= cfg.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
