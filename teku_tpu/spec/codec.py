"""Milestone-aware SignedBeaconBlock wire codec.

The serialization boundary problem the reference solves with
fork-digest-scoped gossip topics and per-milestone schema registries
(reference: networking/eth2 gossip/forks/GossipForkManager.java +
spec/schemas/SchemaDefinitions): a phase0 decoder cannot parse an
altair block.  Every SignedBeaconBlock variant shares the same outer
framing — [u32 message offset][96-byte signature][message: slot is its
first u64] — so the governing milestone can be read from the slot
BEFORE choosing the schema, and serialization is polymorphic on the
container class itself.
"""

import struct

from .config import SpecConfig
from .milestones import build_fork_schedule


def serialize_signed_block(signed_block) -> bytes:
    """Polymorphic: the instance's own class IS its schema."""
    return type(signed_block).serialize(signed_block)


def peek_signed_block_slot(data: bytes) -> int:
    if len(data) < 112:
        raise ValueError("not a signed beacon block")
    (offset,) = struct.unpack_from("<I", data, 0)
    if offset + 8 > len(data):
        raise ValueError("truncated signed beacon block")
    (slot,) = struct.unpack_from("<Q", data, offset)
    return slot


def deserialize_signed_block(cfg: SpecConfig, data: bytes):
    """Route to the schema of the milestone governing the block's slot."""
    slot = peek_signed_block_slot(data)
    version = build_fork_schedule(cfg).version_at_slot(slot)
    return version.schemas.SignedBeaconBlock.deserialize(data)


def deserialize_state(cfg: SpecConfig, data: bytes):
    """States carry their slot at byte offset 40 (genesis_time u64 +
    genesis_validators_root 32 bytes)."""
    if len(data) < 48:
        raise ValueError("not a beacon state")
    (slot,) = struct.unpack_from("<Q", data, 40)
    version = build_fork_schedule(cfg).version_at_slot(slot)
    return version.schemas.BeaconState.deserialize(data)


def deserialize_attestation_wire(cfg: SpecConfig, data: bytes,
                                 current_slot=None):
    """Decode a subnet attestation message; the wire container changes
    at electra (SingleAttestation replaces the one-bit Attestation).

    Length alone cannot disambiguate — a pre-electra attestation over
    an 88-95 member committee is exactly SingleAttestation's 240 fixed
    bytes — so a candidate decode is accepted only if its OWN data.slot
    maps back to the candidate's milestone AND sits near the wall clock
    (a misparse reads 8 root bytes as the slot: astronomically far
    future).  This is the codec-level dual of the per-topic schema the
    reference gets from fork-digest-scoped topics."""
    schedule = build_fork_schedule(cfg)
    from .milestones import SpecMilestone
    last = None
    for version in reversed(schedule.versions):
        if version.milestone >= SpecMilestone.ELECTRA:
            schema = version.schemas.SingleAttestation
        else:
            schema = version.schemas.Attestation
        try:
            msg = schema.deserialize(data)
        except Exception as exc:
            last = exc
            continue
        slot = msg.data.slot
        if schedule.milestone_at_slot(slot) is not version.milestone:
            last = ValueError("attestation slot outside this fork")
            continue
        if current_slot is not None \
                and slot > current_slot + cfg.SLOTS_PER_EPOCH * 2:
            last = ValueError("implausibly distant attestation slot")
            continue
        return msg
    raise last if last is not None else ValueError("empty message")
