"""Phase0 consensus datastructures on the SSZ engine.

Equivalent of the reference's spec/datastructures tree (reference:
ethereum/spec/src/main/java/tech/pegasys/teku/spec/datastructures/ —
there versioned schema registries; here Container classes built per
SpecConfig, since list limits and vector lengths are preset-dependent).

`Schemas(config)` materializes the full phase0 family once per config
and is cached; `SCHEMAS_MAINNET` / `SCHEMAS_MINIMAL` are the common
instantiations.
"""

from functools import lru_cache

from ..ssz import (Bitlist, Bitvector, boolean, Bytes4, Bytes32, Bytes48,
                   Bytes96, Container, List, uint64, Vector)
from ..ssz.types import _ContainerMeta
from .config import MAINNET, MINIMAL, SpecConfig


def _container(name, fields):
    """Create a Container subclass from (field, schema) pairs."""
    return _ContainerMeta(name, (Container,),
                          {"__annotations__": dict(fields)})


# ---- preset-independent containers (defined once, module level) ----

class Fork(Container):
    previous_version: Bytes4
    current_version: Bytes4
    epoch: uint64


class ForkData(Container):
    current_version: Bytes4
    genesis_validators_root: Bytes32


class Checkpoint(Container):
    epoch: uint64
    root: Bytes32


class Validator(Container):
    pubkey: Bytes48
    withdrawal_credentials: Bytes32
    effective_balance: uint64
    slashed: boolean
    activation_eligibility_epoch: uint64
    activation_epoch: uint64
    exit_epoch: uint64
    withdrawable_epoch: uint64


class AttestationData(Container):
    slot: uint64
    index: uint64
    beacon_block_root: Bytes32
    source: Checkpoint
    target: Checkpoint


class Eth1Data(Container):
    deposit_root: Bytes32
    deposit_count: uint64
    block_hash: Bytes32


class DepositMessage(Container):
    pubkey: Bytes48
    withdrawal_credentials: Bytes32
    amount: uint64


class DepositData(Container):
    pubkey: Bytes48
    withdrawal_credentials: Bytes32
    amount: uint64
    signature: Bytes96


class BeaconBlockHeader(Container):
    slot: uint64
    proposer_index: uint64
    parent_root: Bytes32
    state_root: Bytes32
    body_root: Bytes32


class SignedBeaconBlockHeader(Container):
    message: BeaconBlockHeader
    signature: Bytes96


class ProposerSlashing(Container):
    signed_header_1: SignedBeaconBlockHeader
    signed_header_2: SignedBeaconBlockHeader


class VoluntaryExit(Container):
    epoch: uint64
    validator_index: uint64


class SignedVoluntaryExit(Container):
    message: VoluntaryExit
    signature: Bytes96


class SigningData(Container):
    object_root: Bytes32
    domain: Bytes32


class Eth1Block(Container):
    timestamp: uint64
    deposit_root: Bytes32
    deposit_count: uint64


class Status(Container):
    """Req/resp status message (networking/eth2 rpc)."""
    fork_digest: Bytes4
    finalized_root: Bytes32
    finalized_epoch: uint64
    head_root: Bytes32
    head_slot: uint64


class Goodbye(Container):
    reason: uint64


class Ping(Container):
    seq_number: uint64


class MetadataMessage(Container):
    seq_number: uint64
    attnets: Bitvector(64)


class Schemas:
    """Preset-parameterized phase0 schema family.

    Mirrors the reference's SchemaDefinitions registry (reference:
    ethereum/spec/.../spec/schemas/SchemaDefinitions.java): one object
    holding every container class for a given SpecConfig.
    """

    def __init__(self, cfg: SpecConfig):
        self.config = cfg

        # re-export the preset-independent ones for a single namespace
        self.Fork = Fork
        self.ForkData = ForkData
        self.Checkpoint = Checkpoint
        self.Validator = Validator
        self.AttestationData = AttestationData
        self.Eth1Data = Eth1Data
        self.DepositMessage = DepositMessage
        self.DepositData = DepositData
        self.BeaconBlockHeader = BeaconBlockHeader
        self.SignedBeaconBlockHeader = SignedBeaconBlockHeader
        self.ProposerSlashing = ProposerSlashing
        self.VoluntaryExit = VoluntaryExit
        self.SignedVoluntaryExit = SignedVoluntaryExit
        self.SigningData = SigningData

        self.IndexedAttestation = _container("IndexedAttestation", [
            ("attesting_indices",
             List(uint64, cfg.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData),
            ("signature", Bytes96),
        ])
        self.AttesterSlashing = _container("AttesterSlashing", [
            ("attestation_1", self.IndexedAttestation),
            ("attestation_2", self.IndexedAttestation),
        ])
        self.Attestation = _container("Attestation", [
            ("aggregation_bits",
             Bitlist(cfg.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData),
            ("signature", Bytes96),
        ])
        self.PendingAttestation = _container("PendingAttestation", [
            ("aggregation_bits",
             Bitlist(cfg.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", AttestationData),
            ("inclusion_delay", uint64),
            ("proposer_index", uint64),
        ])
        self.Deposit = _container("Deposit", [
            ("proof", Vector(Bytes32, cfg.DEPOSIT_CONTRACT_TREE_DEPTH + 1)),
            ("data", DepositData),
        ])
        self.AggregateAndProof = _container("AggregateAndProof", [
            ("aggregator_index", uint64),
            ("aggregate", self.Attestation),
            ("selection_proof", Bytes96),
        ])
        self.SignedAggregateAndProof = _container("SignedAggregateAndProof", [
            ("message", self.AggregateAndProof),
            ("signature", Bytes96),
        ])
        self.BeaconBlockBody = _container("BeaconBlockBody", [
            ("randao_reveal", Bytes96),
            ("eth1_data", Eth1Data),
            ("graffiti", Bytes32),
            ("proposer_slashings",
             List(ProposerSlashing, cfg.MAX_PROPOSER_SLASHINGS)),
            ("attester_slashings",
             List(self.AttesterSlashing, cfg.MAX_ATTESTER_SLASHINGS)),
            ("attestations", List(self.Attestation, cfg.MAX_ATTESTATIONS)),
            ("deposits", List(self.Deposit, cfg.MAX_DEPOSITS)),
            ("voluntary_exits",
             List(SignedVoluntaryExit, cfg.MAX_VOLUNTARY_EXITS)),
        ])
        self.BeaconBlock = _container("BeaconBlock", [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = _container("SignedBeaconBlock", [
            ("message", self.BeaconBlock),
            ("signature", Bytes96),
        ])
        self.HistoricalBatch = _container("HistoricalBatch", [
            ("block_roots", Vector(Bytes32, cfg.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Bytes32, cfg.SLOTS_PER_HISTORICAL_ROOT)),
        ])
        self.BeaconState = _container("BeaconState", [
            ("genesis_time", uint64),
            ("genesis_validators_root", Bytes32),
            ("slot", uint64),
            ("fork", Fork),
            ("latest_block_header", BeaconBlockHeader),
            ("block_roots", Vector(Bytes32, cfg.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Bytes32, cfg.SLOTS_PER_HISTORICAL_ROOT)),
            ("historical_roots", List(Bytes32, cfg.HISTORICAL_ROOTS_LIMIT)),
            ("eth1_data", Eth1Data),
            ("eth1_data_votes",
             List(Eth1Data, cfg.EPOCHS_PER_ETH1_VOTING_PERIOD
                  * cfg.SLOTS_PER_EPOCH)),
            ("eth1_deposit_index", uint64),
            ("validators",
             List(Validator, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("balances", List(uint64, cfg.VALIDATOR_REGISTRY_LIMIT)),
            ("randao_mixes",
             Vector(Bytes32, cfg.EPOCHS_PER_HISTORICAL_VECTOR)),
            ("slashings", Vector(uint64, cfg.EPOCHS_PER_SLASHINGS_VECTOR)),
            ("previous_epoch_attestations",
             List(self.PendingAttestation,
                  cfg.MAX_ATTESTATIONS * cfg.SLOTS_PER_EPOCH)),
            ("current_epoch_attestations",
             List(self.PendingAttestation,
                  cfg.MAX_ATTESTATIONS * cfg.SLOTS_PER_EPOCH)),
            ("justification_bits", Bitvector(4)),
            ("previous_justified_checkpoint", Checkpoint),
            ("current_justified_checkpoint", Checkpoint),
            ("finalized_checkpoint", Checkpoint),
        ])


@lru_cache(maxsize=8)
def _schemas_for(cfg: SpecConfig) -> Schemas:
    return Schemas(cfg)


def get_schemas(cfg: SpecConfig) -> Schemas:
    return _schemas_for(cfg)


SCHEMAS_MAINNET = get_schemas(MAINNET)
SCHEMAS_MINIMAL = get_schemas(MINIMAL)
