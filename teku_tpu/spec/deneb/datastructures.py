"""Deneb containers: blob-gas execution payload, blob commitments in
the block body, BlobSidecar with its commitment inclusion proof.

reference: ethereum/spec/.../spec/datastructures/execution/versions/
deneb/ExecutionPayloadDeneb*.java, blobs/versions/deneb/BlobSidecar.java
(+ MiscHelpersDeneb.verifyBlobSidecarMerkleProof), state/versions/deneb/.
"""

from functools import lru_cache

from ...ssz import (Bytes32, Bytes48, Bytes96, ByteVector, Container,
                    List, merkle_branch, mix_in_length, uint64, Vector)
from ...ssz.hash import hash_pair
from ..config import SpecConfig
from ..datastructures import SignedBeaconBlockHeader
from ..bellatrix.datastructures import _PAYLOAD_COMMON, _container
from ..capella.datastructures import (Withdrawal, get_capella_schemas)
from ...ssz import ByteList
from ..bellatrix.datastructures import (MAX_BYTES_PER_TRANSACTION,
                                        MAX_TRANSACTIONS_PER_PAYLOAD)

BYTES_PER_FIELD_ELEMENT = 32

_DENEB_PAYLOAD_EXTRA = [("blob_gas_used", uint64),
                        ("excess_blob_gas", uint64)]


def _deneb_payload_pair(cfg: SpecConfig):
    payload = _container("ExecutionPayloadDeneb", _PAYLOAD_COMMON + [
        ("transactions", List(ByteList(MAX_BYTES_PER_TRANSACTION),
                              MAX_TRANSACTIONS_PER_PAYLOAD)),
        ("withdrawals", List(Withdrawal, cfg.MAX_WITHDRAWALS_PER_PAYLOAD)),
    ] + _DENEB_PAYLOAD_EXTRA)
    header = _container("ExecutionPayloadHeaderDeneb", _PAYLOAD_COMMON + [
        ("transactions_root", Bytes32),
        ("withdrawals_root", Bytes32),
    ] + _DENEB_PAYLOAD_EXTRA)
    return payload, header


def payload_to_header_deneb(payload):
    schema = type(payload)._ssz_fields
    kw = {name: getattr(payload, name) for name, _ in _PAYLOAD_COMMON}
    kw["transactions_root"] = schema["transactions"].hash_tree_root(
        payload.transactions)
    kw["withdrawals_root"] = schema["withdrawals"].hash_tree_root(
        payload.withdrawals)
    kw["blob_gas_used"] = payload.blob_gas_used
    kw["excess_blob_gas"] = payload.excess_blob_gas
    return payload.__deneb_header__(**kw)


def kzg_commitment_inclusion_proof_depth(cfg: SpecConfig) -> int:
    """Total depth of the proof from one commitment to the body root:
    commitments-list subtree + the length mix-in + the body field tree
    (17 on mainnet: 12 + 1 + 4)."""
    commitments_depth = max(
        1, (cfg.MAX_BLOB_COMMITMENTS_PER_BLOCK - 1).bit_length())
    n_fields = 12  # deneb BeaconBlockBody field count
    body_depth = (n_fields - 1).bit_length()
    return commitments_depth + 1 + body_depth


class DenebSchemas:
    def __getattr__(self, name):
        if name == "capella":
            raise AttributeError(name)
        return getattr(self.capella, name)

    def __init__(self, cfg: SpecConfig):
        self.config = cfg
        self.capella = get_capella_schemas(cfg)
        C = self.capella
        payload, header = _deneb_payload_pair(cfg)
        payload.__deneb_header__ = header
        self.ExecutionPayload = payload
        self.ExecutionPayloadHeader = header
        self.Blob = ByteVector(cfg.FIELD_ELEMENTS_PER_BLOB
                               * BYTES_PER_FIELD_ELEMENT)
        self.KZGCommitment = Bytes48
        self.KZGProof = Bytes48

        body_fields = dict(C.BeaconBlockBody._ssz_fields.items())
        body_fields["execution_payload"] = payload
        body_fields["blob_kzg_commitments"] = List(
            Bytes48, cfg.MAX_BLOB_COMMITMENTS_PER_BLOCK)
        self.BeaconBlockBody = _container("BeaconBlockBodyDeneb",
                                          body_fields.items())
        self.BeaconBlock = _container("BeaconBlockDeneb", [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = _container("SignedBeaconBlockDeneb", [
            ("message", self.BeaconBlock),
            ("signature", Bytes96),
        ])

        state_fields = dict(C.BeaconState._ssz_fields.items())
        state_fields["latest_execution_payload_header"] = header
        self.BeaconState = _container("BeaconStateDeneb",
                                      state_fields.items())

        depth = kzg_commitment_inclusion_proof_depth(cfg)
        self.BlobSidecar = _container("BlobSidecar", [
            ("index", uint64),
            ("blob", self.Blob),
            ("kzg_commitment", Bytes48),
            ("kzg_proof", Bytes48),
            ("signed_block_header", SignedBeaconBlockHeader),
            ("kzg_commitment_inclusion_proof", Vector(Bytes32, depth)),
        ])
        self.BlobIdentifier = _container("BlobIdentifier", [
            ("block_root", Bytes32),
            ("index", uint64),
        ])


@lru_cache(maxsize=8)
def get_deneb_schemas(cfg: SpecConfig) -> DenebSchemas:
    return DenebSchemas(cfg)


# ---- commitment inclusion proofs (build + verify) ----

def compute_commitment_inclusion_proof(cfg: SpecConfig, body,
                                       index: int):
    """Sibling path from body.blob_kzg_commitments[index] to the body
    root: branch inside the commitments subtree, then the list-length
    chunk, then the body-level field siblings."""
    fields = type(body)._ssz_fields
    limit = cfg.MAX_BLOB_COMMITMENTS_PER_BLOCK
    leaves = [Bytes48.hash_tree_root(c)
              for c in body.blob_kzg_commitments]
    inner = merkle_branch(leaves, index, limit)
    length_chunk = len(leaves).to_bytes(32, "little")
    field_roots = []
    field_idx = None
    for i, (name, schema) in enumerate(fields.items()):
        from ...ssz.types import _schema as _sch
        field_roots.append(_sch(schema).hash_tree_root(
            getattr(body, name)))
        if name == "blob_kzg_commitments":
            field_idx = i
    outer = merkle_branch(field_roots, field_idx)
    return inner + [length_chunk] + outer, field_idx


def make_blob_sidecars(cfg: SpecConfig, signed_block, blobs, proofs):
    """Sidecars for one signed block (the producer side the reference
    implements in BlobSidecarSchema.create / MiscHelpersDeneb
    constructBlobSidecars): one per commitment, each carrying the
    signed header and its commitment's inclusion proof."""
    from ..datastructures import BeaconBlockHeader
    S = get_deneb_schemas(cfg)
    block = signed_block.message
    body = block.body
    n = len(body.blob_kzg_commitments)
    assert len(blobs) == n and len(proofs) == n, \
        "one blob+proof per commitment"
    signed_header = SignedBeaconBlockHeader(
        message=BeaconBlockHeader(
            slot=block.slot, proposer_index=block.proposer_index,
            parent_root=block.parent_root, state_root=block.state_root,
            body_root=body.htr()),
        signature=signed_block.signature)
    out = []
    for i in range(n):
        branch, _ = compute_commitment_inclusion_proof(cfg, body, i)
        out.append(S.BlobSidecar(
            index=i, blob=blobs[i],
            kzg_commitment=body.blob_kzg_commitments[i],
            kzg_proof=proofs[i],
            signed_block_header=signed_header,
            kzg_commitment_inclusion_proof=tuple(branch)))
    return out


def verify_commitment_inclusion_proof(cfg: SpecConfig, sidecar) -> bool:
    """Spec verify_blob_sidecar_inclusion_proof: walk the branch from
    hash_tree_root(commitment) up to the claimed body_root."""
    depth = kzg_commitment_inclusion_proof_depth(cfg)
    commitments_depth = max(
        1, (cfg.MAX_BLOB_COMMITMENTS_PER_BLOCK - 1).bit_length())
    # generalized position: index within subtree, subtree under the
    # length mix (bit 0 at level commitments_depth), field slot above
    field_idx = 11  # blob_kzg_commitments is the 12th deneb body field
    gindex = sidecar.index + (field_idx << (commitments_depth + 1))
    value = Bytes48.hash_tree_root(sidecar.kzg_commitment)
    branch = sidecar.kzg_commitment_inclusion_proof
    if len(branch) != depth:
        return False
    idx = gindex
    for sib in branch:
        if idx & 1:
            value = hash_pair(sib, value)
        else:
            value = hash_pair(value, sib)
        idx >>= 1
    return value == sidecar.signed_block_header.message.body_root
