"""Deneb epoch processing: capella's flow, with the registry-update
churn cap of EIP-7514 applied inside get_validator_churn_limit's
activation side (the reference handles it in EpochProcessorDeneb via
getActivationChurnLimit)."""

from .. import epoch as E0
from .. import helpers as H
from ..capella import epoch as CE
from ..config import SpecConfig

def get_activation_churn_limit(cfg: SpecConfig, state) -> int:
    """EIP-7514: activations per epoch are capped regardless of set
    growth (preset-dependent: 8 mainnet, 4 minimal)."""
    return min(cfg.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT,
               H.get_validator_churn_limit(cfg, state))


def process_registry_updates(cfg: SpecConfig, state):
    return E0.process_registry_updates(
        cfg, state, activation_limit=get_activation_churn_limit(cfg, state))


def process_epoch(cfg: SpecConfig, state):
    from ..altair import epoch as AE
    state = AE.process_justification_and_finalization(cfg, state)
    state = AE.process_inactivity_updates(cfg, state)
    state = AE.process_rewards_and_penalties(
        cfg, state,
        inactivity_quotient=cfg.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
    state = process_registry_updates(cfg, state)
    state = AE.process_slashings(
        cfg, state,
        multiplier=cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX)
    state = E0.process_eth1_data_reset(cfg, state)
    state = E0.process_effective_balance_updates(cfg, state)
    state = E0.process_slashings_reset(cfg, state)
    state = E0.process_randao_mixes_reset(cfg, state)
    state = CE.process_historical_summaries_update(cfg, state)
    state = AE.process_participation_flag_updates(cfg, state)
    state = AE.process_sync_committee_updates(cfg, state)
    return state
