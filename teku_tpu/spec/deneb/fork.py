"""Capella → deneb fork upgrade (spec upgrade_to_deneb): reshape the
payload header with zeroed blob-gas fields."""

from .. import helpers as H
from ..config import SpecConfig
from ..datastructures import Fork
from .datastructures import get_deneb_schemas


def upgrade_to_deneb(cfg: SpecConfig, pre):
    S = get_deneb_schemas(cfg)
    epoch = H.get_current_epoch(cfg, pre)
    fields = {name: getattr(pre, name)
              for name in type(pre)._ssz_fields}
    old = fields.pop("latest_execution_payload_header")
    fields["fork"] = Fork(previous_version=pre.fork.current_version,
                          current_version=cfg.DENEB_FORK_VERSION,
                          epoch=epoch)
    header = S.ExecutionPayloadHeader(
        **{name: getattr(old, name)
           for name in type(old)._ssz_fields},
        blob_gas_used=0, excess_blob_gas=0)
    return S.BeaconState(**fields,
                         latest_execution_payload_header=header)
