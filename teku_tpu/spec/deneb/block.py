"""Deneb block processing: blob-commitment-aware execution payload,
pinned exit domains (EIP-7044), extended attestation inclusion
(EIP-7045).

reference: ethereum/spec/.../logic/versions/deneb/block/
BlockProcessorDeneb.java (processExecutionPayload passes the blob
versioned hashes to the engine; MiscHelpersDeneb
kzgCommitmentToVersionedHash) and util/AttestationUtilDeneb.
"""

from .. import block as B0
from .. import helpers as H
from ..altair import block as AB
from ..bellatrix import block as BB
from ..capella import block as CB
from ..config import SpecConfig, VERSIONED_HASH_VERSION_KZG
from ..verifiers import SignatureVerifier, SIMPLE
from .datastructures import payload_to_header_deneb

_require = B0._require


def kzg_commitment_to_versioned_hash(commitment: bytes) -> bytes:
    return VERSIONED_HASH_VERSION_KZG + H.hash32(commitment)[1:]


def max_blobs_for_slot(cfg: SpecConfig, slot: int) -> int:
    """The blob-count cap governing `slot` (electra raises it) — the
    one lookup gossip validation, pools, and RPC should all share."""
    from ..milestones import build_fork_schedule, SpecMilestone
    ms = build_fork_schedule(cfg).milestone_at_slot(slot)
    return (cfg.MAX_BLOBS_PER_BLOCK_ELECTRA
            if ms >= SpecMilestone.ELECTRA else cfg.MAX_BLOBS_PER_BLOCK)


def process_execution_payload(cfg: SpecConfig, state, body,
                              execution_engine=BB.ACCEPT_ALL_ENGINE):
    # deneb adds: the block's blob load must fit, and the engine gets
    # the versioned hashes to check against the payload's blob txs
    _require(len(body.blob_kzg_commitments) <= cfg.MAX_BLOBS_PER_BLOCK,
             "too many blob commitments")
    versioned_hashes = [kzg_commitment_to_versioned_hash(c)
                        for c in body.blob_kzg_commitments]
    engine = _VersionedHashEngine(execution_engine, versioned_hashes)
    # merge complete by construction at deneb: guard dropped
    return BB.process_execution_payload(
        cfg, state, body, engine,
        to_header=payload_to_header_deneb, transition_guard=False)


class _VersionedHashEngine:
    """Adapter handing the engine the blob versioned hashes alongside
    the payload (the reference's engine_newPayloadV3 carries them)."""

    def __init__(self, engine, versioned_hashes):
        self._engine = engine
        self.versioned_hashes = versioned_hashes

    def notify_new_payload(self, payload) -> bool:
        notify = getattr(self._engine, "notify_new_payload_deneb", None)
        if notify is not None:
            return notify(payload, self.versioned_hashes)
        return self._engine.notify_new_payload(payload)


def process_block(cfg: SpecConfig, state, block,
                  verifier: SignatureVerifier,
                  deposit_verifier: SignatureVerifier = SIMPLE,
                  execution_engine=BB.ACCEPT_ALL_ENGINE):
    state = B0.process_block_header(cfg, state, block)
    state = CB.process_withdrawals(cfg, state,
                                   block.body.execution_payload)
    state = process_execution_payload(cfg, state, block.body,
                                      execution_engine)
    state = B0.process_randao(cfg, state, block.body, verifier)
    state = B0.process_eth1_data(cfg, state, block.body)
    state = CB._process_operations(
        cfg, state, block.body, verifier, deposit_verifier,
        enforce_attestation_window=False,          # EIP-7045
        exit_fork_version=cfg.CAPELLA_FORK_VERSION)  # EIP-7044
    state = AB.process_sync_aggregate(cfg, state,
                                      block.body.sync_aggregate, verifier)
    return state
