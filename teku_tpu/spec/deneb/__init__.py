"""Deneb milestone (EIP-4844 blobs, EIP-7044 pinned exit domains,
EIP-7045 extended attestation inclusion, EIP-7514 churn cap).

reference: ethereum/spec/src/main/java/tech/pegasys/teku/spec/logic/
versions/deneb/ and datastructures/blobs/versions/deneb/.
"""
