"""Synthetic large-validator states for state-transition benchmarks.

The reference benchmarks epoch transitions against generated states of
300k+ validators (reference: eth-benchmark-tests/.../
EpochTransitionBenchmark.java and its .ssz state resources); this
module builds the equivalent in-memory — real containers, plausible
balances/participation, NO BLS work (pubkeys are synthetic: epoch
processing never checks signatures, so keygen would be pure waste on
the hot path we're measuring).
"""

import dataclasses
import random

from . import config as C
from . import helpers as H
from .config import FAR_FUTURE_EPOCH, SpecConfig
from .datastructures import (BeaconBlockHeader, Checkpoint, Eth1Data,
                             Fork, Validator)


def perf_config(base: SpecConfig = None) -> SpecConfig:
    """Mainnet-preset config with altair live at genesis."""
    return dataclasses.replace(base or C.MAINNET, ALTAIR_FORK_EPOCH=0)


def perf_config_electra(base: SpecConfig = None) -> SpecConfig:
    """Mainnet-preset config with every fork live at genesis."""
    return dataclasses.replace(
        base or C.MAINNET, ALTAIR_FORK_EPOCH=0, BELLATRIX_FORK_EPOCH=0,
        CAPELLA_FORK_EPOCH=0, DENEB_FORK_EPOCH=0, ELECTRA_FORK_EPOCH=0)


def make_synthetic_altair_state(cfg: SpecConfig, n_validators: int,
                                epoch: int = 5,
                                participation_rate: float = 0.99,
                                seed: int = 1234):
    """An altair BeaconState at the LAST slot of `epoch` (the slot
    process_epoch runs for), with `participation_rate` of validators
    carrying all three timely flags and the rest absent."""
    from .altair.datastructures import get_altair_schemas

    assert cfg.ALTAIR_FORK_EPOCH == 0, "build against an altair config"
    S = get_altair_schemas(cfg)
    rng = random.Random(seed)
    max_eb = cfg.MAX_EFFECTIVE_BALANCE
    validators = tuple(
        Validator(
            pubkey=i.to_bytes(6, "little") * 8,
            withdrawal_credentials=b"\x01" + bytes(11)
            + i.to_bytes(20, "little"),
            effective_balance=max_eb,
            activation_eligibility_epoch=0, activation_epoch=0,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH)
        for i in range(n_validators))
    balances = tuple(
        max_eb + rng.randrange(-10 ** 9, 10 ** 9)
        for _ in range(n_validators))
    full = (1 << 0) | (1 << 1) | (1 << 2)        # all timely flags
    participation = tuple(
        full if rng.random() < participation_rate else 0
        for _ in range(n_validators))
    slot = (epoch + 1) * cfg.SLOTS_PER_EPOCH - 1
    root = b"\x5b" * 32
    committee_pubkeys = tuple(
        validators[i % n_validators].pubkey
        for i in range(cfg.SYNC_COMMITTEE_SIZE))
    sync_committee = S.SyncCommittee(
        pubkeys=committee_pubkeys,
        aggregate_pubkey=b"\xc0" + bytes(47))
    return S.BeaconState(
        genesis_time=0,
        genesis_validators_root=b"\x33" * 32,
        slot=slot,
        fork=Fork(previous_version=cfg.GENESIS_FORK_VERSION,
                  current_version=cfg.ALTAIR_FORK_VERSION,
                  epoch=0),
        latest_block_header=BeaconBlockHeader(body_root=b"\x44" * 32),
        block_roots=tuple(root
                          for _ in range(cfg.SLOTS_PER_HISTORICAL_ROOT)),
        state_roots=tuple(bytes(32)
                          for _ in range(cfg.SLOTS_PER_HISTORICAL_ROOT)),
        eth1_data=Eth1Data(deposit_root=bytes(32),
                           deposit_count=n_validators,
                           block_hash=b"\x42" * 32),
        eth1_deposit_index=n_validators,
        validators=validators,
        balances=balances,
        randao_mixes=tuple(
            b"\x77" * 32 for _ in range(cfg.EPOCHS_PER_HISTORICAL_VECTOR)),
        slashings=tuple(0 for _ in range(cfg.EPOCHS_PER_SLASHINGS_VECTOR)),
        previous_epoch_participation=participation,
        current_epoch_participation=participation,
        justification_bits=(True, True, True, True),
        previous_justified_checkpoint=Checkpoint(epoch=epoch - 2,
                                                 root=root),
        current_justified_checkpoint=Checkpoint(epoch=epoch - 1,
                                                root=root),
        finalized_checkpoint=Checkpoint(epoch=epoch - 2, root=root),
        inactivity_scores=tuple(0 for _ in range(n_validators)),
        current_sync_committee=sync_committee,
        next_sync_committee=sync_committee,
    )


def make_synthetic_electra_state(cfg: SpecConfig, n_validators: int,
                                 epoch: int = 5,
                                 participation_rate: float = 0.99,
                                 compounding_rate: float = 0.25,
                                 seed: int = 1234):
    """An electra BeaconState at the last slot of `epoch`: mixed
    0x01/0x02 withdrawal credentials, balances straddling the
    per-credential caps, empty pending queues (electra-only fields
    take their schema defaults).  The surface the reference's
    EpochTransitionBenchmark measures, on the latest fork."""
    from .electra.datastructures import get_electra_schemas

    assert cfg.ELECTRA_FORK_EPOCH == 0, "build against an electra config"
    S = get_electra_schemas(cfg)
    rng = random.Random(seed)
    min_ab = cfg.MIN_ACTIVATION_BALANCE
    max_eb = cfg.MAX_EFFECTIVE_BALANCE_ELECTRA
    validators = []
    balances = []
    for i in range(n_validators):
        compounding = rng.random() < compounding_rate
        prefix = b"\x02" if compounding else b"\x01"
        eb = max_eb if compounding and rng.random() < 0.5 else min_ab
        validators.append(Validator(
            pubkey=i.to_bytes(6, "little") * 8,
            withdrawal_credentials=prefix + bytes(11)
            + i.to_bytes(20, "little"),
            effective_balance=eb,
            activation_eligibility_epoch=0, activation_epoch=0,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH))
        balances.append(eb + rng.randrange(-10 ** 9, 10 ** 9))
    full = (1 << 0) | (1 << 1) | (1 << 2)
    participation = tuple(
        full if rng.random() < participation_rate else 0
        for _ in range(n_validators))
    slot = (epoch + 1) * cfg.SLOTS_PER_EPOCH - 1
    root = b"\x5b" * 32
    committee_pubkeys = tuple(
        validators[i % n_validators].pubkey
        for i in range(cfg.SYNC_COMMITTEE_SIZE))
    sync_committee = S.SyncCommittee(
        pubkeys=committee_pubkeys,
        aggregate_pubkey=b"\xc0" + bytes(47))
    return S.BeaconState(
        genesis_time=0,
        genesis_validators_root=b"\x33" * 32,
        slot=slot,
        fork=Fork(previous_version=cfg.DENEB_FORK_VERSION,
                  current_version=cfg.ELECTRA_FORK_VERSION,
                  epoch=0),
        latest_block_header=BeaconBlockHeader(body_root=b"\x44" * 32),
        block_roots=tuple(root
                          for _ in range(cfg.SLOTS_PER_HISTORICAL_ROOT)),
        state_roots=tuple(bytes(32)
                          for _ in range(cfg.SLOTS_PER_HISTORICAL_ROOT)),
        eth1_data=Eth1Data(deposit_root=bytes(32),
                           deposit_count=n_validators,
                           block_hash=b"\x42" * 32),
        eth1_deposit_index=n_validators,
        validators=tuple(validators),
        balances=tuple(balances),
        randao_mixes=tuple(
            b"\x77" * 32 for _ in range(cfg.EPOCHS_PER_HISTORICAL_VECTOR)),
        slashings=tuple(0 for _ in range(cfg.EPOCHS_PER_SLASHINGS_VECTOR)),
        previous_epoch_participation=participation,
        current_epoch_participation=participation,
        justification_bits=(True, True, True, True),
        previous_justified_checkpoint=Checkpoint(epoch=epoch - 2,
                                                 root=root),
        current_justified_checkpoint=Checkpoint(epoch=epoch - 1,
                                                root=root),
        finalized_checkpoint=Checkpoint(epoch=epoch - 2, root=root),
        inactivity_scores=tuple(0 for _ in range(n_validators)),
        current_sync_committee=sync_committee,
        next_sync_committee=sync_committee,
        deposit_requests_start_index=C.UNSET_DEPOSIT_REQUESTS_START_INDEX,
    )
