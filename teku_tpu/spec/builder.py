"""Block and attestation production over the spec engine.

Equivalent of the reference's block-production utilities (reference:
ethereum/spec/src/main/java/tech/pegasys/teku/spec/logic/common/util/
BlockProposalUtil.java and beacon/validator/.../BlockFactoryPhase0) and
the attestation-production side of AttestationUtil.java — used by the
validator client's duties and by chain-scenario tests (the reference's
ChainBuilder testFixture plays the same role).

Signing goes through a `signer(validator_index, signing_root) -> bytes`
callback so callers can plug local keys, slashing-protected signers, or
remote signers.
"""

from typing import Callable, Dict, List, Optional, Sequence

from .config import (DOMAIN_AGGREGATE_AND_PROOF, DOMAIN_BEACON_ATTESTER,
                     DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO,
                     DOMAIN_SELECTION_PROOF, SpecConfig)
from .datastructures import AttestationData, Checkpoint, get_schemas
from . import helpers as H
from .transition import process_slots
from .verifiers import SIMPLE
from ..crypto import bls

Signer = Callable[[int, bytes], bytes]


def make_local_signer(secret_keys: Dict[int, int]) -> Signer:
    def signer(validator_index: int, signing_root: bytes) -> bytes:
        return bls.sign(secret_keys[validator_index], signing_root)
    return signer


def get_randao_reveal(cfg: SpecConfig, state, epoch: int,
                      proposer_index: int, signer: Signer) -> bytes:
    return signer(proposer_index,
                  H.randao_signing_root(cfg, state, epoch))


def attestation_data_for(cfg: SpecConfig, state, slot: int,
                         index: int, head_root: bytes) -> AttestationData:
    """AttestationData per the validator spec: head = current head,
    target = epoch-boundary block."""
    epoch = H.compute_epoch_at_slot(cfg, slot)
    start_slot = H.compute_start_slot_at_epoch(cfg, epoch)
    if start_slot == state.slot or start_slot >= state.slot:
        target_root = head_root
    else:
        target_root = H.get_block_root_at_slot(cfg, state, start_slot)
    return AttestationData(
        slot=slot, index=index, beacon_block_root=head_root,
        source=state.current_justified_checkpoint,
        target=Checkpoint(epoch=epoch, root=target_root))


def produce_attestations(cfg: SpecConfig, state, slot: int,
                         head_root: bytes, signer: Signer,
                         committee_indices: Optional[Sequence[int]] = None,
                         ) -> List:
    """One fully-aggregated attestation per committee at `slot` (every
    member signs; bits all set) — the shape a perfect devnet produces.
    Electra attestations carry the committee in committee_bits with
    data.index == 0 (EIP-7549)."""
    from .milestones import build_fork_schedule, SpecMilestone
    version = build_fork_schedule(cfg).version_at_slot(slot)
    S = version.schemas
    electra = version.milestone >= SpecMilestone.ELECTRA
    epoch = H.compute_epoch_at_slot(cfg, slot)
    out = []
    n_committees = H.get_committee_count_per_slot(cfg, state, epoch)
    targets = (range(n_committees) if committee_indices is None
               else committee_indices)
    for ci in targets:
        committee = H.get_beacon_committee(cfg, state, slot, ci)
        if not committee:
            continue
        data = attestation_data_for(cfg, state, slot,
                                    0 if electra else ci, head_root)
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER, epoch)
        root = H.compute_signing_root(data, domain)
        sigs = [signer(v, root) for v in committee]
        kw = dict(aggregation_bits=tuple(True for _ in committee),
                  data=data, signature=bls.aggregate_signatures(sigs))
        if electra:
            kw["committee_bits"] = tuple(
                i == ci for i in range(cfg.MAX_COMMITTEES_PER_SLOT))
        out.append(S.Attestation(**kw))
    return out


def build_unsigned_block(cfg: SpecConfig, pre, slot: int,
                         randao_reveal: bytes,
                         attestations: Sequence = (),
                         deposits: Sequence = (),
                         proposer_slashings: Sequence = (),
                         attester_slashings: Sequence = (),
                         voluntary_exits: Sequence = (),
                         graffiti: bytes = bytes(32),
                         fee_recipient: Optional[bytes] = None,
                         proposer_index: Optional[int] = None,
                         sync_aggregate=None,
                         eth1_vote=None,
                         blob_kzg_commitments: Sequence = (),
                         bls_to_execution_changes: Sequence = ()):
    """(unsigned block with state root filled, post_state) on an
    already-slot-advanced pre-state — the ONE body-construction recipe
    shared by local production and the validator API (reference:
    BlockProposalUtil.createNewUnsignedBlock).  Milestone-routed: an
    altair+ body carries a sync aggregate (empty participation with the
    infinity signature is spec-valid when none is supplied)."""
    from .milestones import build_fork_schedule
    version = build_fork_schedule(cfg).version_at_slot(slot)
    S = version.schemas
    assert pre.slot == slot, "pre-state must be advanced to the slot"
    if proposer_index is None:
        proposer_index = H.get_beacon_proposer_index(cfg, pre)
    # at a fork that reshapes the attestation container (electra), the
    # previous slot's attestations can't ride in the new body — drop
    # the mismatched shapes, as clients do across the fork boundary
    att_elem = S.BeaconBlockBody._ssz_fields["attestations"].elem
    att_cls = getattr(att_elem, "cls", att_elem)
    attestations = [a for a in attestations if isinstance(a, att_cls)]
    body_kwargs = dict(
        randao_reveal=randao_reveal,
        # the proposer's eth1 vote (reference Eth1DataCache majority
        # vote); default = re-vote the current committed eth1_data
        eth1_data=eth1_vote if eth1_vote is not None else pre.eth1_data,
        graffiti=graffiti,
        proposer_slashings=tuple(proposer_slashings),
        attester_slashings=tuple(attester_slashings),
        attestations=tuple(attestations), deposits=tuple(deposits),
        voluntary_exits=tuple(voluntary_exits))
    if "sync_aggregate" in S.BeaconBlockBody._ssz_fields:
        if sync_aggregate is None:
            from ..crypto.bls.pure_impl import G2_INFINITY
            sync_aggregate = S.SyncAggregate(
                sync_committee_bits=tuple(
                    False for _ in range(cfg.SYNC_COMMITTEE_SIZE)),
                sync_committee_signature=G2_INFINITY)
        body_kwargs["sync_aggregate"] = sync_aggregate
    if "execution_payload" in S.BeaconBlockBody._ssz_fields:
        if "withdrawals" in S.ExecutionPayload._ssz_fields:
            # capella+: payload checks run unconditionally, so build a
            # minimal payload that chains on the stored header, matches
            # randao/timestamp, and carries the expected withdrawals
            body_kwargs["execution_payload"] = _devnet_payload(
                cfg, pre, slot, S, fee_recipient=fee_recipient)
        else:
            # bellatrix default (empty) payload = merge not yet
            # transitioned: the processor skips execution checks
            # (is_execution_enabled False)
            body_kwargs["execution_payload"] = S.ExecutionPayload()
    if "bls_to_execution_changes" in S.BeaconBlockBody._ssz_fields:
        body_kwargs["bls_to_execution_changes"] = tuple(
            bls_to_execution_changes)
    elif bls_to_execution_changes:
        raise ValueError("bls_to_execution_changes need a capella+ fork")
    if "blob_kzg_commitments" in S.BeaconBlockBody._ssz_fields:
        body_kwargs["blob_kzg_commitments"] = tuple(blob_kzg_commitments)
    elif blob_kzg_commitments:
        raise ValueError("blob commitments need a deneb+ fork")
    body = S.BeaconBlockBody(**body_kwargs)
    block = S.BeaconBlock(
        slot=slot, proposer_index=proposer_index,
        parent_root=_parent_root(pre), state_root=bytes(32), body=body)
    post = version.process_block(cfg, pre, block, _TRUSTING, _TRUSTING)
    return block.copy_with(state_root=post.htr()), post


def produce_block(cfg: SpecConfig, state, slot: int, signer: Signer,
                  attestations: Sequence = (),
                  deposits: Sequence = (),
                  proposer_slashings: Sequence = (),
                  attester_slashings: Sequence = (),
                  voluntary_exits: Sequence = (),
                  graffiti: bytes = bytes(32),
                  sync_aggregate=None):
    """Produce and sign a block for `slot` on top of `state`.

    Returns (signed_block, post_state).  The state root is computed by
    running the real transition with signature validation disabled
    (production trusts its own signatures)."""
    from .milestones import build_fork_schedule
    S = build_fork_schedule(cfg).version_at_slot(slot).schemas
    pre = process_slots(cfg, state, slot) if state.slot < slot else state
    proposer_index = H.get_beacon_proposer_index(cfg, pre)
    epoch = H.compute_epoch_at_slot(cfg, slot)
    reveal = get_randao_reveal(cfg, pre, epoch, proposer_index, signer)
    block, post = build_unsigned_block(
        cfg, pre, slot, reveal, attestations, deposits,
        proposer_slashings, attester_slashings, voluntary_exits, graffiti,
        proposer_index=proposer_index, sync_aggregate=sync_aggregate)
    domain = H.get_domain(cfg, pre, DOMAIN_BEACON_PROPOSER, epoch)
    root = H.compute_signing_root(block, domain)
    signed = S.SignedBeaconBlock(message=block,
                                 signature=signer(proposer_index, root))
    return signed, post


def _devnet_payload(cfg: SpecConfig, pre, slot: int, S, fee_recipient=None):
    """A self-consistent execution payload with no real EL attached:
    block hashes chain deterministically off the previous payload header
    (the reference's stubbed EL plays the same role,
    ExecutionLayerManagerStub)."""
    from .bellatrix.block import compute_timestamp_at_slot
    if hasattr(pre, "pending_partial_withdrawals"):
        # electra: the sweep drains the partial queue and uses the
        # compounding-aware predicates
        from .electra.block import get_expected_withdrawals
        withdrawals, _ = get_expected_withdrawals(cfg, pre)
    else:
        from .capella.block import get_expected_withdrawals
        withdrawals = get_expected_withdrawals(cfg, pre)
    header = pre.latest_execution_payload_header
    parent_hash = header.block_hash
    block_hash = H.hash32(b"teku-tpu-devnet-exec" + parent_hash
                          + slot.to_bytes(8, "little"))
    kw = dict(
        parent_hash=parent_hash,
        fee_recipient=(fee_recipient if fee_recipient is not None
                       else bytes(20)),
        prev_randao=H.get_randao_mix(cfg, pre,
                                     H.get_current_epoch(cfg, pre)),
        block_number=header.block_number + 1,
        gas_limit=30_000_000,
        timestamp=compute_timestamp_at_slot(cfg, pre, slot),
        block_hash=block_hash,
        withdrawals=tuple(withdrawals))
    if "excess_blob_gas" in S.ExecutionPayload._ssz_fields:
        kw["blob_gas_used"] = 0
        kw["excess_blob_gas"] = 0
    return S.ExecutionPayload(**kw)


def _parent_root(pre) -> bytes:
    """Root of the latest block header with its state_root filled in
    (process_slot has already done that for any caught-up state)."""
    hdr = pre.latest_block_header
    if hdr.state_root == bytes(32):
        hdr = hdr.copy_with(state_root=pre.htr())
    return hdr.htr()


class _Trusting:
    def verify(self, public_keys, message, signature) -> bool:
        return True


_TRUSTING = _Trusting()


def get_selection_proof(cfg: SpecConfig, state, slot: int,
                        validator_index: int, signer: Signer) -> bytes:
    return signer(validator_index,
                  H.selection_proof_signing_root(cfg, state, slot))


def is_aggregator_by_size(cfg: SpecConfig, committee_size: int,
                          selection_proof: bytes) -> bool:
    """Spec is_aggregator given the committee LENGTH — what a remote VC
    knows from its attester duty without any state (the duty carries
    committee_length precisely so this check needs no shuffling)."""
    modulo = max(1, committee_size // cfg.TARGET_AGGREGATORS_PER_COMMITTEE)
    return (int.from_bytes(H.hash32(selection_proof)[:8], "little")
            % modulo == 0)


def is_aggregator(cfg: SpecConfig, state, slot: int, index: int,
                  selection_proof: bytes) -> bool:
    committee = H.get_beacon_committee(cfg, state, slot, index)
    return is_aggregator_by_size(cfg, len(committee), selection_proof)


def produce_aggregate_and_proof(cfg: SpecConfig, state, aggregate,
                                aggregator_index: int, signer: Signer):
    S = get_schemas(cfg)
    proof = get_selection_proof(cfg, state, aggregate.data.slot,
                                aggregator_index, signer)
    msg = S.AggregateAndProof(aggregator_index=aggregator_index,
                              aggregate=aggregate, selection_proof=proof)
    domain = H.get_domain(cfg, state, DOMAIN_AGGREGATE_AND_PROOF,
                          H.compute_epoch_at_slot(cfg, aggregate.data.slot))
    root = H.compute_signing_root(msg, domain)
    return S.SignedAggregateAndProof(message=msg,
                                     signature=signer(aggregator_index, root))
