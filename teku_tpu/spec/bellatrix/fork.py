"""Altair → bellatrix fork upgrade (spec upgrade_to_bellatrix)."""

from .. import helpers as H
from ..config import SpecConfig
from ..datastructures import Fork
from .datastructures import ExecutionPayloadHeader, get_bellatrix_schemas


def upgrade_to_bellatrix(cfg: SpecConfig, pre):
    S = get_bellatrix_schemas(cfg)
    epoch = H.get_current_epoch(cfg, pre)
    fields = {name: getattr(pre, name)
              for name in type(pre)._ssz_fields}
    fields["fork"] = Fork(previous_version=pre.fork.current_version,
                          current_version=cfg.BELLATRIX_FORK_VERSION,
                          epoch=epoch)
    return S.BeaconState(
        **fields,
        latest_execution_payload_header=ExecutionPayloadHeader())
