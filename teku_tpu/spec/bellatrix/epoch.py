"""Bellatrix epoch processing: altair flow with bellatrix quotients.

reference: ethereum/spec/.../logic/versions/bellatrix/ — the epoch
processor only swaps the inactivity-penalty quotient and proportional
slashing multiplier (spec upgrade notes), everything else is altair's.
"""

from .. import epoch as E0
from ..altair import epoch as AE
from ..config import SpecConfig


def process_epoch(cfg: SpecConfig, state):
    state = AE.process_justification_and_finalization(cfg, state)
    state = AE.process_inactivity_updates(cfg, state)
    state = AE.process_rewards_and_penalties(
        cfg, state,
        inactivity_quotient=cfg.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
    state = E0.process_registry_updates(cfg, state)
    state = AE.process_slashings(
        cfg, state,
        multiplier=cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX)
    state = E0.process_eth1_data_reset(cfg, state)
    state = E0.process_effective_balance_updates(cfg, state)
    state = E0.process_slashings_reset(cfg, state)
    state = E0.process_randao_mixes_reset(cfg, state)
    state = E0.process_historical_roots_update(cfg, state)
    state = AE.process_participation_flag_updates(cfg, state)
    state = AE.process_sync_committee_updates(cfg, state)
    return state
