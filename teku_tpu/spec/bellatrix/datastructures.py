"""Bellatrix containers: execution payload (+header), state/body.

reference: ethereum/spec/.../spec/datastructures/execution/versions/
bellatrix/ExecutionPayload*.java and state/beaconstate/versions/
bellatrix/.
"""

from functools import lru_cache

from ...ssz import (Bitvector, ByteList, Bytes4, Bytes20, Bytes32,
                    Bytes48, Bytes96, Container, List, uint8, uint64,
                    uint256, Vector)
from ..config import SpecConfig
from ..altair.datastructures import get_altair_schemas
# ONE shared Container-from-pairs builder (capella re-imports it from
# here; the phase0 module owns the definition)
from ..datastructures import _container

MAX_BYTES_PER_TRANSACTION = 2 ** 30
MAX_TRANSACTIONS_PER_PAYLOAD = 2 ** 20
BYTES_PER_LOGS_BLOOM = 256
MAX_EXTRA_DATA_BYTES = 32


_PAYLOAD_COMMON = [
    ("parent_hash", Bytes32),
    ("fee_recipient", Bytes20),
    ("state_root", Bytes32),
    ("receipts_root", Bytes32),
    ("logs_bloom", Vector(uint8, BYTES_PER_LOGS_BLOOM)),
    ("prev_randao", Bytes32),
    ("block_number", uint64),
    ("gas_limit", uint64),
    ("gas_used", uint64),
    ("timestamp", uint64),
    ("extra_data", ByteList(MAX_EXTRA_DATA_BYTES)),
    ("base_fee_per_gas", uint256),
    ("block_hash", Bytes32),
]

ExecutionPayload = _container("ExecutionPayload", _PAYLOAD_COMMON + [
    ("transactions", List(ByteList(MAX_BYTES_PER_TRANSACTION),
                          MAX_TRANSACTIONS_PER_PAYLOAD)),
])

ExecutionPayloadHeader = _container(
    "ExecutionPayloadHeader", _PAYLOAD_COMMON + [
        ("transactions_root", Bytes32),
    ])


def payload_to_header(payload) -> "Container":
    tx_schema = ExecutionPayload._ssz_fields["transactions"]
    return ExecutionPayloadHeader(
        **{name: getattr(payload, name)
           for name, _ in _PAYLOAD_COMMON},
        transactions_root=tx_schema.hash_tree_root(payload.transactions))


class BellatrixSchemas:
    def __getattr__(self, name):
        if name == "altair":
            raise AttributeError(name)
        return getattr(self.altair, name)

    def __init__(self, cfg: SpecConfig):
        self.config = cfg
        self.altair = get_altair_schemas(cfg)
        A = self.altair
        self.ExecutionPayload = ExecutionPayload
        self.ExecutionPayloadHeader = ExecutionPayloadHeader
        self.BeaconBlockBody = _container("BeaconBlockBodyBellatrix", [
            *A.BeaconBlockBody._ssz_fields.items(),
            ("execution_payload", ExecutionPayload),
        ])
        self.BeaconBlock = _container("BeaconBlockBellatrix", [
            ("slot", A.BeaconBlock._ssz_fields["slot"]),
            ("proposer_index", A.BeaconBlock._ssz_fields["proposer_index"]),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = _container("SignedBeaconBlockBellatrix", [
            ("message", self.BeaconBlock),
            ("signature", Bytes96),
        ])
        self.BeaconState = _container("BeaconStateBellatrix", [
            *A.BeaconState._ssz_fields.items(),
            ("latest_execution_payload_header", ExecutionPayloadHeader),
        ])


@lru_cache(maxsize=8)
def get_bellatrix_schemas(cfg: SpecConfig) -> BellatrixSchemas:
    return BellatrixSchemas(cfg)
