"""Bellatrix block processing: altair + execution payload.

reference: ethereum/spec/.../logic/versions/bellatrix/block/
BlockProcessorBellatrix.java — processExecutionPayload verifies
parent-hash continuity, prev_randao, timestamp, then hands the payload
to the (optimistic) execution engine and stores its header.
"""

from .. import block as B0
from .. import helpers as H
from ..altair import block as AB
from ..config import SpecConfig
from ..verifiers import SignatureVerifier, SIMPLE
from .datastructures import payload_to_header

_require = B0._require


# the execution-engine seam: swap in EngineJsonRpcClient-backed logic
# at node wiring; the default accepts everything (the reference's
# ExecutionLayerManagerStub / pre-merge behavior)
class _AcceptAllEngine:
    def notify_new_payload(self, payload) -> bool:
        return True


ACCEPT_ALL_ENGINE = _AcceptAllEngine()


def is_merge_transition_complete(state) -> bool:
    # compare against the state's OWN header type: a capella+ state's
    # default header must also read as "merge not complete"
    header = state.latest_execution_payload_header
    return header != type(header)()


def is_merge_transition_block(state, body) -> bool:
    payload = body.execution_payload
    return (not is_merge_transition_complete(state)
            and payload != type(payload)())


def is_execution_enabled(state, body) -> bool:
    return is_merge_transition_block(state, body) \
        or is_merge_transition_complete(state)


def compute_timestamp_at_slot(cfg: SpecConfig, state, slot: int) -> int:
    return state.genesis_time + slot * cfg.SECONDS_PER_SLOT


def process_execution_payload(cfg: SpecConfig, state, body,
                              execution_engine=ACCEPT_ALL_ENGINE,
                              to_header=payload_to_header,
                              transition_guard=True):
    """The ONE payload-validation recipe shared by every post-merge
    fork: later forks swap `to_header` (withdrawals/blob-gas fields)
    and drop `transition_guard` once the merge is complete by
    construction (deneb+)."""
    payload = body.execution_payload
    if not transition_guard or is_merge_transition_complete(state):
        _require(payload.parent_hash
                 == state.latest_execution_payload_header.block_hash,
                 "payload parent hash mismatch")
    _require(payload.prev_randao == H.get_randao_mix(
        cfg, state, H.get_current_epoch(cfg, state)),
        "payload prev_randao mismatch")
    _require(payload.timestamp
             == compute_timestamp_at_slot(cfg, state, state.slot),
             "payload timestamp mismatch")
    _require(execution_engine.notify_new_payload(payload),
             "execution engine rejected the payload")
    return state.copy_with(
        latest_execution_payload_header=to_header(payload))


def process_block(cfg: SpecConfig, state, block,
                  verifier: SignatureVerifier,
                  deposit_verifier: SignatureVerifier = SIMPLE,
                  execution_engine=ACCEPT_ALL_ENGINE):
    state = B0.process_block_header(cfg, state, block)
    if is_execution_enabled(state, block.body):
        state = process_execution_payload(cfg, state, block.body,
                                          execution_engine)
    state = B0.process_randao(cfg, state, block.body, verifier)
    state = B0.process_eth1_data(cfg, state, block.body)
    state = AB._process_operations(cfg, state, block.body, verifier,
                                   deposit_verifier)
    state = AB.process_sync_aggregate(cfg, state,
                                      block.body.sync_aggregate, verifier)
    return state
