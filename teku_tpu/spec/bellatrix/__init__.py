"""Bellatrix milestone: execution payloads + the merge.

Equivalent of the reference's bellatrix logic tree (reference:
ethereum/spec/src/main/java/tech/pegasys/teku/spec/logic/versions/
bellatrix/ — BlockProcessorBellatrix with processExecutionPayload and
the optimistic OptimisticExecutionPayloadExecutor seam, MiscHelpers
Bellatrix merge-transition predicates).
"""

from .datastructures import get_bellatrix_schemas
from .fork import upgrade_to_bellatrix
