"""Capella milestone: withdrawals, BLS-to-execution changes,
historical summaries.

reference: ethereum/spec/src/main/java/tech/pegasys/teku/spec/logic/
versions/capella/ and datastructures/execution/versions/capella/.
"""
