"""Bellatrix → capella fork upgrade (spec upgrade_to_capella):
carry everything, re-shape the payload header with an empty
withdrawals_root, zero the withdrawal cursors, start the summaries
list empty."""

from .. import helpers as H
from ..config import SpecConfig
from ..datastructures import Fork
from .datastructures import get_capella_schemas


def upgrade_to_capella(cfg: SpecConfig, pre):
    S = get_capella_schemas(cfg)
    epoch = H.get_current_epoch(cfg, pre)
    fields = {name: getattr(pre, name)
              for name in type(pre)._ssz_fields}
    old = fields.pop("latest_execution_payload_header")
    fields["fork"] = Fork(previous_version=pre.fork.current_version,
                          current_version=cfg.CAPELLA_FORK_VERSION,
                          epoch=epoch)
    header = S.ExecutionPayloadHeader(
        **{name: getattr(old, name)
           for name in type(old)._ssz_fields},
        withdrawals_root=bytes(32))
    return S.BeaconState(
        **fields,
        latest_execution_payload_header=header,
        next_withdrawal_index=0,
        next_withdrawal_validator_index=0,
        historical_summaries=())
