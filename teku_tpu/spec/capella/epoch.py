"""Capella epoch processing: bellatrix flow, but the per-period
historical accumulation appends a HistoricalSummary instead of a
HistoricalBatch root (spec process_historical_summaries_update).

reference: ethereum/spec/.../logic/versions/capella/statetransition/
epoch/EpochProcessorCapella.java.
"""

from .. import epoch as E0
from .. import helpers as H
from ..altair import epoch as AE
from ..config import SpecConfig
from .datastructures import HistoricalSummary


def process_historical_summaries_update(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    if next_epoch % (cfg.SLOTS_PER_HISTORICAL_ROOT
                     // cfg.SLOTS_PER_EPOCH) == 0:
        fields = type(state)._ssz_fields
        summary = HistoricalSummary(
            block_summary_root=fields["block_roots"].hash_tree_root(
                state.block_roots),
            state_summary_root=fields["state_roots"].hash_tree_root(
                state.state_roots))
        return state.copy_with(
            historical_summaries=tuple(state.historical_summaries)
            + (summary,))
    return state


def process_epoch(cfg: SpecConfig, state):
    state = AE.process_justification_and_finalization(cfg, state)
    state = AE.process_inactivity_updates(cfg, state)
    state = AE.process_rewards_and_penalties(
        cfg, state,
        inactivity_quotient=cfg.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
    state = E0.process_registry_updates(cfg, state)
    state = AE.process_slashings(
        cfg, state,
        multiplier=cfg.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX)
    state = E0.process_eth1_data_reset(cfg, state)
    state = E0.process_effective_balance_updates(cfg, state)
    state = E0.process_slashings_reset(cfg, state)
    state = E0.process_randao_mixes_reset(cfg, state)
    state = process_historical_summaries_update(cfg, state)
    state = AE.process_participation_flag_updates(cfg, state)
    state = AE.process_sync_committee_updates(cfg, state)
    return state
