"""Capella containers: withdrawals-bearing execution payload,
BLS-to-execution changes, historical summaries, state/body.

reference: ethereum/spec/.../spec/datastructures/execution/versions/
capella/ExecutionPayloadCapella*.java, operations/BlsToExecutionChange.java,
state/versions/capella/ (BeaconStateCapella adds next_withdrawal_index,
next_withdrawal_validator_index, historical_summaries).
"""

from functools import lru_cache

from ...ssz import (Bytes20, Bytes32, Bytes48, Bytes96, Container, List,
                    uint64)
from ...ssz.types import _ContainerMeta
from ..config import SpecConfig
from ..bellatrix.datastructures import (_PAYLOAD_COMMON, _container,
                                        MAX_BYTES_PER_TRANSACTION,
                                        MAX_TRANSACTIONS_PER_PAYLOAD,
                                        get_bellatrix_schemas)
from ...ssz import ByteList


class Withdrawal(Container):
    index: uint64
    validator_index: uint64
    address: Bytes20
    amount: uint64


class BLSToExecutionChange(Container):
    validator_index: uint64
    from_bls_pubkey: Bytes48
    to_execution_address: Bytes20


class SignedBLSToExecutionChange(Container):
    message: BLSToExecutionChange
    signature: Bytes96


class HistoricalSummary(Container):
    """Drop-in replacement for HistoricalBatch's root: summarizes one
    SLOTS_PER_HISTORICAL_ROOT window by the roots of the two vectors,
    so the state stops accumulating full batches (EIP-4788 era
    light-client friendliness)."""
    block_summary_root: Bytes32
    state_summary_root: Bytes32


def _capella_payload_pair(cfg: SpecConfig):
    """(ExecutionPayload, ExecutionPayloadHeader) with withdrawals;
    preset-dependent because MAX_WITHDRAWALS_PER_PAYLOAD differs."""
    payload = _container("ExecutionPayloadCapella", _PAYLOAD_COMMON + [
        ("transactions", List(ByteList(MAX_BYTES_PER_TRANSACTION),
                              MAX_TRANSACTIONS_PER_PAYLOAD)),
        ("withdrawals", List(Withdrawal, cfg.MAX_WITHDRAWALS_PER_PAYLOAD)),
    ])
    header = _container("ExecutionPayloadHeaderCapella", _PAYLOAD_COMMON + [
        ("transactions_root", Bytes32),
        ("withdrawals_root", Bytes32),
    ])
    return payload, header


def payload_to_header_capella(payload):
    """Capella header: transactions and withdrawals summarized by root."""
    schema = type(payload)._ssz_fields
    from ..bellatrix.datastructures import _PAYLOAD_COMMON as common
    kw = {name: getattr(payload, name) for name, _ in common}
    kw["transactions_root"] = schema["transactions"].hash_tree_root(
        payload.transactions)
    kw["withdrawals_root"] = schema["withdrawals"].hash_tree_root(
        payload.withdrawals)
    return payload.__capella_header__(**kw)


class CapellaSchemas:
    def __getattr__(self, name):
        if name == "bellatrix":
            raise AttributeError(name)
        return getattr(self.bellatrix, name)

    def __init__(self, cfg: SpecConfig):
        self.config = cfg
        self.bellatrix = get_bellatrix_schemas(cfg)
        B = self.bellatrix
        self.Withdrawal = Withdrawal
        self.BLSToExecutionChange = BLSToExecutionChange
        self.SignedBLSToExecutionChange = SignedBLSToExecutionChange
        self.HistoricalSummary = HistoricalSummary
        payload, header = _capella_payload_pair(cfg)
        payload.__capella_header__ = header
        self.ExecutionPayload = payload
        self.ExecutionPayloadHeader = header

        body_fields = dict(B.BeaconBlockBody._ssz_fields.items())
        body_fields["execution_payload"] = payload
        body_fields["bls_to_execution_changes"] = List(
            SignedBLSToExecutionChange, cfg.MAX_BLS_TO_EXECUTION_CHANGES)
        self.BeaconBlockBody = _container("BeaconBlockBodyCapella",
                                          body_fields.items())
        self.BeaconBlock = _container("BeaconBlockCapella", [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = _container("SignedBeaconBlockCapella", [
            ("message", self.BeaconBlock),
            ("signature", Bytes96),
        ])

        state_fields = dict(B.BeaconState._ssz_fields.items())
        state_fields["latest_execution_payload_header"] = header
        state_fields["next_withdrawal_index"] = uint64
        state_fields["next_withdrawal_validator_index"] = uint64
        state_fields["historical_summaries"] = List(
            HistoricalSummary, cfg.HISTORICAL_ROOTS_LIMIT)
        self.BeaconState = _container("BeaconStateCapella",
                                      state_fields.items())


@lru_cache(maxsize=8)
def get_capella_schemas(cfg: SpecConfig) -> CapellaSchemas:
    return CapellaSchemas(cfg)
