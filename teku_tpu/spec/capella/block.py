"""Capella block processing: withdrawals sweep, BLS-to-execution
changes, post-merge-only execution payload.

reference: ethereum/spec/.../logic/versions/capella/block/
BlockProcessorCapella.java — processWithdrawals validates the payload's
withdrawal list against the state's expected sweep, processBlsToExecutionChange
re-keys a validator's withdrawal credential after verifying a signature
over the GENESIS fork domain (valid across all forks, spec
process_bls_to_execution_change).
"""

from .. import block as B0
from .. import helpers as H
from ..altair import block as AB
from ..bellatrix import block as BB
from ..config import (DOMAIN_BLS_TO_EXECUTION_CHANGE,
                      ETH1_ADDRESS_WITHDRAWAL_PREFIX, SpecConfig)
from ..verifiers import SignatureVerifier, SIMPLE
from .datastructures import Withdrawal, payload_to_header_capella

_require = B0._require


# ---- withdrawal-credential predicates (spec capella helpers) ----

def has_eth1_withdrawal_credential(validator) -> bool:
    return validator.withdrawal_credentials[:1] \
        == ETH1_ADDRESS_WITHDRAWAL_PREFIX


def is_fully_withdrawable_validator(cfg: SpecConfig, validator,
                                    balance: int, epoch: int) -> bool:
    return (has_eth1_withdrawal_credential(validator)
            and validator.withdrawable_epoch <= epoch
            and balance > 0)


def is_partially_withdrawable_validator(cfg: SpecConfig, validator,
                                        balance: int) -> bool:
    return (has_eth1_withdrawal_credential(validator)
            and validator.effective_balance == cfg.MAX_EFFECTIVE_BALANCE
            and balance > cfg.MAX_EFFECTIVE_BALANCE)


# ---- withdrawals ----

def get_expected_withdrawals(cfg: SpecConfig, state):
    """The deterministic sweep: starting at next_withdrawal_validator_index,
    visit up to MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP validators, emitting
    full withdrawals for exited eth1-credentialed validators and skims
    above MAX_EFFECTIVE_BALANCE, capped at MAX_WITHDRAWALS_PER_PAYLOAD."""
    epoch = H.get_current_epoch(cfg, state)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    withdrawals = []
    n = len(state.validators)
    from .. import vectorized as _V
    if n >= _V.VECTOR_THRESHOLD:
        out = []
        for vi, amount in _V.sweep_withdrawal_hits(
                cfg, state, electra=False
        )[:cfg.MAX_WITHDRAWALS_PER_PAYLOAD]:
            out.append(Withdrawal(
                index=withdrawal_index, validator_index=vi,
                address=state.validators[vi]
                .withdrawal_credentials[12:], amount=amount))
            withdrawal_index += 1
        return out
    for _ in range(min(n, cfg.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        v = state.validators[validator_index]
        balance = state.balances[validator_index]
        address = v.withdrawal_credentials[12:]
        if is_fully_withdrawable_validator(cfg, v, balance, epoch):
            withdrawals.append(Withdrawal(
                index=withdrawal_index, validator_index=validator_index,
                address=address, amount=balance))
            withdrawal_index += 1
        elif is_partially_withdrawable_validator(cfg, v, balance):
            withdrawals.append(Withdrawal(
                index=withdrawal_index, validator_index=validator_index,
                address=address,
                amount=balance - cfg.MAX_EFFECTIVE_BALANCE))
            withdrawal_index += 1
        if len(withdrawals) == cfg.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = (validator_index + 1) % n
    return withdrawals


def process_withdrawals(cfg: SpecConfig, state, payload):
    expected = get_expected_withdrawals(cfg, state)
    _require(len(payload.withdrawals) == len(expected),
             "withdrawals: wrong count in payload")
    for got, want in zip(payload.withdrawals, expected):
        _require(got == want, "withdrawals: payload/sweep mismatch")
        state = H.decrease_balance(state, want.validator_index, want.amount)
    n = len(state.validators)
    updates = {}
    if expected:
        updates["next_withdrawal_index"] = expected[-1].index + 1
    if len(expected) == cfg.MAX_WITHDRAWALS_PER_PAYLOAD:
        # sweep saturated: resume right after the last withdrawn validator
        updates["next_withdrawal_validator_index"] = \
            (expected[-1].validator_index + 1) % n
    else:
        # sweep exhausted its visit budget: jump the cursor past it
        updates["next_withdrawal_validator_index"] = \
            (state.next_withdrawal_validator_index
             + cfg.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP) % n
    return state.copy_with(**updates)


# ---- BLS to execution change ----

def process_bls_to_execution_change(cfg: SpecConfig, state, signed_change,
                                    verifier: SignatureVerifier):
    change = signed_change.message
    _require(change.validator_index < len(state.validators),
             "bls change: unknown validator")
    v = state.validators[change.validator_index]
    _require(v.withdrawal_credentials[:1] == cfg.BLS_WITHDRAWAL_PREFIX,
             "bls change: not a BLS credential")
    _require(v.withdrawal_credentials[1:]
             == H.hash32(change.from_bls_pubkey)[1:],
             "bls change: credential does not commit to this key")
    # deliberately fork-agnostic domain: GENESIS fork version so a
    # change signed once stays valid after every fork
    domain = H.compute_domain(DOMAIN_BLS_TO_EXECUTION_CHANGE,
                              cfg.GENESIS_FORK_VERSION,
                              state.genesis_validators_root)
    root = H.compute_signing_root(change, domain)
    _require(verifier.verify([change.from_bls_pubkey], root,
                             signed_change.signature),
             "bls change: bad signature")
    validators = list(state.validators)
    validators[change.validator_index] = v.copy_with(
        withdrawal_credentials=(ETH1_ADDRESS_WITHDRAWAL_PREFIX
                                + bytes(11)
                                + change.to_execution_address))
    return state.copy_with(validators=tuple(validators))


# ---- execution payload ----

def process_execution_payload(cfg: SpecConfig, state, body,
                              execution_engine=BB.ACCEPT_ALL_ENGINE):
    # bellatrix recipe with the capella header shape; the merge
    # transition guard still applies (only deneb removes it)
    return BB.process_execution_payload(
        cfg, state, body, execution_engine,
        to_header=payload_to_header_capella)


def _process_operations(cfg, state, body, verifier, deposit_verifier,
                        enforce_attestation_window: bool = True,
                        exit_fork_version=None):
    state = AB._process_operations(
        cfg, state, body, verifier, deposit_verifier,
        enforce_attestation_window=enforce_attestation_window,
        exit_fork_version=exit_fork_version)
    for op in body.bls_to_execution_changes:
        state = process_bls_to_execution_change(cfg, state, op, verifier)
    return state


def process_block(cfg: SpecConfig, state, block,
                  verifier: SignatureVerifier,
                  deposit_verifier: SignatureVerifier = SIMPLE,
                  execution_engine=BB.ACCEPT_ALL_ENGINE):
    state = B0.process_block_header(cfg, state, block)
    # capella KEEPS the pre-merge guard (an empty-payload block on a
    # not-yet-merged chain skips execution checks); deneb removes it
    if BB.is_execution_enabled(state, block.body):
        state = process_withdrawals(cfg, state,
                                    block.body.execution_payload)
        state = process_execution_payload(cfg, state, block.body,
                                          execution_engine)
    state = B0.process_randao(cfg, state, block.body, verifier)
    state = B0.process_eth1_data(cfg, state, block.body)
    state = _process_operations(cfg, state, block.body, verifier,
                                deposit_verifier)
    state = AB.process_sync_aggregate(cfg, state,
                                      block.body.sync_aggregate, verifier)
    return state
