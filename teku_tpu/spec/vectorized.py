"""Vectorized (numpy) epoch-processing hot loops.

The per-validator Python loops in altair epoch processing are O(V)
interpreter iterations each; at mainnet scale (300k-1M validators)
that's seconds per epoch.  These numpy passes compute the same exact
integer math over flat arrays — the TPU-framework shape (struct-of-
arrays, batch math) applied to the state transition's own hot path,
mirroring how the reference leans on optimized batch processing for
exactly these loops (reference: eth-benchmark-tests/src/jmh/java/
tech/pegasys/teku/benchmarks/EpochTransitionBenchmark.java measures
them; ethereum/spec/.../epoch/RewardsAndPenaltiesCalculatorAltair.java
is the scalar source of truth).

Every function here is an exact drop-in for its scalar twin: all
arithmetic is integer, floor-division ordering is preserved, and the
scalar implementations remain the differential-test oracle
(tests/test_vectorized_epoch.py).  int64 overflow is checked up front;
states that could overflow (pathological inactivity scores) fall back
to the scalar path.
"""

from typing import Optional, Tuple

import numpy as np

from .config import (PARTICIPATION_FLAG_WEIGHTS, SpecConfig,
                     TIMELY_HEAD_FLAG_INDEX, TIMELY_TARGET_FLAG_INDEX,
                     WEIGHT_DENOMINATOR)
from . import helpers as H

# below this the numpy fixed costs beat the loop they replace
VECTOR_THRESHOLD = 256


class OverflowRisk(Exception):
    """Raised when int64 headroom cannot be guaranteed — callers fall
    back to exact big-int scalar code."""


# (validators_tuple, arrays) pairs, newest first: the registry tuple
# is immutable and shared across most of an epoch's passes, so one
# O(V) attribute-extraction pass serves them all.  Identity-keyed —
# any registry change produces a new tuple.
_ARRAY_CACHE: list = []
# strong refs pin whole registries (tuples aren't weakref-able): keep
# the cache just deep enough for one epoch's passes over the current
# and one predecessor registry
_ARRAY_CACHE_MAX = 2


def validator_arrays(state):
    """Struct-of-arrays view of the validator registry (one O(V) pass;
    everything downstream is array math)."""
    vals = state.validators
    for entry in _ARRAY_CACHE:
        if entry[0] is vals:
            return entry[1]
    n = len(vals)
    eb = np.empty(n, dtype=np.int64)
    slashed = np.empty(n, dtype=bool)
    activation = np.empty(n, dtype=np.int64)
    exit_epoch = np.empty(n, dtype=np.int64)
    withdrawable = np.empty(n, dtype=np.int64)
    eligibility = np.empty(n, dtype=np.int64)
    far = np.iinfo(np.int64).max
    for i, v in enumerate(vals):
        eb[i] = v.effective_balance
        slashed[i] = v.slashed
        activation[i] = min(v.activation_epoch, far)
        exit_epoch[i] = min(v.exit_epoch, far)
        withdrawable[i] = min(v.withdrawable_epoch, far)
        eligibility[i] = min(v.activation_eligibility_epoch, far)
    arrays = (eb, slashed, activation, exit_epoch, withdrawable,
              eligibility)
    _ARRAY_CACHE.insert(0, (vals, arrays))
    del _ARRAY_CACHE[_ARRAY_CACHE_MAX:]
    return arrays


def _checked_sum(arr: np.ndarray) -> int:
    """Exact sum of non-negative int64 entries, or OverflowRisk.

    numpy int64 sums WRAP silently at 2^63 (per-element loads raise,
    sums do not), so before trusting one the worst case n * max must
    fit.  Unreachable with real balances (total stake is bounded far
    below 2^63 gwei) but reachable with synthetic states — those fall
    back to exact big-int scalar code."""
    if arr.size and arr.size * int(arr.max()) >= 2 ** 63:
        raise OverflowRisk("int64 sum headroom")
    return int(arr.sum())


def total_active_balance(cfg: SpecConfig, state) -> int:
    """Exact twin of H.get_total_active_balance without the index-set
    build (O(V) python loop → one masked array sum)."""
    cur = H.get_current_epoch(cfg, state)
    eb, _, activation, exit_epoch, _, _ = validator_arrays(state)
    active = (activation <= cur) & (cur < exit_epoch)
    return max(cfg.EFFECTIVE_BALANCE_INCREMENT, _checked_sum(eb[active]))


def _epoch_masks(cfg: SpecConfig, state):
    """(eligible, active_prev, prev_participation) shared by the reward
    and inactivity passes."""
    prev_epoch = H.get_previous_epoch(cfg, state)
    eb, slashed, activation, exit_epoch, withdrawable, _ = \
        validator_arrays(state)
    active_prev = (activation <= prev_epoch) & (prev_epoch < exit_epoch)
    eligible = active_prev | (slashed & (prev_epoch + 1 < withdrawable))
    part = np.fromiter(state.previous_epoch_participation,
                       dtype=np.int64, count=len(eb))
    return eb, slashed, active_prev, eligible, part


def _unslashed_flag_mask(active_prev, slashed, part, flag_index):
    return active_prev & ~slashed & ((part >> flag_index) & 1 == 1)


def process_rewards_and_penalties(cfg: SpecConfig, state,
                                  inactivity_quotient=None):
    """Altair+ rewards/penalties: all flag deltas plus inactivity
    penalties in one array pass (scalar twin:
    altair/epoch.py get_flag_index_deltas +
    get_inactivity_penalty_deltas + process_rewards_and_penalties)."""
    from .altair import helpers as AH

    eb, slashed, active_prev, eligible, part = _epoch_masks(cfg, state)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    total_active = total_active_balance(cfg, state)
    active_increments = total_active // inc
    base_per_inc = (inc * cfg.BASE_REWARD_FACTOR
                    // H.integer_squareroot(total_active))
    base_reward = (eb // inc) * base_per_inc
    from . import epoch as E0
    leaking = E0.is_in_inactivity_leak(cfg, state)

    # int64 headroom for base_reward * weight * unslashed_increments:
    # bound with the REGISTRY-WIDE increment total — per-flag
    # unslashed_increments can exceed active_increments (mass exits:
    # last epoch's participants dwarf the current active set), so the
    # guard must cover the worst multiplicand, not the current one
    max_increments = max(1, _checked_sum(eb) // inc)
    if int(base_reward.max(initial=0)) * 64 * max_increments >= 2 ** 62:
        raise OverflowRisk("flag delta product")

    # the scalar oracle clamps at zero after EACH delta list (one per
    # flag, then inactivity) — a drained balance zeroed by one list's
    # penalty then re-credited by the next differs from a single net
    # clamp, so the application order IS consensus-relevant
    balances = np.fromiter(state.balances, dtype=np.int64,
                           count=len(eb))
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        unslashed = _unslashed_flag_mask(active_prev, slashed, part,
                                         flag_index)
        unslashed_increments = max(
            inc, _checked_sum(eb[unslashed])) // inc
        rewards = np.zeros(len(eb), dtype=np.int64)
        penalties = np.zeros(len(eb), dtype=np.int64)
        if not leaking:
            flag_rewards = (base_reward * weight * unslashed_increments
                            // (active_increments * WEIGHT_DENOMINATOR))
            rewards = np.where(eligible & unslashed, flag_rewards, 0)
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            flag_pens = base_reward * weight // WEIGHT_DENOMINATOR
            penalties = np.where(eligible & ~unslashed, flag_pens, 0)
        balances = np.maximum(0, balances + rewards - penalties)

    # inactivity penalties (their own delta list, own clamp)
    quotient = (cfg.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
                if inactivity_quotient is None else inactivity_quotient)
    scores = np.fromiter(state.inactivity_scores, dtype=np.int64,
                         count=len(eb))
    if int(eb.max(initial=0)) * int(scores.max(initial=0)) >= 2 ** 62:
        raise OverflowRisk("inactivity product")
    not_target = ~_unslashed_flag_mask(active_prev, slashed, part,
                                       TIMELY_TARGET_FLAG_INDEX)
    divisor = cfg.INACTIVITY_SCORE_BIAS * quotient
    inact = np.where(eligible & not_target, eb * scores // divisor, 0)
    balances = np.maximum(0, balances - inact)
    return state.copy_with(balances=tuple(balances.tolist()))


def process_inactivity_updates(cfg: SpecConfig, state):
    """Scalar twin: altair/epoch.py process_inactivity_updates."""
    from . import epoch as E0

    eb, slashed, active_prev, eligible, part = _epoch_masks(cfg, state)
    scores = np.fromiter(state.inactivity_scores, dtype=np.int64,
                         count=len(eb))
    # score-headroom guard: adding INACTIVITY_SCORE_BIAS must not wrap
    # int64 (pathological synthetic scores fall back to scalar code)
    if scores.size and int(scores.max()) >= 2 ** 63 \
            - cfg.INACTIVITY_SCORE_BIAS:
        raise OverflowRisk("inactivity score headroom")
    participated = _unslashed_flag_mask(active_prev, slashed, part,
                                        TIMELY_TARGET_FLAG_INDEX)
    scores = np.where(eligible & participated,
                      scores - np.minimum(1, scores), scores)
    scores = np.where(eligible & ~participated,
                      scores + cfg.INACTIVITY_SCORE_BIAS, scores)
    if not E0.is_in_inactivity_leak(cfg, state):
        scores = np.where(
            eligible,
            scores - np.minimum(cfg.INACTIVITY_SCORE_RECOVERY_RATE,
                                scores),
            scores)
    return state.copy_with(inactivity_scores=tuple(scores.tolist()))


def process_effective_balance_updates(cfg: SpecConfig, state,
                                      max_eb_fn=None):
    """Hysteresis sweep: numpy finds the (typically few) validators
    whose effective balance moves; only those objects are rebuilt
    (scalar twin: epoch.py process_effective_balance_updates; electra
    passes max_eb_fn for per-credential caps)."""
    n = len(state.validators)
    eb = validator_arrays(state)[0]
    balances = np.fromiter(state.balances, dtype=np.int64, count=n)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    down = inc * cfg.HYSTERESIS_DOWNWARD_MULTIPLIER \
        // cfg.HYSTERESIS_QUOTIENT
    up = inc * cfg.HYSTERESIS_UPWARD_MULTIPLIER // cfg.HYSTERESIS_QUOTIENT
    moved = (balances + down < eb) | (eb + up < balances)
    idx = np.nonzero(moved)[0]
    if not len(idx):
        return state
    validators = list(state.validators)
    for i in idx.tolist():
        v = validators[i]
        cap = (cfg.MAX_EFFECTIVE_BALANCE if max_eb_fn is None
               else max_eb_fn(cfg, v))
        validators[i] = v.copy_with(effective_balance=min(
            int(balances[i]) - int(balances[i]) % inc, cap))
    return state.copy_with(validators=tuple(validators))


_FAR_I64 = np.iinfo(np.int64).max    # FAR_FUTURE_EPOCH clipped


def process_slashings(cfg: SpecConfig, state, multiplier: int,
                      per_increment: bool = False):
    """Correlation-penalty sweep: array detection of the (rare)
    validators slashed half a slashings-vector ago, exact big-int math
    per hit.  `per_increment` selects the EIP-7251 electra rounding
    (scalar twins: epoch.py/altair/electra process_slashings)."""
    epoch = H.get_current_epoch(cfg, state)
    _, slashed, _, _, withdrawable, _ = validator_arrays(state)
    target = slashed & (withdrawable
                        == epoch + cfg.EPOCHS_PER_SLASHINGS_VECTOR // 2)
    idx = np.nonzero(target)[0]
    if not len(idx):
        return state.copy_with(balances=state.balances)
    total = total_active_balance(cfg, state)
    adjusted = min(sum(state.slashings) * multiplier, total)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    balances = list(state.balances)
    for i in idx.tolist():
        eb = state.validators[i].effective_balance
        if per_increment:
            penalty = (adjusted // (total // inc)) * (eb // inc)
        else:
            penalty = eb // inc * adjusted // total * inc
        balances[i] = max(0, balances[i] - penalty)
    return state.copy_with(balances=tuple(balances))


def process_registry_updates(cfg: SpecConfig, state,
                             activation_limit=None):
    """Phase0 registry sweep with array candidate detection; the
    per-validator object work happens only on actual hits (scalar
    twin: epoch.py process_registry_updates)."""
    current_epoch = H.get_current_epoch(cfg, state)
    eb, slashed, activation, exit_epoch, withdrawable, eligibility = \
        validator_arrays(state)

    # entry into the activation queue
    enter = (eligibility == _FAR_I64) & (eb == cfg.MAX_EFFECTIVE_BALANCE)
    enter_idx = np.nonzero(enter)[0]
    if len(enter_idx):
        validators = list(state.validators)
        for i in enter_idx.tolist():
            validators[i] = validators[i].copy_with(
                activation_eligibility_epoch=current_epoch + 1)
        state = state.copy_with(validators=tuple(validators))

    # ejections (exit-queue helper mutates sequentially — keep scalar
    # per hit; hits are rare)
    active_now = (activation <= current_epoch) \
        & (current_epoch < exit_epoch)
    eject = active_now & (eb <= cfg.EJECTION_BALANCE)
    for i in np.nonzero(eject)[0].tolist():
        state = H.initiate_validator_exit(cfg, state, i)

    # dequeue up to the churn limit, ordered by (eligibility, index);
    # NOTE: arrays above predate the entry/ejection edits, but entry
    # this epoch sets eligibility=current+1 > finalized so those rows
    # can't be dequeued, and ejection touches exit fields only
    finalized_epoch = state.finalized_checkpoint.epoch
    if len(enter_idx):   # registry changed: refresh the dequeue view
        _, _, activation, _, _, eligibility = validator_arrays(state)
    ready = (eligibility <= finalized_epoch) & (activation == _FAR_I64)
    queue = sorted(np.nonzero(ready)[0].tolist(),
                   key=lambda i: (int(eligibility[i]), i))
    churn = ((max(cfg.MIN_PER_EPOCH_CHURN_LIMIT,
                  int(active_now.sum()) // cfg.CHURN_LIMIT_QUOTIENT))
             if activation_limit is None else activation_limit)
    if queue:
        validators = list(state.validators)
        target_epoch = H.compute_activation_exit_epoch(cfg, current_epoch)
        for i in queue[:churn]:
            validators[i] = validators[i].copy_with(
                activation_epoch=target_epoch)
        state = state.copy_with(validators=tuple(validators))
    return state


_CRED_CACHE: list = []


def credential_first_bytes(state) -> np.ndarray:
    """Identity-cached first byte of every withdrawal credential (the
    prefix that routes capella/electra withdrawal predicates)."""
    vals = state.validators
    for entry in _CRED_CACHE:
        if entry[0] is vals:
            return entry[1]
    out = np.fromiter((v.withdrawal_credentials[0] for v in vals),
                      dtype=np.uint8, count=len(vals))
    _CRED_CACHE.insert(0, (vals, out))
    del _CRED_CACHE[_ARRAY_CACHE_MAX:]
    return out


_PUBKEY_CACHE: list = []


def pubkey_index_map(state) -> dict:
    """Identity-cached pubkey -> index map (electra pending-deposit
    processing needs it every epoch; rebuilding is O(V))."""
    vals = state.validators
    for entry in _PUBKEY_CACHE:
        if entry[0] is vals:
            return entry[1]
    out = {v.pubkey: i for i, v in enumerate(vals)}
    _PUBKEY_CACHE.insert(0, (vals, out))
    del _PUBKEY_CACHE[_ARRAY_CACHE_MAX:]
    return out


def sweep_withdrawal_hits(cfg: SpecConfig, state, electra: bool,
                          skip_amounts=None):
    """Vectorized withdrawals-sweep window: the (validator_index,
    amount) hits, in sweep order, over the bounded visit window
    (scalar twins: capella/block.py and electra/block.py
    get_expected_withdrawals sweep loops).  The caller applies the
    MAX_WITHDRAWALS_PER_PAYLOAD cap and builds the containers."""
    import operator

    n = len(state.validators)
    m = min(n, cfg.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    start = state.next_withdrawal_validator_index
    idx = np.arange(start, start + m, dtype=np.int64) % n
    eb, _, _, _, withdrawable, _ = validator_arrays(state)
    cred0 = credential_first_bytes(state)
    # balances change per block: gather ONLY the window (C-level)
    if m == 1:
        bals = np.array([state.balances[int(idx[0])]], dtype=np.int64)
    else:
        bals = np.fromiter(
            operator.itemgetter(*idx.tolist())(state.balances),
            dtype=np.int64, count=m)
    if skip_amounts:
        for i, vi in enumerate(idx.tolist()):
            if vi in skip_amounts:
                bals[i] -= skip_amounts[vi]
    w_eb = eb[idx]
    w_wd = withdrawable[idx]
    w_cred = cred0[idx]
    epoch = H.get_current_epoch(cfg, state)
    if electra:
        exec_cred = (w_cred == 1) | (w_cred == 2)
        max_eb = np.where(w_cred == 2,
                          cfg.MAX_EFFECTIVE_BALANCE_ELECTRA,
                          cfg.MIN_ACTIVATION_BALANCE)
    else:
        exec_cred = w_cred == 1
        max_eb = np.full(m, cfg.MAX_EFFECTIVE_BALANCE, dtype=np.int64)
    full = exec_cred & (w_wd <= epoch) & (bals > 0)
    partial = ~full & exec_cred & (w_eb == max_eb) & (bals > max_eb)
    hits = np.nonzero(full | partial)[0]
    return [(int(idx[k]),
             int(bals[k]) if full[k] else int(bals[k] - max_eb[k]))
            for k in hits.tolist()]


def process_registry_updates_electra(cfg: SpecConfig, state):
    """Electra registry sweep: vector candidate detection, scalar
    object work on the (rare) hits (scalar twin:
    electra/epoch.py process_registry_updates)."""
    current_epoch = H.get_current_epoch(cfg, state)
    eb, _, activation, exit_epoch, _, eligibility = \
        validator_arrays(state)

    enter = (eligibility == _FAR_I64) \
        & (eb >= cfg.MIN_ACTIVATION_BALANCE)
    enter_idx = np.nonzero(enter)[0]
    if len(enter_idx):
        validators = list(state.validators)
        for i in enter_idx.tolist():
            validators[i] = validators[i].copy_with(
                activation_eligibility_epoch=current_epoch + 1)
        state = state.copy_with(validators=tuple(validators))

    from .electra import helpers as EH
    active_now = (activation <= current_epoch) \
        & (current_epoch < exit_epoch)
    eject = active_now & (eb <= cfg.EJECTION_BALANCE)
    for i in np.nonzero(eject)[0].tolist():
        state = EH.initiate_validator_exit(cfg, state, i)

    # activation: EVERY finalized-eligible validator (no queue cap —
    # electra's churn was paid at deposit time).  Arrays predate the
    # edits above, but new entrants carry eligibility current+1 >
    # finalized, and ejection touches exit fields only.
    finalized_epoch = state.finalized_checkpoint.epoch
    if len(enter_idx):
        _, _, activation, _, _, eligibility = validator_arrays(state)
    ready = (eligibility <= finalized_epoch) & (activation == _FAR_I64)
    ready_idx = np.nonzero(ready)[0]
    if len(ready_idx):
        target = H.compute_activation_exit_epoch(cfg, current_epoch)
        validators = list(state.validators)
        for i in ready_idx.tolist():
            validators[i] = validators[i].copy_with(
                activation_epoch=target)
        state = state.copy_with(validators=tuple(validators))
    return state


def target_participation_balances(cfg: SpecConfig, state
                                  ) -> Tuple[int, int]:
    """(previous_target_balance, current_target_balance) for altair
    justification — array sums instead of building index sets (scalar
    twin: altair/epoch.py process_justification_and_finalization)."""
    prev_epoch = H.get_previous_epoch(cfg, state)
    cur_epoch = H.get_current_epoch(cfg, state)
    eb, slashed, activation, exit_epoch, _, _ = validator_arrays(state)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    out = []
    for epoch, raw in ((prev_epoch, state.previous_epoch_participation),
                       (cur_epoch, state.current_epoch_participation)):
        part = np.fromiter(raw, dtype=np.int64, count=len(eb))
        active = (activation <= epoch) & (epoch < exit_epoch)
        mask = active & ~slashed & (
            (part >> TIMELY_TARGET_FLAG_INDEX) & 1 == 1)
        out.append(max(inc, _checked_sum(eb[mask])))
    return out[0], out[1]
