"""Signature-verifier seams between the spec engine and the BLS backend.

All spec-level verification code is written against these interfaces,
never against the BLS facade directly — the reference's second SPI seam
(reference: infrastructure/bls/src/main/java/tech/pegasys/teku/bls/
BLSSignatureVerifier.java:1-87; ethereum/spec/src/main/java/tech/pegasys/
teku/spec/logic/common/util/AsyncBLSSignatureVerifier.java:24-60;
AsyncBatchBLSSignatureVerifier.java:24-60), so block import can swap in
the collect-then-batch verifier and gossip validation can swap in the
TPU batching service without the spec logic knowing.
"""

import asyncio
import contextlib
import contextvars
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..crypto import bls
from ..infra import faults, tracing
from ..services.admission import VerifyClass

Triple = Tuple[Sequence[bytes], bytes, bytes]

# ambient class override: lets a call site that does not own the
# verifier (e.g. the node's deferred-gossip retry loop re-running a
# validator) demote everything submitted inside the block to a lower
# class without threading a parameter through every layer.  ContextVars
# propagate through awaits within the task, so the whole validate()
# coroutine inherits it.
_CLASS_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "verify_class_override", default=None)


@contextlib.contextmanager
def verify_class(cls: VerifyClass):
    """Run the enclosed (possibly async) code with every service-bound
    verification submitted at `cls` — e.g. OPTIMISTIC for speculative
    re-validation of deferred gossip."""
    token = _CLASS_OVERRIDE.set(cls)
    try:
        yield
    finally:
        _CLASS_OVERRIDE.reset(token)


def effective_class(cls: Optional[VerifyClass]
                    ) -> Optional[VerifyClass]:
    """The ambient override beats the call-site default: a retry loop
    demoting to OPTIMISTIC wins over a validator's GOSSIP."""
    override = _CLASS_OVERRIDE.get()
    return override if override is not None else cls


class SignatureVerifier:
    """Sync seam: verify one (pubkeys, message, signature) triple."""

    def verify(self, public_keys: Sequence[bytes], message: bytes,
               signature: bytes) -> bool:
        raise NotImplementedError


class SimpleSignatureVerifier(SignatureVerifier):
    """Immediate verification straight through the BLS facade (the
    reference's BLSSignatureVerifier.SIMPLE)."""

    def verify(self, public_keys, message, signature) -> bool:
        # `verifiers.dispatch` fault site: the spec-level seam, so
        # injected faults reach block import exactly where a sick
        # backend would
        faults.check("verifiers.dispatch")
        # root span: SIMPLE serves cold paths (no batching service in
        # front), so the trace is opened here and the dispatch IS it
        with tracing.trace("verify", kind="simple"):
            with tracing.span("dispatch"):
                if len(public_keys) == 1:
                    ok = bls.verify(public_keys[0], message, signature)
                else:
                    ok = bls.fast_aggregate_verify(
                        list(public_keys), message, signature)
        return faults.transform("verifiers.dispatch", ok)


SIMPLE = SimpleSignatureVerifier()


class BatchSignatureVerifier(SignatureVerifier):
    """Disposable collect-then-verify verifier for block import.

    verify() only records the triple and optimistically returns True;
    batch_verify() submits everything as ONE random-multiplier batch
    (reference: ethereum/spec/.../statetransition/blockvalidator/
    BatchSignatureVerifier.java:38-108 — there prepareBatchVerify over a
    parallel stream + one completeBatchVerify; here one padded device
    dispatch via bls.batch_verify).  Use once per imported block; a
    False batch_verify invalidates every optimistic True.

    Class: BLOCK_IMPORT — this path bypasses the batching queue
    entirely (one direct dispatch), which IS the strongest priority:
    it never waits behind gossip and can never be shed.  The class is
    stamped on the trace and the capacity model's arrival accounting
    so overload attribution still sees block-import demand.
    """

    cls = VerifyClass.BLOCK_IMPORT

    def __init__(self):
        self._jobs: List[Triple] = []
        self._complete = False

    def verify(self, public_keys, message, signature) -> bool:
        assert not self._complete, "verifier already completed"
        if not public_keys:
            return False
        self._jobs.append((list(public_keys), message, signature))
        return True

    def batch_verify(self) -> bool:
        assert not self._complete, "verifier already completed"
        self._complete = True
        if not self._jobs:
            return True
        faults.check("verifiers.dispatch")
        # offered-load accounting: block-import verifies are demand on
        # the same device the gossip queue shares — the capacity
        # model's utilization must see them or brownout reads low
        from ..infra import capacity
        capacity.record_arrival(self.cls.label, len(self._jobs))
        # root span per imported block's signature batch — the
        # provider's host_prep/device_enqueue/device_sync spans nest
        # inside
        with tracing.trace("verify", kind="block_import",
                           cls=self.cls.label,
                           jobs=str(len(self._jobs))):
            with tracing.span("dispatch"):
                ok = bls.batch_verify(self._jobs)
        return faults.transform("verifiers.dispatch", ok)


class AsyncSignatureVerifier:
    """Async seam: the gossip-side interface the batching service
    implements (reference AsyncBLSSignatureVerifier).  ``cls`` is the
    submitting call site's ``VerifyClass``; ``source`` names the
    arrival's demand stream in the capacity model (the sync-committee
    verbs carry their own) — implementations without a priority queue
    or capacity accounting ignore both."""

    async def verify(self, public_keys: Sequence[bytes], message: bytes,
                     signature: bytes,
                     cls: Optional[VerifyClass] = None,
                     source: Optional[str] = None) -> bool:
        raise NotImplementedError

    @staticmethod
    def wrap(sync_verifier: SignatureVerifier) -> "AsyncSignatureVerifier":
        return _WrappedAsync(sync_verifier)


class _WrappedAsync(AsyncSignatureVerifier):
    def __init__(self, inner: SignatureVerifier):
        self._inner = inner

    async def verify(self, public_keys, message, signature,
                     cls: Optional[VerifyClass] = None,
                     source: Optional[str] = None) -> bool:
        return self._inner.verify(public_keys, message, signature)


class ServiceAsyncSignatureVerifier(AsyncSignatureVerifier):
    """Adapter onto AggregatingSignatureVerificationService (the TPU
    batcher) — futures resolve when the device batch lands.  Threads
    the caller's priority class (validator default or the ambient
    ``verify_class`` override) and arrival source into the service's
    per-class queue and capacity accounting."""

    def __init__(self, service):
        self._service = service

    async def verify(self, public_keys, message, signature,
                     cls: Optional[VerifyClass] = None,
                     source: Optional[str] = None) -> bool:
        return await self._service.verify(
            list(public_keys), message, signature,
            cls=effective_class(cls), source=source)

    async def verify_multi(self, triples: Sequence[Triple],
                           cls: Optional[VerifyClass] = None,
                           source: Optional[str] = None) -> bool:
        return await self._service.verify_multi(
            list(triples), cls=effective_class(cls), source=source)


class AsyncBatchSignatureVerifier:
    """Collect-then-submit adapter: verify() records triples and returns
    True; batch_verify() submits ALL collected triples as ONE atomic
    task to the async delegate, so e.g. a SignedAggregateAndProof's
    three signatures verify together or not at all (reference:
    AsyncBatchBLSSignatureVerifier.java:24-60, used at
    AggregateAttestationValidator.java:124-126,242).  The constructing
    validator stamps its priority class on the whole atomic task.
    """

    def __init__(self, delegate: AsyncSignatureVerifier,
                 cls: Optional[VerifyClass] = None,
                 source: Optional[str] = None):
        self._delegate = delegate
        self._cls = cls
        self._source = source
        self._jobs: List[Triple] = []

    def verify(self, public_keys, message, signature) -> bool:
        self._jobs.append((list(public_keys), message, signature))
        return True

    async def batch_verify(self) -> bool:
        if not self._jobs:
            return True
        if isinstance(self._delegate, ServiceAsyncSignatureVerifier):
            return await self._delegate.verify_multi(
                self._jobs, cls=self._cls, source=self._source)
        for pks, msg, sig in self._jobs:
            if not await self._delegate.verify(pks, msg, sig,
                                               cls=self._cls,
                                               source=self._source):
                return False
        return True
