"""Weak subjectivity: safe-epoch window + anchor validation.

Equivalent of the reference's weak-subjectivity module (reference:
ethereum/weaksubjectivity/src/main/java/tech/pegasys/teku/
weaksubjectivity/WeakSubjectivityCalculator.java and
WeakSubjectivityValidator.java, checked at startup from
BeaconChainController.java:495-502): the period formula from the
public consensus specs, and a validator that refuses to start from a
checkpoint older than the window.
"""

import logging

from . import helpers as H
from .config import SpecConfig

_LOG = logging.getLogger(__name__)


def compute_weak_subjectivity_period(cfg: SpecConfig, state) -> int:
    """Spec compute_weak_subjectivity_period (safety decay 10%)."""
    ws_period = cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    N = len(H.get_active_validator_indices(
        state, H.get_current_epoch(cfg, state)))
    t = (H.get_total_active_balance(cfg, state) // N
         // 10 ** 9) if N else 0          # avg balance in ETH
    T = cfg.MAX_EFFECTIVE_BALANCE // 10 ** 9
    delta = H.get_validator_churn_limit(cfg, state)
    Delta = cfg.MAX_DEPOSITS * cfg.SLOTS_PER_EPOCH
    D = 10  # SAFETY_DECAY percent
    if T * (200 + 3 * D) < t * (200 + 12 * D):
        epochs_for_validator_set_churn = (
            N * (t * (200 + 12 * D) - T * (200 + 3 * D))
            // (600 * delta * (2 * t + T)))
        epochs_for_balance_top_ups = (
            N * (200 + 3 * D) // (600 * Delta))
        ws_period += max(epochs_for_validator_set_churn,
                         epochs_for_balance_top_ups)
    else:
        ws_period += (3 * N * D * t
                      // (200 * Delta * (T - t))) if T > t else ws_period
    return ws_period


class WeakSubjectivityValidator:
    def __init__(self, cfg: SpecConfig):
        self.cfg = cfg

    def is_within_period(self, ws_state, current_epoch: int) -> bool:
        """May the node still trust this weak-subjectivity anchor?"""
        ws_epoch = H.get_current_epoch(self.cfg, ws_state)
        period = compute_weak_subjectivity_period(self.cfg, ws_state)
        return current_epoch <= ws_epoch + period

    def validate_anchor(self, anchor_state, current_epoch: int) -> None:
        if not self.is_within_period(anchor_state, current_epoch):
            raise ValueError(
                "weak subjectivity anchor is outside the safe period — "
                "obtain a recent finalized checkpoint")
        _LOG.info("weak subjectivity check passed (period=%d epochs)",
                  compute_weak_subjectivity_period(self.cfg, anchor_state))
