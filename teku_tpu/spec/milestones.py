"""Milestones + fork schedule: the per-fork routing seam.

Equivalent of the reference's SpecMilestone/ForkSchedule/SpecVersion
trio (reference: ethereum/spec/src/main/java/tech/pegasys/teku/spec/
SpecMilestone.java, ForkSchedule.java, SpecVersion.java — Spec.java:108
routes every operation via atSlot/atEpoch/forMilestone): each milestone
bundles its fork version, activation epoch, schema family and logic
functions; the schedule answers "which milestone governs this slot".

Phase0 logic is complete; later milestones register here as their
logic lands (the delegation machinery is fork-count agnostic, matching
the reference's subclass-the-previous-fork pattern).
"""

import enum
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .config import FAR_FUTURE_EPOCH, SpecConfig


class SpecMilestone(enum.IntEnum):
    PHASE0 = 0
    ALTAIR = 1
    BELLATRIX = 2
    CAPELLA = 3
    DENEB = 4
    ELECTRA = 5

    def is_at_least(self, other: "SpecMilestone") -> bool:
        return self >= other


@dataclass
class SpecVersion:
    """One milestone's bundle (reference SpecVersion.java)."""
    milestone: SpecMilestone
    fork_version: bytes
    fork_epoch: int
    schemas: object
    # logic entry points (phase0 signatures; later forks override)
    process_block: Callable
    process_epoch: Callable
    # justification/finalization alone (fork choice pulls up tips)
    process_justification: Optional[Callable] = None
    upgrade_state: Optional[Callable] = None   # previous-fork state -> ours


class ForkSchedule:
    """Activation epochs → governing milestone (reference
    ForkSchedule.java getSpecMilestoneAtEpoch/Slot)."""

    def __init__(self, cfg: SpecConfig, versions: List[SpecVersion]):
        self.cfg = cfg
        # only milestones actually scheduled (epoch != FAR_FUTURE)
        self.versions = sorted(
            (v for v in versions if v.fork_epoch != FAR_FUTURE_EPOCH),
            key=lambda v: v.fork_epoch)
        assert self.versions and self.versions[0].fork_epoch == 0, (
            "the genesis milestone must activate at epoch 0")

    def milestone_at_epoch(self, epoch: int) -> SpecMilestone:
        governing = self.versions[0]
        for v in self.versions:
            if v.fork_epoch <= epoch:
                governing = v
        return governing.milestone

    def milestone_at_slot(self, slot: int) -> SpecMilestone:
        return self.milestone_at_epoch(slot // self.cfg.SLOTS_PER_EPOCH)

    def version_for(self, milestone: SpecMilestone) -> SpecVersion:
        for v in self.versions:
            if v.milestone == milestone:
                return v
        raise KeyError(f"milestone {milestone.name} not scheduled")

    def version_at_slot(self, slot: int) -> SpecVersion:
        return self.version_for(self.milestone_at_slot(slot))

    def fork_at_epoch(self, epoch: int):
        """(previous_version, current_version, fork_epoch) triple for
        building the state Fork at an epoch."""
        cur = self.version_for(self.milestone_at_epoch(epoch))
        idx = self.versions.index(cur)
        prev = self.versions[idx - 1] if idx > 0 else cur
        return prev.fork_version, cur.fork_version, cur.fork_epoch

    def upgrades_between(self, from_epoch: int, to_epoch: int
                         ) -> List[SpecVersion]:
        """Fork activations in (from_epoch, to_epoch] — process_slots
        applies each version's upgrade_state when crossing its epoch."""
        return [v for v in self.versions
                if from_epoch < v.fork_epoch <= to_epoch
                and v.upgrade_state is not None]


def phase0_version(cfg: SpecConfig) -> SpecVersion:
    from . import block as B
    from . import epoch as E
    from .datastructures import get_schemas
    from .verifiers import SIMPLE

    return SpecVersion(
        milestone=SpecMilestone.PHASE0,
        fork_version=cfg.GENESIS_FORK_VERSION,
        fork_epoch=0,
        schemas=get_schemas(cfg),
        process_block=B.process_block,
        process_epoch=E.process_epoch,
        process_justification=E.process_justification_and_finalization)


def altair_version(cfg: SpecConfig) -> SpecVersion:
    from .altair import block as AB
    from .altair import epoch as AE
    from .altair.datastructures import get_altair_schemas
    from .altair.fork import upgrade_to_altair

    return SpecVersion(
        milestone=SpecMilestone.ALTAIR,
        fork_version=cfg.ALTAIR_FORK_VERSION,
        fork_epoch=cfg.ALTAIR_FORK_EPOCH,
        schemas=get_altair_schemas(cfg),
        process_block=AB.process_block,
        process_epoch=AE.process_epoch,
        process_justification=AE.process_justification_and_finalization,
        upgrade_state=lambda state: upgrade_to_altair(cfg, state))


def bellatrix_version(cfg: SpecConfig) -> SpecVersion:
    from .altair import epoch as AE
    from .bellatrix import block as BB
    from .bellatrix import epoch as BE
    from .bellatrix.datastructures import get_bellatrix_schemas
    from .bellatrix.fork import upgrade_to_bellatrix

    return SpecVersion(
        milestone=SpecMilestone.BELLATRIX,
        fork_version=cfg.BELLATRIX_FORK_VERSION,
        fork_epoch=cfg.BELLATRIX_FORK_EPOCH,
        schemas=get_bellatrix_schemas(cfg),
        process_block=BB.process_block,
        process_epoch=BE.process_epoch,
        process_justification=AE.process_justification_and_finalization,
        upgrade_state=lambda state: upgrade_to_bellatrix(cfg, state))


def capella_version(cfg: SpecConfig) -> SpecVersion:
    from .altair import epoch as AE
    from .capella import block as CB
    from .capella import epoch as CE
    from .capella.datastructures import get_capella_schemas
    from .capella.fork import upgrade_to_capella

    return SpecVersion(
        milestone=SpecMilestone.CAPELLA,
        fork_version=cfg.CAPELLA_FORK_VERSION,
        fork_epoch=cfg.CAPELLA_FORK_EPOCH,
        schemas=get_capella_schemas(cfg),
        process_block=CB.process_block,
        process_epoch=CE.process_epoch,
        process_justification=AE.process_justification_and_finalization,
        upgrade_state=lambda state: upgrade_to_capella(cfg, state))


def deneb_version(cfg: SpecConfig) -> SpecVersion:
    from .altair import epoch as AE
    from .deneb import block as DB
    from .deneb import epoch as DE
    from .deneb.datastructures import get_deneb_schemas
    from .deneb.fork import upgrade_to_deneb

    return SpecVersion(
        milestone=SpecMilestone.DENEB,
        fork_version=cfg.DENEB_FORK_VERSION,
        fork_epoch=cfg.DENEB_FORK_EPOCH,
        schemas=get_deneb_schemas(cfg),
        process_block=DB.process_block,
        process_epoch=DE.process_epoch,
        process_justification=AE.process_justification_and_finalization,
        upgrade_state=lambda state: upgrade_to_deneb(cfg, state))


def electra_version(cfg: SpecConfig) -> SpecVersion:
    from .altair import epoch as AE
    from .electra import block as XB
    from .electra import epoch as XE
    from .electra.datastructures import get_electra_schemas
    from .electra.fork import upgrade_to_electra

    return SpecVersion(
        milestone=SpecMilestone.ELECTRA,
        fork_version=cfg.ELECTRA_FORK_VERSION,
        fork_epoch=cfg.ELECTRA_FORK_EPOCH,
        schemas=get_electra_schemas(cfg),
        process_block=XB.process_block,
        process_epoch=XE.process_epoch,
        process_justification=AE.process_justification_and_finalization,
        upgrade_state=lambda state: upgrade_to_electra(cfg, state))


from functools import lru_cache


@lru_cache(maxsize=16)
def build_fork_schedule(cfg: SpecConfig) -> ForkSchedule:
    """All scheduled milestones for this config: phase0 plus every
    later fork whose epoch is set."""
    return ForkSchedule(cfg, [phase0_version(cfg), altair_version(cfg),
                              bellatrix_version(cfg),
                              capella_version(cfg),
                              deneb_version(cfg),
                              electra_version(cfg)])
