"""Phase0 epoch processing: justification/finalization, rewards,
registry updates, slashings, final updates.

Equivalent of the reference's EpochProcessor (reference: ethereum/spec/
src/main/java/tech/pegasys/teku/spec/logic/common/statetransition/epoch/
EpochProcessor.java and versions/phase0/.../EpochProcessorPhase0.java).
Deltas are accumulated in flat arrays and applied in one state rebuild
per sub-step — the batch-friendly shape — instead of the reference's
per-validator object mutation.
"""

from typing import List, Sequence, Set, Tuple

from .config import GENESIS_EPOCH, FAR_FUTURE_EPOCH, SpecConfig
from .datastructures import Checkpoint, get_schemas
from . import helpers as H

BASE_REWARDS_PER_EPOCH = 4


# --------------------------------------------------------------------------
# Matching attestations
# --------------------------------------------------------------------------

def get_matching_source_attestations(cfg, state, epoch):
    if epoch == H.get_current_epoch(cfg, state):
        return state.current_epoch_attestations
    assert epoch == H.get_previous_epoch(cfg, state)
    return state.previous_epoch_attestations


def get_matching_target_attestations(cfg, state, epoch):
    src = get_matching_source_attestations(cfg, state, epoch)
    if not src:
        # avoid the boundary-root lookup (asserts when state.slot IS the
        # epoch start, which pulled-up-tip evaluation can hit)
        return ()
    root = H.get_block_root(cfg, state, epoch)
    return tuple(a for a in src if a.data.target.root == root)


def get_matching_head_attestations(cfg, state, epoch):
    return tuple(
        a for a in get_matching_target_attestations(cfg, state, epoch)
        if a.data.beacon_block_root
        == H.get_block_root_at_slot(cfg, state, a.data.slot))


def get_unslashed_attesting_indices(cfg, state, attestations) -> Set[int]:
    out: Set[int] = set()
    for a in attestations:
        out.update(H.get_attesting_indices(
            cfg, state, a.data, a.aggregation_bits))
    return {i for i in out if not state.validators[i].slashed}


def get_attesting_balance(cfg, state, attestations) -> int:
    return H.get_total_balance(
        cfg, state, get_unslashed_attesting_indices(
            cfg, state, attestations))


# --------------------------------------------------------------------------
# Justification & finalization
# --------------------------------------------------------------------------

def process_justification_and_finalization(cfg: SpecConfig, state):
    if H.get_current_epoch(cfg, state) <= GENESIS_EPOCH + 1:
        return state
    previous_epoch = H.get_previous_epoch(cfg, state)
    current_epoch = H.get_current_epoch(cfg, state)
    prev_target = get_attesting_balance(
        cfg, state,
        get_matching_target_attestations(cfg, state, previous_epoch))
    cur_target = get_attesting_balance(
        cfg, state,
        get_matching_target_attestations(cfg, state, current_epoch))
    total = H.get_total_active_balance(cfg, state)
    return weigh_justification_and_finalization(
        cfg, state, total, prev_target, cur_target)


def weigh_justification_and_finalization(cfg, state, total_balance,
                                         previous_target, current_target):
    previous_epoch = H.get_previous_epoch(cfg, state)
    current_epoch = H.get_current_epoch(cfg, state)
    old_prev = state.previous_justified_checkpoint
    old_cur = state.current_justified_checkpoint

    bits = list(state.justification_bits)
    bits = [False] + bits[:3]
    prev_just = old_cur
    cur_just = old_cur
    if previous_target * 3 >= total_balance * 2:
        cur_just = Checkpoint(
            epoch=previous_epoch,
            root=H.get_block_root(cfg, state, previous_epoch))
        bits[1] = True
    if current_target * 3 >= total_balance * 2:
        cur_just = Checkpoint(
            epoch=current_epoch,
            root=H.get_block_root(cfg, state, current_epoch))
        bits[0] = True

    finalized = state.finalized_checkpoint
    # 2nd/3rd/4th most recent epochs justified
    if all(bits[1:4]) and old_prev.epoch + 3 == current_epoch:
        finalized = old_prev
    if all(bits[1:3]) and old_prev.epoch + 2 == current_epoch:
        finalized = old_prev
    if all(bits[0:3]) and old_cur.epoch + 2 == current_epoch:
        finalized = old_cur
    if all(bits[0:2]) and old_cur.epoch + 1 == current_epoch:
        finalized = old_cur

    return state.copy_with(
        previous_justified_checkpoint=prev_just,
        current_justified_checkpoint=cur_just,
        justification_bits=tuple(bits),
        finalized_checkpoint=finalized)


# --------------------------------------------------------------------------
# Rewards & penalties
# --------------------------------------------------------------------------

def get_base_reward(cfg, state, index, total_balance) -> int:
    eff = state.validators[index].effective_balance
    return (eff * cfg.BASE_REWARD_FACTOR
            // H.integer_squareroot(total_balance)
            // BASE_REWARDS_PER_EPOCH)


def get_proposer_reward(cfg, state, index, total_balance) -> int:
    return (get_base_reward(cfg, state, index, total_balance)
            // cfg.PROPOSER_REWARD_QUOTIENT)


def get_finality_delay(cfg, state) -> int:
    return (H.get_previous_epoch(cfg, state)
            - state.finalized_checkpoint.epoch)


def is_in_inactivity_leak(cfg, state) -> bool:
    return get_finality_delay(cfg, state) > cfg.MIN_EPOCHS_TO_INACTIVITY_PENALTY


def get_eligible_validator_indices(cfg, state) -> List[int]:
    previous_epoch = H.get_previous_epoch(cfg, state)
    return [i for i, v in enumerate(state.validators)
            if H.is_active_validator(v, previous_epoch)
            or (v.slashed and previous_epoch + 1 < v.withdrawable_epoch)]


def _component_deltas(cfg, state, attestations, n, total_balance,
                      eligible):
    rewards = [0] * n
    penalties = [0] * n
    unslashed = get_unslashed_attesting_indices(cfg, state, attestations)
    attesting_balance = H.get_total_balance(cfg, state, unslashed)
    increment = cfg.EFFECTIVE_BALANCE_INCREMENT
    leak = is_in_inactivity_leak(cfg, state)
    for index in eligible:
        base = get_base_reward(cfg, state, index, total_balance)
        if index in unslashed:
            if leak:
                rewards[index] += base
            else:
                rewards[index] += (base * (attesting_balance // increment)
                                   // (total_balance // increment))
        else:
            penalties[index] += base
    return rewards, penalties


def get_attestation_deltas(cfg, state) -> Tuple[List[int], List[int]]:
    n = len(state.validators)
    total_balance = H.get_total_active_balance(cfg, state)
    eligible = get_eligible_validator_indices(cfg, state)
    previous_epoch = H.get_previous_epoch(cfg, state)
    src = get_matching_source_attestations(cfg, state, previous_epoch)
    tgt = get_matching_target_attestations(cfg, state, previous_epoch)
    head = get_matching_head_attestations(cfg, state, previous_epoch)

    r1, p1 = _component_deltas(cfg, state, src, n, total_balance, eligible)
    r2, p2 = _component_deltas(cfg, state, tgt, n, total_balance, eligible)
    r3, p3 = _component_deltas(cfg, state, head, n, total_balance, eligible)

    # inclusion-delay rewards
    r4 = [0] * n
    att_cache = {}
    for a in src:
        for i in H.get_attesting_indices(cfg, state, a.data,
                                         a.aggregation_bits):
            prev = att_cache.get(i)
            if prev is None or a.inclusion_delay < prev.inclusion_delay:
                att_cache[i] = a
    for index in get_unslashed_attesting_indices(cfg, state, src):
        a = att_cache[index]
        base = get_base_reward(cfg, state, index, total_balance)
        proposer_reward = base // cfg.PROPOSER_REWARD_QUOTIENT
        r4[a.proposer_index] += proposer_reward
        max_attester = base - proposer_reward
        r4[index] += max_attester // a.inclusion_delay

    # inactivity penalties
    p4 = [0] * n
    if is_in_inactivity_leak(cfg, state):
        tgt_unslashed = get_unslashed_attesting_indices(cfg, state, tgt)
        delay = get_finality_delay(cfg, state)
        for index in eligible:
            base = get_base_reward(cfg, state, index, total_balance)
            p4[index] += (BASE_REWARDS_PER_EPOCH * base
                          - base // cfg.PROPOSER_REWARD_QUOTIENT)
            if index not in tgt_unslashed:
                eff = state.validators[index].effective_balance
                p4[index] += (eff * delay
                              // cfg.INACTIVITY_PENALTY_QUOTIENT)

    rewards = [r1[i] + r2[i] + r3[i] + r4[i] for i in range(n)]
    penalties = [p1[i] + p2[i] + p3[i] + p4[i] for i in range(n)]
    return rewards, penalties


def process_rewards_and_penalties(cfg: SpecConfig, state):
    if H.get_current_epoch(cfg, state) == GENESIS_EPOCH:
        return state
    rewards, penalties = get_attestation_deltas(cfg, state)
    balances = list(state.balances)
    for i in range(len(balances)):
        balances[i] = max(0, balances[i] + rewards[i] - penalties[i])
    return state.copy_with(balances=tuple(balances))


# --------------------------------------------------------------------------
# Registry updates / slashings / final updates
# --------------------------------------------------------------------------

def process_registry_updates(cfg: SpecConfig, state,
                             activation_limit=None):
    """`activation_limit` overrides the churn-derived activation cap
    (deneb's EIP-7514 activation churn limit routes through here)."""
    from . import vectorized as _V
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_registry_updates(cfg, state,
                                               activation_limit)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    current_epoch = H.get_current_epoch(cfg, state)
    validators = list(state.validators)
    changed = False
    for i, v in enumerate(validators):
        if H.is_eligible_for_activation_queue(cfg, v):
            validators[i] = v.copy_with(
                activation_eligibility_epoch=current_epoch + 1)
            changed = True
    if changed:
        state = state.copy_with(validators=tuple(validators))
    for i, v in enumerate(state.validators):
        if (H.is_active_validator(v, current_epoch)
                and v.effective_balance <= cfg.EJECTION_BALANCE):
            state = H.initiate_validator_exit(cfg, state, i)

    queue = sorted(
        (i for i, v in enumerate(state.validators)
         if H.is_eligible_for_activation(state, v)),
        key=lambda i: (state.validators[i].activation_eligibility_epoch, i))
    churn = (H.get_validator_churn_limit(cfg, state)
             if activation_limit is None else activation_limit)
    if queue:
        validators = list(state.validators)
        target_epoch = H.compute_activation_exit_epoch(cfg, current_epoch)
        for i in queue[:churn]:
            validators[i] = validators[i].copy_with(
                activation_epoch=target_epoch)
        state = state.copy_with(validators=tuple(validators))
    return state


def process_slashings(cfg: SpecConfig, state):
    from . import vectorized as _V
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_slashings(
                cfg, state, cfg.PROPORTIONAL_SLASHING_MULTIPLIER)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    epoch = H.get_current_epoch(cfg, state)
    total_balance = H.get_total_active_balance(cfg, state)
    adjusted = min(sum(state.slashings)
                   * cfg.PROPORTIONAL_SLASHING_MULTIPLIER, total_balance)
    increment = cfg.EFFECTIVE_BALANCE_INCREMENT
    balances = list(state.balances)
    for i, v in enumerate(state.validators):
        if (v.slashed and epoch + cfg.EPOCHS_PER_SLASHINGS_VECTOR // 2
                == v.withdrawable_epoch):
            penalty = (v.effective_balance // increment * adjusted
                       // total_balance * increment)
            balances[i] = max(0, balances[i] - penalty)
    return state.copy_with(balances=tuple(balances))


def process_eth1_data_reset(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    if next_epoch % cfg.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        return state.copy_with(eth1_data_votes=())
    return state


def process_effective_balance_updates(cfg: SpecConfig, state):
    from . import vectorized as _V
    if len(state.validators) >= _V.VECTOR_THRESHOLD:
        try:
            return _V.process_effective_balance_updates(cfg, state)
        except (_V.OverflowRisk, OverflowError):
            pass     # exact big-int scalar path below
    validators = list(state.validators)
    changed = False
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    down = inc * cfg.HYSTERESIS_DOWNWARD_MULTIPLIER // cfg.HYSTERESIS_QUOTIENT
    up = inc * cfg.HYSTERESIS_UPWARD_MULTIPLIER // cfg.HYSTERESIS_QUOTIENT
    for i, v in enumerate(validators):
        balance = state.balances[i]
        if (balance + down < v.effective_balance
                or v.effective_balance + up < balance):
            validators[i] = v.copy_with(effective_balance=min(
                balance - balance % inc, cfg.MAX_EFFECTIVE_BALANCE))
            changed = True
    if changed:
        return state.copy_with(validators=tuple(validators))
    return state


def process_slashings_reset(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    slashings = list(state.slashings)
    slashings[next_epoch % cfg.EPOCHS_PER_SLASHINGS_VECTOR] = 0
    return state.copy_with(slashings=tuple(slashings))


def process_randao_mixes_reset(cfg: SpecConfig, state):
    current_epoch = H.get_current_epoch(cfg, state)
    next_epoch = current_epoch + 1
    mixes = list(state.randao_mixes)
    mixes[next_epoch % cfg.EPOCHS_PER_HISTORICAL_VECTOR] = (
        H.get_randao_mix(cfg, state, current_epoch))
    return state.copy_with(randao_mixes=tuple(mixes))


def process_historical_roots_update(cfg: SpecConfig, state):
    next_epoch = H.get_current_epoch(cfg, state) + 1
    if next_epoch % (cfg.SLOTS_PER_HISTORICAL_ROOT
                     // cfg.SLOTS_PER_EPOCH) == 0:
        S = get_schemas(cfg)
        batch = S.HistoricalBatch(block_roots=state.block_roots,
                                  state_roots=state.state_roots)
        return state.copy_with(
            historical_roots=tuple(state.historical_roots) + (batch.htr(),))
    return state


def process_participation_record_updates(cfg: SpecConfig, state):
    return state.copy_with(
        previous_epoch_attestations=state.current_epoch_attestations,
        current_epoch_attestations=())


def process_epoch(cfg: SpecConfig, state):
    state = process_justification_and_finalization(cfg, state)
    state = process_rewards_and_penalties(cfg, state)
    state = process_registry_updates(cfg, state)
    state = process_slashings(cfg, state)
    state = process_eth1_data_reset(cfg, state)
    state = process_effective_balance_updates(cfg, state)
    state = process_slashings_reset(cfg, state)
    state = process_randao_mixes_reset(cfg, state)
    state = process_historical_roots_update(cfg, state)
    state = process_participation_record_updates(cfg, state)
    return state
