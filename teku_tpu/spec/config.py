"""Consensus spec configuration: presets + per-network parameters.

Equivalent of the reference's SpecConfig/preset system (reference:
ethereum/spec/src/main/java/tech/pegasys/teku/spec/config/SpecConfig.java
and the bundled preset YAMLs under spec/config/configs/) — here a plain
frozen dataclass with the mainnet and minimal presets inlined (the
values are protocol constants from the public consensus specs, not
reference-repo code).
"""

from dataclasses import dataclass, field, replace
from typing import Dict, Tuple

FAR_FUTURE_EPOCH = 2 ** 64 - 1
GENESIS_SLOT = 0
GENESIS_EPOCH = 0

# BLS domain types (consensus spec constants)
DOMAIN_BEACON_PROPOSER = bytes.fromhex("00000000")
DOMAIN_BEACON_ATTESTER = bytes.fromhex("01000000")
DOMAIN_RANDAO = bytes.fromhex("02000000")
DOMAIN_DEPOSIT = bytes.fromhex("03000000")
DOMAIN_VOLUNTARY_EXIT = bytes.fromhex("04000000")
DOMAIN_SELECTION_PROOF = bytes.fromhex("05000000")
DOMAIN_AGGREGATE_AND_PROOF = bytes.fromhex("06000000")
DOMAIN_SYNC_COMMITTEE = bytes.fromhex("07000000")
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = bytes.fromhex("08000000")
DOMAIN_CONTRIBUTION_AND_PROOF = bytes.fromhex("09000000")
DOMAIN_BLS_TO_EXECUTION_CHANGE = bytes.fromhex("0A000000")
DOMAIN_APPLICATION_MASK = bytes.fromhex("00000001")


@dataclass(frozen=True)
class SpecConfig:
    """Phase0(+) spec parameters; field names follow the consensus spec."""

    preset_name: str = "mainnet"
    config_name: str = "mainnet"

    # Misc
    MAX_COMMITTEES_PER_SLOT: int = 64
    TARGET_COMMITTEE_SIZE: int = 128
    MAX_VALIDATORS_PER_COMMITTEE: int = 2048
    SHUFFLE_ROUND_COUNT: int = 90
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT: int = 16384
    MIN_GENESIS_TIME: int = 1606824000
    HYSTERESIS_QUOTIENT: int = 4
    HYSTERESIS_DOWNWARD_MULTIPLIER: int = 1
    HYSTERESIS_UPWARD_MULTIPLIER: int = 5
    PROPORTIONAL_SLASHING_MULTIPLIER: int = 1

    # Gwei values
    MIN_DEPOSIT_AMOUNT: int = 10 ** 9
    MAX_EFFECTIVE_BALANCE: int = 32 * 10 ** 9
    EJECTION_BALANCE: int = 16 * 10 ** 9
    EFFECTIVE_BALANCE_INCREMENT: int = 10 ** 9

    # Initial values
    GENESIS_FORK_VERSION: bytes = bytes(4)
    GENESIS_DELAY: int = 604800
    BLS_WITHDRAWAL_PREFIX: bytes = b"\x00"

    # Time parameters
    SECONDS_PER_SLOT: int = 12
    SECONDS_PER_ETH1_BLOCK: int = 14
    MIN_ATTESTATION_INCLUSION_DELAY: int = 1
    SLOTS_PER_EPOCH: int = 32
    MIN_SEED_LOOKAHEAD: int = 1
    MAX_SEED_LOOKAHEAD: int = 4
    EPOCHS_PER_ETH1_VOTING_PERIOD: int = 64
    SLOTS_PER_HISTORICAL_ROOT: int = 8192
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY: int = 256
    SHARD_COMMITTEE_PERIOD: int = 256
    MIN_EPOCHS_TO_INACTIVITY_PENALTY: int = 4

    # State list lengths
    EPOCHS_PER_HISTORICAL_VECTOR: int = 65536
    EPOCHS_PER_SLASHINGS_VECTOR: int = 8192
    HISTORICAL_ROOTS_LIMIT: int = 2 ** 24
    VALIDATOR_REGISTRY_LIMIT: int = 2 ** 40

    # Validator cycle
    MIN_PER_EPOCH_CHURN_LIMIT: int = 4
    CHURN_LIMIT_QUOTIENT: int = 65536

    # Rewards and penalties
    BASE_REWARD_FACTOR: int = 64
    WHISTLEBLOWER_REWARD_QUOTIENT: int = 512
    PROPOSER_REWARD_QUOTIENT: int = 8
    INACTIVITY_PENALTY_QUOTIENT: int = 2 ** 26
    MIN_SLASHING_PENALTY_QUOTIENT: int = 128

    # Max operations per block
    MAX_PROPOSER_SLASHINGS: int = 16
    MAX_ATTESTER_SLASHINGS: int = 2
    MAX_ATTESTATIONS: int = 128
    MAX_DEPOSITS: int = 16
    MAX_VOLUNTARY_EXITS: int = 16

    # Deposit contract
    DEPOSIT_CONTRACT_TREE_DEPTH: int = 32
    DEPOSIT_CHAIN_ID: int = 1
    DEPOSIT_NETWORK_ID: int = 1

    # Fork choice
    PROPOSER_SCORE_BOOST: int = 40
    INTERVALS_PER_SLOT: int = 3

    # Networking / gossip validation windows
    ATTESTATION_PROPAGATION_SLOT_RANGE: int = 32
    MAXIMUM_GOSSIP_CLOCK_DISPARITY_MS: int = 500

    # Validator
    TARGET_AGGREGATORS_PER_COMMITTEE: int = 16
    RANDOM_SUBNETS_PER_VALIDATOR: int = 1
    EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION: int = 256
    ATTESTATION_SUBNET_COUNT: int = 64

    # --- Altair ---
    ALTAIR_FORK_VERSION: bytes = bytes.fromhex("01000000")
    ALTAIR_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    SYNC_COMMITTEE_SIZE: int = 512
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD: int = 256
    MIN_SYNC_COMMITTEE_PARTICIPANTS: int = 1
    INACTIVITY_PENALTY_QUOTIENT_ALTAIR: int = 3 * 2 ** 24
    MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR: int = 64
    PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR: int = 2
    INACTIVITY_SCORE_BIAS: int = 4
    INACTIVITY_SCORE_RECOVERY_RATE: int = 16

    # --- Bellatrix ---
    BELLATRIX_FORK_VERSION: bytes = bytes.fromhex("02000000")
    BELLATRIX_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    INACTIVITY_PENALTY_QUOTIENT_BELLATRIX: int = 2 ** 24
    MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX: int = 32
    PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX: int = 3

    # --- Capella ---
    CAPELLA_FORK_VERSION: bytes = bytes.fromhex("03000000")
    CAPELLA_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    MAX_BLS_TO_EXECUTION_CHANGES: int = 16
    MAX_WITHDRAWALS_PER_PAYLOAD: int = 16
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP: int = 16384

    # --- Deneb ---
    DENEB_FORK_VERSION: bytes = bytes.fromhex("04000000")
    DENEB_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    MAX_BLOB_COMMITMENTS_PER_BLOCK: int = 4096
    MAX_BLOBS_PER_BLOCK: int = 6
    FIELD_ELEMENTS_PER_BLOB: int = 4096
    MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS: int = 4096
    MAX_REQUEST_BLOCKS_DENEB: int = 128
    MAX_REQUEST_BLOB_SIDECARS: int = 768


MAINNET = SpecConfig()

DOMAIN_SYNC_COMMITTEE_SELECTION = DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF

# participation flag indices / incentive weights (altair constants)
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = (TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT,
                              TIMELY_HEAD_WEIGHT)

MINIMAL = SpecConfig(
    preset_name="minimal",
    config_name="minimal",
    SYNC_COMMITTEE_SIZE=32,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
    MAX_COMMITTEES_PER_SLOT=4,
    TARGET_COMMITTEE_SIZE=4,
    SHUFFLE_ROUND_COUNT=10,
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
    MIN_GENESIS_TIME=1578009600,
    SECONDS_PER_SLOT=6,
    SLOTS_PER_EPOCH=8,
    EPOCHS_PER_ETH1_VOTING_PERIOD=4,
    SLOTS_PER_HISTORICAL_ROOT=64,
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY=256,
    SHARD_COMMITTEE_PERIOD=64,
    EPOCHS_PER_HISTORICAL_VECTOR=64,
    EPOCHS_PER_SLASHINGS_VECTOR=64,
    HISTORICAL_ROOTS_LIMIT=2 ** 24,
    VALIDATOR_REGISTRY_LIMIT=2 ** 40,
    GENESIS_DELAY=300,
    CHURN_LIMIT_QUOTIENT=32,
    INACTIVITY_PENALTY_QUOTIENT=2 ** 25,
    MIN_SLASHING_PENALTY_QUOTIENT=64,
    PROPORTIONAL_SLASHING_MULTIPLIER=2,
    GENESIS_FORK_VERSION=bytes.fromhex("00000001"),
    MAX_WITHDRAWALS_PER_PAYLOAD=4,
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=16,
    FIELD_ELEMENTS_PER_BLOB=4096,
    MAX_BLOB_COMMITMENTS_PER_BLOCK=16,
)

# withdrawal-credential prefixes (consensus spec constants)
BLS_WITHDRAWAL_PREFIX_BYTE = b"\x00"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"
COMPOUNDING_WITHDRAWAL_PREFIX = b"\x02"

# EIP-4844: versioned-hash prefix for KZG commitments
VERSIONED_HASH_VERSION_KZG = b"\x01"

NETWORKS: Dict[str, SpecConfig] = {
    "mainnet": MAINNET,
    "minimal": MINIMAL,
}


def get_config(name: str) -> SpecConfig:
    try:
        return NETWORKS[name]
    except KeyError:
        raise ValueError(f"unknown network/preset {name!r}") from None
