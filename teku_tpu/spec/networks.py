"""Per-network configuration bundles: mainnet, sepolia, holesky, gnosis.

Equivalent of the reference's bundled network configs + builder
(reference: ethereum/networks/src/main/resources/ fork schedules and
Eth2NetworkConfiguration.java with deposit contract, bootnodes and
checkpoint-sync URLs).  Values are public protocol constants from the
published network configs.

A bundle = the SpecConfig (preset + network overrides: fork versions/
epochs, churn, deposit chain) + network identity (genesis validators
root, genesis time, deposit contract address) + operational defaults
(bootnode ENRs, checkpoint-sync URLs).  `--network <name>` resolves
here (teku_tpu/cli.py -> spec.create_spec).
"""

from dataclasses import dataclass, replace
from typing import Dict, Optional, Tuple

from .config import FAR_FUTURE_EPOCH, MAINNET, MINIMAL, SpecConfig


@dataclass(frozen=True)
class NetworkBundle:
    name: str
    config: SpecConfig
    genesis_validators_root: Optional[bytes] = None
    genesis_time: Optional[int] = None
    deposit_contract: Optional[bytes] = None       # 20-byte address
    bootnodes: Tuple[str, ...] = ()
    checkpoint_sync_urls: Tuple[str, ...] = ()


# --------------------------------------------------------------------------
# mainnet — the real fork schedule (preset values already in MAINNET)
# --------------------------------------------------------------------------

MAINNET_NETWORK = NetworkBundle(
    name="mainnet",
    config=replace(
        MAINNET,
        config_name="mainnet",
        ALTAIR_FORK_EPOCH=74240,
        BELLATRIX_FORK_EPOCH=144896,
        CAPELLA_FORK_EPOCH=194048,
        DENEB_FORK_EPOCH=269568,
        ELECTRA_FORK_EPOCH=364032,
    ),
    genesis_validators_root=bytes.fromhex(
        "4b363db94e286120d76eb905340fdd4e54bfe9f06bf33ff6cf5ad27f511bfe95"),
    genesis_time=1606824023,
    deposit_contract=bytes.fromhex(
        "00000000219ab540356cbb839cbe05303d7705fa"),
    bootnodes=(
        # EF + client-team mainnet bootnode ENRs ship with every client;
        # carried as opaque strings for the discovery layer
        "enr:-Ku4QImhMc1z8yCiNJ1TyUxdcfNucje3BGwEHzodEZUan8PherEo4sF7pPHPSIB1NNuSg5fZy7qFsjmUKs2ea1Whi0EBh2F0dG5ldHOIAAAAAAAAAACEZXRoMpD1pf1CAAAAAP__________gmlkgnY0gmlwhBLf22SJc2VjcDI1NmsxoQOVphkDqal4QzPMksc5wnpuC3gvSC8AfbFOnZY_On34wIN1ZHCCIyg",
        "enr:-Ku4QP2xDnEtUXIjzJ_DhlCRN9SN99RYQPJL92TMlSv7U5C1YnYLjwOQHgZIUXw6c-BvRg2Yc2QsZxxoS_pPRVe0yK8Bh2F0dG5ldHOIAAAAAAAAAACEZXRoMpD1pf1CAAAAAP__________gmlkgnY0gmlwhBLf22SJc2VjcDI1NmsxoQMeFF5GrS7UZpAH2Ly84aLK-TyvH-dRo0JM1i8yygH50YN1ZHCCJxA",
    ),
    checkpoint_sync_urls=(
        "https://beaconstate.info",
        "https://mainnet-checkpoint-sync.attestant.io",
    ),
)


# --------------------------------------------------------------------------
# sepolia — permissioned-deposit testnet (mainnet preset)
# --------------------------------------------------------------------------

SEPOLIA_NETWORK = NetworkBundle(
    name="sepolia",
    config=replace(
        MAINNET,
        config_name="sepolia",
        MIN_GENESIS_TIME=1655647200,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=1300,
        GENESIS_DELAY=86400,
        GENESIS_FORK_VERSION=bytes.fromhex("90000069"),
        ALTAIR_FORK_VERSION=bytes.fromhex("90000070"),
        ALTAIR_FORK_EPOCH=50,
        BELLATRIX_FORK_VERSION=bytes.fromhex("90000071"),
        BELLATRIX_FORK_EPOCH=100,
        CAPELLA_FORK_VERSION=bytes.fromhex("90000072"),
        CAPELLA_FORK_EPOCH=56832,
        DENEB_FORK_VERSION=bytes.fromhex("90000073"),
        DENEB_FORK_EPOCH=132608,
        ELECTRA_FORK_VERSION=bytes.fromhex("90000074"),
        ELECTRA_FORK_EPOCH=222464,
        DEPOSIT_CHAIN_ID=11155111,
        DEPOSIT_NETWORK_ID=11155111,
    ),
    genesis_validators_root=bytes.fromhex(
        "d8ea171f3c94aea21ebc42a1ed61052acf3f9209c00e4efbaaddac09ed9b8078"),
    genesis_time=1655733600,
    deposit_contract=bytes.fromhex(
        "7f02c3e3c98b133055b8b348b2ac625669ed295d"),
    checkpoint_sync_urls=(
        "https://sepolia.beaconstate.info",
        "https://checkpoint-sync.sepolia.ethpandaops.io",
    ),
)


# --------------------------------------------------------------------------
# holesky — large public testnet (mainnet preset)
# --------------------------------------------------------------------------

HOLESKY_NETWORK = NetworkBundle(
    name="holesky",
    config=replace(
        MAINNET,
        config_name="holesky",
        MIN_GENESIS_TIME=1695902100,
        GENESIS_DELAY=300,
        GENESIS_FORK_VERSION=bytes.fromhex("01017000"),
        ALTAIR_FORK_VERSION=bytes.fromhex("02017000"),
        ALTAIR_FORK_EPOCH=0,
        BELLATRIX_FORK_VERSION=bytes.fromhex("03017000"),
        BELLATRIX_FORK_EPOCH=0,
        CAPELLA_FORK_VERSION=bytes.fromhex("04017000"),
        CAPELLA_FORK_EPOCH=256,
        DENEB_FORK_VERSION=bytes.fromhex("05017000"),
        DENEB_FORK_EPOCH=29696,
        ELECTRA_FORK_VERSION=bytes.fromhex("06017000"),
        ELECTRA_FORK_EPOCH=115968,
        EJECTION_BALANCE=28 * 10 ** 9,
        DEPOSIT_CHAIN_ID=17000,
        DEPOSIT_NETWORK_ID=17000,
    ),
    genesis_validators_root=bytes.fromhex(
        "9143aa7c615a7f7115e2b6aac319c03529df8242ae705fba9df39b79c59fa8b1"),
    genesis_time=1695902400,
    deposit_contract=bytes.fromhex(
        "4242424242424242424242424242424242424242"),
    checkpoint_sync_urls=(
        "https://holesky.beaconstate.ethstaker.cc",
        "https://checkpoint-sync.holesky.ethpandaops.io",
    ),
)


# --------------------------------------------------------------------------
# gnosis — independent chain on the gnosis preset (5s slots, 16/epoch)
# --------------------------------------------------------------------------

GNOSIS_NETWORK = NetworkBundle(
    name="gnosis",
    config=replace(
        MAINNET,
        preset_name="gnosis",
        config_name="gnosis",
        SECONDS_PER_SLOT=5,
        SLOTS_PER_EPOCH=16,
        EPOCHS_PER_ETH1_VOTING_PERIOD=64,
        SECONDS_PER_ETH1_BLOCK=6,
        EPOCHS_PER_SYNC_COMMITTEE_PERIOD=512,
        MAX_WITHDRAWALS_PER_PAYLOAD=8,
        MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=8192,
        CHURN_LIMIT_QUOTIENT=4096,
        MIN_GENESIS_TIME=1638968400,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=4096,
        GENESIS_DELAY=6000,
        BASE_REWARD_FACTOR=25,
        GENESIS_FORK_VERSION=bytes.fromhex("00000064"),
        ALTAIR_FORK_VERSION=bytes.fromhex("01000064"),
        ALTAIR_FORK_EPOCH=512,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02000064"),
        BELLATRIX_FORK_EPOCH=385536,
        CAPELLA_FORK_VERSION=bytes.fromhex("03000064"),
        CAPELLA_FORK_EPOCH=648704,
        DENEB_FORK_VERSION=bytes.fromhex("04000064"),
        DENEB_FORK_EPOCH=889856,
        DEPOSIT_CHAIN_ID=100,
        DEPOSIT_NETWORK_ID=100,
    ),
    genesis_validators_root=bytes.fromhex(
        "f5dcb5564e829aab27264b9becd5dfaa017085611224cb3036f573368dbb9d47"),
    genesis_time=1638993340,
    deposit_contract=bytes.fromhex(
        "0b98057ea310f4d31f2a452b414647007d1645d9"),
    checkpoint_sync_urls=(
        "https://checkpoint.gnosischain.com",
    ),
)


MINIMAL_NETWORK = NetworkBundle(name="minimal", config=MINIMAL)
# the bare mainnet PRESET (phase0 at genesis, forks unscheduled) stays
# reachable for interop/devnet use under its historical name
MAINNET_PRESET_NETWORK = NetworkBundle(name="mainnet-preset",
                                       config=MAINNET)

BUNDLES: Dict[str, NetworkBundle] = {
    b.name: b for b in (
        MAINNET_NETWORK, SEPOLIA_NETWORK, HOLESKY_NETWORK,
        GNOSIS_NETWORK, MINIMAL_NETWORK, MAINNET_PRESET_NETWORK)
}


def get_bundle(name: str) -> NetworkBundle:
    try:
        return BUNDLES[name]
    except KeyError:
        raise ValueError(f"unknown network {name!r} (available: "
                         f"{', '.join(sorted(BUNDLES))})") from None
