"""State transition driver: process_slots + full per-block transition.

Equivalent of the reference's StateTransition (reference: ethereum/spec/
src/main/java/tech/pegasys/teku/spec/logic/StateTransition.java:29-118)
and the processAndValidateBlock entry in AbstractBlockProcessor.java:
133-152: slot catch-up with epoch boundaries, then block processing with
a per-block BatchSignatureVerifier whose ONE device dispatch settles
every collected signature.
"""

from .config import SpecConfig
from . import block as B
from . import epoch as E
from . import helpers as H
from .verifiers import (BatchSignatureVerifier, SIMPLE, SignatureVerifier)


class StateTransitionError(Exception):
    """Invalid block (the reference's StateTransitionException)."""


def _schedule(cfg: SpecConfig):
    from .milestones import build_fork_schedule
    return build_fork_schedule(cfg)


def process_slot(cfg: SpecConfig, state):
    previous_state_root = state.htr()
    roots = list(state.state_roots)
    roots[state.slot % cfg.SLOTS_PER_HISTORICAL_ROOT] = previous_state_root
    state = state.copy_with(state_roots=tuple(roots))
    if state.latest_block_header.state_root == bytes(32):
        state = state.copy_with(
            latest_block_header=state.latest_block_header.copy_with(
                state_root=previous_state_root))
    block_roots = list(state.block_roots)
    block_roots[state.slot % cfg.SLOTS_PER_HISTORICAL_ROOT] = (
        state.latest_block_header.htr())
    return state.copy_with(block_roots=tuple(block_roots))


def process_slots(cfg: SpecConfig, state, slot: int):
    """Slot catch-up with milestone-routed epoch processing and fork
    upgrades applied exactly at their activation boundary (reference:
    StateTransition.processSlots + the per-fork delegation in
    Spec.atSlot)."""
    if slot <= state.slot:
        raise StateTransitionError(
            f"cannot rewind: state at {state.slot}, asked for {slot}")
    schedule = _schedule(cfg)
    while state.slot < slot:
        state = process_slot(cfg, state)
        if (state.slot + 1) % cfg.SLOTS_PER_EPOCH == 0:
            # the CURRENT epoch's milestone governs its own processing
            version = schedule.version_at_slot(state.slot)
            state = version.process_epoch(cfg, state)
        state = state.copy_with(slot=state.slot + 1)
        if state.slot % cfg.SLOTS_PER_EPOCH == 0:
            new_epoch = state.slot // cfg.SLOTS_PER_EPOCH
            for version in schedule.upgrades_between(new_epoch - 1,
                                                     new_epoch):
                state = version.upgrade_state(state)
    return state


def state_transition(cfg: SpecConfig, state, signed_block,
                     validate_result: bool = True):
    """Full transition: slots catch-up, batched signature verification,
    block processing, state-root check.  Raises StateTransitionError on
    any invalidity (when validate_result)."""
    block = signed_block.message
    state = process_slots(cfg, state, block.slot)
    verifier: SignatureVerifier = (
        BatchSignatureVerifier() if validate_result else _ACCEPT_ALL)
    process_block = _schedule(cfg).version_at_slot(
        block.slot).process_block
    try:
        if validate_result and not B.verify_block_signature(
                cfg, state, signed_block, verifier):
            raise StateTransitionError("bad proposer signature")
        state = process_block(cfg, state, block, verifier,
                              deposit_verifier=SIMPLE)
    except B.BlockProcessingError as exc:
        raise StateTransitionError(str(exc)) from exc
    if validate_result:
        if not verifier.batch_verify():
            raise StateTransitionError("batch signature verification failed")
        if block.state_root != state.htr():
            raise StateTransitionError("state root mismatch")
    return state


class _AcceptAll(SignatureVerifier):
    def verify(self, public_keys, message, signature) -> bool:
        return True


_ACCEPT_ALL = _AcceptAll()
