"""Multi-chip parallelism: device meshes + sharded kernel dispatch.

The scaling dimension of this domain is signature-message volume, so
the production multi-chip layout is data-parallel lanes over an ICI
mesh: each chip runs the identical per-lane pipeline (hash-to-G2,
scalar ladders, Miller loops) on its shard, then ONE tiny all_gather
(a per-device Fq12 partial product + G2 partial point-sum) crosses the
interconnect before the replicated final exponentiation
(teku_tpu/ops/verify.py:verify_kernel_sharded).  The reference has no
chip-mesh analogue — its scale-out is worker threads over blst
(AggregatingSignatureVerificationService.java:121-132); this package
is where the TPU build goes wider than one chip.

Used by the driver's dryrun_multichip hook, the sharded-kernel tests
(8 virtual CPU devices) and JaxBls12381(mesh=...) for real meshes.
"""

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh

DEFAULT_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None,
              axis: str = DEFAULT_AXIS) -> Mesh:
    """1-D device mesh over the first n available devices.

    On hardware this is the ICI ring; in tests/dry runs it is the
    virtual CPU mesh (xla_force_host_platform_device_count)."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def sharded_verify_fn(mesh: Mesh, axis: str = DEFAULT_AXIS):
    """Jitted sharded batch-verification kernel over `mesh`.

    hm-INPUT contract (ops/verify.verify_kernel_sharded): callers
    supply per-lane H(m) affine points — the provider computes them
    once over the batch's unique messages (H(m) cache-aware) and
    scatters them to lanes before sharding; N must divide mesh size."""
    from ..ops import verify as V
    return jax.jit(V.verify_kernel_sharded(mesh, axis))


class ShardedVerifier:
    """Pads + dispatches global batches through the sharded kernel.

    The padding rule keeps shapes static per bucket (pow2, >= mesh
    size, so every shard is equal) — the multi-chip twin of the
    provider's single-chip bucket rule."""

    def __init__(self, mesh: Mesh, axis: str = DEFAULT_AXIS,
                 min_bucket: int = 16):
        self.mesh = mesh
        self.axis = axis
        self.n_devices = int(np.prod([mesh.shape[a] for a in
                                      mesh.axis_names]))
        if self.n_devices & (self.n_devices - 1):
            # pow2 buckets must divide evenly across shards
            raise ValueError("mesh size must be a power of two")
        self.min_bucket = max(min_bucket, self.n_devices)
        self._fn = sharded_verify_fn(mesh, axis)

    def __call__(self, *args):
        return self._fn(*args)
