"""Multi-chip parallelism: device meshes + sharded kernel dispatch.

The scaling dimension of this domain is signature-message volume, so
the production multi-chip layout is data-parallel over an ICI mesh —
and since PR 5 the unit of per-lane work is the MESSAGE GROUP (h2c and
the Miller loops run once per unique message), the production sharding
unit is the group row, not the raw lane: ``plan_group_shards`` packs
whole (message, lane-chunk) rows onto shards so every chip runs the
full dedup-aware pipeline (grouped Miller rows, optionally the
GLV+Pippenger MSM scalars stage) on its shard, then ONE tiny
all_gather (a per-device Fq12 partial product + G2 partial point-sum)
crosses the interconnect before the replicated final exponentiation
(teku_tpu/ops/verify.py:verify_kernel_sharded_grouped).

The reference has no chip-mesh analogue — its scale-out is worker
threads over blst (AggregatingSignatureVerificationService.java:
121-132); this package is where the TPU build goes wider than one
chip.  ``JaxBls12381(mesh=...)`` (constructed by the loader under
``--mesh {off,auto,N}`` / TEKU_TPU_MESH) routes production dispatches
through ``GroupShardedVerifier``; the lane-sharded ``ShardedVerifier``
remains for the driver's dryrun_multichip hook and the
8-virtual-device CI harness.
"""

import logging
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from ..infra import flightrecorder
from ..infra.metrics import GLOBAL_REGISTRY
from ..infra.pow2 import floor_pow2 as _floor_pow2
from ..infra.pow2 import next_pow2 as _next_pow2

_LOG = logging.getLogger(__name__)

DEFAULT_AXIS = "dp"

ENV_VAR = "TEKU_TPU_MESH"

# the last-constructed mesh's self-description: MULTICHIP runs and the
# readiness snapshot must say WHICH devices the mesh took (make_mesh
# silently taking the first N was satellite-fixed in PR 10)
_ACTIVE = {"devices": [], "n": 0, "axis": DEFAULT_AXIS}
_lock = threading.Lock()
_warned_demotion = [False]

GLOBAL_REGISTRY.gauge(
    "bls_mesh_devices",
    "device count of the most recently constructed verify mesh "
    "(0 = single-device dispatch, no mesh built)",
    supplier=lambda: float(_ACTIVE["n"]))


def describe_mesh() -> dict:
    """The active mesh's self-description (readiness snapshot shape)."""
    with _lock:
        return {"devices": list(_ACTIVE["devices"]),
                "n_devices": _ACTIVE["n"], "axis": _ACTIVE["axis"]}


def reset_active_mesh() -> None:
    """Clear the active-mesh self-description: the self-healer shrank
    to a single device (or the oracle) and the ``bls_mesh_devices``
    gauge must stop advertising a mesh nothing dispatches over."""
    with _lock:
        _ACTIVE["devices"] = []
        _ACTIVE["n"] = 0


def advertise_mesh(device_names: Sequence[str],
                   axis: str = DEFAULT_AXIS) -> None:
    """Publish the SERVING mesh self-description.  The self-healer's
    install hook calls this when the reshaped provider actually
    swaps in — constructing a candidate mesh must NOT advertise it
    (a vetoed install would leave the gauge/readiness pointing at a
    mesh that never served)."""
    names = [str(d) for d in device_names]
    with _lock:
        _ACTIVE["devices"] = names
        _ACTIVE["n"] = len(names)
        _ACTIVE["axis"] = axis
    _LOG.info("verify mesh: %d device(s) over axis %r: %s",
              len(names), axis, ", ".join(names))


def resolve_mesh_devices(spec, available: Optional[int] = None) -> int:
    """Resolve a ``--mesh {off,auto,N}`` spec to a usable device count.

    Returns 0 for "no mesh" (single-device dispatch).  ``auto`` takes
    the largest power of two <= the available devices; an explicit N
    (possibly non-pow-2, possibly larger than the host) DEMOTES to the
    largest pow-2 <= min(N, available) with ONE warning — mirroring the
    mxu-on-CPU demotion: an over-ambitious knob must never fail node
    boot (ShardedVerifier/GroupShardedVerifier raise on non-pow-2 at
    construction, so the resolution happens here, before them)."""
    if spec is None:
        return 0
    raw = str(spec).strip().lower()
    if raw in ("", "off", "0", "none", "false", "no"):
        return 0
    if available is None:
        available = len(jax.devices())
    if raw == "auto":
        n = _floor_pow2(max(available, 1))
        return n if n >= 2 else 0
    try:
        requested = int(raw)
    except ValueError:
        if not _warned_demotion[0]:
            _warned_demotion[0] = True
            _LOG.warning("%s=%r is not off/auto/N; mesh disabled",
                         ENV_VAR, spec)
            # a mis-knobbed boot must self-explain in the flight
            # recorder, not only in a log line that scrolled away
            flightrecorder.config_demotion(
                "mesh", spec, 0,
                f"{ENV_VAR} not off/auto/N; mesh disabled",
                available=available)
        return 0
    if requested <= 1:
        return 0
    n = _floor_pow2(min(requested, max(available, 1)))
    if n != requested and not _warned_demotion[0]:
        _warned_demotion[0] = True
        _LOG.warning(
            "mesh of %d devices unavailable (have %d, shards must be "
            "a power of two); demoting to a %d-device mesh",
            requested, available, n)
        flightrecorder.config_demotion(
            "mesh", requested, n,
            "mesh demoted to the largest pow-2 <= "
            "min(requested, available)",
            available=available)
    return n if n >= 2 else 0


def make_mesh(n_devices: Optional[int] = None,
              axis: str = DEFAULT_AXIS, devices=None,
              advertise: bool = True) -> Mesh:
    """1-D device mesh over the first n available devices, or over an
    EXPLICIT device list (``devices=``) — the self-healing reshape
    path builds meshes over the surviving healthy subset, which is not
    a prefix of jax.devices() once a middle chip is ejected.

    On hardware this is the ICI ring; in tests/dry runs it is the
    virtual CPU mesh (xla_force_host_platform_device_count).  The
    chosen device set is LOGGED and exported (``bls_mesh_devices``
    gauge + describe_mesh() for the readiness snapshot) so multi-chip
    runs self-describe instead of silently taking the first N —
    except under ``advertise=False`` (the healer's CANDIDATE meshes:
    a reshape advertises at install time, after the warm proved it,
    never at construction)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"need {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    else:
        devices = list(devices)
    if advertise:
        advertise_mesh([str(d) for d in devices], axis)
    return Mesh(np.array(devices), (axis,))


def sharded_verify_fn(mesh: Mesh, axis: str = DEFAULT_AXIS):
    """Jitted LANE-sharded batch-verification kernel over `mesh`.

    hm-INPUT contract (ops/verify.verify_kernel_sharded): callers
    supply per-lane H(m) affine points — the provider computes them
    once over the batch's unique messages (H(m) cache-aware) and
    scatters them to lanes before sharding; N must divide mesh size.
    The dryrun/CI harness kernel; production uses
    GroupShardedVerifier."""
    from ..ops import verify as V
    return jax.jit(V.verify_kernel_sharded(mesh, axis))


class ShardPlan:
    """Host-side group-aligned shard layout for ONE dispatch.

    ``lane_pos[i]`` is the global (permuted) lane slot of original lane
    i — each shard's contiguous lane block holds exactly the lanes of
    the rows that shard owns; ``row_layout[p]`` is the canonical row
    index occupying global row slot p (-1 = padding row).  All shapes
    are pow-2 and identical across shards, so shard_map splits evenly.
    """

    __slots__ = ("n_shards", "lanes_per_shard", "rows_per_shard",
                 "padded", "rows_total", "lane_pos", "row_layout",
                 "shard_lanes", "shard_rows")

    def __init__(self, n_shards, lanes_per_shard, rows_per_shard,
                 lane_pos, row_layout, shard_lanes=None,
                 shard_rows=None):
        self.n_shards = n_shards
        self.lanes_per_shard = lanes_per_shard
        self.rows_per_shard = rows_per_shard
        self.padded = n_shards * lanes_per_shard
        self.rows_total = n_shards * rows_per_shard
        self.lane_pos = lane_pos
        self.row_layout = row_layout
        # per-shard REAL loads (pre-padding): the dispatch ledger's
        # makespan/imbalance evidence — which chip the LPT packer made
        # the straggler, and by how much
        self.shard_lanes = list(shard_lanes or [])
        self.shard_rows = list(shard_rows or [])

    @property
    def makespan_ratio(self) -> float:
        """max shard lane load / mean shard lane load (>= 1.0; the
        sharded dispatch's wall time is the max shard's, so this IS
        the imbalance overhead factor)."""
        total = sum(self.shard_lanes)
        if not total or not self.n_shards:
            return 0.0
        return max(self.shard_lanes) / (total / self.n_shards)


def plan_group_shards(rows: Sequence[Tuple[int, List[int]]],
                      n_lanes: int, n_shards: int,
                      min_lanes: int = 1,
                      min_rows: int = 1) -> ShardPlan:
    """Pack message-group rows onto shards, whole rows only.

    LPT bin-packing (longest rows first, least-loaded shard wins) keeps
    the per-shard lane counts balanced; each shard's lane/row blocks
    pad to the same pow-2 so the sharded kernel's shapes stay static.
    ``min_lanes``/``min_rows`` are PER-SHARD floors (the global
    min_bucket / h2c bucket floors divided across shards), so the
    global padded shapes stay inside the same bucket families the
    single-device dispatch uses."""
    m = n_shards
    order = sorted(range(len(rows)), key=lambda r: -len(rows[r][1]))
    bin_rows: List[List[int]] = [[] for _ in range(m)]
    bin_lanes = [0] * m
    for r in order:
        b = min(range(m),
                key=lambda i: (bin_lanes[i], len(bin_rows[i]), i))
        bin_rows[b].append(r)
        bin_lanes[b] += len(rows[r][1])
    lanes_per = max(_next_pow2(max(bin_lanes + [1])),
                    _next_pow2(max(min_lanes, 1)))
    rows_per = max(_next_pow2(max([len(br) for br in bin_rows] + [1])),
                   _next_pow2(max(min_rows, 1)))
    lane_pos = np.zeros(n_lanes, dtype=np.int64)
    row_layout = np.full(m * rows_per, -1, dtype=np.int64)
    for s in range(m):
        cursor = s * lanes_per
        for k, r in enumerate(bin_rows[s]):
            row_layout[s * rows_per + k] = r
            for i in rows[r][1]:
                lane_pos[i] = cursor
                cursor += 1
    return ShardPlan(m, lanes_per, rows_per, lane_pos, row_layout,
                     shard_lanes=bin_lanes,
                     shard_rows=[len(br) for br in bin_rows])


class ShardedVerifier:
    """LEGACY lane-sharded dispatch: pads + dispatches global batches
    through verify_kernel_sharded (per-lane Miller rows — the grouping
    and MSM stages are forfeited because groups cross shards).  Kept
    for the dryrun hook and the CI harness; production dispatch goes
    through GroupShardedVerifier."""

    def __init__(self, mesh: Mesh, axis: str = DEFAULT_AXIS,
                 min_bucket: int = 16):
        self.mesh = mesh
        self.axis = axis
        self.n_devices = int(np.prod([mesh.shape[a] for a in
                                      mesh.axis_names]))
        if self.n_devices & (self.n_devices - 1):
            # pow2 buckets must divide evenly across shards
            raise ValueError("mesh size must be a power of two")
        self.min_bucket = max(min_bucket, self.n_devices)
        self._fn = sharded_verify_fn(mesh, axis)

    def __call__(self, *args):
        return self._fn(*args)


# process-level sharded-kernel memo, keyed by (device set, axis, msm
# path): two GroupShardedVerifier instances over the SAME devices are
# the same program, so they must share ONE jitted callable — and its
# in-memory jit cache of compiled shapes.  This is what makes the
# self-healer's GROW reshape near-free: re-admitting a device rebuilds
# a mesh the process already served, and every warmed shape is still
# resident (eject→readmit cycles re-trace nothing).
_KERNELS: dict = {}
_KERNELS_LOCK = threading.Lock()


def kernel_store_name(devices: Sequence[str], axis: str,
                      msm_path: str) -> str:
    """AOT-store kernel name for a sharded verify program.  The
    device LIST (not just the count) is part of the name: a serialized
    executable binds its device assignment, so an entry compiled for
    mesh [0..3] must never deserialize onto a healed mesh that ejected
    device 2 — those are different programs to the store.  Mont path
    likewise (it changes the traced field arithmetic)."""
    import hashlib

    from ..ops import mxu
    dev = hashlib.sha256(repr(tuple(devices)).encode()).hexdigest()[:8]
    return (f"mesh:{len(devices)}:{axis}:{msm_path}:"
            f"{mxu.resolve()}:{dev}")


class GroupShardedVerifier:
    """Group-aligned production mesh dispatch.

    Owns the per-dispatch shard planner (plan()) and one jitted
    verify_kernel_sharded_grouped per MSM path (the ladder and
    pippenger scalars stages are different programs).  The padding
    rule keeps every shard's shapes identical (pow2 lanes/rows per
    shard) — the multi-chip twin of the provider's bucket rule."""

    def __init__(self, mesh: Mesh, axis: str = DEFAULT_AXIS,
                 min_bucket: int = 16):
        self.mesh = mesh
        self.axis = axis
        self.n_devices = int(np.prod([mesh.shape[a] for a in
                                      mesh.axis_names]))
        if self.n_devices & (self.n_devices - 1):
            raise ValueError("mesh size must be a power of two")
        self.min_bucket = max(min_bucket, self.n_devices)
        self.devices = [str(d) for d in np.ravel(mesh.devices)]

    def describe(self) -> dict:
        return {"devices": list(self.devices),
                "n_devices": self.n_devices, "axis": self.axis}

    def plan(self, rows, n_lanes: int,
             min_rows_total: int = 1) -> ShardPlan:
        return plan_group_shards(
            rows, n_lanes, self.n_devices,
            min_lanes=self.min_bucket // self.n_devices,
            min_rows=max(min_rows_total // self.n_devices, 1))

    def kernel_key(self, msm_path: str) -> tuple:
        """The identity of the shared jitted kernel serving this
        verifier (the provider's jit-outcome accounting keys on it:
        a fresh instance over known devices is NOT a fresh program)."""
        return (tuple(self.devices), self.axis, msm_path)

    def kernel(self, msm_path: str):
        key = self.kernel_key(msm_path)
        with _KERNELS_LOCK:
            fn = _KERNELS.get(key)
            if fn is None:
                from ..infra import aotstore
                from ..ops import verify as V
                fn = aotstore.wrap(
                    kernel_store_name(self.devices, self.axis,
                                      msm_path),
                    jax.jit(V.verify_kernel_sharded_grouped(
                        self.mesh, self.axis, msm_path)))
                _KERNELS[key] = fn
        return fn
